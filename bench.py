#!/usr/bin/env python
"""TSBS-style benchmark: double-groupby-all (the north-star metric,
BASELINE.md — reference GreptimeDB v0.8.0: 2215.44 ms on 8-core local).

Workload (mirrors TSBS devops `cpu-only` double-groupby-all): `cpu` table
with 10 DOUBLE usage fields; query = avg of all 10 fields GROUP BY
(hour bucket, hostname) over a 12h window. Dataset: HOSTS hosts sampled
every 10s for 12h (default 4000 hosts -> 17.28M rows x 10 fields).

Pipeline measured end-to-end through the SQL engine: SQL parse -> plan ->
region scan (SST/memtable) -> device blocks -> fused filter+group+segment
reduction kernel -> host result assembly. Median of repeated runs after one
warm-up, matching the reference's warm-page-cache TSBS methodology (here
the warm cache is HBM-resident column blocks).

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "ms", "vs_baseline": N}
vs_baseline > 1 means faster than the reference's 2215.44 ms.
"""

import json
import os
import shutil
import subprocess
import sys
import tempfile
import time
import traceback

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

BASELINE_MS = 2215.44  # BASELINE.md double-groupby-all, local 8c

INIT_RETRIES = int(os.environ.get("BENCH_INIT_RETRIES", "3"))
INIT_TIMEOUT_S = int(os.environ.get("BENCH_INIT_TIMEOUT_S", "90"))

HOSTS = int(os.environ.get("BENCH_HOSTS", "4000"))
HOURS = int(os.environ.get("BENCH_HOURS", "12"))
STEP_S = int(os.environ.get("BENCH_STEP_S", "10"))
REPEATS = int(os.environ.get("BENCH_REPEATS", "3"))
FIELDS = [f"usage_{n}" for n in (
    "user", "system", "idle", "nice", "iowait", "irq", "softirq",
    "steal", "guest", "guest_nice")]


def log(msg):
    print(msg, file=sys.stderr, flush=True)


def build_db(data_dir):
    from greptimedb_tpu.catalog import Catalog, MemoryKv
    from greptimedb_tpu.query import QueryEngine
    from greptimedb_tpu.storage import RegionEngine
    from greptimedb_tpu.storage.engine import EngineConfig

    engine = RegionEngine(EngineConfig(data_dir=data_dir))
    qe = QueryEngine(Catalog(MemoryKv()), engine)
    field_defs = ",\n  ".join(f"{f} DOUBLE" for f in FIELDS)
    qe.execute_one(f"""
        CREATE TABLE cpu (
          hostname STRING,
          ts TIMESTAMP(3) NOT NULL,
          {field_defs},
          TIME INDEX (ts),
          PRIMARY KEY (hostname)
        ) WITH (append_mode = 'true')
    """)
    return engine, qe


def ingest(engine, qe, t0_ms):
    """Ingest through the write path (RecordBatch put = the gRPC-analog
    bulk route), one batch per simulated time slice group."""
    from greptimedb_tpu.datatypes import DictVector, RecordBatch

    info = qe.catalog.table("public", "cpu")
    schema = info.schema
    rid = info.region_ids[0]
    rng = np.random.default_rng(7)
    points = HOURS * 3600 // STEP_S
    host_names = np.asarray([f"host_{i}" for i in range(HOSTS)], dtype=object)
    rows_total = 0
    t_start = time.perf_counter()
    slice_points = max(1, (1 << 21) // HOSTS)  # ~2M rows per batch
    for p0 in range(0, points, slice_points):
        p1 = min(p0 + slice_points, points)
        npts = p1 - p0
        n = npts * HOSTS
        host_codes = np.tile(np.arange(HOSTS, dtype=np.int32), npts)
        ts = np.repeat(
            t0_ms + (np.arange(p0, p1, dtype=np.int64) * STEP_S * 1000), HOSTS
        )
        cols = {
            "hostname": DictVector(host_codes, host_names),
            "ts": ts,
        }
        for f in FIELDS:
            cols[f] = rng.uniform(0.0, 100.0, n)
        batch = RecordBatch(schema, cols)
        engine.put(rid, batch)
        rows_total += n
    ingest_s = time.perf_counter() - t_start
    return rows_total, ingest_s


def probe_backend():
    """Verify jax backend init in a throwaway subprocess before touching it
    in-process. TPU plugin init is flaky (round-1 BENCH_r01 rc=1: UNAVAILABLE
    at setup) and can hang; a child process can neither poison our backend
    cache nor hang us past the timeout. Bounded retries with backoff; on
    persistent failure fall back to CPU so a number is still produced."""
    # the axon sitecustomize overrides the JAX_PLATFORMS env var at
    # interpreter start; jax.config.update after import is authoritative
    code = (
        "import os, jax\n"
        "if os.environ.get('JAX_PLATFORMS') == 'cpu':\n"
        "    jax.config.update('jax_platforms', 'cpu')\n"
        "print([d.platform for d in jax.devices()])"
    )
    for attempt in range(1, INIT_RETRIES + 1):
        try:
            r = subprocess.run(
                [sys.executable, "-c", code],
                capture_output=True, text=True, timeout=INIT_TIMEOUT_S,
            )
        except subprocess.TimeoutExpired:
            log(f"backend probe {attempt}/{INIT_RETRIES}: "
                f"TIMED OUT after {INIT_TIMEOUT_S}s")
            r = None
        if r is not None and r.returncode == 0:
            log(f"backend probe {attempt}/{INIT_RETRIES}: OK {r.stdout.strip()}")
            return "default"
        if r is not None:
            log(f"backend probe {attempt}/{INIT_RETRIES}: rc={r.returncode}\n"
                + "\n".join(r.stderr.splitlines()[-6:]))
        if attempt < INIT_RETRIES:
            backoff = 5 * attempt
            log(f"retrying backend init in {backoff}s ...")
            time.sleep(backoff)
    log("WARNING: accelerator backend unavailable after "
        f"{INIT_RETRIES} attempts — falling back to CPU")
    os.environ["JAX_PLATFORMS"] = "cpu"
    return "cpu"


def main():
    data_dir = tempfile.mkdtemp(prefix="gtpu_bench_")
    try:
        backend = probe_backend()
        import jax
        if backend == "cpu" or os.environ.get("JAX_PLATFORMS") == "cpu":
            # the env var alone is NOT sufficient — the axon sitecustomize
            # overrides it at interpreter start; config.update is what
            # actually pins the platform (tests/conftest.py recipe)
            jax.config.update("jax_platforms", "cpu")
            backend = "cpu"
        log(f"devices: {jax.devices()}")
        engine, qe = build_db(data_dir)
        t0_ms = 1456790400000  # 2016-03-01T00:00:00Z
        log(f"ingesting {HOSTS} hosts x {HOURS}h @{STEP_S}s ...")
        rows, ingest_s = ingest(engine, qe, t0_ms)
        log(f"ingested {rows} rows in {ingest_s:.1f}s "
            f"({rows / ingest_s:,.0f} rows/s)")
        engine.flush(qe.catalog.table("public", "cpu").region_ids[0])
        log("flushed to SST")

        t_end_ms = t0_ms + HOURS * 3600 * 1000
        avg_list = ", ".join(f"avg({f})" for f in FIELDS)
        sql = (
            f"SELECT date_bin(INTERVAL '1 hour', ts) AS hour, hostname, {avg_list} "
            f"FROM cpu WHERE ts >= {t0_ms} AND ts < {t_end_ms} "
            f"GROUP BY hour, hostname ORDER BY hour, hostname"
        )
        # warm-up: compile + fill the HBM block cache
        t = time.perf_counter()
        r = qe.execute_one(sql)
        log(f"warm-up run: {(time.perf_counter() - t) * 1000:.1f} ms, "
            f"{r.num_rows} groups")
        assert r.num_rows == HOSTS * HOURS, r.num_rows

        times = []
        for i in range(REPEATS):
            t = time.perf_counter()
            r = qe.execute_one(sql)
            dt = (time.perf_counter() - t) * 1000
            times.append(dt)
            log(f"run {i + 1}: {dt:.1f} ms")
        value = float(np.median(times))
        print(json.dumps({
            "metric": "tsbs_double_groupby_all_p50_ms",
            "value": round(value, 2),
            "unit": "ms",
            "vs_baseline": round(BASELINE_MS / value, 3),
            "detail": {
                "backend": jax.devices()[0].platform,
                "rows": rows,
                "hosts": HOSTS,
                "hours": HOURS,
                "fields": len(FIELDS),
                "groups": HOSTS * HOURS,
                "ingest_rows_per_s": round(rows / ingest_s),
                "baseline_ms": BASELINE_MS,
                "runs_ms": [round(t, 1) for t in times],
            },
        }))
        engine.close()
    finally:
        shutil.rmtree(data_dir, ignore_errors=True)


def supervise():
    """Run the real bench as a child process under a hard wall-clock cap.

    The backend probe can pass and the tunnel still die before the
    in-process init — then the bench hangs inside a C call that no
    in-process guard can interrupt. The supervisor is immune: it never
    touches jax. Child attempt 1 uses the default backend; if it times out
    or dies without emitting JSON, attempt 2 forces CPU; if that fails too,
    the supervisor emits the error JSON itself. Always ends with ONE JSON
    line on stdout."""
    total_s = int(os.environ.get("BENCH_TOTAL_TIMEOUT_S", "2400"))
    deadline = time.monotonic() + total_s
    # full TSBS scale runs everywhere since the prepared-plane fast path
    # (~0.5 s for 17M rows even on CPU); detail.backend records which
    # backend produced the number
    attempts = [{}, {"JAX_PLATFORMS": "cpu"}]
    last_err = "unknown"
    for i, extra_env in enumerate(attempts, 1):
        remaining = deadline - time.monotonic()
        if remaining <= 60:
            last_err = f"total budget {total_s}s exhausted before attempt {i}"
            break
        env = dict(os.environ, BENCH_CHILD="1", **extra_env)
        label = "default backend" if not extra_env else "cpu fallback"
        # non-final attempts may not starve the fallback: reserve it a slice
        attempt_s = remaining if i == len(attempts) \
            else max(60, remaining - 900)
        log(f"supervisor: attempt {i}/{len(attempts)} ({label}), "
            f"timeout {attempt_s:.0f}s")
        try:
            r = subprocess.run(
                [sys.executable, os.path.abspath(__file__)],
                capture_output=True, text=True, timeout=attempt_s, env=env,
            )
        except subprocess.TimeoutExpired as e:
            tail = e.stderr or b""
            if isinstance(tail, bytes):
                tail = tail.decode(errors="replace")
            log(f"supervisor: attempt {i} TIMED OUT after {attempt_s:.0f}s\n"
                f"{tail[-2000:]}")
            last_err = f"bench timed out after {attempt_s:.0f}s ({label})"
            continue
        sys.stderr.write(r.stderr)
        json_line = None
        for line in reversed(r.stdout.splitlines()):
            if line.startswith("{"):
                json_line = line
                break
        if json_line is not None and r.returncode == 0:
            print(json_line)
            return 0
        last_err = (r.stderr.strip().splitlines() or ["no stderr"])[-1]
        log(f"supervisor: attempt {i} failed rc={r.returncode}")
    print(json.dumps({
        "metric": "tsbs_double_groupby_all_p50_ms",
        "value": None,
        "unit": "ms",
        "vs_baseline": None,
        "detail": {"error": last_err},
    }))
    return 1


if __name__ == "__main__":
    if os.environ.get("BENCH_CHILD") != "1":
        sys.exit(supervise())
    try:
        main()
    except BaseException:
        # the supervisor parses our last stdout line as JSON — always emit
        # one, even on catastrophic failure, so the round records a
        # diagnosis instead of a bare rc=1
        traceback.print_exc(file=sys.stderr)
        print(json.dumps({
            "metric": "tsbs_double_groupby_all_p50_ms",
            "value": None,
            "unit": "ms",
            "vs_baseline": None,
            "detail": {"error": traceback.format_exc().strip().splitlines()[-1]},
        }))
        sys.exit(1)
