#!/usr/bin/env python
"""TSBS-style benchmark suite covering every BASELINE.json tracked config.

Headline metric stays double-groupby-all (the north star, BASELINE.md —
reference GreptimeDB v0.8.0: 2215.44 ms local). The other tracked axes
run in the same process and land in detail.configs:

  1. single_groupby_1_1_1  — 1 field, 1 host, 1h @1m buckets (15.70 ms ref)
  2. double_groupby_all    — avg of 10 fields by (hour, hostname) (2215.44)
  3. lastpoint             — newest row per host via last_value (6756.12)
  4. high_cpu_all          — full-scan filter usage_user > 90 (5402.31)
  5. promql_rate           — TQL rate() over 10k series x 1 day @15s
                             (tracked config #3), with a same-box numpy
                             straw-man anchor; budget-sized span
  6. high_cardinality      — segment-sum over 1M tag combos scaled
                             toward the 1B-row tracked config #5
  7. compaction_reencode   — L0→L1 merge re-encode throughput (rows/s)
  8. sql_insert            — durable SQL INSERT statement path (rows/s)
  9. qps_single_groupby    — 50 keep-alive HTTP clients (ref 1165.73 qps)
 10. double_groupby_100m   — the headline query at tracked config #2
                             scale (100M rows / 4k hosts), budget-sized
 11. qps_mixed_tenants     — 3-tenant mixed workload (dashboard /
                             point lastpoint / high-card groupby) with
                             per-tenant p99/p999 + plan-cache hit rate
 12. incremental_agg       — partial-aggregate cache: cold fold vs warm
                             repeat vs post-flush one-new-file fold,
                             bit-for-bit digests + delta-row proof

Pipeline measured end-to-end through the SQL engine: SQL parse -> plan ->
region scan (SST/memtable) -> device blocks -> fused filter+group+segment
reduction kernel -> host result assembly. Median of repeated runs after one
warm-up, matching the reference's warm-page-cache TSBS methodology (here
the warm cache is HBM-resident column blocks).

When the accelerator backend is live, one double-groupby run is captured
under jax.profiler (trace dir in detail.profile_dir) for MFU/bandwidth
analysis.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "ms", "vs_baseline": N, "detail": ...}
vs_baseline > 1 means faster than the reference's 2215.44 ms.
"""

import json
import os
import shutil
import signal
import subprocess
import sys
import tempfile
import time
import traceback

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

# BASELINE.md reference numbers (v0.8.0, local 8-core)
BASELINE_MS = 2215.44           # double-groupby-all
BASE_SINGLE_MS = 15.70          # single-groupby-1-1-1
BASE_LASTPOINT_MS = 6756.12     # lastpoint
BASE_HIGH_CPU_MS = 5402.31      # high-cpu-all
BASE_GBOL_MS = 754.50           # groupby-orderby-limit
BASE_MAX_ALL_8_MS = 51.69       # cpu-max-all-8
BASE_INGEST_ROWS_S = 315369.66  # TSBS ingest rate

INIT_RETRIES = int(os.environ.get("BENCH_INIT_RETRIES", "3"))
INIT_TIMEOUT_S = int(os.environ.get("BENCH_INIT_TIMEOUT_S", "90"))

HOSTS = int(os.environ.get("BENCH_HOSTS", "4000"))
HOURS = int(os.environ.get("BENCH_HOURS", "12"))
STEP_S = int(os.environ.get("BENCH_STEP_S", "10"))
REPEATS = int(os.environ.get("BENCH_REPEATS", "3"))
PROM_SERIES = int(os.environ.get("BENCH_PROM_SERIES", "10000"))
# tracked config #3 (BASELINE.json): 10k series x 1 DAY @15s = 57.6M rows
PROM_HOURS = int(os.environ.get("BENCH_PROM_HOURS", "24"))
HC_COMBOS = int(os.environ.get("BENCH_HC_COMBOS", "1000000"))
HC_POINTS = int(os.environ.get("BENCH_HC_POINTS", "10"))
COMPACT_ROWS = int(os.environ.get("BENCH_COMPACT_ROWS", "4000000"))
# comma-separated subset, e.g. BENCH_CONFIGS=double_groupby_all,lastpoint
CONFIGS = [c for c in os.environ.get("BENCH_CONFIGS", "").split(",") if c]
FIELDS = [f"usage_{n}" for n in (
    "user", "system", "idle", "nice", "iowait", "irq", "softirq",
    "steal", "guest", "guest_nice")]

T0_MS = 1456790400000  # 2016-03-01T00:00:00Z

T_MAIN_START = None  # set by main(); basis for wall-clock budget sizing


def partial_path() -> str:
    """Where every emit_result is mirrored on disk. The supervisor hands
    the path to its children via env; an EXTERNAL kill (rc=124 wrapping
    the supervisor itself — the r05 incident left `parsed: null`) can
    then still salvage the newest checkpoint from the file."""
    return os.environ.get(
        "BENCH_PARTIAL_PATH",
        os.path.join(tempfile.gettempdir(), "gtpu_bench_partial.json"))


def write_partial(line: str) -> None:
    """Atomically persist the latest result line (flush + fsync: the
    whole point is surviving a SIGKILL moments later)."""
    try:
        path = partial_path()
        tmp = f"{path}.{os.getpid()}.tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            f.write(line + "\n")
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    except OSError as e:  # noqa: PERF203 — salvage is best-effort
        log(f"write_partial failed: {e}")


def budget_left_s(reserve=150.0):
    """Seconds of the supervisor-granted wall budget still unspent.
    The big tracked configs (100M double-groupby, 24h PromQL, 1B-target
    high-cardinality) size their ingest against this so one config
    overrunning cannot starve the final JSON emit. The default reserve
    was widened 90 -> 150 after r05: the anchor configs must always
    land even when a supervisor timeout hits mid-run."""
    total = float(os.environ.get("BENCH_TOTAL_TIMEOUT_S", "2400"))
    if T_MAIN_START is None:
        return total - reserve
    return total - (time.monotonic() - T_MAIN_START) - reserve


def affordable_rows(reserve_s, ingest_rps, width_factor=1.0):
    """Rows the remaining budget can ingest: `reserve_s` is held back
    for the config's own query runs + the configs after it;
    `width_factor` scales the measured 12-column cpu ingest rate for
    narrower tables (3-column rows move ~2x faster). A 0.75 derate
    covers flush/compaction debt at scale — round-5 incident: sized at
    the measured 195k rows/s, achieved 115k, blew the supervisor
    window."""
    rps = max(ingest_rps, 50000.0) * width_factor * 0.75
    return int(max(0.0, budget_left_s() - reserve_s) * rps)


def log(msg):
    print(msg, file=sys.stderr, flush=True)


def enabled(name):
    return not CONFIGS or name in CONFIGS


def build_db(data_dir):
    from greptimedb_tpu.catalog import Catalog, MemoryKv
    from greptimedb_tpu.query import QueryEngine
    from greptimedb_tpu.storage import RegionEngine
    from greptimedb_tpu.storage.engine import EngineConfig

    engine = RegionEngine(EngineConfig(data_dir=data_dir))
    qe = QueryEngine(Catalog(MemoryKv()), engine)
    field_defs = ",\n  ".join(f"{f} DOUBLE" for f in FIELDS)
    qe.execute_one(f"""
        CREATE TABLE cpu (
          hostname STRING,
          ts TIMESTAMP(3) NOT NULL,
          {field_defs},
          TIME INDEX (ts),
          PRIMARY KEY (hostname)
        ) WITH (append_mode = 'true')
    """)
    return engine, qe


def ingest(engine, qe, t0_ms):
    """Ingest through the write path (RecordBatch put = the gRPC-analog
    bulk route), one batch per simulated time slice group."""
    from greptimedb_tpu.datatypes import DictVector, RecordBatch

    info = qe.catalog.table("public", "cpu")
    schema = info.schema
    rid = info.region_ids[0]
    rng = np.random.default_rng(7)
    points = HOURS * 3600 // STEP_S
    host_names = np.asarray([f"host_{i}" for i in range(HOSTS)], dtype=object)
    rows_total = 0
    t_start = time.perf_counter()
    slice_points = max(1, (1 << 21) // HOSTS)  # ~2M rows per batch
    for p0 in range(0, points, slice_points):
        p1 = min(p0 + slice_points, points)
        npts = p1 - p0
        n = npts * HOSTS
        host_codes = np.tile(np.arange(HOSTS, dtype=np.int32), npts)
        ts = np.repeat(
            t0_ms + (np.arange(p0, p1, dtype=np.int64) * STEP_S * 1000), HOSTS
        )
        cols = {
            "hostname": DictVector(host_codes, host_names),
            "ts": ts,
        }
        for f in FIELDS:
            cols[f] = rng.uniform(0.0, 100.0, n)
        batch = RecordBatch(schema, cols)
        engine.put(rid, batch)
        rows_total += n
    ingest_s = time.perf_counter() - t_start
    return rows_total, ingest_s


def timed_sql(qe, sql, repeats=None, expect_rows=None):
    """Warm-up once (compile + HBM cache fill), then median of repeats.
    The warm-up runs under a fresh trace so its cost decomposes into
    engine spans (scan/aggregate/...) — distinguishing XLA compile time
    from SST read + decode when diagnosing cold starts. The execution
    tier that served the query (device | host — physical.tier_for)
    rides back in the spans dict under "tier"."""
    from greptimedb_tpu.session import QueryContext
    from greptimedb_tpu.utils import tracing

    tid = tracing.new_trace_id()
    t = time.perf_counter()
    r = qe.execute_one(sql, QueryContext(trace_id=tid))
    warm_ms = (time.perf_counter() - t) * 1000
    spans = {}
    for s in tracing.spans_for(tid):
        spans[s.name] = round(spans.get(s.name, 0.0) + s.duration_ms, 1)
    spans["tier"] = getattr(qe.executor, "last_tier", None)
    if expect_rows is not None:
        assert r.num_rows == expect_rows, (r.num_rows, expect_rows)
    times = []
    for _ in range(repeats or REPEATS):
        t = time.perf_counter()
        qe.execute_one(sql)
        times.append((time.perf_counter() - t) * 1000)
    return float(np.median(times)), warm_ms, r.num_rows, spans


def bench_cpu_suite(qe, results, guard=None, checkpoint=None):
    """Quick TSBS configs. Each config runs isolated (`guard`) and the
    salvageable summary refreshes after every one (`checkpoint`) —
    r01/r04 ended rc=0 with `parsed: null` because one config crashing
    inside this suite sank every result before the first checkpoint."""
    t_end_ms = T0_MS + HOURS * 3600 * 1000

    def _run(name, fn):
        if guard is not None:
            guard(name, fn)
        elif enabled(name):
            fn()
        if checkpoint is not None:
            checkpoint()

    def _single_groupby():
        sql = (
            "SELECT date_bin(INTERVAL '1 minute', ts) AS minute, "
            "max(usage_user) FROM cpu "
            f"WHERE hostname = 'host_0' AND ts >= {T0_MS} "
            f"AND ts < {T0_MS + 3600 * 1000} "
            "GROUP BY minute ORDER BY minute"
        )
        p50, warm, nrows, _ = timed_sql(qe, sql, expect_rows=60)
        log(f"single-groupby-1-1-1: {p50:.1f} ms (warm-up {warm:.0f} ms)")
        results["single_groupby_1_1_1"] = {
            "p50_ms": round(p50, 2), "tier": qe.executor.last_tier, "baseline_ms": BASE_SINGLE_MS,
            "vs_baseline": round(BASE_SINGLE_MS / p50, 3)}

    _run("single_groupby_1_1_1", _single_groupby)

    def _double_groupby():
        avg_list = ", ".join(f"avg({f})" for f in FIELDS)
        sql = (
            f"SELECT date_bin(INTERVAL '1 hour', ts) AS hour, hostname, "
            f"{avg_list} FROM cpu WHERE ts >= {T0_MS} AND ts < {t_end_ms} "
            f"GROUP BY hour, hostname ORDER BY hour, hostname"
        )
        p50, warm, nrows, wspans = timed_sql(qe, sql,
                                             expect_rows=HOSTS * HOURS)
        log(f"double-groupby-all: {p50:.1f} ms (warm-up {warm:.0f} ms, "
            f"{nrows} groups)")
        results["double_groupby_all"] = {
            "p50_ms": round(p50, 2), "tier": qe.executor.last_tier, "warmup_ms": round(warm, 1),
            "groups": nrows, "warmup_spans_ms": wspans,
            "baseline_ms": BASELINE_MS,
            "vs_baseline": round(BASELINE_MS / p50, 3)}
        import jax as _jax
        if _jax.default_backend() != "cpu":
            # A/B both tiers on the headline: the router (with the
            # first-touch hedge) may have served host-side while the
            # device executable compiled in the background — measure
            # each tier explicitly so the artifact carries the chip
            # number AND what the link costs
            prev = os.environ.get("GREPTIMEDB_TPU_HOST_TIER")
            try:
                os.environ["GREPTIMEDB_TPU_HOST_TIER"] = "off"
                p50_d, _, _, _ = timed_sql(qe, sql, repeats=2,
                                           expect_rows=HOSTS * HOURS)
                os.environ["GREPTIMEDB_TPU_HOST_TIER"] = "force"
                p50_h, _, _, _ = timed_sql(qe, sql, repeats=2,
                                           expect_rows=HOSTS * HOURS)
            finally:
                if prev is None:
                    os.environ.pop("GREPTIMEDB_TPU_HOST_TIER", None)
                else:
                    os.environ["GREPTIMEDB_TPU_HOST_TIER"] = prev
            log(f"double-groupby-all A/B: device {p50_d:.1f} ms, "
                f"host {p50_h:.1f} ms")
            results["double_groupby_all"]["device_tier_p50_ms"] = \
                round(p50_d, 2)
            results["double_groupby_all"]["host_tier_p50_ms"] = \
                round(p50_h, 2)

    _run("double_groupby_all", _double_groupby)

    def _gbol():
        # TSBS groupby-orderby-limit: last 5 minute-buckets of max before
        # a cutoff inside the range
        cutoff = T0_MS + (HOURS * 3600 * 1000) * 3 // 4
        sql = (
            "SELECT date_bin(INTERVAL '1 minute', ts) AS minute, "
            f"max(usage_user) FROM cpu WHERE ts < {cutoff} "
            "GROUP BY minute ORDER BY minute DESC LIMIT 5"
        )
        p50, warm, nrows, _ = timed_sql(qe, sql, expect_rows=5)
        log(f"groupby-orderby-limit: {p50:.1f} ms")
        results["groupby_orderby_limit"] = {
            "p50_ms": round(p50, 2), "tier": qe.executor.last_tier, "baseline_ms": BASE_GBOL_MS,
            "vs_baseline": round(BASE_GBOL_MS / p50, 3)}

    _run("groupby_orderby_limit", _gbol)

    def _max_all_8():
        # TSBS cpu-max-all-8: max of all 10 fields for 8 hosts over 8h
        max_list = ", ".join(f"max({f})" for f in FIELDS)
        hosts8 = ", ".join(f"'host_{i}'" for i in range(8))
        sql = (
            f"SELECT date_bin(INTERVAL '1 hour', ts) AS hour, {max_list} "
            f"FROM cpu WHERE hostname IN ({hosts8}) "
            f"AND ts >= {T0_MS} AND ts < {T0_MS + 8 * 3600 * 1000} "
            "GROUP BY hour ORDER BY hour"
        )
        p50, warm, nrows, _ = timed_sql(qe, sql, expect_rows=min(8, HOURS))
        log(f"cpu-max-all-8: {p50:.1f} ms")
        results["cpu_max_all_8"] = {
            "p50_ms": round(p50, 2), "tier": qe.executor.last_tier, "baseline_ms": BASE_MAX_ALL_8_MS,
            "vs_baseline": round(BASE_MAX_ALL_8_MS / p50, 3)}

    _run("cpu_max_all_8", _max_all_8)

    def _lastpoint():
        lv_list = ", ".join(
            f"last_value({f} ORDER BY ts)" for f in FIELDS)
        sql = f"SELECT hostname, {lv_list} FROM cpu GROUP BY hostname"
        p50, warm, nrows, _ = timed_sql(qe, sql, expect_rows=HOSTS)
        path = qe.executor.last_path or ""
        log(f"lastpoint: {p50:.1f} ms (warm-up {warm:.0f} ms, "
            f"path={path})")
        results["lastpoint"] = {
            "p50_ms": round(p50, 2), "tier": qe.executor.last_tier,
            "path": path,  # "lastscan+..." = newest-first pruning hit
            "baseline_ms": BASE_LASTPOINT_MS,
            "vs_baseline": round(BASE_LASTPOINT_MS / p50, 3)}

    _run("lastpoint", _lastpoint)

    def _high_cpu():
        sql = (
            f"SELECT * FROM cpu WHERE usage_user > 90.0 "
            f"AND ts >= {T0_MS} AND ts < {t_end_ms}"
        )
        p50, warm, nrows, _ = timed_sql(qe, sql)
        log(f"high-cpu-all: {p50:.1f} ms ({nrows} rows out)")
        results["high_cpu_all"] = {
            "p50_ms": round(p50, 2), "tier": qe.executor.last_tier, "rows_out": nrows,
            "baseline_ms": BASE_HIGH_CPU_MS,
            "vs_baseline": round(BASE_HIGH_CPU_MS / p50, 3)}

    _run("high_cpu_all", _high_cpu)


def bench_promql(engine, qe, results, ingest_rps=300000.0):
    """Config 3: PromQL rate() over PROM_SERIES x PROM_HOURS @15s —
    tracked spec is 10k series x 1 DAY (57.6M rows). Budget-sized: the
    span shrinks (recorded in `at_spec`/`hours`) if the wall budget
    cannot fit the full day's ingest."""
    from greptimedb_tpu.datatypes import DictVector, RecordBatch

    # width_factor 1.0 ON PURPOSE despite the narrow rows: the numpy
    # anchor re-reads and pivots the whole series set (~half the ingest
    # cost again) and the full-span evals pay XLA compiles — treating
    # the effective rate as the plain ingest rate covers both
    # (round-5: the 24h shape overran the window twice without this)
    affordable = affordable_rows(300, ingest_rps, width_factor=1.0)
    hours = PROM_HOURS
    while hours > 1 and hours * 3600 // 15 * PROM_SERIES > affordable:
        hours //= 2
    if hours < PROM_HOURS:
        log(f"promql span cut to {hours}h (budget {budget_left_s():.0f}s "
            "left)")
    qe.execute_one(
        "CREATE TABLE prom_cpu (host STRING, val DOUBLE, "
        "ts TIMESTAMP(3) NOT NULL, TIME INDEX (ts), PRIMARY KEY (host)) "
        "WITH (append_mode = 'true')")
    info = qe.catalog.table("public", "prom_cpu")
    rid = info.region_ids[0]
    rng = np.random.default_rng(11)
    points = hours * 3600 // 15
    names = np.asarray([f"s{i}" for i in range(PROM_SERIES)], dtype=object)
    slice_points = max(1, (1 << 21) // PROM_SERIES)
    t_start = time.perf_counter()
    rows = 0
    flush_every = max(1, points // (slice_points * 8))
    # counter-style: per-series monotone increments so rate() is
    # realistic. Periodic flushes produce time-bounded SST files (the
    # shape continuous ingestion creates), so scans prune by time.
    for i, p0 in enumerate(range(0, points, slice_points)):
        p1 = min(p0 + slice_points, points)
        npts = p1 - p0
        n = npts * PROM_SERIES
        codes = np.tile(np.arange(PROM_SERIES, dtype=np.int32), npts)
        ts = np.repeat(
            T0_MS + np.arange(p0, p1, dtype=np.int64) * 15000, PROM_SERIES)
        base = np.repeat(
            np.arange(p0, p1, dtype=np.float64) * 50.0, PROM_SERIES)
        vals = base + rng.uniform(0, 50.0, n)
        batch = RecordBatch(info.schema, {
            "host": DictVector(codes, names), "ts": ts, "val": vals})
        engine.put(rid, batch)
        rows += n
        if (i + 1) % flush_every == 0:
            engine.flush(rid)
    log(f"prom ingest: {rows} rows in {time.perf_counter() - t_start:.1f}s")
    engine.flush(rid)
    t0_s = T0_MS // 1000
    t_end_s = t0_s + hours * 3600
    # evaluate over the FULL ingested span at the dashboard step (the
    # tracked config is rate over the whole retention window, not a
    # trailing slice — round-3 verdict weak #5), plus the trailing
    # 10-minute window every dashboard refresh issues
    step_s = max(60, hours * 3600 // 240)  # ~240 eval points
    # rate window scales with the step (a 1-day dashboard uses [6m] at
    # 6m resolution, not [2m]) — and the blocked-window evaluator needs
    # range to be a positive MULTIPLE of step (e.g. 6h span: step 90s
    # needs window 180s, not 120s)
    window_s = -(-max(120, step_s) // step_s) * step_s
    tql = (f"TQL EVAL ({t0_s}, {t_end_s}, '{step_s}s') "
           f"sum(rate(prom_cpu[{window_s}s]))")
    p50, warm, nrows, _ = timed_sql(qe, tql)
    tql_tail = (f"TQL EVAL ({t_end_s - 600}, {t_end_s}, '60s') "
                "sum(rate(prom_cpu[2m]))")
    p50_tail, _, _, _ = timed_sql(qe, tql_tail)
    log(f"promql rate: full-span {p50:.1f} ms, trailing-10m "
        f"{p50_tail:.1f} ms (warm-up {warm:.0f} ms)")
    anchor = None
    try:
        anchor = promql_anchor(engine, qe, t0_s, t_end_s, step_s,
                               window_s)
    except Exception as e:  # noqa: BLE001 — comparator must not sink the run
        log(f"promql anchor failed: {e!r}")
        anchor = {"error": repr(e)[:200]}
    # like-for-like: the engine p50 is the post-warm-up median with
    # series resident in HBM, so the comparator is the anchor's
    # eval-only time, not its one-time parquet load (same convention
    # as anchor_pyarrow_double_groupby's agg_only_p50_ms)
    vs_anchor = None
    if anchor and anchor.get("eval_only_p50_ms"):
        vs_anchor = round(anchor["eval_only_p50_ms"] / p50, 3)
    results["promql_rate"] = {
        "p50_ms": round(p50, 2), "span": "full",
        "eval_points": (t_end_s - t0_s) // step_s,
        "tail_10m_p50_ms": round(p50_tail, 2),
        "series": PROM_SERIES,
        "hours": hours, "at_spec": hours >= PROM_HOURS, "rows": rows,
        "step_s": step_s, "window_s": window_s,
        "anchor": anchor,
        "baseline_ms": (anchor or {}).get("eval_only_p50_ms"),
        "vs_baseline": vs_anchor,
        "note": ("baseline is the same-box numpy straw-man anchor's "
                 "eval-only time (no published reference number for "
                 "this shape)")}


def promql_anchor(engine, qe, t0_s, t_end_s, step_s, window_s=120):
    """Same-box numpy straw-man for `sum(rate(prom_cpu[W]))` — the
    comparator the round-4 verdict asked for (weak #7). Reads the same
    SST parquet, pivots to a dense [S, P] matrix (all series share the
    15s grid), then evaluates Prometheus extrapolated-rate boundary
    semantics (ref src/promql/src/functions/extrapolate_rate.rs:85-92)
    per eval point with vectorized searchsorted — what a competent
    engineer would hand-write in numpy for exactly this data. No
    counter-reset correction: the generated series are strictly
    increasing by construction (base +50/point, noise < 50), so resets
    never occur in this dataset and both sides compute the same
    function. e2e includes the parquet read + pivot; eval_only assumes
    the matrix is resident."""
    import statistics

    import pyarrow.parquet as pq

    info = qe.catalog.table("public", "prom_cpu")
    paths = []
    for rid in info.region_ids:
        region = engine.region(rid)
        paths += [region.sst_reader.path(m.file_id)
                  for m in region.files.values()]
    if not paths:
        return {"skipped": "no SST files"}

    def load():
        import pyarrow as pa
        t = pa.concat_tables(pq.read_table(
            p, columns=["host", "ts", "val"]) for p in paths)
        host = t.column("host").combine_chunks()
        codes = np.asarray(host.dictionary_encode().indices)
        ts = np.asarray(t.column("ts").cast("int64")) // 1000  # s
        vals = np.asarray(t.column("val"))
        grid, t_inv = np.unique(ts, return_inverse=True)
        n_s = int(codes.max()) + 1
        mat = np.empty((n_s, len(grid)))
        mat.fill(np.nan)
        mat[codes, t_inv] = vals
        return grid, mat

    def eval_rate(grid, mat):
        window = window_s
        out = np.empty((t_end_s - t0_s) // step_s + 1)
        for k, t in enumerate(range(t0_s, t_end_s + 1, step_s)):
            # Prometheus range windows are left-open: (t-window, t]
            i0 = np.searchsorted(grid, t - window, side="right")
            i1 = np.searchsorted(grid, t, side="right") - 1
            if i1 <= i0:
                out[k] = np.nan
                continue
            first, last = mat[:, i0], mat[:, i1]
            tf, tl = grid[i0], grid[i1]
            sampled = tl - tf
            slope = (last - first) / sampled
            # Prometheus extrapolation: extend fully to a window edge
            # when the gap is < 1.1x the average sample interval,
            # else cap at half an interval (extrapolate_rate.rs:85-92)
            avg_gap = sampled / max(i1 - i0, 1)
            head, tail = tf - (t - window), t - tl
            duration = sampled \
                + (head if head < 1.1 * avg_gap else avg_gap / 2) \
                + (tail if tail < 1.1 * avg_gap else avg_gap / 2)
            out[k] = float(np.nansum(slope * duration)) / window
        return out

    t0 = time.perf_counter()
    grid, mat = load()
    load_s = time.perf_counter() - t0
    eval_times = []
    for _ in range(max(REPEATS, 1)):
        t0 = time.perf_counter()
        eval_rate(grid, mat)
        eval_times.append(time.perf_counter() - t0)
    eval_p50 = statistics.median(eval_times) * 1000
    e2e_p50 = load_s * 1000 + eval_p50
    log(f"promql anchor (numpy over same SSTs): load {load_s * 1000:.0f} ms "
        f"+ eval {eval_p50:.0f} ms = {e2e_p50:.0f} ms")
    return {"e2e_p50_ms": round(e2e_p50, 2),
            "load_ms": round(load_s * 1000, 2),
            "eval_only_p50_ms": round(eval_p50, 2),
            "note": ("numpy extrapolated-rate straw-man over the same "
                     "parquet on this box; e2e = read+pivot+eval")}


def bench_high_cardinality(engine, qe, results, ingest_rps=300000.0):
    """Config 5: segment-sum over HC_COMBOS distinct tag combos —
    tracked spec is 1B rows x 1M combos (north star). Points-per-combo
    scales toward BENCH_HC_TARGET_ROWS (default 1B) under the wall
    budget; the actual rows and the cut are recorded (`at_spec`)."""
    from greptimedb_tpu.datatypes import DictVector, RecordBatch

    target_rows = int(os.environ.get("BENCH_HC_TARGET_ROWS",
                                     "1000000000"))
    affordable = affordable_rows(150, ingest_rps, width_factor=2.0)
    rows_planned = max(HC_COMBOS * HC_POINTS,
                       min(target_rows, affordable))
    points = max(HC_POINTS, rows_planned // HC_COMBOS)
    qe.execute_one(
        "CREATE TABLE hc (tag STRING, v DOUBLE, ts TIMESTAMP(3) NOT NULL, "
        "TIME INDEX (ts), PRIMARY KEY (tag)) WITH (append_mode = 'true')")
    info = qe.catalog.table("public", "hc")
    rid = info.region_ids[0]
    rng = np.random.default_rng(13)
    names = np.asarray([f"t{i:07d}" for i in range(HC_COMBOS)], dtype=object)
    t_start = time.perf_counter()
    rows = 0
    combos_done = 0
    combos_per_slice = max(1, (1 << 21) // points)
    flushed = 0
    for c0 in range(0, HC_COMBOS, combos_per_slice):
        c1 = min(c0 + combos_per_slice, HC_COMBOS)
        ncomb = c1 - c0
        n = ncomb * points
        codes = np.repeat(np.arange(ncomb, dtype=np.int32), points)
        ts = np.tile(
            T0_MS + np.arange(points, dtype=np.int64) * 1000, ncomb)
        batch = RecordBatch(info.schema, {
            "tag": DictVector(codes, names[c0:c1]), "ts": ts,
            "v": rng.uniform(0, 1, n)})
        engine.put(rid, batch)
        rows += n
        combos_done = c1
        if rows - flushed >= 30_000_000:
            engine.flush(rid)
            flushed = rows
        if budget_left_s() < 420:
            # the query itself scans rows/5M-per-second x (warm + runs)
            # — reserve for it, not just the emit
            log(f"hc ingest stopped at {rows} rows: budget")
            break
    log(f"hc ingest: {rows} rows in {time.perf_counter() - t_start:.1f}s")
    engine.flush(rid)
    sql = "SELECT tag, sum(v) FROM hc GROUP BY tag"
    p50, warm, nrows, _ = timed_sql(qe, sql,
                                    repeats=1 if rows > 50_000_000
                                    else max(1, REPEATS - 1),
                                    expect_rows=combos_done)
    rps = rows / (p50 / 1000.0)
    log(f"high-cardinality: {p50:.1f} ms ({nrows} groups, "
        f"{rps / 1e6:.1f}M rows/s)")
    results["high_cardinality"] = {
        "p50_ms": round(p50, 2), "tier": qe.executor.last_tier,
        "combos": combos_done, "target_combos": HC_COMBOS, "rows": rows,
        "target_rows": target_rows, "at_spec": rows >= target_rows,
        "scan_rows_per_s": round(rps), "baseline_ms": None,
        "vs_baseline": None}
    if budget_left_s() > 150:
        results["high_cardinality"]["sparse_envelope"] = \
            _bench_sparse_envelope(engine, qe)
    else:
        log("hc sparse envelope skipped: budget")


def _bench_sparse_envelope(engine, qe):
    """ISSUE 20 acceptance leg: a 256k-group group-by served by the
    sort-compact plane on the fused and incremental tiers (no dense
    fallback — the served paths are asserted, not assumed), its warm
    repeat against the pre-sparse fallback (whole-scan recompute with
    the partial cache refusing >64k groups), a label-selector lastpoint,
    and the sparse dispatch/compaction metrics for the capture file."""
    import jax

    from greptimedb_tpu.datatypes import DictVector, RecordBatch
    from greptimedb_tpu.utils.metrics import (
        SPARSE_COMPACTION_RATIO,
        SPARSE_DISPATCHES,
    )

    groups = int(os.environ.get("BENCH_HC_SPARSE_GROUPS", str(1 << 18)))
    points = int(os.environ.get("BENCH_HC_SPARSE_POINTS", "4"))
    qe.execute_one(
        "CREATE TABLE hc_sparse (tag STRING, v DOUBLE, ts TIMESTAMP(3) "
        "NOT NULL, TIME INDEX (ts), PRIMARY KEY (tag)) "
        "WITH (append_mode = 'true')")
    info = qe.catalog.table("public", "hc_sparse")
    rid = info.region_ids[0]
    rng = np.random.default_rng(17)
    names = np.asarray([f"t{i:06d}" for i in range(groups)], dtype=object)
    # ts tracks the row index, so a ts window selects a GROUP subset —
    # what lets the CPU fused leg (interpret mode) run a budget-sized
    # slice that still crosses the 4096-segment envelope
    n_total = groups * points
    t0 = time.perf_counter()
    written = 0
    while written < n_total:
        n = min(1 << 21, n_total - written)
        idx = written + np.arange(n)
        codes = (idx // points).astype(np.int32)
        c0, c1 = int(codes[0]), int(codes[-1]) + 1
        engine.put(rid, RecordBatch(info.schema, {
            "tag": DictVector(codes - c0, names[c0:c1]),
            "ts": (T0_MS + idx).astype(np.int64),
            "v": np.floor(rng.uniform(0, 1000, n))}))
        prev = written
        written += n
        if prev < n_total // 2 <= written:
            engine.flush(rid)  # two files: the incremental fold has parts
    engine.flush(rid)
    ingest_s = time.perf_counter() - t0
    log(f"hc sparse: {n_total} rows / {groups} groups ingested in "
        f"{ingest_s:.1f}s")

    paths = {}

    def leg(name, sql, repeats=REPEATS, **overrides):
        saved = {k: os.environ.get(k) for k in overrides}
        for k, v in overrides.items():
            os.environ[k] = v
        try:
            p50, warm_ms, nrows, _ = timed_sql(qe, sql, repeats=repeats)
            paths[name] = qe.executor.last_path
            return p50, warm_ms, nrows
        finally:
            for k, v in saved.items():
                if v is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = v

    # the 256k domain sits inside the default dense budget; the
    # sparse_groups_min knob is exactly the lever that routes it onto
    # the sort-compact plane (as a 1M+ domain would route by itself)
    force = {"GREPTIMEDB_TPU_SPARSE_GROUPS_MIN": "1"}
    sql = "SELECT tag, sum(v), count(v), max(v) FROM hc_sparse GROUP BY tag"
    inc_p50, inc_cold, nrows = leg("incremental", sql, **force)
    assert nrows == groups, (nrows, groups)
    fb_p50, _, _ = leg("fallback", sql,
                       GREPTIMEDB_TPU_PARTIAL_CACHE="off",
                       GREPTIMEDB_TPU_PALLAS="off", **force)
    on_tpu = jax.default_backend() == "tpu"
    fused_rows = n_total if on_tpu else int(
        os.environ.get("BENCH_HC_FUSED_ROWS", "20480"))  # 5120 groups:
    # past the 4096-segment envelope, so interpret mode really tiles
    fused_sql = sql if on_tpu else (
        f"SELECT tag, sum(v) FROM hc_sparse WHERE ts < "
        f"{T0_MS + fused_rows} GROUP BY tag")
    fused_p50, _, fused_groups = leg(
        "fused", fused_sql, repeats=1,
        GREPTIMEDB_TPU_PALLAS="on",
        GREPTIMEDB_TPU_PARTIAL_CACHE="off", **force)
    lp_sql = ("SELECT last_value(v ORDER BY ts) FROM hc_sparse "
              f"WHERE tag = 't{groups // 2:06d}'")
    lp_p50, _, _ = leg("lastpoint", lp_sql)

    for name in ("incremental", "fallback", "fused"):
        if "sparse" not in (paths.get(name) or ""):
            raise RuntimeError(
                f"hc sparse leg {name!r} fell back to {paths.get(name)!r} "
                "— dense fallback is an acceptance failure")
    speedup = fb_p50 / inc_p50 if inc_p50 > 0 else float("inf")
    log(f"hc sparse 256k-group: warm {inc_p50:.1f} ms vs pre-sparse "
        f"fallback {fb_p50:.1f} ms ({speedup:.1f}x); fused "
        f"{fused_p50:.1f} ms over {fused_groups} groups; lastpoint "
        f"{lp_p50:.2f} ms")
    return {
        "groups": groups, "rows": n_total,
        "ingest_rows_per_s": round(n_total / ingest_s),
        "groupby_warm_p50_ms": round(inc_p50, 2),
        "groupby_cold_ms": round(inc_cold, 2),
        "fallback_p50_ms": round(fb_p50, 2),
        "warm_speedup_vs_fallback": round(speedup, 2),
        "meets_2x": speedup >= 2.0,
        "fused_p50_ms": round(fused_p50, 2), "fused_rows": fused_rows,
        "fused_groups": int(fused_groups),
        "lastpoint_p50_ms": round(lp_p50, 2),
        "paths": paths,
        "sparse_dispatch_total": {
            p: SPARSE_DISPATCHES.get(path=p)
            for p in ("classic", "fused", "sharded", "incremental",
                      "vmapped")},
        "compaction_ratio": round(SPARSE_COMPACTION_RATIO.get(), 6)}


def bench_double_groupby_100m(engine, qe, results, ingest_rps):
    """Tracked config #2 (BASELINE.json): double-groupby-all at 100M
    rows / 4k hosts / 10 fields — the HEADLINE QUERY pointed at the
    streaming machinery (round-4 verdict weak #6: `stream_large` ran a
    different query). Ingest is sized against the wall-clock budget;
    if the full 100M cannot fit, it runs at the largest size that does
    and records the cut explicitly (`at_spec`: false)."""
    from greptimedb_tpu.datatypes import DictVector, RecordBatch

    rows_target = int(os.environ.get("BENCH_STREAM_ROWS", "100000000"))
    n_hosts = 4000
    # reserve for the query itself (~120 s warm + runs) and the
    # remaining tracked configs (promql/hc/compaction, ~480 s). The
    # extra 0.4 derate is measured, not cautious: the 17M calibration
    # ingest ran at 590k rows/s but the 100M sustained 190k — flush
    # and L0 debt compound at scale
    affordable = affordable_rows(600, ingest_rps * 0.4)
    rows_planned = min(rows_target, affordable)
    if rows_planned < 10_000_000:
        left = budget_left_s()
        log(f"double_groupby_100m skipped: budget affords only "
            f"{rows_planned} rows ({left:.0f}s left)")
        results["double_groupby_100m"] = {
            "skipped": f"budget ({left:.0f}s left)",
            "target_rows": rows_target, "at_spec": False}
        return
    points = rows_planned // n_hosts
    step_ms = 10_000
    field_defs = ", ".join(f"{f} DOUBLE" for f in FIELDS)
    qe.execute_one(
        f"CREATE TABLE cpu_big (hostname STRING, ts TIMESTAMP(3) NOT "
        f"NULL, {field_defs}, TIME INDEX (ts), PRIMARY KEY (hostname)) "
        "WITH (append_mode = 'true')")
    info = qe.catalog.table("public", "cpu_big")
    rid = info.region_ids[0]
    rng = np.random.default_rng(23)
    names = np.asarray([f"host_{i}" for i in range(n_hosts)], dtype=object)
    slice_points = max(1, (1 << 21) // n_hosts)
    rows = 0
    t_start = time.perf_counter()
    t_logged = t_start
    for i, p0 in enumerate(range(0, points, slice_points)):
        p1 = min(p0 + slice_points, points)
        npts = p1 - p0
        n = npts * n_hosts
        codes = np.tile(np.arange(n_hosts, dtype=np.int32), npts)
        ts = np.repeat(
            T0_MS + np.arange(p0, p1, dtype=np.int64) * step_ms, n_hosts)
        cols = {"hostname": DictVector(codes, names), "ts": ts}
        for f in FIELDS:
            cols[f] = rng.uniform(0.0, 100.0, n)
        engine.put(rid, RecordBatch(info.schema, cols))
        rows += n
        if (i + 1) % 4 == 0:
            engine.flush(rid)  # bound memtable growth during ingest
        now = time.perf_counter()
        if now - t_logged > 60:
            log(f"100m ingest progress: {rows} rows, "
                f"{rows / (now - t_start):,.0f} rows/s")
            t_logged = now
        if budget_left_s() < 480:
            # the plan was affordable at start, but sustained ingest
            # rate on a shared box swings 3x run to run — stop HERE,
            # measure what landed, and leave the remaining configs
            # their reserve (the cut is recorded via rows < target)
            log(f"100m ingest stopped at {rows} rows: budget")
            break
    engine.flush(rid)
    ingest_s = time.perf_counter() - t_start
    log(f"100m ingest: {rows} rows in {ingest_s:.0f}s "
        f"({rows / ingest_s:,.0f} rows/s)")
    points = rows // n_hosts  # bucket math below reflects actual rows
    hours = -(-(points * step_ms) // 3_600_000)  # ceil
    avg_list = ", ".join(f"avg({f})" for f in FIELDS)
    sql = (f"SELECT date_bin(INTERVAL '1 hour', ts) AS hour, hostname, "
           f"{avg_list} FROM cpu_big GROUP BY hour, hostname")
    # every host appears in every hour bucket by construction — a
    # partial scan cannot silently post a fast p50
    p50, warm, nrows, wspans = timed_sql(qe, sql, repeats=1,
                                         expect_rows=n_hosts * hours)
    path = qe.executor.last_path
    rps = rows / (p50 / 1000.0)
    log(f"double-groupby-100m: {p50:.0f} ms over {rows} rows, "
        f"{nrows} groups ({rps / 1e6:.0f}M rows/s, path={path})")
    results["double_groupby_100m"] = {
        "p50_ms": round(p50, 1), "tier": qe.executor.last_tier, "warmup_ms": round(warm, 1),
        "rows": rows, "target_rows": rows_target,
        "at_spec": rows >= rows_target, "hosts": n_hosts,
        "sim_hours": hours, "groups": nrows, "path": path,
        "scan_rows_per_s": round(rps), "warmup_spans_ms": wspans,
        "baseline_ms": None, "vs_baseline": None,
        "note": ("the headline double-groupby-all query at tracked "
                 "config #2 scale; no published reference number at "
                 "100M — reference 2215.44 ms is at TSBS-scale")}


def bench_compaction(engine, qe, results):
    """Config 4 analog: L0→L1 TWCS merge re-encode throughput."""
    from greptimedb_tpu.datatypes import DictVector, RecordBatch

    qe.execute_one(
        "CREATE TABLE comp (host STRING, v DOUBLE, ts TIMESTAMP(3) NOT "
        "NULL, TIME INDEX (ts), PRIMARY KEY (host))")
    info = qe.catalog.table("public", "comp")
    rid = info.region_ids[0]
    rng = np.random.default_rng(17)
    n_hosts = 1000
    names = np.asarray([f"h{i}" for i in range(n_hosts)], dtype=object)
    n_files = 4
    per_file = COMPACT_ROWS // n_files
    for f in range(n_files):
        pts = per_file // n_hosts
        codes = np.tile(np.arange(n_hosts, dtype=np.int32), pts)
        # overlapping time ranges across files force a real merge
        ts = np.repeat(
            T0_MS + f * 500 + np.arange(pts, dtype=np.int64) * 1000, n_hosts)
        batch = RecordBatch(info.schema, {
            "host": DictVector(codes, names), "ts": ts,
            "v": rng.uniform(0, 1, pts * n_hosts)})
        engine.put(rid, batch)
        engine.flush(rid)
    rows = n_files * per_file // n_hosts * n_hosts
    t = time.perf_counter()
    engine.compact(rid)
    dt = time.perf_counter() - t
    rps = rows / dt
    log(f"compaction re-encode: {rows} rows in {dt:.2f}s "
        f"({rps / 1e6:.2f}M rows/s)")
    results["compaction_reencode"] = {
        "seconds": round(dt, 2), "rows": rows,
        "reencode_rows_per_s": round(rps), "baseline_ms": None,
        "vs_baseline": None}


def bench_anchor(engine, qe, results):
    """Same-box anchor for the headline number (round-3 verdict weak #1:
    the published reference ran on different hardware). Re-runs the
    double-groupby-all computation over the SAME SST files with pyarrow's
    C++ hash group-by — a best-effort conventional columnar engine on
    THIS machine — so vs_baseline has a local comparator whose hardware
    noise cancels."""
    import pyarrow as pa
    import pyarrow.compute as pc
    import pyarrow.parquet as pq

    import statistics

    info = qe.catalog.table("public", "cpu")
    paths = []
    for rid in info.region_ids:
        region = engine.region(rid)
        paths += [region.sst_reader.path(m.file_id)
                  for m in region.files.values()]
    if not paths:
        log("anchor skipped: no SST files (nothing flushed?)")
        results["anchor_pyarrow_double_groupby"] = {
            "skipped": "no SST files"}
        return
    cols = ["hostname", "ts"] + FIELDS

    def agg(t):
        # hour bucketing is INSIDE the timed op: the engine's p50 pays
        # date_bin per query too — both sides time the same computation
        hour = pc.floor_temporal(t.column("ts"), unit="hour")
        t = t.drop_columns(["ts"]).append_column("hour", hour)
        return t.group_by(["hour", "hostname"]).aggregate(
            [(f, "mean") for f in FIELDS])

    def read():
        return pa.concat_tables(pq.read_table(p, columns=cols)
                                for p in paths)

    agg(read())  # warm the page cache like the engine's warm-up does
    e2e, agg_only = [], []
    cached = read()
    for _ in range(max(REPEATS, 1)):
        t0 = time.perf_counter()
        out = agg(read())
        e2e.append(time.perf_counter() - t0)
        t0 = time.perf_counter()
        agg(cached)
        agg_only.append(time.perf_counter() - t0)
    p50 = statistics.median(e2e) * 1000
    p50_agg = statistics.median(agg_only) * 1000
    log(f"anchor (pyarrow over same SSTs): read+agg {p50:.0f} ms, "
        f"agg-only {p50_agg:.0f} ms ({out.num_rows} groups, "
        f"{cached.num_rows} rows)")
    results["anchor_pyarrow_double_groupby"] = {
        "p50_ms": round(p50, 2),
        "agg_only_p50_ms": round(p50_agg, 2),
        "groups": out.num_rows,
        "rows_read": cached.num_rows,
        "note": ("pyarrow C++ hash aggregate (incl. hour bucketing) over "
                 "the same parquet on this machine — the same-box "
                 "comparator for double_groupby_all (agg-only excludes "
                 "the parquet read, matching the engine's HBM-cached "
                 "p50)")}


def bench_maintenance(engine, qe, results):
    """Maintenance-plane micro-phase (ISSUE 4): async flush submission
    latency (what the writer actually pays), downsample job throughput,
    and the rollup-substituted coarse query against its raw oracle."""
    maint = getattr(engine, "maintenance", None)
    if maint is None:
        results["maintenance"] = {"skipped": "plane disabled"}
        return
    from greptimedb_tpu.datatypes import DictVector, RecordBatch

    qe.execute_one(
        "CREATE TABLE mbench (host STRING, v DOUBLE, ts TIMESTAMP(3) "
        "TIME INDEX, PRIMARY KEY(host))")
    info = qe.catalog.table("public", "mbench")
    rid = info.region_ids[0]
    schema = info.schema
    hosts, points = 20, 7200  # 2h @1s x 20 hosts = 144k rows
    host_names = np.asarray([f"m{i}" for i in range(hosts)], dtype=object)
    rng = np.random.default_rng(11)
    n = hosts * points
    batch = RecordBatch(schema, {
        "host": DictVector(np.tile(np.arange(hosts, dtype=np.int32),
                                   points), host_names),
        "ts": np.repeat(np.arange(points, dtype=np.int64) * 1000, hosts),
        "v": np.floor(rng.uniform(0.0, 100.0, n)),  # exact in f64
    })
    engine.put(rid, batch)
    t0 = time.perf_counter()
    r = qe.execute_one("ADMIN flush_table('mbench')")
    submit_ms = (time.perf_counter() - t0) * 1000  # what a writer pays
    flush_jobs = [maint.wait(int(row[0]), timeout=120) for row in r.rows()]
    t0 = time.perf_counter()
    rj = qe.execute_one("ADMIN rollup_table('mbench', '1m')")
    rollup_jobs = [maint.wait(int(row[0]), timeout=300) for row in rj.rows()]
    rollup_ms = (time.perf_counter() - t0) * 1000
    sql = ("SELECT host, date_bin(INTERVAL '5 minutes', ts) AS b, "
           "min(v), max(v), sum(v), count(*) FROM mbench "
           "WHERE ts >= 0 AND ts < 6000000 GROUP BY host, b "
           "ORDER BY host, b")
    os.environ["GTPU_ROLLUP_SUBSTITUTE"] = "0"
    try:
        raw_p50, raw_warm, raw_rows, _ = timed_sql(qe, sql)
    finally:
        os.environ.pop("GTPU_ROLLUP_SUBSTITUTE", None)
    sub_p50, sub_warm, sub_rows, _ = timed_sql(qe, sql)
    substituted = "+rollup" in (getattr(qe.executor, "last_path", "") or "")
    os.environ["GTPU_ROLLUP_SUBSTITUTE"] = "0"
    try:
        oracle_rows = qe.execute_one(sql).rows()
    finally:
        os.environ.pop("GTPU_ROLLUP_SUBSTITUTE", None)
    exact_match = oracle_rows == qe.execute_one(sql).rows()
    from greptimedb_tpu.utils.metrics import WRITE_STALL_SECONDS

    results["maintenance"] = {
        "rows": n,
        "flush_submit_ms": round(submit_ms, 2),
        "flush_job_ms": round(max(
            (j.duration_ms or 0.0) for j in flush_jobs), 1),
        "rollup_job_ms": round(rollup_ms, 1),
        "rollup_rows_out": sum(
            j.detail.get("rows_out", 0) for j in rollup_jobs),
        "coarse_query_raw_p50_ms": round(raw_p50, 2),
        "coarse_query_rollup_p50_ms": round(sub_p50, 2),
        "substituted": substituted,
        "results_match": exact_match,
        "write_stall_seconds": round(WRITE_STALL_SECONDS.total(), 3),
    }
    log(f"maintenance: flush submit {submit_ms:.1f} ms, rollup job "
        f"{rollup_ms:.0f} ms -> {results['maintenance']['rollup_rows_out']}"
        f" plane rows, coarse query {raw_p50:.1f} -> {sub_p50:.1f} ms "
        f"(substituted={substituted})")


def bench_scan_pipeline(engine, qe, results):
    """Scan-pipeline micro-phase (ISSUE 5): the cold double-groupby-
    shaped scan through the parallel decode pool vs the sequential
    path (bit-for-bit checked), the warm per-file-cache scan, and the
    post-flush incremental scan that must decode ONLY the new file."""
    from greptimedb_tpu.datatypes import DictVector, RecordBatch

    rows_target = int(os.environ.get("BENCH_SCANPIPE_ROWS", "4000000"))
    n_files, n_hosts = 4, 1000
    field_defs = ", ".join(f"{f} DOUBLE" for f in FIELDS)
    qe.execute_one(
        f"CREATE TABLE scanp (hostname STRING, ts TIMESTAMP(3) NOT NULL, "
        f"{field_defs}, TIME INDEX (ts), PRIMARY KEY (hostname)) "
        "WITH (append_mode = 'true')")
    info = qe.catalog.table("public", "scanp")
    rid = info.region_ids[0]
    rng = np.random.default_rng(29)
    names = np.asarray([f"host_{i}" for i in range(n_hosts)], dtype=object)
    per_file = rows_target // n_files
    pts = per_file // n_hosts
    for f in range(n_files):
        codes = np.tile(np.arange(n_hosts, dtype=np.int32), pts)
        ts = np.repeat(
            T0_MS + (f * pts + np.arange(pts, dtype=np.int64)) * 1000,
            n_hosts)
        cols = {"hostname": DictVector(codes, names), "ts": ts}
        for fld in FIELDS:
            cols[fld] = rng.uniform(0.0, 100.0, pts * n_hosts)
        engine.put(rid, RecordBatch(info.schema, cols))
        engine.flush(rid)
    region = engine.region(rid)

    def clear_caches(parts=True):
        with region._lock:
            region._scan_cache.clear()
            if parts:
                region._part_cache.clear()
                region._part_cache_bytes = 0

    def cold_scan(threads):
        clear_caches()
        prev = os.environ.get("GREPTIMEDB_TPU_SCAN_DECODE_THREADS")
        os.environ["GREPTIMEDB_TPU_SCAN_DECODE_THREADS"] = str(threads)
        try:
            t0 = time.perf_counter()
            scan = engine.scan(rid)
            ms = (time.perf_counter() - t0) * 1000
        finally:
            if prev is None:
                os.environ.pop("GREPTIMEDB_TPU_SCAN_DECODE_THREADS", None)
            else:
                os.environ["GREPTIMEDB_TPU_SCAN_DECODE_THREADS"] = prev
        return ms, scan

    seq_ms, seq_scan = cold_scan(1)
    par_ms, par_scan = cold_scan(0)
    identical = (
        seq_scan.num_rows == par_scan.num_rows
        and seq_scan.sorted_part_offsets == par_scan.sorted_part_offsets
        and all(np.array_equal(np.asarray(seq_scan.columns[k]),
                               np.asarray(par_scan.columns[k]))
                for k in seq_scan.columns)
        and np.array_equal(seq_scan.seq, par_scan.seq)
        and np.array_equal(seq_scan.op_type, par_scan.op_type))
    # warm: whole-scan cache cleared, per-file parts kept -> 0 decodes
    clear_caches(parts=False)
    t0 = time.perf_counter()
    warm_scan = engine.scan(rid)
    warm_ms = (time.perf_counter() - t0) * 1000
    # incremental: one small flush -> exactly ONE file decoded
    small = 10 * n_hosts
    codes = np.tile(np.arange(n_hosts, dtype=np.int32), 10)
    ts = np.repeat(
        T0_MS + (n_files * pts + np.arange(10, dtype=np.int64)) * 1000,
        n_hosts)
    cols = {"hostname": DictVector(codes, names), "ts": ts}
    for fld in FIELDS:
        cols[fld] = rng.uniform(0.0, 100.0, small)
    engine.put(rid, RecordBatch(info.schema, cols))
    engine.flush(rid)
    t0 = time.perf_counter()
    incr_scan = engine.scan(rid)
    incr_ms = (time.perf_counter() - t0) * 1000
    speedup = seq_ms / par_ms if par_ms > 0 else None
    log(f"scan-pipeline: cold seq {seq_ms:.0f} ms -> parallel "
        f"{par_ms:.0f} ms ({speedup:.2f}x, identical={identical}), "
        f"part-warm {warm_ms:.0f} ms "
        f"({warm_scan.stats['files_decoded']} decodes), post-flush "
        f"{incr_ms:.0f} ms ({incr_scan.stats['files_decoded']} decodes)")
    results["scan_pipeline"] = {
        "rows": int(seq_scan.num_rows),
        "files": n_files,
        "cold_sequential_ms": round(seq_ms, 1),
        "cold_parallel_ms": round(par_ms, 1),
        "parallel_speedup": round(speedup, 2) if speedup else None,
        "bit_for_bit_identical": bool(identical),
        "decode_workers": par_scan.stats.get("decode_workers"),
        "warm_part_cache_ms": round(warm_ms, 1),
        "warm_files_decoded": warm_scan.stats["files_decoded"],
        "post_flush_ms": round(incr_ms, 1),
        "post_flush_files_decoded": incr_scan.stats["files_decoded"],
        "baseline_ms": None, "vs_baseline": None}


def bench_device_tier(engine, qe, results):
    """Device-tier micro-phase (ISSUE 7): the headline double-groupby
    shape pinned to the device tier — cold (empty hot set) vs hot-set-
    warm p50, warmup compile seconds, per-query H2D bytes from the
    transfer-counter deltas, accountant-folded achieved GB/s and
    roofline fraction (ledger bytes over probed link peak — replaces
    the old allocator-only hbm_utilization readout), and the
    post-flush query that must re-upload ONLY the new file's blocks."""
    from greptimedb_tpu.datatypes import DictVector, RecordBatch
    from greptimedb_tpu.utils import ledger, roofline
    from greptimedb_tpu.utils.metrics import (
        DEVICE_HOT_SET_BYTES,
        DEVICE_TRANSFER_BYTES,
        PALLAS_DISPATCHES,
        XLA_COMPILE_SECONDS,
    )

    avg_list = ", ".join(f"avg({f})" for f in FIELDS)
    t_end_ms = T0_MS + HOURS * 3600 * 1000
    sql = (
        f"SELECT date_bin(INTERVAL '1 hour', ts) AS hour, hostname, "
        f"{avg_list} FROM cpu WHERE ts >= {T0_MS} AND ts < {t_end_ms} "
        f"GROUP BY hour, hostname ORDER BY hour, hostname"
    )
    ex = qe.executor

    def h2d():
        return DEVICE_TRANSFER_BYTES.get(direction="h2d")

    def compile_s():
        with XLA_COMPILE_SECONDS._lock:
            return sum(XLA_COMPILE_SECONDS._sum.values())

    def fused_dispatches():
        return PALLAS_DISPATCHES.get(kernel="fused_agg")

    prev = os.environ.get("GREPTIMEDB_TPU_HOST_TIER")
    os.environ["GREPTIMEDB_TPU_HOST_TIER"] = "off"  # pin the device tier
    try:
        ex.cache.clear()  # cold: nothing resident in HBM
        c0, b0, f0 = compile_s(), h2d(), fused_dispatches()
        # fold the cold run (the bandwidth-bound one: real H2D traffic)
        # through the per-query ledger so the roofline numbers come from
        # the same accountant that stamps spans and slow-query records
        with ledger.attach_fresh() as led:
            t0 = time.perf_counter()
            qe.execute_one(sql)
            cold_ms = (time.perf_counter() - t0) * 1000
        cold_counts = ledger.derive(led.snapshot()) if led is not None \
            else {}
        warmup_compile_s = compile_s() - c0
        cold_h2d = h2d() - b0
        path = ex.last_path
        # hot-set-warm: every block is already HBM-resident, so the
        # steady-state dashboard repeat should pay ~zero H2D
        reps = max(REPEATS, 5)
        times, b1 = [], h2d()
        for _ in range(reps):
            t0 = time.perf_counter()
            qe.execute_one(sql)
            times.append((time.perf_counter() - t0) * 1000)
        warm_ms = float(np.median(times))
        warm_h2d_per_q = (h2d() - b1) / reps
        # post-flush incremental: the file-anchored hot set keeps the
        # old files' blocks, so the re-upload is the new file only
        info = qe.catalog.table("public", "cpu")
        rid = info.region_ids[0]
        small = 200
        names = np.asarray([f"host_{i}" for i in range(small)],
                           dtype=object)
        # INSIDE the queried window: an out-of-range flush would be
        # pruned outright and the "new file only" H2D claim would
        # measure nothing
        cols = {"hostname": DictVector(
                    np.arange(small, dtype=np.int32), names),
                "ts": np.full(small, t_end_ms - 1000, dtype=np.int64)}
        rng = np.random.default_rng(31)
        for fld in FIELDS:
            cols[fld] = rng.uniform(0.0, 100.0, small)
        engine.put(rid, RecordBatch(info.schema, cols))
        engine.flush(rid)
        b2 = h2d()
        t0 = time.perf_counter()
        qe.execute_one(sql)
        incr_ms = (time.perf_counter() - t0) * 1000
        incr_h2d = h2d() - b2
        hot_bytes = DEVICE_HOT_SET_BYTES.get()
        fused_served = fused_dispatches() - f0
    finally:
        if prev is None:
            os.environ.pop("GREPTIMEDB_TPU_HOST_TIER", None)
        else:
            os.environ["GREPTIMEDB_TPU_HOST_TIER"] = prev
    # accountant-folded roofline for the cold (bandwidth-bound) run:
    # ledger bytes over device time vs the probed link peak — the same
    # numbers stamped on spans, so bench and traces can't disagree
    rf = roofline.account(cold_counts, duration_ms=cold_ms)
    achieved = round(rf["achieved_gbps"], 3) if rf else None
    fraction = round(rf["roofline_fraction"], 4) if rf else None
    log(f"device-tier: cold {cold_ms:.0f} ms ({cold_h2d / 1e6:.0f} MB "
        f"H2D, compile {warmup_compile_s:.1f}s) -> warm {warm_ms:.1f} ms "
        f"({warm_h2d_per_q / 1e6:.2f} MB/query), post-flush "
        f"{incr_ms:.0f} ms ({incr_h2d / 1e6:.1f} MB), path={path}, "
        f"hot set {hot_bytes / 1e6:.0f} MB, achieved_gbps={achieved} "
        f"roofline_fraction={fraction}")
    results["device_tier"] = {
        "path": path,
        "cold_ms": round(cold_ms, 1),
        "warm_p50_ms": round(warm_ms, 2),
        "warmup_compile_s": round(warmup_compile_s, 2),
        "cold_h2d_bytes": int(cold_h2d),
        "warm_h2d_bytes_per_query": int(warm_h2d_per_q),
        "post_flush_ms": round(incr_ms, 1),
        "post_flush_h2d_bytes": int(incr_h2d),
        "hot_set_bytes": int(hot_bytes),
        "fused_kernel_dispatches": int(fused_served),
        "achieved_gbps": achieved,
        "roofline_fraction": fraction,
        "baseline_ms": None, "vs_baseline": None}


def bench_sql_insert(qe, results, rows_total=None, per_stmt=500):
    """SQL INSERT path (parse -> bind -> region write incl. WAL), the
    slower sibling of the bulk RecordBatch route the headline ingest
    number uses — reported separately so both write paths are tracked."""
    rows_total = rows_total or int(
        os.environ.get("BENCH_SQL_INSERT_ROWS", "50000"))
    rng = np.random.default_rng(11)
    t_ms = T0_MS + 365 * 24 * 3600 * 1000  # far from the scan data
    done = 0
    t_start = time.perf_counter()
    while done < rows_total:
        n = min(per_stmt, rows_total - done)
        vals = ", ".join(
            f"('host_{int(h)}', {t_ms + i}, " +
            ", ".join(f"{v:.3f}" for v in row) + ")"
            for i, (h, row) in enumerate(zip(
                rng.integers(0, HOSTS, n),
                rng.uniform(0.0, 100.0, (n, len(FIELDS)))))
        )
        qe.execute_one(
            f"INSERT INTO cpu (hostname, ts, {', '.join(FIELDS)}) "
            f"VALUES {vals}")
        t_ms += n
        done += n
    dt = time.perf_counter() - t_start
    rps = done / dt
    log(f"sql insert: {done} rows in {dt:.1f}s ({rps:,.0f} rows/s)")
    results["sql_insert"] = {
        "rows": done, "rows_per_s": round(rps),
        "vs_bulk_note": "statement path; headline ingest uses bulk "
                        "RecordBatch puts"}


def bench_ingest_qps(engine, qe, results, writers=None, seconds=None):
    """Config: production-rate protocol ingest (ISSUE 9). N concurrent
    writers — a line-protocol + SQL INSERT mix, the two statement-path
    front doors real users hit — hammer a dedicated table while
    background readers keep querying the warm cpu table. Reports
    aggregate rows/s (anchor: the 7.4k rows/s pre-pipeline statement
    path), p99 ack latency per front door, the write-stall delta, and
    read-p50 degradation vs idle — write/read isolation under the
    maintenance plane's backpressure."""
    import threading

    from greptimedb_tpu.servers.influx import write_lines
    from greptimedb_tpu.utils.metrics import (
        INGEST_GROUP_COMMIT_EVENTS,
        WRITE_STALL_SECONDS,
    )

    # sizing: the line-protocol parse is GIL-bound, so writer count
    # tracks cores (oversubscription convoys the GIL on small boxes);
    # ONE app-style SQL INSERT stream rides along at a steady pace —
    # its per-statement parse is pure Python and an unpaced tight loop
    # would measure GIL starvation, not the serving stack
    default_w = max(5, min(12, 2 * (os.cpu_count() or 4) + 1))
    writers = writers or int(os.environ.get("BENCH_INGEST_WRITERS",
                                            str(default_w)))
    duration = seconds or float(os.environ.get("BENCH_INGEST_SECONDS", "12"))
    sql_writers = 1
    sql_pace_s = 0.1
    lp_writers = max(1, writers - sql_writers)
    # 5000 lines/request = Telegraf's default max batch; the commit
    # pipeline amortizes one fsync over the whole group, so request
    # size sets the floor on rows-per-fsync when the disk is slow
    lp_rows, sql_rows = 5000, 500
    rng = np.random.default_rng(23)
    ingest_fields = [f"f{i}" for i in range(5)]

    def lp_body(w, i):
        t0 = 1_000_000 + (w * 1000 + i) * lp_rows
        vals = rng.uniform(0.0, 100.0, (lp_rows, len(ingest_fields)))
        hosts = rng.integers(0, 200, lp_rows)
        field_list = ",".join(ingest_fields)
        return "\n".join(
            f"ingestq,hostname=host_{int(h)} "
            + ",".join(f"{f}={v:.3f}" for f, v in zip(ingest_fields, row))
            + f" {t0 + j}"
            for j, (h, row) in enumerate(zip(hosts, vals))), field_list

    def sql_stmt(w, i):
        t0 = 500_000_000 + (w * 1000 + i) * sql_rows
        vals = ", ".join(
            f"('host_{int(h)}', {t0 + j}, "
            + ", ".join(f"{v:.3f}" for v in row) + ")"
            for j, (h, row) in enumerate(zip(
                rng.integers(0, 200, sql_rows),
                rng.uniform(0.0, 100.0, (sql_rows, len(ingest_fields))))))
        return (f"INSERT INTO ingestq (hostname, ts, "
                f"{', '.join(ingest_fields)}) VALUES {vals}")

    # auto-create the table + pre-generate the request pool OUTSIDE the
    # clock (client-side cost, not serving cost); writers cycle their
    # pool — duplicate (host, ts) keys are fine for a rate measurement
    write_lines(qe, "public", lp_body(99, 0)[0], precision="ms")
    lp_pool = [[lp_body(w, i)[0] for i in range(4)]
               for w in range(lp_writers)]
    sql_pool = [[sql_stmt(w, i) for i in range(4)]
                for w in range(sql_writers)]

    read_sql = (
        f"SELECT date_bin(INTERVAL '1 minute', ts) AS minute, "
        f"max(usage_user) FROM cpu WHERE hostname = 'host_1' "
        f"AND ts >= {T0_MS} AND ts < {T0_MS + 3600 * 1000} GROUP BY minute")
    qe.execute_one(read_sql)  # warm
    idle = []
    for _ in range(20):
        t0 = time.perf_counter()
        qe.execute_one(read_sql)
        idle.append(time.perf_counter() - t0)
    idle_p50 = float(np.median(idle)) * 1000

    stall0 = WRITE_STALL_SECONDS.total()
    gc0 = {e: INGEST_GROUP_COMMIT_EVENTS.total(event=e)
           for e in ("lead", "follow", "overflow")}
    sync0 = getattr(engine.wal, "sync_count", 0)
    stop = threading.Event()
    rows_done = [0] * (lp_writers + sql_writers)
    lp_lat: list = [[] for _ in range(lp_writers)]
    sql_lat: list = [[] for _ in range(sql_writers)]
    read_lat: list = [[] for _ in range(2)]
    errors = [0] * (lp_writers + sql_writers)

    def lp_writer(w):
        i = 0
        while not stop.is_set():
            t0 = time.perf_counter()
            try:
                write_lines(qe, "public", lp_pool[w][i % len(lp_pool[w])],
                            precision="ms")
            except Exception:  # noqa: BLE001 — typed Overloaded included
                errors[w] += 1
                continue
            lp_lat[w].append(time.perf_counter() - t0)
            rows_done[w] += lp_rows
            i += 1

    def sql_writer(w):
        i = 0
        while not stop.is_set():
            t0 = time.perf_counter()
            try:
                qe.execute_one(sql_pool[w][i % len(sql_pool[w])])
            except Exception:  # noqa: BLE001 — typed Overloaded included
                errors[lp_writers + w] += 1
                continue
            sql_lat[w].append(time.perf_counter() - t0)
            rows_done[lp_writers + w] += sql_rows
            i += 1
            time.sleep(sql_pace_s)

    def reader(r):
        while not stop.is_set():
            t0 = time.perf_counter()
            try:
                qe.execute_one(read_sql)
            except Exception:  # noqa: BLE001 — keep reading under load
                continue
            read_lat[r].append(time.perf_counter() - t0)

    threads = ([threading.Thread(target=lp_writer, args=(w,))
                for w in range(lp_writers)]
               + [threading.Thread(target=sql_writer, args=(w,))
                  for w in range(sql_writers)]
               + [threading.Thread(target=reader, args=(r,))
                  for r in range(2)])
    t_start = time.perf_counter()
    for t in threads:
        t.start()
    time.sleep(duration)
    stop.set()
    for t in threads:
        t.join(60)
    wall = time.perf_counter() - t_start

    total_rows = sum(rows_done)
    rate = total_rows / wall
    lp_all = np.asarray([x for l in lp_lat for x in l])
    sql_all = np.asarray([x for l in sql_lat for x in l])
    reads = np.asarray([x for l in read_lat for x in l])
    stall_delta = WRITE_STALL_SECONDS.total() - stall0
    gc = {e: INGEST_GROUP_COMMIT_EVENTS.total(event=e) - gc0[e]
          for e in gc0}
    syncs = getattr(engine.wal, "sync_count", 0) - sync0
    commits = max(1.0, gc["lead"])
    loaded_p50 = (float(np.median(reads)) * 1000 if reads.size
                  else None)
    lp_p99 = (float(np.percentile(lp_all, 99)) * 1000
              if lp_all.size else None)
    log(f"ingest_qps: {rate:,.0f} rows/s over {wall:.1f}s "
        f"({lp_writers} lp + {sql_writers} sql writers; "
        f"lp p99 {-1.0 if lp_p99 is None else lp_p99:.1f} ms, "
        f"{gc['lead']:.0f} commits / {syncs} fsyncs, "
        f"{gc['follow']:.0f} followers, stall {stall_delta:.2f}s, "
        f"read p50 {idle_p50:.1f} -> {loaded_p50 or -1:.1f} ms, "
        f"{sum(errors)} errors)")
    results["ingest_qps"] = {
        "rows_per_s": round(rate),
        "writers": {"line_protocol": lp_writers, "sql_insert": sql_writers},
        "rows": total_rows,
        "errors": sum(errors),
        "lp_p99_ack_ms": None if lp_p99 is None else round(lp_p99, 2),
        "sql_p99_ack_ms": round(float(np.percentile(sql_all, 99)) * 1000, 2)
        if sql_all.size else None,
        "group_commits": int(gc["lead"]),
        "followers": int(gc["follow"]),
        "overflows": int(gc["overflow"]),
        "wal_fsyncs": int(syncs),
        "rows_per_commit": round(total_rows / commits, 1),
        "write_stall_seconds_delta": round(stall_delta, 3),
        "read_p50_idle_ms": round(idle_p50, 2),
        "read_p50_loaded_ms": (None if loaded_p50 is None
                               else round(loaded_p50, 2)),
        "read_degradation": (None if loaded_p50 is None or idle_p50 == 0
                             else round(loaded_p50 / idle_p50, 2)),
        # the pre-pipeline statement path managed 7.4k rows/s (r05);
        # acceptance wants >= 10x through the protocol front doors
        "anchor_rows_s": 7400,
        "vs_anchor": round(rate / 7400, 2),
        "differential": "tests/test_ingest.py::TestGroupCommitDifferential "
                        "proves bit-for-bit parity vs [ingest] "
                        "group_commit=false",
    }


_BATCH_EVENTS = ("join", "coalesced", "stacked", "vmapped",
                 "serial_fallback")
_STAGES = ("parse", "plan", "execute", "fast_bind", "fast_execute")


def _serving_snapshot():
    """Counter/histogram state before a qps phase: per-shape batching
    events, batch/vmap width histograms, and the execute-vs-encode
    wall-time split (engine seconds vs encode-pool seconds)."""
    from greptimedb_tpu.utils.metrics import (
        ADMISSION_WAIT_SECONDS,
        ENCODE_POOL_EVENTS,
        ENCODE_SECONDS,
        FAST_LANE_EVENTS,
        PARTIAL_AGG_CACHE_EVENTS,
        PARTIAL_AGG_DELTA_ROWS,
        QUERY_BATCH_EVENTS,
        QUERY_BATCH_SIZE,
        QUERY_DURATION,
        STAGE_SECONDS,
        VMAP_BATCH_WIDTH,
    )

    return {
        "fl": {e: FAST_LANE_EVENTS.get(event=e)
               for e in ("hit", "miss", "coalesced", "invalidate")},
        "fl_fallback": FAST_LANE_EVENTS.total(event="fallback"),
        "stages": {s: STAGE_SECONDS.sum(stage=s)
                   for s in _STAGES},
        "stage_n": {s: STAGE_SECONDS.count(stage=s)
                    for s in _STAGES},
        "admission_wait_s": ADMISSION_WAIT_SECONDS.sum(),
        "pc_hit": PARTIAL_AGG_CACHE_EVENTS.get(event="hit"),
        "pc_miss": PARTIAL_AGG_CACHE_EVENTS.get(event="miss"),
        "pc_fallback": PARTIAL_AGG_CACHE_EVENTS.get(event="fallback"),
        "pc_delta_rows": PARTIAL_AGG_DELTA_ROWS.get(kind="delta"),
        "pc_cached_rows": PARTIAL_AGG_DELTA_ROWS.get(kind="cached"),
        "events": {e: QUERY_BATCH_EVENTS.get(event=e)
                   for e in _BATCH_EVENTS},
        "batch_sum": QUERY_BATCH_SIZE.sum(),
        "batch_n": QUERY_BATCH_SIZE.count(),
        "vmap_sum": VMAP_BATCH_WIDTH.sum(),
        "vmap_n": VMAP_BATCH_WIDTH.count(),
        "exec_s": QUERY_DURATION.sum(kind="sql"),
        "exec_n": QUERY_DURATION.count(kind="sql"),
        # thread-mode encodes observe protocol="http"; process-mode
        # round trips are timed parent-side as protocol="process"
        "encode_s": ENCODE_SECONDS.sum(protocol="http")
        + ENCODE_SECONDS.sum(protocol="process"),
        "encode_n": ENCODE_SECONDS.count(protocol="http")
        + ENCODE_SECONDS.count(protocol="process"),
        "offloaded": ENCODE_POOL_EVENTS.get(event="offload")
        + ENCODE_POOL_EVENTS.get(event="offload_process"),
        "inline": ENCODE_POOL_EVENTS.get(event="inline"),
        "small_inline": ENCODE_POOL_EVENTS.get(event="small_inline"),
    }


def _serving_report(before):
    """The per-shape batching breakdown + execute/encode split since
    `before` — makes the vmap and GIL-escape wins separately
    attributable in BENCH_* output."""
    now = _serving_snapshot()
    ev = {e: int(now["events"][e] - before["events"][e])
          for e in _BATCH_EVENTS}
    groups = now["batch_n"] - before["batch_n"]
    widths = now["batch_sum"] - before["batch_sum"]
    vgroups = now["vmap_n"] - before["vmap_n"]
    vwidths = now["vmap_sum"] - before["vmap_sum"]
    exec_s = now["exec_s"] - before["exec_s"]
    exec_n = now["exec_n"] - before["exec_n"]
    enc_s = now["encode_s"] - before["encode_s"]
    enc_n = now["encode_n"] - before["encode_n"]
    pc_hit = now["pc_hit"] - before["pc_hit"]
    pc_miss = now["pc_miss"] - before["pc_miss"]
    pc_delta = now["pc_delta_rows"] - before["pc_delta_rows"]
    pc_cached = now["pc_cached_rows"] - before["pc_cached_rows"]
    fl = {e: now["fl"][e] - before["fl"][e] for e in now["fl"]}
    fl_fb = now["fl_fallback"] - before["fl_fallback"]
    fl_requests = fl["hit"] + fl["miss"] + fl_fb
    stages = {s: now["stages"][s] - before["stages"][s] for s in _STAGES}
    stage_n = {s: now["stage_n"][s] - before["stage_n"][s]
               for s in _STAGES}
    adm_wait = now["admission_wait_s"] - before["admission_wait_s"]
    enc_stage = now["encode_s"] - before["encode_s"]
    stage_total = sum(stages.values()) + adm_wait + enc_stage
    return {
        # the per-stage wall breakdown (ISSUE 14): where serving time
        # actually went — parse share ~= 0 proves warm fast-lane
        # requests never touch the parser
        "stage_breakdown": {
            **{f"{s}_s": round(stages[s], 3) for s in _STAGES},
            "admission_wait_s": round(adm_wait, 3),
            "encode_s": round(enc_stage, 3),
            "counts": {s: int(stage_n[s]) for s in _STAGES
                       if stage_n[s]},
            "shares": ({s: round(v / stage_total, 4)
                        for s, v in {**stages,
                                     "admission_wait": adm_wait,
                                     "encode": enc_stage}.items()}
                       if stage_total > 0 else None),
            "parse_share": (round(stages["parse"] / stage_total, 4)
                            if stage_total > 0 else None),
        },
        "fast_lane": {
            "hits": int(fl["hit"]),
            "misses": int(fl["miss"]),
            "fallbacks": int(fl_fb),
            "coalesced": int(fl["coalesced"]),
            "invalidates": int(fl["invalidate"]),
            "hit_rate": (round(fl["hit"] / fl_requests, 4)
                         if fl_requests else None),
        },
        "partial_cache": {
            "hits": int(pc_hit),
            "misses": int(pc_miss),
            "hit_rate": (round(pc_hit / (pc_hit + pc_miss), 4)
                         if pc_hit + pc_miss else None),
            "fallbacks": int(now["pc_fallback"] - before["pc_fallback"]),
            "delta_rows_folded": int(pc_delta),
            "cached_rows_served": int(pc_cached),
            "delta_row_share": (round(pc_delta / (pc_delta + pc_cached), 4)
                                if pc_delta + pc_cached else None),
        },
        "batching": {
            **ev,
            "mean_batch_width": (round(widths / groups, 2)
                                 if groups else None),
            "mean_vmap_width": (round(vwidths / vgroups, 2)
                                if vgroups else None),
        },
        "encode_split": {
            "execute_s": round(exec_s, 3),
            "encode_s": round(enc_s, 3),
            "encode_share": (round(enc_s / (exec_s + enc_s), 4)
                             if exec_s + enc_s > 0 else None),
            "mean_execute_ms": (round(exec_s / exec_n * 1000, 3)
                                if exec_n else None),
            "mean_encode_ms": (round(enc_s / enc_n * 1000, 3)
                               if enc_n else None),
            "encode_offloaded": int(now["offloaded"]
                                    - before["offloaded"]),
            "encode_inline": int(now["inline"] - before["inline"]),
            # results under [concurrency] encode_min_rows: encoded on
            # the request thread by design (handoff > serialization)
            "encode_small_inline": int(now["small_inline"]
                                       - before["small_inline"]),
        },
    }


def bench_qps(qe, results, clients=None, requests_total=None):
    """Config: concurrent query throughput over real HTTP (reference
    tracks 1165.73 qps @50 clients on single-groupby-1-1-1,
    docs/benchmarks/tsbs/v0.8.0.md:53-58). N client threads fire
    single-groupby-1-1-1 POSTs at the in-process HTTP server; the warm
    HBM cache makes each query ~ms, so this measures the serving stack
    (HTTP parse, auth, engine dispatch, JSON encode) under the GIL."""
    import http.client
    import threading
    import urllib.parse
    import urllib.request

    from greptimedb_tpu.servers.http import HttpServer

    clients = clients or int(os.environ.get("BENCH_QPS_CLIENTS", "50"))
    requests_total = requests_total or int(
        os.environ.get("BENCH_QPS_REQUESTS", "2000"))
    sql = (
        f"SELECT date_bin(INTERVAL '1 minute', ts) AS minute, "
        f"max(usage_user) FROM cpu WHERE hostname = 'host_1' "
        f"AND ts >= {T0_MS} AND ts < {T0_MS + 3600 * 1000} GROUP BY minute"
    )
    from greptimedb_tpu.utils.metrics import (
        PLAN_CACHE_EVENTS,
        QUERY_ACHIEVED_GBPS,
        QUERY_BATCH_EVENTS,
    )

    srv = HttpServer(qe, host="127.0.0.1", port=0)
    try:
        port = srv.start()
        url = f"http://127.0.0.1:{port}/v1/sql"
        body = urllib.parse.urlencode({"sql": sql}).encode()
        # warm once (compile + cache) before the clock starts
        urllib.request.urlopen(
            urllib.request.Request(url, data=body), timeout=60)

        # cold-vs-warm partial-cache p50 split: the same request against
        # an emptied partial-aggregate cache (full per-part fold) vs
        # warm repeats that serve cached [G, F] partials and fold only
        # the memtable delta
        from greptimedb_tpu.query import partial_cache as _pc

        def _one_req():
            t0 = time.perf_counter()
            urllib.request.urlopen(
                urllib.request.Request(url, data=body), timeout=60)
            return (time.perf_counter() - t0) * 1000

        _pc.global_cache().clear()
        cold_cache_ms = _one_req()
        warm_cache_ms = float(np.median([_one_req() for _ in range(9)]))
        cache0 = (PLAN_CACHE_EVENTS.get(event="hit"),
                  PLAN_CACHE_EVENTS.get(event="miss"))
        batch0 = (QUERY_BATCH_EVENTS.get(event="coalesced"),
                  QUERY_BATCH_EVENTS.get(event="stacked"),
                  QUERY_BATCH_EVENTS.get(event="vmapped"))
        serving0 = _serving_snapshot()
        gbps0 = (QUERY_ACHIEVED_GBPS.total_count(),
                 QUERY_ACHIEVED_GBPS.total_sum())

        per_client = max(1, requests_total // clients)
        latencies = [[] for _ in range(clients)]
        errors = [0] * clients  # per-thread: += across threads drops counts

        headers = {"Content-Type": "application/x-www-form-urlencoded"}

        def client(i):
            # one keep-alive connection per client, like a real TSBS
            # load generator — reconnect-per-request would measure TCP
            # setup, not the serving stack
            conn = http.client.HTTPConnection("127.0.0.1", port, timeout=60)
            try:
                for _ in range(per_client):
                    t0 = time.perf_counter()
                    try:
                        conn.request("POST", "/v1/sql", body=body,
                                     headers=headers)
                        resp = conn.getresponse()
                        resp.read()
                        if resp.status != 200:
                            errors[i] += 1
                            continue
                    except Exception:
                        errors[i] += 1
                        conn.close()  # reconnect on next iteration
                        continue
                    latencies[i].append(time.perf_counter() - t0)
            finally:
                conn.close()

        threads = [threading.Thread(target=client, args=(i,))
                   for i in range(clients)]
        t_start = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        wall = time.perf_counter() - t_start

        # observability overhead A/B (ISSUE 15): the same request on
        # one keep-alive connection with the tracing plane (spans +
        # ledger + exporter hook) on vs GTPU_TRACING=off — the <3%
        # budget gate. Sequential single-connection runs are far less
        # noisy than re-running the full 50-client storm.
        from greptimedb_tpu.utils import tracing as _tr

        def _seq_qps(n):
            conn = http.client.HTTPConnection("127.0.0.1", port,
                                              timeout=60)
            try:
                for _ in range(10):  # settle the lane/caches per mode
                    conn.request("POST", "/v1/sql", body=body,
                                 headers=headers)
                    conn.getresponse().read()
                t0 = time.perf_counter()
                for _ in range(n):
                    conn.request("POST", "/v1/sql", body=body,
                                 headers=headers)
                    conn.getresponse().read()
                return n / (time.perf_counter() - t0)
            finally:
                conn.close()

        ab_n = max(100, min(400, requests_total // 5))
        # spans per query: ride the W3C ingress — a request with a
        # known traceparent lands its whole tree under that id
        ab_tid = "feedbeefcafe4242"
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=60)
        conn.request("POST", "/v1/sql", body=body, headers={
            **headers,
            "traceparent": f"00-{ab_tid.rjust(32, '0')}-00f067aa0ba902b7-01"})
        conn.getresponse().read()
        conn.close()
        spans_per_query = len(_tr.spans_for(ab_tid))
        from greptimedb_tpu.utils.otlp_trace import OTLP_TRACE_SPANS
        otlp0 = (OTLP_TRACE_SPANS.total(event="exported"),
                 OTLP_TRACE_SPANS.total(event="dropped"))
        # alternate on/off rounds and take per-mode medians: a single
        # sequential pair confounds the mode with drift on a busy box
        prev_tracing = os.environ.get("GTPU_TRACING")
        on_rounds, off_rounds = [], []
        try:
            for _ in range(3):
                if prev_tracing is None:
                    os.environ.pop("GTPU_TRACING", None)
                else:
                    os.environ["GTPU_TRACING"] = prev_tracing
                on_rounds.append(_seq_qps(ab_n))
                os.environ["GTPU_TRACING"] = "off"
                off_rounds.append(_seq_qps(ab_n))
        finally:
            if prev_tracing is None:
                os.environ.pop("GTPU_TRACING", None)
            else:
                os.environ["GTPU_TRACING"] = prev_tracing
        qps_on = float(np.median(on_rounds))
        qps_off = float(np.median(off_rounds))
        overhead_pct = (1.0 - qps_on / qps_off) * 100 if qps_off else 0.0
        tracing_ab = {
            "qps_tracing_on": round(qps_on, 1),
            "qps_tracing_off": round(qps_off, 1),
            "overhead_pct": round(overhead_pct, 2),
            "budget_pct": 3.0,
            "spans_per_query": spans_per_query,
            "otlp_exported": int(OTLP_TRACE_SPANS.total(event="exported")
                                 - otlp0[0]),
            "otlp_dropped": int(OTLP_TRACE_SPANS.total(event="dropped")
                                - otlp0[1]),
        }

        # continuous-profiler overhead A/B (ISSUE 17): the same
        # sequential lane with the flame sampler on vs fully stopped —
        # the <=2% budget gate for leaving it always-on in production.
        # The top-10 self-time digest rides into BENCH detail so the
        # re-capture lands with attribution built in.
        from greptimedb_tpu.utils import flame as _fl

        prof_prev = _fl.running()
        prof_on_rounds, prof_off_rounds, flame_digest = [], [], None
        try:
            for _ in range(3):
                _fl.configure(enabled=True)
                prof_on_rounds.append(_seq_qps(ab_n))
                # read the digest while the windows are still live
                flame_digest = _fl.summary(top=10)
                _fl.shutdown()
                prof_off_rounds.append(_seq_qps(ab_n))
        finally:
            if prof_prev:
                _fl.configure(enabled=True)
        prof_on = float(np.median(prof_on_rounds))
        prof_off = float(np.median(prof_off_rounds))
        profiling_ab = {
            "qps_profiling_on": round(prof_on, 1),
            "qps_profiling_off": round(prof_off, 1),
            "overhead_pct": round(
                (1.0 - prof_on / prof_off) * 100 if prof_off else 0.0, 2),
            "budget_pct": 2.0,
            "flame_samples": (flame_digest or {}).get("samples", 0),
            "flame_attributed": (flame_digest or {}).get("attributed", 0),
            "flame_top10": [
                f"{t['frame']} x{t['self']}"
                for t in (flame_digest or {}).get("top", [])],
        }
    except Exception as e:  # one config may not sink the whole bench
        log(f"qps bench failed: {e!r}")
        results["qps_single_groupby"] = {"error": repr(e)[:200]}
        return
    finally:
        srv.stop()
    lats = np.asarray([x for l in latencies for x in l])
    done = len(lats)
    n_err = sum(errors)
    if done == 0:
        log(f"qps: all {n_err} requests failed")
        results["qps_single_groupby"] = {
            "qps": 0.0, "clients": clients, "requests": 0, "errors": n_err}
        return
    qps = done / wall
    d_cnt = QUERY_ACHIEVED_GBPS.total_count() - gbps0[0]
    d_sum = QUERY_ACHIEVED_GBPS.total_sum() - gbps0[1]
    mean_gbps = (d_sum / d_cnt) if d_cnt else None
    from greptimedb_tpu.utils import roofline as _rl

    peak = _rl.peak_gbps()
    rl_fraction = (mean_gbps / peak) if (mean_gbps and peak) else None
    d_hit = PLAN_CACHE_EVENTS.get(event="hit") - cache0[0]
    d_miss = PLAN_CACHE_EVENTS.get(event="miss") - cache0[1]
    hit_rate = d_hit / (d_hit + d_miss) if (d_hit + d_miss) else None
    batched = (QUERY_BATCH_EVENTS.get(event="coalesced") - batch0[0]
               + QUERY_BATCH_EVENTS.get(event="stacked") - batch0[1]
               + QUERY_BATCH_EVENTS.get(event="vmapped") - batch0[2])
    serving = _serving_report(serving0)
    log(f"qps: {qps:.0f} qps @{clients} clients "
        f"(mean {lats.mean() * 1000:.1f} ms, p99 "
        f"{np.percentile(lats, 99) * 1000:.1f} ms, {n_err} errors, "
        f"plan-cache hit rate "
        f"{-1.0 if hit_rate is None else hit_rate:.3f}, "
        f"{batched:.0f} batched, batching {serving['batching']}, "
        f"fast lane {serving['fast_lane']}, "
        f"stages {serving['stage_breakdown']['shares']}, "
        f"encode {serving['encode_split']})")
    log(f"qps tracing A/B: on {tracing_ab['qps_tracing_on']} vs off "
        f"{tracing_ab['qps_tracing_off']} qps -> "
        f"{tracing_ab['overhead_pct']:+.2f}% overhead (budget 3%), "
        f"{tracing_ab['spans_per_query']} spans/query, "
        f"otlp exported {tracing_ab['otlp_exported']} / dropped "
        f"{tracing_ab['otlp_dropped']}")
    log(f"qps profiling A/B: on {profiling_ab['qps_profiling_on']} vs "
        f"off {profiling_ab['qps_profiling_off']} qps -> "
        f"{profiling_ab['overhead_pct']:+.2f}% overhead (budget 2%), "
        f"{profiling_ab['flame_samples']} samples "
        f"({profiling_ab['flame_attributed']} attributed); mean achieved "
        f"{-1.0 if mean_gbps is None else mean_gbps:.3f} GB/s")
    results["qps_single_groupby"] = {
        "tracing_overhead": tracing_ab,
        "profiling_overhead": profiling_ab,
        "achieved_gbps_mean": (None if mean_gbps is None
                               else round(mean_gbps, 4)),
        "roofline_fraction_mean": (None if rl_fraction is None
                                   else round(rl_fraction, 6)),
        "qps": round(qps, 1), "clients": clients, "requests": done,
        "errors": n_err,
        "mean_ms": round(float(lats.mean() * 1000), 2),
        "p99_ms": round(float(np.percentile(lats, 99) * 1000), 2),
        "p999_ms": round(float(np.percentile(lats, 99.9) * 1000), 2),
        **serving,
        # the ISSUE-6 acceptance: the repeated-dashboard workload must
        # serve >90% of plans from the shape-keyed cache
        "plan_cache_hit_rate": (None if hit_rate is None
                                else round(hit_rate, 4)),
        "batched_queries": int(batched),
        # single-request split: cold = partial cache emptied (every
        # part re-folds), warm = cached partials + memtable delta only
        "cold_cache_ms": round(cold_cache_ms, 2),
        "warm_cache_p50_ms": round(warm_cache_ms, 2),
        "baseline_qps": 1165.73,
        "vs_baseline": round(qps / 1165.73, 3),
        # per-core normalization: the reference baseline ran on 8
        # cores; dividing both sides by their core counts makes the
        # figure portable across boxes (qps_multiproc scores the same
        # way per frontend process)
        "qps_per_core": round(qps / (os.cpu_count() or 1), 1),
        "baseline_qps_per_core": round(1165.73 / 8, 1),
        "vs_baseline_per_core": round(
            (qps / (os.cpu_count() or 1)) / (1165.73 / 8), 3),
        "note": ("clients run in-process; baseline is the reference on "
                 "8 cores, this box has "
                 f"{os.cpu_count()} — compare per-core")}


def bench_qps_mixed(qe, results, clients_per_tenant=None,
                    requests_total=None):
    """Config: multi-tenant mixed workload over real HTTP (ISSUE-6
    satellite) — the concurrency plane measured, not asserted. Three
    tenants with distinct shapes run concurrently through the full
    frontend path (admission -> plan cache -> batcher):

      dash      repeated single-groupby dashboards, rotating host +
                window literals (the plan-cache + stacking workload)
      ops       point lastpoint per host (cheap, shape-cached)
      analytics high-cardinality groupby over every host (the heavy
                neighbor fairness protects the others from)

    Per-tenant p50/p99/p999 says whether a heavy tenant starves a light
    one; the plan-cache hit rate says whether shapes actually shared."""
    import http.client
    import threading
    import urllib.parse
    import urllib.request

    from greptimedb_tpu.servers.http import HttpServer
    from greptimedb_tpu.utils.metrics import (
        ADMISSION_EVENTS,
        PLAN_CACHE_EVENTS,
        QUERY_BATCH_EVENTS,
    )

    clients_per_tenant = clients_per_tenant or int(
        os.environ.get("BENCH_QPS_MIXED_CLIENTS_PER_TENANT", "10"))
    requests_total = requests_total or int(
        os.environ.get("BENCH_QPS_MIXED_REQUESTS", "3000"))
    hour_ms = 3600 * 1000

    def dash_sql(i):
        lo = T0_MS + (i % max(1, HOURS - 1)) * hour_ms
        return (f"SELECT date_bin(INTERVAL '1 minute', ts) AS minute, "
                f"max(usage_user) FROM cpu "
                f"WHERE hostname = 'host_{i % min(HOSTS, 64)}' "
                f"AND ts >= {lo} AND ts < {lo + hour_ms} GROUP BY minute")

    def ops_sql(i):
        return (f"SELECT last_value(usage_user ORDER BY ts) FROM cpu "
                f"WHERE hostname = 'host_{i % min(HOSTS, 256)}'")

    def analytics_sql(i):
        lo = T0_MS + (i % max(1, HOURS - 1)) * hour_ms
        return (f"SELECT hostname, max(usage_user), avg(usage_system) "
                f"FROM cpu WHERE ts >= {lo} AND ts < {lo + hour_ms} "
                f"GROUP BY hostname")

    tenants = [("dash", dash_sql), ("ops", ops_sql),
               ("analytics", analytics_sql)]
    srv = HttpServer(qe, host="127.0.0.1", port=0)
    try:
        port = srv.start()
        url = f"http://127.0.0.1:{port}/v1/sql"
        for _, gen in tenants:  # one warm compile per shape
            urllib.request.urlopen(urllib.request.Request(
                url, data=urllib.parse.urlencode(
                    {"sql": gen(0)}).encode()), timeout=120)
        cache0 = (PLAN_CACHE_EVENTS.get(event="hit"),
                  PLAN_CACHE_EVENTS.get(event="miss"))
        batch0 = (QUERY_BATCH_EVENTS.get(event="coalesced"),
                  QUERY_BATCH_EVENTS.get(event="stacked"),
                  QUERY_BATCH_EVENTS.get(event="vmapped"))
        rej0 = ADMISSION_EVENTS.total(event="reject_full") \
            + ADMISSION_EVENTS.total(event="reject_timeout")
        serving0 = _serving_snapshot()

        per_client = max(1, requests_total
                         // (3 * clients_per_tenant))
        lat = {name: [[] for _ in range(clients_per_tenant)]
               for name, _ in tenants}
        errors = {name: [0] * clients_per_tenant for name, _ in tenants}

        def client(tenant, gen, i):
            conn = http.client.HTTPConnection("127.0.0.1", port,
                                              timeout=120)
            headers = {"Content-Type":
                       "application/x-www-form-urlencoded",
                       "X-Greptime-Tenant": tenant}
            try:
                for k in range(per_client):
                    body = urllib.parse.urlencode(
                        {"sql": gen(i * per_client + k)}).encode()
                    t0 = time.perf_counter()
                    try:
                        conn.request("POST", "/v1/sql", body=body,
                                     headers=headers)
                        resp = conn.getresponse()
                        resp.read()
                        if resp.status != 200:
                            errors[tenant][i] += 1
                            continue
                    except Exception:
                        errors[tenant][i] += 1
                        conn.close()
                        continue
                    lat[tenant][i].append(time.perf_counter() - t0)
            finally:
                conn.close()

        threads = [
            threading.Thread(target=client, args=(name, gen, i))
            for name, gen in tenants for i in range(clients_per_tenant)
        ]
        t_start = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        wall = time.perf_counter() - t_start
    except Exception as e:
        log(f"qps_mixed bench failed: {e!r}")
        results["qps_mixed_tenants"] = {"error": repr(e)[:200]}
        return
    finally:
        srv.stop()

    d_hit = PLAN_CACHE_EVENTS.get(event="hit") - cache0[0]
    d_miss = PLAN_CACHE_EVENTS.get(event="miss") - cache0[1]
    hit_rate = d_hit / (d_hit + d_miss) if (d_hit + d_miss) else None
    batched = (QUERY_BATCH_EVENTS.get(event="coalesced") - batch0[0]
               + QUERY_BATCH_EVENTS.get(event="stacked") - batch0[1]
               + QUERY_BATCH_EVENTS.get(event="vmapped") - batch0[2])
    rejected = (ADMISSION_EVENTS.total(event="reject_full")
                + ADMISSION_EVENTS.total(event="reject_timeout") - rej0)
    per_tenant = {}
    done = 0
    for name, _ in tenants:
        ls = np.asarray([x for l in lat[name] for x in l])
        n_err = sum(errors[name])
        done += len(ls)
        if len(ls) == 0:
            per_tenant[name] = {"requests": 0, "errors": n_err}
            continue
        per_tenant[name] = {
            "requests": int(len(ls)), "errors": n_err,
            "p50_ms": round(float(np.percentile(ls, 50) * 1000), 2),
            "p99_ms": round(float(np.percentile(ls, 99) * 1000), 2),
            "p999_ms": round(float(np.percentile(ls, 99.9) * 1000), 2),
        }
    qps = done / wall if wall > 0 else 0.0
    serving = _serving_report(serving0)
    log(f"qps_mixed: {qps:.0f} qps @3x{clients_per_tenant} clients, "
        f"plan-cache hit rate "
        f"{-1.0 if hit_rate is None else hit_rate:.3f}, "
        f"{batched:.0f} batched, {rejected:.0f} rejected, "
        f"fast lane {serving['fast_lane']}, "
        f"batching {serving['batching']}; " + ", ".join(
            f"{n} p99 {per_tenant[n].get('p99_ms', '?')} ms"
            for n, _ in tenants))
    results["qps_mixed_tenants"] = {
        "qps": round(qps, 1),
        "clients_per_tenant": clients_per_tenant,
        "tenants": per_tenant,
        **serving,
        "plan_cache_hit_rate": (None if hit_rate is None
                                else round(hit_rate, 4)),
        "batched_queries": int(batched),
        "admission_rejections": int(rejected),
        "note": "3 tenants (dashboard/point-lastpoint/high-card "
                "groupby) through HTTP concurrently; per-tenant tails "
                "measure cross-tenant interference"}


# ---- mesh_scale: shard-count scaling + cluster pushdown ---------------------

MESH_CHILD_HOSTS = 120
MESH_CHILD_POINTS = 1500  # x hosts = 180k rows, 4 SST files


def mesh_scale_child(n_shard: int) -> int:
    """One mesh size measured in a fresh process (the device count is
    fixed at backend init, so each size needs its own interpreter).
    Emits one JSON line on stdout: per-query p50s, a sequential-QPS
    proxy, the serving path, and a parity digest the parent compares
    across sizes (bit-for-bit vs the 1-device oracle)."""
    import hashlib

    import jax
    if os.environ.get("JAX_PLATFORMS") == "cpu":
        jax.config.update("jax_platforms", "cpu")
    data_dir = tempfile.mkdtemp(prefix="gtpu_mesh_")
    try:
        from greptimedb_tpu.datatypes import DictVector, RecordBatch

        engine, qe = build_db(data_dir)
        qe.execute_one(
            "CREATE TABLE mesh_t (host STRING, v0 DOUBLE, v1 DOUBLE, "
            "ts TIMESTAMP(3) NOT NULL, TIME INDEX (ts), PRIMARY KEY "
            "(host)) WITH (append_mode = 'true')")
        info = qe.catalog.table("public", "mesh_t")
        rid = info.region_ids[0]
        rng = np.random.default_rng(17)
        hosts, points = MESH_CHILD_HOSTS, MESH_CHILD_POINTS
        n = hosts * points
        codes = np.repeat(np.arange(hosts, dtype=np.int32), points)
        names = np.asarray([f"h{i:03d}" for i in range(hosts)],
                           dtype=object)
        ts = np.tile(np.arange(points, dtype=np.int64) * 1000, hosts)
        # integer-valued doubles: float sums are associativity-free, so
        # the cross-size digest is exact, not approximate
        v0 = rng.integers(0, 1000, n).astype(np.float64)
        v1 = rng.integers(0, 1000, n).astype(np.float64)
        files = 4
        per = n // files
        for i in range(files):
            sl = slice(i * per, n if i == files - 1 else (i + 1) * per)
            engine.put(rid, RecordBatch(info.schema, {
                "host": DictVector(codes[sl], names), "v0": v0[sl],
                "v1": v1[sl], "ts": ts[sl]}))
            engine.flush(rid)
        dg_sql = ("SELECT host, date_bin(INTERVAL '1 minute', ts) AS b, "
                  "avg(v0), avg(v1), max(v0), min(v1) FROM mesh_t "
                  "GROUP BY host, b ORDER BY host, b")
        sg_sql = ("SELECT host, max(v0), sum(v1) FROM mesh_t "
                  "GROUP BY host ORDER BY host")
        dg_p50, dg_warm, dg_rows, _ = timed_sql(qe, dg_sql, repeats=7)
        path = qe.executor.last_path
        tier = qe.executor.last_tier
        digest = hashlib.sha256(
            repr(qe.execute_one(dg_sql).rows()).encode()).hexdigest()[:16]
        sg_p50, _, _, _ = timed_sql(qe, sg_sql, repeats=7)
        # sequential-QPS proxy for the single-groupby class
        t0 = time.perf_counter()
        reps = 30
        for _ in range(reps):
            qe.execute_one(sg_sql)
        qps = reps / (time.perf_counter() - t0)
        print(json.dumps({
            "shards": n_shard, "rows": n, "path": path, "tier": tier,
            "double_groupby_p50_ms": round(dg_p50, 2),
            "warm_ms": round(dg_warm, 1),
            "groups": dg_rows,
            "single_groupby_p50_ms": round(sg_p50, 2),
            "qps_single_groupby": round(qps, 1),
            "digest": digest,
        }))
        engine.close()
        return 0
    finally:
        shutil.rmtree(data_dir, ignore_errors=True)


def bench_incremental_agg(engine, qe, results):
    """Incremental-aggregation micro-phase (ISSUE 13): the single-
    groupby shape over a multi-file table — cold fold (empty partial
    cache: every part reduces) vs warm repeat (cached [G, F] partials,
    only the memtable delta runs kernels) vs the post-flush fold that
    must compute exactly ONE new file + the memtable tail. Digests are
    bit-for-bit checked against the cache-disabled classic path."""
    import hashlib

    from greptimedb_tpu.datatypes import DictVector, RecordBatch
    from greptimedb_tpu.query import partial_cache as pc

    n_files, n_hosts, pts = 4, 200, 500
    qe.execute_one(
        "CREATE TABLE incragg (hostname STRING, ts TIMESTAMP(3) NOT NULL, "
        "usage_user DOUBLE, usage_system DOUBLE, TIME INDEX (ts), "
        "PRIMARY KEY (hostname)) WITH (append_mode = 'true')")
    info = qe.catalog.table("public", "incragg")
    rid = info.region_ids[0]
    rng = np.random.default_rng(31)
    names = np.asarray([f"host_{i}" for i in range(n_hosts)], dtype=object)

    def put(f, rows, flush):
        codes = np.tile(np.arange(n_hosts, dtype=np.int32), rows)
        ts = np.repeat(
            T0_MS + (f * pts + np.arange(rows, dtype=np.int64)) * 1000,
            n_hosts)
        cols = {"hostname": DictVector(codes, names), "ts": ts,
                "usage_user": rng.uniform(0.0, 100.0, rows * n_hosts),
                "usage_system": rng.uniform(0.0, 100.0, rows * n_hosts)}
        engine.put(rid, RecordBatch(info.schema, cols))
        if flush:
            engine.flush(rid)

    for f in range(n_files):
        put(f, pts, flush=True)
    put(n_files, 50, flush=False)  # memtable tail

    sql = ("SELECT date_bin(INTERVAL '1 minute', ts) AS minute, "
           "max(usage_user), avg(usage_system) FROM incragg "
           f"WHERE hostname = 'host_1' AND ts >= {T0_MS} "
           "GROUP BY minute ORDER BY minute")

    def digest(res):
        h = hashlib.sha256()
        for c in res.columns:
            h.update(np.ascontiguousarray(np.asarray(c, dtype=float)))
        return h.hexdigest()[:16]

    def timed(repeats=9):
        qe.execute_one(sql)  # shape warm-up outside the clock
        times, res = [], None
        for _ in range(repeats):
            t0 = time.perf_counter()
            res = qe.execute_one(sql)
            times.append((time.perf_counter() - t0) * 1000)
        return float(np.median(times)), res

    # classic oracle: partial cache off, bit-for-bit reference (the
    # operator's own A/B env value is restored, never clobbered)
    prev_pc = os.environ.get("GREPTIMEDB_TPU_PARTIAL_CACHE")

    def restore_pc():
        if prev_pc is None:
            os.environ.pop("GREPTIMEDB_TPU_PARTIAL_CACHE", None)
        else:
            os.environ["GREPTIMEDB_TPU_PARTIAL_CACHE"] = prev_pc

    os.environ["GREPTIMEDB_TPU_PARTIAL_CACHE"] = "off"
    try:
        classic_ms, classic_res = timed()
    finally:
        restore_pc()
    classic_digest = digest(classic_res)

    # cold: every part folds (and populates the cache)
    pc.global_cache().clear()
    t0 = time.perf_counter()
    cold_res = qe.execute_one(sql)
    cold_ms = (time.perf_counter() - t0) * 1000
    cold_stats = qe.executor.last_partial_stats or {}
    # warm: cached partials + memtable delta only
    warm_ms, warm_res = timed()
    warm_stats = qe.executor.last_partial_stats or {}
    # post-flush: ONE new file + memtable must fold, nothing else
    put(n_files + 1, 20, flush=True)
    put(n_files + 2, 10, flush=False)
    t0 = time.perf_counter()
    incr_res = qe.execute_one(sql)
    incr_ms = (time.perf_counter() - t0) * 1000
    incr_stats = qe.executor.last_partial_stats or {}
    os.environ["GREPTIMEDB_TPU_PARTIAL_CACHE"] = "off"
    try:
        incr_oracle = qe.execute_one(sql)
    finally:
        restore_pc()

    digests_equal = (digest(cold_res) == classic_digest
                     and digest(warm_res) == classic_digest
                     and digest(incr_res) == digest(incr_oracle))
    log(f"incremental-agg: classic {classic_ms:.1f} ms, cold "
        f"{cold_ms:.1f} ms, warm {warm_ms:.1f} ms "
        f"(delta {warm_stats.get('delta_rows')}/"
        f"{warm_stats.get('total_rows')} rows), post-flush "
        f"{incr_ms:.1f} ms (hits {incr_stats.get('part_hits')}, "
        f"misses {incr_stats.get('part_misses')}), "
        f"bit-for-bit={digests_equal}")
    results["incremental_agg"] = {
        "classic_p50_ms": round(classic_ms, 2),
        "cold_fold_ms": round(cold_ms, 2),
        "warm_repeat_p50_ms": round(warm_ms, 2),
        "warm_vs_classic": (round(classic_ms / warm_ms, 2)
                            if warm_ms > 0 else None),
        "cold_stats": cold_stats,
        "warm_stats": warm_stats,
        "post_flush_ms": round(incr_ms, 2),
        "post_flush_stats": incr_stats,
        "bit_for_bit_identical": bool(digests_equal),
        "path": qe.executor.last_path,
    }


def bench_mesh_scale(results):
    """Shard-count scaling sweep: 1/2/4/8-device meshes each in a child
    process (CPU: --xla_force_host_platform_device_count; a real TPU box
    exposes its chips and GREPTIMEDB_TPU_MESH=Nx1 takes the first N),
    reporting per-size p50, scaling efficiency vs 1 shard, and a
    bit-for-bit parity digest against the 1-device oracle."""
    import subprocess

    import jax

    sizes = [1, 2, 4, 8]
    on_cpu = jax.default_backend() == "cpu"
    if not on_cpu:
        sizes = [s for s in sizes if s <= len(jax.devices())] or [1]
    out = {}
    for s in sizes:
        if budget_left_s() < 180:
            log(f"mesh_scale: budget low, stopping before size {s}")
            break
        env = dict(os.environ)
        env["BENCH_MESH_CHILD"] = str(s)
        env.pop("BENCH_CHILD", None)
        env["GREPTIMEDB_TPU_MESH"] = "off" if s == 1 else f"{s}x1"
        env["GREPTIMEDB_TPU_MESH_MIN_ROWS"] = "1"
        if on_cpu:
            flags = [f for f in env.get("XLA_FLAGS", "").split()
                     if "xla_force_host_platform_device_count" not in f]
            flags.append(f"--xla_force_host_platform_device_count={s}")
            env["XLA_FLAGS"] = " ".join(flags)
            env["JAX_PLATFORMS"] = "cpu"
        log(f"mesh_scale: size {s} ...")
        try:
            p = subprocess.run([sys.executable, os.path.abspath(__file__)],
                               env=env, capture_output=True, text=True,
                               timeout=max(120, budget_left_s() - 60))
            line = [ln for ln in p.stdout.splitlines() if ln.strip()][-1]
            out[str(s)] = json.loads(line)
        except Exception as e:  # noqa: BLE001 — one size must not sink all
            log(f"mesh_scale size {s} failed: {e!r}")
            out[str(s)] = {"error": repr(e)[:200]}
    base = out.get("1", {})
    base_p50 = base.get("double_groupby_p50_ms")
    base_digest = base.get("digest")
    for s, d in out.items():
        p50 = d.get("double_groupby_p50_ms")
        if base_p50 and p50 and s != "1":
            d["speedup_vs_1"] = round(base_p50 / p50, 2)
            d["scaling_efficiency"] = round(base_p50 / (int(s) * p50), 2)
        if base_digest and d.get("digest"):
            d["parity_vs_1"] = d["digest"] == base_digest
    results["mesh_scale"] = out
    log(f"mesh_scale: {json.dumps(out)}")


# ---- qps_multiproc: serving-fabric scaling across frontend processes -------

MP_HOSTS = 60
MP_POINTS = 400


def qps_multiproc_child(idx: int) -> int:
    """One frontend process of the qps_multiproc phase: its own engine
    + data replica + HTTP server, attached to the shared serving
    fabric (GTPU_SHM_FABRIC* inherited from the parent). Protocol: run
    the first query — recording its wall time and how many XLA
    compiles it forced; with the shared executable cache, every
    process after the first must record ZERO — write <run>/<idx>.ready,
    wait for <run>/go, serve the timed workload, emit one JSON line."""
    import http.client
    import threading
    import urllib.parse

    import jax
    if os.environ.get("JAX_PLATFORMS") == "cpu":
        jax.config.update("jax_platforms", "cpu")
    run_dir = os.environ["BENCH_QPS_MP_RUN"]
    requests_total = int(os.environ.get("BENCH_QPS_MP_REQUESTS", "400"))
    clients = int(os.environ.get("BENCH_QPS_MP_CLIENTS", "8"))
    data_dir = tempfile.mkdtemp(prefix=f"gtpu_mp{idx}_")
    try:
        from greptimedb_tpu.catalog import Catalog, MemoryKv
        from greptimedb_tpu.datatypes import DictVector, RecordBatch
        from greptimedb_tpu.query import QueryEngine
        from greptimedb_tpu.servers.http import HttpServer
        from greptimedb_tpu.storage import RegionEngine
        from greptimedb_tpu.storage.engine import EngineConfig
        from greptimedb_tpu.utils.metrics import (
            SHM_FABRIC_EVENTS,
            XLA_COMPILES,
        )

        engine = RegionEngine(EngineConfig(data_dir=data_dir))
        qe = QueryEngine(Catalog(MemoryKv()), engine)
        qe.execute_one(
            "CREATE TABLE cpu (hostname STRING, ts TIMESTAMP(3) NOT "
            "NULL, usage_user DOUBLE, TIME INDEX (ts), PRIMARY KEY "
            "(hostname)) WITH (append_mode = 'true')")
        info = qe.catalog.table("public", "cpu")
        rid = info.region_ids[0]
        # same seed in every child: the frontends serve identical
        # replicas, so adopted fabric artifacts face identical data
        rng = np.random.default_rng(41)
        hosts, points = MP_HOSTS, MP_POINTS
        codes = np.repeat(np.arange(hosts, dtype=np.int32), points)
        names = np.asarray([f"host_{i}" for i in range(hosts)],
                           dtype=object)
        ts = np.tile(T0_MS + np.arange(points, dtype=np.int64) * 1000,
                     hosts)
        engine.put(rid, RecordBatch(info.schema, {
            "hostname": DictVector(codes, names),
            "ts": ts,
            "usage_user": rng.uniform(0.0, 100.0, hosts * points)}))
        engine.flush(rid)

        srv = HttpServer(qe, host="127.0.0.1", port=0)
        port = srv.start()
        sql = (f"SELECT date_bin(INTERVAL '1 minute', ts) AS minute, "
               f"max(usage_user) FROM cpu WHERE hostname = 'host_1' "
               f"AND ts >= {T0_MS} AND ts < {T0_MS + 3600 * 1000} "
               f"GROUP BY minute")
        body = urllib.parse.urlencode({"sql": sql}).encode()
        headers = {"Content-Type": "application/x-www-form-urlencoded"}

        def post(conn):
            conn.request("POST", "/v1/sql", body=body, headers=headers)
            r = conn.getresponse()
            return r.status, r.read()

        def mark(name):
            path = os.path.join(run_dir, f"{idx}.{name}")
            with open(path + ".tmp", "w") as f:
                f.write("1")
            os.replace(path + ".tmp", path)

        def wait_file(name, timeout_s=180.0):
            path = os.path.join(run_dir, name)
            deadline = time.monotonic() + timeout_s
            while not os.path.exists(path):
                if time.monotonic() > deadline:
                    raise TimeoutError(f"{name} never appeared")
                time.sleep(0.02)

        # barrier 1: every replica's CREATE TABLE bumps the fabric's
        # (db, table) version — correct DDL semantics, but it would
        # invalidate the warm-up publishes, so ALL setup must land
        # before child 0 warms (a real multi-frontend box runs DDL
        # once through the shared catalog; only this bench replays it
        # per replica)
        mark("setup")
        # warm-up: child 0 pays template probe + plan build + XLA
        # compile into the fabric (two queries: the fast lane publishes
        # its verified binder on the SECOND sighting); children 1..N
        # then adopt — their first query must compile nothing
        wait_file("warm" if idx == 0 else "adopt")
        conn0 = http.client.HTTPConnection("127.0.0.1", port, timeout=60)
        x0 = XLA_COMPILES.total()
        t0 = time.perf_counter()
        status, payload = post(conn0)
        first_ms = (time.perf_counter() - t0) * 1000
        first_compiles = XLA_COMPILES.total() - x0
        if status != 200:
            conn0.close()
            raise RuntimeError(f"first query -> {status}: "
                               f"{payload[-300:]!r}")
        if idx == 0:
            post(conn0)  # second sighting: build + publish the template
        conn0.close()
        mark("warmed")
        wait_file("go")

        per_client = max(1, requests_total // clients)
        lat = [[] for _ in range(clients)]
        errs = [0] * clients

        def client(i):
            conn = http.client.HTTPConnection("127.0.0.1", port,
                                              timeout=60)
            try:
                for _ in range(per_client):
                    t0 = time.perf_counter()
                    try:
                        st, _ = post(conn)
                        if st != 200:
                            errs[i] += 1
                            continue
                    except Exception:
                        errs[i] += 1
                        conn.close()
                        continue
                    lat[i].append(time.perf_counter() - t0)
            finally:
                conn.close()

        threads = [threading.Thread(target=client, args=(i,))
                   for i in range(clients)]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        wall = time.perf_counter() - t0
        lats = np.asarray([x for l in lat for x in l])
        done = len(lats)

        fabric = {k: int(SHM_FABRIC_EVENTS.total(**sel)) for k, sel in (
            ("tpl_hit", dict(event="hit", kind="template")),
            ("tpl_miss", dict(event="miss", kind="template")),
            ("plan_hit", dict(event="hit", kind="plan")),
            ("plan_miss", dict(event="miss", kind="plan")),
            ("publish", dict(event="publish")),
            ("detach", dict(event="detach")))}
        print(json.dumps({
            "idx": idx,
            "qps": round(done / wall, 1) if wall > 0 else 0.0,
            "wall_s": round(wall, 3),
            "requests": int(done),
            "errors": int(sum(errs)),
            "mean_ms": (round(float(lats.mean() * 1000), 2)
                        if done else None),
            "p99_ms": (round(float(np.percentile(lats, 99) * 1000), 2)
                       if done else None),
            "first_query_ms": round(first_ms, 1),
            "first_query_xla_compiles": int(first_compiles),
            "fabric": fabric,
        }))
        srv.stop()
        engine.close()
        return 0
    finally:
        shutil.rmtree(data_dir, ignore_errors=True)


def bench_qps_multiproc(results):
    """Serving-fabric scaling (ISSUE 19): N frontend PROCESSES on one
    box, each with its own engine + data replica + HTTP server, all
    attached to one shared-memory fabric. Child 0 warms alone —
    template probes, plan build, XLA compile land in the fabric — then
    children 1..N-1 start and their FIRST query must adopt those
    artifacts (zero XLA compiles) before all N serve the timed
    workload concurrently. Scored per core (aggregate qps / N) against
    the 8-core reference baseline per core (1165.73 / 8 = 145.7)."""
    import subprocess

    baseline_per_core = round(1165.73 / 8, 1)
    out = {}
    for n in (1, 2, 4):
        if budget_left_s() < 120:
            log(f"qps_multiproc: budget low, stopping before N={n}")
            break
        fabric_dir = tempfile.mkdtemp(prefix="gtpu_fab_bench_")
        run_dir = os.path.join(fabric_dir, "run")
        os.makedirs(run_dir, exist_ok=True)
        env = dict(os.environ)
        env.pop("BENCH_CHILD", None)
        env.pop("BENCH_MESH_CHILD", None)
        # frontends serve from CPU replicas: N processes must not race
        # for one accelerator runtime
        env["JAX_PLATFORMS"] = "cpu"
        env["GTPU_SHM_FABRIC"] = "1"
        env["GTPU_SHM_FABRIC_DIR"] = fabric_dir
        env["BENCH_QPS_MP_RUN"] = run_dir
        procs = []

        def spawn(i):
            e = dict(env)
            e["BENCH_QPS_MP_CHILD"] = str(i)
            p = subprocess.Popen(
                [sys.executable, os.path.abspath(__file__)], env=e,
                stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                text=True)
            procs.append(p)
            return p

        def wait_marks(name, idxs, timeout_s=240.0):
            pending = set(idxs)
            deadline = time.monotonic() + timeout_s
            while pending:
                for i in list(pending):
                    if os.path.exists(
                            os.path.join(run_dir, f"{i}.{name}")):
                        pending.discard(i)
                for p in procs:
                    if p.poll() not in (None, 0):
                        _, stderr = p.communicate()
                        raise RuntimeError(
                            f"child died at {name}: {stderr[-400:]}")
                if time.monotonic() > deadline:
                    raise TimeoutError(
                        f"children {sorted(pending)}: no {name}")
                time.sleep(0.05)

        def release(name):
            with open(os.path.join(run_dir, name), "w") as f:
                f.write("1")

        try:
            log(f"qps_multiproc: N={n} ...")
            for i in range(n):
                spawn(i)
            # all replicas' DDL before any publish (see child comment),
            # then child 0 warms the fabric alone, then the rest adopt
            wait_marks("setup", range(n))
            release("warm")
            wait_marks("warmed", [0])
            release("adopt")
            wait_marks("warmed", range(1, n))
            release("go")
            children = []
            for i, p in enumerate(procs):
                try:
                    stdout, stderr = p.communicate(
                        timeout=max(120, budget_left_s()))
                except subprocess.TimeoutExpired:
                    p.kill()
                    stdout, stderr = p.communicate()
                lines = [ln for ln in stdout.splitlines() if ln.strip()]
                try:
                    children.append(json.loads(lines[-1]))
                except Exception:  # noqa: BLE001 — keep the diagnosis
                    children.append({"idx": i,
                                     "error": (stderr or "")[-300:]})
            agg = sum(c.get("qps") or 0.0 for c in children)
            per_core = agg / n
            warm = [c.get("first_query_xla_compiles") for c in children]
            out[str(n)] = {
                "frontends": n,
                "children": children,
                "qps_aggregate": round(agg, 1),
                "qps_per_core": round(per_core, 1),
                "baseline_qps_per_core": baseline_per_core,
                "vs_baseline_per_core": round(
                    per_core / baseline_per_core, 3),
                "first_query_ms": [c.get("first_query_ms")
                                   for c in children],
                "first_query_xla_compiles": warm,
                # the shared-executable acceptance: every process after
                # the first compiles NOTHING on its first query
                "shared_xla_cache_effective": (
                    all(c == 0 for c in warm[1:]) if n > 1 else None),
            }
        except Exception as e:  # noqa: BLE001 — one N must not sink all
            log(f"qps_multiproc N={n} failed: {e!r}")
            out[str(n)] = {"error": repr(e)[:300]}
        finally:
            for p in procs:
                if p.poll() is None:
                    p.kill()
                    p.communicate()
            # a SIGKILL'd child leaks its attach-lock refcount: unlink
            # both segments defensively before dropping the directory
            from greptimedb_tpu.shm.fabric import (
                _unlink_segment,
                segment_name,
            )

            _unlink_segment(segment_name(fabric_dir))
            _unlink_segment(segment_name(
                os.path.join(fabric_dir, "arena")))
            shutil.rmtree(fabric_dir, ignore_errors=True)
    base = out.get("1", {})
    for s, d in out.items():
        if s != "1" and base.get("qps_per_core") \
                and d.get("qps_per_core") is not None:
            d["scaling_efficiency_vs_1"] = round(
                d["qps_per_core"] / base["qps_per_core"], 3)
    results["qps_multiproc"] = out
    log(f"qps_multiproc: {json.dumps(out)}")


def bench_cluster_pushdown(results):
    """Cluster-mode rollup substitution + lastpoint pruning through the
    distributed frontend: measured with the pushdown planes on vs the
    raw paths (GTPU_ROLLUP_SUBSTITUTE=0 / GTPU_LASTFRAG=0), asserting
    the served last_path so the speedup provably comes from partial
    planes, not noise."""
    import tempfile as _tf

    from greptimedb_tpu.cluster import Cluster
    from greptimedb_tpu.meta.metasrv import MetasrvOptions
    from greptimedb_tpu.partition.rule import (
        PartitionBound,
        RangePartitionRule,
    )

    cdir = _tf.mkdtemp(prefix="gtpu_clbench_")
    out = {}
    try:
        from greptimedb_tpu.datatypes import DictVector, RecordBatch

        c = Cluster(cdir, num_datanodes=3, opts=MetasrvOptions())
        hosts, minutes, per_minute = 96, 20, 300
        split1, split2 = f"host{hosts // 3:03d}", f"host{2 * hosts // 3:03d}"
        bounds = [PartitionBound((split1,)), PartitionBound((split2,)),
                  PartitionBound(())]
        rule = RangePartitionRule(["host"], bounds)
        c.create_partitioned_table(
            "CREATE TABLE cpu (host STRING, v DOUBLE, ts TIMESTAMP(3) "
            "NOT NULL, TIME INDEX (ts), PRIMARY KEY(host))", rule)
        info = c.catalog.table("public", "cpu")
        rng = np.random.default_rng(23)
        names = np.asarray([f"host{h:03d}" for h in range(hosts)],
                           dtype=object)
        # direct scattered puts (the write path's find_regions contract),
        # flushed every 2 minutes: ~5 SSTs per region so the lastpoint
        # A/B below has files for newest-first pruning to skip
        for m in range(minutes):
            n = hosts * per_minute
            codes = np.repeat(np.arange(hosts, dtype=np.int32),
                              per_minute)
            ts = (m * 60_000
                  + np.tile(np.arange(per_minute, dtype=np.int64)
                            * (60_000 // per_minute), hosts))
            v = rng.integers(0, 1000, n).astype(np.float64)
            batch = RecordBatch(info.schema, {
                "host": DictVector(codes, names), "v": v, "ts": ts})
            for idx, rows_idx in rule.split(
                    [names[codes]], n_rows=n).items():
                part = batch.take(rows_idx)
                part = RecordBatch(part.schema, {
                    k: (col.compact() if isinstance(col, DictVector)
                        else col)
                    for k, col in part.columns.items()})
                c.router.put(info.region_ids[idx], part)
            # one SST per region per minute: lastpoint's newest-first
            # termination needs more files than one decode wave, or the
            # wave reads everything and pruning can't pay
            for rid in info.region_ids:
                c.router.flush(rid)
        from greptimedb_tpu.maintenance.rollup import (
            RollupRule,
            rule_slot,
            run_rollup_job,
        )

        rule = RollupRule(resolution_ms=60_000)
        for dn in c.datanodes.values():
            dn.engine.maintenance.rollup_rules = [rule]
            for rid in list(dn.engine.regions):
                run_rollup_job(dn.engine, rid, rule_slot(60_000), rule)
        hi = (minutes - 1) * 60_000
        roll_sql = (f"SELECT host, min(v), max(v), sum(v), count(v) "
                    f"FROM cpu WHERE ts >= 0 AND ts < {hi} "
                    f"GROUP BY host ORDER BY host")

        def p50(sql, reps=5):
            c.sql(sql)  # warm
            times = []
            for _ in range(reps):
                t = time.perf_counter()
                c.sql(sql)
                times.append((time.perf_counter() - t) * 1000)
            return float(np.median(times))

        sub_ms = p50(roll_sql)
        sub_path = c.frontend.executor.last_path
        os.environ["GTPU_ROLLUP_SUBSTITUTE"] = "0"
        try:
            raw_ms = p50(roll_sql)
            raw_path = c.frontend.executor.last_path
        finally:
            os.environ.pop("GTPU_ROLLUP_SUBSTITUTE", None)
        out["rollup"] = {
            "pushdown_p50_ms": round(sub_ms, 2), "path": sub_path,
            "raw_p50_ms": round(raw_ms, 2), "raw_path": raw_path,
            "speedup": round(raw_ms / max(sub_ms, 1e-6), 2)}

        lp_sql = "SELECT host, last(v) FROM cpu GROUP BY host ORDER BY host"

        def p50_postwrite(reps=5):
            """Dashboard-refresh-after-ingest: each repeat lands one
            write first (bumping the data version, as live ingest does
            continuously), so the measured scan is the realistic
            incremental one — this is where newest-first pruning pays
            (the raw fragment re-assembles every region's full row set)."""
            c.sql(lp_sql)  # warm compile
            times = []
            for i in range(reps):
                c.sql("INSERT INTO cpu (host, v, ts) VALUES "
                      f"('host000', 1, {9_000_000 + i})")
                t = time.perf_counter()
                c.sql(lp_sql)
                times.append((time.perf_counter() - t) * 1000)
            return float(np.median(times))

        lp_ms = p50_postwrite()
        lp_path = c.frontend.executor.last_path
        os.environ["GTPU_LASTFRAG"] = "0"
        try:
            lp_raw_ms = p50_postwrite()
        finally:
            os.environ.pop("GTPU_LASTFRAG", None)
        out["lastpoint"] = {
            "postwrite_p50_ms": round(lp_ms, 2), "path": lp_path,
            "unpruned_p50_ms": round(lp_raw_ms, 2),
            "speedup": round(lp_raw_ms / max(lp_ms, 1e-6), 2)}
        c.close()
    finally:
        shutil.rmtree(cdir, ignore_errors=True)
    results["cluster_pushdown"] = out
    log(f"cluster_pushdown: {json.dumps(out)}")


def bench_tail_latency(results):
    """Tail-tolerance A/B over real datanode processes: fixed-QPS
    point-in-time aggregates against a ProcessCluster whose region
    owner suffers probabilistic 400 ms Flight stalls (injected
    server-side via GTPU_CHAOS env inheritance, ~2% of reads — inside
    the <=5% hedge budget by design). Three phases: unstalled baseline,
    stalled with hedging off, stalled with hedging on — reporting
    p50/p99/p999, deadline timeouts, and the hedge counters, so the
    artifact shows whether first-response-wins hedging pulls the
    stalled p99 back toward the unstalled one without extra load."""
    import tempfile as _tf

    from greptimedb_tpu.cluster.process_cluster import ProcessCluster
    from greptimedb_tpu.fault.retry import DeadlineExceeded
    from greptimedb_tpu.meta.metasrv import MetasrvOptions
    from greptimedb_tpu.session import QueryContext
    from greptimedb_tpu.utils.metrics import HEDGE_EVENTS

    SQL = "SELECT count(*), sum(v) FROM cpu"
    N, INTERVAL_S = 150, 0.02  # ~50 QPS offered, ~3 s per phase

    def mk_cluster(tmp):
        c = ProcessCluster(tmp, num_datanodes=2, opts=MetasrvOptions())
        c.beat_all(time.time() * 1000)
        c.sql("CREATE TABLE cpu (host STRING, v DOUBLE, ts TIMESTAMP "
              "TIME INDEX, PRIMARY KEY(host))")
        rows = ", ".join(f"('h{i:03d}', {float(i)}, {1000 * (i + 1)})"
                         for i in range(200))
        c.sql(f"INSERT INTO cpu (host, v, ts) VALUES {rows}")
        return c

    def run_phase(c):
        lat, timeouts = [], 0
        c.sql(SQL)  # warm (plan bind + scan cache path)
        for _ in range(N):
            t0 = time.perf_counter()
            try:
                c.frontend.execute_one(
                    SQL, QueryContext(db="public", timeout_ms=2000))
            except DeadlineExceeded:
                timeouts += 1
            el = time.perf_counter() - t0
            lat.append(el * 1000)
            if INTERVAL_S - el > 0:
                time.sleep(INTERVAL_S - el)
        lat.sort()

        def q(p):
            return round(lat[min(len(lat) - 1, int(p * len(lat)))], 2)

        return {"p50_ms": q(0.50), "p99_ms": q(0.99),
                "p999_ms": q(0.999), "timeouts": timeouts}

    out = {}
    saved = {k: os.environ.get(k) for k in
             ("GTPU_CHAOS", "GTPU_HEDGE", "GTPU_HEDGE_DELAY_MS")}
    dirs = [_tf.mkdtemp(prefix="gtpu_tail_") for _ in range(2)]
    try:
        os.environ.pop("GTPU_CHAOS", None)
        os.environ["GTPU_HEDGE"] = "off"
        c = mk_cluster(dirs[0])
        try:
            out["unstalled"] = run_phase(c)
        finally:
            c.close()
        # children arm the stall from env at spawn: 400 ms latency on
        # ~2% of server-side region reads — the per-request straggler
        # shape hedging exists for (a re-rolled attempt dodges it)
        os.environ["GTPU_CHAOS"] = \
            "flight.do_get=latency,arg:0.4,prob:0.02,@side:server"
        c = mk_cluster(dirs[1])
        try:
            out["stalled_hedge_off"] = run_phase(c)
            os.environ.pop("GTPU_HEDGE", None)  # hedging back on
            os.environ["GTPU_HEDGE_DELAY_MS"] = "25"
            before = {ev: HEDGE_EVENTS.get(event=ev) for ev in
                      ("fired", "won", "lost", "budget_denied")}
            phase = run_phase(c)
            phase.update({f"hedges_{ev}": int(HEDGE_EVENTS.get(event=ev)
                                              - before[ev])
                          for ev in before})
            out["stalled_hedge_on"] = phase
        finally:
            c.close()
        base_p99 = max(out["unstalled"]["p99_ms"], 1e-6)
        out["p99_vs_unstalled"] = {
            "hedge_off": round(
                out["stalled_hedge_off"]["p99_ms"] / base_p99, 2),
            "hedge_on": round(
                out["stalled_hedge_on"]["p99_ms"] / base_p99, 2)}
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        for d in dirs:
            shutil.rmtree(d, ignore_errors=True)
    results["tail_latency"] = out
    log(f"tail_latency: {json.dumps(out)}")


def roofline_detail(platform, results, rows):
    """Analytic achieved-bandwidth/FLOP numbers for the headline query,
    plus the chip roofline when on TPU — the MFU computation the round-3
    verdict asked for. double-groupby-all streams rows x (10 fields + ts
    + hostname + group ids) once through the segment-sum kernel, so
    bytes-touched / p50 is the effective HBM rate; FLOPs are one
    multiply-add per cell (segment-sum), so the op intensity is ~0.25
    FLOP/byte — this workload lives on the HBM-bandwidth wall, not the
    MXU, and bandwidth utilization IS its MFU analog."""
    dg = results.get("double_groupby_all")
    if not dg:
        return None
    p50_s = dg["p50_ms"] / 1000.0
    nf = len(FIELDS)
    # prepared plane (f64): values + ones column; ts i64 + tag i32 for keys
    bytes_planes = rows * (nf + 1) * 8
    bytes_keys = rows * (8 + 4)
    total_bytes = bytes_planes + bytes_keys
    flops = rows * nf * 2  # multiply-add per value cell
    out = {
        "note": ("analytic roofline from query shape; workload is "
                 "bandwidth-bound (op intensity ~0.25 FLOP/B)"),
        "bytes_touched": total_bytes,
        "achieved_gbps": round(total_bytes / p50_s / 1e9, 1),
        "achieved_gflops": round(flops / p50_s / 1e9, 1),
    }
    if platform == "tpu":
        # v5e: 819 GB/s HBM, 197 TFLOP/s bf16 / 98.5 f32 per chip —
        # sourced from the roofline accountant so bench and span stamps
        # share one peak table
        from greptimedb_tpu.utils import roofline
        peak_gbps = roofline.peak_gbps("tpu")
        out["peak_hbm_gbps"] = peak_gbps
        out["hbm_utilization"] = round(
            total_bytes / p50_s / 1e9 / peak_gbps, 3)
    return out


def probe_backend():
    """Verify jax backend init in a throwaway subprocess before touching it
    in-process. TPU plugin init is flaky (round-1 BENCH_r01 rc=1: UNAVAILABLE
    at setup) and can hang; a child process can neither poison our backend
    cache nor hang us past the timeout. Bounded retries with backoff; on
    persistent failure fall back to CPU so a number is still produced.

    Returns (backend, attempts): `attempts` is the full transcript —
    rc/stderr tail/duration per try — and rides into the result JSON
    under detail.probe so the round artifact explains ITSELF when the
    tunnel is down (round-3 verdict: the probe story was lost to stderr)."""
    # the axon sitecustomize overrides the JAX_PLATFORMS env var at
    # interpreter start; jax.config.update after import is authoritative
    code = (
        "import os, jax\n"
        "if os.environ.get('JAX_PLATFORMS') == 'cpu':\n"
        "    jax.config.update('jax_platforms', 'cpu')\n"
        "print([d.platform for d in jax.devices()])"
    )
    # debug logging so a TIMED-OUT probe still records how far backend
    # init got (e.g. "Initializing backend 'axon'" then silence = the
    # tunnel accepted the plugin registration and hung in device init)
    probe_env = dict(os.environ,
                     JAX_DEBUG_LOG_MODULES="jax._src.xla_bridge")
    attempts = []
    for attempt in range(1, INIT_RETRIES + 1):
        t0 = time.monotonic()
        try:
            r = subprocess.run(
                [sys.executable, "-c", code],
                capture_output=True, text=True, timeout=INIT_TIMEOUT_S,
                env=probe_env,
            )
        except subprocess.TimeoutExpired as e:
            log(f"backend probe {attempt}/{INIT_RETRIES}: "
                f"TIMED OUT after {INIT_TIMEOUT_S}s")
            tail = e.stderr or b""
            if isinstance(tail, bytes):
                tail = tail.decode(errors="replace")
            attempts.append({
                "attempt": attempt, "rc": None,
                "outcome": f"timeout after {INIT_TIMEOUT_S}s",
                "seconds": round(time.monotonic() - t0, 1),
                "stderr_tail": tail[-500:],
            })
            r = None
        if r is not None and r.returncode == 0:
            log(f"backend probe {attempt}/{INIT_RETRIES}: OK {r.stdout.strip()}")
            attempts.append({
                "attempt": attempt, "rc": 0,
                "outcome": f"ok {r.stdout.strip()}",
                "seconds": round(time.monotonic() - t0, 1),
            })
            return "default", attempts
        if r is not None:
            log(f"backend probe {attempt}/{INIT_RETRIES}: rc={r.returncode}\n"
                + "\n".join(r.stderr.splitlines()[-6:]))
            attempts.append({
                "attempt": attempt, "rc": r.returncode,
                "outcome": "nonzero exit",
                "seconds": round(time.monotonic() - t0, 1),
                "stderr_tail": r.stderr[-500:],
            })
        if attempt < INIT_RETRIES:
            backoff = 5 * attempt
            log(f"retrying backend init in {backoff}s ...")
            time.sleep(backoff)
    log("WARNING: accelerator backend unavailable after "
        f"{INIT_RETRIES} attempts — falling back to CPU")
    os.environ["JAX_PLATFORMS"] = "cpu"
    return "cpu", attempts


def capture_profile(qe, sql):
    """jax.profiler trace of one hot-path run (only on a real
    accelerator: the trace is for MFU/HBM-bandwidth tuning)."""
    import jax

    profile_dir = os.environ.get(
        "BENCH_PROFILE_DIR", os.path.join(tempfile.gettempdir(),
                                          "gtpu_profile"))
    try:
        with jax.profiler.trace(profile_dir):
            qe.execute_one(sql)
        n_files = sum(len(fs) for _, _, fs in os.walk(profile_dir))
        log(f"profiler trace captured -> {profile_dir} ({n_files} files)")
        return profile_dir
    except Exception as e:  # profiling must never sink the bench
        log(f"profiler capture failed: {e}")
        return None


def main():
    global T_MAIN_START
    data_dir = tempfile.mkdtemp(prefix="gtpu_bench_")
    T_MAIN_START = time.monotonic()
    try:
        backend, probe_attempts = probe_backend()
        import jax
        if backend == "cpu" or os.environ.get("JAX_PLATFORMS") == "cpu":
            # the env var alone is NOT sufficient — the axon sitecustomize
            # overrides it at interpreter start; config.update is what
            # actually pins the platform (tests/conftest.py recipe)
            jax.config.update("jax_platforms", "cpu")
            backend = "cpu"
        log(f"devices: {jax.devices()}")
        platform = jax.devices()[0].platform
        engine, qe = build_db(data_dir)
        log(f"ingesting {HOSTS} hosts x {HOURS}h @{STEP_S}s ...")
        rows, ingest_s = ingest(engine, qe, T0_MS)
        ingest_rps = rows / ingest_s
        log(f"ingested {rows} rows in {ingest_s:.1f}s "
            f"({ingest_rps:,.0f} rows/s)")
        engine.flush(qe.catalog.table("public", "cpu").region_ids[0])
        log("flushed to SST")

        results = {}

        def guarded(name, fn, on=None):
            """One config crashing must degrade to an error entry, not
            sink the whole artifact (round-5 incident: a PromQL span
            edge case killed the TPU attempt outright)."""
            if not (enabled(name) if on is None else on):
                return
            try:
                fn()
            except Exception as e:  # noqa: BLE001 — config isolation
                import traceback

                traceback.print_exc()
                log(f"{name} failed: {e!r}")
                results[name] = {"error": repr(e)[:300]}

        def checkpoint():
            # refresh the salvageable line after EVERY phase (quick ones
            # included): a timeout then loses at most one config, not
            # all of them (round-5: a stale preliminary dropped the
            # completed 100M/promql results on the floor; r05: an
            # EXTERNAL rc=124 kill left no JSON at all — emit_result now
            # also mirrors each line to partial_path())
            emit_result(platform, probe_attempts, results, rows,
                        ingest_rps, None, preliminary=True)

        # first salvageable line BEFORE any query config runs: even a
        # crash inside the cpu suite leaves a parsed artifact carrying
        # the ingest numbers (r01/r04 exited with `parsed: null`
        # because everything before the first checkpoint sank together)
        checkpoint()
        bench_cpu_suite(qe, results, guard=guarded, checkpoint=checkpoint)
        guarded("scan_pipeline",
                lambda: bench_scan_pipeline(engine, qe, results))
        checkpoint()
        guarded("anchor_pyarrow_double_groupby",
                lambda: bench_anchor(engine, qe, results))
        checkpoint()
        # AFTER the anchor: this phase flushes a small extra SST into
        # the cpu table, which must not perturb the anchor's file set
        guarded("device_tier",
                lambda: bench_device_tier(engine, qe, results))
        checkpoint()
        guarded("sql_insert", lambda: bench_sql_insert(qe, results))
        guarded("ingest_qps",
                lambda: bench_ingest_qps(engine, qe, results))
        checkpoint()
        guarded("qps_single_groupby", lambda: bench_qps(qe, results))
        guarded("qps_mixed_tenants",
                lambda: bench_qps_mixed(qe, results))
        guarded("qps_multiproc", lambda: bench_qps_multiproc(results))
        guarded("incremental_agg",
                lambda: bench_incremental_agg(engine, qe, results))
        guarded("mesh_scale", lambda: bench_mesh_scale(results))
        guarded("cluster_pushdown",
                lambda: bench_cluster_pushdown(results))
        guarded("tail_latency", lambda: bench_tail_latency(results))
        guarded("maintenance",
                lambda: bench_maintenance(engine, qe, results))
        # PRELIMINARY emit: the quick configs are done — if a big tracked
        # shape below overruns the supervisor's attempt window, the
        # supervisor salvages this line from the timed-out child's
        # stdout (or the partial file), so a TPU-backed headline
        # survives any overrun
        checkpoint()

        # tracked config #2 first among the big shapes: it is the
        # headline query at scale and must not be starved by the other
        # large ingests ("stream_large" kept as a back-compat alias)
        guarded("double_groupby_100m",
                lambda: bench_double_groupby_100m(engine, qe, results,
                                                  ingest_rps),
                on=(enabled("double_groupby_100m")
                    or enabled("stream_large")))
        checkpoint()
        guarded("promql_rate",
                lambda: bench_promql(engine, qe, results, ingest_rps))
        checkpoint()
        # fixed-cost compaction before the ELASTIC high-cardinality
        # config, which absorbs whatever budget remains
        guarded("compaction_reencode",
                lambda: bench_compaction(engine, qe, results))
        checkpoint()
        guarded("high_cardinality",
                lambda: bench_high_cardinality(engine, qe, results,
                                               ingest_rps))

        profile_dir = None
        if platform not in ("cpu",) and "double_groupby_all" in results:
            avg_list = ", ".join(f"avg({f})" for f in FIELDS)
            t_end_ms = T0_MS + HOURS * 3600 * 1000
            profile_dir = capture_profile(qe, (
                f"SELECT date_bin(INTERVAL '1 hour', ts) AS hour, "
                f"hostname, {avg_list} FROM cpu WHERE ts >= {T0_MS} "
                f"AND ts < {t_end_ms} GROUP BY hour, hostname"))

        emit_result(platform, probe_attempts, results, rows, ingest_rps,
                    profile_dir)
        engine.close()
    finally:
        shutil.rmtree(data_dir, ignore_errors=True)


def emit_result(platform, probe_attempts, results, rows, ingest_rps,
                profile_dir, preliminary=False):
    """Print the one-line result JSON. `proof` is the LAST top-level key
    ON PURPOSE: the round driver captures only a ~4 KB stdout *tail*,
    and in rounds 2-4 the backend/probe/mfu fields (early in `detail`)
    were truncated away, leaving the artifact unable to show whether
    the chip was even tried. Keep the proof block compact (<1 KB) and
    trailing so it always survives the tail capture."""
    dg = results.get("double_groupby_all", {})
    value = dg.get("p50_ms")
    mfu = roofline_detail(platform, results, rows)
    last_probe = probe_attempts[-1] if probe_attempts else {}
    # measured host<->accelerator link profile: the context every
    # device-tier number needs (a tunneled chip pays ~66 ms readback +
    # MB/s-class D2H per query — costs a co-located deployment of the
    # same code does not have)
    try:
        from greptimedb_tpu.query.physical import accelerator_link

        link = accelerator_link()
        link = {k: (None if v == float("inf") else v)
                for k, v in link.items()}
    except Exception:  # noqa: BLE001 — proof must always emit
        link = None
    line = json.dumps({
        "metric": "tsbs_double_groupby_all_p50_ms",
        "value": value,
        "unit": "ms",
        "vs_baseline": dg.get("vs_baseline"),
        "detail": {
            "backend": platform,
            "preliminary": preliminary,
            "probe": probe_attempts,
            "rows": rows,
            "hosts": HOSTS,
            "hours": HOURS,
            "fields": len(FIELDS),
            "ingest_rows_per_s": round(ingest_rps),
            "ingest_vs_baseline": round(
                ingest_rps / BASE_INGEST_ROWS_S, 3),
            "baseline_ms": BASELINE_MS,
            "profile_dir": profile_dir,
            "mfu": mfu,
            "configs": results,
        },
        "proof": {
            "backend": platform,
            "preliminary": preliminary,
            "probe_rc": last_probe.get("rc"),
            "probe_outcome": str(last_probe.get("outcome", ""))[:120],
            "probe_attempts": len(probe_attempts),
            "headline_p50_ms": value,
            "headline_tier": dg.get("tier"),
            "vs_baseline": dg.get("vs_baseline"),
            "warmup_ms": dg.get("warmup_ms"),
            "link": link,
            "mfu": mfu,
        },
    })
    print(line, flush=True)
    # incremental checkpoint: every emit (preliminary or final) is
    # mirrored to disk so ANY kill — child, supervisor, or the whole
    # process tree — leaves the newest completed-phase results readable
    write_partial(line)


def supervise():
    """Run the real bench as a child process under a hard wall-clock cap.

    The backend probe can pass and the tunnel still die before the
    in-process init — then the bench hangs inside a C call that no
    in-process guard can interrupt. The supervisor is immune: it never
    touches jax. Child attempt 1 uses the default backend; if it times out
    or dies without emitting JSON, attempt 2 forces CPU; if that fails too,
    the supervisor emits the error JSON itself. Always ends with ONE JSON
    line on stdout."""
    total_s = int(os.environ.get("BENCH_TOTAL_TIMEOUT_S", "2400"))
    deadline = time.monotonic() + total_s
    # children mirror every emit here; pin the path so this process and
    # its children agree even across tempdir-per-process environments
    os.environ.setdefault(
        "BENCH_PARTIAL_PATH",
        os.path.join(tempfile.gettempdir(),
                     f"gtpu_bench_partial_{os.getpid()}.json"))

    def salvage_partial() -> bool:
        try:
            with open(partial_path(), encoding="utf-8") as f:
                line = f.read().strip()
        except OSError:
            return False
        if line.startswith("{"):
            log("supervisor: salvaged checkpoint from "
                + partial_path())
            print(line, flush=True)
            return True
        return False

    def on_term(signum, frame):
        # the r05 shape: an EXTERNAL timeout kills the SUPERVISOR
        # (rc=124) — stdout pipes from the child die with us, but the
        # checkpoint file survives; emit it as our last act
        log(f"supervisor: signal {signum} — emitting last checkpoint")
        salvage_partial()
        os._exit(0)

    signal.signal(signal.SIGTERM, on_term)
    signal.signal(signal.SIGINT, on_term)
    # full TSBS scale runs everywhere since the prepared-plane fast path
    # (~0.5 s for 17M rows even on CPU); detail.backend records which
    # backend produced the number
    attempts = [{}, {"JAX_PLATFORMS": "cpu"}]
    last_err = "unknown"
    for i, extra_env in enumerate(attempts, 1):
        remaining = deadline - time.monotonic()
        if remaining <= 60:
            last_err = f"total budget {total_s}s exhausted before attempt {i}"
            break
        label = "default backend" if not extra_env else "cpu fallback"
        # non-final attempts may not starve the fallback — but the
        # fallback matters less now that a timed-out attempt's
        # PRELIMINARY line is salvaged (the fallback only covers "the
        # accelerator attempt died before the quick configs finished"),
        # so the reserve is one CPU run up to its own preliminary emit.
        # Widened 300 -> 420 after r05: the anchor phases must fit the
        # fallback window even on a slow box
        attempt_s = remaining if i == len(attempts) \
            else max(60, remaining - 420)
        # the child sizes the big tracked configs against its OWN
        # budget — hand it the attempt deadline, not the global default
        env = dict(os.environ, BENCH_CHILD="1",
                   BENCH_TOTAL_TIMEOUT_S=str(int(attempt_s)), **extra_env)
        log(f"supervisor: attempt {i}/{len(attempts)} ({label}), "
            f"timeout {attempt_s:.0f}s")
        try:
            r = subprocess.run(
                [sys.executable, os.path.abspath(__file__)],
                capture_output=True, text=True, timeout=attempt_s, env=env,
            )
        except subprocess.TimeoutExpired as e:
            tail = e.stderr or b""
            if isinstance(tail, bytes):
                tail = tail.decode(errors="replace")
            log(f"supervisor: attempt {i} TIMED OUT after {attempt_s:.0f}s\n"
                f"{tail[-2000:]}")
            # salvage the child's PRELIMINARY result line: the quick
            # configs completed before a big tracked shape overran the
            # window — a partial artifact from the right backend beats
            # a complete one from the CPU fallback
            partial = e.stdout or b""
            if isinstance(partial, bytes):
                partial = partial.decode(errors="replace")
            for line in reversed(partial.splitlines()):
                if line.startswith("{"):
                    log("supervisor: salvaged preliminary result from "
                        "the timed-out attempt")
                    print(line)
                    return 0
            if salvage_partial():  # stdout empty: fall back to the file
                return 0
            last_err = f"bench timed out after {attempt_s:.0f}s ({label})"
            continue
        sys.stderr.write(r.stderr)
        json_line = None
        for line in reversed(r.stdout.splitlines()):
            if line.startswith("{"):
                json_line = line
                break
        if json_line is not None and r.returncode == 0:
            print(json_line)
            return 0
        last_err = (r.stderr.strip().splitlines() or ["no stderr"])[-1]
        log(f"supervisor: attempt {i} failed rc={r.returncode}")
    if salvage_partial():
        # a failed final attempt may still have checkpointed completed
        # phases — a partial artifact beats a bare error
        return 0
    print(json.dumps({
        "metric": "tsbs_double_groupby_all_p50_ms",
        "value": None,
        "unit": "ms",
        "vs_baseline": None,
        "detail": {"error": last_err},
        "proof": {"backend": None, "error": str(last_err)[:500]},
    }))
    return 1


if __name__ == "__main__":
    if os.environ.get("BENCH_MESH_CHILD"):
        # one mesh_scale size in its own interpreter (device count is
        # fixed at backend init) — must run BEFORE the supervisor check
        sys.exit(mesh_scale_child(int(os.environ["BENCH_MESH_CHILD"])))
    if os.environ.get("BENCH_QPS_MP_CHILD"):
        # one qps_multiproc frontend process (serving fabric attach is
        # per-process) — must run BEFORE the supervisor check
        sys.exit(qps_multiproc_child(
            int(os.environ["BENCH_QPS_MP_CHILD"])))
    if os.environ.get("BENCH_CHILD") != "1":
        sys.exit(supervise())
    try:
        main()
    except BaseException:
        # the supervisor parses our last stdout line as JSON — always emit
        # one, even on catastrophic failure, so the round records a
        # diagnosis instead of a bare rc=1
        traceback.print_exc(file=sys.stderr)
        err = traceback.format_exc().strip().splitlines()[-1]
        print(json.dumps({
            "metric": "tsbs_double_groupby_all_p50_ms",
            "value": None,
            "unit": "ms",
            "vs_baseline": None,
            "detail": {"error": err},
            "proof": {"backend": None, "error": err[:500]},
        }))
        sys.exit(1)
