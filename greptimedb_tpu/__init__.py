"""greptimedb_tpu — a TPU-native time-series database framework.

A from-scratch re-design of the capabilities of GreptimeDB (the reference
surveyed in SURVEY.md): SQL + PromQL engines, LSM columnar storage over
Parquet with WAL durability, region partitioning with a metadata plane, and
continuous aggregation — with the scan/aggregate/PromQL hot path executed as
XLA-compiled kernels on TPU via JAX (segment reductions for group-by,
sort-based merge-dedup, blockwise windowed kernels for time buckets and
PromQL range vectors, sharded partial aggregation over a jax.sharding.Mesh).

Layer map (mirrors SURVEY.md §1, re-designed TPU-first):

  servers/    wire protocols (HTTP SQL/PromQL, Influx line protocol, ...)
  query/      SQL logical plan -> jit'd device stages (QueryEngine)
  sql/        SQL parser (hand-written; reference forked sqlparser-rs)
  promql/     PromQL parser + compiler onto the same plan algebra
  catalog/    table catalog over a KvBackend trait (memory impl first)
  storage/    region engine: memtable, WAL, Parquet SST, manifest, flush
  ops/        the device kernel library (the differentiator)
  parallel/   mesh construction, sharded partial aggregation
  datatypes/  Arrow-backed type system with time-index metadata
"""

import os as _os

import jax

# Timestamps are int64 nanoseconds end-to-end (reference:
# src/common/time/src/timestamp.rs); sums over billions of rows need f64
# accumulators on CPU test paths. TPU kernels down-cast hot-loop field data
# to f32/bf16 explicitly where profitable.
jax.config.update("jax_enable_x64", True)

# Operability escape hatch: pin the jax platform regardless of what the
# host's sitecustomize forces (JAX_PLATFORMS alone is overridden there).
# A server on a box whose accelerator tunnel is down would otherwise
# hang forever inside backend init — GREPTIMEDB_TPU_PLATFORM=cpu keeps
# it serving on the host tier.
_plat = _os.environ.get("GREPTIMEDB_TPU_PLATFORM")
if _plat:
    jax.config.update("jax_platforms", _plat)

# Persistent XLA compilation cache: first-compile of the fused aggregation
# program costs ~20-40s on TPU; caching it on disk makes every later
# process (server restarts, the bench, CLI tools) start warm. Opt out with
# GREPTIMEDB_TPU_COMPILE_CACHE=off, redirect with =<dir>.
_cc = _os.environ.get("GREPTIMEDB_TPU_COMPILE_CACHE", "")
if _cc.lower() not in ("off", "0", "none", "false", "no", "disabled"):
    try:
        jax.config.update(
            "jax_compilation_cache_dir",
            _cc or _os.path.join(_os.path.expanduser("~"), ".cache",
                                 "greptimedb_tpu_xla"))
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
    except Exception:  # noqa: BLE001 — older jax: feature is optional
        pass

__version__ = "0.1.0"
