"""greptimedb_tpu — a TPU-native time-series database framework.

A from-scratch re-design of the capabilities of GreptimeDB (the reference
surveyed in SURVEY.md): SQL + PromQL engines, LSM columnar storage over
Parquet with WAL durability, region partitioning with a metadata plane, and
continuous aggregation — with the scan/aggregate/PromQL hot path executed as
XLA-compiled kernels on TPU via JAX (segment reductions for group-by,
sort-based merge-dedup, blockwise windowed kernels for time buckets and
PromQL range vectors, sharded partial aggregation over a jax.sharding.Mesh).

Layer map (mirrors SURVEY.md §1, re-designed TPU-first):

  servers/    wire protocols (HTTP SQL/PromQL, Influx line protocol, ...)
  query/      SQL logical plan -> jit'd device stages (QueryEngine)
  sql/        SQL parser (hand-written; reference forked sqlparser-rs)
  promql/     PromQL parser + compiler onto the same plan algebra
  catalog/    table catalog over a KvBackend trait (memory impl first)
  storage/    region engine: memtable, WAL, Parquet SST, manifest, flush
  ops/        the device kernel library (the differentiator)
  parallel/   mesh construction, sharded partial aggregation
  datatypes/  Arrow-backed type system with time-index metadata
"""

import os as _os

import jax

# Timestamps are int64 nanoseconds end-to-end (reference:
# src/common/time/src/timestamp.rs); sums over billions of rows need f64
# accumulators on CPU test paths. TPU kernels down-cast hot-loop field data
# to f32/bf16 explicitly where profitable.
jax.config.update("jax_enable_x64", True)

# Operability escape hatch: pin the jax platform regardless of what the
# host's sitecustomize forces (JAX_PLATFORMS alone is overridden there).
# A server on a box whose accelerator tunnel is down would otherwise
# hang forever inside backend init — GREPTIMEDB_TPU_PLATFORM=cpu keeps
# it serving on the host tier.
_plat = _os.environ.get("GREPTIMEDB_TPU_PLATFORM")
if _plat:
    jax.config.update("jax_platforms", _plat)

# Persistent XLA compilation cache: first-compile of the fused aggregation
# program costs ~20-40s on TPU; caching it on disk makes every later
# process (server restarts, the bench, CLI tools) start warm. Opt out with
# GREPTIMEDB_TPU_COMPILE_CACHE=off, redirect with =<dir>.
_cc = _os.environ.get("GREPTIMEDB_TPU_COMPILE_CACHE", "")
if _cc.lower() not in ("off", "0", "none", "false", "no", "disabled"):
    def _host_salt() -> str:
        """CPU-feature fingerprint in the cache path: XLA's cache key
        ignores the host microarchitecture, and on shared VMs that
        MIGRATE between machine types it loads AOT results compiled for
        the other profile (observed: +prefer-no-scatter executables
        running the slow non-scatter codegen here, with a cpu_aot_loader
        'could lead to SIGILL' warning). A per-profile directory means a
        mismatched executable is never loaded."""
        try:
            import hashlib

            keep = ("flags", "model name", "model\t", "cpu family",
                    "stepping", "vendor_id")
            lines = []
            with open("/proc/cpuinfo", encoding="utf-8") as f:
                for line in f:
                    if line.startswith(keep):
                        lines.append(line)
                    if line.strip() == "" and lines:
                        break  # first core is representative
            joined = "".join(lines)
            # cloud VMs MASK the microarch ("Intel(R) Xeon(R) Processor
            # @ 2.10GHz" on every profile) AND live-migrate between
            # physical hosts WITHOUT rebooting — cpuinfo and boot_id
            # both stay constant while XLA's CPUID probe sees a
            # different machine, so no salt keeps a persistent XLA:CPU
            # executable valid (round-5: +prefer-no-scatter entries
            # compiled hours earlier in the SAME boot loaded onto a
            # migrated host and ran ~3x slow). Policy on masked hosts:
            # - CPU-pinned process: DISABLE the cache (every cached
            #   executable is an XLA:CPU one at risk); the hedged
            #   warm-up absorbs cold compiles.
            # - accelerator-capable process: keep a BOOT-salted cache —
            #   TPU executables target the chip, not the host CPU, and
            #   first-compiles through a remote helper cost ~25 s each.
            masked = "model name" not in joined or \
                "Processor @" in joined
            if masked:
                cpu_pinned = _plat == "cpu" or \
                    _os.environ.get("JAX_PLATFORMS", "") == "cpu"
                if cpu_pinned:
                    return None
                try:
                    with open("/proc/sys/kernel/random/boot_id",
                              encoding="utf-8") as f:
                        joined += f.read()
                except OSError:
                    pass
            if joined:
                return hashlib.sha256(joined.encode()).hexdigest()[:12]
        except OSError:
            pass
        return "noflags"

    try:
        _salt = _host_salt()
        if _cc or _salt is not None:
            jax.config.update(
                "jax_compilation_cache_dir",
                _cc or _os.path.join(_os.path.expanduser("~"), ".cache",
                                     f"greptimedb_tpu_xla_{_salt}"))
            jax.config.update(
                "jax_persistent_cache_min_compile_time_secs", 0.5)
        # masked-microarch host and no explicit dir: persistent cache
        # stays OFF (see _host_salt) — explicitly setting
        # GREPTIMEDB_TPU_COMPILE_CACHE=<dir> overrides for operators
        # who know their fleet doesn't live-migrate
    except Exception:  # noqa: BLE001 — older jax: feature is optional
        pass

# Runtime lock-order validation (lint/lockdep.py): GTPU_LOCKDEP=1
# wraps threading.Lock/RLock *before* any repo module constructs one,
# so every lock the storage/concurrency/maintenance planes create is
# tracked and tier-1 can assert the observed nesting stays acyclic.
if _os.environ.get("GTPU_LOCKDEP") == "1":
    from greptimedb_tpu.lint import lockdep as _lockdep

    _lockdep.install()

__version__ = "0.1.0"
