from greptimedb_tpu.cli import main

main()
