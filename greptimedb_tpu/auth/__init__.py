"""Authentication & authorization (mirrors reference `src/auth`:
`UserProvider` trait, static file/options user providers, permission
checks — src/auth/src/lib.rs, user_provider.rs, permission.rs).

Providers verify credentials per wire protocol:
- HTTP: Basic auth (username:password)
- MySQL: mysql_native_password scramble (SHA1 challenge-response)
- PostgreSQL: cleartext password message

Authorization is a coarse per-statement permission check
(reference `PermissionChecker`, src/auth/src/permission.rs).
"""

from __future__ import annotations

import base64
import hashlib
import hmac
import os
from dataclasses import dataclass, field
from typing import Optional

__all__ = [
    "AuthError",
    "UserInfo",
    "UserProvider",
    "StaticUserProvider",
    "PermissionChecker",
    "user_provider_from_option",
    "mysql_native_scramble",
]


class AuthError(Exception):
    """Authentication / authorization failure (wire boundary error)."""


@dataclass(frozen=True)
class UserInfo:
    """Authenticated principal (reference src/auth/src/user_info.rs)."""

    username: str
    # coarse grants: statement kinds this user may run; None = all
    grants: Optional[frozenset] = None

    def can(self, permission: str) -> bool:
        return self.grants is None or permission in self.grants


class UserProvider:
    """Base provider (reference `UserProvider` trait,
    src/auth/src/user_provider.rs). Subclasses implement `lookup`."""

    name = "user_provider"

    def lookup(self, username: str) -> Optional[str]:
        """Return the stored plaintext password for `username`, or None
        if the user is unknown."""
        raise NotImplementedError

    # -- protocol-specific verification --------------------------------------

    def authenticate(self, username: str, password: str) -> UserInfo:
        stored = self.lookup(username)
        if stored is None or not hmac.compare_digest(
                stored.encode(), password.encode()):
            raise AuthError(f"access denied for user {username!r}")
        return UserInfo(username)

    def authenticate_basic(self, authorization_header: str) -> UserInfo:
        """HTTP `Authorization: Basic <b64>` (reference
        servers/src/http/authorize.rs)."""
        scheme, _, payload = authorization_header.partition(" ")
        if scheme.lower() != "basic" or not payload:
            raise AuthError("unsupported authorization scheme")
        try:
            decoded = base64.b64decode(payload.strip()).decode()
            username, _, password = decoded.partition(":")
        except Exception as e:  # noqa: BLE001 — wire boundary
            raise AuthError("malformed basic auth payload") from e
        return self.authenticate(username, password)

    def authenticate_mysql(self, username: str, auth_response: bytes,
                           salt: bytes) -> UserInfo:
        """mysql_native_password: client sends
        SHA1(pwd) XOR SHA1(salt + SHA1(SHA1(pwd)))."""
        stored = self.lookup(username)
        if stored is None:
            raise AuthError(f"access denied for user {username!r}")
        # empty stored password ⇒ client sends a zero-length auth response
        expect = mysql_native_scramble(stored, salt) if stored else b""
        if not hmac.compare_digest(auth_response, expect):
            raise AuthError(f"access denied for user {username!r}")
        return UserInfo(username)

    # back-compat shim for the earlier name-only hook used by the wire
    # servers before password auth landed
    def allow(self, username: str) -> bool:
        return self.lookup(username) is not None


class StaticUserProvider(UserProvider):
    """Fixed user/password table, from inline pairs or a credentials file
    (reference static_user_provider, src/auth/src/user_provider/
    static_user_provider.rs: `static_user_provider:file:<path>` and
    `static_user_provider:cmd:<u>=<p>[,<u>=<p>]`)."""

    name = "static_user_provider"

    def __init__(self, users: dict[str, str]):
        if not users:
            raise AuthError("static user provider needs at least one user")
        self._users = dict(users)

    @classmethod
    def from_pairs(cls, spec: str) -> "StaticUserProvider":
        users = {}
        for part in spec.split(","):
            user, sep, pwd = part.partition("=")
            if not sep or not user:
                raise AuthError(f"malformed user spec {part!r}")
            users[user.strip()] = pwd
        return cls(users)

    @classmethod
    def from_file(cls, path: str) -> "StaticUserProvider":
        if not os.path.exists(path):
            raise AuthError(f"user file {path!r} not found")
        users = {}
        with open(path, encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if not line or line.startswith("#"):
                    continue
                user, sep, pwd = line.partition("=")
                if sep:
                    users[user.strip()] = pwd.strip()
        return cls(users)

    def lookup(self, username: str) -> Optional[str]:
        return self._users.get(username)


def user_provider_from_option(option: str) -> UserProvider:
    """Parse `--user-provider` style option strings (reference
    src/auth/src/lib.rs user_provider_from_option)."""
    kind, _, rest = option.partition(":")
    if kind != StaticUserProvider.name:
        raise AuthError(f"unknown user provider {kind!r}")
    mode, _, value = rest.partition(":")
    if mode == "file":
        return StaticUserProvider.from_file(value)
    if mode == "cmd":
        return StaticUserProvider.from_pairs(value)
    raise AuthError(f"unknown static provider mode {mode!r}")


def mysql_native_scramble(password: str, salt: bytes) -> bytes:
    """SHA1(pwd) XOR SHA1(salt + SHA1(SHA1(pwd))) per the MySQL
    native-password handshake."""
    h1 = hashlib.sha1(password.encode()).digest()
    h2 = hashlib.sha1(h1).digest()
    h3 = hashlib.sha1(salt + h2).digest()
    return bytes(a ^ b for a, b in zip(h1, h3))


# ---- authorization ----------------------------------------------------------


#: statement-class → permission name (reference permission.rs maps
#: Statement kinds to read/write requirements per catalog/schema)
_WRITE_STMTS = frozenset({
    "Insert", "Delete", "CreateTable", "CreateDatabase", "DropTable",
    "TruncateTable", "AlterTable", "CreateFlow", "DropFlow", "AdminFunc",
    "CreateView", "DropView",
    # COPY FROM writes into tables; COPY TO writes server-side files —
    # both require the write grant
    "CopyTable", "CopyDatabase",
})


class PermissionChecker:
    """Coarse statement authorization (reference `PermissionChecker`
    trait, src/auth/src/permission.rs). Deny reads/writes on protected
    schemas; consult the user's grants."""

    PROTECTED_SCHEMAS = frozenset({"greptime_private"})

    def check(self, user: Optional[UserInfo], stmt, db: str) -> None:
        kind = type(stmt).__name__
        needed = "write" if kind in _WRITE_STMTS else "read"
        self.check_access(user, needed, db)

    def check_access(self, user: Optional[UserInfo], needed: str,
                     db: str) -> None:
        """Grant + protected-schema check for a raw access kind — used by
        non-SQL entry points (Flight do_put bulk ingest, region scans)
        that don't carry a statement AST."""
        # protected-schema rule applies to every context, authenticated or
        # not: only the admin user may write greptime_private; reads are
        # allowed for everyone
        if db in self.PROTECTED_SCHEMAS and needed == "write" \
                and (user is None or user.username != "greptime"):
            raise AuthError(f"schema {db!r} is protected")
        if user is None:
            return
        if not user.can(needed):
            raise AuthError(
                f"user {user.username!r} lacks {needed} permission")
