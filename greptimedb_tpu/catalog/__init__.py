"""Catalog: table metadata over a KvBackend (mirrors reference
src/catalog `KvBackendCatalogManager` + src/common/meta key schema).

The reference's key trick (SURVEY.md §4): every metadata consumer is
written against the `KvBackend` trait so tests swap in the memory impl and
the whole metadata plane runs in one process. Same here.
"""

from greptimedb_tpu.catalog.kv import KvBackend, MemoryKv, FileKv
from greptimedb_tpu.catalog.catalog import Catalog, TableInfo

__all__ = ["KvBackend", "MemoryKv", "FileKv", "Catalog", "TableInfo"]
