"""Catalog manager: databases + tables over a KvBackend.

Key schema mirrors reference src/common/meta/src/key/:
  __catalog/<db>                      -> "{}"
  __table_name/<db>/<table>           -> table_id
  __table_info/<table_id>             -> {name, db, schema, options, regions}
  __seq/table_id                      -> id sequence
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import Optional

from greptimedb_tpu.catalog.kv import KvBackend
from greptimedb_tpu.datatypes.schema import Schema

DEFAULT_DB = "public"


class CatalogError(Exception):
    pass


@dataclass
class TableInfo:
    table_id: int
    name: str
    db: str
    schema: Schema
    options: dict = field(default_factory=dict)
    region_ids: list[int] = field(default_factory=list)
    partition_rules: Optional[list] = None  # (round 1: single region)
    # user-declared column order from CREATE TABLE; the Schema itself is
    # canonicalized to (tags, ts, fields) for storage, but positional
    # INSERT and DESCRIBE follow the declared order
    column_order: Optional[list] = None

    @property
    def append_mode(self) -> bool:
        return str(self.options.get("append_mode", "false")).lower() == "true"

    def to_json(self) -> str:
        return json.dumps(
            {
                "table_id": self.table_id,
                "name": self.name,
                "db": self.db,
                "schema": self.schema.to_dict(),
                "options": self.options,
                "region_ids": self.region_ids,
                "partition_rules": self.partition_rules,
                "column_order": self.column_order,
            }
        )

    @staticmethod
    def from_json(s: str) -> "TableInfo":
        d = json.loads(s)
        return TableInfo(
            table_id=d["table_id"],
            name=d["name"],
            db=d["db"],
            schema=Schema.from_dict(d["schema"]),
            options=d.get("options", {}),
            region_ids=d.get("region_ids", []),
            partition_rules=d.get("partition_rules"),
            column_order=d.get("column_order"),
        )


class Catalog:
    def __init__(self, kv: KvBackend):
        self.kv = kv
        if self.kv.get(f"__catalog/{DEFAULT_DB}") is None:
            self.kv.put(f"__catalog/{DEFAULT_DB}", "{}")

    # ---- databases ---------------------------------------------------------

    def create_database(self, name: str, if_not_exists: bool = False) -> None:
        if not self.kv.compare_and_put(f"__catalog/{name}", None, "{}"):
            if not if_not_exists:
                raise CatalogError(f"database {name!r} already exists")

    def list_databases(self) -> list[str]:
        return [k.split("/", 1)[1] for k, _ in self.kv.range("__catalog/")]

    def database_exists(self, name: str) -> bool:
        return self.kv.get(f"__catalog/{name}") is not None

    # ---- tables ------------------------------------------------------------

    def create_table(
        self,
        db: str,
        name: str,
        schema: Schema,
        options: Optional[dict] = None,
        if_not_exists: bool = False,
        num_regions: int = 1,
        partition_rules: Optional[list] = None,
        column_order: Optional[list] = None,
        region_ids: Optional[list] = None,
        table_id: Optional[int] = None,
    ) -> TableInfo:
        if not self.database_exists(db):
            raise CatalogError(f"database {db!r} not found")
        existing = self.kv.get(f"__table_name/{db}/{name}")
        if existing is not None:
            if if_not_exists:
                return self.table(db, name)
            raise CatalogError(f"table {db}.{name} already exists")
        if self.kv.get(f"__view/{db}/{name}") is not None:
            # a view would shadow the table at read time while writes hit
            # the table — never allow the name collision
            raise CatalogError(f"{db}.{name} exists as a view")
        if table_id is None:
            table_id = self.kv.incr("__seq/table_id", start=1023)
        if region_ids is None:
            # region id layout mirrors the reference: table_id << 32 | region_number
            region_ids = [(table_id << 32) | i for i in range(num_regions)]
        info = TableInfo(
            table_id=table_id, name=name, db=db, schema=schema,
            options=options or {}, region_ids=region_ids,
            partition_rules=partition_rules, column_order=column_order,
        )
        self.kv.put(f"__table_info/{table_id}", info.to_json())
        if not self.kv.compare_and_put(f"__table_name/{db}/{name}", None, str(table_id)):
            raise CatalogError(f"concurrent create of {db}.{name}")
        return info

    def table(self, db: str, name: str) -> TableInfo:
        tid = self.kv.get(f"__table_name/{db}/{name}")
        if tid is None:
            raise CatalogError(f"table {db}.{name} not found")
        return TableInfo.from_json(self.kv.get(f"__table_info/{tid}"))

    def table_exists(self, db: str, name: str) -> bool:
        return self.kv.get(f"__table_name/{db}/{name}") is not None

    # ---- views (reference common/meta view keys + ddl create_view) ---------

    def create_view(self, db: str, name: str, query_sql: str,
                    or_replace: bool = False,
                    if_not_exists: bool = False) -> None:
        if not self.database_exists(db):
            raise CatalogError(f"database {db!r} not found")
        if self.table_exists(db, name):
            raise CatalogError(f"{db}.{name} exists as a table")
        key = f"__view/{db}/{name}"
        if self.kv.get(key) is not None and not or_replace:
            if if_not_exists:
                return
            raise CatalogError(f"view {db}.{name} already exists")
        self.kv.put(key, query_sql)

    def view(self, db: str, name: str) -> Optional[str]:
        return self.kv.get(f"__view/{db}/{name}")

    def drop_view(self, db: str, name: str, if_exists: bool = False) -> bool:
        key = f"__view/{db}/{name}"
        if self.kv.get(key) is None:
            if if_exists:
                return False
            raise CatalogError(f"view {db}.{name} not found")
        self.kv.delete(key)
        return True

    def list_views(self, db: str) -> list[str]:
        return [k.rsplit("/", 1)[1] for k, _ in self.kv.range(f"__view/{db}/")]

    def table_id(self, db: str, name: str) -> Optional[int]:
        """The id the name currently maps to, or None — lets callers
        (journaled DDL) distinguish 'our table is gone' from 'a different
        table took the name' without knowing the key schema."""
        tid = self.kv.get(f"__table_name/{db}/{name}")
        return int(tid) if tid is not None else None

    def list_tables(self, db: str) -> list[str]:
        return [k.rsplit("/", 1)[1] for k, _ in self.kv.range(f"__table_name/{db}/")]

    def drop_table(self, db: str, name: str, if_exists: bool = False) -> Optional[TableInfo]:
        tid = self.kv.get(f"__table_name/{db}/{name}")
        if tid is None:
            if if_exists:
                return None
            raise CatalogError(f"table {db}.{name} not found")
        info = TableInfo.from_json(self.kv.get(f"__table_info/{tid}"))
        self.kv.delete(f"__table_name/{db}/{name}")
        self.kv.delete(f"__table_info/{tid}")
        return info

    def update_table(self, info: TableInfo) -> None:
        self.kv.put(f"__table_info/{info.table_id}", info.to_json())

    def all_tables(self) -> list[TableInfo]:
        return [TableInfo.from_json(v) for _, v in self.kv.range("__table_info/")]
