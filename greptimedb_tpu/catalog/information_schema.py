"""`information_schema` virtual tables (mirrors reference
src/catalog/src/information_schema/*.rs: tables, columns, schemata,
partitions, region_peers, cluster_info, runtime_metrics, engines, flows).

Virtual tables materialize from catalog/engine state at query time as
host-side column dicts; a small host evaluator applies WHERE / projection
/ ORDER BY / LIMIT (these tables are tiny — no device round-trip).
"""

from __future__ import annotations

import time

import numpy as np

from greptimedb_tpu.datatypes.types import DataType
from greptimedb_tpu.query.result import QueryResult
from greptimedb_tpu.sql import ast

INFORMATION_SCHEMA = "information_schema"

_START_TIME = time.time()

#: virtual table name -> builder(qe, ctx) -> dict[col -> list]
_TABLES = {}


def _virtual(name):
    def deco(fn):
        _TABLES[name] = fn
        return fn
    return deco


def is_information_schema_query(table: str, db: str) -> bool:
    if table is None:
        return False
    t = table.lower()
    return t.startswith(INFORMATION_SCHEMA + ".") or (
        db.lower() == INFORMATION_SCHEMA and t.split(".")[0] in _TABLES
    )


def table_names() -> list[str]:
    return sorted(_TABLES)


# ---- builders ---------------------------------------------------------------


@_virtual("schemata")
def _schemata(qe, ctx):
    dbs = qe.catalog.list_databases()
    return {
        "catalog_name": ["greptime"] * (len(dbs) + 1),
        "schema_name": list(dbs) + [INFORMATION_SCHEMA],
    }


@_virtual("tables")
def _tables(qe, ctx):
    cols = {k: [] for k in ("table_catalog", "table_schema", "table_name",
                            "table_type", "table_id", "engine")}
    for db in qe.catalog.list_databases():
        for name in qe.catalog.list_tables(db):
            info = qe.catalog.table(db, name)
            cols["table_catalog"].append("greptime")
            cols["table_schema"].append(db)
            cols["table_name"].append(name)
            cols["table_type"].append("BASE TABLE")
            cols["table_id"].append(info.table_id)
            cols["engine"].append(info.options.get("engine", "mito"))
    for vt in table_names():
        cols["table_catalog"].append("greptime")
        cols["table_schema"].append(INFORMATION_SCHEMA)
        cols["table_name"].append(vt)
        cols["table_type"].append("LOCAL TEMPORARY")
        cols["table_id"].append(0)
        cols["engine"].append("virtual")
    return cols


@_virtual("columns")
def _columns(qe, ctx):
    cols = {k: [] for k in (
        "table_catalog", "table_schema", "table_name", "column_name",
        "ordinal_position", "data_type", "semantic_type", "is_nullable",
        "column_default")}
    for db in qe.catalog.list_databases():
        for name in qe.catalog.list_tables(db):
            info = qe.catalog.table(db, name)
            for i, c in enumerate(info.schema.columns):
                cols["table_catalog"].append("greptime")
                cols["table_schema"].append(db)
                cols["table_name"].append(name)
                cols["column_name"].append(c.name)
                cols["ordinal_position"].append(i + 1)
                cols["data_type"].append(c.dtype.value)
                cols["semantic_type"].append(c.semantic.value.upper())
                cols["is_nullable"].append("Yes" if c.nullable else "No")
                cols["column_default"].append(
                    "" if c.default is None else str(c.default))
    return cols


@_virtual("partitions")
def _partitions(qe, ctx):
    cols = {k: [] for k in ("table_catalog", "table_schema", "table_name",
                            "partition_name", "partition_expression",
                            "greptime_partition_id")}
    for db in qe.catalog.list_databases():
        for name in qe.catalog.list_tables(db):
            info = qe.catalog.table(db, name)
            exprs = [None] * len(info.region_ids)
            if info.partition_rules:
                rules = info.partition_rules
                if isinstance(rules, dict):
                    bounds = rules.get("bounds") or []
                    exprs = [str(b) for b in bounds] + [None]
                    exprs = exprs[:len(info.region_ids)] or [None]
            for i, rid in enumerate(info.region_ids):
                cols["table_catalog"].append("greptime")
                cols["table_schema"].append(db)
                cols["table_name"].append(name)
                cols["partition_name"].append(f"p{i}")
                cols["partition_expression"].append(
                    exprs[i] if i < len(exprs) else None)
                cols["greptime_partition_id"].append(rid)
    return cols


@_virtual("region_peers")
def _region_peers(qe, ctx):
    cols = {k: [] for k in ("region_id", "peer_id", "peer_addr",
                            "is_leader", "status")}
    cluster = getattr(qe, "cluster", None)
    route = {}
    if cluster is not None and hasattr(cluster, "region_routes"):
        route = cluster.region_routes()
    for db in qe.catalog.list_databases():
        for name in qe.catalog.list_tables(db):
            info = qe.catalog.table(db, name)
            for rid in info.region_ids:
                peer = route.get(rid, 0)
                cols["region_id"].append(rid)
                cols["peer_id"].append(peer)
                cols["peer_addr"].append(f"datanode-{peer}")
                cols["is_leader"].append("Yes")
                cols["status"].append("ALIVE")
    return cols


@_virtual("cluster_info")
def _cluster_info(qe, ctx):
    from greptimedb_tpu import __version__

    cols = {k: [] for k in ("peer_id", "peer_type", "peer_addr", "version",
                            "start_time", "uptime")}
    cluster = getattr(qe, "cluster", None)
    peers = []
    if cluster is not None and hasattr(cluster, "datanode_ids"):
        peers = [(pid, "DATANODE") for pid in cluster.datanode_ids()]
        peers += [(0, "METASRV")]
    peers.append((0, "STANDALONE") if not peers else (0, "FRONTEND"))
    uptime = time.time() - _START_TIME
    for pid, ptype in peers:
        cols["peer_id"].append(pid)
        cols["peer_type"].append(ptype)
        cols["peer_addr"].append("127.0.0.1")
        cols["version"].append(__version__)
        cols["start_time"].append(int(_START_TIME * 1000))
        cols["uptime"].append(f"{uptime:.0f}s")
    return cols


@_virtual("runtime_metrics")
def _runtime_metrics(qe, ctx):
    from greptimedb_tpu.utils.metrics import REGISTRY

    cols = {"metric_name": [], "value": [], "labels": [],
            "timestamp": []}
    now = int(time.time() * 1000)
    for name, value, labels in REGISTRY.samples():
        cols["metric_name"].append(name)
        cols["value"].append(float(value))
        cols["labels"].append(labels)
        cols["timestamp"].append(now)
    return cols


@_virtual("slow_queries")
def _slow_queries(qe, ctx):
    """Slow-query ring (utils/slow_query.py), newest first — the system
    table surface of the slow-query log (the reference exposes its slow
    queries the same way)."""
    from greptimedb_tpu.utils import slow_query

    cols = {k: [] for k in (
        "trace_id", "kind", "query", "db", "duration_ms", "threshold_ms",
        "rows", "execution_path", "plan_cache_skip", "started_at",
        "stages", "ledger", "achieved_gbps", "roofline_fraction")}
    for rec in slow_query.records():
        cols["trace_id"].append(rec.trace_id)
        cols["kind"].append(rec.kind)
        cols["query"].append(rec.query)
        cols["db"].append(rec.db)
        cols["duration_ms"].append(round(rec.duration_ms, 3))
        cols["threshold_ms"].append(rec.threshold_ms)
        cols["rows"].append(rec.rows)
        cols["execution_path"].append(rec.execution_path or "")
        cols["plan_cache_skip"].append(rec.plan_cache_skip or "")
        cols["started_at"].append(int(rec.started_at * 1000))
        cols["stages"].append("; ".join(
            f"{'' if n == 'local' else '[' + str(n) + '] '}{s}={d:.2f}ms"
            for n, s, d in rec.stages))
        from greptimedb_tpu.utils import ledger as _ledger

        cols["ledger"].append(_ledger.format_dict(rec.ledger))
        cols["achieved_gbps"].append(rec.achieved_gbps)
        cols["roofline_fraction"].append(rec.roofline_fraction)
    return cols


@_virtual("running_queries")
def _running_queries(qe, ctx):
    """Live statements on this frontend (utils/deadline.py RUNNING
    registry) — id, text, origin, elapsed vs remaining budget, and
    whether a cancel is already pending. The id column feeds
    KILL QUERY <id> and DELETE /v1/queries/<id>."""
    from greptimedb_tpu.utils import deadline

    cols = {k: [] for k in (
        "id", "query", "db", "channel", "tenant", "trace_id",
        "started_at", "elapsed_ms", "remaining_ms", "cancelled")}
    for e in deadline.RUNNING.list():
        cols["id"].append(e["id"])
        cols["query"].append(e["query"][:4096])
        cols["db"].append(e["db"])
        cols["channel"].append(e["channel"])
        cols["tenant"].append(e["tenant"])
        cols["trace_id"].append(e["trace_id"])
        cols["started_at"].append(e["start_time_ms"])
        cols["elapsed_ms"].append(round(e["elapsed_ms"], 3))
        cols["remaining_ms"].append(
            None if e["remaining_ms"] is None
            else round(e["remaining_ms"], 3))
        cols["cancelled"].append(e["cancelled"])
    return cols


@_virtual("cluster_profile")
def _cluster_profile(qe, ctx):
    """Merged continuous-profiling view (utils/flame.py): one row per
    (node × coarse stage) from the local sampler plus every datanode
    digest that rode in on Flight piggybacks or heartbeats. Empty when
    profiling is disabled everywhere. The `share` column is that
    stage's fraction of the node's samples; `top_frames` names the
    node's hottest self-time frames."""
    from greptimedb_tpu.utils import flame

    cols = {k: [] for k in (
        "node", "stage", "stage_samples", "share", "node_samples",
        "attributed_ratio", "hz", "window_s", "captured_at",
        "top_frames")}
    view = flame.cluster_view()
    for node in sorted(view["nodes"]):
        summ = view["nodes"][node]
        total = summ.get("samples", 0) or 0
        top = "; ".join(f"{r['frame']} x{r['self']}"
                        for r in (summ.get("top") or [])[:3])
        for stage, n in sorted((summ.get("stages") or {}).items()):
            cols["node"].append(node)
            cols["stage"].append(stage)
            cols["stage_samples"].append(int(n))
            cols["share"].append(round(n / total, 4) if total else 0.0)
            cols["node_samples"].append(int(total))
            cols["attributed_ratio"].append(
                round(summ.get("attributed", 0) / total, 4) if total
                else 0.0)
            cols["hz"].append(float(summ.get("hz", 0.0)))
            cols["window_s"].append(float(summ.get("window_s", 0.0)))
            cols["captured_at"].append(int(summ.get("ts_ms", 0)))
            cols["top_frames"].append(top)
    return cols


@_virtual("cluster_faults")
def _cluster_faults(qe, ctx):
    """Armed chaos state + fire counts (fault/ package): one row per
    (armed point × observed counter series), so a chaos run can SELECT
    which node/edge a schedule actually hit, plus one row per installed
    network partition. Empty when chaos is off — the debuggability
    surface for 'the scenario is red, what was armed and what fired?'."""
    from greptimedb_tpu.fault import FAULTS, chaos_seed
    from greptimedb_tpu.utils.metrics import FAULT_INJECTIONS

    cols = {k: [] for k in ("point", "kind", "schedule", "matchers",
                            "edge", "node", "fires", "chaos_seed")}
    seed = chaos_seed()

    def add(point, kind, schedule, matchers, edge, node, fires):
        cols["point"].append(point)
        cols["kind"].append(kind)
        cols["schedule"].append(schedule)
        cols["matchers"].append(matchers)
        cols["edge"].append(edge)
        cols["node"].append(node)
        cols["fires"].append(fires)
        cols["chaos_seed"].append(seed)

    for f in FAULTS.describe():
        matchers = ",".join(f"{k}:{v}" for k, v in sorted(f["match"].items()))
        edges = f["edges"] or [""]
        fired = FAULT_INJECTIONS.series(point=f["point"], kind=f["kind"])
        if not fired:
            for edge in edges:
                add(f["point"], f["kind"], f["schedule"], matchers, edge,
                    "", 0.0)
            continue
        for labels, count in fired:
            add(f["point"], f["kind"], f["schedule"], matchers,
                labels.get("edge", edges[0]), labels.get("node", ""),
                count)
    for edge in FAULTS.partitions():
        add("partition", "partition", "installed", "", edge, "",
            FAULT_INJECTIONS.total(kind="partition", edge=edge))
    return cols


@_virtual("maintenance_jobs")
def _maintenance_jobs(qe, ctx):
    """Background maintenance plane job queue + recent history
    (maintenance/scheduler.py), newest first. Empty when the engine has
    no plane (frontend routers, maintenance_workers=0)."""
    import json as _json

    cols = {k: [] for k in (
        "job_id", "kind", "region_id", "state", "priority", "error",
        "detail", "queued_at", "started_at", "finished_at",
        "duration_ms")}
    maint = getattr(qe.region_engine, "maintenance", None)
    for job in (maint.jobs() if maint is not None else []):
        d = job.to_dict()
        cols["job_id"].append(d["job_id"])
        cols["kind"].append(d["kind"])
        cols["region_id"].append(d["region_id"])
        cols["state"].append(d["state"])
        cols["priority"].append(d["priority"])
        cols["error"].append(d["error"])
        cols["detail"].append(_json.dumps(d["detail"], sort_keys=True))
        cols["queued_at"].append(int(d["queued_at"] * 1000))
        cols["started_at"].append(
            None if d["started_at"] is None else int(d["started_at"] * 1000))
        cols["finished_at"].append(
            None if d["finished_at"] is None
            else int(d["finished_at"] * 1000))
        cols["duration_ms"].append(
            None if d["duration_ms"] is None else round(d["duration_ms"], 3))
    return cols


@_virtual("engines")
def _engines(qe, ctx):
    names = ["mito", "metric", "file"]
    return {
        "engine": names,
        "support": ["DEFAULT"] + ["YES"] * (len(names) - 1),
        "comment": [
            "TPU-native LSM time-series engine",
            "logical tables multiplexed over one physical region",
            "external files as read-only tables",
        ],
    }


@_virtual("views")
def _views(qe, ctx):
    cols = {"table_catalog": [], "table_schema": [], "table_name": [],
            "view_definition": []}
    for db in qe.catalog.list_databases():
        for name in qe.catalog.list_views(db):
            cols["table_catalog"].append("greptime")
            cols["table_schema"].append(db)
            cols["table_name"].append(name)
            cols["view_definition"].append(qe.catalog.view(db, name))
    return cols


@_virtual("flows")
def _flows(qe, ctx):
    cols = {"flow_name": [], "table_catalog": [], "flow_schema": [],
            "source_table": [], "sink_table": [], "raw_sql": []}
    for db in qe.catalog.list_databases():
        for f in qe.flow_engine.list_flows(db):
            cols["flow_name"].append(f.name)
            cols["table_catalog"].append("greptime")
            cols["flow_schema"].append(db)
            cols["source_table"].append(f.source_table)
            cols["sink_table"].append(f.sink_table)
            cols["raw_sql"].append(f.sql)
    return cols


# ---- host-side mini executor ------------------------------------------------


@_virtual("key_column_usage")
def _key_column_usage(qe, ctx):
    """Primary-key / time-index membership per column (reference
    catalog/src/information_schema/key_column_usage.rs:40-55)."""
    cols = {k: [] for k in (
        "constraint_catalog", "constraint_schema", "constraint_name",
        "table_catalog", "table_schema", "table_name", "column_name",
        "ordinal_position")}
    from greptimedb_tpu.datatypes.types import SemanticType

    def add(db, name, constraint, col, pos):
        cols["constraint_catalog"].append("def")
        cols["constraint_schema"].append(db)
        cols["constraint_name"].append(constraint)
        cols["table_catalog"].append("def")
        cols["table_schema"].append(db)
        cols["table_name"].append(name)
        cols["column_name"].append(col)
        cols["ordinal_position"].append(pos)

    for db in qe.catalog.list_databases():
        for name in qe.catalog.list_tables(db):
            info = qe.catalog.table(db, name)
            pos = 1
            for c in info.schema.columns:
                if c.semantic is SemanticType.TAG:
                    add(db, name, "PRIMARY", c.name, pos)
                    pos += 1
            ti = info.schema.time_index
            if ti is not None:
                add(db, name, "TIME INDEX", ti.name, 1)
    return cols


@_virtual("table_constraints")
def _table_constraints(qe, ctx):
    """PRIMARY KEY + TIME INDEX constraints per table (reference
    catalog/src/information_schema/table_constraints.rs)."""
    cols = {k: [] for k in (
        "constraint_catalog", "constraint_schema", "constraint_name",
        "table_schema", "table_name", "constraint_type")}
    for db in qe.catalog.list_databases():
        for name in qe.catalog.list_tables(db):
            info = qe.catalog.table(db, name)
            entries = []
            if info.schema.tag_columns:
                entries.append(("PRIMARY", "PRIMARY KEY"))
            if info.schema.time_index is not None:
                entries.append(("TIME INDEX", "TIME INDEX"))
            for cname, ctype in entries:
                cols["constraint_catalog"].append("def")
                cols["constraint_schema"].append(db)
                cols["constraint_name"].append(cname)
                cols["table_schema"].append(db)
                cols["table_name"].append(name)
                cols["constraint_type"].append(ctype)
    return cols


@_virtual("character_sets")
def _character_sets(qe, ctx):
    # utf8-only, like the reference (memory_table/tables.rs CHARACTER_SETS)
    return {
        "character_set_name": ["utf8"],
        "default_collate_name": ["utf8_bin"],
        "description": ["UTF-8 Unicode"],
        "maxlen": [4],
    }


@_virtual("collations")
def _collations(qe, ctx):
    return {
        "collation_name": ["utf8_bin"],
        "character_set_name": ["utf8"],
        "id": [1],
        "is_default": ["Yes"],
        "is_compiled": ["Yes"],
        "sortlen": [1],
    }


@_virtual("build_info")
def _build_info(qe, ctx):
    import greptimedb_tpu

    return {
        "git_branch": ["main"],
        "git_commit": ["unknown"],
        "git_commit_short": ["unknown"],
        "git_dirty": ["false"],
        "pkg_version": [greptimedb_tpu.__version__],
    }


def execute_virtual_select(qe, sel: ast.Select, ctx) -> QueryResult:
    """SELECT over an information_schema table: materialize, then apply
    WHERE / projection / ORDER BY / LIMIT on host."""
    from greptimedb_tpu.query.expr import PlanError

    t = sel.table.lower()
    name = t.split(".", 1)[1] if t.startswith(INFORMATION_SCHEMA + ".") \
        else t.split(".")[0]
    builder = _TABLES.get(name)
    if builder is None:
        raise PlanError(f"information_schema table {name!r} not found")
    if sel.group_by or sel.having is not None or sel.distinct:
        raise PlanError(
            "GROUP BY/HAVING/DISTINCT not supported on information_schema")
    from greptimedb_tpu.query.expr import eval_host

    data = {k: np.asarray(v, dtype=object) for k, v in builder(qe, ctx).items()}
    n = len(next(iter(data.values()))) if data else 0

    def ev(expr):
        return eval_host(expr, data, None, None, n)

    mask = np.ones(n, dtype=bool)
    if sel.where is not None:
        mask = np.broadcast_to(
            np.asarray(ev(sel.where), dtype=bool), (n,))
    idx = np.nonzero(mask)[0]

    # projection
    star = any(isinstance(it.expr, ast.Star) for it in sel.items)
    is_count = [isinstance(it.expr, ast.FuncCall)
                and it.expr.name.lower() == "count" for it in sel.items]
    if star:
        names = list(data)
        out_cols = [data[c][idx] for c in names]
    elif any(is_count):
        # aggregate shape: only count(*) items allowed (no GROUP BY here)
        if not all(is_count):
            raise PlanError(
                "cannot mix count(*) with plain columns on "
                "information_schema without GROUP BY")
        names = [it.alias or "count(*)" for it in sel.items]
        out_cols = [np.asarray([len(idx)], dtype=object) for _ in sel.items]
    else:
        names, out_cols = [], []
        for i, it in enumerate(sel.items):
            vals = np.asarray(ev(it.expr), dtype=object)
            if vals.ndim == 0:
                vals = np.full(n, vals[()], dtype=object)
            names.append(it.alias or _expr_name(it.expr, i))
            out_cols.append(vals[idx])

    # ORDER BY over projected or source columns; multi-key sort applies
    # keys last-to-first with a stable argsort. DESC negates factorized
    # codes (reversing a stable sort would also reverse equal-key runs
    # and destroy the ordering of later keys).
    if sel.order_by:
        perm = np.arange(len(out_cols[0]) if out_cols else 0)
        for ob in reversed(sel.order_by):
            col = _order_col(ob, names, out_cols, data, idx)
            try:
                codes = np.unique(col, return_inverse=True)[1]
            except TypeError:
                # None/mixed types: NULLs first, rest by string value
                skey = np.asarray(
                    ["" if v is None else "\x01" + str(v) for v in col])
                codes = np.unique(skey, return_inverse=True)[1]
            asc = ob.asc if hasattr(ob, "asc") else True
            key = codes if asc else -codes
            perm = perm[np.argsort(key[perm], kind="stable")]
        out_cols = [c[perm] for c in out_cols]
    if sel.offset:
        out_cols = [c[sel.offset:] for c in out_cols]
    if sel.limit is not None:
        out_cols = [c[:sel.limit] for c in out_cols]

    dtypes = [_dtype_of(c) for c in out_cols]
    return QueryResult(names, dtypes, out_cols)


def _order_col(ob, names, out_cols, data, idx):
    expr = ob.expr if hasattr(ob, "expr") else ob
    if isinstance(expr, ast.Column):
        if expr.name in names:
            return out_cols[names.index(expr.name)]
        if expr.name in data:
            return data[expr.name][idx]
    raise_err = getattr(expr, "name", str(expr))
    from greptimedb_tpu.query.expr import PlanError
    raise PlanError(f"cannot ORDER BY {raise_err!r} on information_schema")


def _expr_name(expr, i):
    if isinstance(expr, ast.Column):
        return expr.name
    return f"column{i}"


def _dtype_of(col) -> DataType:
    for v in col:
        if isinstance(v, bool):
            return DataType.BOOL
        if isinstance(v, (int, np.integer)):
            return DataType.INT64
        if isinstance(v, (float, np.floating)):
            return DataType.FLOAT64
        break
    return DataType.STRING
