"""KvBackend trait + memory and file-backed implementations.

Mirrors reference src/common/meta/src/kv_backend/ (etcd.rs / memory.rs):
ordered key-value store with range scans and compare-and-put — enough for
catalog keys, sequences, and (later) the metadata plane's table routes and
procedure store. The file impl journals to JSON for standalone durability
(the analog of the reference's embedded raft-engine kv store,
cmd/src/standalone.rs:405-411).
"""

from __future__ import annotations

import json
import os
import threading
from typing import Iterator, Optional


class KvBackend:
    def get(self, key: str) -> Optional[str]:
        raise NotImplementedError

    def put(self, key: str, value: str) -> None:
        raise NotImplementedError

    def delete(self, key: str) -> bool:
        raise NotImplementedError

    def range(self, prefix: str) -> Iterator[tuple[str, str]]:
        """Ordered scan of keys with the given prefix."""
        raise NotImplementedError

    def compare_and_put(self, key: str, expect: Optional[str], value: str) -> bool:
        """Atomic CAS (None expect == key must not exist). The primitive
        DDL procedures build transactions from (reference
        common/meta key txn helpers)."""
        raise NotImplementedError

    def incr(self, key: str, start: int = 0) -> int:
        """Atomic sequence (reference common/meta/src/sequence.rs)."""
        while True:
            cur = self.get(key)
            nxt = (int(cur) if cur is not None else start) + 1
            if self.compare_and_put(key, cur, str(nxt)):
                return nxt


class MemoryKv(KvBackend):
    def __init__(self):
        self._data: dict[str, str] = {}
        self._lock = threading.RLock()

    def get(self, key):
        with self._lock:
            return self._data.get(key)

    def put(self, key, value):
        with self._lock:
            self._data[key] = value

    def delete(self, key):
        with self._lock:
            return self._data.pop(key, None) is not None

    def range(self, prefix):
        with self._lock:
            items = sorted((k, v) for k, v in self._data.items() if k.startswith(prefix))
        yield from items

    def compare_and_put(self, key, expect, value):
        with self._lock:
            cur = self._data.get(key)
            if cur != expect:
                return False
            self._data[key] = value
            return True


class FileKv(MemoryKv):
    """MemoryKv snapshotted to a JSON file on every mutation (atomic
    rename). Good enough for standalone-mode catalog durability."""

    def __init__(self, path: str):
        super().__init__()
        self.path = path
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        if os.path.exists(path):
            with open(path) as f:
                self._data.update(json.load(f))

    def _persist(self):
        tmp = self.path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(self._data, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self.path)

    def put(self, key, value):
        with self._lock:
            super().put(key, value)
            self._persist()

    def delete(self, key):
        with self._lock:
            existed = super().delete(key)
            if existed:
                self._persist()
            return existed

    def compare_and_put(self, key, expect, value):
        with self._lock:
            ok = super().compare_and_put(key, expect, value)
            if ok:
                self._persist()
            return ok
