"""Process entry point (mirrors reference src/cmd: the `greptime` binary's
`standalone start` subcommand and `cli` REPL, cmd/src/bin/greptime.rs:35-55).

    python -m greptimedb_tpu standalone start --data-home /tmp/db \
        --http-addr 127.0.0.1:4000
    python -m greptimedb_tpu repl --data-home /tmp/db
"""

from __future__ import annotations

import argparse
import os
import signal
import sys
import time


def build_standalone(data_home: str, opts=None):
    """Assemble the standalone stack (reference cmd/src/standalone.rs:381-530
    wiring: kv backend -> catalog -> region engine -> query engine)."""
    from greptimedb_tpu import options as optmod
    from greptimedb_tpu.catalog import Catalog, FileKv
    from greptimedb_tpu.query import QueryEngine
    from greptimedb_tpu.storage import RegionEngine
    from greptimedb_tpu.storage.engine import EngineConfig

    os.makedirs(data_home, exist_ok=True)
    tz = "UTC"
    if opts is not None:
        optmod.apply_query_env(opts)
        optmod.apply_observability(opts)
        optmod.apply_concurrency(opts)
        optmod.apply_shm(opts)
        cfg = optmod.engine_config(opts, os.path.join(data_home, "data"))
        tz = opts.default_timezone
    else:
        cfg = EngineConfig(data_dir=os.path.join(data_home, "data"))
    engine = RegionEngine(cfg)
    catalog = Catalog(FileKv(os.path.join(data_home, "catalog.json")))
    qe = QueryEngine(catalog, engine, default_timezone=tz)
    return engine, qe


def _split_addr(addr: str) -> tuple[str, int]:
    host, _, port = addr.rpartition(":")
    return host or "127.0.0.1", int(port)


def _user_provider(opts):
    if not opts.auth.static_users:
        return None
    from greptimedb_tpu.auth import StaticUserProvider
    from greptimedb_tpu.options import ConfigError

    pairs = {}
    for entry in opts.auth.static_users.split(","):
        user, sep, password = entry.partition("=")
        if not sep or not user.strip():
            raise ConfigError(
                f"auth.static_users entry {entry!r} is not user=password")
        if user.strip() in pairs:
            raise ConfigError(
                f"auth.static_users: duplicate user {user.strip()!r} — "
                "note passwords may not contain ','")
        pairs[user.strip()] = password
    return StaticUserProvider(pairs)


def _tls(tls_opts):
    if tls_opts.mode == "disable":
        return None
    if not tls_opts.cert_path or not tls_opts.key_path:
        # never downgrade silently: a config that asks for TLS but can't
        # provide it must abort boot, not serve plaintext
        from greptimedb_tpu.options import ConfigError

        raise ConfigError(
            f"tls.mode = {tls_opts.mode!r} requires cert_path and key_path")
    from greptimedb_tpu.servers.tls import TlsConfig

    return TlsConfig(cert_path=tls_opts.cert_path,
                     key_path=tls_opts.key_path,
                     mode=tls_opts.mode)


def cmd_standalone(args):
    """Boot the full server set per layered options (reference
    frontend/src/server.rs:174-263 Services::build — always HTTP, optional
    Flight/MySQL/Postgres, plus the export-metrics self-scrape)."""
    from greptimedb_tpu.options import load_options
    from greptimedb_tpu.parallel.mesh import init_distributed

    # cross-host mesh: must join the jax.distributed job BEFORE the
    # first backend touch so jax.devices() is the global device list
    # (no-op unless GREPTIMEDB_TPU_COORDINATOR is configured)
    init_distributed()
    overrides: dict = {}
    if args.http_addr:
        overrides.setdefault("http", {})["addr"] = args.http_addr
    opts = load_options(args.config_file, overrides=overrides)
    engine, qe = build_standalone(args.data_home or opts.storage.data_home,
                                  opts)
    user_provider = _user_provider(opts)
    servers = []
    if opts.http.enable:
        from greptimedb_tpu.servers import HttpServer

        host, port = _split_addr(opts.http.addr)
        http_server = HttpServer(qe, host, port, user_provider=user_provider,
                                 timeout_s=opts.http.timeout_s)
        actual = http_server.start()
        servers.append(http_server)
        print(f"greptimedb_tpu standalone listening on http://{host}:{actual}",
              flush=True)
    if opts.grpc.enable:
        from greptimedb_tpu.servers.flight import FlightServer

        ghost, gport = _split_addr(opts.grpc.addr)
        fs = FlightServer(qe, ghost, gport, user_provider=user_provider)
        threading_start(fs)
        servers.append(fs)
        print(f"flight on grpc://{ghost}:{fs.port}", flush=True)
    if opts.mysql.enable:
        from greptimedb_tpu.servers.mysql import MysqlServer

        mhost, mport = _split_addr(opts.mysql.addr)
        ms = MysqlServer(qe, mhost, mport, user_provider=user_provider,
                         tls=_tls(opts.mysql.tls))
        ms.start()
        servers.append(ms)
        print(f"mysql on {mhost}:{ms.port}", flush=True)
    if opts.postgres.enable:
        from greptimedb_tpu.servers.postgres import PostgresServer

        phost, pport = _split_addr(opts.postgres.addr)
        ps = PostgresServer(qe, phost, pport, user_provider=user_provider,
                            tls=_tls(opts.postgres.tls))
        ps.start()
        servers.append(ps)
        print(f"postgres on {phost}:{ps.port}", flush=True)
    task = None
    if opts.metrics.enable:
        from greptimedb_tpu.utils.export_metrics import ExportMetricsTask

        task = ExportMetricsTask(qe, db=opts.metrics.db,
                                 interval_s=opts.metrics.write_interval_s)
        task.start()
    telemetry = None
    if opts.telemetry.enable:
        from greptimedb_tpu.utils.telemetry import TelemetryTask

        home = args.data_home or opts.storage.data_home
        post = None
        if not opts.telemetry.url:
            # no endpoint configured: log the payload locally so the
            # operator can see exactly what WOULD be sent
            def post(_url, body):
                print(f"telemetry: {body.decode()}", flush=True)
        telemetry = TelemetryTask(opts.telemetry.url, "standalone", home,
                                  interval_s=opts.telemetry.interval_s,
                                  post=post)
        telemetry.start()
    try:
        _wait_stop()
    finally:
        if task is not None:
            task.stop()
        if telemetry is not None:
            telemetry.stop()
        for s in servers:
            try:
                s.stop()
            except AttributeError:
                s.shutdown()
        # reclaim encode workers deterministically (spawn-mode worker
        # PROCESSES especially must not outlive a clean shutdown)
        qe.concurrency.shutdown()
        engine.close()


def threading_start(flight_server):
    import threading

    t = threading.Thread(target=flight_server.serve, daemon=True)
    t.start()


def cmd_dump_config(args):
    from greptimedb_tpu.options import example_toml

    sys.stdout.write(example_toml())


def _wait_stop():
    stop = []
    signal.signal(signal.SIGTERM, lambda *a: stop.append(1))
    signal.signal(signal.SIGINT, lambda *a: stop.append(1))
    while not stop:
        time.sleep(0.2)


def _write_port_file(path: str, value) -> None:
    """Atomic port-file publish: readers never see a partial file."""
    if not path:
        return
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        f.write(str(value))
    os.replace(tmp, path)


def cmd_metasrv(args):
    """Metadata-plane service process (reference cmd/src/metasrv.rs):
    FileKv-durable Metasrv + the networked KV/heartbeat HTTP service +
    a real-clock tick loop driving failure detection and failover."""
    from greptimedb_tpu.catalog.kv import FileKv
    from greptimedb_tpu.meta.kv_service import (MetaHttpService,
                                                MetasrvTicker, NotifyingKv)
    from greptimedb_tpu.meta.metasrv import Metasrv, MetasrvOptions

    os.makedirs(args.data_home, exist_ok=True)
    kv = NotifyingKv(FileKv(os.path.join(args.data_home, "meta_kv.json")))
    opts = MetasrvOptions(
        region_lease_s=args.region_lease,
        heartbeat_interval_s=args.heartbeat_interval,
        failure_threshold=args.failure_threshold)
    metasrv = Metasrv(kv, opts)
    host, port = _split_addr(args.bind_addr)
    service = MetaHttpService(metasrv, host, port)
    service.start()
    ticker = MetasrvTicker(metasrv, interval_s=min(
        1.0, opts.heartbeat_interval_s))
    ticker.start()
    print(f"greptimedb_tpu metasrv listening on http://{service.addr}",
          flush=True)
    _write_port_file(args.port_file, str(service.port))
    try:
        _wait_stop()
    finally:
        ticker.stop()
        service.stop()


def cmd_datanode(args):
    """Region-server service process with its OWN heartbeat task +
    region alive-keeper (reference cmd/src/datanode.rs +
    datanode/src/heartbeat.rs:47-183, alive_keeper.rs:49-112)."""
    # a datanode never touches the accelerator tunnel: scans execute on
    # the frontend's device; pin CPU before any backend init
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax

    jax.config.update("jax_platforms", "cpu")
    from greptimedb_tpu.cluster.datanode_service import DatanodeService
    from greptimedb_tpu.storage.engine import EngineConfig, RegionEngine

    engine = RegionEngine(EngineConfig(
        data_dir=args.data_home, wal_backend="remote", write_workers=2))
    host, port = _split_addr(args.rpc_addr)
    svc = DatanodeService(args.node_id, engine, args.metasrv,
                          rpc_host=host, rpc_port=port,
                          heartbeat_interval_s=args.heartbeat_interval)
    svc.start()
    print(f"greptimedb_tpu datanode {args.node_id} serving regions on "
          f"grpc://{svc.addr} (metasrv {args.metasrv})", flush=True)
    _write_port_file(args.port_file, str(svc.server.port))
    try:
        _wait_stop()
    finally:
        svc.stop()


def cmd_flownode(args):
    """Continuous-aggregation service process (reference
    cmd/src/flownode.rs + flow/src/adapter.rs:507-527 run_available
    loop): builds a frontend-style engine over the remote metadata
    plane and ticks every flow on an interval. Flows created through
    any frontend are visible here via the shared KV."""
    import threading

    from greptimedb_tpu.cluster.frontend import build_frontend

    qe, nodes = build_frontend(args.metasrv)
    flow = qe.flow_engine
    stop = threading.Event()

    def loop():
        while not stop.wait(args.tick_interval):
            try:
                for db_row in qe.execute_one("SHOW DATABASES").rows():
                    out = flow.run_available(db=db_row[0])
                    if out:
                        print(f"flownode: ticked {out}", flush=True)
            except Exception:  # noqa: BLE001 — loop must never die
                import traceback

                traceback.print_exc()

    t = threading.Thread(target=loop, daemon=True)
    t.start()
    print(f"greptimedb_tpu flownode ticking every {args.tick_interval}s "
          f"(metasrv {args.metasrv})", flush=True)
    _write_port_file(args.port_file, "0")
    try:
        _wait_stop()
    finally:
        stop.set()
        nodes.close()


def cmd_frontend(args):
    """Stateless query-serving process over remote metadata + remote
    regions (reference cmd/src/frontend.rs)."""
    from greptimedb_tpu.cluster.frontend import build_frontend
    from greptimedb_tpu.servers import HttpServer

    qe, nodes = build_frontend(args.metasrv)
    host, port = _split_addr(args.http_addr)
    http_server = HttpServer(qe, host, port)
    actual = http_server.start()
    print(f"greptimedb_tpu frontend listening on http://{host}:{actual} "
          f"(metasrv {args.metasrv})", flush=True)
    _write_port_file(args.port_file, str(actual))
    try:
        _wait_stop()
    finally:
        http_server.stop()
        nodes.close()


def _qi(name: str) -> str:
    """Quote an identifier for SQL (reserved words, dashes, ...)."""
    return '"' + name.replace('"', '""') + '"'


def _qs(text: str) -> str:
    """Quote a string/path literal."""
    return "'" + text.replace("'", "''") + "'"


def cmd_export(args):
    """Backup: schemas (SHOW CREATE TABLE) + data (COPY DATABASE TO
    parquet), one subdirectory per database (reference cli export,
    cmd/src/cli/export.rs:44-119)."""
    from greptimedb_tpu.query.engine import QueryContext

    engine, qe = build_standalone(args.data_home)
    try:
        os.makedirs(args.output_dir, exist_ok=True)
        dbs = [args.db] if args.db else [
            r[0] for r in qe.execute_one("SHOW DATABASES").rows()
            if r[0] != "information_schema"
        ]
        for db in dbs:
            ctx = QueryContext(db=db)
            out = os.path.join(args.output_dir, db)
            os.makedirs(out, exist_ok=True)
            tables = qe.catalog.list_tables(db)
            ddl = []
            for t in sorted(tables):
                r = qe.execute_one(f"SHOW CREATE TABLE {_qi(t)}", ctx)
                ddl.append(r.rows()[0][1] + ";\n")
            with open(os.path.join(out, "create_tables.sql"), "w") as f:
                f.write("\n".join(ddl))
            n = qe.execute_one(
                f"COPY DATABASE {_qi(db)} TO {_qs(out)} WITH (format = 'parquet')",
                ctx).affected_rows
            print(f"exported {db}: {len(tables)} tables, {n} rows -> {out}")
    finally:
        engine.close()


def cmd_import(args):
    """Restore a cli-export dump: run the DDL file, then COPY DATABASE
    FROM the parquet directory."""
    from greptimedb_tpu.query.engine import QueryContext

    engine, qe = build_standalone(args.data_home)
    try:
        for db in sorted(os.listdir(args.input_dir)):
            src = os.path.join(args.input_dir, db)
            if not os.path.isdir(src):
                continue
            qe.execute_one(f"CREATE DATABASE IF NOT EXISTS {_qi(db)}")
            ctx = QueryContext(db=db)
            ddl_path = os.path.join(src, "create_tables.sql")
            if os.path.exists(ddl_path):
                with open(ddl_path) as f:
                    sql = f.read()
                if sql.strip():
                    qe.execute_sql(sql, ctx)
            n = qe.execute_one(
                f"COPY DATABASE {_qi(db)} FROM {_qs(src)} WITH (format = 'parquet')",
                ctx).affected_rows
            print(f"imported {db}: {n} rows")
    finally:
        engine.close()


def cmd_repl(args):
    engine, qe = build_standalone(args.data_home)
    print("greptimedb_tpu REPL — SQL or TQL, \\q to quit")
    try:
        while True:
            try:
                line = input("sql> ")
            except EOFError:
                break
            if line.strip() in ("\\q", "exit", "quit"):
                break
            if not line.strip():
                continue
            try:
                r = qe.execute_one(line)
                if r.is_query:
                    print("\t".join(r.names))
                    for row in r.rows()[:100]:
                        print("\t".join(str(v) for v in row))
                    if r.num_rows > 100:
                        print(f"... ({r.num_rows} rows)")
                else:
                    print(f"OK, {r.affected_rows} rows affected")
            except Exception as e:  # noqa: BLE001 — REPL boundary
                print(f"error: {e}")
    finally:
        engine.close()


def main(argv=None):
    parser = argparse.ArgumentParser(prog="greptimedb_tpu")
    sub = parser.add_subparsers(dest="cmd", required=True)

    p_sa = sub.add_parser("standalone", help="run the standalone server")
    sa_sub = p_sa.add_subparsers(dest="subcmd", required=True)
    p_start = sa_sub.add_parser("start")
    p_start.add_argument("--data-home", default="")
    p_start.add_argument("--http-addr", default="")
    p_start.add_argument("-c", "--config-file", default=None,
                         help="layered TOML config (defaults < file < "
                              "GREPTIMEDB_TPU__* env < flags)")
    p_start.set_defaults(fn=cmd_standalone)

    p_ms = sub.add_parser("metasrv", help="run the metadata-plane service")
    ms_sub = p_ms.add_subparsers(dest="subcmd", required=True)
    p_ms_start = ms_sub.add_parser("start")
    p_ms_start.add_argument("--data-home", required=True)
    p_ms_start.add_argument("--bind-addr", default="127.0.0.1:4002")
    p_ms_start.add_argument("--region-lease", type=float, default=9.0)
    p_ms_start.add_argument("--heartbeat-interval", type=float, default=3.0)
    p_ms_start.add_argument("--failure-threshold", type=float, default=8.0)
    p_ms_start.add_argument("--port-file", default="")
    p_ms_start.set_defaults(fn=cmd_metasrv)

    p_dn = sub.add_parser("datanode", help="run a region-server datanode")
    dn_sub = p_dn.add_subparsers(dest="subcmd", required=True)
    p_dn_start = dn_sub.add_parser("start")
    p_dn_start.add_argument("--node-id", required=True)
    p_dn_start.add_argument("--metasrv", required=True,
                            help="metasrv HTTP addr, host:port")
    p_dn_start.add_argument("--data-home", required=True,
                            help="SHARED storage path (object-store "
                                 "deployment shape; WAL is remote)")
    p_dn_start.add_argument("--rpc-addr", default="127.0.0.1:0")
    p_dn_start.add_argument("--heartbeat-interval", type=float, default=3.0)
    p_dn_start.add_argument("--port-file", default="",
                            help="write the bound Flight port here")
    p_dn_start.set_defaults(fn=cmd_datanode)

    p_fe = sub.add_parser("frontend", help="run a query-serving frontend")
    fe_sub = p_fe.add_subparsers(dest="subcmd", required=True)
    p_fe_start = fe_sub.add_parser("start")
    p_fe_start.add_argument("--metasrv", required=True)
    p_fe_start.add_argument("--http-addr", default="127.0.0.1:4000")
    p_fe_start.add_argument("--port-file", default="")
    p_fe_start.set_defaults(fn=cmd_frontend)

    p_fn = sub.add_parser("flownode",
                          help="run a continuous-aggregation flownode")
    fn_sub = p_fn.add_subparsers(dest="subcmd", required=True)
    p_fn_start = fn_sub.add_parser("start")
    p_fn_start.add_argument("--metasrv", required=True)
    p_fn_start.add_argument("--tick-interval", type=float, default=1.0)
    p_fn_start.add_argument("--port-file", default="")
    p_fn_start.set_defaults(fn=cmd_flownode)

    p_repl = sub.add_parser("repl", help="interactive SQL/TQL shell")
    p_repl.add_argument("--data-home", default="./greptimedb_tpu_data")
    p_repl.set_defaults(fn=cmd_repl)

    p_dump = sub.add_parser("dump-config",
                            help="print the documented example TOML config")
    p_dump.set_defaults(fn=cmd_dump_config)

    p_exp = sub.add_parser("export", help="dump schemas + parquet data")
    p_exp.add_argument("--data-home", default="./greptimedb_tpu_data")
    p_exp.add_argument("--output-dir", required=True)
    p_exp.add_argument("--db", default=None,
                       help="one database (default: all)")
    p_exp.set_defaults(fn=cmd_export)

    p_imp = sub.add_parser("import", help="restore a cli-export dump")
    p_imp.add_argument("--data-home", default="./greptimedb_tpu_data")
    p_imp.add_argument("--input-dir", required=True)
    p_imp.set_defaults(fn=cmd_import)

    args = parser.parse_args(argv)
    # every service role stamps trace_id= on its log records so logs,
    # metrics, and spans join on one id
    from greptimedb_tpu.utils.tracing import install_trace_logging

    install_trace_logging()
    args.fn(args)


if __name__ == "__main__":
    main()
