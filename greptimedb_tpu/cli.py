"""Process entry point (mirrors reference src/cmd: the `greptime` binary's
`standalone start` subcommand and `cli` REPL, cmd/src/bin/greptime.rs:35-55).

    python -m greptimedb_tpu standalone start --data-home /tmp/db \
        --http-addr 127.0.0.1:4000
    python -m greptimedb_tpu repl --data-home /tmp/db
"""

from __future__ import annotations

import argparse
import os
import signal
import sys
import time


def build_standalone(data_home: str):
    """Assemble the standalone stack (reference cmd/src/standalone.rs:381-530
    wiring: kv backend -> catalog -> region engine -> query engine)."""
    from greptimedb_tpu.catalog import Catalog, FileKv
    from greptimedb_tpu.query import QueryEngine
    from greptimedb_tpu.storage import RegionEngine
    from greptimedb_tpu.storage.engine import EngineConfig

    os.makedirs(data_home, exist_ok=True)
    engine = RegionEngine(EngineConfig(data_dir=os.path.join(data_home, "data")))
    catalog = Catalog(FileKv(os.path.join(data_home, "catalog.json")))
    qe = QueryEngine(catalog, engine)
    return engine, qe


def cmd_standalone(args):
    from greptimedb_tpu.servers import HttpServer

    engine, qe = build_standalone(args.data_home)
    host, _, port = args.http_addr.rpartition(":")
    server = HttpServer(qe, host or "127.0.0.1", int(port))
    actual = server.start()
    print(f"greptimedb_tpu standalone listening on http://{host or '127.0.0.1'}:{actual}",
          flush=True)
    stop = []
    signal.signal(signal.SIGTERM, lambda *a: stop.append(1))
    signal.signal(signal.SIGINT, lambda *a: stop.append(1))
    try:
        while not stop:
            time.sleep(0.2)
    finally:
        server.stop()
        engine.close()


def cmd_repl(args):
    engine, qe = build_standalone(args.data_home)
    print("greptimedb_tpu REPL — SQL or TQL, \\q to quit")
    try:
        while True:
            try:
                line = input("sql> ")
            except EOFError:
                break
            if line.strip() in ("\\q", "exit", "quit"):
                break
            if not line.strip():
                continue
            try:
                r = qe.execute_one(line)
                if r.is_query:
                    print("\t".join(r.names))
                    for row in r.rows()[:100]:
                        print("\t".join(str(v) for v in row))
                    if r.num_rows > 100:
                        print(f"... ({r.num_rows} rows)")
                else:
                    print(f"OK, {r.affected_rows} rows affected")
            except Exception as e:  # noqa: BLE001 — REPL boundary
                print(f"error: {e}")
    finally:
        engine.close()


def main(argv=None):
    parser = argparse.ArgumentParser(prog="greptimedb_tpu")
    sub = parser.add_subparsers(dest="cmd", required=True)

    p_sa = sub.add_parser("standalone", help="run the standalone server")
    sa_sub = p_sa.add_subparsers(dest="subcmd", required=True)
    p_start = sa_sub.add_parser("start")
    p_start.add_argument("--data-home", default="./greptimedb_tpu_data")
    p_start.add_argument("--http-addr", default="127.0.0.1:4000")
    p_start.set_defaults(fn=cmd_standalone)

    p_repl = sub.add_parser("repl", help="interactive SQL/TQL shell")
    p_repl.add_argument("--data-home", default="./greptimedb_tpu_data")
    p_repl.set_defaults(fn=cmd_repl)

    args = parser.parse_args(argv)
    args.fn(args)


if __name__ == "__main__":
    main()
