from .cluster import Cluster, Datanode, RegionRouter

__all__ = ["Cluster", "Datanode", "RegionRouter"]
