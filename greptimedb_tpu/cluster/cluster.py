"""In-process multi-datanode cluster: metasrv + N datanodes + frontend.

Mirrors reference tests-integration/src/cluster.rs:66-135 (a real cluster in
one process over in-memory wiring) and the distributed deployment shape
(SURVEY.md §3.1): frontends route region requests via table-route metadata;
datanodes heartbeat RegionStats to the metasrv and obey its Instructions;
region data + WAL live on a shared store (the object-storage/remote-WAL
deployment, which is what makes failover possible).

The frontend side is `RegionRouter`: it satisfies the RegionEngine surface
the QueryEngine expects (scan/put/delete/create/open/region) but routes each
region to its owning datanode per the route table, with an invalidation-
driven cache (reference src/cache + frontend route re-fetch).
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque
from typing import Optional

from ..catalog.catalog import Catalog, TableInfo
from ..catalog.kv import KvBackend, MemoryKv
from ..datatypes.schema import Schema
from ..fault import FAULTS, FaultError, Unavailable, is_transient
from ..meta.heartbeat import HeartbeatTask
from ..meta.instruction import Instruction, InstructionKind
from ..meta.metasrv import Metasrv, MetasrvOptions, RegionStat
from ..meta.route import RegionRoute, TableRoute
from ..partition.rule import RangePartitionRule
from ..query.engine import QueryContext, QueryEngine
from ..storage.engine import EngineConfig, RegionEngine, RegionRequest, RequestType
from ..utils.metrics import DEGRADED


#: Flight error class names the router may fix by re-resolving the
#: route. FlightServerError is included deliberately: a stale route over
#: the wire surfaces as the REMOTE engine's KeyError wrapped in it.
#: Auth errors and Arrow data errors (ArrowInvalid etc.) are excluded —
#: re-routing cannot fix them and must not mask them as Unavailable.
_RECOVERABLE_FLIGHT = frozenset({
    "FlightUnavailableError", "FlightTimedOutError",
    "FlightInternalError", "FlightServerError",
})


def _recoverable(e: BaseException, region_id: int) -> bool:
    """Errors the router may fix by re-resolving the route: stale routes
    (KeyError naming this region, from an engine/router that no longer
    owns it — a KeyError about anything else is a programming error and
    must surface), injected or self-described transient failures, and
    Flight transport errors after the client's own retries are
    exhausted."""
    if isinstance(e, KeyError) or (isinstance(e, Unavailable)
                                   and e.cause is None):
        # every ownership-contract error (engine "region N not open",
        # router "no route for region N", the typed "region N has no
        # live datanode" Unavailable) names the region with this exact
        # phrase; a KeyError about anything else (a column, a dict key)
        # does not, and a cause-carrying Unavailable is already the
        # terminal verdict of a refresh-and-retry loop
        return f"region {region_id}" in str(e)
    if isinstance(e, FaultError) or is_transient(e):
        return True
    return type(e).__module__.startswith("pyarrow") \
        and type(e).__name__ in _RECOVERABLE_FLIGHT


class Datanode:
    """One region server + its heartbeat task (datanode/src/datanode.rs:192
    + heartbeat.rs analog)."""

    def __init__(self, node_id: str, shared_dir: str, metasrv: Metasrv,
                 wire: bool = False):
        self.node_id = node_id
        # datanodes run the worker model like the reference's region
        # servers (worker.rs WorkerGroup); a small fixed pool — requests
        # arrive pre-batched from the frontend, workers add group commit
        self.engine = RegionEngine(EngineConfig(data_dir=shared_dir,
                                                write_workers=2))
        self.metasrv = metasrv
        self.heartbeat = HeartbeatTask(
            node_id, metasrv, self._region_stats, self._apply_instruction
        )
        self.alive = True
        # wire transport: serve this node's regions over Flight and give
        # the frontend a network client instead of the in-process engine
        # (reference: region requests always cross gRPC,
        # datanode/src/region_server.rs)
        self.server = None
        self.remote = None
        if wire:
            from ..servers.flight import FlightServer, RemoteRegionEngine

            self.server = FlightServer(None, port=0,
                                       region_engine=self.engine,
                                       node_id=node_id)
            self.remote = RemoteRegionEngine(f"127.0.0.1:{self.server.port}",
                                             peer=node_id)

    def data_engine(self):
        """What the frontend router talks to: the Flight client in wire
        mode, the in-process engine otherwise."""
        return self.remote if self.remote is not None else self.engine

    def _region_stats(self) -> list[RegionStat]:
        stats = []
        for rid, region in self.engine.regions.items():
            stats.append(
                RegionStat(
                    region_id=rid,
                    table=str(rid >> 32),
                    rows=region.memtable.num_rows if hasattr(region, "memtable") else 0,
                    memtable_bytes=region.memtable_bytes,
                )
            )
        return stats

    def _apply_instruction(self, inst: Instruction) -> None:
        if inst.kind is InstructionKind.OPEN_REGION:
            self.engine.open_region(inst.region_id)
        elif inst.kind is InstructionKind.CLOSE_REGION:
            self.engine.handle_request(
                RegionRequest(RequestType.CLOSE, inst.region_id)
            )
        elif inst.kind is InstructionKind.DOWNGRADE_REGION:
            pass  # writes are fenced by the router's route state
        elif inst.kind is InstructionKind.UPGRADE_REGION:
            self.engine.open_region(inst.region_id)

    def beat(self, now_ms: Optional[float] = None) -> None:
        if not self.alive:
            return
        try:
            FAULTS.fire("datanode.crash", node=self.node_id)
        except FaultError:
            self.kill()  # the chaos schedule chose this beat to die on
            return
        self.heartbeat.beat(now_ms)

    def enforce_leases(self, now_ms: Optional[float] = None) -> list[int]:
        """RegionAliveKeeper: self-close regions whose lease expired
        (alive_keeper.rs:49-112)."""
        expired = self.heartbeat.alive_keeper.expired(now_ms)
        for rid in expired:
            self.engine.handle_request(RegionRequest(RequestType.CLOSE, rid))
            self.heartbeat.alive_keeper.forget(rid)
        return expired

    def kill(self) -> None:
        """Simulate process death: stop heartbeating, drop open regions,
        stop serving the wire."""
        self.alive = False
        if self.engine.workers is not None:
            # a dead process has no writer threads; without this each
            # simulated death leaks the worker pool (and a dequeued write
            # could still land in the shared WAL)
            self.engine.workers.stop()
            self.engine.workers = None
        for rid in list(self.engine.regions):
            self.engine.regions.pop(rid, None)
        if self.server is not None:
            self.server.shutdown()
            self.server = None

    def close(self) -> None:
        if self.remote is not None:
            self.remote.close()
        if self.server is not None:
            self.server.shutdown()
        self.engine.close()


class _HedgePlane:
    """Adaptive request hedging for remote fragment reads (the
    tail-tolerance half of `[cluster]`): when a peer's response is
    slower than its own recent p99 (floored at `hedge_delay_ms`), race
    a second attempt and take the first response — a per-request
    straggler (GC pause, queue-head blocking, an injected stall) loses
    to the hedge instead of setting the query's tail. A token bucket
    caps hedges at `hedge_budget_pct` of eligible requests so a slow
    CLUSTER degrades to plain waiting instead of doubling its own load.
    Knobs ride the env (options.apply_query_env writes them) so child
    datanode processes and tests see one source of truth."""

    #: burst cap: at most this many banked hedges (bucket depth)
    _CAP = 10.0

    def __init__(self):
        self._lock = threading.Lock()
        self._lat: dict[str, deque] = {}
        self._credit = 1.0  # one immediate hedge; then pct-per-request

    @staticmethod
    def enabled() -> bool:
        return os.environ.get("GTPU_HEDGE", "") != "off"

    @staticmethod
    def floor_s() -> float:
        try:
            return float(os.environ.get("GTPU_HEDGE_DELAY_MS", "")
                         or 30.0) / 1000.0
        except ValueError:
            return 0.03

    @staticmethod
    def budget_pct() -> float:
        try:
            return float(os.environ.get("GTPU_HEDGE_BUDGET_PCT", "")
                         or 5.0)
        except ValueError:
            return 5.0

    def delay_s(self, peer: str) -> float:
        """When to fire the hedge: the peer's recent p99, floored — a
        cold ring (under 8 samples) has no p99 worth trusting."""
        floor = self.floor_s()
        with self._lock:
            ring = self._lat.get(peer)
            if not ring or len(ring) < 8:
                return floor
            srt = sorted(ring)
            p99 = srt[min(len(srt) - 1, int(len(srt) * 0.99))]
        return max(floor, p99)

    def record(self, peer: str, elapsed_s: float) -> None:
        with self._lock:
            self._lat.setdefault(peer, deque(maxlen=128)).append(elapsed_s)

    def accrue(self) -> None:
        """One eligible request = pct/100 of a hedge earned."""
        with self._lock:
            self._credit = min(self._CAP,
                               self._credit + self.budget_pct() / 100.0)

    def try_fire(self) -> bool:
        with self._lock:
            if self._credit >= 1.0:
                self._credit -= 1.0
                return True
            return False


class RegionRouter:
    """Frontend-side region request routing over table routes."""

    def __init__(self, metasrv: Metasrv, datanodes: dict[str, Datanode]):
        self.metasrv = metasrv
        self.datanodes = datanodes
        self._region_node: dict[int, str] = {}
        self._agg_executors: dict[int, object] = {}  # per-engine pushdown
        # rollup_probe TTL cache: dashboards re-asking the same window
        # within the coverage-state TTL skip the per-region RPC fan-out
        self._rollup_probe_cache: dict[tuple, tuple] = {}
        self._hedge = _HedgePlane()
        self._lock = threading.Lock()
        metasrv.subscribe_invalidation(self._on_invalidate)

    def _on_invalidate(self, table: str) -> None:
        with self._lock:
            self._region_node.clear()
            # pushdown executors pin their engines (and device caches):
            # drop them with the routes so failed-over engines can free
            self._agg_executors.clear()
            self._rollup_probe_cache.clear()

    def _refresh(self) -> None:
        with self._lock:
            self._region_node.clear()
            for route in self.metasrv.routes.all():
                for rr in route.regions:
                    if rr.leader_node is not None:
                        self._region_node[rr.region_id] = rr.leader_node

    @staticmethod
    def _route_rid(region_id: int) -> int:
        """Routing identity for a region id: rollup COMPANION regions
        (raw_rid + ROLLUP_RID_FLAG + slot<<20, maintenance/rollup.py)
        are created by the owning datanode's maintenance plane and never
        get their own route entry — they live wherever their raw region
        lives, so route lookups strip the companion bits."""
        from greptimedb_tpu.maintenance.rollup import ROLLUP_RID_FLAG

        if region_id & ROLLUP_RID_FLAG:
            return (region_id >> 32 << 32) | (region_id & ((1 << 20) - 1))
        return region_id

    def _engine_for(self, region_id: int) -> RegionEngine:
        region_id = self._route_rid(region_id)
        node = self._region_node.get(region_id)
        if node is None:
            self._refresh()
            node = self._region_node.get(region_id)
        if node is None:
            raise KeyError(f"no route for region {region_id}")
        dn = self.datanodes[node]
        if not dn.alive:
            # stale route to a dead node; force a re-fetch
            self._refresh()
            node = self._region_node.get(region_id)
            dn = self.datanodes[node] if node else None
            if dn is None or not dn.alive:
                # transient by contract: the leader died and failover
                # has not landed yet — typed so clients retry, never a
                # bare KeyError escaping the routing table
                raise Unavailable(
                    f"region {region_id} has no live datanode "
                    f"(failover pending)")
        return dn.data_engine()

    # --- RegionEngine surface used by QueryEngine ---
    def region(self, region_id: int):
        return self._engine_for(region_id).region(region_id)

    def open_region(self, region_id: int) -> None:
        self._engine_for(region_id).open_region(region_id)

    def select_node(self) -> str:
        """Datanode placement via the metasrv selector (selector/ role)."""
        node = self.metasrv.selector.select(
            self.metasrv.alive_nodes() or sorted(self.datanodes),
            self.metasrv.node_stats(),
        )
        return node if node is not None else sorted(self.datanodes)[0]

    def create_region(self, region_id: int, schema: Schema) -> None:
        """Placement: pick a datanode via the metasrv selector, create the
        region there, and record the route (the CreateTable DDL procedure's
        region-allocation step, common/meta/src/ddl/create_table.rs analog).

        NOT idempotent across calls: the stateful selector may pick a
        different node each time. Journaled DDL must pin the node first
        (select_node) and call create_region_on — re-running THAT is a
        datanode-level no-op."""
        self.create_region_on(self.select_node(), region_id, schema)

    def create_region_on(self, node: str, region_id: int,
                         schema: Schema) -> None:
        self.datanodes[node].data_engine().create_region(region_id, schema)
        table_key = str(region_id >> 32)
        route = self.metasrv.routes.get(table_key)
        if route is None:
            route = TableRoute(table=table_key, regions=[])
            self.metasrv.routes.put_new(route)
            route = self.metasrv.routes.get(table_key)
        route.regions = [r for r in route.regions if r.region_id != region_id]
        route.regions.append(RegionRoute(region_id=region_id, leader_node=node))
        self.metasrv.routes.update(route)
        with self._lock:
            self._region_node[region_id] = node

    def put(self, region_id: int, batch) -> int:
        return self._engine_for(region_id).put(region_id, batch)

    def delete(self, region_id: int, batch) -> int:
        return self._engine_for(region_id).delete(region_id, batch)

    def flush(self, region_id: int) -> None:
        self._engine_for(region_id).flush(region_id)

    def compact(self, region_id: int) -> None:
        self._engine_for(region_id).compact(region_id)

    def _with_failover(self, region_id: int, op):
        """Graceful degradation for the read path: when the engine's own
        retries are exhausted (or the route is stale), re-resolve the
        route — picking up any failover that moved the region — and try
        once on the new owner; only then surface a typed `Unavailable`
        instead of a transport stack trace."""
        try:
            return op(self._engine_for(region_id))
        except Exception as e:  # noqa: BLE001 — predicate filters below
            if not _recoverable(e, region_id):
                raise
            DEGRADED.inc(point="router.scan")
            with self._lock:
                self._region_node.pop(self._route_rid(region_id), None)
            self._refresh()
            try:
                return op(self._engine_for(region_id))
            except Exception as e2:  # noqa: BLE001
                if not _recoverable(e2, region_id):
                    raise
                raise Unavailable(
                    f"region {region_id} unavailable after retries "
                    "and route refresh", e2) from e2

    def scan(self, region_id: int, ts_range=None, projection=None,
             tag_predicates=None, seq_min=None):
        def op(eng):
            call = lambda e: e.scan(region_id, ts_range, projection,  # noqa: E731
                                    tag_predicates, seq_min=seq_min)
            if hasattr(eng, "execute_fragment") and _HedgePlane.enabled():
                # wire-mode region read: the same hedge plane as
                # fragment pushdown — a straggling scan races a backup
                return self._hedged_call(region_id, eng, call)
            return call(eng)
        return self._with_failover(region_id, op)

    def scan_stream(self, region_id: int, ts_range=None, projection=None,
                    tag_predicates=None):
        # degradation covers stream CONSTRUCTION only: chunks read
        # lazily after return cannot be replayed on a refreshed route
        # without duplicating data (they lean on the objectstore seam's
        # own retries instead)
        return self._with_failover(
            region_id,
            lambda eng: eng.scan_stream(region_id, ts_range, projection,
                                        tag_predicates))

    def _local_executor_for(self, eng):
        """Per-engine pushdown executor cache (holds device caches; the
        invalidation hook drops them with the routes)."""
        from greptimedb_tpu.query.physical import PhysicalExecutor

        with self._lock:
            ex = self._agg_executors.get(id(eng))
            if ex is None:
                ex = PhysicalExecutor(eng)
                self._agg_executors[id(eng)] = ex
        return ex

    def execute_fragment(self, region_id: int, frag):
        """Plan-fragment pushdown: run the region-side stage pipeline ON
        the node that owns the region (over Flight in wire mode), so
        only the terminal stage's output — partial planes, top-k
        candidates, or filtered rows — returns to the frontend
        (reference dist_plan Partial/Final split, analyzer.rs:35).
        Wire-mode reads hedge (see _HedgePlane): an attempt slower than
        the peer's adaptive delay races a second one, first response
        wins, the loser's token is cancelled."""
        def op(eng):
            if hasattr(eng, "execute_fragment"):  # RemoteRegionEngine: wire
                call = lambda e: e.execute_fragment(region_id, frag)  # noqa: E731
                if _HedgePlane.enabled():
                    return self._hedged_call(region_id, eng, call)
                return call(eng)
            # in-process datanode: same computation, no serialization
            from greptimedb_tpu.query.dist_agg import execute_region_fragment

            return execute_region_fragment(self._local_executor_for(eng),
                                           region_id, frag)
        return self._with_failover(region_id, op)

    def _hedged_call(self, region_id: int, eng, call):
        """First-response-wins hedged dispatch of `call(eng)`.

        Both attempts run under CHILD tokens carrying the outer
        statement's remaining budget — never the outer token itself, so
        cancelling the loser cannot cancel the query. The winner's
        latency feeds the peer's p99 ring; the loser's cancel unwinds
        its retry loop locally and its server-side work via the
        budget the ticket carried. The waiter itself stays on the
        OUTER token: a KILL or deadline during the race unwinds typed
        here and the finally cancels both attempts."""
        from greptimedb_tpu.utils import deadline as dl
        from greptimedb_tpu.utils import tracing
        from greptimedb_tpu.utils.metrics import HEDGE_EVENTS

        peer = self._region_node.get(self._route_rid(region_id)) or "?"
        self._hedge.accrue()
        outer = dl.current()
        budget = dl.budget_ms()
        lock = threading.Lock()
        done = threading.Event()
        winner: list = [None]  # (tag, ok, value, elapsed_s)
        tokens: dict[str, dl.CancelToken] = {}
        # capture the caller's trace context HERE: attempts run on their
        # own threads, and the remote_region_* spans they open must stay
        # attached to the statement's span tree
        run = tracing.propagate(lambda: call(eng))

        def attempt(tag):
            tok = tokens[tag]
            t0 = time.monotonic()
            with dl.activate(tok):
                try:
                    ok, val = True, run()
                except BaseException as e:  # noqa: BLE001 — relayed to waiter
                    ok, val = False, e
            with lock:
                if winner[0] is None:
                    winner[0] = (tag, ok, val, time.monotonic() - t0)
                    done.set()

        def spawn(tag):
            tokens[tag] = dl.CancelToken(timeout_ms=budget)
            threading.Thread(target=attempt, args=(tag,),
                             name=f"gtpu-hedge-{tag}", daemon=True).start()

        try:
            spawn("primary")
            delay = self._hedge.delay_s(peer)
            if outer is not None:
                # a hedge fired after the deadline helps nobody
                delay = outer.clip(delay)
            if not done.wait(delay):
                if self._hedge.try_fire():
                    HEDGE_EVENTS.inc(event="fired")
                    spawn("hedge")
                else:
                    HEDGE_EVENTS.inc(event="budget_denied")
            while not dl.wait_event(done, 30.0, where="hedged fragment"):
                pass
        finally:
            with lock:
                won = winner[0][0] if winner[0] is not None else None
            for tag, tok in tokens.items():
                if tag != won:
                    tok.cancel("hedge loser", kind="cancelled",
                               count=False)
        tag, ok, val, elapsed = winner[0]
        self._hedge.record(peer, elapsed)
        if "hedge" in tokens:
            HEDGE_EVENTS.inc(event="won" if tag == "hedge" else "lost")
        if not ok:
            raise val
        return val

    #: rollup_probe answers stay valid for about as long as the
    #: datanode-side coverage-state cache (maintenance/rollup.py)
    _ROLLUP_PROBE_TTL_S = 2.0

    def rollup_probe(self, region_id: int, lo: int, hi: int) -> list:
        """Ask the region's owner which rollup rules fully cover
        [lo, hi) on it (maintenance/rollup.probe_region_rollups) — the
        eligibility half of cluster-mode rollup substitution. Only
        NEGATIVE answers are cached (tables with no usable rollup would
        otherwise fan an RPC per query forever): a positive answer must
        stay live, because the datanode's late-data check is what keeps
        substituted aggregates exact after an out-of-order write."""
        import time as _time

        key = (region_id, int(lo), int(hi))
        now = _time.monotonic()
        with self._lock:
            hit = self._rollup_probe_cache.get(key)
            if hit is not None and hit[0] > now:
                return hit[1]
            if len(self._rollup_probe_cache) > 4096:
                self._rollup_probe_cache.clear()

        def op(eng):
            if hasattr(eng, "rollup_probe"):  # RemoteRegionEngine: wire
                return eng.rollup_probe(region_id, lo, hi)
            from greptimedb_tpu.maintenance.rollup import (
                probe_region_rollups,
            )

            return probe_region_rollups(eng, region_id, int(lo), int(hi))

        out = self._with_failover(region_id, op)
        if not out:
            with self._lock:
                self._rollup_probe_cache[key] = (
                    now + self._ROLLUP_PROBE_TTL_S, out)
        return out

    def alter_region_schema(self, region_id: int, schema) -> None:
        self._engine_for(region_id).alter_region_schema(region_id, schema)

    def drop_region(self, region_id: int) -> None:
        """Drop a region wherever it lives and forget its route (DDL
        drop/rollback step, common/meta/src/ddl/drop_table.rs analog).

        Route cleanup needs no live engine and must happen even when the
        owning datanode is dead — otherwise a later failover tick would
        resurrect the dropped table's region from the stale route."""
        from greptimedb_tpu.storage.engine import RegionRequest, RequestType

        try:
            eng = self._engine_for(region_id)
        except (KeyError, Unavailable):
            eng = None  # no route, or no live datanode: metadata-only drop
        if eng is not None:
            try:
                eng.region(region_id)
            except KeyError:
                try:
                    eng.open_region(region_id)
                except Exception:  # noqa: BLE001 — never created on disk
                    pass
            try:
                eng.handle_request(RegionRequest(RequestType.DROP, region_id))
            except KeyError:
                pass
        table_key = str(region_id >> 32)
        route = self.metasrv.routes.get(table_key)
        if route is not None:
            route.regions = [r for r in route.regions
                             if r.region_id != region_id]
            self.metasrv.routes.update(route)
        with self._lock:
            self._region_node.pop(region_id, None)

    def handle_request(self, req: RegionRequest) -> int:
        return self._engine_for(req.region_id).handle_request(req)


class Cluster:
    """N datanodes + metasrv + a distributed frontend QueryEngine."""

    def __init__(
        self,
        data_dir: str,
        num_datanodes: int = 3,
        kv: Optional[KvBackend] = None,
        opts: Optional[MetasrvOptions] = None,
        wire_transport: bool = False,
    ):
        self.kv = kv or MemoryKv()
        self.metasrv = Metasrv(self.kv, opts)
        self.datanodes: dict[str, Datanode] = {}
        shared = os.path.join(data_dir, "shared")
        for i in range(num_datanodes):
            node_id = f"dn-{i}"
            self.datanodes[node_id] = Datanode(node_id, shared, self.metasrv,
                                               wire=wire_transport)
        # topology for the fault layer: per-edge specs naming a node
        # outside this set are typos and fail at arm time. The
        # coordinator is registered under its REAL node id (the identity
        # heartbeat/kv edges carry), not a role alias that never matches
        FAULTS.register_nodes([*self.datanodes, "frontend",
                               self.metasrv.node_id])
        self.router = RegionRouter(self.metasrv, self.datanodes)
        self.catalog = Catalog(self.kv)
        # distributed DDL runs as journaled procedures on the metasrv's
        # persistent procedure manager (DdlManager, ddl_manager.rs analog);
        # QueryEngine delegates when the engine exposes one
        from greptimedb_tpu.meta.ddl import DdlManager

        self.router.ddl_manager = DdlManager(self.metasrv.procedures,
                                             self.router, self.catalog)
        self.frontend = QueryEngine(self.catalog, self.router)

    def beat_all(self, now_ms: Optional[float] = None) -> None:
        for dn in self.datanodes.values():
            dn.beat(now_ms)

    def tick(self, now_ms: Optional[float] = None) -> list[str]:
        return self.metasrv.tick(now_ms)

    def sql(self, sql: str, db: str = "public"):
        return self.frontend.execute_one(sql, QueryContext(db=db))

    def create_partitioned_table(
        self,
        sql_create: str,
        rule: RangePartitionRule,
        db: str = "public",
    ) -> TableInfo:
        """CREATE TABLE with N partitioned regions placed across datanodes
        (PARTITION ON COLUMNS clause analog)."""
        from ..sql import parse_sql

        stmt = parse_sql(sql_create)[0]
        ctx = QueryContext(db=db)
        self.frontend._create_table_partitioned(stmt, ctx, rule)
        return self.catalog.table(db, stmt.name)

    def close(self) -> None:
        for dn in self.datanodes.values():
            dn.close()
