"""Standalone datanode process entrypoint.

`python -m greptimedb_tpu.cluster.datanode_main <shared_dir> <port_file>`
builds a RegionEngine over the SHARED data dir with the remote
(object-store) WAL and serves it over Flight — the real process shape of
a reference datanode (datanode/src/datanode.rs: region server behind
gRPC, WAL on shared storage so failover candidates can replay it).

The process writes its bound port to <port_file> and then serves until
killed; `kill -9` is the expected shutdown in the failover harness
(tests-integration/src/cluster.rs kills real processes the same way).
"""

from __future__ import annotations

import os
import sys
import time


def main() -> None:
    # never touch a TPU tunnel from a datanode child: pin CPU before any
    # backend init (the env var alone is overridden by sitecustomize)
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax

    jax.config.update("jax_platforms", "cpu")

    shared_dir, port_file = sys.argv[1], sys.argv[2]
    write_workers = int(sys.argv[3]) if len(sys.argv) > 3 else 2

    from greptimedb_tpu.servers.flight import FlightServer
    from greptimedb_tpu.storage.engine import EngineConfig, RegionEngine
    from greptimedb_tpu.utils import flame
    from greptimedb_tpu.utils.otlp_trace import maybe_install
    from greptimedb_tpu.utils.tracing import install_trace_logging

    install_trace_logging()
    # inherited GTPU_OTLP_ENDPOINT: datanode children export their own
    # spans under the same trace ids the frontend propagates
    maybe_install()
    # inherited GTPU_PROFILE*: the child samples itself and its digest
    # rides Flight piggybacks into the frontend's cluster profile
    flame.maybe_install()

    def _env_num(name, default, cast):
        try:
            return cast(os.environ.get(name, default))
        except (TypeError, ValueError):
            return default

    # the background maintenance plane is per-datanode; harnesses tune
    # it via env (spawned children inherit) — GTPU_MAINT_WORKERS=0
    # restores inline flush for tests that need the pre-plane shape
    engine = RegionEngine(EngineConfig(
        data_dir=shared_dir, wal_backend="remote",
        write_workers=write_workers,
        maintenance_workers=_env_num("GTPU_MAINT_WORKERS", 1, int),
        maintenance_tick_s=_env_num("GTPU_MAINT_TICK_S", 0.0, float),
        retention_ttl_ms=_env_num("GTPU_MAINT_TTL_MS", 0, int)))
    server = FlightServer(None, port=0, region_engine=engine)
    tmp = port_file + ".tmp"
    with open(tmp, "w") as f:
        f.write(str(server.port))
    os.replace(tmp, port_file)  # atomic: readers never see a partial file
    try:
        from greptimedb_tpu.lint import lockdep

        while True:
            if lockdep.enabled() and os.environ.get("GTPU_LOCKDEP_DIR"):
                # the parent stops children with SIGKILL (the failover
                # scenario IS abrupt death), so atexit never runs here:
                # refresh the edge dump continuously instead
                lockdep.dump()
                time.sleep(1.0)
            else:
                time.sleep(3600)
    except KeyboardInterrupt:
        pass


if __name__ == "__main__":
    main()
