"""Deployable datanode role: region server + SELF-OWNED heartbeat task.

`python -m greptimedb_tpu datanode start --node-id dn-0
    --metasrv 127.0.0.1:4002 --data-home /shared --rpc-addr 127.0.0.1:0`

Mirrors reference src/datanode/src/datanode.rs (region server behind
gRPC) + heartbeat.rs:47-183 (the datanode's own HeartbeatTask reporting
RegionStats and applying returned Instructions) + alive_keeper.rs:49-112
(lease countdown per region; when the metasrv stops renewing — network
partition, or this node was failed over — the region self-closes: the
split-brain guard). Unlike the test harness (`process_cluster.py`, where
the parent beats on behalf of children), the heartbeat loop lives HERE,
in the datanode process, crossing a real wire to the metasrv.

Storage is the shared-object-store deployment shape: data + remote WAL
on a shared path so a failover candidate can replay this node's WAL.
"""

from __future__ import annotations

import threading
import time
from typing import Optional

from ..meta.heartbeat import HeartbeatTask
from ..meta.instruction import Instruction, InstructionKind
from ..meta.kv_service import MetaClient, MetaServiceError
from ..meta.metasrv import RegionStat
from ..storage.engine import RegionEngine, RegionRequest, RequestType


class DatanodeService:
    """Engine + Flight server + heartbeat/alive-keeper loop."""

    def __init__(self, node_id: str, engine: RegionEngine,
                 metasrv_addr: str, rpc_host: str = "127.0.0.1",
                 rpc_port: int = 0, heartbeat_interval_s: float = 3.0):
        from ..servers.flight import FlightServer

        self.node_id = node_id
        self.engine = engine
        self.server = FlightServer(None, host=rpc_host, port=rpc_port,
                                   region_engine=engine, node_id=node_id)
        self.addr = f"{rpc_host}:{self.server.port}"
        self.meta = MetaClient(metasrv_addr, node_addr=self.addr)
        self.heartbeat = HeartbeatTask(node_id, self.meta,
                                       self._region_stats,
                                       self._apply_instruction)
        self.interval_s = heartbeat_interval_s
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # ----------------------------------------------------------- heartbeat
    def _region_stats(self) -> list[RegionStat]:
        stats = []
        for rid, region in self.engine.regions.items():
            stats.append(RegionStat(
                region_id=rid, table=str(rid >> 32),
                memtable_bytes=region.memtable_bytes))
        return stats

    def _apply_instruction(self, inst: Instruction) -> None:
        if inst.kind in (InstructionKind.OPEN_REGION,
                         InstructionKind.UPGRADE_REGION):
            self.engine.open_region(inst.region_id)
        elif inst.kind is InstructionKind.CLOSE_REGION:
            try:
                self.engine.handle_request(
                    RegionRequest(RequestType.CLOSE, inst.region_id))
            except KeyError:
                pass  # already closed
        elif inst.kind is InstructionKind.DOWNGRADE_REGION:
            pass  # writes fence at the router via route state

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.heartbeat.beat()
            except MetaServiceError:
                # metasrv unreachable: keep serving; the alive-keeper
                # below closes regions when the lease actually lapses
                pass
            except Exception:  # noqa: BLE001 — loop must never die
                import traceback

                traceback.print_exc()
            for rid in self.heartbeat.alive_keeper.expired():
                # lease lapsed ⇒ the metasrv may have given the region
                # away; serving writes now would split-brain
                try:
                    self.engine.handle_request(
                        RegionRequest(RequestType.CLOSE, rid))
                except Exception:  # noqa: BLE001 — already gone
                    pass
                self.heartbeat.alive_keeper.forget(rid)

    # ------------------------------------------------------------- control
    def start(self) -> None:
        try:
            self.heartbeat.beat()  # register immediately (addr publish)
        except MetaServiceError:
            pass  # metasrv not up yet; the loop retries
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def serve_forever(self) -> None:
        """Block until killed (the CLI entrypoint's main thread)."""
        try:
            while not self._stop.is_set():
                time.sleep(0.2)
        except KeyboardInterrupt:
            pass

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
        self.server.shutdown()
        self.engine.close()
