"""Deployable frontend role: query engine over remote metadata + remote
regions.

`python -m greptimedb_tpu frontend start --metasrv 127.0.0.1:4002
    --http-addr 127.0.0.1:4000`

Mirrors reference src/frontend (instance.rs: catalog over the remote
meta KV, region requests routed by table-route metadata fetched from the
metasrv, DDL submitted as distributed procedures). The existing
`RegionRouter` carries all routing/pushdown logic; this module supplies
its two remote dependencies:

- `RemoteMetasrv`: the Metasrv surface the router + DdlManager consume
  (routes / procedures / selector / alive_nodes / node_stats), all
  backed by `HttpKv` + `MetaClient` instead of an in-process Metasrv.
- `RemoteNodeMap`: node_id -> datanode handle, resolved lazily from the
  heartbeat-maintained addr registry and connected over Flight.

Route/cache invalidation is pull-based here: `alive` reads the
metasrv's failure detector (briefly cached), and the router already
re-fetches routes when a node is dead or a region has no route — a
failover shows up at the frontend within one cache TTL.
"""

from __future__ import annotations

import threading
import time
from typing import Iterator

from ..catalog.catalog import Catalog
from ..fault import retry_call
from ..meta.kv_service import MetaClient
from ..meta.route import TableRouteManager
from ..meta.selector import SELECTORS
from ..procedure import ProcedureManager
from ..utils.metrics import DEGRADED
from .cluster import RegionRouter

ALIVE_TTL_S = 0.5


class RemoteMetasrv:
    """The slice of the Metasrv surface RegionRouter/DdlManager use,
    served remotely: metadata via the shared KV, liveness via admin
    HTTP, placement via a frontend-local selector over that liveness
    (the reference frontend asks the metasrv to allocate regions; the
    journaled DDL procedure pins the chosen node either way)."""

    def __init__(self, meta: MetaClient):
        self.meta = meta
        self.kv = meta.kv
        self.routes = TableRouteManager(meta.kv)
        self.procedures = ProcedureManager(meta.kv)
        self.selector = SELECTORS["round_robin"]()
        self._subs = []
        self._alive: tuple[float, list[str]] = (0.0, [])
        self._lock = threading.Lock()

    def alive_nodes(self, now_ms=None) -> list[str]:
        with self._lock:
            ts, nodes = self._alive
            if time.monotonic() - ts < ALIVE_TTL_S:
                return nodes
        from ..meta.kv_service import MetaServiceError

        try:
            nodes = retry_call(lambda: self.meta.alive_nodes(now_ms),
                               point="meta.rpc",
                               retryable=(OSError, MetaServiceError))
        except (OSError, MetaServiceError):  # metasrv briefly away
            # degrade to the last-known liveness view: stale beats none
            # (the router re-resolves routes on any stale-route error);
            # anything else — a programming error — must propagate.
            if ts == 0.0:
                raise  # never reached the metasrv: surface the error,
                # don't masquerade it as an empty cluster
            # re-stamp the cache so callers don't each re-pay the full
            # retry budget while the metasrv stays down
            DEGRADED.inc(point="meta.rpc")
            with self._lock:
                self._alive = (time.monotonic(), nodes)
            return nodes
        with self._lock:
            self._alive = (time.monotonic(), nodes)
        return nodes

    def node_stats(self) -> dict:
        return self.meta.node_stats()

    def migrate_region(self, table, region_id, to_node, now_ms=None):
        return self.meta.migrate_region(table, region_id, to_node)

    def subscribe_invalidation(self, fn) -> None:
        self._subs.append(fn)

    def invalidate_caches(self, table: str) -> None:
        for fn in self._subs:
            fn(table)


class RemoteNode:
    """Parent-free datanode handle: Flight client + liveness from the
    metasrv's failure detector."""

    def __init__(self, node_id: str, addr: str, metasrv: RemoteMetasrv):
        from ..servers.flight import RemoteRegionEngine

        self.node_id = node_id
        self.addr = addr
        self.metasrv = metasrv
        self.remote = RemoteRegionEngine(addr)

    @property
    def alive(self) -> bool:
        return self.node_id in self.metasrv.alive_nodes()

    def data_engine(self):
        return self.remote

    def close(self) -> None:
        try:
            self.remote.close()
        except Exception:  # noqa: BLE001 — peer may be gone
            pass


class RemoteNodeMap:
    """dict-like node_id -> RemoteNode for RegionRouter, resolved from
    the addr registry the datanodes publish via heartbeat."""

    ADDR_RECHECK_S = 5.0

    def __init__(self, metasrv: RemoteMetasrv):
        self.metasrv = metasrv
        self._handles: dict[str, RemoteNode] = {}
        self._checked: dict[str, float] = {}
        self._lock = threading.Lock()
        self.closed = False

    def __getitem__(self, node_id: str) -> RemoteNode:
        now = time.monotonic()
        with self._lock:
            h = self._handles.get(node_id)
            fresh = now - self._checked.get(node_id, 0.0) < \
                self.ADDR_RECHECK_S
        if h is not None and fresh:
            return h
        addr = self.metasrv.meta.node_addrs().get(node_id)
        if addr is None:
            raise KeyError(f"datanode {node_id} has no registered address")
        with self._lock:
            h = self._handles.get(node_id)
            if h is not None and h.addr != addr:
                # node restarted on a new port: retire the stale client
                h.close()
                h = None
            if h is None:
                h = RemoteNode(node_id, addr, self.metasrv)
                self._handles[node_id] = h
            self._checked[node_id] = now
            return h

    def __iter__(self) -> Iterator[str]:
        return iter(sorted(self.metasrv.meta.node_addrs()))

    def __len__(self) -> int:
        return len(self.metasrv.meta.node_addrs())

    def __contains__(self, node_id: str) -> bool:
        return node_id in self.metasrv.meta.node_addrs()

    def values(self):
        with self._lock:
            return list(self._handles.values())

    def close(self) -> None:
        self.closed = True
        for h in self.values():
            h.close()


def build_frontend(metasrv_addr: str, default_timezone: str = "UTC"):
    """Assemble a frontend QueryEngine against a remote metasrv: returns
    (query_engine, node_map) — close the node_map on shutdown."""
    from ..meta.ddl import DdlManager
    from ..meta.route import ROUTE_PREFIX
    from ..query.engine import QueryEngine

    meta = MetaClient(metasrv_addr)
    remote_meta = RemoteMetasrv(meta)
    nodes = RemoteNodeMap(remote_meta)
    router = RegionRouter(remote_meta, nodes)
    catalog = Catalog(meta.kv)
    router.ddl_manager = DdlManager(remote_meta.procedures, router, catalog)
    qe = QueryEngine(catalog, router, default_timezone=default_timezone)

    # remote DDL / route swaps must evict this frontend's cached plan
    # shapes too — the same channel the router uses for its route cache
    # ("" = can't tell which table: flush every shape)
    def _drop_plans(table: str) -> None:
        name = table.rsplit(".", 1)[-1] if table else None
        qe.concurrency.invalidate_table(name=name or None)

    remote_meta.subscribe_invalidation(_drop_plans)

    # push-based invalidation: long-poll the metasrv's watch on the
    # route prefix; a failover/migration route swap clears the router's
    # caches within one poll round-trip instead of a liveness-TTL miss
    # (the reference's cache-invalidation channel, src/cache)
    def _watch_loop():
        rev = 0
        while not nodes.closed:
            try:
                out = meta.watch(ROUTE_PREFIX, rev, timeout_s=20.0)
                new_rev = out.get("rev", rev)
                if new_rev < rev:
                    # metasrv restarted: its in-memory revision reset —
                    # resync from the new counter and invalidate once
                    # (routes may have moved while we were blind)
                    remote_meta.invalidate_caches("")
                elif out.get("changed"):
                    remote_meta.invalidate_caches("")
                rev = new_rev
            except Exception:  # noqa: BLE001 — metasrv briefly away
                time.sleep(1.0)

    threading.Thread(target=_watch_loop, daemon=True).start()
    return qe, nodes
