"""Multi-PROCESS metasrv harness: N electing metasrv OS processes over
the real kv_service wire.

The in-process chaos scenarios exercise elections against a shared
in-memory KV; this harness makes the election REAL: each metasrv peer is
a child process (metasrv_main) whose every KV op — campaign CAS, lease
read, route mutation — crosses the `kv_service` HTTP wire to the KV-host
service in the parent (the etcd analog: one process owns the store, so
CAS atomicity holds cluster-wide). The parent keeps the store wrapped in
whatever KvBackend the caller supplies — the chaos oracle passes an
`ElectionEpochJournal` so every successful lease CAS is journaled as
ground truth for the at-most-one-leader-per-epoch invariant.

Time is virtual: no ticker runs anywhere; the harness drives each
peer's `/admin/tick` with explicit timestamps, so seeded schedules
replay deterministically. Chaos reaches every layer:

- `election.lease` (+ `@node`) fires INSIDE a child (forced lease loss),
- `metasrv.kv` (+ `@edge`/`@op`) and `partition=meta-1<->kv-host` fire
  in the parent's wire service (the KV access cut),
- `GTPU_CLOCK_SKEW_MS` skews one child's clock (the Jepsen clock
  nemesis).
"""

from __future__ import annotations

import os
import subprocess
import sys
import time
from typing import Optional

from ..catalog.kv import KvBackend, MemoryKv
from ..fault import FAULTS
from ..meta.kv_service import MetaClient, MetaHttpService
from ..meta.metasrv import Metasrv, MetasrvOptions

#: the parent-side KV host's node identity (the dst of every
#: metasrv.kv edge a child's wire op crosses)
KV_HOST_ID = "kv-host"


class ProcMetasrv:
    """Parent-side handle for one electing metasrv child process."""

    def __init__(self, node_id: str, kv_addr: str, run_dir: str,
                 lease_s: float, clock_skew_ms: float = 0.0):
        self.node_id = node_id
        self.port_file = os.path.join(run_dir, f"{node_id}.port")
        self.stderr_path = os.path.join(run_dir, f"{node_id}.stderr")
        self._stderr_f = open(self.stderr_path, "wb")
        self.proc = subprocess.Popen(
            [sys.executable, "-m", "greptimedb_tpu.cluster.metasrv_main",
             kv_addr, self.port_file, node_id],
            stdout=subprocess.DEVNULL, stderr=self._stderr_f,
            env={**os.environ, "JAX_PLATFORMS": "cpu",
                 "GTPU_NODE_ID": node_id,
                 "GTPU_LEASE_S": str(lease_s),
                 "GTPU_CLOCK_SKEW_MS": str(clock_skew_ms)},
        )
        self.client: Optional[MetaClient] = None

    def _stderr_tail(self) -> str:
        try:
            with open(self.stderr_path, "rb") as f:
                return f.read()[-2000:].decode(errors="replace")
        except OSError:
            return ""

    def wait_ready(self, timeout_s: float = 60.0) -> None:
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            if self.proc.poll() is not None:
                raise RuntimeError(
                    f"metasrv {self.node_id} died at startup:\n"
                    f"{self._stderr_tail()}")
            if os.path.exists(self.port_file):
                with open(self.port_file) as f:
                    raw = f.read().strip()
                try:
                    port = int(raw)
                except ValueError:
                    time.sleep(0.05)
                    continue
                self.client = MetaClient(f"127.0.0.1:{port}",
                                         metasrv_node_id=self.node_id)
                return
            time.sleep(0.05)
        raise TimeoutError(f"metasrv {self.node_id} did not come up")

    @property
    def alive(self) -> bool:
        return self.proc.poll() is None

    def kill(self) -> None:
        self.proc.kill()
        self.proc.wait()

    def close(self) -> None:
        if self.proc.poll() is None:
            self.proc.kill()
        self.proc.wait()
        self._stderr_f.close()


class MetasrvProcessCluster:
    """N metasrv child processes electing over the parent's KV wire."""

    def __init__(self, data_dir: str, num_metasrv: int = 3,
                 kv: Optional[KvBackend] = None, lease_s: float = 9.0,
                 clock_skew_ms: Optional[dict] = None):
        self.kv = kv or MemoryKv()
        self.lease_s = lease_s
        # the KV host is a wire front only: no election, never ticked —
        # its Metasrv exists because MetaHttpService serves one
        self.host = Metasrv(self.kv, MetasrvOptions(), node_id=KV_HOST_ID)
        self.service = MetaHttpService(self.host)
        self.service.start()
        self.run_dir = os.path.join(data_dir, "meta_run")
        os.makedirs(self.run_dir, exist_ok=True)
        skews = clock_skew_ms or {}
        self.metasrvs: dict[str, ProcMetasrv] = {}
        try:
            for i in range(num_metasrv):
                node_id = f"meta-{i}"
                self.metasrvs[node_id] = ProcMetasrv(
                    node_id, self.service.addr, self.run_dir, lease_s,
                    clock_skew_ms=float(skews.get(node_id, 0.0)))
            for ms in self.metasrvs.values():
                ms.wait_ready()
        except BaseException:
            for ms in self.metasrvs.values():
                try:
                    ms.close()
                except Exception:  # noqa: BLE001 — best-effort reap
                    pass
            try:
                self.service.stop()
            except Exception:  # noqa: BLE001
                pass
            raise
        FAULTS.register_nodes([*self.metasrvs, KV_HOST_ID, "frontend"])

    def tick_all(self, now_ms: float) -> dict:
        """Drive every live peer's virtual clock one step; a peer whose
        wire access is under chaos reports its typed error instead of
        the tick result (the caller classifies)."""
        out: dict = {}
        for node_id, ms in self.metasrvs.items():
            if not ms.alive:
                continue
            try:
                out[node_id] = ms.client.tick(now_ms)
            except Exception as e:  # noqa: BLE001 — classified by caller
                out[node_id] = e
        return out

    def leader(self, now_ms: float) -> Optional[str]:
        """The authoritative lease holder per the parent's KV (the same
        ground truth the epoch journal records)."""
        import json

        from ..meta.election import ELECTION_KEY

        raw = self.kv.get(ELECTION_KEY)
        if not raw:
            return None
        rec = json.loads(raw)
        if now_ms < rec.get("lease_until_ms", 0.0):
            return rec.get("node")
        return None

    def chaos_reset_all(self) -> None:
        """Disarm every live child's registry (the parent's is the
        caller's to clear) so final verification runs chaos-free."""
        for ms in self.metasrvs.values():
            if ms.alive and ms.client is not None:
                ms.client.chaos_reset()

    def kill_metasrv(self, node_id: str) -> None:
        self.metasrvs[node_id].kill()

    def close(self) -> None:
        for ms in self.metasrvs.values():
            ms.close()
        try:
            self.service.stop()
        except Exception:  # noqa: BLE001 — port may already be gone
            pass
