"""Standalone HA-metasrv process entrypoint (election over the wire).

`python -m greptimedb_tpu.cluster.metasrv_main <kv_addr> <port_file>
<node_id>` builds one electing metasrv peer: a KvElection + Metasrv over
an `HttpKv` pointed at the shared KV host (the etcd analog — CAS
atomicity lives in that one process), fronted by its own
`MetaHttpService` so the parent harness can drive `/admin/tick` with an
explicit virtual clock and observe `/heartbeat` / `/admin/*` redirects.

No MetasrvTicker runs here: the chaos harness owns time. The process
writes its bound port to <port_file> and serves until killed; election
chaos arrives via the inherited GTPU_CHAOS / GTPU_CHAOS_SEED env
(election.lease fires inside THIS process) and clock skew via
GTPU_CLOCK_SKEW_MS (the Jepsen clock nemesis, per-node).
"""

from __future__ import annotations

import os
import sys
import time


def main() -> None:
    # metasrv children never touch an accelerator tunnel: pin CPU before
    # any backend init (the env var alone is overridden by sitecustomize)
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax

    jax.config.update("jax_platforms", "cpu")

    kv_addr, port_file, node_id = sys.argv[1], sys.argv[2], sys.argv[3]

    from greptimedb_tpu.meta.election import KvElection
    from greptimedb_tpu.meta.kv_service import HttpKv, MetaHttpService
    from greptimedb_tpu.meta.metasrv import Metasrv, MetasrvOptions
    from greptimedb_tpu.utils.tracing import install_trace_logging

    install_trace_logging()

    def _env_num(name, default, cast):
        try:
            return cast(os.environ.get(name, default))
        except (TypeError, ValueError):
            return default

    kv = HttpKv(kv_addr)
    election = KvElection(kv, node_id,
                          lease_s=_env_num("GTPU_LEASE_S", 9.0, float))
    election.clock_skew_ms = _env_num("GTPU_CLOCK_SKEW_MS", 0.0, float)
    metasrv = Metasrv(kv, MetasrvOptions(), node_id=node_id,
                      election=election)
    service = MetaHttpService(metasrv)
    service.start()
    tmp = port_file + ".tmp"
    with open(tmp, "w") as f:
        f.write(str(service.port))
    os.replace(tmp, port_file)  # atomic: readers never see a partial file
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        pass


if __name__ == "__main__":
    main()
