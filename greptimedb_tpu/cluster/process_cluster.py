"""Multi-PROCESS cluster harness: N datanode OS processes + in-parent
metasrv/frontend.

Mirrors the reference's integration harness
(tests-integration/src/cluster.rs:66-135: real datanode/frontend/metasrv
instances, regions on shared storage, kill-based failover tests). Here
each datanode is a real child process (datanode_main) serving its
regions over Flight sockets; the frontend and metasrv run in the parent
and route through the same RegionRouter the in-process cluster uses —
the wire path is identical, only the process boundary is real.

Heartbeats: the parent beats the metasrv on behalf of each child while
its process is alive (liveness = the OS process), and applies returned
instructions over the wire (OPEN_REGION → Flight region_admin open).
`kill -9` on a child stops its beats; the metasrv's failure detector
expires it and failover re-opens its regions on a survivor, which
replays the remote WAL from the shared object store.
"""

from __future__ import annotations

import os
import subprocess
import sys
import time
from typing import Optional

from ..catalog.catalog import Catalog
from ..catalog.kv import KvBackend, MemoryKv
from ..fault import FAULTS, FaultError, Unavailable
from ..meta.instruction import Instruction, InstructionKind
from ..meta.metasrv import HeartbeatRequest, Metasrv, MetasrvOptions
from ..query.engine import QueryContext, QueryEngine
from .cluster import RegionRouter


class ProcDatanode:
    """Parent-side handle for one datanode child process: satisfies the
    RegionRouter's expectations (.alive, .data_engine())."""

    def __init__(self, node_id: str, shared_dir: str, run_dir: str):
        self.node_id = node_id
        self.port_file = os.path.join(run_dir, f"{node_id}.port")
        # stderr goes to a FILE, not a pipe: a pipe nobody drains blocks
        # the child once the OS buffer fills, and the file doubles as the
        # post-crash diagnostic
        self.stderr_path = os.path.join(run_dir, f"{node_id}.stderr")
        self._stderr_f = open(self.stderr_path, "wb")
        child_env = {**os.environ, "JAX_PLATFORMS": "cpu",
                     # GTPU_NODE_ID: identity stamped on the spans the
                     # child piggybacks on its Flight responses
                     # (EXPLAIN ANALYZE attribution)
                     "GTPU_NODE_ID": node_id}
        if os.environ.get("GTPU_LOCKDEP") \
                and not os.environ.get("GTPU_LOCKDEP_DIR"):
            # cross-process lockdep: children dump their observed edge
            # sets next to the port files; lockdep.merged_report unions
            # them with the parent's graph
            child_env["GTPU_LOCKDEP_DIR"] = run_dir
        self.proc = subprocess.Popen(
            [sys.executable, "-m", "greptimedb_tpu.cluster.datanode_main",
             shared_dir, self.port_file],
            stdout=subprocess.DEVNULL, stderr=self._stderr_f,
            env=child_env,
        )
        self.remote = None  # connected lazily once the port file appears

    def _stderr_tail(self) -> str:
        try:
            with open(self.stderr_path, "rb") as f:
                return f.read()[-2000:].decode(errors="replace")
        except OSError:
            return ""

    def wait_ready(self, timeout_s: float = 60.0) -> None:
        from ..servers.flight import RemoteRegionEngine

        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            if self.proc.poll() is not None:
                raise RuntimeError(
                    f"datanode {self.node_id} died at startup:\n"
                    f"{self._stderr_tail()}")
            if os.path.exists(self.port_file):
                with open(self.port_file) as f:
                    raw = f.read().strip()
                try:
                    port = int(raw)
                except ValueError:
                    # empty/partial write (non-atomic filesystems, or a
                    # child mid-write): not ready yet, keep polling
                    time.sleep(0.05)
                    continue
                # peer identity makes every RPC an edge the fault layer
                # can cut: (frontend, <this node>) partitions
                self.remote = RemoteRegionEngine(f"127.0.0.1:{port}",
                                                 peer=self.node_id)
                return
            time.sleep(0.05)
        raise TimeoutError(f"datanode {self.node_id} did not come up")

    @property
    def alive(self) -> bool:
        return self.proc.poll() is None

    def data_engine(self):
        return self.remote

    def kill(self) -> None:
        """SIGKILL — the abrupt-death failover scenario."""
        self.proc.kill()
        self.proc.wait()

    def close(self) -> None:
        if self.remote is not None:
            try:
                self.remote.close()
            except Exception:  # noqa: BLE001 — process may be gone
                pass
        if self.proc.poll() is None:
            self.proc.kill()
        self.proc.wait()
        self._stderr_f.close()


class ProcessCluster:
    """N datanode processes + parent metasrv/frontend (see module doc)."""

    def __init__(self, data_dir: str, num_datanodes: int = 3,
                 kv: Optional[KvBackend] = None,
                 opts: Optional[MetasrvOptions] = None,
                 election=None, metasrv_node_id: str = "metasrv-0"):
        self.kv = kv or MemoryKv()
        # an attached election makes the parent metasrv one HA candidate
        # among peers over the shared KV — the lease-loss chaos scenarios
        # run a standby Metasrv beside it and force re-election
        self.metasrv = Metasrv(self.kv, opts, node_id=metasrv_node_id,
                               election=election)
        self.run_dir = os.path.join(data_dir, "run")
        os.makedirs(self.run_dir, exist_ok=True)
        shared = os.path.join(data_dir, "shared")
        os.makedirs(shared, exist_ok=True)
        self.datanodes: dict[str, ProcDatanode] = {}
        try:
            for i in range(num_datanodes):
                node_id = f"dn-{i}"
                self.datanodes[node_id] = ProcDatanode(node_id, shared,
                                                       self.run_dir)
            for dn in self.datanodes.values():
                dn.wait_ready()
        except BaseException:
            # a failed bring-up (startup timeout, chaos hitting a boot
            # path) must not orphan the children already spawned — the
            # caller never gets a handle to close them
            for dn in self.datanodes.values():
                try:
                    dn.close()
                except Exception:  # noqa: BLE001 — best-effort reap
                    pass
            raise
        # topology for the fault layer's per-edge typo guard: the
        # coordinator under its real node id (what heartbeat/kv edges
        # carry), never a role alias that would validate but not match
        FAULTS.register_nodes([*self.datanodes, "frontend",
                               metasrv_node_id])
        self.router = RegionRouter(self.metasrv, self.datanodes)
        self.catalog = Catalog(self.kv)
        from ..meta.ddl import DdlManager

        self.router.ddl_manager = DdlManager(self.metasrv.procedures,
                                             self.router, self.catalog)
        self.frontend = QueryEngine(self.catalog, self.router)

    # ---- control plane ------------------------------------------------------

    def _region_stats_for(self, node_id: str) -> list:
        """Region stats from the route table (the reference reports them
        from the engine; the parent-side proxy derives them from routes —
        the metasrv needs them to know WHAT to fail over)."""
        from ..meta.metasrv import RegionStat

        stats = []
        for route in self.metasrv.routes.all():
            for rr in route.regions:
                if rr.leader_node == node_id:
                    stats.append(RegionStat(region_id=rr.region_id,
                                            table=route.table))
        return stats

    def beat_all(self, now_ms: Optional[float] = None,
                 metasrv: Optional[Metasrv] = None) -> None:
        """Heartbeat the metasrv for every child whose PROCESS is alive,
        applying returned instructions over the wire. `metasrv` overrides
        the target coordinator — the HA scenarios beat whichever peer
        currently holds the election lease."""
        now_ms = now_ms if now_ms is not None else time.time() * 1000
        target = metasrv if metasrv is not None else self.metasrv
        for node_id, dn in self.datanodes.items():
            if not dn.alive:
                continue
            try:
                FAULTS.fire("datanode.crash", node=node_id)
            except FaultError:
                dn.kill()  # the chaos schedule SIGKILLs this child now
                continue
            try:
                # src/dst: a (node, <metasrv id>) partition silences
                # this one — dst names the coordinator actually targeted
                # so HA scenarios can cut a node from ONE metasrv peer
                FAULTS.fire("heartbeat.send", node=node_id,
                            src=node_id, dst=target.node_id)
            except FaultError:
                continue  # dropped: the metasrv never hears this beat
            resp = target.handle_heartbeat(
                HeartbeatRequest(node_id=node_id,
                                 region_stats=self._region_stats_for(
                                     node_id),
                                 now_ms=now_ms))
            for inst in resp.instructions:
                try:
                    self._apply(dn, inst)
                except Exception as e:  # noqa: BLE001 — classified below
                    typed = isinstance(e, (FaultError, Unavailable)) \
                        or "Flight" in type(e).__name__
                    requeue = getattr(target, "send_instruction", None)
                    if not typed or requeue is None:
                        raise
                    # the mailbox contract is redeliver-until-applied: a
                    # chaos fault mid-delivery (WAL replay dying inside
                    # an OpenRegion) must NOT drop the instruction, or
                    # the region stays routed-but-closed forever
                    requeue(node_id, inst)

    def _apply(self, dn: ProcDatanode, inst: Instruction) -> None:
        from ..storage.engine import RegionRequest, RequestType

        if inst.kind in (InstructionKind.OPEN_REGION,
                         InstructionKind.UPGRADE_REGION):
            dn.remote.open_region(inst.region_id)
        elif inst.kind is InstructionKind.CLOSE_REGION:
            dn.remote.handle_request(
                RegionRequest(RequestType.CLOSE, inst.region_id))

    def tick(self, now_ms: Optional[float] = None,
             metasrv: Optional[Metasrv] = None) -> list[str]:
        return (metasrv if metasrv is not None else self.metasrv).tick(now_ms)

    def sql(self, sql: str, db: str = "public"):
        return self.frontend.execute_one(sql, QueryContext(db=db))

    def kill_datanode(self, node_id: str) -> None:
        self.datanodes[node_id].kill()

    def close(self) -> None:
        for dn in self.datanodes.values():
            dn.close()
