"""Frontend concurrency plane (the ISSUE-6 subsystem).

A genuinely new layer between the protocol servers (L6/L5) and the
query engine (L3) that makes fleet-scale concurrent traffic cheap:

- `plan_cache`   — shape-keyed parameterized logical-plan cache (one
                   plan + one XLA executable shared by thousands of
                   near-identical dashboard queries), invalidated on
                   DDL/schema/rollup-state change;
- `admission`    — bounded admission queue + per-tenant weighted fair
                   scheduling with typed `Overloaded` rejection;
- `batcher`      — a short collection window that coalesces identical
                   statements and executes parameter-sibling aggregates
                   (multi-tag selectors, differing time windows) as one
                   vmap'd stacked dispatch, bit-for-bit with serial;
- `encode_pool`  — a bounded pool that serializes query results off the
                   request threads (admission slots are released at
                   execute-done, serialization never holds one).

`QueryEngine` routes every statement through the plane; configuration
comes from the `[concurrency]` options section via `configure()` (env
vars prefixed GTPU_ override for benches/tests).
"""

from __future__ import annotations

import os
import threading
from contextlib import contextmanager
from dataclasses import dataclass

from greptimedb_tpu.concurrency.admission import (  # noqa: F401
    AdmissionController,
    Overloaded,
    parse_weights,
)
from greptimedb_tpu.concurrency.batcher import QueryBatcher
from greptimedb_tpu.concurrency.encode_pool import EncodePool
from greptimedb_tpu.concurrency.fast_lane import FastLane
from greptimedb_tpu.concurrency.plan_cache import PlanCache

__all__ = ["ConcurrencyConfig", "ConcurrencyPlane", "Overloaded",
           "configure", "current_config"]


@dataclass
class ConcurrencyConfig:
    enabled: bool = True
    #: concurrent statements in execution; 0 = auto (max(32, 4*cpu))
    max_concurrency: int = 0
    queue_size: int = 512
    queue_timeout_s: float = 30.0
    #: "tenantA=3,tenantB=1" weighted round-robin shares; unlisted = 1
    tenant_weights: str = ""
    plan_cache_entries: int = 512
    #: text-keyed parse-free serving fast lane (concurrency/fast_lane.py)
    fast_lane: bool = True
    #: fast-lane template capacity; 0 disables
    fast_lane_entries: int = 512
    batching: bool = True
    batch_window_ms: float = 2.0
    batch_max_queries: int = 64
    #: stacked dispatch only below this estimated row count (single
    #: kernel dispatch keeps float parity provable); 0 = no bound
    batch_max_rows: int = 4 << 20
    #: vmap'd multi-query kernel for parameter-sibling batch members
    #: (off -> IN-list stacking / serial fallback only)
    batch_vmap: bool = True
    #: bounded result-encode pool (off -> serialize on request threads)
    encode_offload: bool = True
    #: encode workers; 0 = auto (max(2, min(8, cpu/2)))
    encode_workers: int = 0
    #: serializations in flight before inline fallback
    encode_queue: int = 64
    #: results smaller than this many rows encode inline (a thread
    #: handoff costs more than serializing a dashboard-sized result)
    encode_min_rows: int = 256
    #: spawn-mode worker processes instead of threads (full GIL escape;
    #: pays pickling) — legacy pin: True forces every offload to the
    #: process pool (same as encode_process_mode="on")
    encode_process_pool: bool = False
    #: process-pool routing: "auto" escapes to spawn workers only for
    #: results at/above encode_process_min_rows (measured size picks the
    #: executor), "on" pins process mode, "off" disables it (A/B knob,
    #: GTPU_ENCODE_PROCESS_MODE)
    encode_process_mode: str = "auto"
    #: auto-mode threshold: results at/above this many rows serialize in
    #: a worker process; dashboard-sized rows keep the thread pool
    encode_process_min_rows: int = 100_000


_config = ConcurrencyConfig()
_config_lock = threading.Lock()


def configure(cfg: ConcurrencyConfig) -> None:
    """Install the process-wide default config (options layer calls
    this; engines built afterwards pick it up)."""
    global _config
    with _config_lock:
        _config = cfg


def _env_num(name, cur, cast):
    v = os.environ.get(name)
    if not v:
        return cur
    try:
        return cast(v)
    except ValueError:
        return cur


def current_config() -> ConcurrencyConfig:
    """The installed config with env overrides applied (benches/tests
    A/B the plane without an options object)."""
    with _config_lock:
        cfg = ConcurrencyConfig(**vars(_config))
    cfg.enabled = _env_num("GTPU_CONCURRENCY", int(cfg.enabled), int) != 0
    cfg.max_concurrency = _env_num("GTPU_MAX_CONCURRENCY",
                                   cfg.max_concurrency, int)
    cfg.plan_cache_entries = _env_num("GTPU_PLAN_CACHE_ENTRIES",
                                      cfg.plan_cache_entries, int)
    cfg.fast_lane = _env_num("GTPU_FAST_LANE", int(cfg.fast_lane),
                             int) != 0
    cfg.fast_lane_entries = _env_num("GTPU_FAST_LANE_ENTRIES",
                                     cfg.fast_lane_entries, int)
    cfg.batching = _env_num("GTPU_QUERY_BATCHING", int(cfg.batching),
                            int) != 0
    cfg.batch_window_ms = _env_num("GTPU_BATCH_WINDOW_MS",
                                   cfg.batch_window_ms, float)
    cfg.batch_vmap = _env_num("GTPU_BATCH_VMAP", int(cfg.batch_vmap),
                              int) != 0
    cfg.encode_offload = _env_num("GTPU_ENCODE_OFFLOAD",
                                  int(cfg.encode_offload), int) != 0
    cfg.encode_workers = _env_num("GTPU_ENCODE_WORKERS",
                                  cfg.encode_workers, int)
    mode = os.environ.get("GTPU_ENCODE_PROCESS_MODE", "").lower()
    if mode in ("auto", "on", "off"):
        cfg.encode_process_mode = mode
    cfg.encode_process_min_rows = _env_num("GTPU_ENCODE_PROCESS_MIN_ROWS",
                                           cfg.encode_process_min_rows, int)
    return cfg


class ConcurrencyPlane:
    def __init__(self, cfg: ConcurrencyConfig | None = None):
        cfg = cfg or current_config()
        self.cfg = cfg
        limit = cfg.max_concurrency
        if limit <= 0:
            limit = max(32, 4 * (os.cpu_count() or 8))
        self.admission = AdmissionController(
            limit, cfg.queue_size, cfg.queue_timeout_s,
            parse_weights(cfg.tenant_weights),
            enabled=cfg.enabled)
        self.plan_cache = PlanCache(
            cfg.plan_cache_entries if cfg.enabled else 0)
        # the fast lane needs the plan cache: its entries hold
        # plan-cache entries, so disabling the cache disables the lane
        self.fast_lane = FastLane(
            cfg.fast_lane_entries,
            enabled=(cfg.enabled and cfg.fast_lane
                     and self.plan_cache.enabled))
        self.batcher = QueryBatcher(
            window_s=cfg.batch_window_ms / 1000.0,
            max_queries=cfg.batch_max_queries,
            max_rows=cfg.batch_max_rows,
            enabled=cfg.enabled and cfg.batching,
            vmap=cfg.batch_vmap)
        self.encode = EncodePool(
            workers=cfg.encode_workers,
            queue_size=cfg.encode_queue,
            process=cfg.encode_process_pool,
            enabled=cfg.enabled and cfg.encode_offload,
            min_rows=cfg.encode_min_rows,
            process_mode=("on" if cfg.encode_process_pool
                          else cfg.encode_process_mode),
            process_min_rows=cfg.encode_process_min_rows)
        self._tls = threading.local()
        # the serving fabric (shm/): attach once per process, register
        # the scrape-time collectors (fabric gauges + worker-metrics
        # fold), and default the persistent XLA compilation cache to the
        # shared namespace — all no-ops when GTPU_SHM_FABRIC is off
        from greptimedb_tpu import shm

        if cfg.enabled and shm.get_fabric() is not None:
            from greptimedb_tpu.shm import metrics_bridge

            metrics_bridge.install_collector()
            shm.install_stats_collector()
            shm.apply_shared_xla_cache()
            # the engine builds its PhysicalExecutor BEFORE this plane,
            # so the executor's enable_compilation_cache() ran without
            # the shared dir; re-wire now (idempotent, process-global
            # jax config) so THIS process caches into the fabric
            from greptimedb_tpu.query.physical import (
                enable_compilation_cache,
            )

            if enable_compilation_cache():
                # in the shared namespace cache even sub-second
                # compiles: on an N-process box every executable cached
                # here is another frontend's first-query win
                import jax

                jax.config.update(
                    "jax_persistent_cache_min_compile_time_secs", 0.0)

    # ---- batching gate -----------------------------------------------------

    @contextmanager
    def suppress_batching(self):
        """EXPLAIN/TQL ANALYZE must observe ITS execution's spans —
        riding another leader's run would report an empty trace."""
        prev = getattr(self._tls, "no_batch", False)
        self._tls.no_batch = True
        try:
            yield
        finally:
            self._tls.no_batch = prev

    def execute_select(self, qe, sel, info, ctx):
        """Route one table SELECT: batch when this is a top-level
        statement on a busy server, else straight through."""
        if (not self.batcher.enabled
                or self.admission.depth() != 1
                or getattr(self._tls, "no_batch", False)):
            return qe._select_table(sel, info, ctx)
        return self.batcher.execute(qe, sel, info, ctx,
                                    busy=self.admission.active > 1)

    # ---- tenancy -----------------------------------------------------------

    @staticmethod
    def tenant_of(ctx) -> str:
        t = getattr(ctx, "tenant", None)
        if t:
            return str(t)
        user = getattr(ctx, "user", None)
        name = getattr(user, "username", None)
        return name or "default"

    # ---- lifecycle ---------------------------------------------------------

    def shutdown(self) -> None:
        """Deterministic teardown of pool resources (encode workers —
        the GC finalizer is only the backstop for discarded planes)."""
        self.encode.shutdown()

    # ---- invalidation ------------------------------------------------------

    def invalidate_table(self, db=None, name=None) -> int:
        # one seam for all layers: DDL hooks and the remote-catalog
        # watch invalidate plan shapes, text templates, AND (fabric on)
        # every peer process's published artifacts for the table
        self._fabric_invalidate(db, name)
        self.fast_lane.invalidate_table(db, name)
        return self.plan_cache.invalidate_table(db, name)

    @staticmethod
    def _fabric_invalidate(db, name) -> None:
        """Bump the (db, table) fabric version so artifacts peers
        published under the old one die on their next adopt check; a
        widened match (None field — the remote watch can't tell what
        moved) wipes the whole fabric."""
        from greptimedb_tpu import shm
        from greptimedb_tpu.shm.fabric import FabricError
        from greptimedb_tpu.utils.metrics import SHM_FABRIC_EVENTS

        fabric = shm.get_fabric()
        if fabric is None:
            return
        try:
            if db is None or name is None:
                fabric.wipe()
            else:
                fabric.bump_version(db, name)
            SHM_FABRIC_EVENTS.inc(event="invalidate", kind="fabric")
        except (FabricError, OSError, ValueError):
            shm.detach()
