"""Admission control + per-tenant weighted fair scheduling.

A bounded queue in front of statement execution (the reference frontend
bounds its runtime the same way): at most `max_concurrency` statements
execute at once; excess callers wait in per-tenant FIFO queues drained
by weighted round-robin, so a flooding tenant's backlog cannot starve a
light tenant — the light tenant's next query is served after at most
one full WRR turn, not after the flood drains. A full queue or an
expired wait raises the typed `Overloaded` (an `Unavailable` subclass:
HTTP maps it to 503, MySQL to 1040, and the cluster retry machinery
already treats it as a terminal degradation signal) instead of letting
unbounded pile-up take the process down.

Re-entrant by thread: nested statements (views, CTEs, EXPLAIN ANALYZE,
flow ticks inside an admitted statement) pass through on the slot their
top-level statement already holds — an inner acquire would deadlock
against a full house.

Uncontended fast path (ISSUE 14): execution slots are a token pool
(`_tokens`), and when nobody is queued an acquire is one GIL-atomic
`list.pop` + a sharded-counter inc — no lock, no condition round-trip.
The slow path (waiters exist, or the pool is empty) keeps the classic
lock + per-tenant WRR queues. The lost-wakeup race between a lock-free
release and a concurrent enqueue is closed from both sides: release
re-checks the queue AFTER returning its token (and rescues under the
lock), and a waiter re-checks the pool AFTER enqueuing — under the
GIL's total order one of the two always observes the other.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from contextlib import contextmanager

from greptimedb_tpu.fault.retry import Unavailable
from greptimedb_tpu.utils import ledger
from greptimedb_tpu.utils.metrics import (
    ADMISSION_EVENTS,
    ADMISSION_QUEUE_DEPTH,
    ADMISSION_WAIT_SECONDS,
)


class Overloaded(Unavailable):
    """Typed admission rejection: the server is saturated; back off and
    retry, don't stack-trace."""


class _Waiter:
    __slots__ = ("event", "granted", "tenant")

    def __init__(self, tenant: str):
        self.event = threading.Event()
        self.granted = False
        self.tenant = tenant


def parse_weights(spec: str) -> dict[str, int]:
    """"tenantA=3,tenantB=1" -> {...}; unlisted tenants weigh 1."""
    out: dict[str, int] = {}
    for entry in (spec or "").split(","):
        name, sep, w = entry.partition("=")
        if not sep or not name.strip():
            continue
        try:
            out[name.strip()] = max(1, int(w))
        except ValueError:
            continue
    return out


class AdmissionController:
    def __init__(self, max_concurrency: int, queue_size: int = 256,
                 queue_timeout_s: float = 30.0,
                 weights: dict[str, int] | None = None,
                 enabled: bool = True):
        self.max_concurrency = max(1, int(max_concurrency))
        self.queue_size = max(0, int(queue_size))
        self.queue_timeout_s = float(queue_timeout_s)
        self.weights = dict(weights or {})
        self.enabled = enabled
        self._lock = threading.Lock()
        # free execution slots; invariant: active = max_concurrency -
        # len(_tokens) (a direct waiter handoff transfers a slot without
        # touching the pool, keeping both sides constant)
        self._tokens: list = [None] * self.max_concurrency
        self._queued = 0
        self._queues: dict[str, deque] = {}
        self._ring: list[str] = []
        self._credits: dict[str, int] = {}
        self._idx = 0
        self._tls = threading.local()

    # ---- public ------------------------------------------------------------

    @property
    def active(self) -> int:
        return self.max_concurrency - len(self._tokens)

    @property
    def queued(self) -> int:
        return self._queued

    def depth(self) -> int:
        """This thread's statement nesting depth (1 = top level)."""
        return getattr(self._tls, "depth", 0)

    @contextmanager
    def slot(self, tenant: str):
        d = getattr(self._tls, "depth", 0)
        self._tls.depth = d + 1
        try:
            if d > 0 or not self.enabled:
                yield
                return
            self._acquire(tenant or "default")
            try:
                yield
            finally:
                self._release()
        finally:
            self._tls.depth = d

    # ---- internals ---------------------------------------------------------

    def _weight(self, tenant: str) -> int:
        return self.weights.get(tenant, 1)

    def _acquire(self, tenant: str) -> None:
        # fast path: atomic slot grab when nobody is queued. The
        # _queued read is racy, but a request that slips past a
        # concurrently-enqueuing waiter grabbed a token that waiter
        # could not have been handed yet — fairness drift of at most
        # one request, never a lost slot.
        if self._queued == 0:
            try:
                self._tokens.pop()
            except IndexError:
                pass
            else:
                ADMISSION_EVENTS.inc(event="admit")
                return
        with self._lock:
            if self._queued == 0 and self._tokens:
                self._tokens.pop()
                ADMISSION_EVENTS.inc(event="admit")
                return
            if self._queued >= self.queue_size:
                ADMISSION_EVENTS.inc(event="reject_full", tenant=tenant)
                raise Overloaded(
                    f"admission queue full ({self._queued} waiting, "
                    f"{self.active} executing)")
            w = _Waiter(tenant)
            q = self._queues.get(tenant)
            if q is None:
                q = deque()
                self._queues[tenant] = q
                self._ring.append(tenant)
                self._credits.setdefault(tenant, self._weight(tenant))
            q.append(w)
            self._queued += 1
            ADMISSION_QUEUE_DEPTH.set(float(self._queued))
            ADMISSION_EVENTS.inc(event="queue", tenant=tenant)
        # close the lock-free release race: a token appended between our
        # fast-path check and the enqueue above would strand this waiter
        # until timeout — re-check the pool now that we are visible in
        # _queued (one of the two sides always sees the other)
        if self._tokens:
            self._rescue()
        t0 = time.perf_counter()
        from greptimedb_tpu.utils import deadline as dl

        try:
            # deadline/cancel-aware wait: a killed or expired query
            # leaves the queue typed instead of burning queue_timeout_s
            granted = dl.wait_event(w.event, self.queue_timeout_s,
                                    where="admission queue")
        except Unavailable:
            waited = time.perf_counter() - t0
            ADMISSION_WAIT_SECONDS.observe(waited)
            ledger.add("admission_wait_ms", waited * 1000.0)
            with self._lock:
                granted_in_race = w.granted
                if not granted_in_race:
                    q = self._queues.get(tenant)
                    if q is not None:
                        try:
                            q.remove(w)
                            self._queued -= 1
                            ADMISSION_QUEUE_DEPTH.set(float(self._queued))
                        except ValueError:
                            granted_in_race = w.granted
            if granted_in_race:
                # a slot was handed over in the race window: give it
                # back so the typed unwind cannot leak admission
                self._release()
            ADMISSION_EVENTS.inc(event="deadline", tenant=tenant)
            raise
        waited = time.perf_counter() - t0
        ADMISSION_WAIT_SECONDS.observe(waited)
        ledger.add("admission_wait_ms", waited * 1000.0)
        if granted:
            return
        with self._lock:
            if w.granted:  # granted in the race window after timeout
                return
            q = self._queues.get(tenant)
            if q is not None:
                try:
                    q.remove(w)
                    self._queued -= 1
                    ADMISSION_QUEUE_DEPTH.set(float(self._queued))
                except ValueError:
                    pass
        ADMISSION_EVENTS.inc(event="reject_timeout", tenant=tenant)
        raise Overloaded(
            f"query waited longer than {self.queue_timeout_s:g}s for "
            "admission")

    def _release(self) -> None:
        if self._queued:
            with self._lock:
                w = self._next_waiter()
                if w is not None:
                    # hand the slot over directly: the token pool is
                    # untouched, so `active` stays constant
                    w.granted = True
                    self._queued -= 1
                    ADMISSION_QUEUE_DEPTH.set(float(self._queued))
                    ADMISSION_EVENTS.inc(event="admit")
                    w.event.set()
                    return
        # nobody visibly queued: return the token lock-free, then
        # re-check — a waiter that enqueued between the read above and
        # the append is rescued under the lock instead of timing out
        self._tokens.append(None)
        if self._queued:
            self._rescue()

    def _rescue(self) -> None:
        """Match free tokens to queued waiters under the lock. Both
        lock-free halves (release's token append, a fresh waiter's
        enqueue) call this after publishing their side, which closes
        the lost-wakeup window in every interleaving."""
        with self._lock:
            while self._queued and self._tokens:
                w = self._next_waiter()
                if w is None:
                    break
                self._tokens.pop()
                w.granted = True
                self._queued -= 1
                ADMISSION_QUEUE_DEPTH.set(float(self._queued))
                ADMISSION_EVENTS.inc(event="admit")
                w.event.set()

    def _next_waiter(self):
        """Weighted round-robin pop (caller holds the lock): serve up to
        `weight` consecutive waiters per tenant before yielding the
        turn; tenants with drained queues leave the ring."""
        steps = 0
        while self._ring and steps <= 2 * len(self._ring) + 1:
            pos = self._idx % len(self._ring)
            t = self._ring[pos]
            q = self._queues.get(t)
            if not q:
                self._ring.pop(pos)
                self._queues.pop(t, None)
                self._credits.pop(t, None)
                continue
            if self._credits.get(t, 0) > 0:
                self._credits[t] -= 1
                w = q.popleft()
                if not q:
                    self._ring.pop(pos)
                    self._queues.pop(t, None)
                    self._credits.pop(t, None)
                return w
            self._credits[t] = self._weight(t)
            self._idx += 1
            steps += 1
        return None
