"""Cross-query batching: one device dispatch for a window of queries.

Under fleet-scale dashboard traffic the engine used to serialize 50
concurrent single-groupby queries into 50 kernel launches over the same
resident blocks. The batcher opens a short collection window when the
server is busy, groups admitted SELECTs by shape, and serves a group
with less work than member-by-member execution:

- **coalescing**: members whose statements are identical (same shape,
  same parameters, same db/timezone) share ONE execution — the common
  case for dashboard fan-out, trivially bit-for-bit.
- **vmapped dispatch**: members identical except for the values of
  *parameter conjuncts* — tag equalities (one or several: multi-tag
  selectors stack) and time-index comparisons (different windows stack
  via one scan covering the union, per-member masks) — execute as ONE
  `jax.vmap`'d kernel over a stacked parameter axis
  (query/vmapped.py). Each member's output is a slice of the [M, G, F]
  accumulator: separated by construction, no demux. Per (group, member)
  the kernel folds exactly the member's rows in the member's row order,
  and excluded rows land in the dead segment, so results are
  bit-for-bit identical to serial execution (tier-1 asserts this,
  including window-union and multi-tag members).
- **stacked dispatch** (fallback): when the vmapped path declines (a
  scan part spanning several device blocks, sparse group domains, host
  aggregates) and the members differ in a single tag equality, the
  group rewrites into one combined query — the selector tag becomes the
  leading group key and the predicate becomes `host IN (v1..vN)` — and
  demultiplexes the combined result, bit-for-bit as before.

Only aggregate shapes whose parity is provable batch (plain
sum/count/min/max/avg over columns, non-empty GROUP BY, a conjunctive
WHERE); everything else falls back to coalescing or per-member serial
execution inside the same admission slot. The collection window only
opens when other queries are in flight — an idle client never pays it.

Batched results carry a shared `encode_memo` dict so the protocol
servers' result encoders materialize the (identical) wire rows once
per group, not once per member.
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Optional

import numpy as np

from greptimedb_tpu.fault.retry import Cancelled, DeadlineExceeded
from greptimedb_tpu.query.result import QueryResult
from greptimedb_tpu.sql import ast
from greptimedb_tpu.utils import deadline as dl
from greptimedb_tpu.utils.metrics import (
    QUERY_BATCH_EVENTS,
    QUERY_BATCH_SIZE,
    VMAP_BATCH_WIDTH,
)

#: aggregate functions whose masked/stacked evaluation is exactly the
#: serial evaluation (order-insensitive, or identity-element exact;
#: first/last resolve by their companion timestamps in the vmapped
#: kernel and by ordinary serial evaluation in the IN-list rewrite)
SAFE_FUNCS = frozenset(
    {"sum", "count", "min", "max", "avg", "mean", "first", "last"})

BATCH_TAG = "__batch_tag"

#: time-index comparison operators that can become stacked parameters
_TS_PARAM_OPS = frozenset({"=", "<", "<=", ">", ">="})


def _replace_node(e, target, repl):
    """Rebuild `e` with the node `target` (by identity) replaced."""
    if e is target:
        return repl
    if isinstance(e, (list, tuple)):
        return type(e)(_replace_node(x, target, repl) for x in e)
    if dataclasses.is_dataclass(e) and not isinstance(e, type) \
            and not isinstance(e, ast.Statement):
        changes = {}
        for f in dataclasses.fields(e):
            v = getattr(e, f.name)
            if isinstance(v, (ast.Expr, list, tuple)) or (
                    dataclasses.is_dataclass(v)
                    and not isinstance(v, (type, ast.Statement))):
                nv = _replace_node(v, target, repl)
                if nv is not v:
                    changes[f.name] = nv
        return dataclasses.replace(e, **changes) if changes else e
    return e


def _conjuncts(e) -> list:
    from greptimedb_tpu.query.expr import split_conjuncts

    return split_conjuncts(e)


def _statement_errors() -> tuple:
    """Error classes that belong to ONE statement (plan validation,
    catalog lookups) — a vmapped-dispatch failure of this kind must not
    latch the path off for the whole process."""
    from greptimedb_tpu.catalog.catalog import CatalogError
    from greptimedb_tpu.query.planner import PlanError

    return (PlanError, CatalogError)


class BatchParam:
    """One parameter conjunct of a stack-eligible SELECT: the conjunct
    node (by identity, inside the statement's WHERE), the column it
    constrains, its kind ("tag" equality | "ts" comparison), and the
    normalized operator (column on the left)."""

    __slots__ = ("conjunct", "col", "kind", "op")

    def __init__(self, conjunct, col, kind, op):
        self.conjunct = conjunct
        self.col = col
        self.kind = kind
        self.op = op


class BatchShape:
    """Analysis of one batch-eligible SELECT: its parameter conjuncts,
    this statement's parameter values, and the statement with the
    parameter literals masked (the group key — members share it iff
    they differ ONLY in parameter values)."""

    __slots__ = ("params", "values", "masked")

    def __init__(self, params, values, masked):
        self.params = params  # tuple[BatchParam]
        self.values = values  # tuple of this statement's literal values
        self.masked = masked


def analyze(sel: ast.Select, info) -> Optional[BatchShape]:
    """None when the statement can't join a parameterized group (it may
    still coalesce with byte-identical statements)."""
    if (sel.joins or sel.ctes or sel.from_subquery is not None
            or sel.distinct or sel.having is not None or sel.order_by
            or sel.limit is not None or sel.offset
            or sel.align is not None or not sel.group_by
            or sel.where is None):
        return None
    from greptimedb_tpu.query import range_select as rs
    from greptimedb_tpu.query.expr import (
        _flip,
        collect_columns,
        has_aggregate,
    )
    from greptimedb_tpu.query.planner import _FUNC_CANON
    from greptimedb_tpu.query.window import select_has_window

    if rs.is_range_select(sel) or select_has_window(sel):
        return None
    n_aggs = 0
    for it in sel.items:
        e = it.expr
        if isinstance(e, ast.Star):
            return None
        if not has_aggregate(e):
            continue  # a group key expression: shared across members
        if not isinstance(e, ast.FuncCall) or e.distinct \
                or e.order_within is not None:
            return None
        func = _FUNC_CANON.get(e.name)
        if func not in SAFE_FUNCS:
            return None
        if len(e.args) == 1 and isinstance(e.args[0], ast.Star):
            if func != "count":
                return None
        elif len(e.args) != 1 or not isinstance(e.args[0], ast.Column):
            return None
        n_aggs += 1
    if n_aggs == 0:
        return None
    schema = info.schema
    tag_names = {c.name for c in schema.tag_columns}
    ts_name = schema.time_index.name
    # a selector tag must not feed the output relation: a tag that is
    # also a group key / projected column changes shape when batched.
    # (The time index IS typically a group key via date_bin — that's
    # fine: window parameters only mask rows, the bucket key decodes by
    # value.)
    used: set = set()
    for it in sel.items:
        collect_columns(it.expr, used)
    for g in sel.group_by:
        collect_columns(g, used)

    params: list[BatchParam] = []
    values: list = []
    for c in _conjuncts(sel.where):
        if not isinstance(c, ast.BinaryOp):
            continue
        col, lit, flipped = c.left, c.right, False
        if isinstance(col, ast.Literal) and isinstance(lit, ast.Column):
            col, lit, flipped = lit, col, True
        if not (isinstance(col, ast.Column) and isinstance(lit, ast.Literal)):
            continue
        if col.table not in (None, sel.table, sel.table_alias):
            continue
        op = _flip(c.op) if flipped else c.op
        if (op == "=" and col.name in tag_names
                and col.name not in used
                and isinstance(lit.value, str)):
            params.append(BatchParam(c, col.name, "tag", "="))
            values.append(lit.value)
        elif (op in _TS_PARAM_OPS and col.name == ts_name
                and isinstance(lit.value, (int, float, str))
                and not isinstance(lit.value, bool)):
            params.append(BatchParam(c, col.name, "ts", op))
            values.append(lit.value)
    if not params:
        return None
    masked_where = sel.where
    for i, p in enumerate(params):
        marker = ast.BinaryOp(p.op, ast.Column(p.col),
                              ast.Literal(("__gtpu_batch_param__", i)))
        masked_where = _replace_node(masked_where, p.conjunct, marker)
    masked = repr(dataclasses.replace(sel, where=masked_where))
    return BatchShape(tuple(params), tuple(values), masked)


def combined_select(base: ast.Select, shape: BatchShape,
                    values: list[str],
                    param: BatchParam) -> ast.Select:
    """The legacy stacked rewrite (single varying tag parameter):
    selector eq -> IN over every member value, selector tag prepended
    as the leading group key (leading so each member's groups come back
    as one contiguous, serial-ordered run) and appended to the
    projection for demux. `param` names the one VARYING tag parameter —
    the caller determines it, defaulting would silently rewrite the
    wrong conjunct on multi-param shapes."""
    p = param
    tagcol = ast.Column(p.col)
    in_list = ast.InList(tagcol, tuple(ast.Literal(v) for v in values))
    new_where = _replace_node(base.where, p.conjunct, in_list)
    items = list(base.items) + [ast.SelectItem(tagcol, alias=BATCH_TAG)]
    group_by = [tagcol] + list(base.group_by)
    return dataclasses.replace(base, items=items, group_by=group_by,
                               where=new_where)


def demux(combined: QueryResult, value: str) -> QueryResult:
    """One member's slice of the combined result, BATCH_TAG dropped.
    combined_select APPENDS its tag column, so the demux key is the
    LAST occurrence — a user column aliased __batch_tag sits earlier
    and must come back in the member's result, not be used as the key."""
    tag_idx = (len(combined.names) - 1
               - combined.names[::-1].index(BATCH_TAG))
    tagcol = np.asarray(combined.columns[tag_idx])
    idx = np.flatnonzero(tagcol == value)
    keep = [i for i in range(len(combined.names)) if i != tag_idx]
    return QueryResult(
        [combined.names[i] for i in keep],
        [combined.dtypes[i] for i in keep],
        [np.asarray(combined.columns[i])[idx] for i in keep])


#: by_value sentinel: this member executes its own statement on its own
#: thread (the group could not be served batched, and leader-serial
#: execution would park N-1 admitted threads behind one — pre-batching
#: traffic ran these queries in parallel and still must)
SELF_EXECUTE = object()


class _Relay:
    """Fallback coalescing for one distinct non-leader value: the first
    member with the value executes and publishes here; its duplicates
    wait on it instead of re-running the same query."""

    __slots__ = ("event", "result", "error", "path")

    def __init__(self):
        self.event = threading.Event()
        self.result = None
        self.error = None
        self.path = None


class _Member:
    __slots__ = ("event", "result", "error", "path", "value", "sel",
                 "self_execute", "relay", "wait_relay", "abandoned")

    def __init__(self, value, sel):
        self.event = threading.Event()
        self.result = None
        self.error = None
        self.path = None
        self.value = value
        self.sel = sel
        self.self_execute = False
        self.relay = None       # publish my self-execution here
        self.wait_relay = None  # ride another member's self-execution
        self.abandoned = False  # deadline/cancel drop-out (no relay duty)


class _Group:
    __slots__ = ("members", "closed", "shape", "sel", "value")

    def __init__(self, sel, shape):
        self.members: list[_Member] = []
        self.closed = False
        self.shape = shape
        self.sel = sel
        self.value = shape.values if shape is not None else None


def _copy(r: QueryResult, memo: Optional[dict] = None) -> QueryResult:
    # column arrays shared (read-only downstream); the container is
    # per-caller so one member's post-processing can't surprise another.
    # `memo` is the group-shared encode cache: every copy of one
    # execution's result points at the same dict, so the HTTP/MySQL
    # encoders materialize the wire rows once per group.
    out = QueryResult(list(r.names), list(r.dtypes), list(r.columns))
    if memo is not None:
        out.encode_memo = memo
    return out


class QueryBatcher:
    def __init__(self, window_s: float = 0.002, max_queries: int = 64,
                 max_rows: int = 4 << 20, enabled: bool = True,
                 vmap: bool = True):
        self.window_s = window_s
        self.max_queries = max_queries
        self.max_rows = max_rows
        self.enabled = enabled
        self.vmap = vmap
        #: runtime-failure latch (mirrors the fused kernel's
        #: _FUSED_DISABLED): one unexpected vmapped-dispatch failure —
        #: compile error, device OOM — routes this and every later
        #: group to the stacked/serial fallbacks instead of re-failing
        #: whole batches per window
        self._vmap_failed = False
        self._lock = threading.Lock()
        self._open: dict[tuple, _Group] = {}

    # ---- entry -------------------------------------------------------------

    def execute(self, qe, sel: ast.Select, info, ctx, busy: bool) -> QueryResult:
        """Join or lead a batch group for `sel`. `busy` gates the
        collection window: an idle server executes immediately."""
        if not busy and not self._open:
            # idle server with no leader collecting: no group could be
            # joined or led, so skip the analyze/repr bookkeeping
            # entirely (single-client traffic must not pay batching
            # overhead on the parse/plan hot path). Racy read on
            # purpose: a group opening concurrently only costs a missed
            # join, never correctness.
            return qe._select_table(sel, info, ctx)
        shape = analyze(sel, info)
        gkey = (info.db, info.table_id, ctx.timezone,
                shape.masked if shape is not None else repr(sel))
        with self._lock:
            g = self._open.get(gkey)
            if g is not None and not g.closed \
                    and len(g.members) < self.max_queries:
                m = _Member(shape.values if shape is not None else None,
                            sel)
                g.members.append(m)
                QUERY_BATCH_EVENTS.inc(event="join")
                joined = True
            else:
                g = _Group(sel, shape)
                self._open[gkey] = g
                joined = False
        if joined:
            return self._await(qe, m, info, ctx)
        interrupted = None
        try:
            if busy and self.window_s > 0:
                # deadline-aware: an expired leader aborts the window
                # instead of burning its last budget collecting
                dl.sleep(self.window_s, "batch window")
        except BaseException as e:  # noqa: BLE001 — members must not hang
            interrupted = e
        finally:
            with self._lock:
                g.closed = True
                if self._open.get(gkey) is g:
                    del self._open[gkey]
        if interrupted is not None:
            typed = isinstance(interrupted, (DeadlineExceeded, Cancelled))
            for m in g.members:
                if typed:
                    # the leader's own deadline/cancel: members
                    # re-execute for themselves (see _lead)
                    m.self_execute = True
                else:
                    m.error = interrupted
                m.event.set()
            raise interrupted
        return self._lead(qe, g, info, ctx)

    def _await(self, qe, m: _Member, info, ctx) -> QueryResult:
        # wait as long as the leader runs: its execution IS this
        # member's execution, so a slow leader means a slow query, not
        # an overload (the leader sets every member's event in a
        # finally on ALL exit paths — see execute()/_lead). The
        # periodic wakeup exists only so a wedged process shows a live
        # thread doing something diagnosable instead of parking forever.
        try:
            while not dl.wait_event(m.event, 30.0, where="batch member"):
                pass
        except (DeadlineExceeded, Cancelled):
            # drop out of the group: the leader continues for everyone
            # else. The abandon is claimed under the batcher lock so it
            # is atomic against the leader's relay assignment — a
            # member the leader already tasked with relay duty (event
            # set) must stay and serve it: its execution unwinds typed
            # below and publishes the error, and duplicates recover by
            # self-executing.
            with self._lock:
                if not m.event.is_set():
                    m.abandoned = True
                    raise
            if not m.self_execute:
                raise
        if m.error is not None:
            raise m.error
        if m.self_execute:
            # the group fell back without a batched execution for this
            # member's parameters: run it here, in parallel with the
            # other members, exactly as un-batched traffic would —
            # publishing to the relay so duplicates don't re-run it
            try:
                res = qe._select_table(m.sel, info, ctx)
            except BaseException as e:
                if m.relay is not None:
                    m.relay.error = e
                    m.relay.event.set()
                raise
            if m.relay is not None:
                res.encode_memo = {}
                m.relay.result = res
                m.relay.path = qe.executor.last_path
                m.relay.event.set()
                return _copy(res, res.encode_memo)
            return res
        if m.wait_relay is not None:
            r = m.wait_relay
            while not dl.wait_event(r.event, 30.0, where="batch relay"):
                pass
            if r.error is not None:
                if isinstance(r.error, (DeadlineExceeded, Cancelled)):
                    # the relay executor hit ITS deadline/cancel, not
                    # ours (our own token raised from the wait above):
                    # serve this member's statement directly
                    return qe._select_table(m.sel, info, ctx)
                raise r.error
            qe.executor.last_path = r.path
            return _copy(r.result, r.result.encode_memo)
        qe.executor.last_path = m.path
        return _copy(m.result, getattr(m.result, "encode_memo", None))

    # ---- leader ------------------------------------------------------------

    def _lead(self, qe, g: _Group, info, ctx) -> QueryResult:
        run = lambda s: qe._select_table(s, info, ctx)  # noqa: E731
        if not g.members:
            return run(g.sel)
        QUERY_BATCH_SIZE.observe(float(1 + len(g.members)))
        try:
            by_value: dict = {}
            if g.shape is None:
                # every member is statement-identical: one execution
                res = run(g.sel)
                res.encode_memo = {}
                path = qe.executor.last_path
                QUERY_BATCH_EVENTS.inc(float(len(g.members)),
                                       event="coalesced")
                for m in g.members:
                    m.result, m.path = res, path
                    m.event.set()
                return _copy(res, res.encode_memo)
            order: list = [g.value]
            for m in g.members:
                if m.value not in order:
                    order.append(m.value)
            if len(order) == 1:
                res = run(g.sel)
                path = qe.executor.last_path
                by_value[g.value] = (res, path)
                QUERY_BATCH_EVENTS.inc(float(len(g.members)),
                                       event="coalesced")
            else:
                by_value = self._execute_group(qe, g, info, ctx, order,
                                               run)
            for v, entry in by_value.items():
                if entry is not SELF_EXECUTE:
                    entry[0].encode_memo = {}
            relays: dict = {}
            # assignment runs under the batcher lock so it is atomic
            # against a member abandoning on deadline/cancel: an
            # abandoned member never receives relay duty, and a member
            # that sees its event set always serves the duty it got
            with self._lock:
                for m in g.members:
                    entry = by_value[m.value]
                    if entry is SELF_EXECUTE:
                        r = relays.get(m.value)
                        if r is None and not m.abandoned:
                            # first live member with this value executes
                            # for all its duplicates (one execution per
                            # distinct value, like the old leader-serial
                            # fallback — but in parallel across values)
                            relays[m.value] = m.relay = _Relay()
                            m.self_execute = True
                        elif r is not None:
                            m.wait_relay = r
                    else:
                        m.result, m.path = entry
                    m.event.set()
            res, path = by_value[g.value]  # the leader always executes
            qe.executor.last_path = path
            return _copy(res, res.encode_memo)
        except (DeadlineExceeded, Cancelled):
            # the LEADER's deadline/cancel must not fail the other
            # members (their deadlines are their own): every unserved
            # member re-executes its statement on its own thread
            with self._lock:
                for m in g.members:
                    if not m.event.is_set():
                        m.self_execute = True
                        m.event.set()
            raise
        except BaseException as e:
            for m in g.members:
                if not m.event.is_set():
                    m.error = e
                    m.event.set()
            raise

    def _execute_group(self, qe, g: _Group, info, ctx, order, run) -> dict:
        """Execute one multi-member group: vmapped stacked axis first,
        the legacy IN-list rewrite for single-tag shapes it declines,
        serial per distinct member as the last resort."""
        by_value: dict = {}
        shape = g.shape
        if self.vmap and not self._vmap_failed \
                and self._stack_ok(qe, info):
            from greptimedb_tpu.query.vmapped import (
                VmapIneligible,
                run_vmapped,
            )

            from greptimedb_tpu.fault import FaultError, Unavailable

            try:
                results = run_vmapped(qe.executor, g.sel, info,
                                      shape.params, order)
            except VmapIneligible:
                pass
            except (Unavailable, FaultError):
                # typed, transient (region unavailable, chaos seam):
                # the fallbacks reproduce the real per-member error or
                # ride a retry — no reason to disable the path forever
                pass
            except _statement_errors():
                # statement-scoped (a DDL race invalidating the plan, a
                # bad literal): the member's own serial run surfaces the
                # same error; the NEXT group is healthy — don't latch
                pass
            except Exception:  # noqa: BLE001 — members must not inherit
                # a batched-dispatch infra failure (compile error,
                # device OOM) their serial runs would not hit; latch,
                # degrade, and let the fallbacks serve the group
                self._vmap_failed = True
                QUERY_BATCH_EVENTS.inc(event="vmapped_failed")
                import logging

                logging.getLogger("greptimedb_tpu.batcher").exception(
                    "vmapped dispatch failed; latching fallback")
            else:
                path = qe.executor.last_path or "dense_vmapped"
                for v, res in zip(order, results):
                    by_value[v] = (res, path)
                QUERY_BATCH_EVENTS.inc(float(len(order)), event="vmapped")
                VMAP_BATCH_WIDTH.observe(float(len(order)))
                return by_value
        # IN-list rewrite fallback: only one parameter actually varies
        # across the members and it is a tag equality (the constant
        # window/tag conjuncts stay literal in the leader's statement)
        varying = [j for j in range(len(shape.params))
                   if len({v[j] for v in order}) > 1]
        single_tag = (len(varying) == 1
                      and shape.params[varying[0]].kind == "tag")
        if single_tag and self._stack_ok(qe, info):
            j = varying[0]
            vals = sorted({v[j] for v in order})
            combined = combined_select(g.sel, shape, vals,
                                       param=shape.params[j])
            full = run(combined)
            path = (qe.executor.last_path or "") + "+stacked"
            for v in order:
                by_value[v] = (demux(full, v[j]), path)
            QUERY_BATCH_EVENTS.inc(float(len(order)), event="stacked")
            return by_value
        # vmapped declined and the IN-list rewrite doesn't cover the
        # shape (or the scan is too big to stack safely): the leader
        # executes ITS statement (members sharing its parameters still
        # coalesce onto it); everyone else self-executes on their own
        # thread — pre-batching traffic ran these distinct queries in
        # parallel, and a leader-serial loop would park N-1 admitted
        # threads behind one
        by_value[g.value] = (run(g.sel), qe.executor.last_path)
        for v in order:
            if v != g.value:
                by_value[v] = SELF_EXECUTE
        QUERY_BATCH_EVENTS.inc(float(len(order)), event="serial_fallback")
        return by_value

    def _stack_ok(self, qe, info) -> bool:
        """Stacked parity needs the whole scan in one kernel dispatch:
        estimate rows from region metadata; routers/remote engines
        can't say, so they stack only when no bound is configured."""
        if self.max_rows <= 0:
            return True
        est = 0
        for rid in info.region_ids:
            try:
                region = qe.region_engine.region(rid)
            except Exception:  # noqa: BLE001 — remote/unrouted region
                return False
            num = getattr(region, "num_sst_rows", None)
            if num is None:
                return False
            est += int(num)
            mem = getattr(region, "memtable", None)
            if mem is not None:
                est += int(getattr(mem, "bytes_estimate", 0) // 64)
        return est <= self.max_rows
