"""Cross-query batching: one device dispatch for a window of queries.

Under fleet-scale dashboard traffic the engine used to serialize 50
concurrent single-groupby queries into 50 kernel launches over the same
resident blocks. The batcher opens a short collection window when the
server is busy, groups admitted SELECTs by shape, and serves a group
with less work than member-by-member execution:

- **coalescing**: members whose statements are identical (same shape,
  same parameters, same db/timezone) share ONE execution — the common
  case for dashboard fan-out, trivially bit-for-bit.
- **stacked dispatch**: members identical except for the value of one
  tag-equality predicate (`... WHERE host = ? ...`) rewrite into a
  single combined query — the selector tag becomes the leading group
  key and the predicate becomes `host IN (v1..vN)` — so one stacked
  segment-aggregate dispatch over the shared scan computes every
  member's groups. Demultiplexing slices each member's rows back out
  of the combined result. Per (tag, bucket) group the kernel folds
  exactly the member's rows in the member's row order, and excluded
  rows contribute the exact additive/extremal identity, so results are
  bit-for-bit identical to serial execution (tier-1 asserts this).

Only aggregate shapes whose parity is provable stack (plain
sum/count/min/max/avg over columns, non-empty GROUP BY, a conjunctive
WHERE); everything else falls back to coalescing or per-member serial
execution inside the same admission slot. The collection window only
opens when other queries are in flight — an idle client never pays it.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Optional

import numpy as np

from greptimedb_tpu.query.result import QueryResult
from greptimedb_tpu.sql import ast
from greptimedb_tpu.utils.metrics import (
    QUERY_BATCH_EVENTS,
    QUERY_BATCH_SIZE,
)

#: aggregate functions whose masked/stacked evaluation is exactly the
#: serial evaluation (order-insensitive, or identity-element exact)
SAFE_FUNCS = frozenset(
    {"sum", "count", "min", "max", "avg", "mean"})

BATCH_TAG = "__batch_tag"


def _replace_node(e, target, repl):
    """Rebuild `e` with the node `target` (by identity) replaced."""
    if e is target:
        return repl
    if isinstance(e, (list, tuple)):
        return type(e)(_replace_node(x, target, repl) for x in e)
    if dataclasses.is_dataclass(e) and not isinstance(e, type) \
            and not isinstance(e, ast.Statement):
        changes = {}
        for f in dataclasses.fields(e):
            v = getattr(e, f.name)
            if isinstance(v, (ast.Expr, list, tuple)) or (
                    dataclasses.is_dataclass(v)
                    and not isinstance(v, (type, ast.Statement))):
                nv = _replace_node(v, target, repl)
                if nv is not v:
                    changes[f.name] = nv
        return dataclasses.replace(e, **changes) if changes else e
    return e


def _conjuncts(e) -> list:
    from greptimedb_tpu.query.expr import split_conjuncts

    return split_conjuncts(e)


class BatchShape:
    """Analysis of one stack-eligible SELECT: which tag selects the
    member, its value, and the statement with that value masked (the
    group key — members share it iff they differ ONLY in the value)."""

    __slots__ = ("tag", "value", "conjunct", "masked")

    def __init__(self, tag, value, conjunct, masked):
        self.tag = tag
        self.value = value
        self.conjunct = conjunct
        self.masked = masked


def analyze(sel: ast.Select, info) -> Optional[BatchShape]:
    """None when the statement can't join a stacked group (it may still
    coalesce with byte-identical statements)."""
    if (sel.joins or sel.ctes or sel.from_subquery is not None
            or sel.distinct or sel.having is not None or sel.order_by
            or sel.limit is not None or sel.offset
            or sel.align is not None or not sel.group_by
            or sel.where is None):
        return None
    from greptimedb_tpu.query import range_select as rs
    from greptimedb_tpu.query.expr import collect_columns, has_aggregate
    from greptimedb_tpu.query.planner import _FUNC_CANON
    from greptimedb_tpu.query.window import select_has_window

    if rs.is_range_select(sel) or select_has_window(sel):
        return None
    n_aggs = 0
    for it in sel.items:
        e = it.expr
        if isinstance(e, ast.Star):
            return None
        if not has_aggregate(e):
            continue  # a group key expression: shared across members
        if not isinstance(e, ast.FuncCall) or e.distinct \
                or e.order_within is not None:
            return None
        func = _FUNC_CANON.get(e.name)
        if func not in SAFE_FUNCS:
            return None
        if len(e.args) == 1 and isinstance(e.args[0], ast.Star):
            if func != "count":
                return None
        elif len(e.args) != 1 or not isinstance(e.args[0], ast.Column):
            return None
        n_aggs += 1
    if n_aggs == 0:
        return None
    schema = info.schema
    tag_names = {c.name for c in schema.tag_columns}
    # the selector must not feed the output relation: a tag that is
    # also a group key / projected column changes shape when batched
    used: set = set()
    for it in sel.items:
        collect_columns(it.expr, used)
    for g in sel.group_by:
        collect_columns(g, used)
    conj = _conjuncts(sel.where)
    selector = None
    for c in conj:
        if not (isinstance(c, ast.BinaryOp) and c.op == "="):
            continue
        col, lit = c.left, c.right
        if isinstance(col, ast.Literal) and isinstance(lit, ast.Column):
            col, lit = lit, col
        if not (isinstance(col, ast.Column) and isinstance(lit, ast.Literal)):
            continue
        if col.table not in (None, sel.table, sel.table_alias):
            continue
        if col.name in tag_names and col.name not in used \
                and isinstance(lit.value, str):
            selector = (c, col.name, lit.value)
            break
    if selector is None:
        return None
    conjunct, tag, value = selector
    marker = ast.BinaryOp("=", ast.Column(tag),
                          ast.Literal("__gtpu_batch_value__"))
    masked = repr(dataclasses.replace(
        sel, where=_replace_node(sel.where, conjunct, marker)))
    return BatchShape(tag, value, conjunct, masked)


def combined_select(base: ast.Select, shape: BatchShape,
                    values: list[str]) -> ast.Select:
    """The stacked rewrite: selector eq -> IN over every member value,
    selector tag prepended as the leading group key (leading so each
    member's groups come back as one contiguous, serial-ordered run)
    and appended to the projection for demux."""
    tagcol = ast.Column(shape.tag)
    in_list = ast.InList(tagcol, tuple(ast.Literal(v) for v in values))
    new_where = _replace_node(base.where, shape.conjunct, in_list)
    items = list(base.items) + [ast.SelectItem(tagcol, alias=BATCH_TAG)]
    group_by = [tagcol] + list(base.group_by)
    return dataclasses.replace(base, items=items, group_by=group_by,
                               where=new_where)


def demux(combined: QueryResult, value: str) -> QueryResult:
    """One member's slice of the combined result, BATCH_TAG dropped.
    combined_select APPENDS its tag column, so the demux key is the
    LAST occurrence — a user column aliased __batch_tag sits earlier
    and must come back in the member's result, not be used as the key."""
    tag_idx = (len(combined.names) - 1
               - combined.names[::-1].index(BATCH_TAG))
    tagcol = np.asarray(combined.columns[tag_idx])
    idx = np.flatnonzero(tagcol == value)
    keep = [i for i in range(len(combined.names)) if i != tag_idx]
    return QueryResult(
        [combined.names[i] for i in keep],
        [combined.dtypes[i] for i in keep],
        [np.asarray(combined.columns[i])[idx] for i in keep])


class _Member:
    __slots__ = ("event", "result", "error", "path", "value", "sel")

    def __init__(self, value, sel):
        self.event = threading.Event()
        self.result = None
        self.error = None
        self.path = None
        self.value = value
        self.sel = sel


class _Group:
    __slots__ = ("members", "closed", "shape", "sel", "value")

    def __init__(self, sel, shape):
        self.members: list[_Member] = []
        self.closed = False
        self.shape = shape
        self.sel = sel
        self.value = shape.value if shape is not None else None


def _copy(r: QueryResult) -> QueryResult:
    # column arrays shared (read-only downstream); the container is
    # per-caller so one member's post-processing can't surprise another
    return QueryResult(list(r.names), list(r.dtypes), list(r.columns))


class QueryBatcher:
    def __init__(self, window_s: float = 0.002, max_queries: int = 64,
                 max_rows: int = 4 << 20, enabled: bool = True):
        self.window_s = window_s
        self.max_queries = max_queries
        self.max_rows = max_rows
        self.enabled = enabled
        self._lock = threading.Lock()
        self._open: dict[tuple, _Group] = {}

    # ---- entry -------------------------------------------------------------

    def execute(self, qe, sel: ast.Select, info, ctx, busy: bool) -> QueryResult:
        """Join or lead a batch group for `sel`. `busy` gates the
        collection window: an idle server executes immediately."""
        if not busy and not self._open:
            # idle server with no leader collecting: no group could be
            # joined or led, so skip the analyze/repr bookkeeping
            # entirely (single-client traffic must not pay batching
            # overhead on the parse/plan hot path). Racy read on
            # purpose: a group opening concurrently only costs a missed
            # join, never correctness.
            return qe._select_table(sel, info, ctx)
        shape = analyze(sel, info)
        gkey = (info.db, info.table_id, ctx.timezone,
                shape.masked if shape is not None else repr(sel))
        with self._lock:
            g = self._open.get(gkey)
            if g is not None and not g.closed \
                    and len(g.members) < self.max_queries:
                m = _Member(shape.value if shape is not None else None, sel)
                g.members.append(m)
                QUERY_BATCH_EVENTS.inc(event="join")
                joined = True
            else:
                g = _Group(sel, shape)
                self._open[gkey] = g
                joined = False
        if joined:
            return self._await(qe, m)
        interrupted = None
        try:
            if busy and self.window_s > 0:
                time.sleep(self.window_s)
        except BaseException as e:  # noqa: BLE001 — members must not hang
            interrupted = e
        finally:
            with self._lock:
                g.closed = True
                if self._open.get(gkey) is g:
                    del self._open[gkey]
        if interrupted is not None:
            for m in g.members:
                m.error = interrupted
                m.event.set()
            raise interrupted
        return self._lead(qe, g, info, ctx)

    def _await(self, qe, m: _Member) -> QueryResult:
        # wait as long as the leader runs: its execution IS this
        # member's execution, so a slow leader means a slow query, not
        # an overload (the leader sets every member's event in a
        # finally on ALL exit paths — see execute()/_lead). The
        # periodic wakeup exists only so a wedged process shows a live
        # thread doing something diagnosable instead of parking forever.
        while not m.event.wait(30.0):
            pass
        if m.error is not None:
            raise m.error
        qe.executor.last_path = m.path
        return _copy(m.result)

    # ---- leader ------------------------------------------------------------

    def _lead(self, qe, g: _Group, info, ctx) -> QueryResult:
        run = lambda s: qe._select_table(s, info, ctx)  # noqa: E731
        if not g.members:
            return run(g.sel)
        QUERY_BATCH_SIZE.observe(float(1 + len(g.members)))
        try:
            by_value: dict = {}
            if g.shape is None:
                # every member is statement-identical: one execution
                res = run(g.sel)
                path = qe.executor.last_path
                QUERY_BATCH_EVENTS.inc(float(len(g.members)),
                                       event="coalesced")
                for m in g.members:
                    m.result, m.path = res, path
                    m.event.set()
                return _copy(res)
            order: list = [g.value]
            for m in g.members:
                if m.value not in order:
                    order.append(m.value)
            if len(order) == 1:
                res = run(g.sel)
                path = qe.executor.last_path
                by_value[g.value] = (res, path)
                QUERY_BATCH_EVENTS.inc(float(len(g.members)),
                                       event="coalesced")
            elif self._stack_ok(qe, info):
                combined = combined_select(g.sel, g.shape, sorted(order))
                full = run(combined)
                path = (qe.executor.last_path or "") + "+stacked"
                for v in order:
                    by_value[v] = (demux(full, v), path)
                QUERY_BATCH_EVENTS.inc(float(len(order)), event="stacked")
            else:
                # too big to stack safely: serial per distinct value,
                # duplicates still coalesce
                for v in order:
                    one = g.sel if v == g.value else _replace_node(
                        g.sel, g.shape.conjunct,
                        ast.BinaryOp("=", ast.Column(g.shape.tag),
                                     ast.Literal(v)))
                    by_value[v] = (run(one), qe.executor.last_path)
                QUERY_BATCH_EVENTS.inc(float(len(order)),
                                       event="serial_fallback")
            for m in g.members:
                m.result, m.path = by_value[m.value]
                m.event.set()
            res, path = by_value[g.value]
            qe.executor.last_path = path
            return _copy(res)
        except BaseException as e:
            for m in g.members:
                if not m.event.is_set():
                    m.error = e
                    m.event.set()
            raise

    def _stack_ok(self, qe, info) -> bool:
        """Stacked parity needs the whole scan in one kernel dispatch:
        estimate rows from region metadata; routers/remote engines
        can't say, so they stack only when no bound is configured."""
        if self.max_rows <= 0:
            return True
        est = 0
        for rid in info.region_ids:
            try:
                region = qe.region_engine.region(rid)
            except Exception:  # noqa: BLE001 — remote/unrouted region
                return False
            num = getattr(region, "num_sst_rows", None)
            if num is None:
                return False
            est += int(num)
            mem = getattr(region, "memtable", None)
            if mem is not None:
                est += int(getattr(mem, "bytes_estimate", 0) // 64)
        return est <= self.max_rows
