"""Bounded result-encode pool: serving I/O off the engine threads.

The 50-client qps bench is parse/JSON-bound on host threads: every
connection thread that just finished executing re-enters the GIL to
materialize Python row objects and JSON-encode them, convoying with the
threads still executing queries. The pool bounds that contention:

- the admission slot is released at *execute-done* (the engine holds it
  only inside `execute_sql`), so serialization never occupies an
  execution slot;
- at most `workers` serializations run at once — the other request
  threads park on a future (releasing the GIL) instead of thrashing it;
- the encoders themselves are columnar (servers/encode.py): numpy
  C-loop casts and a single C `json.dumps`, no per-value Python
  sanitization, and batched results share one materialization through
  their group `encode_memo`;
- process mode moves the serialization into spawn-mode worker processes
  for a true GIL escape. It is selected PER RESULT by measured size
  (`process_mode="auto"`, the default): results at or above
  `process_min_rows` rows pay the pickle round trip to escape the GIL,
  dashboard-sized results keep the thread pool (handoff to a process
  costs more than their serialization). `process_mode="on"` pins every
  offload to the process pool (the legacy [concurrency]
  encode_process_pool=true behavior), `"off"` disables it — the A/B
  knob (GTPU_ENCODE_PROCESS_MODE).

Saturation degrades, never drops: when every worker is busy and the
queue is full, the request thread encodes inline (the pre-pool
behavior), counted as `encode_pool_events_total{event="inline"}`.
"""

from __future__ import annotations

import threading
from concurrent.futures import ProcessPoolExecutor, ThreadPoolExecutor
from typing import Optional

from greptimedb_tpu.utils import deadline
from greptimedb_tpu.utils.metrics import (
    ENCODE_POOL_EVENTS,
    ENCODE_POOL_QUEUE_DEPTH,
)


def _auto_workers() -> int:
    import os

    return max(2, min(8, (os.cpu_count() or 4) // 2))


def _worker_watchdog(parent: int) -> None:
    import os
    import time

    while True:
        time.sleep(5.0)
        if os.getppid() != parent:
            # the serving process died without reclaiming us (SIGKILL:
            # shutdown() never ran, the call-queue read blocks forever).
            # An orphan worker holds attach flocks on the shm fabric and
            # result arena, pinning segments no live frontend uses —
            # exit and let the kernel release them
            os._exit(0)


def _worker_init() -> None:
    import os

    t = threading.Thread(target=_worker_watchdog, args=(os.getppid(),),
                         daemon=True, name="gtpu-encode-watchdog")
    t.start()


class EncodePool:
    def __init__(self, workers: int = 0, queue_size: int = 64,
                 process: bool = False, enabled: bool = True,
                 min_rows: int = 256, process_mode: Optional[str] = None,
                 process_min_rows: int = 100_000):
        self.workers = workers if workers > 0 else _auto_workers()
        self.queue_size = max(1, int(queue_size))
        # process_mode supersedes the boolean `process` (kept for
        # back-compat: True maps to "on")
        if process_mode is None:
            process_mode = "on" if process else "auto"
        process_mode = str(process_mode).strip().lower()
        if process_mode not in ("auto", "on", "off"):
            # fail loudly at plane construction: a typo'd TOML value
            # silently pinning thread mode would make the A/B knob
            # measure nothing
            raise ValueError(
                f"encode_process_mode must be auto|on|off, "
                f"got {process_mode!r}")
        self.process_mode = process_mode
        self.process_min_rows = max(0, int(process_min_rows))
        self.enabled = enabled
        self.min_rows = max(0, int(min_rows))
        self._lock = threading.Lock()
        self._thread_executor = None
        self._process_executor = None
        self._inflight = 0

    # ---- lifecycle ---------------------------------------------------------

    def _want_process(self, cost_rows: Optional[int]) -> bool:
        """Per-result routing: is THIS serialization big enough that a
        spawn-mode worker (pickle round trip included) beats fighting
        the request threads for the GIL?"""
        if self.process_mode == "on":
            return True
        if self.process_mode != "auto":
            return False
        return cost_rows is not None and cost_rows >= self.process_min_rows

    def _pool(self, process: bool):
        """Lazy executor construction: servers that never serve a query
        (storage-only datanodes) must not spawn encode workers, and the
        process pool only exists once a result actually routed to it."""
        import weakref

        with self._lock:
            if process:
                if self._process_executor is None:
                    import multiprocessing

                    # spawn, not fork: the serving process has live JAX
                    # runtime threads a fork would copy mid-lock
                    self._process_executor = ProcessPoolExecutor(
                        max_workers=self.workers,
                        mp_context=multiprocessing.get_context("spawn"),
                        initializer=_worker_init)
                    # a discarded plane (tests, embedded engines) must
                    # not leak idle workers until interpreter exit
                    weakref.finalize(self, self._process_executor.shutdown,
                                     wait=False)
                return self._process_executor
            if self._thread_executor is None:
                self._thread_executor = ThreadPoolExecutor(
                    max_workers=self.workers,
                    thread_name_prefix="gtpu-encode")
                weakref.finalize(self, self._thread_executor.shutdown,
                                 wait=False)
            return self._thread_executor

    def shutdown(self) -> None:
        with self._lock:
            pools = (self._thread_executor, self._process_executor)
            self._thread_executor = self._process_executor = None
        for ex in pools:
            if ex is not None:
                ex.shutdown(wait=False)

    # ---- entry -------------------------------------------------------------

    def run(self, fn, *args, cost_rows: Optional[int] = None,
            shm_result: bool = False):
        """Run `fn(*args)` on a pool worker and wait for the bytes; the
        calling request thread sleeps on the future (GIL released)
        instead of competing for it. Falls back to inline encoding when
        the pool is disabled or saturated — output is byte-identical
        either way (same encoder function). `cost_rows` gates the
        handoff twice: results under `min_rows` encode inline (handoff
        costs more than dashboard-sized serialization), and results at
        or above `process_min_rows` escape to the process pool in auto
        mode (measured size picks the executor, not a static flag).

        With the serving fabric on, process-mode workers hand bytes
        payloads back through the shared-memory result arena instead of
        the executor's pickle queue; `shm_result=True` callers (the
        HTTP writer) may receive a zero-copy `ShmPayload` view over the
        segment, everyone else gets plain bytes copied out of it."""
        if not self.enabled:
            return fn(*args)
        if cost_rows is not None and cost_rows < self.min_rows:
            ENCODE_POOL_EVENTS.inc(event="small_inline")
            return fn(*args)
        process = self._want_process(cost_rows)
        shm_results = None
        if process:
            from greptimedb_tpu.shm import results as _sr

            if _sr.get_arena() is not None:
                shm_results = _sr
        with self._lock:
            if self._inflight >= self.queue_size:
                saturated = True
            else:
                saturated = False
                self._inflight += 1
                ENCODE_POOL_QUEUE_DEPTH.set(float(self._inflight))
        if saturated:
            ENCODE_POOL_EVENTS.inc(event="inline")
            return fn(*args)
        try:
            try:
                if shm_results is not None:
                    fut = self._pool(process).submit(
                        shm_results.shm_encode, fn, *args)
                else:
                    fut = self._pool(process).submit(fn, *args)
            except RuntimeError:
                # executor torn down concurrently (submit after
                # shutdown): the request still gets its bytes. Errors
                # raised by the encoder itself propagate from
                # fut.result() below — they must NOT be retried inline
                ENCODE_POOL_EVENTS.inc(event="inline")
                return fn(*args)
            ENCODE_POOL_EVENTS.inc(
                event="offload_process" if process else "offload")
            if process:
                if shm_results is not None:
                    # the worker timed its encode EXACTLY (shm_encode)
                    # and the metrics bridge folds it into /metrics —
                    # no parent-side round-trip approximation needed
                    out = deadline.wait_future(fut, "encode offload")
                    out = shm_results.resolve(out, fn, args)
                    if getattr(out, "is_shm_payload", False) \
                            and not shm_result:
                        data = bytes(out)
                        out.release()
                        return data
                    return out
                # fabric off: a worker PROCESS observes its metrics
                # into its own registry (lost to the parent's /metrics)
                # — time the round trip here so the encode split stays
                # visible, approximately
                import time

                from greptimedb_tpu.utils.metrics import ENCODE_SECONDS

                t0 = time.perf_counter()
                out = deadline.wait_future(fut, "encode offload")
                ENCODE_SECONDS.observe(time.perf_counter() - t0,
                                       protocol="process")
                return out
            return deadline.wait_future(fut, "encode offload")
        finally:
            with self._lock:
                self._inflight -= 1
                ENCODE_POOL_QUEUE_DEPTH.set(float(self._inflight))
