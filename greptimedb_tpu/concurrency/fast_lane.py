"""Parse-free serving fast lane: a text-keyed template cache in front
of the plan cache (ISSUE 14).

The shape-keyed plan cache (plan_cache.py) removed re-planning, but a
repeat-shape request still paid the full Python front-matter per hit:
`parse_sql` over the raw text, the AST normalization walk (a `repr` of
the whole statement tree), and the statement-dispatch scaffolding. At
benchmark concurrency that front-matter — not execution — dominates the
request (~115 ms wall for a 1.6 ms warm execute).

This module keys a cache on the statement TEXT instead: one C-speed
regex pass over the raw bytes lifts every literal out of the statement
(`scan`), producing a template string plus the literal values in text
order. A known template resolves directly to an entry holding the
already-validated plan-cache entry, a verified literal→parameter
binder, and the statement metadata the scaffolding needs — so a repeat
request goes straight from socket bytes to admission → bind → execute
→ encode with **zero parse_sql, zero AST, zero logical planning**.

Correctness is anchored in three mechanisms, not in trusting the
scanner:

- **probe-verified binders**: a first sighting only marks the template
  (a never-repeated ad-hoc statement must not pay the probe cost); the
  second sighting runs the full slow lane and builds the entry — each
  text slot is probed by splicing a magic literal into the raw text,
  re-parsing, and re-normalizing. A
  slot proves bindable only if the probe parses to the SAME shape with
  exactly that parameter changed; every other slot (LIMIT values,
  INTERVAL strings, GROUP BY ordinals — anything structural) is
  *pinned*: future requests must carry the identical value or they
  build their own entry. Parsing branches on token kinds, never literal
  values, so single-slot proofs compose to joint variation.
- **typed, counted fallbacks**: any scan ambiguity (comments, embedded
  quotes, non-SELECT verbs, multi-statement text, plugin rewrites,
  unseen templates, pending rollup-substitution probes) takes the slow
  lane and lands in gtpu_fast_lane_events_total{event="fallback"} with
  a reason label. Byte-for-byte response parity with the slow lane is
  the contract; the fast lane only serves what it can prove.
- **existing invalidation seams**: DDL through this engine and the
  remote-catalog watch fan out through `ConcurrencyPlane
  .invalidate_table`, and every hit re-validates the entry's TableInfo
  snapshot against the live catalog (the plan cache's safety net for
  DDL this process never saw). Rollup-substitution eligibility rides
  the plan-cache entry's version-stamped memo: the moment rollup state
  changes, hits fall back until the slow lane re-probes.

Concurrent identical requests single-flight: followers ride the
leader's in-flight execution (the cross-query batcher's coalescing
semantics, without the collection window).

With the serving fabric on (`[shm] fabric`), a template another process
on the box already validated is ADOPTED instead of re-proved: the
published payload carries only the value-independent structure — which
text slot binds which parameter position, which slots are pinned, the
plan shape — never the publisher's literal values. A first sighting
that finds a peer's payload skips both the second-sighting wait and the
O(slots) probe parses: it runs the slow lane once (stamping its own
plan + TableInfo) and assembles the entry from the adopted binder,
re-checking `_type_eq` per bound slot and shape equality, so a peer
running subtly different code degrades to the normal probe build.
"""

from __future__ import annotations

import hashlib
import pickle
import re
import threading
import time
from collections import OrderedDict
from typing import Optional

from greptimedb_tpu.concurrency.plan_cache import _info_matches, normalize
from greptimedb_tpu.fault.retry import Cancelled, DeadlineExceeded
from greptimedb_tpu.sql import ast
from greptimedb_tpu.utils import ledger, roofline
from greptimedb_tpu.utils.metrics import (
    FAST_LANE_EVENTS,
    QUERY_ACHIEVED_GBPS,
    SHM_FABRIC_EVENTS,
    STAGE_SECONDS,
    STMT_DURATION,
)

#: statements longer than this never template (bulk INSERT texts etc.
#: are gated out by the SELECT check anyway; this bounds scan cost)
_MAX_TEXT = 4096
_MAX_SLOTS = 64
#: per-template bound on pinned-value variants (distinct LIMITs,
#: intervals, ordinals) before the oldest is evicted
_MAX_VARIANTS = 8

# literal scanner: mirrors the SQL lexer's string/number token grammar
# exactly (sql/lexer.py _TOKEN_RE) so a captured slot is precisely one
# lexer token. Quoted identifiers are consumed (their digits are not
# literals); comment openers outside strings make the text ambiguous.
_SCAN_RE = re.compile(
    r"""(?P<s>'(?:[^']|'')*')
      | (?P<q>"(?:[^"]|"")*"|`(?:[^`]|``)*`)
      | (?P<c>--|/\*)
      | (?P<n>(?<![\w."'`])(?:\d+\.\d*|\.\d+|\d+)(?:[eE][+-]?\d+)?)
    """,
    re.VERBOSE,
)

#: template placeholders by slot kind; NUL cannot appear in valid SQL
#: (scan rejects texts containing it), so placeholders never collide
_PLACEHOLDER = {"s": "\x00s", "n": "\x00n"}

_SELECT_RE = re.compile(r"\s*select\b", re.IGNORECASE)


def scan(sql: str):
    """One regex pass over the statement text -> ((template, values,
    spans), None) or (None, fallback_reason). `values` carry the exact
    Python values `parse_sql` would produce for each literal token
    (int/float per the lexer's number rule, unescaped strings)."""
    if "\x00" in sql or len(sql) > _MAX_TEXT:
        return None, "ambiguous"
    parts: list = []
    values: list = []
    spans: list = []
    last = 0
    for m in _SCAN_RE.finditer(sql):
        g = m.lastgroup
        if g == "q":
            continue  # quoted identifier: stays in the template
        if g == "c":
            return None, "comment"
        text = m.group()
        if g == "s":
            inner = text[1:-1]
            if "'" in inner:
                # embedded ('' -escaped) quote: the template/value
                # round-trip is no longer trivially token-local
                return None, "quoted_literal"
            value: object = inner
        else:
            value = (float(text) if "." in text or "e" in text
                     or "E" in text else int(text))
        start = m.start()
        parts.append(sql[last:start])
        parts.append(_PLACEHOLDER[g])
        last = m.end()
        values.append(value)
        spans.append((start, last))
        if len(values) > _MAX_SLOTS:
            return None, "ambiguous"
    parts.append(sql[last:])
    template = "".join(parts)
    if not _SELECT_RE.match(template):
        return None, "non_select"
    body = template.rstrip()
    while body.endswith(";"):
        body = body[:-1].rstrip()
    if ";" in body:
        return None, "multi_statement"
    return (template, values, spans), None


def _type_eq(a, b) -> bool:
    """Type-strict value equality: 5 == 5.0 and True == 1 in Python,
    but they are different literals to the planner."""
    return type(a) is type(b) and a == b


class _Ticket:
    """Thread-local build ticket: armed by a fast-lane miss, stamped by
    the engine at the moment a statement executes a plan-cache plan."""

    __slots__ = ("stamps", "sel", "info", "entry")

    def __init__(self):
        self.stamps = 0
        self.sel = None
        self.info = None
        self.entry = None


class _Flight:
    """One in-flight execution concurrent identical requests ride."""

    __slots__ = ("event", "result", "error", "done")

    def __init__(self):
        self.event = threading.Event()
        self.result = None
        self.error = None
        self.done = False


class _BindFailed(Exception):
    pass


class _Entry:
    """One (template, pinned-values) variant: everything a repeat
    request needs to execute without parsing."""

    __slots__ = ("db", "table", "stmt", "info", "plan_entry", "binder",
                 "pinned", "needs_sub_check", "shape")

    def __init__(self, db, table, stmt, info, plan_entry, binder, pinned,
                 needs_sub_check, shape):
        self.db = db                  # resolved table database
        self.table = table            # resolved table name
        self.stmt = stmt              # template Select (permission check)
        self.info = info              # TableInfo snapshot at build
        self.plan_entry = plan_entry  # plan-cache _Entry (plan + slots)
        self.binder = binder          # per-param: ("s", slot) | ("c", v)
        self.pinned = pinned          # ((slot, type_name, value), ...)
        self.needs_sub_check = needs_sub_check
        self.shape = shape            # plan-cache shape key (re-arm check)

    def matches_pinned(self, values) -> bool:
        for i, tname, v in self.pinned:
            val = values[i]
            if type(val).__name__ != tname or val != v:
                return False
        return True

    def bind_params(self, values) -> tuple:
        return tuple(values[x] if tag == "s" else x
                     for tag, x in self.binder)


class _Template:
    __slots__ = ("entries", "uncacheable", "builds")

    def __init__(self):
        self.entries: list[_Entry] = []
        self.uncacheable = False
        self.builds = 0  # churn guard: rebuilds paid for this template


class FastLane:
    """Engine-wide template cache + the fast execution path.

    Locking: `_lock` guards the template LRU, `_flight_lock` the
    single-flight registry; neither is ever held across a parse, a
    bind, or an execution, and nothing else is acquired under them.
    """

    def __init__(self, capacity: int = 512, enabled: bool = True):
        self.capacity = int(capacity)
        self.enabled = bool(enabled) and self.capacity > 0
        self._lock = threading.Lock()
        self._templates: "OrderedDict[tuple, _Template]" = OrderedDict()
        self._flight_lock = threading.Lock()
        self._flights: dict = {}
        self._tls = threading.local()

    # ---- engine hook -------------------------------------------------------

    def note_plan_execution(self, sel, info, entry) -> None:
        """Called by QueryEngine._select_table right before it executes
        a plan-cache plan: stamps the build ticket a fast-lane miss
        armed on this thread (no-op otherwise)."""
        t = getattr(self._tls, "ticket", None)
        if t is not None:
            t.stamps += 1
            t.sel, t.info, t.entry = sel, info, entry

    # ---- entry point -------------------------------------------------------

    def execute(self, qe, sql: str, ctx) -> list:
        """Serve one statement: template hit -> the parse-free path;
        anything else -> the engine's slow lane (building a template on
        the way when the statement proves eligible)."""
        if not self.enabled:
            return qe._execute_sql_slow(sql, ctx)
        # run the plugin interceptor chain at most ONCE per statement
        # (auditing/rate-limit interceptors count invocations); the
        # slow lane is told via _intercepted that it already ran
        intercepted = False
        interceptors = getattr(qe.plugins, "_sql_interceptors", None)
        if interceptors:
            rewritten = qe.plugins.intercept_sql(sql, ctx)
            if rewritten != sql:
                # rewriting plugins: the text does not determine the
                # statement — slow lane on the rewritten text
                FAST_LANE_EVENTS.inc(event="fallback", reason="plugin")
                return qe._execute_sql_slow(rewritten, ctx,
                                            _intercepted=True)
            intercepted = True
        scanned, reason = scan(sql)
        if scanned is None:
            FAST_LANE_EVENTS.inc(event="fallback", reason=reason)
            return qe._execute_sql_slow(sql, ctx, _intercepted=intercepted)
        template, values, spans = scanned
        key = (ctx.db, template)
        with self._lock:
            tmpl = self._templates.get(key)
            if tmpl is not None:
                self._templates.move_to_end(key)
        if tmpl is None:
            adopted = self._fabric_probe(key)
            if adopted is not None and adopted.get("uncacheable"):
                # a peer already proved this template context-dependent
                self._mark_uncacheable(key, publish=False)
                FAST_LANE_EVENTS.inc(event="fallback",
                                     reason="uncacheable")
                return qe._execute_sql_slow(sql, ctx,
                                            _intercepted=intercepted)
            if adopted is not None:
                # a peer proved the template repeats AND published its
                # verified binder: build NOW (skipping the second-
                # sighting wait and the probe parses)
                return self._miss(qe, sql, ctx, key, values, spans,
                                  "miss", intercepted, adopted=adopted)
            # first sighting: just mark the template. Probing costs
            # O(slots) parses, which a never-repeated ad-hoc statement
            # must not pay — the SECOND sighting proves the template
            # repeats and builds the entry.
            FAST_LANE_EVENTS.inc(event="miss")
            ledger.cache_event("fast_lane", "miss")
            self._note_seen(key)
            return qe._execute_sql_slow(sql, ctx, _intercepted=intercepted)
        if tmpl.uncacheable:
            FAST_LANE_EVENTS.inc(event="fallback", reason="uncacheable")
            return qe._execute_sql_slow(sql, ctx, _intercepted=intercepted)
        entry = None
        with self._lock:
            for e in tmpl.entries:
                if e.matches_pinned(values):
                    entry = e
                    break
        if entry is None:
            # seen template, no matching variant (second sighting, or a
            # different LIMIT / interval): build through the slow lane
            return self._miss(qe, sql, ctx, key, values, spans, "miss",
                              intercepted)
        return self._hit(qe, sql, ctx, key, entry, values, spans,
                         intercepted)

    def _note_seen(self, key) -> None:
        with self._lock:
            if key not in self._templates:
                self._templates[key] = _Template()
                while len(self._templates) > self.capacity:
                    self._templates.popitem(last=False)

    # ---- miss / build ------------------------------------------------------

    def _miss(self, qe, sql, ctx, key, values, spans, event: str,
              intercepted: bool = False, adopted: Optional[dict] = None) -> list:
        FAST_LANE_EVENTS.inc(event=event)
        if qe.concurrency.admission.depth() != 0:
            # nested statement (script, flow tick): serve it, but only
            # top-level statements build templates
            return qe._execute_sql_slow(sql, ctx, _intercepted=intercepted)
        ticket = _Ticket()
        self._tls.ticket = ticket
        try:
            # batching suppressed: a build run must stamp ITS OWN
            # statement's plan, not a batch leader's combined rewrite
            # (serial execution is the batcher's own fallback, so the
            # semantics are unchanged)
            with qe.concurrency.suppress_batching():
                results = qe._execute_sql_slow(sql, ctx,
                                               _intercepted=intercepted)
        finally:
            self._tls.ticket = None
        try:
            self._build(qe, sql, ctx, key, values, spans, ticket,
                        adopted=adopted)
        except Exception:  # noqa: BLE001 — a build bug must never fail serving
            self._mark_uncacheable(key)
        return results

    def _build(self, qe, sql, ctx, key, values, spans, ticket,
               adopted: Optional[dict] = None) -> None:
        """Probe-verify a literal->parameter binder and store the entry
        (see module docstring). Any doubt marks the template
        uncacheable — the slow lane stays authoritative."""
        if ticket.stamps != 1 or ticket.entry is None:
            # the statement did not execute exactly one plan-cache plan
            # (DDL, rollup substitution, batched leader, view, CTE, ...)
            self._mark_uncacheable(key)
            return
        stmts = qe._parse_cached(sql)
        if len(stmts) != 1 or stmts[0] != ticket.sel:
            # context-dependent rewriting (session funcs, folded
            # subqueries) — the text does not determine the plan
            self._mark_uncacheable(key)
            return
        sel, info, plan_entry = ticket.sel, ticket.info, ticket.entry
        shape0, params0 = normalize(sel)
        if len(plan_entry.slots) != len(params0):
            self._mark_uncacheable(key)
            return
        binder, pinned = self._adopt_binder(adopted, shape0, params0,
                                            values)
        if binder is None:
            binder, pinned = self._probe_binder(sql, spans, values,
                                                shape0, params0)
        from greptimedb_tpu.query.expr import has_aggregate

        entry = _Entry(
            db=info.db, table=info.name, stmt=sel, info=info,
            plan_entry=plan_entry, binder=tuple(binder),
            pinned=tuple(pinned),
            needs_sub_check=bool(sel.group_by
                                 or any(has_aggregate(it.expr)
                                        for it in sel.items)),
            shape=shape0)
        churned = False
        with self._lock:
            tmpl = self._templates.get(key)
            if tmpl is None:
                tmpl = _Template()
                self._templates[key] = tmpl
            if tmpl.uncacheable:
                return
            tmpl.builds += 1
            if tmpl.builds > 4 * _MAX_VARIANTS \
                    and len(tmpl.entries) >= _MAX_VARIANTS:
                # churn guard: the variant list is saturated yet builds
                # keep coming — a pinned slot is rotating per request
                # (ever-changing LIMIT / interval), so the per-request
                # probe rebuild costs more than the lane saves
                tmpl.uncacheable = True
                tmpl.entries = []
                churned = True
            else:
                tmpl.entries = [e for e in tmpl.entries
                                if e.pinned != entry.pinned]
                tmpl.entries.append(entry)
                if len(tmpl.entries) > _MAX_VARIANTS:
                    tmpl.entries.pop(0)
                self._templates.move_to_end(key)
                while len(self._templates) > self.capacity:
                    self._templates.popitem(last=False)
        if churned:
            self._fabric_publish_uncacheable(key)
            return
        if adopted is None:
            # locally proven binders are shared; adopted ones are
            # already published (by their prover)
            self._fabric_publish(key, entry)

    def _adopt_binder(self, adopted: Optional[dict], shape0, params0,
                      values):
        """Assemble (binder, pinned) from a peer's published structure
        — re-deriving every VALUE from this process's own parse, so the
        payload only steers which slot feeds which position. Returns
        (None, None) on any doubt; the caller probe-builds as usual."""
        if adopted is None:
            return None, None
        try:
            if adopted.get("shape") != shape0:
                return None, None
            bound_pairs = adopted["bound"]
            pinned_idx = adopted["pinned"]
            if len(bound_pairs) + len(pinned_idx) != len(values):
                return None, None
            binder: list = [("c", p) for p in params0]
            seen_slots: set = set()
            for pos, slot in bound_pairs:
                if not (0 <= pos < len(params0)) \
                        or not (0 <= slot < len(values)) \
                        or slot in seen_slots \
                        or not _type_eq(params0[pos], values[slot]):
                    return None, None
                binder[pos] = ("s", slot)
                seen_slots.add(slot)
            pinned: list = []
            for i in pinned_idx:
                if not (0 <= i < len(values)) or i in seen_slots:
                    return None, None
                seen_slots.add(i)
                pinned.append((i, type(values[i]).__name__, values[i]))
            SHM_FABRIC_EVENTS.inc(event="hit", kind="template")
            return binder, pinned
        except (KeyError, TypeError, ValueError):
            return None, None

    def _probe_binder(self, sql, spans, values, shape0, params0):
        """The original probe loop: prove each text slot bindable by
        splicing a magic literal and re-parsing (see module
        docstring)."""
        from greptimedb_tpu.sql import parse_sql

        binder: list = [("c", p) for p in params0]
        bound: set = set()
        pinned: list = []
        for i, ((a, b), val) in enumerate(zip(spans, values)):
            ok = False
            magic_val, magic_text = _magic(i, val, params0)
            try:
                # direct parse, NOT _parse_cached: probe texts are
                # one-shot and would evict useful statement-LRU entries
                ps = parse_sql(sql[:a] + magic_text + sql[b:])
                if len(ps) == 1 and isinstance(ps[0], ast.Select):
                    shape_i, params_i = normalize(ps[0])
                    if shape_i == shape0 and len(params_i) == len(params0):
                        diff = [j for j in range(len(params0))
                                if not _type_eq(params_i[j], params0[j])]
                        if (len(diff) == 1
                                and _type_eq(params_i[diff[0]], magic_val)
                                and diff[0] not in bound):
                            binder[diff[0]] = ("s", i)
                            bound.add(diff[0])
                            ok = True
            except Exception:  # noqa: BLE001 — unparsable probe: pin the slot
                ok = False
            if not ok:
                # structural / fragile slot: the value must match this
                # entry exactly, or the request builds its own variant
                pinned.append((i, type(val).__name__, val))
        return binder, pinned

    def _mark_uncacheable(self, key, publish: bool = True) -> None:
        with self._lock:
            tmpl = self._templates.get(key)
            if tmpl is None:
                tmpl = _Template()
                self._templates[key] = tmpl
                while len(self._templates) > self.capacity:
                    self._templates.popitem(last=False)
            tmpl.uncacheable = True
            tmpl.entries = []
        if publish:
            self._fabric_publish_uncacheable(key)

    # ---- fabric tier -------------------------------------------------------

    @staticmethod
    def _fabric_key(key: tuple) -> bytes:
        h = hashlib.blake2b(digest_size=16)
        for part in key:
            b = part.encode()
            h.update(len(b).to_bytes(4, "little"))
            h.update(b)
        return h.digest()

    def _fabric_probe(self, key: tuple) -> Optional[dict]:
        """First sighting of a template: fetch a peer's published
        structure (or its uncacheable verdict). None = nothing shared
        (or no fabric) — the normal second-sighting rule applies."""
        from greptimedb_tpu import shm
        from greptimedb_tpu.shm.fabric import FabricError

        fabric = shm.get_fabric()
        if fabric is None:
            return None
        try:
            blob = fabric.get("tpl", self._fabric_key(key))
        except (FabricError, OSError, ValueError):
            shm.detach()
            return None
        if blob is None:
            SHM_FABRIC_EVENTS.inc(event="miss", kind="template")
            return None
        try:
            payload = pickle.loads(blob)
        except Exception:  # noqa: BLE001 — a stale-code peer's blob
            return None
        if not isinstance(payload, dict):
            return None
        if not payload.get("uncacheable"):
            try:
                if payload.get("ver") != fabric.version(
                        payload["db"], payload["table"]):
                    # peer DDL since publish: the binder structure may
                    # describe a dead shape
                    SHM_FABRIC_EVENTS.inc(event="miss", kind="template")
                    return None
            except (FabricError, OSError, ValueError):
                shm.detach()
                return None
            except KeyError:
                return None
        return payload

    def _fabric_publish(self, key: tuple, entry: _Entry) -> None:
        """Share a locally proven binder — structure only, no literal
        values (adopters re-derive those from their own parse)."""
        from greptimedb_tpu import shm
        from greptimedb_tpu.shm.fabric import FabricError

        fabric = shm.get_fabric()
        if fabric is None:
            return
        payload = {
            "db": entry.db,
            "table": entry.table,
            "shape": entry.shape,
            "bound": [(pos, x) for pos, (tag, x)
                      in enumerate(entry.binder) if tag == "s"],
            "pinned": [i for (i, _t, _v) in entry.pinned],
        }
        try:
            payload["ver"] = fabric.version(entry.db, entry.table)
            blob = pickle.dumps(payload,
                                protocol=pickle.HIGHEST_PROTOCOL)
            if fabric.put("tpl", self._fabric_key(key), blob):
                SHM_FABRIC_EVENTS.inc(event="publish", kind="template")
        except (FabricError, OSError, ValueError):
            shm.detach()

    def _fabric_publish_uncacheable(self, key: tuple) -> None:
        from greptimedb_tpu import shm
        from greptimedb_tpu.shm.fabric import FabricError

        fabric = shm.get_fabric()
        if fabric is None:
            return
        try:
            blob = pickle.dumps({"uncacheable": True},
                                protocol=pickle.HIGHEST_PROTOCOL)
            if fabric.put("tpl", self._fabric_key(key), blob):
                SHM_FABRIC_EVENTS.inc(event="publish", kind="template")
        except (FabricError, OSError, ValueError):
            shm.detach()

    # ---- hit ---------------------------------------------------------------

    def _hit(self, qe, sql, ctx, key, entry, values, spans,
             intercepted: bool = False) -> list:
        try:
            info = qe.catalog.table(entry.db, entry.table)
            qe._ensure_open(info)
        except Exception:  # noqa: BLE001 — dropped table etc.: slow lane raises it
            self._drop_entry(key, entry)
            FAST_LANE_EVENTS.inc(event="invalidate")
            return qe._execute_sql_slow(sql, ctx, _intercepted=intercepted)
        if not _info_matches(entry.info, info):
            # DDL this process never executed (remote frontend's ALTER):
            # the snapshot comparison is the safety net, same as the
            # plan cache's — drop and rebuild through the slow lane
            self._drop_entry(key, entry)
            return self._miss(qe, sql, ctx, key, values, spans,
                              "invalidate", intercepted)
        if entry.needs_sub_check \
                and not entry.plan_entry.skip_substitution():
            # rollup state moved (or was never probed): only the slow
            # lane can decide substitution — serve through it, then
            # re-point the entry at the plan-cache entry it stamped
            FAST_LANE_EVENTS.inc(event="fallback", reason="substitution")
            return self._refresh_entry(qe, sql, ctx, entry, intercepted)
        params = entry.bind_params(values)
        FAST_LANE_EVENTS.inc(event="hit")
        ledger.cache_event("fast_lane", "hit")
        return self._run(qe, sql, ctx, key, entry, params, intercepted)

    def _refresh_entry(self, qe, sql, ctx, entry,
                       intercepted: bool = False) -> list:
        """Serve a pending-substitution statement through the slow lane
        and re-arm the template: the slow run re-probes and stamps a
        plan-cache entry for this shape — possibly a NEW object if the
        old one was LRU-evicted — and the binder survives the swap (it
        maps text slots to parameter POSITIONS, which depend only on
        the shape). Without this, eviction + a rollup-state bump would
        strand the template on the slow lane forever."""
        if qe.concurrency.admission.depth() != 0:
            return qe._execute_sql_slow(sql, ctx, _intercepted=intercepted)
        ticket = _Ticket()
        self._tls.ticket = ticket
        try:
            with qe.concurrency.suppress_batching():
                results = qe._execute_sql_slow(sql, ctx,
                                               _intercepted=intercepted)
        finally:
            self._tls.ticket = None
        try:
            if ticket.stamps == 1 and ticket.entry is not None \
                    and len(ticket.entry.slots) == len(entry.binder) \
                    and normalize(ticket.sel)[0] == entry.shape:
                # GIL-atomic re-point; racing readers see old or new,
                # both safe (old just falls back here again)
                entry.plan_entry = ticket.entry
                entry.info = ticket.info
        except Exception:  # noqa: BLE001 — refresh is best-effort
            pass
        return results

    def _run(self, qe, sql, ctx, key, entry, params,
             intercepted: bool = False) -> list:
        """The parse-free statement scaffold: everything the slow lane
        does per statement except parse/plan — plugin function scope,
        slow-query watch, admission, authorization, session timezone,
        statement metrics — then bind + execute."""
        from greptimedb_tpu.plugins import reset_active, set_active
        from greptimedb_tpu.query.expr import (
            reset_session_tz,
            set_session_tz,
        )
        from greptimedb_tpu.utils import slow_query, tracing

        token = set_active(qe.plugins)
        try:
            with slow_query.watch("sql", sql, ctx.db) as w:
                qe.executor.last_path = None
                with qe.concurrency.admission.slot(
                        qe.concurrency.tenant_of(ctx)):
                    qe.permission_checker.check(ctx.user, entry.stmt,
                                                ctx.db)
                    ctx.trace_id = tracing.set_trace(ctx.trace_id)
                    tz_token = set_session_tz(ctx.timezone
                                              or qe.default_timezone)
                    try:
                        # the same stmt span the slow lane opens per
                        # statement: warm traffic must not vanish from
                        # span-based trace tooling
                        with STMT_DURATION.time(stmt="Select"), \
                                tracing.span("stmt:Select"):
                            result = self._execute_shared(
                                qe, entry, params,
                                ctx.timezone or qe.default_timezone)
                    except _BindFailed:
                        # template drift the probes could not foresee:
                        # drop the entry, serve through the slow lane
                        # (re-entrant admission: the nested statement
                        # rides this slot)
                        self._drop_entry(key, entry)
                        FAST_LANE_EVENTS.inc(event="invalidate")
                        return qe._execute_sql_slow(
                            sql, ctx, _intercepted=intercepted)
                    except (DeadlineExceeded, Cancelled):
                        # the fast lane bypasses execute_statement, so
                        # the deadline event is stamped on the slow-
                        # query record here
                        from greptimedb_tpu.utils import deadline as dl

                        tok = dl.current()
                        w.deadline_event = (tok.kind
                                            if tok and tok.kind
                                            else "expired")
                        raise
                    finally:
                        reset_session_tz(tz_token)
                w.rows = result.num_rows
                w.execution_path = qe.executor.last_path
                return [result]
        finally:
            reset_active(token)

    def _execute_shared(self, qe, entry, params, tz):
        """Single-flight: concurrent identical (entry, params) requests
        share one bind+execute (the batcher's coalescing semantics for
        the fast lane — identical statements were the dominant batch
        shape, and the collection window is pure latency here). The
        session timezone is part of the key: naive string timestamp
        literals bind under it, so same-text requests from differently
        zoned sessions must not share an execution."""
        fkey = (id(entry), params, tz)
        with self._flight_lock:
            flight = self._flights.get(fkey)
            leader = flight is None
            if leader:
                flight = _Flight()
                self._flights[fkey] = flight
        if not leader:
            from greptimedb_tpu.utils import deadline as dl

            # a cancelled/expired follower unwinds typed; the leader
            # (and everyone else in the flight) keeps executing
            if dl.wait_event(flight.event, 30.0,
                             where="fast-lane single-flight") \
                    and flight.done:
                FAST_LANE_EVENTS.inc(event="coalesced")
                if flight.error is not None:
                    raise flight.error
                return flight.result
            return self._bind_execute(qe, entry, params)
        try:
            result = self._bind_execute(qe, entry, params)
            flight.result = result
            flight.done = True
            return result
        except BaseException as e:
            flight.error = e
            flight.done = True
            raise
        finally:
            with self._flight_lock:
                self._flights.pop(fkey, None)
            flight.event.set()

    def _bind_execute(self, qe, entry, params):
        from greptimedb_tpu.utils import deadline as dl

        dl.check("fast-lane bind")
        t0 = time.perf_counter()
        try:
            plan = qe.concurrency.plan_cache._bind(entry.plan_entry,
                                                   params)
        except Exception as e:
            raise _BindFailed(str(e)) from e
        STAGE_SECONDS.observe(time.perf_counter() - t0, stage="fast_bind")
        t1 = time.perf_counter()
        try:
            # the parse-free lane bypasses execute_statement, so the
            # roofline accountant folds here too — one observation per
            # materialization (coalesced followers share the leader's)
            with ledger.attach() as led:
                led0 = led.snapshot() if led is not None else {}
                try:
                    result = qe.executor.execute(plan)
                finally:
                    if led is not None:
                        d = ledger.diff(led0, led.snapshot())
                        rf = roofline.account(
                            d, duration_ms=(time.perf_counter() - t1) * 1e3)
                        if rf is not None:
                            QUERY_ACHIEVED_GBPS.observe(
                                rf["achieved_gbps"], stmt="Select")
        finally:
            STAGE_SECONDS.observe(time.perf_counter() - t1,
                                  stage="fast_execute")
        # batch-group style memo: coalesced followers and the encoder
        # share one row materialization / schema header
        result.encode_memo = {}
        return result

    # ---- invalidation ------------------------------------------------------

    def _drop_entry(self, key, entry) -> None:
        with self._lock:
            tmpl = self._templates.get(key)
            if tmpl is not None:
                tmpl.entries = [e for e in tmpl.entries if e is not entry]
                if not tmpl.entries and not tmpl.uncacheable:
                    self._templates.pop(key, None)

    def invalidate_table(self, db: Optional[str] = None,
                         name: Optional[str] = None) -> int:
        """Drop every entry whose resolved table matches (None widens,
        like the plan cache) — called through ConcurrencyPlane
        .invalidate_table, i.e. the same DDL/remote-catalog seams."""
        dropped = 0
        with self._lock:
            doomed_keys = []
            for key, tmpl in self._templates.items():
                if db is None and name is None:
                    doomed_keys.append(key)
                    dropped += len(tmpl.entries)
                    continue
                keep = [e for e in tmpl.entries
                        if (db is not None and e.db != db)
                        or (name is not None and e.table != name)]
                dropped += len(tmpl.entries) - len(keep)
                tmpl.entries = keep
                if not keep and not tmpl.uncacheable:
                    doomed_keys.append(key)
            for key in doomed_keys:
                self._templates.pop(key, None)
        if dropped:
            FAST_LANE_EVENTS.inc(float(dropped), event="invalidate")
        return dropped

    def __len__(self) -> int:
        with self._lock:
            return sum(len(t.entries) for t in self._templates.values())


def _magic(i: int, original, params0) -> tuple:
    """A probe literal for slot `i` of the same token kind as the
    original, guaranteed distinct (type-strict) from the original and
    every existing parameter value."""
    if isinstance(original, str):
        v = f"gtpu\x02probe\x02{i}"
        while any(_type_eq(v, p) for p in params0) or v == original:
            v += "\x02"
        return v, "'" + v + "'"
    v = 8 * 10 ** 14 + 7919 * i + 3
    while any(_type_eq(v, p) for p in params0) \
            or _type_eq(v, original):
        v += 1
    return v, str(v)
