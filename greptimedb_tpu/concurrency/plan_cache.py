"""Shape-keyed logical-plan cache with parameterized WHERE literals.

2000 near-identical dashboard queries differ only in the literals of
their WHERE clause (which host, which time window). This cache
normalizes a SELECT by hoisting every WHERE literal into a positional
parameter, so all of them share ONE cache entry — one validated logical
plan, and (because the plan shape is what keys the XLA jit cache
downstream) one compiled device executable. A hit skips star expansion,
alias/ordinal resolution, aggregate validation and column collection
(`plan_select`), and only re-binds the parameter literals + recomputes
the time-range pushdown, which depend on the parameter values.

Invalidation is two-layered:
- explicit: DDL through this engine (ALTER/DROP/TRUNCATE/CREATE) and
  remote catalog invalidation (cluster frontends) call
  `invalidate_table`;
- implicit: every hit re-validates the entry's TableInfo snapshot
  against the catalog's current one (schema, region set, options), so a
  DDL this process never saw — another frontend's ALTER — still evicts
  the stale shape instead of serving it.

Entries also memoize a NEGATIVE rollup-substitution decision (the
eligibility probe costs region/state lookups per query); the memo is
stamped with `rollup.substitution_state_version()` and dies the moment
any rollup state changes (a new roll, a drop), so a shape that becomes
substitutable is re-probed.

With the serving fabric on (`[shm] fabric`), the cache grows a third
tier: a local miss probes the shared-memory fabric for a peer process's
published entry before re-planning, and every local build publishes its
entry for peers. Adoption runs the SAME safety nets an in-process hit
runs (`_info_matches` + `_bind`), plus a fabric-version check: a peer
DDL bumps the (db, table) version through the fabric, killing every
artifact published under the old one. The rollup-substitution memo is
NEVER adopted — it indexes this process's rollup state.

Every event lands in gtpu_plan_cache_events_total{event=hit|miss|evict|
invalidate}; fabric traffic in gtpu_shm_fabric_events_total{kind=plan}.
"""

from __future__ import annotations

import dataclasses
import hashlib
import pickle
import threading
from collections import OrderedDict
from typing import Optional

from greptimedb_tpu.query import logical as lp
from greptimedb_tpu.sql import ast
from greptimedb_tpu.utils.metrics import PLAN_CACHE_EVENTS, SHM_FABRIC_EVENTS


def _map_where_literals(e, fn):
    """Rebuild `e` with every ast.Literal replaced by fn(lit), visiting
    in deterministic field order (the SAME order for normalization,
    slot collection, and re-binding — positional parameters depend on
    it). Descends containers and expression dataclasses, never embedded
    statements."""
    if isinstance(e, ast.Literal):
        return fn(e)
    if isinstance(e, (list, tuple)):
        return type(e)(_map_where_literals(x, fn) for x in e)
    if dataclasses.is_dataclass(e) and not isinstance(e, type) \
            and not isinstance(e, ast.Statement):
        changes = {}
        for f in dataclasses.fields(e):
            v = getattr(e, f.name)
            if isinstance(v, (ast.Expr, list, tuple)) or (
                    dataclasses.is_dataclass(v)
                    and not isinstance(v, (type, ast.Statement))):
                nv = _map_where_literals(v, fn)
                if nv is not v and nv != v:
                    changes[f.name] = nv
        return dataclasses.replace(e, **changes) if changes else e
    return e


def normalize(sel: ast.Select) -> tuple[str, tuple]:
    """(shape key, parameter values). Only WHERE literals parameterize:
    values elsewhere (GROUP BY ordinals, LIMIT, percentile parameters,
    bucket intervals) can change the plan STRUCTURE, so they stay in
    the shape by value — two queries differing there are two shapes."""
    if sel.where is None:
        return repr(sel), ()
    params: list = []

    def mark(lit: ast.Literal):
        params.append(lit.value)
        return ast.Literal(("?", len(params) - 1))

    key_where = _map_where_literals(sel.where, mark)
    return repr(dataclasses.replace(sel, where=key_where)), tuple(params)


def collect_slots(where) -> list[ast.Literal]:
    """The WHERE's Literal objects in normalization order — the
    positional slots a cached plan re-binds through."""
    slots: list = []

    def keep(lit: ast.Literal):
        slots.append(lit)
        return lit

    _map_where_literals(where, keep)
    return slots


def _info_matches(a, b) -> bool:
    """Is the entry's TableInfo snapshot still the live table? Content
    comparison (not identity): the catalog materializes a fresh
    TableInfo per statement."""
    return (a.table_id == b.table_id
            and a.region_ids == b.region_ids
            and a.schema == b.schema
            and a.options == b.options
            and a.partition_rules == b.partition_rules
            and a.column_order == b.column_order)


class _Entry:
    __slots__ = ("plan", "where", "slots", "info", "sub_skip_version")

    def __init__(self, plan, where, slots, info):
        self.plan = plan
        self.where = where          # the Filter predicate template
        self.slots = slots          # its Literal objects, slot order
        self.info = info            # TableInfo snapshot at build
        self.sub_skip_version = None  # rollup version when proven
        #                               substitution-ineligible

    def skip_substitution(self) -> bool:
        if self.sub_skip_version is None:
            return False
        from greptimedb_tpu.maintenance import rollup

        # the stamp pairs the rollup-state version with the enable
        # toggle: a probe skipped while substitution was OFF proves
        # nothing about it being ON (and vice versa)
        return self.sub_skip_version == (
            rollup.substitution_state_version(),
            rollup.substitution_enabled())

    def mark_sub_ineligible(self, stamp=None) -> None:
        # callers that probed must pass the stamp captured BEFORE the
        # probe: a rollup finishing mid-probe bumps the version, and
        # stamping with the post-probe value would memoize "ineligible"
        # against state the probe never saw — permanently skipping a
        # now-available plane
        self.sub_skip_version = (substitution_stamp() if stamp is None
                                 else stamp)


def substitution_stamp() -> tuple:
    """The (rollup state version, enable toggle) pair a negative
    substitution probe is memoized against."""
    from greptimedb_tpu.maintenance import rollup

    return (rollup.substitution_state_version(),
            rollup.substitution_enabled())


class PlanCache:
    """Per-engine LRU of _Entry keyed by (db, table, shape)."""

    def __init__(self, capacity: int = 512):
        self.capacity = capacity
        self._entries: "OrderedDict[tuple, _Entry]" = OrderedDict()
        self._lock = threading.Lock()

    @property
    def enabled(self) -> bool:
        return self.capacity > 0

    # ---- lookup ------------------------------------------------------------

    def lookup(self, sel: ast.Select, info):
        """(plan | None, entry | None, binding). A non-None plan is a
        fully bound, ready-to-execute LogicalPlan. `binding` goes back
        to store() after a miss so the normalization walk runs once."""
        if not self.enabled:
            return None, None, None
        try:
            shape, params = normalize(sel)
        except Exception:  # noqa: BLE001 — exotic AST: plan uncached
            return None, None, None
        key = (info.db, info.name, shape)
        with self._lock:
            ent = self._entries.get(key)
            if ent is not None:
                self._entries.move_to_end(key)
        from greptimedb_tpu.utils import ledger

        adopted = False
        if ent is None:
            ent = self._fabric_probe(key, info)
            adopted = ent is not None
        if ent is None:
            PLAN_CACHE_EVENTS.inc(event="miss")
            ledger.cache_event("plan", "miss")
            return None, None, (key, params)
        if not _info_matches(ent.info, info):
            # DDL this process never executed (remote frontend's ALTER,
            # DROP+CREATE): the snapshot comparison is the safety net
            with self._lock:
                self._entries.pop(key, None)
            PLAN_CACHE_EVENTS.inc(event="invalidate")
            ledger.cache_event("plan", "invalidate")
            return None, None, (key, params)
        try:
            plan = self._bind(ent, params)
        except Exception:  # noqa: BLE001 — any doubt means re-plan
            PLAN_CACHE_EVENTS.inc(event="miss")
            ledger.cache_event("plan", "miss")
            return None, None, (key, params)
        if adopted:
            # insert only after the bind proved the adopted entry sound
            with self._lock:
                self._entries[key] = ent
                self._entries.move_to_end(key)
                while len(self._entries) > self.capacity:
                    self._entries.popitem(last=False)
        PLAN_CACHE_EVENTS.inc(event="hit")
        ledger.cache_event("plan", "hit")
        return plan, ent, (key, params)

    # ---- fabric tier -------------------------------------------------------

    @staticmethod
    def _fabric_key(key: tuple) -> bytes:
        """(db, table, shape) → fixed digest; shape reprs routinely
        exceed the fabric's key cap."""
        h = hashlib.blake2b(digest_size=16)
        for part in key:
            b = part.encode()
            h.update(len(b).to_bytes(4, "little"))
            h.update(b)
        return h.digest()

    def _fabric_probe(self, key: tuple, info) -> Optional[_Entry]:
        """After a local miss: adopt a peer process's published entry.
        Returns None on any doubt (absent fabric, stale version, info
        drift, undecodable blob) — the caller re-plans as before."""
        from greptimedb_tpu import shm
        from greptimedb_tpu.shm.fabric import FabricError

        fabric = shm.get_fabric()
        if fabric is None:
            return None
        try:
            blob = fabric.get("plan", self._fabric_key(key))
            if blob is None:
                SHM_FABRIC_EVENTS.inc(event="miss", kind="plan")
                return None
            cur = fabric.version(key[0], key[1])
        except (FabricError, OSError, ValueError):
            shm.detach()
            return None
        try:
            ver, ent = pickle.loads(blob)
        except Exception:  # noqa: BLE001 — a stale-code peer's blob
            return None
        if not isinstance(ent, _Entry) or ver != cur \
                or not _info_matches(ent.info, info):
            SHM_FABRIC_EVENTS.inc(event="miss", kind="plan")
            return None
        # the substitution memo indexes the PUBLISHER's rollup state;
        # this process re-probes from scratch
        ent.sub_skip_version = None
        SHM_FABRIC_EVENTS.inc(event="hit", kind="plan")
        return ent

    def _fabric_publish(self, key: tuple, ent: _Entry) -> None:
        """After a local build: share the validated entry. The version
        is read BEFORE the put — a concurrent DDL bumping it makes the
        published artifact fail its adopt check (fail closed)."""
        from greptimedb_tpu import shm
        from greptimedb_tpu.shm.fabric import FabricError

        fabric = shm.get_fabric()
        if fabric is None:
            return
        try:
            ver = fabric.version(key[0], key[1])
            blob = pickle.dumps((ver, ent),
                                protocol=pickle.HIGHEST_PROTOCOL)
        except (FabricError, OSError, ValueError):
            shm.detach()
            return
        except Exception:  # noqa: BLE001 — unpicklable plan: not shared
            return
        try:
            if fabric.put("plan", self._fabric_key(key), blob):
                SHM_FABRIC_EVENTS.inc(event="publish", kind="plan")
        except (FabricError, OSError, ValueError):
            shm.detach()

    def _bind(self, ent: _Entry, params: tuple) -> lp.LogicalPlan:
        """Re-bind the template to this query's parameter values and
        recompute the value-dependent Scan.ts_range. Everything else —
        projection items, aggregate specs, sort keys — is shared by
        reference with the template (read-only downstream)."""
        if ent.where is None:
            if params:
                raise ValueError("params for a where-less template")
            return ent.plan
        if len(params) != len(ent.slots):
            raise ValueError("slot arity drift")
        it = iter(params)
        new_where = _map_where_literals(ent.where,
                                        lambda _lit: ast.Literal(next(it)))
        from greptimedb_tpu.query.expr import extract_ts_bounds

        def rebuild(node):
            if isinstance(node, lp.Scan):
                ts_col = node.table.schema.time_index
                ts_range = extract_ts_bounds(new_where, ts_col.name,
                                             ts_col.dtype)
                return lp.Scan(node.table, node.columns, ts_range)
            if isinstance(node, lp.Filter):
                return lp.Filter(rebuild(node.input), new_where)
            if isinstance(node, lp.Aggregate):
                return lp.Aggregate(rebuild(node.input), node.keys,
                                    node.aggs)
            if isinstance(node, lp.Having):
                return lp.Having(rebuild(node.input), node.predicate)
            if isinstance(node, lp.Project):
                return lp.Project(rebuild(node.input), node.items)
            if isinstance(node, lp.Sort):
                return lp.Sort(rebuild(node.input), node.keys)
            if isinstance(node, lp.Limit):
                return lp.Limit(rebuild(node.input), node.limit,
                                node.offset)
            raise ValueError(f"uncacheable node {type(node).__name__}")

        return rebuild(ent.plan)

    # ---- store -------------------------------------------------------------

    def store(self, binding, sel: ast.Select, info, plan) -> Optional[_Entry]:
        """Cache a freshly planned SELECT. The plan references `sel`'s
        own Literal objects (the planner passes expressions through by
        reference), so sel.where's literals in walk order ARE the
        re-bind slots; a mismatch (a planner rewrite copied them, a
        duplicate object) refuses to cache rather than mis-bind."""
        if not self.enabled or binding is None:
            return None
        key, params = binding
        slots: list = []
        if sel.where is not None:
            slots = collect_slots(sel.where)
            if len(slots) != len(params) \
                    or any(s.value is not p and s.value != p
                           for s, p in zip(slots, params)) \
                    or len({id(s) for s in slots}) != len(slots):
                return None
        ent = _Entry(plan, sel.where, tuple(slots), info)
        with self._lock:
            self._entries[key] = ent
            self._entries.move_to_end(key)
            evicted = 0
            while len(self._entries) > self.capacity:
                self._entries.popitem(last=False)
                evicted += 1
        if evicted:
            PLAN_CACHE_EVENTS.inc(float(evicted), event="evict")
        self._fabric_publish(key, ent)
        return ent

    # ---- invalidation ------------------------------------------------------

    def invalidate_table(self, db: Optional[str] = None,
                         name: Optional[str] = None) -> int:
        """Drop every shape for (db, name); None fields widen the match
        (None/None = flush everything — the remote catalog watch fires
        it when it can't tell what moved)."""
        with self._lock:
            doomed = [k for k in self._entries
                      if (db is None or k[0] == db)
                      and (name is None or k[1] == name)]
            for k in doomed:
                self._entries.pop(k, None)
        if doomed:
            PLAN_CACHE_EVENTS.inc(float(len(doomed)), event="invalidate")
        return len(doomed)

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)
