"""Global runtime configuration (the analog of reference MitoConfig /
QueryEngineState knobs, layered defaults <- env vars)."""

from __future__ import annotations

import os

import jax
import jax.numpy as jnp
import numpy as np


def _platform() -> str:
    try:
        return jax.devices()[0].platform
    except Exception:
        return "cpu"


def compute_dtype() -> np.dtype:
    """Float dtype for field values inside device kernels. TPU has no
    native f64 (emulated, slow) — default f32 there; CPU keeps f64 so
    results are bit-comparable with numpy oracles in tests.

    Override with GREPTIMEDB_TPU_COMPUTE_DTYPE=float32|float64|bfloat16.
    """
    env = os.environ.get("GREPTIMEDB_TPU_COMPUTE_DTYPE")
    if env:
        return jnp.dtype(env)
    return jnp.dtype(jnp.float32) if _platform() in ("tpu", "axon") else jnp.dtype(jnp.float64)


def device_cache_bytes() -> int:
    """HBM budget for the device block cache (reference: CacheManager page
    cache, mito2/src/cache.rs:53-61 — here the 'page cache' IS device HBM).
    """
    env = os.environ.get("GREPTIMEDB_TPU_DEVICE_CACHE_BYTES")
    if env:
        return int(env)
    return 8 << 30 if _platform() in ("tpu", "axon") else 1 << 30
