"""Global runtime configuration (the analog of reference MitoConfig /
QueryEngineState knobs, layered defaults <- env vars)."""

from __future__ import annotations

import os

import jax
import jax.numpy as jnp
import numpy as np


def _platform() -> str:
    try:
        return jax.devices()[0].platform
    except Exception:
        return "cpu"


def compute_dtype() -> np.dtype:
    """Float dtype for field values inside device kernels. TPU has no
    native f64 (emulated, slow) — default f32 there; CPU keeps f64 so
    results are bit-comparable with numpy oracles in tests.

    Override with GREPTIMEDB_TPU_COMPUTE_DTYPE=float32|float64|bfloat16.
    """
    env = os.environ.get("GREPTIMEDB_TPU_COMPUTE_DTYPE")
    if env:
        return jnp.dtype(env)
    return jnp.dtype(jnp.float32) if _platform() in ("tpu", "axon") else jnp.dtype(jnp.float64)


def query_mesh():
    """Device mesh for distributed query execution, or None when a single
    device is visible (the common standalone case). All devices ride the
    "shard" (row) axis — the collective MergeScan (SURVEY §2.6: reference
    gathers region streams point-to-point at merge_scan.rs:122; here
    partial aggregates combine with psum over ICI).

    GREPTIMEDB_TPU_MESH=off disables; =NxM forces an (shard, field) shape.
    """
    env = os.environ.get("GREPTIMEDB_TPU_MESH", "auto")
    if env.lower() in ("off", "0", "none"):
        return None
    try:
        # LOCAL devices only: the scan data plane device_puts
        # process-local arrays, which cannot target another host's
        # chips. Cross-host distribution rides the PlanFragment
        # pushdown over Flight instead (parallel/mesh.init_distributed
        # docstring has the division of labor).
        local = jax.local_devices()
    except Exception:
        return None
    n = len(local)
    from greptimedb_tpu.parallel.mesh import make_mesh

    if env not in ("auto", ""):
        s, _, f = env.partition("x")
        shape = (int(s), int(f or 1))
        if shape[0] * shape[1] > n:
            raise ValueError(f"mesh {shape} needs {shape[0]*shape[1]} devices, have {n}")
        return make_mesh(local[: shape[0] * shape[1]], shape)
    if n <= 1:
        return None
    return make_mesh(local)


def dense_groups_max() -> int:
    """Largest dense group-id product the aggregate kernel materializes as
    [G, F] planes (1M groups x 10 f64 fields = 80 MiB per plane). Beyond
    this the sparse (sort-compact) path runs — the TPU answer to the
    reference's unbounded hash aggregate (SURVEY §7 hard part)."""
    return int(os.environ.get("GREPTIMEDB_TPU_DENSE_GROUPS_MAX", str(1 << 20)))


def sparse_groups_max() -> int:
    """Cap on *observed* distinct groups in the sparse aggregate path
    (output planes are [cap, F]); queries observing more raise."""
    return int(os.environ.get("GREPTIMEDB_TPU_SPARSE_GROUPS_MAX", str(1 << 22)))


def sparse_groups_min() -> int:
    """Key products at or above this ALSO take the sparse sort-compact
    path even when they fit the dense budget (0 = off, the default:
    dense wins while its planes fit). The lever for date_bin queries
    whose bucket domain blows the fused kernel's 4096-segment envelope
    but whose observed groups compact well — the tiled sparse-fused
    path keeps them on the kernel."""
    return int(os.environ.get("GREPTIMEDB_TPU_SPARSE_GROUPS_MIN", "0"))


def tier_admission() -> bool:
    """Hot-set-aware tier admission: before the latency-history router,
    consult which tier's device/HBM hot set already holds the scan's
    file-anchored blocks and route there (re-uploading a hot scan to
    the OTHER tier pays the full H2D cost for nothing).
    GREPTIMEDB_TPU_TIER_ADMISSION=off restores pure history/heuristic
    routing — the A/B benching override."""
    return os.environ.get("GREPTIMEDB_TPU_TIER_ADMISSION", "on").lower() \
        not in ("0", "false", "off")


def stream_threshold_rows() -> int:
    """Aggregate scans at or above this row estimate run the streaming
    (bounded-memory) path: lazy row-group chunks -> fixed-shape device
    blocks -> incremental on-device combine, instead of materializing the
    whole scan on host (reference streams lazy row groups with a page
    cache, mito2/src/sst/parquet/row_group.rs). Below the threshold the
    materialized path keeps whole column snapshots HBM-cached across
    repeated queries (the TSBS warm-cache regime); the default hands over
    to streaming where those snapshots stop fitting."""
    return int(os.environ.get("GREPTIMEDB_TPU_STREAM_THRESHOLD_ROWS",
                              str(32 << 20)))


def stream_block_rows() -> int:
    """Fixed device block shape for the streaming path (one compile)."""
    return int(os.environ.get("GREPTIMEDB_TPU_STREAM_BLOCK_ROWS",
                              str(2 << 20)))


def mesh_min_rows() -> int:
    """Scans below this row count skip the mesh path: per-shard dispatch
    overhead beats the parallelism on tiny results."""
    return int(os.environ.get("GREPTIMEDB_TPU_MESH_MIN_ROWS", "65536"))


def default_hash_partitions() -> int:
    """Hash-partition count for cluster CREATE TABLE without an explicit
    PARTITION clause ([partition] default_hash_regions); 0/1 = one
    region (the standalone default)."""
    return int(os.environ.get("GREPTIMEDB_TPU_DEFAULT_HASH_REGIONS", "0"))


def hash_partition_columns() -> list:
    """Columns for default hash partitioning ([partition] hash_columns,
    comma-separated); empty = the table's leading tag column."""
    env = os.environ.get("GREPTIMEDB_TPU_HASH_PARTITION_COLUMNS", "")
    return [s.strip() for s in env.split(",") if s.strip()]


def device_cache_bytes() -> int:
    """HBM budget for the device block cache (reference: CacheManager page
    cache, mito2/src/cache.rs:53-61 — here the 'page cache' IS device HBM).
    """
    env = os.environ.get("GREPTIMEDB_TPU_DEVICE_CACHE_BYTES")
    if env:
        return int(env)
    if _platform() in ("tpu", "axon"):
        return 8 << 30
    # CPU backend: "device" memory IS host RAM — budget a quarter of it
    # (reference page cache defaults to mem/16; the block cache carries
    # the whole warm working set here, so it gets more)
    try:
        ram = os.sysconf("SC_PAGE_SIZE") * os.sysconf("SC_PHYS_PAGES")
    except (ValueError, OSError):
        ram = 8 << 30
    return max(1 << 30, min(ram // 4, 32 << 30))


def host_tier_mode() -> str:
    """Tiered execution policy: "auto" routes interactive queries to the
    host (CPU) tier when the accelerator link is remote/slow (probed at
    first query — physical.accelerator_link()), "off" pins everything to
    the default backend, "force" pins everything to the host tier
    (A/B measurement + emergency bypass). A TPU reached through a
    network tunnel costs tens of ms per result readback; a co-located
    chip costs ~0."""
    return os.environ.get("GREPTIMEDB_TPU_HOST_TIER", "auto").lower()


def compilation_cache_dir() -> str:
    """Directory for JAX's persistent compilation cache, or "" when
    disabled. Default: on for accelerator platforms (the ~25 s Mosaic/
    XLA warmup compile becomes a once-per-cluster cost), off on CPU
    (tests and dev shells churn shapes for no reuse). Override with
    GREPTIMEDB_TPU_COMPILATION_CACHE_DIR=<dir> (off/0/none disables)."""
    env = os.environ.get("GREPTIMEDB_TPU_COMPILATION_CACHE_DIR")
    if env is not None:
        return "" if env.lower() in ("off", "0", "none", "") else env
    if _platform() in ("tpu", "axon"):
        return os.path.expanduser("~/.cache/greptimedb_tpu/xla-cache")
    return ""


def prewarm_enabled() -> bool:
    """Background pre-warm of the dominant Pallas kernel shapes at
    executor construction (region-open time), so first-query latency
    stops hiding the Mosaic compile. GREPTIMEDB_TPU_PREWARM=off
    disables; default on for accelerator platforms only."""
    env = os.environ.get("GREPTIMEDB_TPU_PREWARM")
    if env is not None:
        return env.lower() not in ("0", "false", "off")
    return _platform() in ("tpu", "axon")


def tier_adaptive() -> bool:
    """Measured tier routing: consult per-tier latency history so a
    tier that is losing stops being chosen (GREPTIMEDB_TPU_TIER_ADAPTIVE
    =off pins the static heuristic — the benching override)."""
    return os.environ.get("GREPTIMEDB_TPU_TIER_ADAPTIVE", "on").lower() \
        not in ("0", "false", "off")


def device_tier_rows() -> int:
    """Aggregate scans at or above this row count run on the accelerator
    even over a slow link (the resident-plane fold amortizes readback);
    smaller interactive queries take the host tier."""
    return int(os.environ.get("GREPTIMEDB_TPU_DEVICE_TIER_ROWS",
                              str(4 << 20)))
