"""External file formats (mirrors reference `common/datasource`,
src/common/datasource/src/file_format.rs:57-61: CSV / JSON(ndjson) /
Parquet / ORC, with compression) — backs COPY TO/FROM and the file
engine. ORC rides pyarrow.orc; like parquet it is a container format,
so the .gz wrapper applies only to the text formats.
"""

from __future__ import annotations

import gzip
import io
import json
import os
from typing import Optional

import numpy as np
import pyarrow as pa

from greptimedb_tpu.datatypes.types import DataType, SemanticType
from greptimedb_tpu.query.result import QueryResult

FORMATS = ("csv", "json", "parquet", "orc")


class DataSourceError(Exception):
    pass


def infer_format(path: str, explicit: Optional[str] = None) -> str:
    """Format from the WITH (format=...) option or the file extension
    (reference file_format.rs `try_from` on extension)."""
    if explicit:
        f = explicit.lower()
        if f == "ndjson":
            f = "json"
        if f not in FORMATS:
            raise DataSourceError(f"unsupported format {explicit!r} "
                                  f"(supported: {', '.join(FORMATS)})")
        return f
    base = path[:-3] if path.endswith(".gz") else path
    ext = os.path.splitext(base)[1].lstrip(".").lower()
    if ext in ("ndjson", "jsonl"):
        ext = "json"
    if ext in FORMATS:
        return ext
    raise DataSourceError(f"cannot infer format from {path!r}; "
                          "pass WITH (format = '...')")


def read_file(path: str, fmt: Optional[str] = None) -> pa.Table:
    fmt = infer_format(path, fmt)
    if not os.path.exists(path):
        raise DataSourceError(f"file {path!r} not found")
    if fmt == "parquet":
        import pyarrow.parquet as pq
        return pq.read_table(path)
    if fmt == "orc":
        import pyarrow.orc as po
        return po.read_table(path)
    raw = open(path, "rb").read()
    if path.endswith(".gz"):
        raw = gzip.decompress(raw)
    if fmt == "csv":
        import pyarrow.csv as pacsv
        return pacsv.read_csv(io.BytesIO(raw))
    # ndjson
    import pyarrow.json as pajson
    return pajson.read_json(io.BytesIO(raw))


def write_file(table: pa.Table, path: str, fmt: Optional[str] = None) -> int:
    fmt = infer_format(path, fmt)
    parent = os.path.dirname(path)
    if parent:
        os.makedirs(parent, exist_ok=True)
    if fmt == "parquet":
        import pyarrow.parquet as pq
        pq.write_table(table, path)
        return table.num_rows
    if fmt == "orc":
        import pyarrow.orc as po
        po.write_table(table, path)
        return table.num_rows
    buf = io.BytesIO()
    if fmt == "csv":
        import pyarrow.csv as pacsv
        pacsv.write_csv(table, buf)
    else:  # ndjson
        cols = {f.name: table.column(f.name).to_pylist() for f in table.schema}
        lines = []
        for i in range(table.num_rows):
            lines.append(json.dumps({k: v[i] for k, v in cols.items()},
                                    default=str))
        buf.write(("\n".join(lines) + "\n").encode())
    data = buf.getvalue()
    if path.endswith(".gz"):
        data = gzip.compress(data)
    with open(path, "wb") as f:
        f.write(data)
    return table.num_rows


# ---- Arrow → engine ingest (shared by COPY FROM and Flight do_put) ----------


def insert_arrow_table(qe, table_name: str, t: pa.Table, ctx) -> int:
    """Columnar insert of an Arrow table into an existing engine table,
    mapping columns by name and applying schema coercions (tags →
    dictionary codes, timestamps → time-index unit)."""
    from greptimedb_tpu.datatypes.recordbatch import RecordBatch
    from greptimedb_tpu.datatypes.vector import DictVector
    from greptimedb_tpu.utils.time import coerce_ts_literal

    info = qe._table(table_name, ctx)
    schema = info.schema
    nrows = t.num_rows
    have = set(t.schema.names)
    cols: dict = {}
    for c in schema.columns:
        if c.name in have:
            vals = t.column(c.name).to_pylist()
        else:
            vals = [c.default] * nrows
        if c.semantic is SemanticType.TAG or c.dtype.is_string:
            cols[c.name] = DictVector.encode(
                [None if v is None else str(v) for v in vals])
        elif c.dtype.is_timestamp:
            coerced = []
            for v in vals:
                if v is None:
                    raise DataSourceError(f"time index {c.name} cannot be NULL")
                coerced.append(coerce_ts_literal(v, c.dtype))
            cols[c.name] = np.asarray(coerced, dtype=np.int64)
        elif c.dtype.is_float:
            cols[c.name] = np.asarray(
                [np.nan if v is None else float(v) for v in vals],
                dtype=c.dtype.to_numpy())
        elif c.dtype is DataType.BOOL:
            cols[c.name] = np.asarray(
                [False if v is None else bool(v) for v in vals])
        else:
            cols[c.name] = np.asarray(
                [0 if v is None else int(v) for v in vals],
                dtype=c.dtype.to_numpy())
    batch = RecordBatch(schema, cols)
    return qe._sharded_write(info, batch, delete=False)


# ---- QueryResult ⇄ Arrow (shared by COPY TO and the Flight services) --------


def result_to_table(r: QueryResult) -> pa.Table:
    arrays, fields = [], []
    for name, dt, col in zip(r.names, r.dtypes, r.columns):
        if dt is None:
            dt = DataType.from_numpy(np.asarray(col).dtype)
        arr = pa.array(col.tolist(), type=dt.to_arrow())
        arrays.append(arr)
        fields.append(pa.field(name, arr.type))
    return pa.Table.from_arrays(arrays, schema=pa.schema(fields))


def table_to_result(t: pa.Table) -> QueryResult:
    names, dtypes, cols = [], [], []
    for field, col in zip(t.schema, t.columns):
        names.append(field.name)
        dt = DataType.from_arrow(field.type)
        dtypes.append(dt)
        if dt.to_numpy() == np.dtype(object):
            cols.append(np.asarray(col.to_pylist(), dtype=object))
        else:
            arr = col.to_numpy(zero_copy_only=False)
            if arr.dtype != dt.to_numpy() and arr.dtype.kind != "f":
                arr = arr.astype(dt.to_numpy())
            cols.append(arr)
    return QueryResult(names, dtypes, cols)
