"""Type system substrate (mirrors reference src/datatypes, ~16k LoC Rust).

Arrow-backed: every column is a numpy/pyarrow array on the host and a padded
device array inside kernels. Tags are dictionary-encoded end-to-end — the
kernel ABI only ever sees int32 codes (SURVEY.md §7 "hard parts" #2).
"""

from greptimedb_tpu.datatypes.types import (
    DataType,
    SemanticType,
    TimeUnit,
)
from greptimedb_tpu.datatypes.schema import ColumnSchema, Schema
from greptimedb_tpu.datatypes.recordbatch import RecordBatch
from greptimedb_tpu.datatypes.vector import DictVector

__all__ = [
    "DataType",
    "SemanticType",
    "TimeUnit",
    "ColumnSchema",
    "Schema",
    "RecordBatch",
    "DictVector",
]
