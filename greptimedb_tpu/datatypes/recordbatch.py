"""RecordBatch: schema + host columns (mirrors reference
src/common/recordbatch/src/recordbatch.rs:35).

The host-side unit of data exchange: protocol servers, storage, and the
query engine edges all speak RecordBatch; device kernels speak padded
column blocks (ops/blocks.py). Conversion to/from pyarrow is zero-copy for
numeric columns.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional, Union

import numpy as np
import pyarrow as pa

from greptimedb_tpu.datatypes.schema import Schema
from greptimedb_tpu.datatypes.vector import DictVector

Column = Union[np.ndarray, DictVector]


@dataclass
class RecordBatch:
    schema: Schema
    columns: dict[str, Column]

    def __post_init__(self):
        n = None
        for name in self.schema.names:
            if name not in self.columns:
                raise ValueError(f"missing column {name!r}")
            ln = len(self.columns[name])
            if n is None:
                n = ln
            elif ln != n:
                raise ValueError(f"column {name!r} length {ln} != {n}")

    def __len__(self) -> int:
        return len(self.columns[self.schema.names[0]])

    @property
    def num_rows(self) -> int:
        return len(self)

    def column(self, name: str) -> Column:
        return self.columns[name]

    def take(self, indices: np.ndarray) -> "RecordBatch":
        return RecordBatch(
            self.schema,
            {
                k: (v.take(indices) if isinstance(v, DictVector) else v[indices])
                for k, v in self.columns.items()
            },
        )

    def slice(self, start: int, stop: int) -> "RecordBatch":
        return RecordBatch(
            self.schema,
            {
                k: (DictVector(v.codes[start:stop], v.values) if isinstance(v, DictVector) else v[start:stop])
                for k, v in self.columns.items()
            },
        )

    # ---- arrow interop -----------------------------------------------------

    def to_arrow(self) -> pa.RecordBatch:
        arrays = []
        for c in self.schema.columns:
            col = self.columns[c.name]
            if isinstance(col, DictVector):
                arrays.append(col.to_arrow())
            elif c.dtype.is_timestamp:
                arrays.append(pa.array(col, type=c.dtype.to_arrow()))
            elif col.dtype == object:
                arrays.append(pa.array(col.tolist(), type=c.dtype.to_arrow()))
            else:
                arrays.append(pa.array(col, type=c.dtype.to_arrow()))
        fields = [
            pa.field(c.name, a.type, nullable=c.nullable)
            for c, a in zip(self.schema.columns, arrays)
        ]
        return pa.RecordBatch.from_arrays(arrays, schema=pa.schema(fields))

    @staticmethod
    def from_arrow(batch: pa.RecordBatch, schema: Optional[Schema] = None) -> "RecordBatch":
        if schema is None:
            schema = Schema.from_arrow(batch.schema)
        cols: dict[str, Column] = {}
        for c in schema.columns:
            arr = batch.column(batch.schema.get_field_index(c.name))
            if c.dtype.is_string or pa.types.is_dictionary(arr.type):
                cols[c.name] = DictVector.from_arrow(arr)
            elif c.dtype.is_timestamp:
                np_arr = arr.to_numpy(zero_copy_only=False)
                cols[c.name] = np_arr.astype(np.int64)
            else:
                cols[c.name] = arr.to_numpy(zero_copy_only=False)
        return RecordBatch(schema, cols)

    @staticmethod
    def concat(batches: list["RecordBatch"]) -> "RecordBatch":
        assert batches, "cannot concat zero batches"
        if len(batches) == 1:
            return batches[0]
        schema = batches[0].schema
        cols: dict[str, Column] = {}
        for c in schema.columns:
            parts = [b.columns[c.name] for b in batches]
            if isinstance(parts[0], DictVector):
                # merge dictionaries: encode against the first dict, remapping others
                merged_vals = list(parts[0].values)
                table = {v: i for i, v in enumerate(merged_vals)}
                codes_parts = [parts[0].codes]
                for p in parts[1:]:
                    mapping = np.empty(max(len(p.values), 1), dtype=np.int32)
                    for i, v in enumerate(p.values):
                        if v not in table:
                            table[v] = len(merged_vals)
                            merged_vals.append(v)
                        mapping[i] = table[v]
                    codes_parts.append(
                        np.where(p.codes >= 0, mapping[np.clip(p.codes, 0, None)], -1).astype(np.int32)
                    )
                cols[c.name] = DictVector(
                    np.concatenate(codes_parts), np.asarray(merged_vals, dtype=object)
                )
            else:
                cols[c.name] = np.concatenate(parts)
        return RecordBatch(schema, cols)

    def to_pydict(self) -> dict[str, list]:
        out = {}
        for c in self.schema.columns:
            col = self.columns[c.name]
            if isinstance(col, DictVector):
                out[c.name] = col.decode().tolist()
            else:
                out[c.name] = col.tolist()
        return out
