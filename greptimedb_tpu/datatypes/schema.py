"""Schema with time-index and primary-key (tag) metadata.

Mirrors the reference's `Schema` (src/datatypes/src/schema.rs:37) which
carries the time-index column in arrow metadata, and region metadata
(src/store-api/src/metadata.rs) which orders columns as
(tags..., time index, fields...).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Sequence

import pyarrow as pa

from greptimedb_tpu.datatypes.types import DataType, SemanticType


@dataclass(frozen=True)
class ColumnSchema:
    name: str
    dtype: DataType
    semantic: SemanticType = SemanticType.FIELD
    nullable: bool = True
    default: object = None

    def __post_init__(self):
        if self.semantic is SemanticType.TIMESTAMP and not self.dtype.is_timestamp:
            raise ValueError(
                f"time index column {self.name!r} must be a timestamp type, "
                f"got {self.dtype}"
            )


@dataclass(frozen=True)
class Schema:
    """Table/region schema. Column order is canonicalized to
    (tags..., time index, fields...) like the reference region metadata —
    this is also the sort-key order of the storage layer."""

    columns: tuple[ColumnSchema, ...]

    def __init__(self, columns: Sequence[ColumnSchema]):
        names = [c.name for c in columns]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate column names: {names}")
        ts_cols = [c for c in columns if c.semantic is SemanticType.TIMESTAMP]
        if len(ts_cols) != 1:
            raise ValueError(f"schema needs exactly one time index, got {len(ts_cols)}")
        tags = tuple(c for c in columns if c.semantic is SemanticType.TAG)
        fields = tuple(c for c in columns if c.semantic is SemanticType.FIELD)
        object.__setattr__(self, "columns", tags + (ts_cols[0],) + fields)

    # ---- lookups -----------------------------------------------------------

    @property
    def names(self) -> list[str]:
        return [c.name for c in self.columns]

    @property
    def tag_columns(self) -> list[ColumnSchema]:
        return [c for c in self.columns if c.semantic is SemanticType.TAG]

    @property
    def field_columns(self) -> list[ColumnSchema]:
        return [c for c in self.columns if c.semantic is SemanticType.FIELD]

    @property
    def time_index(self) -> ColumnSchema:
        return next(c for c in self.columns if c.semantic is SemanticType.TIMESTAMP)

    def column(self, name: str) -> ColumnSchema:
        for c in self.columns:
            if c.name == name:
                return c
        raise KeyError(name)

    def __contains__(self, name: str) -> bool:
        return any(c.name == name for c in self.columns)

    def index_of(self, name: str) -> int:
        for i, c in enumerate(self.columns):
            if c.name == name:
                return i
        raise KeyError(name)

    # ---- arrow interop -----------------------------------------------------

    def to_arrow(self) -> pa.Schema:
        fields = []
        for c in self.columns:
            md = {b"semantic": c.semantic.value.encode()}
            fields.append(
                pa.field(c.name, c.dtype.to_arrow(), nullable=c.nullable, metadata=md)
            )
        return pa.schema(fields, metadata={b"time_index": self.time_index.name.encode()})

    @staticmethod
    def from_arrow(s: pa.Schema) -> "Schema":
        time_index = (s.metadata or {}).get(b"time_index", b"").decode()
        cols = []
        for f in s:
            md = f.metadata or {}
            sem = md.get(b"semantic")
            if sem is not None:
                semantic = SemanticType(sem.decode())
            elif f.name == time_index:
                semantic = SemanticType.TIMESTAMP
            else:
                semantic = SemanticType.FIELD
            cols.append(
                ColumnSchema(f.name, DataType.from_arrow(f.type), semantic, f.nullable)
            )
        return Schema(cols)

    def to_dict(self) -> dict:
        return {
            "columns": [
                {
                    "name": c.name,
                    "dtype": c.dtype.value,
                    "semantic": c.semantic.value,
                    "nullable": c.nullable,
                    "default": c.default,
                }
                for c in self.columns
            ]
        }

    @staticmethod
    def from_dict(d: dict) -> "Schema":
        return Schema(
            [
                ColumnSchema(
                    c["name"],
                    DataType(c["dtype"]),
                    SemanticType(c["semantic"]),
                    c.get("nullable", True),
                    c.get("default"),
                )
                for c in d["columns"]
            ]
        )
