"""Concrete data types and column semantic roles.

Mirrors the reference's `ConcreteDataType` (src/datatypes/src/data_type.rs:46)
and the Tag/Field/Timestamp column roles used by its storage and metric
engines. Re-designed for TPU: each type knows its numpy storage dtype and its
on-device compute dtype (f64 fields are computed in f32 on TPU by default —
the MXU/VPU have no native f64; precision-sensitive accumulations use
mean-offset or pairwise strategies inside the kernels, see ops/segment.py).
"""

from __future__ import annotations

import enum
from dataclasses import dataclass

import numpy as np
import pyarrow as pa


class TimeUnit(enum.Enum):
    SECOND = "s"
    MILLISECOND = "ms"
    MICROSECOND = "us"
    NANOSECOND = "ns"

    @property
    def nanos_per_unit(self) -> int:
        return {"s": 10**9, "ms": 10**6, "us": 10**3, "ns": 1}[self.value]


class SemanticType(enum.Enum):
    """Column role (reference: api::v1::SemanticType; used throughout mito2).

    TAG columns form the series primary key and are dictionary-encoded;
    TIMESTAMP is the single time index; FIELD columns carry measurements.
    """

    TAG = "tag"
    FIELD = "field"
    TIMESTAMP = "timestamp"


class DataType(enum.Enum):
    BOOL = "bool"
    INT8 = "int8"
    INT16 = "int16"
    INT32 = "int32"
    INT64 = "int64"
    UINT8 = "uint8"
    UINT16 = "uint16"
    UINT32 = "uint32"
    UINT64 = "uint64"
    FLOAT32 = "float32"
    FLOAT64 = "float64"
    STRING = "string"
    BINARY = "binary"
    TIMESTAMP_SECOND = "timestamp_s"
    TIMESTAMP_MILLISECOND = "timestamp_ms"
    TIMESTAMP_MICROSECOND = "timestamp_us"
    TIMESTAMP_NANOSECOND = "timestamp_ns"

    # ---- classification ----------------------------------------------------

    @property
    def is_timestamp(self) -> bool:
        return self.value.startswith("timestamp")

    @property
    def is_numeric(self) -> bool:
        return self in _NUMERIC

    @property
    def is_float(self) -> bool:
        return self in (DataType.FLOAT32, DataType.FLOAT64)

    @property
    def is_integer(self) -> bool:
        return self.is_numeric and not self.is_float

    @property
    def is_string(self) -> bool:
        return self in (DataType.STRING, DataType.BINARY)

    @property
    def time_unit(self) -> TimeUnit:
        assert self.is_timestamp, self
        return TimeUnit(self.value.split("_", 1)[1])

    # ---- conversions -------------------------------------------------------

    def to_numpy(self) -> np.dtype:
        if self.is_timestamp:
            return np.dtype(np.int64)
        if self is DataType.STRING or self is DataType.BINARY:
            return np.dtype(object)
        return np.dtype(self.value)

    def to_arrow(self) -> pa.DataType:
        if self.is_timestamp:
            return pa.timestamp(self.time_unit.value)
        return _TO_ARROW[self]

    @staticmethod
    def from_arrow(t: pa.DataType) -> "DataType":
        if pa.types.is_timestamp(t):
            return DataType("timestamp_" + t.unit)
        if pa.types.is_dictionary(t):
            return DataType.from_arrow(t.value_type)
        if pa.types.is_large_string(t) or pa.types.is_string(t):
            return DataType.STRING
        if pa.types.is_large_binary(t) or pa.types.is_binary(t):
            return DataType.BINARY
        if pa.types.is_date32(t) or pa.types.is_date64(t):
            return DataType.TIMESTAMP_MILLISECOND
        return _FROM_ARROW[t]

    @staticmethod
    def from_numpy(dt: np.dtype) -> "DataType":
        dt = np.dtype(dt)
        if dt.kind == "M":  # datetime64
            unit = np.datetime_data(dt)[0]
            return DataType("timestamp_" + unit)
        if dt.kind in ("U", "S", "O"):
            return DataType.STRING
        return DataType(dt.name)


_NUMERIC = {
    DataType.INT8, DataType.INT16, DataType.INT32, DataType.INT64,
    DataType.UINT8, DataType.UINT16, DataType.UINT32, DataType.UINT64,
    DataType.FLOAT32, DataType.FLOAT64,
}

_TO_ARROW = {
    DataType.BOOL: pa.bool_(),
    DataType.INT8: pa.int8(),
    DataType.INT16: pa.int16(),
    DataType.INT32: pa.int32(),
    DataType.INT64: pa.int64(),
    DataType.UINT8: pa.uint8(),
    DataType.UINT16: pa.uint16(),
    DataType.UINT32: pa.uint32(),
    DataType.UINT64: pa.uint64(),
    DataType.FLOAT32: pa.float32(),
    DataType.FLOAT64: pa.float64(),
    DataType.STRING: pa.string(),
    DataType.BINARY: pa.binary(),
}
_FROM_ARROW = {v: k for k, v in _TO_ARROW.items()}


@dataclass(frozen=True)
class Value:
    """A single typed scalar (reference: src/datatypes/src/value.rs)."""

    dtype: DataType
    value: object  # python scalar; None == NULL

    @property
    def is_null(self) -> bool:
        return self.value is None


def parse_sql_type(name: str) -> DataType:
    """Map SQL type names to DataType (reference: sql/src/statements.rs
    sql_data_type_to_concrete_data_type)."""
    n = name.strip().lower()
    aliases = {
        "boolean": DataType.BOOL, "bool": DataType.BOOL,
        "tinyint": DataType.INT8, "smallint": DataType.INT16,
        "int": DataType.INT32, "integer": DataType.INT32,
        "int8": DataType.INT8, "int16": DataType.INT16,
        "int32": DataType.INT32, "int64": DataType.INT64,
        "bigint": DataType.INT64,
        "tinyint unsigned": DataType.UINT8,
        "smallint unsigned": DataType.UINT16,
        "int unsigned": DataType.UINT32,
        "bigint unsigned": DataType.UINT64,
        "uint8": DataType.UINT8, "uint16": DataType.UINT16,
        "uint32": DataType.UINT32, "uint64": DataType.UINT64,
        "float": DataType.FLOAT32, "float32": DataType.FLOAT32,
        "real": DataType.FLOAT32,
        "double": DataType.FLOAT64, "float64": DataType.FLOAT64,
        "string": DataType.STRING, "text": DataType.STRING,
        "varchar": DataType.STRING, "char": DataType.STRING,
        "binary": DataType.BINARY, "varbinary": DataType.BINARY,
        "timestamp": DataType.TIMESTAMP_MILLISECOND,
        "timestamp_s": DataType.TIMESTAMP_SECOND,
        "timestamp_ms": DataType.TIMESTAMP_MILLISECOND,
        "timestamp_us": DataType.TIMESTAMP_MICROSECOND,
        "timestamp_ns": DataType.TIMESTAMP_NANOSECOND,
        "timestamp(0)": DataType.TIMESTAMP_SECOND,
        "timestamp(3)": DataType.TIMESTAMP_MILLISECOND,
        "timestamp(6)": DataType.TIMESTAMP_MICROSECOND,
        "timestamp(9)": DataType.TIMESTAMP_NANOSECOND,
        "datetime": DataType.TIMESTAMP_MICROSECOND,
        "date": DataType.TIMESTAMP_MILLISECOND,
    }
    if n in aliases:
        return aliases[n]
    raise ValueError(f"unsupported SQL type: {name!r}")
