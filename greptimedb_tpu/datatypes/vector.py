"""Column vectors. Mirrors the reference's Vector hierarchy
(src/datatypes/src/vectors.rs:88) but collapsed to two host representations:

- plain numpy arrays (numeric/timestamp/bool), nullable via a separate mask
- `DictVector` for strings/tags: int32 codes + a value table. The device
  kernel ABI only ever sees the codes (SURVEY.md §7: dictionary-encoded tag
  ids end-to-end, matching mito2's dictionary-encoded primary keys,
  reference sst/parquet/format.rs).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional, Sequence

import numpy as np
import pyarrow as pa


@dataclass
class DictVector:
    """Dictionary-encoded string column: codes[i] indexes values.

    code -1 encodes NULL (pyarrow dictionary nulls round-trip through this).
    """

    codes: np.ndarray  # int32 [N]
    values: np.ndarray  # object/str [K]

    def __post_init__(self):
        self.codes = np.asarray(self.codes, dtype=np.int32)
        self.values = np.asarray(self.values, dtype=object)

    def __len__(self) -> int:
        return len(self.codes)

    def decode(self) -> np.ndarray:
        """Materialize the string values (host-side, edges only)."""
        out = np.empty(len(self.codes), dtype=object)
        valid = self.codes >= 0
        out[valid] = self.values[self.codes[valid]]
        out[~valid] = None
        return out

    def take(self, indices: np.ndarray) -> "DictVector":
        return DictVector(self.codes[indices], self.values)

    def compact(self) -> "DictVector":
        """Shrink the dictionary to the values the codes actually use
        (NULL codes preserved). The partition write scatter calls this
        per region slice: without it every region's tag registry learns
        every OTHER region's series too, which silently disables any
        optimization that reasons over the registry's value set
        (lastpoint's newest-first termination waits forever for series
        that can never appear in that region)."""
        used = np.unique(self.codes)
        used = used[used >= 0]
        if len(used) == len(self.values):
            return self
        remap = np.full(len(self.values) + 1, -1, dtype=np.int32)
        remap[used] = np.arange(len(used), dtype=np.int32)
        # index -1 hits the sentinel slot (remap[-1] == last) — keep
        # NULLs NULL by writing the sentinel last
        remap[-1] = -1
        return DictVector(remap[self.codes], self.values[used])

    @staticmethod
    def encode(strings: Sequence, values: Optional[np.ndarray] = None) -> "DictVector":
        """Encode a sequence of strings (None == NULL) against an optional
        pre-existing dictionary; new values are appended. Vectorized via
        np.unique — only distinct values touch Python."""
        arr = np.asarray(strings, dtype=object)
        table: dict = {}
        vals: list = []
        if values is not None:
            vals = list(values)
            table = {v: i for i, v in enumerate(vals)}
        if len(arr) == 0:
            return DictVector(np.empty(0, np.int32), np.asarray(vals, dtype=object))
        null_mask = np.frompyfunc(lambda x: x is None, 1, 1)(arr).astype(bool)
        codes = np.full(len(arr), -1, dtype=np.int32)
        present = ~null_mask
        if present.any():
            uniq, inv = np.unique(arr[present].astype(str), return_inverse=True)
            mapping = np.empty(len(uniq), dtype=np.int32)
            for i, s in enumerate(uniq):
                code = table.get(s)
                if code is None:
                    code = len(vals)
                    table[s] = code
                    vals.append(s)
                mapping[i] = code
            codes[present] = mapping[inv]
        return DictVector(codes, np.asarray(vals, dtype=object))

    @staticmethod
    def from_arrow(arr: pa.Array) -> "DictVector":
        arr = arr.combine_chunks() if isinstance(arr, pa.ChunkedArray) else arr
        if pa.types.is_dictionary(arr.type):
            codes = arr.indices.to_numpy(zero_copy_only=False)
            codes = np.where(np.isnan(codes), -1, codes) if codes.dtype.kind == "f" else codes
            values = np.asarray(arr.dictionary.to_pylist(), dtype=object)
            return DictVector(codes.astype(np.int32), values)
        return DictVector.encode(arr.to_pylist())

    def to_arrow(self) -> pa.Array:
        mask = self.codes < 0
        codes = pa.array(self.codes, type=pa.int32(), mask=mask)
        return pa.DictionaryArray.from_arrays(codes, pa.array(list(self.values), type=pa.string()))

    def remap(self, mapping: np.ndarray) -> "DictVector":
        """Rewrite codes through `mapping` (old code -> new code), used when
        merging per-SST dictionaries into a region-global dictionary."""
        return DictVector(remap_codes(self.codes, mapping), self.values)


def remap_codes(codes: np.ndarray, mapping: np.ndarray) -> np.ndarray:
    """codes -> mapping[codes] with NULL (-1) preserved. Safe for an empty
    mapping — an all-NULL tag column has an empty dictionary, and indexing
    an empty array even with clipped codes raises."""
    codes = np.asarray(codes)
    if mapping.size == 0:
        return np.full(len(codes), -1, dtype=np.int32)
    return np.where(codes >= 0,
                    mapping[np.clip(codes, 0, None)], -1).astype(np.int32)
