"""Deterministic fault injection ("chaos") + unified resilience layer.

The reference survives node death because failures are *routine*: every
I/O edge retries transient errors, and the metadata plane converts node
loss into failover (PAPER.md §1). This package supplies both halves for
the reproduction:

- `FaultRegistry` (`FAULTS`, process-wide): named injection points armed
  with deterministic, seed-driven schedules. The I/O seams call
  `FAULTS.fire(point)` (control-path faults: fail / latency) or
  `FAULTS.mangle(point, data)` (data-path faults: torn writes / short
  reads) at their boundaries. Unarmed points cost ONE dict lookup —
  production builds pay nothing else.
- `RetryPolicy` / `retry_call` (fault.retry): capped exponential backoff
  with full jitter and a deadline, shared by every call site that used
  to fail hard (object store backends, WAL append/replay, Flight RPC,
  the region router).

Injection points (the fault matrix, see README "Robustness & chaos
testing"):

    objectstore.read   objectstore.write
    wal.append         wal.replay
    flight.do_get      flight.do_put
    heartbeat.send     datanode.crash
    metasrv.kv         (KV ops over the kv_service HTTP seam; per-op
                        targeting via @op:get|put|cas|range|delete|watch)
    election.lease     (forced lease expiry in meta/election.py)

Arming is programmatic (`FAULTS.arm("wal.append", Fault(...))`) or via
env so child datanode processes inherit the schedule:

    GTPU_CHAOS="objectstore.read=fail,nth:3;flight.do_get=latency,arg:0.05,prob:0.5"
    GTPU_CHAOS_SEED=42

Network partitions (Jepsen-style nemesis): faults can be scoped to a
(src-node, dst-node) EDGE on the points that cross a process boundary
(`flight.do_get`/`do_put`, `heartbeat.send`, `metasrv.kv`), and the
registry holds partition state installable without arming a schedule:

    FAULTS.install_partition("frontend", "dn-1")      # symmetric
    FAULTS.heal_partition("frontend", "dn-1")
    GTPU_CHAOS="partition=frontend<->dn-1"            # same, via env
    GTPU_CHAOS="heartbeat.send=fail,@edge:dn-1->metasrv-0"  # asymmetric

Partitions may carry a call-count WINDOW so install/heal timing lives
in the same deterministic call-space as nth schedules (the chaos
explorer samples these): `partition=a<->b,nth:3,times:5` drops calls
3..7 on each cut direction independently, then heals itself. Without a
window the cut is permanent until heal_partition()/reset().

(coordinator-bound edges name the metasrv's real node id — default
"metasrv-0" — so HA runs can cut a node from ONE metasrv peer)

Every partitioned call raises a transient FaultError(kind="partition")
and counts under `fault_injections_total{kind="partition",edge="a->b"}`
— the retry + degradation layers treat it exactly like a dropped
packet.

Every probabilistic schedule draws from its own `random.Random` seeded
by `GTPU_CHAOS_SEED` (xor'd with the crc32 of the point name at arm
time, so different points fire independently), and the same seed
reproduces the same fault schedule call-for-call. Every injection
is counted in `greptimedb_tpu_fault_injections_total{point,kind}`
(utils/metrics.py) and rendered at /metrics.
"""

from __future__ import annotations

import logging
import os
import threading
import time
from dataclasses import dataclass, field
from typing import Optional

from greptimedb_tpu.utils.metrics import FAULT_INJECTIONS

from .retry import (  # noqa: F401 — the package's public resilience surface
    DEFAULT_POLICY,
    RetryPolicy,
    Unavailable,
    retry_call,
)

#: canonical injection points — arming anything else is a typo guard
POINTS = frozenset({
    "objectstore.read", "objectstore.write",
    "wal.append", "wal.replay",
    "flight.do_get", "flight.do_put",
    "heartbeat.send", "datanode.crash",
    # metadata-plane KV over the kv_service HTTP seam (ROADMAP fault
    # matrix): fired per dispatched op with an `op` label, so chaos runs
    # can target (and count) get/put/cas/range/delete independently
    "metasrv.kv",
    # election lease loss (meta/election.py): a fired fault force-expires
    # the held lease so elections churn under test (GC-pause analog)
    "election.lease",
    # background maintenance plane (maintenance/scheduler.py): fired at
    # job start (labels op=flush|compact|rollup|expire, phase=start) and
    # at each job's manifest/coverage swap boundary (phase=swap) — chaos
    # runs crash a compaction mid-swap and assert the pre-compaction
    # file list stays readable
    "maintenance.job",
    # per-region group-commit ingest pipeline (storage/group_commit.py):
    # fired when a leader starts draining the queue (op=drain), before
    # the WAL append+fsync (op=append), and between the durable append
    # and the memtable apply (op=apply) — chaos runs kill a leader
    # mid-drain and assert no acknowledged write is lost and no torn
    # WAL frame survives; @op targets one phase
    "ingest.commit",
    # OTLP trace exporter POST (utils/otlp_trace.py): fired before each
    # export batch hits the wire — chaos runs arm it to prove a dead
    # collector degrades typed (failed counter, log throttle) with zero
    # query impact
    "otlp.export",
})

#: points that cross a process boundary and therefore have a peer: the
#: only points a (src, dst) edge matcher or a partition can apply to
EDGE_POINTS = frozenset({
    "flight.do_get", "flight.do_put", "heartbeat.send", "metasrv.kv",
})

#: fault kinds a schedule can produce ("partition" is registry state,
#: not an armable schedule kind — see install_partition)
KINDS = frozenset({"fail", "latency", "torn", "short_read", "enospc"})

_LOG = logging.getLogger("greptimedb_tpu.fault")


def _log_throttle_s() -> float:
    """Per-(point, kind) minimum spacing of injection log lines —
    chaos schedules can fire thousands of times a second and the log
    must stay readable (GTPU_CHAOS_LOG_THROTTLE_S overrides)."""
    try:
        return float(os.environ.get("GTPU_CHAOS_LOG_THROTTLE_S", "1.0"))
    except ValueError:
        return 1.0


def chaos_seed() -> int:
    """The run's chaos seed (GTPU_CHAOS_SEED, default 0). Printed by the
    chaos test harness on failure so any red run is replayable."""
    try:
        return int(os.environ.get("GTPU_CHAOS_SEED", "0"))
    except ValueError:
        return 0


def local_node() -> str:
    """This process's node identity for edge-scoped faults: datanode
    children carry GTPU_NODE_ID (stamped at spawn); the parent process
    plays the frontend role."""
    return os.environ.get("GTPU_NODE_ID") or "frontend"


def _parse_edge(spec: str) -> list[tuple[str, str]]:
    """'a->b' (asymmetric) or 'a<->b' (symmetric) → directed edge list
    (a symmetric spec is simply both directions)."""
    if "<->" in spec:
        a, _, b = spec.partition("<->")
        sym = True
    elif "->" in spec:
        a, _, b = spec.partition("->")
        sym = False
    else:
        raise ValueError(f"bad edge spec {spec!r} (want 'a->b' or 'a<->b')")
    a, b = a.strip(), b.strip()
    if not a or not b:
        raise ValueError(f"bad edge spec {spec!r}: empty endpoint")
    if any("," in e or "->" in e for e in (a, b)):
        # "partition=a<->b,c" or "a->b<->c" would otherwise install an
        # inert cut whose endpoint literally contains the junk — a
        # malformed spec must raise, never yield a meaningless green run
        raise ValueError(
            f"bad edge spec {spec!r}: one edge per entry "
            "(separate entries with ';')")
    return [(a, b), (b, a)] if sym else [(a, b)]


def _deadline_sleep(delay_s: float, point: str) -> None:
    """Injected latency honours the caller's deadline: a stall that
    outlives the query budget surfaces as typed DeadlineExceeded at the
    injection point instead of blocking uninterruptibly — the same
    behaviour a real slow peer exhibits once gRPC deadlines fire.
    Lazy import: utils.deadline depends on fault.retry."""
    from greptimedb_tpu.utils import deadline
    deadline.sleep(delay_s, f"injected latency at {point}")


class FaultError(Exception):
    """An injected fault. `transient=True` faults model retryable I/O
    errors (including partition drops — a healed cut makes the retry
    meaningful); torn writes and enospc are non-transient (a crash
    mid-write already put partial bytes down; a full disk does not
    un-fill itself inside a retry budget)."""

    def __init__(self, point: str, kind: str = "fail",
                 transient: bool = True):
        super().__init__(f"injected {kind} fault at {point!r}")
        self.point = point
        self.kind = kind
        self.transient = transient


@dataclass
class Fault:
    """One armed schedule: WHAT to inject (`kind` + `arg`) and WHEN
    (`nth`/`times` for fail-Nth, `prob` for seeded coin flips, neither
    for every call).

    kind: fail | latency | torn | short_read
    arg:  latency seconds, or the fraction of bytes KEPT by torn/short
    """

    kind: str = "fail"
    arg: float = 0.0
    nth: Optional[int] = None  # fire on the nth call (1-based)...
    times: int = 1             # ...and the following times-1 calls
    prob: float = 0.0          # or per-call probability (seed-driven)
    seed: Optional[int] = None
    #: only fire when the call site's labels match (Jepsen-style nemesis
    #: targeting, e.g. {"node": "dn-1"} drops ONE node's heartbeats);
    #: non-matching calls do not consume the schedule
    match: Optional[dict] = None
    #: only fire on these directed (src, dst) edges — faults scoped to a
    #: node PAIR rather than a point (asymmetric/symmetric partitions);
    #: valid only on EDGE_POINTS, checked at arm time
    edges: Optional[list] = None

    calls: int = field(default=0, init=False)

    def __post_init__(self):
        if self.kind not in KINDS:
            raise ValueError(f"unknown fault kind {self.kind!r}")
        import random

        self._rng = random.Random(
            self.seed if self.seed is not None else chaos_seed())
        # fire/mangle run on server threads (Flight handlers, HTTP pool):
        # unsynchronized counter/rng draws would break nth schedules and
        # the seed-replay guarantee
        self._lock = threading.Lock()

    def matches(self, labels: dict) -> bool:
        if self.edges is not None and \
                (labels.get("src"), labels.get("dst")) not in self.edges:
            return False
        if labels.get("side") == "server" and \
                (not self.match or "side" not in self.match):
            # a flight.* call now has TWO seams (client RPC + inside the
            # server's scan span). Schedules without an explicit @side
            # keep their PR-1 meaning — the client seam only — so
            # existing nth/prob specs replay call-for-call; @side:server
            # opts into the in-server seam
            return False
        return not self.match or all(
            labels.get(k) == v for k, v in self.match.items())

    def should_fire(self) -> bool:
        with self._lock:
            self.calls += 1
            if self.nth is not None:
                return self.nth <= self.calls < self.nth + self.times
            if self.prob:
                return self._rng.random() < self.prob
            return True


class FaultRegistry:
    """Process-wide named injection points. Disarmed points cost one
    dict lookup; `reset()` between chaos tests restores production
    behavior."""

    def __init__(self):
        self._points: dict[str, Fault] = {}
        #: installed network partitions: directed (src, dst) edge →
        #: optional call-count window ({"nth", "times", "calls"}, None =
        #: permanent) every EDGE_POINTS call is checked against, armed
        #: schedule or not
        self._partitions: dict = {}
        #: cluster topology registered by the harnesses — when non-empty,
        #: edge/@node specs naming an unknown node fail at arm time (the
        #: typo guard that matches the canonical-point check)
        self._known_nodes: set = set()
        self._lock = threading.Lock()
        #: last injection log timestamp per (point, kind) — see
        #: _log_injection
        self._log_last: dict = {}

    # ---- topology -----------------------------------------------------------

    def register_nodes(self, node_ids) -> None:
        """Declare the run's node identities (datanodes + 'frontend' +
        'metasrv' + any metasrv election ids) so per-edge specs are
        validated against real topology."""
        with self._lock:
            self._known_nodes.update(str(n) for n in node_ids)

    def _check_node(self, node: str, what: str) -> None:
        if self._known_nodes and node not in self._known_nodes:
            raise ValueError(
                f"unknown node {node!r} in {what} "
                f"(known: {sorted(self._known_nodes)})")

    # ---- partitions ----------------------------------------------------------

    def install_partition(self, a: str, b: str, symmetric: bool = True,
                          nth: Optional[int] = None,
                          times: int = 1) -> None:
        """Sever the network between two nodes: every EDGE_POINTS call
        whose (src, dst) crosses the cut raises a transient
        FaultError(kind="partition"). Symmetric by default; pass
        symmetric=False to cut only the a→b direction.

        With `nth` the cut is WINDOWED: only calls nth..nth+times-1 on
        the edge drop (each direction counts its own calls), after which
        the cut self-heals — install/heal timing expressed in the same
        deterministic call-space as nth fault schedules."""
        for n in (a, b):
            self._check_node(n, "install_partition")
        window = None
        if nth is not None:
            if nth < 1 or times < 1:
                raise ValueError(
                    f"bad partition window nth:{nth},times:{times} "
                    "(nth and times are 1-based counts)")
            window = {"nth": nth, "times": times}
        with self._lock:
            self._partitions[(a, b)] = \
                dict(window, calls=0) if window else None
            if symmetric:
                self._partitions[(b, a)] = \
                    dict(window, calls=0) if window else None

    def heal_partition(self, a: str, b: str,
                       symmetric: bool = True) -> None:
        with self._lock:
            self._partitions.pop((a, b), None)
            if symmetric:
                self._partitions.pop((b, a), None)

    def heal_partitions(self) -> None:
        with self._lock:
            self._partitions.clear()

    def partitions(self) -> list[str]:
        """Installed cuts as 'src->dst' strings (debug surfaces)."""
        with self._lock:
            return sorted(f"{a}->{b}" for a, b in self._partitions)

    # ---- arming -------------------------------------------------------------

    def arm(self, point: str, fault: Fault) -> None:
        if point not in POINTS:
            raise ValueError(
                f"unknown injection point {point!r} (have: {sorted(POINTS)})")
        if fault.edges is not None:
            if point not in EDGE_POINTS:
                raise ValueError(
                    f"point {point!r} has no peer concept — @edge "
                    f"matchers apply only to {sorted(EDGE_POINTS)}")
            for a, b in fault.edges:
                self._check_node(a, f"@edge on {point}")
                self._check_node(b, f"@edge on {point}")
        if fault.match:
            # every node-valued matcher key is topology-checked — a
            # typo'd @node/@src/@dst would otherwise never fire and
            # silently green the run
            for key in ("node", "src", "dst"):
                if key in fault.match:
                    self._check_node(fault.match[key],
                                     f"@{key} on {point}")
        if fault.seed is None:
            # default seeding decorrelates points (crc32, stable across
            # processes — hash() is salted) while staying replayable
            # from GTPU_CHAOS_SEED alone; an explicit seed wins
            import random
            import zlib

            fault._rng = random.Random(
                chaos_seed() ^ zlib.crc32(point.encode()))
        with self._lock:
            self._points[point] = fault

    def disarm(self, point: str) -> None:
        with self._lock:
            self._points.pop(point, None)

    def reset(self) -> None:
        with self._lock:
            self._points.clear()
            self._partitions.clear()
            self._known_nodes.clear()

    def armed(self, point: str) -> bool:
        return point in self._points

    def describe(self) -> list[dict]:
        """Armed schedules as dicts (the debug surface behind
        information_schema.cluster_faults and /v1/faults)."""
        with self._lock:
            out = []
            for point, f in sorted(self._points.items()):
                sched = "always"
                if f.nth is not None:
                    sched = f"nth:{f.nth}" + \
                        (f",times:{f.times}" if f.times != 1 else "")
                elif f.prob:
                    sched = f"prob:{f.prob}"
                out.append({
                    "point": point, "kind": f.kind, "schedule": sched,
                    "arg": f.arg,
                    "match": dict(f.match) if f.match else {},
                    "edges": sorted(f"{a}->{b}" for a, b in f.edges)
                    if f.edges else [],
                    "calls": f.calls,
                })
            return out

    def fingerprint(self) -> dict:
        """Canonical armed-state snapshot (schedules + partitions, call
        counters excluded) for schedule-equality checks — the repro
        round-trip contract: `arm_from_env(repro's GTPU_CHAOS)` on a
        fresh registry must produce an identical fingerprint."""
        with self._lock:
            points = {}
            for point, f in sorted(self._points.items()):
                points[point] = {
                    "kind": f.kind, "arg": f.arg, "nth": f.nth,
                    "times": f.times, "prob": f.prob, "seed": f.seed,
                    "match": dict(f.match) if f.match else {},
                    "edges": sorted(f"{a}->{b}" for a, b in f.edges)
                    if f.edges else [],
                }
            parts = {}
            for (a, b), window in sorted(self._partitions.items()):
                parts[f"{a}->{b}"] = None if window is None else {
                    "nth": window["nth"], "times": window["times"]}
            return {"points": points, "partitions": parts}

    def arm_from_env(self, spec: Optional[str] = None) -> None:
        """Parse GTPU_CHAOS and arm each entry. Grammar (`;`-separated):

            point=kind[,nth:N][,times:T][,prob:P][,arg:F][,seed:S][,@label:value]
            partition=a<->b | a->b  [,nth:N][,times:T]

        `@label:value` tokens restrict the fault to matching call sites
        (e.g. `heartbeat.send=fail,@node:dn-1`); `@edge:a->b` (or
        `a<->b`) restricts to a node pair on the points that have one.
        A `partition=` entry installs registry-level partition state —
        no schedule needed, every matching call drops. A malformed spec
        raises — silently ignoring a chaos schedule would make a green
        run meaningless."""
        spec = spec if spec is not None else os.environ.get("GTPU_CHAOS", "")
        for entry in filter(None, (s.strip() for s in spec.split(";"))):
            point, _, rhs = entry.partition("=")
            if not rhs:
                raise ValueError(f"bad GTPU_CHAOS entry {entry!r}")
            point = point.strip()
            if point == "partition":
                ptoks = [t.strip() for t in rhs.split(",") if t.strip()]
                pkw: dict = {}
                for tok in ptoks[1:]:
                    k, _, v = tok.partition(":")
                    if k in ("nth", "times"):
                        pkw[k] = int(v)
                    else:
                        raise ValueError(
                            f"bad partition token {tok!r} in {entry!r}")
                for a, b in _parse_edge(ptoks[0]):
                    self.install_partition(a, b, symmetric=False, **pkw)
                continue
            tokens = [t.strip() for t in rhs.split(",") if t.strip()]
            kw: dict = {"kind": tokens[0]}
            for tok in tokens[1:]:
                k, _, v = tok.partition(":")
                if k == "@edge":
                    kw["edges"] = _parse_edge(v)
                elif k.startswith("@"):
                    kw.setdefault("match", {})[k[1:]] = v
                elif k in ("nth", "times", "seed"):
                    kw[k] = int(v)
                elif k in ("prob", "arg"):
                    kw[k] = float(v)
                else:
                    raise ValueError(
                        f"bad GTPU_CHAOS token {tok!r} in {entry!r}")
            self.arm(point, Fault(**kw))

    # ---- firing -------------------------------------------------------------

    @staticmethod
    def _counter_labels(labels: Optional[dict]) -> dict:
        """Collapse src/dst into the `edge` label the observability
        surfaces key on (keeps counter cardinality at #edges, not
        #src × #dst)."""
        out = {k: str(v) for k, v in (labels or {}).items()
               if k not in ("src", "dst")}
        if labels and "src" in labels and "dst" in labels:
            out["edge"] = f"{labels['src']}->{labels['dst']}"
        return out

    def _check_partition(self, point: str, labels: dict) -> None:
        if not self._partitions or point not in EDGE_POINTS:
            return
        edge = (labels.get("src"), labels.get("dst"))
        with self._lock:
            if edge not in self._partitions:
                return
            window = self._partitions[edge]
            if window is not None:
                # windowed cut: count this edge's calls and drop only
                # inside [nth, nth+times) — outside the window the call
                # passes (the cut is installed but not yet/no longer
                # active)
                window["calls"] += 1
                lo = window["nth"]
                if not (lo <= window["calls"] < lo + window["times"]):
                    return
        FAULT_INJECTIONS.inc(point=point, kind="partition",
                             edge=f"{edge[0]}->{edge[1]}")
        self._log_injection(point, "partition", labels)
        raise FaultError(point, kind="partition")

    def _log_injection(self, point: str, kind: str,
                       labels: Optional[dict]) -> None:
        """Throttled WARNING line per injection, stamped with the active
        tracing span's trace_id (utils/tracing contextvar) so a red
        chaos run links straight to its span tree. Never raises."""
        try:
            now = time.monotonic()
            key = (point, kind)
            with self._lock:
                last = self._log_last.get(key)
                if last is not None and now - last < _log_throttle_s():
                    return
                self._log_last[key] = now
            trace_id = None
            try:
                from greptimedb_tpu.utils.tracing import current_trace_id

                trace_id = current_trace_id()
            except Exception:  # noqa: BLE001 — tracing is optional here
                pass
            extra = " ".join(
                f"{k}={v}" for k, v in sorted((labels or {}).items()))
            _LOG.warning(
                "fault injected point=%s kind=%s%s%s", point, kind,
                f" {extra}" if extra else "",
                f" trace_id={trace_id}" if trace_id else "")
        except Exception:  # noqa: BLE001 — logging must never mask the fault
            pass

    def fire(self, point: str, **labels) -> None:
        """Control-path hook: may raise FaultError or sleep. Data-kind
        faults (torn/short_read) armed on a control-only point degrade
        to plain failures. Call-site labels ride into the
        fault_injections counter, so chaos assertions can distinguish
        e.g. which KV op, node, or edge the schedule actually hit."""
        self._check_partition(point, labels)
        fault = self._points.get(point)  # the one production dict lookup
        if fault is None or not fault.matches(labels):
            return
        self._apply(point, fault, labels)

    def mangle(self, point: str, data: bytes,
               **labels) -> tuple[bytes, Optional[str]]:
        """Data-path hook: returns (possibly truncated bytes, fail_kind).
        fail_kind "torn" means the caller must surface an error AFTER
        persisting the mangled bytes — the torn-write shape: partial
        bytes down, no acknowledgement. fail_kind "enospc" means the
        device is full: partial bytes may reach STAGING but must never
        become the durable object (mangled_write routes them through the
        caller's cleanup path). `@label`/`@edge` matchers apply here the
        same as in fire(): a non-matching call neither fires nor
        consumes the schedule."""
        fault = self._points.get(point)
        if fault is None or not fault.matches(labels):
            return data, None
        if not fault.should_fire():
            return data, None
        FAULT_INJECTIONS.inc(point=point, kind=fault.kind,
                             **self._counter_labels(labels))
        self._log_injection(point, fault.kind, labels)
        if fault.kind == "latency":
            _deadline_sleep(fault.arg, point)
            return data, None
        if fault.kind == "fail":
            raise FaultError(point)
        keep = max(0, min(len(data),
                          int(len(data) * (fault.arg or 0.5))))
        if fault.kind == "torn":
            return data[:keep], "torn"
        if fault.kind == "enospc":
            return data[:keep], "enospc"
        return data[:keep], None  # short_read: silent truncation

    def mangled_write(self, point: str, data: bytes, sink,
                      spill=None, **labels) -> None:
        """The shared data-path WRITE template: mangle, hand the
        (possibly truncated) bytes to `sink`, then surface the torn-write
        error — partial bytes persisted, call unacknowledged,
        non-retryable. Every durable-write seam (object store, local WAL,
        remote WAL) goes through here so torn semantics stay identical.

        enospc (disk full mid-write) differs from torn in WHERE the
        partial bytes land: they reach the seam's staging area via
        `spill(partial)` — an appended file tail, a tmp object — and the
        caller's crash-consistency path must erase them before the error
        surfaces (chaos tests verify no partial file survives). With no
        spill hook, nothing is persisted at all (atomic backends)."""
        mangled, fail_kind = self.mangle(point, data, **labels)
        if fail_kind == "enospc":
            if spill is not None:
                spill(mangled)
            raise FaultError(point, kind="enospc", transient=False)
        sink(mangled)
        if fail_kind or len(mangled) < len(data):
            # ANY truncation of a durable write must surface: silently
            # acknowledging short bytes (e.g. short_read armed on a
            # write seam) would be acknowledged-write loss by design
            raise FaultError(point, kind="torn", transient=False)

    def mangled_read(self, point: str, data: bytes, **labels) -> bytes:
        """The shared data-path READ template: a torn fault on a read
        means the bytes came back partial AND the error must surface —
        never silently serve the truncated data (that is `short_read`)."""
        mangled, fail_kind = self.mangle(point, data, **labels)
        if fail_kind:  # torn or enospc: never serve partial bytes
            raise FaultError(point, kind=fail_kind, transient=False)
        return mangled

    def _apply(self, point: str, fault: Fault,
               labels: Optional[dict] = None) -> None:
        if not fault.should_fire():
            return
        FAULT_INJECTIONS.inc(point=point, kind=fault.kind,
                             **self._counter_labels(labels))
        self._log_injection(point, fault.kind, labels)
        if fault.kind == "latency":
            _deadline_sleep(fault.arg, point)
            return
        raise FaultError(point, kind=fault.kind,
                         transient=fault.kind not in ("torn", "enospc"))


def is_transient(exc: BaseException) -> bool:
    """Shared retry/degradation predicate: injected transient faults,
    errors self-describing as transient (ObjectStoreError from a 5xx),
    and network-shaped stdlib errors."""
    if isinstance(exc, FaultError):
        return exc.transient
    if getattr(exc, "transient", False):
        return True
    return isinstance(exc, (ConnectionError, TimeoutError))


#: the process-wide registry every I/O seam consults
FAULTS = FaultRegistry()
FAULTS.arm_from_env()
