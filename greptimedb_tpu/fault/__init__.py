"""Deterministic fault injection ("chaos") + unified resilience layer.

The reference survives node death because failures are *routine*: every
I/O edge retries transient errors, and the metadata plane converts node
loss into failover (PAPER.md §1). This package supplies both halves for
the reproduction:

- `FaultRegistry` (`FAULTS`, process-wide): named injection points armed
  with deterministic, seed-driven schedules. The I/O seams call
  `FAULTS.fire(point)` (control-path faults: fail / latency) or
  `FAULTS.mangle(point, data)` (data-path faults: torn writes / short
  reads) at their boundaries. Unarmed points cost ONE dict lookup —
  production builds pay nothing else.
- `RetryPolicy` / `retry_call` (fault.retry): capped exponential backoff
  with full jitter and a deadline, shared by every call site that used
  to fail hard (object store backends, WAL append/replay, Flight RPC,
  the region router).

Injection points (the fault matrix, see README "Robustness & chaos
testing"):

    objectstore.read   objectstore.write
    wal.append         wal.replay
    flight.do_get      flight.do_put
    heartbeat.send     datanode.crash
    metasrv.kv         (KV ops over the kv_service HTTP seam; per-op
                        targeting via @op:get|put|cas|range|delete|watch)

Arming is programmatic (`FAULTS.arm("wal.append", Fault(...))`) or via
env so child datanode processes inherit the schedule:

    GTPU_CHAOS="objectstore.read=fail,nth:3;flight.do_get=latency,arg:0.05,prob:0.5"
    GTPU_CHAOS_SEED=42

Every probabilistic schedule draws from its own `random.Random` seeded
by `GTPU_CHAOS_SEED` (xor'd with the crc32 of the point name at arm
time, so different points fire independently), and the same seed
reproduces the same fault schedule call-for-call. Every injection
is counted in `greptimedb_tpu_fault_injections_total{point,kind}`
(utils/metrics.py) and rendered at /metrics.
"""

from __future__ import annotations

import os
import threading
import time
from dataclasses import dataclass, field
from typing import Optional

from greptimedb_tpu.utils.metrics import FAULT_INJECTIONS

from .retry import (  # noqa: F401 — the package's public resilience surface
    DEFAULT_POLICY,
    RetryPolicy,
    Unavailable,
    retry_call,
)

#: canonical injection points — arming anything else is a typo guard
POINTS = frozenset({
    "objectstore.read", "objectstore.write",
    "wal.append", "wal.replay",
    "flight.do_get", "flight.do_put",
    "heartbeat.send", "datanode.crash",
    # metadata-plane KV over the kv_service HTTP seam (ROADMAP fault
    # matrix): fired per dispatched op with an `op` label, so chaos runs
    # can target (and count) get/put/cas/range/delete independently
    "metasrv.kv",
})

#: fault kinds a schedule can produce
KINDS = frozenset({"fail", "latency", "torn", "short_read"})


def chaos_seed() -> int:
    """The run's chaos seed (GTPU_CHAOS_SEED, default 0). Printed by the
    chaos test harness on failure so any red run is replayable."""
    try:
        return int(os.environ.get("GTPU_CHAOS_SEED", "0"))
    except ValueError:
        return 0


class FaultError(Exception):
    """An injected fault. `transient=True` faults model retryable I/O
    errors; torn writes are non-transient (they model a crash mid-write —
    the bytes are already partially down, a retry is not what a dead
    process does)."""

    def __init__(self, point: str, kind: str = "fail",
                 transient: bool = True):
        super().__init__(f"injected {kind} fault at {point!r}")
        self.point = point
        self.kind = kind
        self.transient = transient


@dataclass
class Fault:
    """One armed schedule: WHAT to inject (`kind` + `arg`) and WHEN
    (`nth`/`times` for fail-Nth, `prob` for seeded coin flips, neither
    for every call).

    kind: fail | latency | torn | short_read
    arg:  latency seconds, or the fraction of bytes KEPT by torn/short
    """

    kind: str = "fail"
    arg: float = 0.0
    nth: Optional[int] = None  # fire on the nth call (1-based)...
    times: int = 1             # ...and the following times-1 calls
    prob: float = 0.0          # or per-call probability (seed-driven)
    seed: Optional[int] = None
    #: only fire when the call site's labels match (Jepsen-style nemesis
    #: targeting, e.g. {"node": "dn-1"} drops ONE node's heartbeats);
    #: non-matching calls do not consume the schedule
    match: Optional[dict] = None

    calls: int = field(default=0, init=False)

    def __post_init__(self):
        if self.kind not in KINDS:
            raise ValueError(f"unknown fault kind {self.kind!r}")
        import random

        self._rng = random.Random(
            self.seed if self.seed is not None else chaos_seed())
        # fire/mangle run on server threads (Flight handlers, HTTP pool):
        # unsynchronized counter/rng draws would break nth schedules and
        # the seed-replay guarantee
        self._lock = threading.Lock()

    def matches(self, labels: dict) -> bool:
        return not self.match or all(
            labels.get(k) == v for k, v in self.match.items())

    def should_fire(self) -> bool:
        with self._lock:
            self.calls += 1
            if self.nth is not None:
                return self.nth <= self.calls < self.nth + self.times
            if self.prob:
                return self._rng.random() < self.prob
            return True


class FaultRegistry:
    """Process-wide named injection points. Disarmed points cost one
    dict lookup; `reset()` between chaos tests restores production
    behavior."""

    def __init__(self):
        self._points: dict[str, Fault] = {}
        self._lock = threading.Lock()

    # ---- arming -------------------------------------------------------------

    def arm(self, point: str, fault: Fault) -> None:
        if point not in POINTS:
            raise ValueError(
                f"unknown injection point {point!r} (have: {sorted(POINTS)})")
        if fault.seed is None:
            # default seeding decorrelates points (crc32, stable across
            # processes — hash() is salted) while staying replayable
            # from GTPU_CHAOS_SEED alone; an explicit seed wins
            import random
            import zlib

            fault._rng = random.Random(
                chaos_seed() ^ zlib.crc32(point.encode()))
        with self._lock:
            self._points[point] = fault

    def disarm(self, point: str) -> None:
        with self._lock:
            self._points.pop(point, None)

    def reset(self) -> None:
        with self._lock:
            self._points.clear()

    def armed(self, point: str) -> bool:
        return point in self._points

    def arm_from_env(self, spec: Optional[str] = None) -> None:
        """Parse GTPU_CHAOS and arm each entry. Grammar (`;`-separated):

            point=kind[,nth:N][,times:T][,prob:P][,arg:F][,seed:S][,@label:value]

        `@label:value` tokens restrict the fault to matching call sites
        (e.g. `heartbeat.send=fail,@node:dn-1`). A malformed spec raises
        — silently ignoring a chaos schedule would make a green run
        meaningless."""
        spec = spec if spec is not None else os.environ.get("GTPU_CHAOS", "")
        for entry in filter(None, (s.strip() for s in spec.split(";"))):
            point, _, rhs = entry.partition("=")
            if not rhs:
                raise ValueError(f"bad GTPU_CHAOS entry {entry!r}")
            tokens = [t.strip() for t in rhs.split(",") if t.strip()]
            kw: dict = {"kind": tokens[0]}
            for tok in tokens[1:]:
                k, _, v = tok.partition(":")
                if k.startswith("@"):
                    kw.setdefault("match", {})[k[1:]] = v
                elif k in ("nth", "times", "seed"):
                    kw[k] = int(v)
                elif k in ("prob", "arg"):
                    kw[k] = float(v)
                else:
                    raise ValueError(
                        f"bad GTPU_CHAOS token {tok!r} in {entry!r}")
            self.arm(point.strip(), Fault(**kw))

    # ---- firing -------------------------------------------------------------

    def fire(self, point: str, **labels) -> None:
        """Control-path hook: may raise FaultError or sleep. Data-kind
        faults (torn/short_read) armed on a control-only point degrade
        to plain failures. Call-site labels ride into the
        fault_injections counter, so chaos assertions can distinguish
        e.g. which KV op or node the schedule actually hit."""
        fault = self._points.get(point)  # the one production dict lookup
        if fault is None or not fault.matches(labels):
            return
        self._apply(point, fault, labels)

    def mangle(self, point: str, data: bytes,
               **labels) -> tuple[bytes, bool]:
        """Data-path hook: returns (possibly truncated bytes, fail_after).
        `fail_after=True` means the caller must surface an error AFTER
        persisting the mangled bytes — the torn-write shape: partial
        bytes down, no acknowledgement. `@label` matchers apply here the
        same as in fire(): a non-matching call neither fires nor
        consumes the schedule."""
        fault = self._points.get(point)
        if fault is None or not fault.matches(labels):
            return data, False
        if not fault.should_fire():
            return data, False
        FAULT_INJECTIONS.inc(point=point, kind=fault.kind)
        if fault.kind == "latency":
            time.sleep(fault.arg)
            return data, False
        if fault.kind == "fail":
            raise FaultError(point)
        keep = max(0, min(len(data),
                          int(len(data) * (fault.arg or 0.5))))
        if fault.kind == "torn":
            return data[:keep], True
        return data[:keep], False  # short_read: silent truncation

    def mangled_write(self, point: str, data: bytes, sink,
                      **labels) -> None:
        """The shared data-path WRITE template: mangle, hand the
        (possibly truncated) bytes to `sink`, then surface the torn-write
        error — partial bytes persisted, call unacknowledged,
        non-retryable. Every durable-write seam (object store, local WAL,
        remote WAL) goes through here so torn semantics stay identical."""
        mangled, fail_after = self.mangle(point, data, **labels)
        sink(mangled)
        if fail_after or len(mangled) < len(data):
            # ANY truncation of a durable write must surface: silently
            # acknowledging short bytes (e.g. short_read armed on a
            # write seam) would be acknowledged-write loss by design
            raise FaultError(point, kind="torn", transient=False)

    def mangled_read(self, point: str, data: bytes, **labels) -> bytes:
        """The shared data-path READ template: a torn fault on a read
        means the bytes came back partial AND the error must surface —
        never silently serve the truncated data (that is `short_read`)."""
        mangled, fail_after = self.mangle(point, data, **labels)
        if fail_after:
            raise FaultError(point, kind="torn", transient=False)
        return mangled

    def _apply(self, point: str, fault: Fault,
               labels: Optional[dict] = None) -> None:
        if not fault.should_fire():
            return
        FAULT_INJECTIONS.inc(point=point, kind=fault.kind,
                             **{k: str(v) for k, v in (labels or {}).items()})
        if fault.kind == "latency":
            time.sleep(fault.arg)
            return
        raise FaultError(point, kind=fault.kind,
                         transient=fault.kind != "torn")


def is_transient(exc: BaseException) -> bool:
    """Shared retry/degradation predicate: injected transient faults,
    errors self-describing as transient (ObjectStoreError from a 5xx),
    and network-shaped stdlib errors."""
    if isinstance(exc, FaultError):
        return exc.transient
    if getattr(exc, "transient", False):
        return True
    return isinstance(exc, (ConnectionError, TimeoutError))


#: the process-wide registry every I/O seam consults
FAULTS = FaultRegistry()
FAULTS.arm_from_env()
