"""Generative chaos explorer: seeded random fault schedules + workloads
against live clusters, with the invariant checkers as a universal
oracle and delta-debugged (ddmin) minimal repros.

PR 3 shipped four hand-written compound scenarios; the Jepsen /
FoundationDB lesson is that hand-picked interleavings find the bugs you
imagined. This module samples the schedule space the registry already
defines — canonical POINTS × oracle-compatible KINDS × EDGE_POINTS
edges × timing knobs (nth/times/prob/arg), plus windowed partitions and
election lease-loss nemeses — and runs every sampled schedule against a
live ProcessCluster (or a 3-process MetasrvProcessCluster in election
mode) under a seeded random workload of concurrent writes, reads,
flush/compact ADMIN calls, node kills, and DDL.

Every run is checked by the PR-3 oracle: no acknowledged write lost,
at most one leader per lease epoch (CAS journal), failover inside its
beat deadline, typed-only degradation, no partial WAL objects. A
failing schedule is delta-debugged down to a minimal entry subset
(`ddmin`), re-verified, and printed as the standard bit-for-bit
`GTPU_CHAOS`/`GTPU_CHAOS_SEED` repro line — the same seed re-runs the
same schedule AND the same workload, entry for entry, op for op.

Determinism contract: schedules derive from `Random(f"schedule:{seed}")`
and workloads from `Random(f"workload:{seed}")` (string seeding hashes
via SHA-512, stable across processes), so `--replay --seed S` with the
printed GTPU_CHAOS regenerates the exact run. Nothing here reads the
wall clock for decisions.

Test-only bug hook: when GTPU_CHAOS_BUG is set ("point:<name>" or
"env:<substring>"), runs short-circuit BEFORE spawning a cluster — the
schedule is validated against a scratch registry and the hook decides
pass/fail. This lets the tier-1 suite prove the whole
explore → catch → shrink → repro pipeline (including that the minimal
repro line re-triggers the failure) in milliseconds.

CLI: tools/chaos_explorer.py. Metrics:
`greptimedb_tpu_chaos_runs_total{outcome}` and
`greptimedb_tpu_chaos_shrink_steps_total`.
"""

from __future__ import annotations

import os
import random
import tempfile
import time
from contextlib import contextmanager
from dataclasses import dataclass
from typing import Callable, Optional, Sequence

from ..utils.metrics import CHAOS_RUNS, CHAOS_SHRINK_STEPS
from . import FAULTS, FaultRegistry
from .scenarios import (
    BEAT_MS,
    CREATE,
    ElectionEpochJournal,
    InvariantViolation,
    ScenarioRun,
    _typed_failure,
    _warm_up,
    scenario_cluster,
    try_insert,
    verify_acked,
    verify_epochs,
    verify_wal_objects_clean,
)

#: oracle-compatible kind pool per point for DATA-plane exploration.
#: Deliberately narrower than KINDS: torn/short_read on these seams
#: corrupt bytes the oracle's strict checkers (complete-frame WAL scan,
#: Arrow decode) would flag as red without any bug — those kinds keep
#: their hand-written scenarios. enospc rides the proven spill+cleanup
#: path; everything else degrades typed through the retry layer.
CLUSTER_KIND_POOL = {
    "objectstore.read": ("fail", "latency"),
    "objectstore.write": ("fail", "latency", "enospc"),
    "wal.append": ("fail", "latency", "enospc"),
    "wal.replay": ("fail", "latency"),
    "flight.do_get": ("fail", "latency"),
    "flight.do_put": ("fail", "latency"),
    "heartbeat.send": ("fail", "latency"),
    "ingest.commit": ("fail", "latency"),
    "maintenance.job": ("fail", "latency"),
}

#: election-mode pool: lease loss in the child + wire chaos in the
#: parent's kv_service seam
ELECTION_KIND_POOL = {
    "election.lease": ("fail",),
    "metasrv.kv": ("fail", "latency"),
}


@dataclass(frozen=True)
class Topology:
    """The node universe a sampler draws edges/targets from — derived
    from what the harness will register, so every sampled spec passes
    the registry's arm-time validation."""

    datanodes: tuple = ()
    metasrvs: tuple = ()
    frontend: str = "frontend"
    coordinator: str = "metasrv-0"  # ProcessCluster's metasrv node id

    @classmethod
    def cluster(cls, num_datanodes: int) -> "Topology":
        return cls(datanodes=tuple(f"dn-{i}"
                                   for i in range(num_datanodes)))

    @classmethod
    def election(cls, num_metasrv: int) -> "Topology":
        from ..cluster.metasrv_cluster import KV_HOST_ID

        return cls(metasrvs=tuple(f"meta-{i}"
                                  for i in range(num_metasrv)),
                   coordinator=KV_HOST_ID)


@dataclass(frozen=True)
class ScheduleEntry:
    """One sampled GTPU_CHAOS entry (a fault schedule or a partition).
    `to_env()` emits exactly the registry grammar, so a schedule and its
    env string round-trip bit-for-bit."""

    point: str                      # a POINTS name or "partition"
    kind: str                       # KINDS member ("partition" implied)
    nth: Optional[int] = None
    times: int = 1
    prob: float = 0.0
    arg: Optional[float] = None
    node: Optional[str] = None      # @node matcher
    edge: Optional[str] = None      # "a->b" / "a<->b" (@edge / cut spec)

    def to_env(self) -> str:
        if self.point == "partition":
            s = f"partition={self.edge}"
            if self.nth is not None:
                s += f",nth:{self.nth}"
                if self.times != 1:
                    s += f",times:{self.times}"
            return s
        toks = [f"{self.point}={self.kind}"]
        if self.nth is not None:
            toks.append(f"nth:{self.nth}")
            if self.times != 1:
                toks.append(f"times:{self.times}")
        if self.prob:
            toks.append(f"prob:{self.prob}")
        if self.arg is not None:
            toks.append(f"arg:{self.arg}")
        if self.node:
            toks.append(f"@node:{self.node}")
        if self.edge:
            toks.append(f"@edge:{self.edge}")
        return ",".join(toks)


def compile_env(entries: Sequence) -> str:
    """Entries (ScheduleEntry or raw env strings) → one GTPU_CHAOS."""
    return ";".join(e.to_env() if isinstance(e, ScheduleEntry) else e
                    for e in entries)


def split_env(chaos_env: str) -> list[str]:
    """GTPU_CHAOS → entry strings (the ddmin atoms on replayed envs)."""
    return [s.strip() for s in chaos_env.split(";") if s.strip()]


# ---- samplers ----------------------------------------------------------------


def _sample_timing(rng: random.Random, entry: dict) -> None:
    """nth-window (70%) or seeded coin flips (30%) — both replay from
    the seed alone."""
    if rng.random() < 0.7:
        entry["nth"] = rng.randint(1, 10)
        entry["times"] = rng.randint(1, 3)
    else:
        entry["prob"] = round(rng.uniform(0.05, 0.25), 3)


#: seams where a beyond-deadline stall exercises the deadline plane —
#: the points a query's wall-clock actually crosses
_DEADLINE_STALL_POINTS = frozenset({
    "flight.do_get", "flight.do_put",
    "objectstore.read", "objectstore.write",
})


def _sample_deadline_ms(seed: int) -> Optional[int]:
    """Per-run query deadline, a pure function of the seed alone (so a
    shrunk replay arms the same budget): ~40% of runs serve under a
    tight `default_timeout_ms`, the rest run unbounded."""
    r = random.Random(f"deadline:{seed}")
    if r.random() < 0.4:
        return r.randint(400, 1200)
    return None


def _sample_arg(rng: random.Random, kind: str,
                deadline_ms: Optional[int] = None,
                point: str = "") -> Optional[float]:
    if kind == "latency":
        if deadline_ms is not None and point in _DEADLINE_STALL_POINTS \
                and rng.random() < 0.5:
            # a stall PAST the run's deadline: the response must still
            # arrive typed within deadline+ε (the oracle checks), not
            # hang for the stall's duration times the retry count
            return round(rng.uniform(deadline_ms * 1.2,
                                     deadline_ms * 3.0) / 1000.0, 3)
        # small enough to keep retry budgets green, large enough to be
        # on the clock
        return round(rng.uniform(0.001, 0.02), 4)
    if kind in ("torn", "short_read", "enospc"):
        return round(rng.uniform(0.0, 0.9), 2)   # fraction of bytes kept
    return None


def sample_schedule(rng: random.Random, topo: Topology,
                    max_entries: int = 4,
                    deadline_ms: Optional[int] = None
                    ) -> list[ScheduleEntry]:
    """A seeded random data-plane schedule: distinct points (the
    registry holds ONE schedule per point), oracle-compatible kinds,
    sampled timing, optional @node/@edge scoping, windowed partitions,
    and — on multi-datanode topologies — a datanode-kill nemesis."""
    slots = sorted(CLUSTER_KIND_POOL)
    slots.append("partition")
    if len(topo.datanodes) >= 2:
        # a kill needs a failover candidate; single-datanode runs keep
        # the cluster readable for the final verification instead
        slots.append("datanode.crash")
    rng.shuffle(slots)
    picked = slots[:rng.randint(2, max(2, max_entries))]
    entries = []
    for point in picked:
        if point == "partition":
            dn = rng.choice(topo.datanodes)
            entries.append(ScheduleEntry(
                point="partition", kind="partition",
                edge=f"{topo.frontend}<->{dn}",
                # always windowed: sampled cuts self-heal in call space,
                # so the final chaos-free verification can reach the node
                nth=rng.randint(1, 8), times=rng.randint(1, 5)))
            continue
        if point == "datanode.crash":
            # nth past the 5-round warm-up + first beats so the victim
            # has reported its regions (failover only covers regions the
            # coordinator has SEEN — an unreported region on a dead node
            # is an orphan by design, not a missed deadline)
            entries.append(ScheduleEntry(
                point=point, kind="fail", nth=rng.randint(7, 14),
                node=rng.choice(topo.datanodes)))
            continue
        kind = rng.choice(CLUSTER_KIND_POOL[point])
        entry: dict = {"point": point, "kind": kind,
                       "arg": _sample_arg(rng, kind, deadline_ms,
                                          point)}
        _sample_timing(rng, entry)
        if point in ("flight.do_get", "flight.do_put") \
                and rng.random() < 0.3:
            entry["edge"] = \
                f"{topo.frontend}->{rng.choice(topo.datanodes)}"
        elif point == "heartbeat.send" and rng.random() < 0.4:
            if rng.random() < 0.5:
                entry["edge"] = \
                    f"{rng.choice(topo.datanodes)}->{topo.coordinator}"
            else:
                entry["node"] = rng.choice(topo.datanodes)
        entries.append(ScheduleEntry(**entry))
    return entries


def sample_election_schedule(rng: random.Random, topo: Topology,
                             max_entries: int = 3) \
        -> list[ScheduleEntry]:
    """Election-mode nemeses: forced lease loss inside a metasrv child,
    kv_service wire faults, and windowed peer↔KV-host partitions."""
    entries = [ScheduleEntry(
        point="election.lease", kind="fail",
        nth=rng.randint(1, 5), times=rng.randint(1, 3),
        node=rng.choice(topo.metasrvs))]
    if rng.random() < 0.7 and max_entries >= 2:
        entry: dict = {"point": "metasrv.kv",
                       "kind": rng.choice(ELECTION_KIND_POOL["metasrv.kv"])}
        entry["arg"] = _sample_arg(rng, entry["kind"])
        _sample_timing(rng, entry)
        if rng.random() < 0.4:
            entry["edge"] = \
                f"{rng.choice(topo.metasrvs)}->{topo.coordinator}"
        entries.append(ScheduleEntry(**entry))
    if rng.random() < 0.6 and max_entries >= 3:
        entries.append(ScheduleEntry(
            point="partition", kind="partition",
            edge=f"{rng.choice(topo.metasrvs)}<->{topo.coordinator}",
            nth=rng.randint(1, 10), times=rng.randint(1, 6)))
    return entries


def sample_skews(rng: random.Random, topo: Topology,
                 lease_s: float) -> dict:
    """The clock nemesis: with 50% probability one metasrv peer runs
    skewed forward by up to 40% of a lease. Seed-derived, so the repro
    seed regenerates it — skew is scenario state, not a GTPU_CHAOS
    entry."""
    if rng.random() < 0.5 and topo.metasrvs:
        node = rng.choice(topo.metasrvs)
        return {node: round(rng.uniform(0.1, 0.4) * lease_s * 1000.0)}
    return {}


def sample_workload(rng: random.Random, steps: int, topo: Topology,
                    allow_kill: bool = True) -> list[tuple]:
    """A seeded random workload: tracked inserts, reads, virtual-clock
    beats, flush/compact ADMIN calls, DDL, and (multi-datanode) node
    kills. Pure function of the rng — execution never feeds back into
    the op sequence, so the same seed replays the same ops even when
    outcomes differ. `allow_kill=False` when the SCHEDULE already
    carries a datanode.crash nemesis: workload kills + a scheduled
    crash could together take every datanode down."""
    ops: list[tuple] = [("create",)]
    weighted = [("insert", 5.0), ("read", 3.0), ("beat", 4.0),
                ("flush", 1.0), ("compact", 1.0), ("ddl", 1.0)]
    killable = list(topo.datanodes[1:])  # dn-0 survives as the
    # failover candidate — every acked write must stay readable
    if allow_kill and killable and len(topo.datanodes) >= 2:
        weighted.append(("kill", 0.7))
    names = [w[0] for w in weighted]
    weights = [w[1] for w in weighted]
    insert_i = ddl_i = 0
    for _ in range(steps):
        op = rng.choices(names, weights=weights)[0]
        if op == "insert":
            ops.append(("insert", insert_i))
            insert_i += 1
        elif op == "ddl":
            ops.append(("ddl", ddl_i))
            ddl_i += 1
        elif op == "kill":
            if not killable:
                ops.append(("beat",))
                continue
            target = rng.choice(killable)
            killable.remove(target)
            ops.append(("kill", target))
        else:
            ops.append((op,))
    ops.append(("beat",))
    return ops


# ---- the live runner ---------------------------------------------------------


@contextmanager
def _chaos_env(seed: int, chaos_env: str):
    """Export GTPU_CHAOS/GTPU_CHAOS_SEED for children, reset the parent
    registry on both sides (the scenario_cluster contract, minus the
    ProcessCluster — election mode brings its own harness)."""
    saved = {k: os.environ.get(k) for k in ("GTPU_CHAOS",
                                            "GTPU_CHAOS_SEED")}
    os.environ["GTPU_CHAOS_SEED"] = str(seed)
    if chaos_env:
        os.environ["GTPU_CHAOS"] = chaos_env
    else:
        os.environ.pop("GTPU_CHAOS", None)
    FAULTS.reset()
    try:
        yield
    finally:
        FAULTS.reset()
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


def _bug_hook() -> Optional[str]:
    return os.environ.get("GTPU_CHAOS_BUG") or None


def _bug_hook_check(run: ScenarioRun, chaos_env: str) -> None:
    """Test-only deliberate invariant bug: 'point:<name>' trips when the
    schedule arms that point, 'env:<substr>' when the env contains the
    text. Raises the same InvariantViolation (repro line attached) a
    real red run would."""
    spec = _bug_hook() or ""
    mode, _, val = spec.partition(":")
    if mode == "point":
        hit = any(e.split("=", 1)[0].strip() == val
                  for e in split_env(chaos_env))
    elif mode == "env":
        hit = val in chaos_env
    else:
        raise ValueError(f"bad GTPU_CHAOS_BUG spec {spec!r} "
                         "(want point:<name> or env:<substr>)")
    run.check(not hit, f"test-only bug hook GTPU_CHAOS_BUG={spec} "
                       "tripped")


def _validate_schedule(chaos_env: str, topo: Topology) -> None:
    """Arm a scratch registry so malformed entries fail loud even on
    the no-cluster (bug hook) path."""
    reg = FaultRegistry()
    nodes = [*topo.datanodes, *topo.metasrvs, topo.frontend,
             topo.coordinator]
    reg.register_nodes(nodes)
    reg.arm_from_env(chaos_env)


def _replay_cmd(seed: int, num_datanodes: int, steps: int,
                election: bool = False) -> str:
    base = f"python tools/chaos_explorer.py --replay --seed {seed}"
    if election:
        return f"{base} --election"
    return f"{base} --datanodes {num_datanodes} --steps {steps}"


def run_schedule(entries: Sequence, seed: int,
                 data_dir: Optional[str] = None, num_datanodes: int = 1,
                 steps: int = 28, cmd: Optional[str] = None) -> dict:
    """Execute one schedule + the seed's workload against a live
    ProcessCluster and run the full oracle. Raises InvariantViolation
    (repro attached) on any violated invariant; returns the report."""
    chaos_env = compile_env(entries)
    topo = Topology.cluster(num_datanodes)
    run = ScenarioRun(f"explore[{seed}]", seed, chaos_env=chaos_env,
                      cmd=cmd or _replay_cmd(seed, num_datanodes, steps))
    _validate_schedule(chaos_env, topo)
    if _bug_hook():
        _bug_hook_check(run, chaos_env)
        run.report.update(dry=True, entries=len(split_env(chaos_env)))
        return run.report
    crash_scheduled = any(
        e.split("=", 1)[0].strip() == "datanode.crash"
        for e in split_env(chaos_env))
    workload = sample_workload(random.Random(f"workload:{seed}"), steps,
                               topo, allow_kill=not crash_scheduled)
    # the per-run deadline replays from the seed alone, so a shrunk
    # schedule re-arms the same budget the failing run served under
    deadline_ms = _sample_deadline_ms(seed)
    saved_timeout = os.environ.get("GTPU_QUERY_DEFAULT_TIMEOUT_MS")
    if deadline_ms is not None:
        os.environ["GTPU_QUERY_DEFAULT_TIMEOUT_MS"] = str(deadline_ms)
        run.report["deadline_ms"] = deadline_ms
    try:
        if data_dir is None:
            with tempfile.TemporaryDirectory(prefix="gtpu_explore_") as d:
                return _run_live(run, chaos_env, seed, d, num_datanodes,
                                 workload, deadline_ms=deadline_ms)
        return _run_live(run, chaos_env, seed, data_dir, num_datanodes,
                         workload, deadline_ms=deadline_ms)
    finally:
        if saved_timeout is None:
            os.environ.pop("GTPU_QUERY_DEFAULT_TIMEOUT_MS", None)
        else:
            os.environ["GTPU_QUERY_DEFAULT_TIMEOUT_MS"] = saved_timeout


def _try_create(run: ScenarioRun, cluster, sql: str = CREATE) -> bool:
    from ..query.expr import PlanError

    try:
        cluster.sql(sql)
        return True
    except PlanError as e:
        # the DDL path's TYPED surface: a journaled procedure that
        # exhausted its retries rolls back and resurfaces as PlanError
        # ("ddl/create_table rolled_back: ..."); "already exists" means
        # an earlier chaos-failed attempt actually committed
        return "already exists" in str(e)
    except Exception as e:  # noqa: BLE001 — classified below
        run.check(_typed_failure(e),
                  f"DDL failed with UNTYPED {type(e).__name__}: {e}")
        return False


def _first_region(cluster) -> Optional[tuple[int, str]]:
    try:
        rid = cluster.catalog.table("public", "m").region_ids[0]
        route = cluster.metasrv.routes.get(str(rid >> 32))
        return rid, route.region(rid).leader_node
    except Exception:  # noqa: BLE001 — table/route not there yet
        return None


def _dead_led_regions(cluster) -> tuple[list[int], list[int]]:
    """Regions whose route leader is dead, split into (reported,
    orphans). Failover's contract covers only regions the coordinator
    SAW in a heartbeat (`_node_regions`); a region whose owner died
    before ever reporting it cannot be failed over by design — it's an
    orphan to record, not a missed deadline."""
    reported, orphans = [], []
    for route in cluster.metasrv.routes.all():
        for rr in route.regions:
            dn = cluster.datanodes.get(rr.leader_node)
            if dn is not None and dn.alive:
                continue
            known = cluster.metasrv._node_regions.get(rr.leader_node, {})
            (reported if rr.region_id in known
             else orphans).append(rr.region_id)
    return reported, orphans


def _max_latency_s(chaos_env: str) -> float:
    """The largest latency-stall arg the schedule can fire — one
    injected sleep is uninterruptible, so the deadline+ε oracle must
    tolerate a single full stall on top of the budget."""
    worst = 0.0
    for e in split_env(chaos_env):
        if "=latency" not in e:
            continue
        for tok in e.split(","):
            if tok.startswith("arg:"):
                try:
                    worst = max(worst, float(tok[4:]))
                except ValueError:
                    pass
    return worst


def _run_live(run: ScenarioRun, chaos_env: str, seed: int,
              data_dir: str, num_datanodes: int,
              workload: Sequence[tuple],
              deadline_ms: Optional[int] = None) -> dict:
    stats = {"ops": 0, "acked": 0, "typed_failures": 0, "skipped": 0,
             "killed": []}
    # within-deadline+ε invariant: ε covers ONE uninterruptible
    # injected stall (time.sleep at the seam) plus scheduling slack —
    # what it must NEVER absorb is an unbounded wait
    deadline_s = None if deadline_ms is None else deadline_ms / 1000.0
    eps_s = _max_latency_s(chaos_env) + 2.0
    with scenario_cluster(seed, data_dir,
                          num_datanodes=num_datanodes,
                          chaos_env=chaos_env or None) as c:
        # the parent registry was reset by scenario_cluster; arm it now
        # that the topology is registered (children armed at import) —
        # this is where partitions and frontend-seam faults come live
        FAULTS.arm_from_env(chaos_env)
        t = _warm_up(c, 0.0)
        acked: dict = {}
        table_ready = False
        aux_ready: set = set()
        for op in workload:
            stats["ops"] += 1
            kind = op[0]
            if kind == "create" or (kind in ("insert", "read", "flush",
                                             "compact")
                                    and not table_ready):
                if not table_ready and _try_create(run, c):
                    table_ready = True
                    c.beat_all(t)  # report the new region before chaos
                    t += BEAT_MS   # can kill its owner (see "ddl")
                if kind == "create":
                    continue
                if not table_ready:
                    stats["skipped"] += 1
                    continue
            if kind == "insert":
                if try_insert(run, c, op[1], acked):
                    stats["acked"] += 1
                else:
                    stats["typed_failures"] += 1
            elif kind == "read":
                t0r = time.monotonic()
                try:
                    c.sql("SELECT count(*) FROM m")
                except Exception as e:  # noqa: BLE001 — classified
                    elapsed = time.monotonic() - t0r
                    run.check(_typed_failure(e),
                              f"read failed with UNTYPED "
                              f"{type(e).__name__}: {e}")
                    if deadline_s is not None:
                        run.check(
                            elapsed <= deadline_s + eps_s,
                            f"typed read failure took {elapsed:.2f}s "
                            f"against a {deadline_s:.2f}s deadline "
                            f"(+{eps_s:.2f}s ε) — a wait the deadline "
                            "plane cannot reach")
                    stats["typed_failures"] += 1
                else:
                    if deadline_s is not None:
                        elapsed = time.monotonic() - t0r
                        run.check(
                            elapsed <= deadline_s + eps_s,
                            f"read succeeded but took {elapsed:.2f}s "
                            f"against a {deadline_s:.2f}s deadline "
                            f"(+{eps_s:.2f}s ε)")
            elif kind == "beat":
                c.beat_all(t)
                c.tick(t)
                t += BEAT_MS
            elif kind in ("flush", "compact"):
                target = _first_region(c)
                if target is None:
                    stats["skipped"] += 1
                    continue
                rid, owner = target
                dn = c.datanodes.get(owner)
                if dn is None:
                    stats["skipped"] += 1
                    continue
                try:
                    getattr(dn.remote, kind)(rid)
                except Exception as e:  # noqa: BLE001 — classified
                    run.check(_typed_failure(e),
                              f"{kind} ADMIN failed with UNTYPED "
                              f"{type(e).__name__}: {e}")
                    stats["typed_failures"] += 1
            elif kind == "ddl":
                name = f"aux{op[1]}"
                if name not in aux_ready and _try_create(
                        run, c,
                        f"CREATE TABLE {name} (host STRING, v DOUBLE, "
                        "ts TIMESTAMP TIME INDEX, PRIMARY KEY(host))"):
                    aux_ready.add(name)
                    # report the fresh region promptly: a node killed
                    # before its next heartbeat orphans regions the
                    # failover machinery can never have seen
                    c.beat_all(t)
                    t += BEAT_MS
            elif kind == "kill":
                node = op[1]
                alive = [n for n, d in c.datanodes.items() if d.alive]
                if node in alive and len(alive) > 1:
                    c.kill_datanode(node)
                    stats["killed"].append(node)
                else:
                    stats["skipped"] += 1

        # the chaos schedule also kills (datanode.crash nemesis) — the
        # oracle below needs to know regardless of who pulled the plug
        stats["killed"] = sorted(
            set(stats["killed"])
            | {n for n, d in c.datanodes.items() if not d.alive})

        # ---- oracle: verify chaos-free ----------------------------------
        # the verification reads must not trip the run's tight deadline
        # on a loaded box: the invariant under test was checked above
        os.environ.pop("GTPU_QUERY_DEFAULT_TIMEOUT_MS", None)
        FAULTS.heal_partitions()
        FAULTS.reset()
        for dn in c.datanodes.values():
            if dn.alive:
                dn.remote.chaos_reset()
        if table_ready:
            rounds = 0
            deadline_rounds = 30
            # settle until failover has landed AND the mailbox is
            # drained — a redelivered OpenRegion (instruction delivery
            # hit by chaos before the heal) needs one more beat to land
            def _unsettled() -> bool:
                if _dead_led_regions(c)[0]:
                    return True
                with c.metasrv._lock:
                    # dead nodes never beat again: their CLOSE_REGION
                    # (split-brain guard) legitimately stays queued
                    return any(
                        insts and n in c.datanodes
                        and c.datanodes[n].alive
                        for n, insts in c.metasrv._pending.items())

            while _unsettled() and rounds < deadline_rounds:
                c.beat_all(t)
                c.tick(t)
                t += BEAT_MS
                rounds += 1
            bad, orphans = _dead_led_regions(c)
            run.check(not bad,
                      f"failover missed its deadline: regions {bad} "
                      f"still led by dead nodes after {rounds} rounds")
            run.report["settle_rounds"] = rounds
            if orphans:
                run.report["orphaned_regions"] = orphans
            try:
                verify_acked(run, c, acked)
            except InvariantViolation:
                raise
            except Exception as e:  # noqa: BLE001 — classified below
                # an orphaned region (owner died pre-report) can make
                # the table unreadable through no failover fault; a
                # typed failure is then recorded, anything else — or a
                # failed read with NO orphan in play — stays a violation
                run.check(_typed_failure(e) and bool(orphans),
                          f"final read after chaos healed failed with "
                          f"{type(e).__name__}: {e}")
                run.report["verify_acked_skipped"] = True
        if not stats["killed"]:
            # SIGKILL mid-write may legally leave staging files the
            # next open cleans; the no-partial-WAL invariant is the
            # ENOSPC-cleanup contract, so it's checked on kill-free runs
            verify_wal_objects_clean(
                run, os.path.join(data_dir, "shared"))
    run.report.update(stats, entries=len(split_env(chaos_env)))
    return run.report


# ---- election mode -----------------------------------------------------------


def run_election_schedule(entries: Sequence, seed: int,
                          data_dir: Optional[str] = None,
                          num_metasrv: int = 3, lease_s: float = 9.0,
                          rounds: int = 24,
                          skews: Optional[dict] = None,
                          cmd: Optional[str] = None) -> dict:
    """Execute one election-chaos schedule against N real metasrv
    processes electing over the kv_service wire. Oracle: at most one
    leader per lease epoch (CAS journal in the parent's KV host), a
    leader re-emerges after chaos heals, follower redirects stay typed
    (`NotLeaderError` with a leader hint over HTTP 409), and every
    tick-time failure is typed."""
    from ..catalog.kv import MemoryKv
    from ..meta.election import NotLeaderError

    chaos_env = compile_env(entries)
    topo = Topology.election(num_metasrv)
    if skews is None:
        skews = sample_skews(random.Random(f"skew:{seed}"), topo,
                             lease_s)
    run = ScenarioRun(f"explore_election[{seed}]", seed,
                      chaos_env=chaos_env,
                      cmd=cmd or _replay_cmd(seed, 0, 0, election=True))
    _validate_schedule(chaos_env, topo)
    if _bug_hook():
        _bug_hook_check(run, chaos_env)
        run.report.update(dry=True, entries=len(split_env(chaos_env)))
        return run.report
    if data_dir is None:
        with tempfile.TemporaryDirectory(prefix="gtpu_elect_") as d:
            return _run_election_live(run, chaos_env, seed, d,
                                      num_metasrv, lease_s, rounds,
                                      skews, NotLeaderError, MemoryKv)
    return _run_election_live(run, chaos_env, seed, data_dir,
                              num_metasrv, lease_s, rounds, skews,
                              NotLeaderError, MemoryKv)


def _run_election_live(run: ScenarioRun, chaos_env: str, seed: int,
                       data_dir: str, num_metasrv: int, lease_s: float,
                       rounds: int, skews: dict, NotLeaderError,
                       MemoryKv) -> dict:
    from ..cluster.metasrv_cluster import MetasrvProcessCluster
    from ..meta.metasrv import HeartbeatRequest

    journal = ElectionEpochJournal(MemoryKv())
    with _chaos_env(seed, chaos_env):
        cluster = MetasrvProcessCluster(data_dir,
                                        num_metasrv=num_metasrv,
                                        kv=journal, lease_s=lease_s,
                                        clock_skew_ms=skews)
        try:
            FAULTS.arm_from_env(chaos_env)
            t = BEAT_MS
            for _ in range(rounds):
                for node, res in cluster.tick_all(t).items():
                    if isinstance(res, Exception):
                        run.check(
                            _typed_failure(res)
                            or isinstance(res, NotLeaderError),
                            f"tick on {node} failed UNTYPED "
                            f"{type(res).__name__}: {res}")
                t += BEAT_MS

            # heal everything, then the cluster must converge on ONE
            # authoritative lease holder within a lease-expiry's worth
            # of rounds
            FAULTS.heal_partitions()
            FAULTS.reset()
            cluster.chaos_reset_all()
            settle = 0
            while cluster.leader(t) is None and settle < 15:
                cluster.tick_all(t)
                t += BEAT_MS
                settle += 1
            leader = cluster.leader(t)
            run.check(leader is not None,
                      f"no leader re-emerged within {settle} rounds "
                      "after chaos healed")
            cluster.tick_all(t)  # followers refresh their local views

            # redirect correctness across processes: a follower answers
            # heartbeats leader=False (+hint) and refuses leader-only
            # admin ops with the TYPED NotLeaderError over the wire
            followers = [n for n, ms in cluster.metasrvs.items()
                         if n != leader and ms.alive]
            run.check(bool(followers),
                      "no live follower left to verify redirects")
            fol = cluster.metasrvs[followers[0]].client
            resp = fol.handle_heartbeat(HeartbeatRequest(
                node_id="dn-probe", region_stats=[], now_ms=t))
            run.check(not resp.leader,
                      f"follower {followers[0]} answered a heartbeat "
                      "as leader")
            try:
                fol.migrate_region("m", 0, "dn-0")
                run.check(False,
                          f"follower {followers[0]} accepted a "
                          "leader-only admin op")
            except NotLeaderError as e:
                run.report["redirect_leader_hint"] = e.leader
            except InvariantViolation:
                raise
            except Exception as e:  # noqa: BLE001 — classified
                run.check(False,
                          f"follower redirect was UNTYPED "
                          f"{type(e).__name__}: {e}")

            run.check(len(journal.epochs) >= 1,
                      "no election epoch was ever granted (vacuous run)")
            verify_epochs(run, journal, lease_s,
                          max_skew_ms=max(skews.values(), default=0.0))
            run.report.update(
                leader=leader, epochs=len(journal.epochs),
                skews=skews, entries=len(split_env(chaos_env)))
        finally:
            cluster.close()
    return run.report


# ---- shrinking ---------------------------------------------------------------


def ddmin(entries: Sequence, still_fails: Callable[[list], bool],
          max_probes: int = 32) -> list:
    """Zeller delta-debugging (complement reduction): find a smaller
    entry subset that still fails. Each probe is one full (seeded,
    deterministic) re-run; `max_probes` bounds the spend and every probe
    counts into gtpu_chaos_shrink_steps."""
    entries = list(entries)
    n = 2
    probes = 0
    while len(entries) >= 2 and probes < max_probes:
        chunk = max(1, len(entries) // n)
        subsets = [entries[i:i + chunk]
                   for i in range(0, len(entries), chunk)]
        reduced = False
        for i in range(len(subsets)):
            complement = [e for j, s in enumerate(subsets)
                          for e in s if j != i]
            if not complement or len(complement) == len(entries):
                continue
            probes += 1
            CHAOS_SHRINK_STEPS.inc()
            if still_fails(complement):
                entries = complement
                n = max(n - 1, 2)
                reduced = True
                break
            if probes >= max_probes:
                break
        if not reduced:
            if n >= len(entries):
                break
            n = min(len(entries), n * 2)
    return entries


def shrink_failing(entries: Sequence, seed: int, *,
                   election: bool = False, num_datanodes: int = 1,
                   steps: int = 28, max_probes: int = 32) \
        -> tuple[list, Optional[InvariantViolation]]:
    """ddmin a failing schedule, then re-verify the minimal subset and
    return (minimal_entries, the re-verified violation). The violation
    carries the final repro line — the contract is that pasting it
    re-triggers the failure."""
    def still_fails(subset: list) -> bool:
        try:
            if election:
                run_election_schedule(subset, seed)
            else:
                run_schedule(subset, seed,
                             num_datanodes=num_datanodes, steps=steps)
        except InvariantViolation:
            return True
        return False

    minimal = ddmin(entries, still_fails, max_probes=max_probes)
    CHAOS_SHRINK_STEPS.inc()  # the final re-verification probe
    try:
        if election:
            run_election_schedule(minimal, seed)
        else:
            run_schedule(minimal, seed, num_datanodes=num_datanodes,
                         steps=steps)
    except InvariantViolation as e:
        return minimal, e
    # the minimal set no longer fails (flaky/non-minimal interaction):
    # fall back to the original, which the caller knows fails
    return list(entries), None


# ---- the explorer loop -------------------------------------------------------


def explore(runs: int = 3, seed: int = 0,
            budget_s: Optional[float] = None, shrink: bool = True,
            num_datanodes: int = 1, steps: int = 28,
            max_entries: int = 4, election: bool = False,
            rounds: int = 24, lease_s: float = 9.0,
            shrink_probes: int = 32) -> dict:
    """Sample and execute `runs` seeded schedules (run i uses seed
    `seed + i`), oracle-checking each; failing schedules are shrunk to a
    minimal repro. Returns the machine-readable report the CLI emits
    with --json."""
    report: dict = {"seed": seed, "mode": "election" if election
                    else "cluster", "runs": [],
                    "passed": 0, "failed": 0, "errors": 0}
    t0 = time.monotonic()
    for i in range(runs):
        if budget_s is not None and report["runs"] \
                and time.monotonic() - t0 > budget_s:
            report["budget_exhausted"] = True
            break
        run_seed = seed + i
        topo = Topology.election(3) if election \
            else Topology.cluster(num_datanodes)
        rng = random.Random(f"schedule:{run_seed}")
        if election:
            entries = [e.to_env() for e in
                       sample_election_schedule(rng, topo, max_entries)]
        else:
            entries = [e.to_env() for e in
                       sample_schedule(
                           rng, topo, max_entries,
                           deadline_ms=_sample_deadline_ms(run_seed))]
        rec: dict = {"seed": run_seed, "chaos_env": compile_env(entries),
                     "entries": len(entries)}
        t_run = time.monotonic()
        try:
            if election:
                rec["report"] = run_election_schedule(entries, run_seed,
                                                      lease_s=lease_s,
                                                      rounds=rounds)
            else:
                rec["report"] = run_schedule(
                    entries, run_seed, num_datanodes=num_datanodes,
                    steps=steps)
            rec["outcome"] = "pass"
            report["passed"] += 1
            CHAOS_RUNS.inc(outcome="pass")
        except InvariantViolation as e:
            rec["outcome"] = "fail"
            rec["violation"] = str(e)
            rec["repro"] = getattr(e, "repro", None)
            report["failed"] += 1
            CHAOS_RUNS.inc(outcome="fail")
            if shrink:
                minimal, verified = shrink_failing(
                    entries, run_seed, election=election,
                    num_datanodes=num_datanodes, steps=steps,
                    max_probes=shrink_probes)
                rec["shrunk_entries"] = len(minimal)
                rec["shrunk_env"] = compile_env(minimal)
                if verified is not None:
                    rec["violation"] = str(verified)
                    rec["repro"] = getattr(verified, "repro",
                                           rec["repro"])
        except Exception as e:  # noqa: BLE001 — harness error, not a
            # cluster invariant: recorded, counted, never hidden
            rec["outcome"] = "error"
            rec["error"] = f"{type(e).__name__}: {e}"
            report["errors"] += 1
            CHAOS_RUNS.inc(outcome="error")
        rec["duration_s"] = round(time.monotonic() - t_run, 2)
        report["runs"].append(rec)
    report["duration_s"] = round(time.monotonic() - t0, 2)
    return report
