"""Shared retry policy: capped exponential backoff + full jitter +
deadline (the AWS full-jitter shape; reference retries live per-crate —
e.g. object-store's RetryLayer and meta-client's retry loop — here one
policy serves every seam so chaos runs exercise a single code path).

`retry_call(op, point=...)` is the only entry point; call sites pass the
exception classes worth retrying on top of the shared transience
predicate. Every retry and every exhaustion increments a labeled counter
(utils/metrics.py) so chaos runs can assert behavior through /metrics.
"""

from __future__ import annotations

import os
import random
import time
from dataclasses import dataclass
from typing import Callable, Optional, Sequence

from greptimedb_tpu.utils.metrics import RETRY_ATTEMPTS, RETRY_EXHAUSTED


class Unavailable(Exception):
    """Typed terminal error: retries AND degradation (route re-resolve)
    exhausted. Servers map this to a 503-shaped response instead of a
    stack trace."""

    def __init__(self, what: str, cause: Optional[BaseException] = None):
        super().__init__(what if cause is None else f"{what}: {cause}")
        self.cause = cause


class DeadlineExceeded(Unavailable):
    """Typed deadline expiry: the query's absolute deadline passed while
    work was still pending (admission wait, device dispatch, scan
    decode, a remote fragment...). An Unavailable sibling so existing
    typed-error plumbing treats it as degradation, but servers map it to
    the timeout shape (HTTP 408 / MySQL 3024 / PG 57014), never 503.
    Catch it BEFORE `except Unavailable` at wire boundaries."""


class Cancelled(Unavailable):
    """Typed cooperative cancellation: the query's CancelToken was
    cancelled (KILL QUERY, DELETE /v1/queries/<id>, or client
    disconnect) while work was still pending. Like DeadlineExceeded, an
    Unavailable sibling with its own wire mapping (HTTP 499 / MySQL 1317
    / PG 57014); catch before `except Unavailable`."""


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, default))
    except (TypeError, ValueError):
        return default


@dataclass(frozen=True)
class RetryPolicy:
    """max_attempts total tries; sleep_i = U(0, min(cap, base * 2^i));
    the deadline bounds the whole call including sleeps."""

    max_attempts: int = 3
    base_s: float = 0.02
    cap_s: float = 0.5
    deadline_s: float = 10.0

    @staticmethod
    def from_env() -> "RetryPolicy":
        return RetryPolicy(
            max_attempts=int(_env_float("GTPU_RETRY_MAX_ATTEMPTS", 3)),
            base_s=_env_float("GTPU_RETRY_BASE_S", 0.02),
            cap_s=_env_float("GTPU_RETRY_CAP_S", 0.5),
            deadline_s=_env_float("GTPU_RETRY_DEADLINE_S", 10.0),
        )

    def backoff_s(self, attempt: int, rng: random.Random) -> float:
        return rng.uniform(0.0, min(self.cap_s, self.base_s * (2 ** attempt)))


#: process-wide default, env-tunable (GTPU_RETRY_*)
DEFAULT_POLICY = RetryPolicy.from_env()

# jitter is seeded by the chaos seed so a chaos run's timing is as
# replayable as its fault schedule (seed 0 when chaos is off)
_jitter_rng = random.Random(
    int(os.environ.get("GTPU_CHAOS_SEED", "0") or 0) ^ 0x5EED)


def retry_call(op: Callable, *, point: str,
               policy: Optional[RetryPolicy] = None,
               retryable: Sequence[type] = (),
               rng: Optional[random.Random] = None):
    """Run `op()` under the retry policy. An exception retries when the
    shared transience predicate says so (injected faults, self-described
    transient errors) or it is an instance of `retryable`. Non-transient
    errors (not-found, auth, torn writes) surface immediately.

    Deadline/cancel aware: the backoff sleep waits on the active query's
    CancelToken (utils/deadline) instead of an uninterruptible
    time.sleep, is clipped to the query's remaining budget, and a token
    already expired/cancelled re-raises typed before the next attempt —
    a killed query never lingers through backoff."""
    from greptimedb_tpu.fault import is_transient  # late: sibling module
    from greptimedb_tpu.utils import deadline as dl

    policy = policy or DEFAULT_POLICY
    rng = rng or _jitter_rng
    deadline = time.monotonic() + policy.deadline_s
    attempt = 0
    while True:
        try:
            return op()
        except Exception as e:  # noqa: BLE001 — predicate filters below
            if isinstance(e, (DeadlineExceeded, Cancelled)):
                raise  # typed unwind, never worth a retry
            if not (is_transient(e) or isinstance(e, tuple(retryable))):
                raise
            dl.check(point)  # expired/killed mid-attempt: unwind typed
            attempt += 1
            if attempt >= policy.max_attempts \
                    or time.monotonic() >= deadline:
                RETRY_EXHAUSTED.inc(point=point)
                raise
            RETRY_ATTEMPTS.inc(point=point)
            delay = policy.backoff_s(attempt - 1, rng)
            if delay > 0:
                dl.sleep(min(delay, max(0.0,
                                        deadline - time.monotonic())),
                         point=point)
