"""Compound-fault chaos scenarios: seeded fault schedules + workload +
invariant checkers over a LIVE ProcessCluster.

This is the step from "failures are injectable" (PR 1's FaultRegistry)
to "failover is a replayable, checked property" — the Jepsen shape
(partition nemesis + workload + invariant checkers) married to
FoundationDB-style deterministic seeds. A scenario is:

- a deterministic fault schedule (parent-side arming + GTPU_CHAOS env
  inherited by datanode child processes, all seeded by GTPU_CHAOS_SEED),
- a workload that tracks exactly which writes were ACKNOWLEDGED,
- invariant checkers run against the live cluster:
    * no acknowledged write lost,
    * at most one metasrv leader per lease epoch (CAS journal over the
      election key),
    * failover completes within a deadline (virtual-clock beat rounds),
    * reads DEGRADE per the PR-1 policy (typed `Unavailable`) instead of
      surfacing transport stack traces,
    * no partial WAL file survives an injected ENOSPC.

Every failure raises `InvariantViolation` carrying the exact
`GTPU_CHAOS`/`GTPU_CHAOS_SEED` reproduction line, so any red run replays
bit-for-bit. Run the matrix locally with `python tools/run_scenarios.py`.
"""

from __future__ import annotations

import json
import os
import shlex
import zlib
from contextlib import contextmanager
from typing import Optional

from ..catalog.kv import KvBackend, MemoryKv
from ..meta.election import ELECTION_KEY, KvElection
from ..meta.metasrv import Metasrv, MetasrvOptions
from ..utils.metrics import FAULT_INJECTIONS
from . import FAULTS, Fault, FaultError, Unavailable, chaos_seed

DEFAULT_SEED = 1234

CREATE = ("CREATE TABLE m (host STRING, v DOUBLE, "
          "ts TIMESTAMP TIME INDEX, PRIMARY KEY(host))")

#: one heartbeat interval of virtual time (MetasrvOptions default)
BEAT_MS = 3000.0


class InvariantViolation(AssertionError):
    """A cluster invariant failed under a seeded fault schedule. The
    message carries the exact reproduction line; the machine-readable
    pieces ride as `.scenario` and `.repro` attributes (tools consume
    them for --json output)."""

    scenario: Optional[str] = None
    repro: Optional[str] = None


class ScenarioRun:
    """One scenario execution: seed bookkeeping, the reproduction line,
    and `check` — every invariant goes through it so every red run
    prints how to replay itself."""

    def __init__(self, name: str, seed: int,
                 chaos_env: Optional[str] = None,
                 cmd: Optional[str] = None):
        self.name = name
        self.seed = seed
        self.chaos_env = chaos_env
        #: the replay command — scenarios default to run_scenarios.py;
        #: the chaos explorer substitutes its own --replay invocation
        self.cmd = cmd or f"python tools/run_scenarios.py {name}"
        self.report: dict = {"name": name, "seed": seed}

    def repro(self) -> str:
        parts = [f"GTPU_CHAOS_SEED={self.seed}"]
        if self.chaos_env:
            # shell-quoted: schedules carry `;` entry separators and
            # `@edge:a->b` tokens that paste-break an unquoted line
            parts.append(f"GTPU_CHAOS={shlex.quote(self.chaos_env)}")
        parts.append(self.cmd)
        return " ".join(parts)

    def check(self, cond: bool, what: str) -> None:
        if not cond:
            err = InvariantViolation(
                f"[{self.name}] invariant violated: {what}\n"
                f"  replay: {self.repro()}")
            err.scenario = self.name
            err.repro = self.repro()
            raise err


@contextmanager
def scenario_cluster(seed: int, data_dir: str, num_datanodes: int = 3,
                     chaos_env: Optional[str] = None,
                     kv: Optional[KvBackend] = None,
                     election: Optional[KvElection] = None,
                     metasrv_node_id: str = "metasrv-0"):
    """A ProcessCluster under a seeded chaos environment. GTPU_CHAOS /
    GTPU_CHAOS_SEED are exported BEFORE the children spawn (they arm
    from env at import) and restored after; the registry is reset on the
    way out so schedules never leak past the scenario."""
    from ..cluster.process_cluster import ProcessCluster

    saved = {k: os.environ.get(k) for k in ("GTPU_CHAOS",
                                            "GTPU_CHAOS_SEED")}
    os.environ["GTPU_CHAOS_SEED"] = str(seed)
    if chaos_env is not None:
        os.environ["GTPU_CHAOS"] = chaos_env
    else:
        os.environ.pop("GTPU_CHAOS", None)
    FAULTS.reset()
    cluster = None
    try:
        # inside the try: a constructor failure (startup timeout, chaos
        # hitting a boot path) must still restore env + registry and
        # reap any children that did spawn
        cluster = ProcessCluster(data_dir, num_datanodes=num_datanodes,
                                 kv=kv, opts=MetasrvOptions(),
                                 election=election,
                                 metasrv_node_id=metasrv_node_id)
        yield cluster
    finally:
        try:
            if cluster is not None:
                cluster.close()
        finally:
            # registry + env restore must survive a failing close():
            # leaking a chaos schedule poisons every later test with
            # failures that don't replay from their printed seed
            FAULTS.reset()
            for k, v in saved.items():
                if v is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = v


# ---- workload ----------------------------------------------------------------


def _typed_failure(e: BaseException) -> bool:
    """Failures the resilience policy is ALLOWED to surface: injected
    faults, the typed Unavailable, metadata-plane service errors, and
    Flight transport errors (a killed peer). Anything else — KeyError,
    AttributeError, Arrow decode errors — is a bug the scenario flags."""
    from ..meta.kv_service import MetaServiceError

    if isinstance(e, (FaultError, Unavailable, MetaServiceError)):
        return True
    return type(e).__module__.startswith("pyarrow") \
        and "Flight" in type(e).__name__


def try_insert(run: ScenarioRun, cluster, i: int, acked: dict) -> bool:
    """One tracked write: records the row in `acked` ONLY when the
    insert returned success. An untyped failure is itself an invariant
    violation (errors must stay typed under chaos)."""
    key, val = f"h{i:02d}", float(i)
    try:
        cluster.sql(f"INSERT INTO m VALUES ('{key}', {val}, "
                    f"{1000 * (i + 1)})")
    except Exception as e:  # noqa: BLE001 — classified below
        run.check(_typed_failure(e),
                  f"write {key} failed with UNTYPED "
                  f"{type(e).__name__}: {e}")
        return False
    acked[key] = val
    return True


def read_degrades_typed(run: ScenarioRun, cluster,
                        sql: str = "SELECT count(*) FROM m") -> bool:
    """Reads under chaos either answer or degrade to the typed
    `Unavailable` (the PR-1 policy). Returns True when degraded."""
    try:
        cluster.sql(sql)
        return False
    except Unavailable:
        return True
    except Exception as e:  # noqa: BLE001 — classified below
        run.check(False,
                  f"read failed with UNTYPED {type(e).__name__}: {e} "
                  "(policy: degrade to Unavailable)")
        return True  # unreachable


# ---- invariants --------------------------------------------------------------


def verify_acked(run: ScenarioRun, cluster, acked: dict,
                 exact: bool = False) -> dict:
    """No acknowledged write lost; with `exact`, additionally no phantom
    rows (only valid when the scenario performed no client retries of
    failed writes — at-least-once duplication is collapsed by LWW, but a
    cleanly-failed write must not resurface)."""
    rows = cluster.sql("SELECT host, v FROM m ORDER BY host").rows()
    got = {r[0]: r[1] for r in rows}
    for k, v in sorted(acked.items()):
        run.check(got.get(k) == v, f"acknowledged write {k}={v} lost "
                                   f"(read back {got.get(k)!r})")
    if exact:
        phantom = sorted(set(got) - set(acked))
        run.check(not phantom, f"phantom rows surfaced: {phantom}")
    return got


def drive_failover(run: ScenarioRun, cluster, t: float, dead_node: str,
                   rid: int, deadline_rounds: int = 30,
                   metasrv=None) -> tuple[float, int]:
    """Beat + tick the virtual clock until failover moves the region off
    `dead_node`; the deadline (in heartbeat rounds) IS the invariant."""
    table_key = str(rid >> 32)
    target = metasrv if metasrv is not None else cluster.metasrv
    rounds = 0
    while rounds < deadline_rounds:
        cluster.beat_all(t, metasrv=metasrv)
        started = cluster.tick(t, metasrv=metasrv)
        t += BEAT_MS
        rounds += 1
        if started:
            cluster.beat_all(t, metasrv=metasrv)  # deliver OPEN_REGION
            break
    leader = target.routes.get(table_key).region(rid).leader_node
    run.check(leader != dead_node,
              f"failover missed its deadline: region {rid} still on "
              f"{dead_node} after {rounds}/{deadline_rounds} rounds")
    run.report["failover_rounds"] = rounds
    return t, rounds


def verify_wal_objects_clean(run: ScenarioRun, shared_dir: str) -> int:
    """ENOSPC cleanup invariant: every remote-WAL segment object under
    the shared store parses as complete CRC-framed entries with NO
    partial tail and no staging leftovers (.tmp/.partial)."""
    from ..storage.wal import _HEADER  # ONE framing definition

    wal_root = os.path.join(shared_dir, "remote_wal")
    checked = 0
    for root, _dirs, files in os.walk(wal_root):
        for name in files:
            path = os.path.join(root, name)
            run.check(not name.endswith((".tmp", ".partial")),
                      f"staging leftover survived ENOSPC: {path}")
            with open(path, "rb") as f:
                data = f.read()
            pos = 0
            while pos + _HEADER.size <= len(data):
                plen, crc, _rid, _seq, _op = _HEADER.unpack_from(
                    data, pos)
                payload = data[pos + _HEADER.size:
                               pos + _HEADER.size + plen]
                if len(payload) != plen or zlib.crc32(payload) != crc:
                    break
                pos += _HEADER.size + plen
            run.check(pos == len(data),
                      f"partial WAL frame survived ENOSPC in {path} "
                      f"(clean bytes {pos}/{len(data)})")
            checked += 1
    run.report["wal_objects_checked"] = checked
    return checked


class ElectionEpochJournal(KvBackend):
    """Delegating KV that journals every successful CAS of the election
    leader key — the ground truth for at-most-one-leader-per-epoch.
    Each journal entry is one granted (or resigned) lease epoch."""

    def __init__(self, inner: KvBackend):
        self.inner = inner
        self.epochs: list[dict] = []

    def get(self, key):
        return self.inner.get(key)

    def put(self, key, value):
        self.inner.put(key, value)

    def delete(self, key):
        return self.inner.delete(key)

    def range(self, prefix):
        return self.inner.range(prefix)

    def compare_and_put(self, key, expect, value):
        ok = self.inner.compare_and_put(key, expect, value)
        if ok and key == ELECTION_KEY:
            entry = json.loads(value)
            entry["prev"] = json.loads(expect) if expect else None
            self.epochs.append(entry)
        return ok


def verify_epochs(run: ScenarioRun, journal: ElectionEpochJournal,
                  lease_s: float, max_skew_ms: float = 0.0) -> None:
    """At most one leader per lease epoch: a takeover by a DIFFERENT
    node is legal only after the previous lease expired (campaign time,
    reconstructed from the granted deadline, past the old deadline) or
    was resigned (deadline zeroed). Overlap = split-brain.

    `max_skew_ms` relaxes the bound under a clock-skew nemesis: a node
    skewed forward by S legally sees the old lease expire S early by the
    true clock, so takeovers up to S before the deadline are correct
    behavior, not split-brain."""
    lease_ms = lease_s * 1000.0
    for prev, cur in zip(journal.epochs, journal.epochs[1:]):
        if cur["node"] == prev["node"]:
            continue  # renewal / retake by the same holder: one leader
        if prev["lease_until_ms"] == 0:
            continue  # previous holder resigned: immediate takeover ok
        granted_at = cur["lease_until_ms"] - lease_ms
        run.check(granted_at > prev["lease_until_ms"] - max_skew_ms,
                  f"epoch overlap: {cur['node']} took the lease at "
                  f"t={granted_at:.0f} while {prev['node']}'s ran to "
                  f"t={prev['lease_until_ms']:.0f}"
                  + (f" (skew slack {max_skew_ms:.0f}ms)"
                     if max_skew_ms else ""))
    run.report["lease_epochs"] = len(journal.epochs)


# ---- shared workload phases --------------------------------------------------


def _warm_up(cluster, t: float, rounds: int = 5, metasrv=None) -> float:
    """Train the phi detector's interval history before any chaos."""
    for _ in range(rounds):
        cluster.beat_all(t, metasrv=metasrv)
        t += BEAT_MS
    return t


def _region_owner(cluster, metasrv=None) -> tuple[int, str]:
    rid = cluster.catalog.table("public", "m").region_ids[0]
    ms = metasrv if metasrv is not None else cluster.metasrv
    return rid, ms.routes.get(str(rid >> 32)).region(rid).leader_node


# ---- the compound scenarios --------------------------------------------------


def scenario_partition_heal(data_dir: str, seed: int,
                            num_datanodes: int = 3) -> dict:
    """(1) Symmetric frontend↔datanode partition + heal: during the cut,
    reads and writes touching the isolated node degrade TYPED; the
    control plane (heartbeats) is untouched, so no spurious failover;
    after heal everything acknowledged is readable and writes flow."""
    name = "partition_heal" if num_datanodes >= 3 else "smoke_partition_heal"
    run = ScenarioRun(name, seed)
    with scenario_cluster(seed, data_dir,
                          num_datanodes=num_datanodes) as c:
        t = _warm_up(c, 0.0)
        c.sql(CREATE)
        t = _warm_up(c, t, rounds=2)
        acked: dict = {}
        for i in range(4):
            run.check(try_insert(run, c, i, acked),
                      f"write {i} failed before any fault was armed")
        _rid, owner = _region_owner(c)

        # delta, not the process-global total: an earlier partition
        # scenario in the same process must not satisfy THIS run's check
        drops_before = FAULT_INJECTIONS.total(kind="partition")
        FAULTS.install_partition("frontend", owner)
        run.check(read_degrades_typed(run, c),
                  "read served through a severed frontend<->datanode "
                  "edge (partition not effective)")
        partition_failures = sum(
            0 if try_insert(run, c, i, acked) else 1 for i in range(4, 7))
        run.check(partition_failures == 3,
                  "writes crossed a severed edge")
        # the DATA-plane cut must not look like node death to the
        # metasrv: heartbeats flow, phi stays low, no failover starts
        t = _warm_up(c, t, rounds=5)
        run.check(not c.tick(t),
                  "data-plane partition triggered failover despite "
                  "healthy heartbeats")

        FAULTS.heal_partition("frontend", owner)
        for i in range(7, 10):
            run.check(try_insert(run, c, i, acked),
                      f"write {i} failed after heal")
        verify_acked(run, c, acked)
        drops = FAULT_INJECTIONS.total(kind="partition") - drops_before
        run.check(drops > 0,
                  "partition drops were not observable in "
                  "fault_injections_total")
        run.report.update(acked=len(acked), partition_drops=drops)
    return run.report


def scenario_partition_crash_failover(data_dir: str, seed: int) -> dict:
    """(2) Datanode crash DURING a partition: the isolated owner dies
    with acknowledged-but-unflushed writes; failover must meet its
    deadline and replay them from the shared remote WAL — compound
    fault, both invariants checked."""
    run = ScenarioRun("partition_crash_failover", seed)
    with scenario_cluster(seed, data_dir, num_datanodes=3) as c:
        t = _warm_up(c, 0.0)
        c.sql(CREATE)
        t = _warm_up(c, t, rounds=2)
        acked: dict = {}
        for i in range(6):
            run.check(try_insert(run, c, i, acked),
                      f"write {i} failed before any fault was armed")
        rid, owner = _region_owner(c)
        # the owner reported its region before dying (the metasrv must
        # know WHAT to fail over)
        t = _warm_up(c, t, rounds=2)

        FAULTS.install_partition("frontend", owner)
        for i in range(6, 8):
            run.check(not try_insert(run, c, i, acked),
                      "write crossed a severed edge")
        c.kill_datanode(owner)

        t, _rounds = drive_failover(run, c, t, owner, rid,
                                    deadline_rounds=30)
        FAULTS.heal_partition("frontend", owner)
        for i in range(8, 10):
            run.check(try_insert(run, c, i, acked),
                      f"write {i} failed after failover")
        verify_acked(run, c, acked)
        run.report.update(acked=len(acked), dead_node=owner)
    return run.report


def scenario_lease_loss_reelection(data_dir: str, seed: int) -> dict:
    """(3) Metasrv lease loss forces re-election: the primary's election
    lease is chaos-expired mid-run (a GC-pause analog spanning several
    keep-alives); the standby takes over, heartbeats follow the lease,
    and the CAS journal proves at most one leader per lease epoch."""
    run = ScenarioRun("lease_loss_reelection", seed)
    lease_s = 9.0
    journal = ElectionEpochJournal(MemoryKv())
    e1 = KvElection(journal, "meta-a", lease_s=lease_s)
    with scenario_cluster(seed, data_dir, num_datanodes=3, kv=journal,
                          election=e1, metasrv_node_id="meta-a") as c:
        FAULTS.register_nodes(["meta-b"])
        e2 = KvElection(journal, "meta-b", lease_s=lease_s)
        standby = Metasrv(journal, MetasrvOptions(), node_id="meta-b",
                          election=e2)
        metasrvs = {"meta-a": c.metasrv, "meta-b": standby}

        t = 0.0
        run.check(e1.campaign(t), "primary failed its first campaign")

        def leader_ms(now):
            node = e1.leader(now)  # both read the same KV key
            return metasrvs.get(node) if node else None

        def round_trip(now):
            # every metasrv ticks (leader renews + detects; follower
            # campaigns on a lapsed lease); beats go to the lease holder
            c.tick(now)
            standby.tick(now)
            lead = leader_ms(now)
            if lead is not None:
                c.beat_all(now, metasrv=lead)
            # at most one FENCED leader at any instant: a stale local
            # flag is allowed, a stale flag that passes the
            # authoritative lease check is split-brain
            fenced = [n for n, e in (("meta-a", e1), ("meta-b", e2))
                      if e.is_leader() and e.leader(now) == n]
            run.check(len(fenced) <= 1,
                      f"two fenced leaders at t={now}: {fenced}")
            return now + BEAT_MS

        for _ in range(5):
            t = round_trip(t)
        c.sql(CREATE)
        for _ in range(2):
            t = round_trip(t)
        acked: dict = {}
        for i in range(4):
            run.check(try_insert(run, c, i, acked),
                      f"write {i} failed before any fault was armed")

        # the GC pause: meta-a's next 4 election calls force-expire its
        # lease — long enough for meta-b to take over and renew
        FAULTS.arm("election.lease",
                   Fault(kind="fail", nth=1, times=4,
                         match={"node": "meta-a"}, seed=seed))
        for _ in range(8):
            t = round_trip(t)
        FAULTS.disarm("election.lease")
        run.check(e1.leader(t) == "meta-b",
                  "standby never took over after forced lease loss")
        run.check(any(ep["node"] == "meta-b" for ep in journal.epochs),
                  "no meta-b epoch in the election journal")

        # the cluster stays writable and readable under the new leader
        for i in range(4, 8):
            run.check(try_insert(run, c, i, acked),
                      f"write {i} failed after re-election")
        for _ in range(3):
            t = round_trip(t)
        verify_acked(run, c, acked)
        verify_epochs(run, journal, lease_s)
        run.report.update(acked=len(acked),
                          final_leader=e1.leader(t))
    return run.report


def scenario_wal_enospc(data_dir: str, seed: int) -> dict:
    """(4) ENOSPC on WAL append inside a datanode child (armed via
    GTPU_CHAOS env inheritance): the partial segment is cleaned up, the
    failed write stays unacknowledged, later writes flow — and after a
    kill + failover the replayed region contains EXACTLY the
    acknowledged set (a leaked partial would resurface phantom rows)."""
    nth = 4  # the owner's 4th append (insert i=3) hits the full disk
    chaos_env = f"wal.append=enospc,arg:0.5,nth:{nth}"
    run = ScenarioRun("wal_enospc", seed, chaos_env=chaos_env)
    with scenario_cluster(seed, data_dir, num_datanodes=3,
                          chaos_env=chaos_env) as c:
        t = _warm_up(c, 0.0)
        c.sql(CREATE)
        t = _warm_up(c, t, rounds=2)
        acked: dict = {}
        results = [try_insert(run, c, i, acked) for i in range(8)]
        run.check(results.count(False) == 1,
                  f"expected exactly one ENOSPC-failed write, got "
                  f"{results.count(False)} failures ({results})")
        run.check(not results[nth - 1],
                  f"the schedule says append #{nth} fails, but write "
                  f"{nth - 1} was acknowledged")

        shared = os.path.join(data_dir, "shared")
        run.check(verify_wal_objects_clean(run, shared) > 0,
                  "no WAL segment objects found — cleanup check vacuous")

        # the acid test for cleanup: kill the owner so the region is
        # rebuilt purely from the remote WAL, then compare EXACTLY
        rid, owner = _region_owner(c)
        t = _warm_up(c, t, rounds=2)
        c.kill_datanode(owner)
        t, _rounds = drive_failover(run, c, t, owner, rid,
                                    deadline_rounds=30)
        verify_acked(run, c, acked, exact=True)
        run.report.update(acked=len(acked), failed_write=nth - 1)
    return run.report


def scenario_smoke_partition_heal(data_dir: str, seed: int) -> dict:
    """Tier-1 smoke: the partition+heal scenario on a 2-datanode
    cluster — one cut, one heal, every invariant live."""
    return scenario_partition_heal(data_dir, seed, num_datanodes=2)


#: the scenario matrix (tools/run_scenarios.py runs it end to end)
SCENARIOS = {
    "smoke_partition_heal": scenario_smoke_partition_heal,
    "partition_heal": scenario_partition_heal,
    "partition_crash_failover": scenario_partition_crash_failover,
    "lease_loss_reelection": scenario_lease_loss_reelection,
    "wal_enospc": scenario_wal_enospc,
}


def run_scenario(name: str, data_dir: Optional[str] = None,
                 seed: Optional[int] = None) -> dict:
    """Run one named scenario; returns its report dict. The seed
    defaults to GTPU_CHAOS_SEED (so an exported seed replays) or the
    fixed DEFAULT_SEED."""
    import tempfile

    if name not in SCENARIOS:
        raise KeyError(f"unknown scenario {name!r} "
                       f"(have: {sorted(SCENARIOS)})")
    if seed is None:
        # an EXPORTED seed always wins — including 0 (the chaos
        # machinery's default): `GTPU_CHAOS_SEED=0 run_scenarios …` must
        # replay seed 0, not silently substitute the fallback
        env = os.environ.get("GTPU_CHAOS_SEED")
        seed = chaos_seed() if env not in (None, "") else DEFAULT_SEED
    if data_dir is not None:
        return SCENARIOS[name](data_dir, seed)
    with tempfile.TemporaryDirectory(prefix=f"gtpu_{name}_") as d:
        return SCENARIOS[name](d, seed)
