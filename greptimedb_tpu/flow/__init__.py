from .engine import FlowEngine, FlowInfo

__all__ = ["FlowEngine", "FlowInfo"]
