"""Flow engine: continuous aggregation (materialized views over streams).

Mirrors reference src/flow (adapter.rs:148 FlownodeManager, run_available
:507-527): a flow is `CREATE FLOW name SINK TO sink AS SELECT <aggregate>`;
as new rows land in the source table the aggregate is kept up to date in
the sink table.

TPU-native re-design (SURVEY.md §7 aux parity): instead of a hydroflow-
style incremental dataflow VM, each tick re-runs the flow's aggregate —
restricted to the time range dirtied since the last tick — through the
normal device query engine, and upserts the resulting groups into the sink.
The storage engine's last-write-wins semantics make the upsert free: sink
rows key on (group tags, bucket timestamp), so recomputed buckets overwrite
their previous values. Correct under late/out-of-order data within the
re-scan horizon, and every tick is one fused device aggregation rather than
row-at-a-time operator state.
"""

from __future__ import annotations

import json
import threading
import time
from dataclasses import dataclass, field
from typing import Optional

import numpy as np

from greptimedb_tpu.catalog.kv import KvBackend
from greptimedb_tpu.datatypes.types import SemanticType
from greptimedb_tpu.query.engine import QueryContext, QueryEngine
from greptimedb_tpu.query.result import QueryResult
from greptimedb_tpu.sql import ast, parse_sql

FLOW_PREFIX = "__flow/"


@dataclass
class FlowInfo:
    name: str
    db: str
    sink_table: str
    source_table: str
    sql: str  # the SELECT, re-parsed on load
    expire_after_s: Optional[int] = None
    comment: str = ""
    # incremental state
    last_version: int = -1  # source data_version at last tick
    watermark_ms: int = 0  # max source ts folded into the sink

    def to_json(self) -> str:
        return json.dumps(self.__dict__)

    @staticmethod
    def from_json(s: str) -> "FlowInfo":
        return FlowInfo(**json.loads(s))


class FlowEngine:
    """Manages flows; `run_available()` ticks every flow (adapter.rs:507)."""

    def __init__(self, query_engine: QueryEngine, kv: Optional[KvBackend] = None):
        self.qe = query_engine
        self.kv = kv if kv is not None else query_engine.catalog.kv
        self._lock = threading.Lock()

    # ------------------------------------------------------------- DDL
    def create_flow(self, stmt: ast.CreateFlow, ctx: QueryContext) -> FlowInfo:
        key = f"{FLOW_PREFIX}{ctx.db}/{stmt.name}"
        if self.kv.get(key) is not None:
            if stmt.if_not_exists:
                return FlowInfo.from_json(self.kv.get(key))
            raise ValueError(f"flow {stmt.name!r} already exists")
        sel = stmt.query
        if not isinstance(sel, ast.Select) or sel.table is None:
            raise ValueError("flow query must be a SELECT over a source table")
        sql = stmt.raw_query.strip() or _render_select(sel)
        info = FlowInfo(
            name=stmt.name, db=ctx.db, sink_table=stmt.sink_table,
            source_table=sel.table, sql=sql,
            expire_after_s=stmt.expire_after_s, comment=stmt.comment,
        )
        self._ensure_sink(info, sel, ctx)
        self.kv.put(key, info.to_json())
        return info

    def drop_flow(self, name: str, db: str = "public", if_exists: bool = False) -> None:
        key = f"{FLOW_PREFIX}{db}/{name}"
        if self.kv.get(key) is None and not if_exists:
            raise ValueError(f"flow {name!r} not found")
        self.kv.delete(key)

    def list_flows(self, db: str = "public") -> list[FlowInfo]:
        return [FlowInfo.from_json(v) for _, v in self.kv.range(f"{FLOW_PREFIX}{db}/")]

    # ------------------------------------------------------------- ticking
    def run_available(self, db: str = "public") -> dict[str, int]:
        """Tick every flow whose source changed; returns rows upserted per
        flow (the run_available loop, adapter.rs:507-527)."""
        out = {}
        for info in self.list_flows(db):
            n = self._tick_flow(info)
            if n:
                out[info.name] = n
        return out

    def flush(self, name: str, db: str = "public") -> int:
        """Tick one flow by name NOW; returns rows upserted. The ADMIN
        flush_flow() surface (reference common/function flush_flow)."""
        for info in self.list_flows(db):
            if info.name == name:
                return self._tick_flow(info)
        raise KeyError(f"flow {name!r} not found")

    def _tick_flow(self, info: FlowInfo) -> int:
        ctx = QueryContext(db=info.db)
        try:
            src = self.qe._table(info.source_table, ctx)
        except Exception:
            return 0
        version = sum(
            self.qe.region_engine.region(rid).data_version
            for rid in src.region_ids
        )
        if version == info.last_version:
            return 0
        sel = parse_sql(info.sql)[0]
        # dirty-horizon restriction: only recompute buckets that new data
        # can touch (watermark minus the expire horizon)
        if info.watermark_ms and info.expire_after_s:
            lo = info.watermark_ms - info.expire_after_s * 1000
            ts_name = src.schema.time_index.name
            cond = ast.BinaryOp(">=", ast.Column(ts_name), ast.Literal(lo))
            sel.where = cond if sel.where is None else ast.BinaryOp("and", sel.where, cond)
        res = self.qe.execute_statement(sel, ctx)
        n = self._upsert_sink(info, res, ctx)
        # advance watermark to max source ts seen
        scan = None
        try:
            scan = self.qe.region_engine.scan(src.region_ids[0])
        except Exception:
            pass
        if scan is not None and scan.num_rows:
            info.watermark_ms = int(np.max(scan.columns[src.schema.time_index.name]))
        info.last_version = version
        self.kv.put(f"{FLOW_PREFIX}{info.db}/{info.name}", info.to_json())
        return n

    # ------------------------------------------------------------- sink
    def _ensure_sink(self, info: FlowInfo, sel: ast.Select, ctx: QueryContext) -> None:
        """Auto-create the sink table from the flow query's output shape:
        group-by string keys become tags, a bucket timestamp becomes the
        time index, aggregates become fields."""
        if self.qe.catalog.table_exists(ctx.db, info.sink_table):
            return
        probe = self.qe.execute_statement(sel, ctx)
        cols_sql = []
        pks = []
        ts_col = None
        for name, dt in zip(probe.names, probe.dtypes):
            safe = _ident(name)
            if dt is not None and getattr(dt, "is_timestamp", False) and ts_col is None:
                ts_col = safe
                cols_sql.append(f"{safe} TIMESTAMP(3) TIME INDEX")
            elif dt is not None and getattr(dt, "is_string", False):
                pks.append(safe)
                cols_sql.append(f"{safe} STRING")
            else:
                cols_sql.append(f"{safe} DOUBLE")
        if ts_col is None:
            cols_sql.append("update_at TIMESTAMP(3) TIME INDEX")
        pk = f", PRIMARY KEY({', '.join(pks)})" if pks else ""
        self.qe.execute_one(
            f"CREATE TABLE {info.sink_table} ({', '.join(cols_sql)}{pk})",
            ctx,
        )

    def _upsert_sink(self, info: FlowInfo, res: QueryResult, ctx: QueryContext) -> int:
        if res.num_rows == 0:
            return 0
        sink = self.qe.catalog.table(ctx.db, info.sink_table)
        names = [_ident(n) for n in res.names]
        has_ts = any(n == sink.schema.time_index.name for n in names)
        rows_sql = []
        for row in res.rows():
            vals = []
            for v in row:
                if v is None or (isinstance(v, float) and np.isnan(v)):
                    vals.append("NULL")
                elif isinstance(v, str):
                    vals.append("'" + v.replace("'", "''") + "'")
                else:
                    vals.append(repr(v) if not isinstance(v, bool) else str(v).upper())
            if not has_ts:
                # un-bucketed flows key the sink purely on the group tags:
                # a constant time index makes each tick's upsert overwrite
                # the group's previous value (LWW)
                vals.append("0")
            rows_sql.append("(" + ", ".join(vals) + ")")
        cols = names + ([] if has_ts else [sink.schema.time_index.name])
        sql = (f"INSERT INTO {info.sink_table} ({', '.join(cols)}) VALUES "
               + ", ".join(rows_sql))
        out = self.qe.execute_one(sql, ctx)
        return out.affected_rows or 0


def _ident(name: str) -> str:
    import re

    safe = re.sub(r"[^0-9a-zA-Z_]", "_", name)
    return safe or "col"


def _render_select(sel: ast.Select) -> str:
    raise ValueError("flow statement carried no raw query text")
