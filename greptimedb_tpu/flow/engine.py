"""Flow engine: continuous aggregation (materialized views over streams).

Mirrors reference src/flow (adapter.rs:148 FlownodeManager, run_available
:507-527): a flow is `CREATE FLOW name SINK TO sink AS SELECT <aggregate>`;
as new rows land in the source table the aggregate is kept up to date in
the sink table.

TPU-native re-design (SURVEY.md §7 aux parity): instead of a hydroflow-
style incremental dataflow VM, ticks fold through one of two paths:

1. INCREMENTAL (append-mode sources, decomposable aggregates): each
   region scan is bounded by the write-sequence fold boundary
   (`seq_min` — only rows written since the last tick leave disk, with
   whole SSTs pruned by FileMeta.max_seq), the new rows reduce to
   partial planes with the same segment kernels the distributed
   Partial step uses, and the planes MERGE with per-group state
   persisted as `__st_*` columns in the sink itself. A tick is
   O(new data), exactly-once per row (sequence-based, so late or
   out-of-order data folds correctly), and the sink's visible columns
   finalize from the merged state (avg = sum/count, ...). This is the
   operator-state role of the reference's dataflow VM
   (flow/src/compute/render.rs reduce operators), re-designed around
   plane algebra instead of row-at-a-time state machines.
2. DIRTY-SPAN fallback (updates/deletes possible, or non-decomposable
   aggregates): re-run the aggregate restricted to the time range
   dirtied since the last tick and upsert groups (LWW makes the
   upsert idempotent).
"""

from __future__ import annotations

import json
import logging
import threading
from dataclasses import dataclass, field
from typing import Optional

import numpy as np

from greptimedb_tpu.catalog.kv import KvBackend
from greptimedb_tpu.query.engine import QueryContext, QueryEngine
from greptimedb_tpu.query.result import QueryResult
from greptimedb_tpu.sql import ast, parse_sql
from greptimedb_tpu.utils.metrics import FLOW_TICK_ERRORS

logger = logging.getLogger(__name__)

FLOW_PREFIX = "__flow/"


@dataclass
class FlowInfo:
    name: str
    db: str
    sink_table: str
    source_table: str
    sql: str  # the SELECT, re-parsed on load
    expire_after_s: Optional[int] = None
    comment: str = ""
    # incremental state
    last_version: int = -1  # source data_version at last tick
    watermark_ms: int = 0  # max source ts folded into the sink
    # sequence fold boundary per source region (incremental path):
    # every row with seq <= last_seqs[str(rid)] has been folded exactly
    # once into the sink's state planes
    last_seqs: dict = field(default_factory=dict)
    incremental: bool = False  # sink carries __st_* state columns

    def to_json(self) -> str:
        return json.dumps(self.__dict__)

    @staticmethod
    def from_json(s: str) -> "FlowInfo":
        return FlowInfo(**json.loads(s))


class FlowEngine:
    """Manages flows; `run_available()` ticks every flow (adapter.rs:507)."""

    def __init__(self, query_engine: QueryEngine, kv: Optional[KvBackend] = None):
        self.qe = query_engine
        self.kv = kv if kv is not None else query_engine.catalog.kv
        self._lock = threading.Lock()

    # ------------------------------------------------------------- DDL
    def create_flow(self, stmt: ast.CreateFlow, ctx: QueryContext) -> FlowInfo:
        key = f"{FLOW_PREFIX}{ctx.db}/{stmt.name}"
        if self.kv.get(key) is not None:
            if stmt.if_not_exists:
                return FlowInfo.from_json(self.kv.get(key))
            raise ValueError(f"flow {stmt.name!r} already exists")
        sel = stmt.query
        if not isinstance(sel, ast.Select) or sel.table is None:
            raise ValueError("flow query must be a SELECT over a source table")
        sql = stmt.raw_query.strip() or _render_select(sel)
        info = FlowInfo(
            name=stmt.name, db=ctx.db, sink_table=stmt.sink_table,
            source_table=sel.table, sql=sql,
            expire_after_s=stmt.expire_after_s, comment=stmt.comment,
        )
        try:
            src = self.qe._table(sel.table, ctx)
            plan = self._incr_plan(parse_sql(sql)[0], src)
        except Exception:  # noqa: BLE001 — eligibility probe only
            plan = None
        info.incremental = plan is not None
        self._ensure_sink(info, sel, ctx, plan)
        if info.incremental:
            # a pre-existing sink without state columns cannot carry
            # the incremental planes — stay on the dirty-span path
            sink = self.qe.catalog.table(ctx.db, info.sink_table)
            need = {c for c, _, _ in self._state_cols(plan)}
            if not need <= set(sink.schema.names):
                info.incremental = False
        self.kv.put(key, info.to_json())
        return info

    def drop_flow(self, name: str, db: str = "public", if_exists: bool = False) -> None:
        key = f"{FLOW_PREFIX}{db}/{name}"
        if self.kv.get(key) is None and not if_exists:
            raise ValueError(f"flow {name!r} not found")
        self.kv.delete(key)

    def list_flows(self, db: str = "public") -> list[FlowInfo]:
        return [FlowInfo.from_json(v) for _, v in self.kv.range(f"{FLOW_PREFIX}{db}/")]

    # ------------------------------------------------------------- ticking
    def run_available(self, db: str = "public") -> dict[str, int]:
        """Tick every flow whose source changed; returns rows upserted per
        flow (the run_available loop, adapter.rs:507-527)."""
        out = {}
        for info in self.list_flows(db):
            n = self._tick_flow(info)
            if n:
                out[info.name] = n
        return out

    def flush(self, name: str, db: str = "public") -> int:
        """Tick one flow by name NOW; returns rows upserted. The ADMIN
        flush_flow() surface (reference common/function flush_flow)."""
        for info in self.list_flows(db):
            if info.name == name:
                return self._tick_flow(info)
        raise KeyError(f"flow {name!r} not found")

    # observability for tests + EXPLAIN-style introspection: stats of
    # the most recent tick that did work
    last_tick_stats: Optional[dict] = None

    #: aggregate functions whose state decomposes onto the plane algebra
    _INCR_FUNCS = frozenset({"sum", "count", "avg", "min", "max",
                             "stddev", "variance", "rows"})

    def _incr_plan(self, sel: ast.Select, src) -> Optional[dict]:
        """Classify the flow query for the incremental path. Returns
        {keys, aggs, where, args, ops, spec_slots, items} or None when
        only the dirty-span re-scan is sound (non-append sources could
        rewrite already-folded rows; non-decomposable aggregates have
        no mergeable state; post-aggregate expressions would need
        re-evaluation over finalized planes)."""
        from greptimedb_tpu.query import logical as lp
        from greptimedb_tpu.query.physical import _needs_host_agg, _PRIMITIVES
        from greptimedb_tpu.query.planner import plan_select

        if not src.append_mode:
            return None
        # only plain LSM regions (local or remote) carry the write
        # sequence the fold boundary needs — metric-engine logical
        # regions share a physical store and external tables have no
        # sequences at all
        for rid in src.region_ids:
            try:
                region = self.qe.region_engine.region(rid)
            except Exception:  # noqa: BLE001 — resolution happens at tick
                continue
            if not (hasattr(region, "files")
                    or hasattr(region, "_client")):
                return None
        node = plan_select(sel, src)
        if not isinstance(node, lp.Project):
            return None
        project = node
        node = node.input
        if not isinstance(node, lp.Aggregate):
            return None
        agg = node
        node = node.input
        where = None
        if isinstance(node, lp.Filter):
            where = node.predicate
            node = node.input
        if not isinstance(node, lp.Scan):
            return None
        for spec in agg.aggs:
            if spec.func not in self._INCR_FUNCS \
                    or _needs_host_agg(spec, src.schema):
                return None
        # each output must be exactly a group key or a plain aggregate
        # call — finalization recomputes visible values from state
        items: list[tuple[str, str, int]] = []  # (col, kind, index)
        for name, expr in project.items:
            hit = None
            for i, (_, kexpr) in enumerate(agg.keys):
                if expr == kexpr:
                    hit = ("key", i)
                    break
            if hit is None:
                for j, spec in enumerate(agg.aggs):
                    if expr == spec.call:
                        hit = ("agg", j)
                        break
            if hit is None:
                return None
            items.append((_ident(name), hit[0], hit[1]))
        # every group key must be projected: the sink re-identifies
        # groups by their key column values
        projected = {idx for _, kind, idx in items if kind == "key"}
        if projected != set(range(len(agg.keys))):
            return None
        args: list[ast.Expr] = []
        spec_slots: list[Optional[int]] = []
        ops: set = {"rows"}
        for spec in agg.aggs:
            ops.update(_PRIMITIVES[spec.func])
            if spec.arg is None:
                spec_slots.append(None)
                continue
            if spec.arg not in args:
                args.append(spec.arg)
            spec_slots.append(args.index(spec.arg))
        return {"keys": agg.keys, "aggs": agg.aggs, "where": where,
                "args": args, "ops": sorted(ops),
                "spec_slots": spec_slots, "items": items}

    @staticmethod
    def _state_cols(plan: dict) -> list[tuple[str, str, Optional[int]]]:
        """[(column name, op, slot)] for the sink's state planes."""
        out = []
        for op in plan["ops"]:
            if op == "rows":
                out.append(("__st_rows", op, None))
            else:
                for slot in range(max(len(plan["args"]), 1)):
                    out.append((f"__st_{op}_{slot}", op, slot))
        return out

    def _tick_flow(self, info: FlowInfo) -> int:
        ctx = QueryContext(db=info.db)
        try:
            src = self.qe._table(info.source_table, ctx)
        except Exception:
            return 0
        version = sum(
            self.qe.region_engine.region(rid).data_version
            for rid in src.region_ids
        )
        if version == info.last_version:
            return 0
        sel = parse_sql(info.sql)[0]
        if info.incremental:
            # an incremental flow may NEVER fall through to the
            # dirty-span path: its upsert writes only visible columns,
            # which would NULL the sink's state planes and corrupt
            # every later merge. On any failure, retry next tick — the
            # boundary only advances on success.
            try:
                plan = self._incr_plan(sel, src)
                if plan is None:
                    raise RuntimeError(
                        f"flow {info.name}: incremental plan no longer "
                        "eligible (source or query changed?)")
                return self._tick_incremental(info, src, ctx, plan,
                                              version)
            except Exception:  # noqa: BLE001 — retry next tick
                # observable, not printed: chaos runs assert on the
                # counter, operators see the log — the boundary did not
                # advance, so the next tick retries the same rows
                FLOW_TICK_ERRORS.inc(flow=info.name)
                logger.warning(
                    "flow %s: incremental tick failed; retrying next tick",
                    info.name, exc_info=True)
                return 0
        # dirty-horizon restriction: only recompute buckets that new data
        # can touch (watermark minus the expire horizon)
        if info.watermark_ms and info.expire_after_s:
            lo = info.watermark_ms - info.expire_after_s * 1000
            ts_name = src.schema.time_index.name
            cond = ast.BinaryOp(">=", ast.Column(ts_name), ast.Literal(lo))
            sel.where = cond if sel.where is None else ast.BinaryOp("and", sel.where, cond)
        # the dirty-span re-aggregate rides the executor's shared
        # delta-fold seam (ISSUE 13): where the shape is partial-cache
        # eligible, immutable parts fold from cached [G, F] partials and
        # only the span's delta (memtable + new files) runs kernels —
        # this path no longer pays a private full re-fold per tick
        res = self.qe.execute_statement(sel, ctx)
        pstats = getattr(self.qe.executor, "last_partial_stats", None)
        FlowEngine.last_tick_stats = {
            "flow": info.name, "path": "dirty_span",
            "partial_cache": pstats}
        n = self._upsert_sink(info, res, ctx)
        # advance watermark to max source ts seen
        scan = None
        try:
            scan = self.qe.region_engine.scan(src.region_ids[0])
        except Exception:
            pass
        if scan is not None and scan.num_rows:
            info.watermark_ms = int(np.max(scan.columns[src.schema.time_index.name]))
        info.last_version = version
        self.kv.put(f"{FLOW_PREFIX}{info.db}/{info.name}", info.to_json())
        return n

    def _tick_incremental(self, info: FlowInfo, src, ctx: QueryContext,
                          plan: dict, version: int) -> int:
        """Fold rows written since the last tick into the sink's state
        planes (module docstring path 1)."""
        from types import SimpleNamespace

        from greptimedb_tpu.query.dist_agg import (combine_partials,
                                                   partial_region_agg)

        executor = self.qe.executor
        # expire horizon (reference flow expire_after): rows older than
        # watermark - expire drop out of the FOLD (a WHERE conjunct,
        # matching the dirty-span path's restriction) — but NOT out of
        # the scan, so their sequences still advance the boundary and
        # they are skipped exactly once, not rescanned forever.
        where = plan["where"]
        if info.expire_after_s and info.watermark_ms:
            lo = info.watermark_ms - info.expire_after_s * 1000
            ts_name = src.schema.time_index.name
            cond = ast.BinaryOp(">=", ast.Column(ts_name), ast.Literal(lo))
            where = cond if where is None \
                else ast.BinaryOp("and", where, cond)
        shim = SimpleNamespace(keys=plan["keys"], args=plan["args"],
                               ops=plan["ops"], where=where,
                               ts_range=None, append_mode=True, tz=None)
        partials = []
        scanned = 0
        new_seqs = dict(info.last_seqs or {})
        max_ts = info.watermark_ms
        for rid in src.region_ids:
            st: dict = {}
            p = partial_region_agg(
                executor, rid, shim, schema=src.schema,
                seq_min=int(new_seqs.get(str(rid), -1)), stats_out=st)
            scanned += st.get("rows", 0)
            if st.get("max_seq") is not None:
                new_seqs[str(rid)] = max(int(st["max_seq"]),
                                         int(new_seqs.get(str(rid), -1)))
            if st.get("max_ts") is not None:
                max_ts = max(max_ts, int(st["max_ts"]))
            if p is not None:
                partials.append(p)
        FlowEngine.last_tick_stats = {
            "flow": info.name, "path": "incremental",
            "scanned_rows": scanned}
        key = f"{FLOW_PREFIX}{info.db}/{info.name}"
        if not partials:
            info.last_seqs = new_seqs
            info.last_version = version
            info.watermark_ms = max_ts
            self.kv.put(key, info.to_json())
            return 0
        n_keys = len(plan["keys"])
        ops = tuple(plan["ops"])
        new = combine_partials(partials, n_keys, ops)
        state = self._read_sink_state(info, plan, new, ctx)
        merged = combine_partials(
            [state, new], n_keys, ops) if state is not None else new
        n = self._write_sink_merged(info, plan, merged, ctx)
        info.last_seqs = new_seqs
        info.last_version = version
        info.watermark_ms = max_ts
        self.kv.put(key, info.to_json())
        return n

    def _sink_key_names(self, plan: dict) -> list[Optional[str]]:
        """Sink column name per group-key index (None if unprojected)."""
        names: list[Optional[str]] = [None] * len(plan["keys"])
        for col, kind, idx in plan["items"]:
            if kind == "key":
                names[idx] = col
        return names

    def _read_sink_state(self, info: FlowInfo, plan: dict, new: dict,
                         ctx: QueryContext) -> Optional[dict]:
        """Current state planes for the groups the new partials touch,
        read back from the sink as a mergeable partial. Bounded by the
        new data's bucket span when the sink is time-keyed."""
        sink = self.qe.catalog.table(ctx.db, info.sink_table)
        ts_col = sink.schema.time_index.name
        key_names = self._sink_key_names(plan)
        state_cols = self._state_cols(plan)
        sel_cols = [n for n in key_names if n is not None] \
            + [c for c, _, _ in state_cols]
        where = ""
        if ts_col in key_names:
            b = np.asarray(new["keys"][key_names.index(ts_col)],
                           dtype=np.int64)
            where = f" WHERE {ts_col} >= {int(b.min())} " \
                    f"AND {ts_col} <= {int(b.max())}"
        res = self.qe.execute_one(
            f"SELECT {', '.join(sel_cols)} FROM {info.sink_table}{where}",
            ctx)
        if res.num_rows == 0:
            return None
        cols = dict(zip(res.names, res.columns))
        keys = [np.asarray(cols[n]) for n in key_names]
        g = res.num_rows
        f = max(len(plan["args"]), 1)
        planes: dict[str, np.ndarray] = {}
        for op in plan["ops"]:
            if op == "rows":
                planes[op] = np.asarray(cols["__st_rows"],
                                        dtype=np.float64)
            else:
                planes[op] = np.stack(
                    [np.asarray(cols[f"__st_{op}_{s}"], dtype=np.float64)
                     for s in range(f)], axis=1)
        return {"keys": keys, "planes": planes}

    def _write_sink_merged(self, info: FlowInfo, plan: dict, merged: dict,
                           ctx: QueryContext) -> int:
        """Upsert merged groups: finalized visible columns + state
        planes (LWW on the sink's keys overwrites the previous row)."""
        from greptimedb_tpu.query.physical import _finalize_agg

        g = len(merged["keys"][0]) if merged["keys"] else 1
        present = np.arange(g)
        out_cols: dict[str, np.ndarray] = {}
        order: list[str] = []
        for col, kind, idx in plan["items"]:
            if kind == "key":
                out_cols[col] = np.asarray(merged["keys"][idx])
            else:
                spec = plan["aggs"][idx]
                out_cols[col] = _finalize_agg(
                    spec.func, merged["planes"], plan["spec_slots"][idx],
                    present)
            order.append(col)
        for col, op, slot in self._state_cols(plan):
            pl = np.asarray(merged["planes"][op], dtype=np.float64)
            out_cols[col] = pl[:, slot] if pl.ndim == 2 else pl
            order.append(col)
        sink = self.qe.catalog.table(ctx.db, info.sink_table)
        ts_col = sink.schema.time_index.name
        if ts_col not in order:
            # group-only flows key the sink on a constant time index
            out_cols[ts_col] = np.zeros(g, dtype=np.int64)
            order.append(ts_col)
        rows_sql = []
        for r in range(g):
            vals = []
            for col in order:
                v = out_cols[col][r]
                if v is None or (isinstance(v, float) and np.isnan(v)):
                    vals.append("NULL")
                elif isinstance(v, str):
                    vals.append("'" + v.replace("'", "''") + "'")
                else:
                    vals.append(repr(v.item() if hasattr(v, "item")
                                     else v))
            rows_sql.append("(" + ", ".join(vals) + ")")
        sql = (f"INSERT INTO {info.sink_table} ({', '.join(order)}) "
               "VALUES " + ", ".join(rows_sql))
        out = self.qe.execute_one(sql, ctx)
        return out.affected_rows or 0

    # ------------------------------------------------------------- sink
    def _ensure_sink(self, info: FlowInfo, sel: ast.Select, ctx: QueryContext,
                     plan: Optional[dict] = None) -> None:
        """Auto-create the sink table from the flow query's output shape:
        group-by string keys become tags, a bucket timestamp becomes the
        time index, aggregates become fields."""
        if self.qe.catalog.table_exists(ctx.db, info.sink_table):
            return
        probe = self.qe.execute_statement(sel, ctx)
        cols_sql = []
        pks = []
        ts_col = None
        for name, dt in zip(probe.names, probe.dtypes):
            safe = _ident(name)
            if dt is not None and getattr(dt, "is_timestamp", False) and ts_col is None:
                ts_col = safe
                cols_sql.append(f"{safe} TIMESTAMP(3) TIME INDEX")
            elif dt is not None and getattr(dt, "is_string", False):
                pks.append(safe)
                cols_sql.append(f"{safe} STRING")
            else:
                cols_sql.append(f"{safe} DOUBLE")
        if ts_col is None:
            cols_sql.append("update_at TIMESTAMP(3) TIME INDEX")
        if plan is not None:
            # state planes for the incremental path ride in the sink
            # itself: the LWW upsert replaces value + state atomically
            for col, _, _ in self._state_cols(plan):
                cols_sql.append(f"{col} DOUBLE")
        pk = f", PRIMARY KEY({', '.join(pks)})" if pks else ""
        self.qe.execute_one(
            f"CREATE TABLE {info.sink_table} ({', '.join(cols_sql)}{pk})",
            ctx,
        )

    def _upsert_sink(self, info: FlowInfo, res: QueryResult, ctx: QueryContext) -> int:
        if res.num_rows == 0:
            return 0
        sink = self.qe.catalog.table(ctx.db, info.sink_table)
        names = [_ident(n) for n in res.names]
        has_ts = any(n == sink.schema.time_index.name for n in names)
        rows_sql = []
        for row in res.rows():
            vals = []
            for v in row:
                if v is None or (isinstance(v, float) and np.isnan(v)):
                    vals.append("NULL")
                elif isinstance(v, str):
                    vals.append("'" + v.replace("'", "''") + "'")
                else:
                    vals.append(repr(v) if not isinstance(v, bool) else str(v).upper())
            if not has_ts:
                # un-bucketed flows key the sink purely on the group tags:
                # a constant time index makes each tick's upsert overwrite
                # the group's previous value (LWW)
                vals.append("0")
            rows_sql.append("(" + ", ".join(vals) + ")")
        cols = names + ([] if has_ts else [sink.schema.time_index.name])
        sql = (f"INSERT INTO {info.sink_table} ({', '.join(cols)}) VALUES "
               + ", ".join(rows_sql))
        out = self.qe.execute_one(sql, ctx)
        return out.affected_rows or 0


def _ident(name: str) -> str:
    import re

    safe = re.sub(r"[^0-9a-zA-Z_]", "_", name)
    return safe or "col"


def _render_select(sel: ast.Select) -> str:
    raise ValueError("flow statement carried no raw query text")
