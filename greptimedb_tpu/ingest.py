"""Columnar ingest builders: the protocol front doors' bulk fast path.

Every front door (Influx line protocol, Prometheus remote-write, OTLP,
OpenTSDB) used to materialize row objects and re-pivot them per column
at write time — O(rows × columns) dict churn that kept protocol ingest
on the slow row-at-a-time path. A ``TableSlab`` accumulates parsed rows
column-major instead: per-column append buffers that materialize as
numpy arrays / DictVectors in one vectorized pass, producing ONE
RecordBatch per table per request. The batch then takes a single
partition-rule scatter (``QueryEngine._sharded_write``) and lands on
the same bulk path the headline ingest number uses, with schema
auto-create/alter batched once per request (one region flush per
request instead of one per new column).
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from greptimedb_tpu.catalog.catalog import CatalogError
from greptimedb_tpu.datatypes import (
    ColumnSchema,
    DataType,
    DictVector,
    RecordBatch,
    Schema,
    SemanticType,
)


class TableSlab:
    """Column-major row accumulator for one table.

    Tags and string fields accumulate as object lists (they become
    dictionary codes anyway); numeric fields accumulate as lists that
    materialize through one ``np.asarray`` — the vectorized conversion
    is where the per-value Python dispatch of the old row path
    disappears. Columns appear lazily and are NULL-padded for rows that
    predate them, so sparse protocols (Influx fields, OTLP attributes)
    cost only what they send."""

    __slots__ = ("rows", "tags", "fields", "ts")

    def __init__(self):
        self.rows = 0
        self.tags: dict[str, list] = {}
        self.fields: dict[str, list] = {}
        self.ts: list[int] = []

    def add_row(self, tags, fields, ts_ms: int) -> None:
        """Append one row: `tags`/`fields` are (name, value) iterables.
        A name repeated within one row keeps the last value (Influx
        semantics)."""
        r = self.rows
        appended = 0
        for k, v in tags:
            col = self.tags.get(k)
            if col is None:
                col = self.tags[k] = [None] * r
            if len(col) == r:
                col.append(v)
                appended += 1
            else:
                col[-1] = v
        for k, v in fields:
            col = self.fields.get(k)
            if col is None:
                col = self.fields[k] = [None] * r
            if len(col) == r:
                col.append(v)
                appended += 1
            else:
                col[-1] = v
        self.ts.append(ts_ms)
        self.rows = r + 1
        if appended != len(self.tags) + len(self.fields):
            # a column this row did not carry: NULL-pad (uniform rows —
            # the common shape — skip this loop entirely)
            for col in self.tags.values():
                if len(col) != self.rows:
                    col.append(None)
            for col in self.fields.values():
                if len(col) != self.rows:
                    col.append(None)

    def extend_column(self, kind: str, name: str, values: list) -> None:
        """Bulk-append `values` to one column without touching the
        others (remote-write: a whole series' samples share one label
        set — extend beats row-at-a-time appends). The caller owns row
        accounting via `extend_rows`."""
        cols = self.tags if kind == "tag" else self.fields
        col = cols.get(name)
        if col is None:
            col = cols[name] = [None] * self.rows
        col.extend(values)

    def extend_rows(self, ts_values: list) -> None:
        """Commit a bulk extension: pad every column shorter than the
        new row count (columns this series did not carry)."""
        self.ts.extend(ts_values)
        self.rows += len(ts_values)
        for cols in (self.tags, self.fields):
            for col in cols.values():
                if len(col) < self.rows:
                    col.extend([None] * (self.rows - len(col)))

    # ---- schema inference / materialization ---------------------------------

    def field_type(self, name: str) -> DataType:
        """Type from the first non-NULL value (Influx convention);
        integers store as FLOAT64 — sparse fields need a NULL
        representation the integer columns do not have."""
        for v in self.fields.get(name, ()):
            if v is None:
                continue
            if isinstance(v, bool):
                return DataType.BOOL
            if isinstance(v, str):
                return DataType.STRING
            return DataType.FLOAT64
        return DataType.FLOAT64

    def to_batch(self, schema: Schema) -> RecordBatch:
        """Materialize against the table's schema order: one vectorized
        conversion per column, NULLs filled per dtype (NaN / False / 0 /
        dictionary NULL code)."""
        n = self.rows
        cols: dict = {}
        for c in schema.columns:
            if c.semantic is SemanticType.TAG:
                cols[c.name] = DictVector.encode(
                    self.tags.get(c.name, [None] * n))
            elif c.semantic is SemanticType.TIMESTAMP:
                cols[c.name] = np.asarray(self.ts, dtype=np.int64)
            else:
                vals = self.fields.get(c.name)
                if vals is None:
                    vals = [None] * n
                if c.dtype.is_float:
                    try:
                        arr = np.asarray(vals, dtype=c.dtype.to_numpy())
                    except (TypeError, ValueError):  # Nones / mixed
                        arr = np.asarray(
                            [np.nan if v is None else float(v)
                             for v in vals], dtype=c.dtype.to_numpy())
                    cols[c.name] = arr
                elif c.dtype is DataType.BOOL:
                    cols[c.name] = np.asarray(
                        [bool(v) for v in vals])
                elif c.dtype.is_string:
                    cols[c.name] = DictVector.encode(
                        [None if v is None else str(v) for v in vals])
                else:
                    cols[c.name] = np.asarray(
                        [0 if v is None else int(v) for v in vals],
                        dtype=np.int64)
        return RecordBatch(schema, cols)


class VectorSlab:
    """Pre-materialized slab from the vectorized parse lane
    (servers/influx._vector_parse): tag columns arrive already
    dictionary-encoded, float fields already numpy — `to_batch` is a
    schema-order assembly, not a conversion. Quacks like TableSlab for
    `ensure_table` (tags/fields key views, field_type, rows)."""

    __slots__ = ("rows", "tags", "fields", "ts")

    def __init__(self, rows: int, tags: dict, fields: dict,
                 ts: np.ndarray):
        self.rows = rows
        self.tags = tags      # name -> DictVector (no NULLs by lane)
        self.fields = fields  # name -> np.float64 array
        self.ts = ts          # np.int64 ms

    def field_type(self, name: str) -> DataType:
        return DataType.FLOAT64  # the lane only admits float fields

    def to_batch(self, schema: Schema) -> RecordBatch:
        n = self.rows
        cols: dict = {}
        for c in schema.columns:
            if c.semantic is SemanticType.TAG:
                dv = self.tags.get(c.name)
                cols[c.name] = dv if dv is not None \
                    else DictVector.encode([None] * n)
            elif c.semantic is SemanticType.TIMESTAMP:
                cols[c.name] = self.ts
            else:
                arr = self.fields.get(c.name)
                if arr is None:
                    if c.dtype.is_float:
                        cols[c.name] = np.full(n, np.nan,
                                               dtype=c.dtype.to_numpy())
                    elif c.dtype is DataType.BOOL:
                        cols[c.name] = np.zeros(n, dtype=bool)
                    elif c.dtype.is_string:
                        cols[c.name] = DictVector.encode([None] * n)
                    else:
                        cols[c.name] = np.zeros(n, dtype=np.int64)
                # present fields coerce like TableSlab coerces numeric
                # values into the table's declared dtype
                elif c.dtype.is_float:
                    cols[c.name] = arr.astype(c.dtype.to_numpy(),
                                              copy=False)
                elif c.dtype is DataType.BOOL:
                    cols[c.name] = arr.astype(bool)
                elif c.dtype.is_string:
                    cols[c.name] = DictVector.encode(
                        [str(v) for v in arr])
                else:
                    cols[c.name] = arr.astype(np.int64)
        return RecordBatch(schema, cols)


def sql_values_batch(schema: Schema, by_col: dict, nrows: int,
                     timezone=None) -> RecordBatch:
    """The SQL `INSERT ... VALUES` columnar seam: raw value columns ->
    one RecordBatch, with the same per-dtype conversions the protocol
    slabs use (one vectorized pass per column; NULLs fill per dtype).

    This is where the statement ingest path joins the bulk path: the
    parser's literal fast lane hands raw column lists straight here, so
    a multi-row INSERT decodes like a line-protocol slab instead of one
    Python dispatch per cell."""
    from greptimedb_tpu.utils.time import coerce_ts_literal

    cols: dict = {}
    for c in schema.columns:
        vals = by_col.get(c.name)
        if vals is None:
            vals = [c.default] * nrows
        if c.semantic is SemanticType.TAG:
            if not all(type(v) is str for v in vals):
                vals = [None if v is None else str(v) for v in vals]
            cols[c.name] = DictVector.encode(vals)
        elif c.dtype.is_timestamp:
            if all(type(v) is int for v in vals):
                # integer literals are already in the column's unit
                cols[c.name] = np.asarray(vals, dtype=np.int64)
                continue
            coerced = []
            for v in vals:
                if v is None:
                    raise ValueError(
                        f"time index {c.name} cannot be NULL")
                coerced.append(coerce_ts_literal(v, c.dtype, timezone))
            cols[c.name] = np.asarray(coerced, dtype=np.int64)
        elif c.dtype.is_string:
            cols[c.name] = DictVector.encode(
                [None if v is None else str(v) for v in vals])
        elif c.dtype.is_float:
            try:
                cols[c.name] = np.asarray(vals, dtype=c.dtype.to_numpy())
            except (TypeError, ValueError):  # Nones / mixed types
                cols[c.name] = np.asarray(
                    [np.nan if v is None else float(v) for v in vals],
                    dtype=c.dtype.to_numpy())
        elif c.dtype is DataType.BOOL:
            cols[c.name] = np.asarray(
                [False if v is None else bool(v) for v in vals])
        else:
            cols[c.name] = np.asarray(
                [0 if v is None else int(v) for v in vals],
                dtype=c.dtype.to_numpy())
    return RecordBatch(schema, cols)


def ensure_table(query_engine, ctx, name: str, slab: TableSlab,
                 time_index: str = "ts",
                 value_field: Optional[str] = None):
    """Auto-create the table from the slab's shape, or auto-ALTER all
    missing field columns in ONE schema swap (reference insert.rs:112
    create_or_alter_tables_on_demand; the old path issued one ALTER —
    and one region flush — per new column)."""
    qe = query_engine
    try:
        info = qe._table(name, ctx)
    except CatalogError:
        cols = [ColumnSchema(t, DataType.STRING, SemanticType.TAG)
                for t in slab.tags]
        cols.append(ColumnSchema(time_index, DataType.TIMESTAMP_MILLISECOND,
                                 SemanticType.TIMESTAMP, nullable=False))
        for fn in slab.fields:
            cols.append(ColumnSchema(fn, slab.field_type(fn),
                                     SemanticType.FIELD))
        info = qe.catalog.create_table(ctx.db, name, Schema(cols),
                                       options={}, if_not_exists=True)
        for rid in info.region_ids:
            qe.region_engine.create_region(rid, info.schema)
            qe._open_regions.add(rid)
        return info
    missing_tags = [t for t in slab.tags if t not in info.schema]
    if missing_tags:
        raise ValueError(
            f"new tag column(s) {missing_tags} on existing table "
            f"{name!r} are not supported")
    missing = [fn for fn in slab.fields if fn not in info.schema]
    if missing:
        new_schema = Schema(
            list(info.schema.columns)
            + [ColumnSchema(fn, slab.field_type(fn), SemanticType.FIELD,
                            True) for fn in missing])
        for fn in missing:
            qe._refresh_column_order(info, added=fn)
        qe._apply_alter(info, new_schema)
        info = qe._table(name, ctx)
    if value_field is not None and value_field not in info.schema:
        raise ValueError(
            f"table {name!r} has no {value_field!r} column")
    return info


def write_slabs(query_engine, ctx, slabs: dict[str, TableSlab],
                time_index: str = "ts") -> int:
    """Write every slab as one RecordBatch per table through the
    partition-rule scatter — the bulk path. Returns total rows."""
    total = 0
    for name, slab in slabs.items():
        if not slab.rows:
            continue
        info = ensure_table(query_engine, ctx, name, slab,
                            time_index=time_index)
        batch = slab.to_batch(info.schema)
        total += query_engine._sharded_write(info, batch, delete=False)
    return total
