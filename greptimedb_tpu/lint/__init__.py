"""gtpu-lint: repo-invariant static analysis (run via tools/gtpu_lint.py).

Seven PRs of this reproduction accumulated cross-cutting invariants that
existed only as convention — the reference enforces the analogous ones
with Rust's type system and clippy lints. This package machine-checks
them over the repo's own AST so the next PR cannot silently regress:

  fault-seam    direct file/socket I/O in storage/wal/cluster/objectstore
                must route through the FaultRegistry seams
  jax-import    storage-only processes must not (non-lazily) import jax
                beyond the documented platform-pinning bootstrap
  tracer        jit/pallas/donated functions must stay traceable: no
                Python control flow on traced values, no host coercions,
                no wall-clock/RNG, no reuse of donated buffers
  typed-error   wire boundaries must map typed Unavailable/Overloaded
                before any broad `except Exception`
  lockdep       the static lock-acquisition graph across the concurrency
                plane must stay acyclic (runtime twin: lint.lockdep,
                GTPU_LOCKDEP=1)
  blocking      no blocking syscall (sleep/fsync/socket/subprocess)
                while holding a lock — the group-commit pipeline's
                fsync-outside-the-region-lock contract, machine-checked
  escape        lambdas/closures built under a `with lock:` that read
                guarded state must not escape the guard into pools,
                queues, threads, or callbacks — the closure runs later
                without the lock the author visibly wrote
  datarace      attributes guarded by a lock in one method must not be
                accessed bare in another (caller-holds-lock docstring
                contracts and the _locked naming convention count as
                guarded)
  span_coverage every FAULTS-registered I/O seam and wire entry point
                executes inside a tracing span — untraced I/O is where
                production stalls hide from EXPLAIN ANALYZE
  deadcode      unused imports / unused module-level names / unreachable
                statements
  metrics       every registered metric is prefixed, documented, charted
                (folds tools/check_metrics.py in as a pass)
  options       options.py dataclasses <-> config/standalone.example.toml
                stay in sync, every scalar option is documented
  exemplars     serving-hot-path Histograms (query_/statement_/encode_/
                admission_) must declare exemplars=True so dashboard
                latency spikes pivot into concrete traces

Escape hatch: `lint_allow.toml` at the repo root — every entry names a
checker, a path glob, a match substring, and a one-line reason. Unused
entries are themselves findings, so the allowlist cannot rot.
"""

from __future__ import annotations

import ast
import fnmatch
import os
from dataclasses import dataclass, field
from typing import Callable, Iterable, Optional

from greptimedb_tpu.options import tomllib

#: directories under the repo root that the source-level checkers walk
SOURCE_ROOTS = ("greptimedb_tpu", "tools")


@dataclass
class Finding:
    checker: str
    path: str          # repo-relative, forward slashes
    line: int
    message: str
    allowed: bool = False
    allow_reason: str = ""

    def render(self) -> str:
        tag = f" [allowed: {self.allow_reason}]" if self.allowed else ""
        return f"{self.path}:{self.line}: [{self.checker}] {self.message}{tag}"

    def as_json(self) -> dict:
        return {"checker": self.checker, "path": self.path,
                "line": self.line, "message": self.message,
                "allowed": self.allowed, "allow_reason": self.allow_reason}


@dataclass
class SourceFile:
    """One parsed module. Checkers never re-read or re-parse; tests feed
    synthetic instances via `Repo(files=[...])` to exercise a checker on
    a fixture snippet without touching disk."""

    path: str          # repo-relative, forward slashes
    text: str
    tree: ast.Module

    @classmethod
    def from_text(cls, path: str, text: str) -> "SourceFile":
        return cls(path=path, text=text, tree=ast.parse(text))

    @property
    def module(self) -> str:
        """Dotted module name ('' for non-package files)."""
        p = self.path[:-3] if self.path.endswith(".py") else self.path
        parts = p.split("/")
        if parts[-1] == "__init__":
            parts = parts[:-1]
        return ".".join(parts)


@dataclass
class Repo:
    root: str = ""
    files: list = field(default_factory=list)

    def by_path(self, path: str) -> Optional[SourceFile]:
        for f in self.files:
            if f.path == path:
                return f
        return None

    def modules(self) -> dict:
        return {f.module: f for f in self.files if f.module}


def repo_root() -> str:
    here = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    return os.path.dirname(here)


def load_repo(root: Optional[str] = None) -> Repo:
    """Parse every repo source file once, shared by all checkers.

    Always the full file set — the import-graph and lock-graph
    checkers need it to stay sound; --changed-only restriction applies
    at *reporting* time (run_checkers' changed_only), not here.
    """
    root = root or repo_root()
    files = []
    for src_root in SOURCE_ROOTS:
        base = os.path.join(root, src_root)
        for dirpath, dirnames, filenames in os.walk(base):
            dirnames[:] = [d for d in dirnames if d != "__pycache__"]
            for name in sorted(filenames):
                if not name.endswith(".py"):
                    continue
                full = os.path.join(dirpath, name)
                rel = os.path.relpath(full, root).replace(os.sep, "/")
                with open(full, encoding="utf-8") as f:
                    text = f.read()
                try:
                    tree = ast.parse(text)
                except SyntaxError as e:  # a broken file is itself a finding
                    tree = ast.Module(body=[], type_ignores=[])
                    files.append(SourceFile(rel, text, tree))
                    files[-1]._syntax_error = e  # type: ignore[attr-defined]
                    continue
                files.append(SourceFile(rel, text, tree))
    return Repo(root=root, files=files)


# ---- allowlist --------------------------------------------------------------


@dataclass
class AllowEntry:
    checker: str
    path: str            # fnmatch glob over the repo-relative path
    match: str           # substring of the finding message ('' = any)
    reason: str
    used: int = 0


def load_allowlist(root: str) -> list:
    path = os.path.join(root, "lint_allow.toml")
    if not os.path.exists(path):
        return []
    with open(path, "rb") as f:
        data = tomllib.load(f)
    entries = []
    for i, raw in enumerate(data.get("allow", [])):
        reason = (raw.get("reason") or "").strip()
        if not reason:
            raise ValueError(
                f"lint_allow.toml entry {i}: every allow entry needs a "
                "non-empty 'reason' (that's the point of the allowlist)")
        entries.append(AllowEntry(
            checker=raw.get("checker", "*"),
            path=raw.get("path", "*"),
            match=raw.get("match", ""),
            reason=reason))
    return entries


def apply_allowlist(findings: Iterable[Finding],
                    entries: list) -> list:
    out = []
    for f in findings:
        for e in entries:
            if e.checker not in ("*", f.checker):
                continue
            if not fnmatch.fnmatch(f.path, e.path):
                continue
            if e.match and e.match not in f.message:
                continue
            f.allowed = True
            f.allow_reason = e.reason
            e.used += 1
            break
        out.append(f)
    return out


# ---- registry ---------------------------------------------------------------

#: name -> callable(Repo) -> list[Finding]; populated by the checker
#: modules at import time via @checker
CHECKERS: dict = {}


def checker(name: str) -> Callable:
    def register(fn):
        CHECKERS[name] = fn
        fn.checker_name = name
        return fn
    return register


def _import_checkers() -> None:
    # imported lazily so `from greptimedb_tpu.lint import lockdep` (the
    # runtime validator, installed at interpreter start under
    # GTPU_LOCKDEP=1) doesn't pay for the static-analysis modules
    from greptimedb_tpu.lint import (  # noqa: F401
        blocking,
        datarace,
        deadcode,
        deadline,
        escape,
        fault_seam,
        jax_imports,
        lockgraph,
        metrics_options,
        span_coverage,
        tracer,
        typed_errors,
    )


def run_checkers(repo: Optional[Repo] = None,
                 names: Optional[Iterable[str]] = None,
                 changed_only: Optional[set] = None) -> list:
    """Run the selected checkers (default: all) and apply the allowlist.

    Returns every finding, allowed ones flagged. When `changed_only`
    (a set of repo-relative paths) is given, whole-repo checkers still
    analyze everything — soundness needs the full import/lock graphs —
    but findings outside the changed set are dropped, and the
    unused-allowlist audit is skipped (entries for unchanged files
    legitimately go unused)."""
    _import_checkers()
    repo = repo or load_repo()
    selected = list(names) if names else sorted(CHECKERS)
    unknown = [n for n in selected if n not in CHECKERS]
    if unknown:
        raise ValueError(f"unknown checker(s): {', '.join(unknown)} "
                         f"(have: {', '.join(sorted(CHECKERS))})")
    findings: list = []
    for f in repo.files:
        err = getattr(f, "_syntax_error", None)
        if err is not None:
            findings.append(Finding("parse", f.path, err.lineno or 1,
                                    f"syntax error: {err.msg}"))
    for name in selected:
        findings.extend(CHECKERS[name](repo))
    entries = load_allowlist(repo.root) if repo.root else []
    findings = apply_allowlist(findings, entries)
    if changed_only is not None:
        findings = [f for f in findings if f.path in changed_only]
    elif repo.root and not names:
        # full run: a stale allowlist entry is itself a finding
        for e in entries:
            if e.used == 0:
                findings.append(Finding(
                    "allowlist", "lint_allow.toml", 1,
                    f"unused allow entry (checker={e.checker!r} "
                    f"path={e.path!r} match={e.match!r}): remove it or "
                    "fix the pattern"))
    findings.sort(key=lambda f: (f.path, f.line, f.checker))
    return findings
