"""Small shared AST helpers for the gtpu-lint checkers."""

from __future__ import annotations

import ast
from typing import Iterator, Optional


def dotted(node: ast.AST) -> Optional[str]:
    """`a.b.c` / `a` -> 'a.b.c' / 'a'; anything else -> None."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def call_name(call: ast.Call) -> Optional[str]:
    return dotted(call.func)


def walk_functions(tree: ast.AST) -> Iterator[ast.AST]:
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node


def iter_calls(node: ast.AST) -> Iterator[ast.Call]:
    for n in ast.walk(node):
        if isinstance(n, ast.Call):
            yield n


def toplevel_statements(tree: ast.Module) -> Iterator[ast.stmt]:
    """Module statements including those nested in top-level `if`/`try`
    bodies (a guarded import is still executed at import time — only
    function/class bodies are lazy)."""
    stack = list(tree.body)
    while stack:
        stmt = stack.pop(0)
        yield stmt
        if isinstance(stmt, (ast.If, ast.Try)):
            for blk in ([stmt.body, stmt.orelse]
                        + ([h.body for h in stmt.handlers]
                           + [stmt.finalbody]
                           if isinstance(stmt, ast.Try) else [])):
                stack = list(blk) + stack
        elif isinstance(stmt, (ast.With, ast.For, ast.While)):
            stack = list(stmt.body) + list(
                getattr(stmt, "orelse", [])) + stack


def toplevel_imports(tree: ast.Module) -> Iterator[ast.stmt]:
    for stmt in toplevel_statements(tree):
        if isinstance(stmt, (ast.Import, ast.ImportFrom)):
            yield stmt


def names_loaded(node: ast.AST) -> set:
    """Every bare Name read anywhere under `node` (attribute roots
    included: `a.b` contributes 'a')."""
    out = set()
    for n in ast.walk(node):
        if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load):
            out.add(n.id)
    return out


def decorator_names(fn) -> list:
    out = []
    for dec in fn.decorator_list:
        target = dec.func if isinstance(dec, ast.Call) else dec
        name = dotted(target)
        if name:
            out.append(name)
        # functools.partial(jax.jit, ...) style: the wrapped callable is
        # the first positional arg
        if isinstance(dec, ast.Call) and name and \
                name.split(".")[-1] == "partial" and dec.args:
            inner = dotted(dec.args[0])
            if inner:
                out.append(inner)
    return out


def find_cycle(graph: dict) -> Optional[list]:
    """First cycle in a {node: iterable-of-successors} graph as
    [n0, n1, ..., n0], or None. Shared by the static lock-graph checker
    and the runtime lockdep validator — one tricolor DFS, deterministic
    (sorted) visit order."""
    color: dict = {}
    stack: list = []

    def dfs(n):
        color[n] = 1
        stack.append(n)
        for m in sorted(graph.get(n, ())):
            if color.get(m) == 1:
                return stack[stack.index(m):] + [m]
            if color.get(m, 0) == 0:
                cyc = dfs(m)
                if cyc:
                    return cyc
        stack.pop()
        color[n] = 2
        return None

    nodes = set(graph) | {m for s in graph.values() for m in s}
    for n in sorted(nodes):
        if color.get(n, 0) == 0:
            cyc = dfs(n)
            if cyc:
                return cyc
    return None


def enclosing_function(tree: ast.AST, node: ast.AST) -> str:
    """Name of the innermost function containing `node` ('<module>' at
    top level) — gives findings a stable, line-number-free anchor that
    allowlist entries can match on."""
    best = "<module>"
    best_span = None
    for fn in ast.walk(tree):
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        end = getattr(fn, "end_lineno", None)
        if end is None:
            continue
        if fn.lineno <= node.lineno <= end:
            span = end - fn.lineno
            if best_span is None or span < best_span:
                best, best_span = fn.name, span
    return best


def has_noqa(text_lines: list, lineno: int, code: str = "") -> bool:
    """True when the physical line carries `# noqa` (optionally scoped
    to a code, e.g. F401) — the repo's existing suppression idiom for
    re-export imports."""
    if not (1 <= lineno <= len(text_lines)):
        return False
    line = text_lines[lineno - 1]
    if "# noqa" not in line:
        return False
    if not code:
        return True
    tail = line.split("# noqa", 1)[1]
    return ":" not in tail.split("#")[0] or code in tail
