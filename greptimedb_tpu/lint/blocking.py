"""blocking: no blocking syscall while holding a lock.

The group-commit ingest pipeline's core contract is that the WAL fsync
runs OUTSIDE the region lock (an fsync under it would stall every
reader and every other writer of the region for the disk's latency —
exactly the cliff the pipeline removes). The same argument covers any
lock in the concurrency/maintenance/storage planes: a blocking syscall
(sleep, fsync, socket I/O, subprocess wait) inside a `with lock:` block
turns one slow disk or peer into a plane-wide stall.

This checker reuses the lockdep model (lock identities, constructor- and
annotation-inferred attribute types, call resolution) and flags a call
that reaches a blocking primitive — directly or through resolvable
calls, transitively — while a lock is lexically held. Condition.wait is
NOT blocking here: it releases the lock it rides on.

Escape hatch: lint_allow.toml, reason required (the legacy serial write
path deliberately keeps WAL append+fsync under one region-lock hold —
it is the bit-for-bit differential baseline, not the production path).
"""

from __future__ import annotations

import ast

from greptimedb_tpu.lint import Finding, Repo, checker
from greptimedb_tpu.lint.astutil import call_name
from greptimedb_tpu.lint.lockgraph import _Model

#: dotted call names that park the thread on the kernel
BLOCKING_CALLS = frozenset({
    "time.sleep",
    "os.fsync", "os.fdatasync",
    "socket.create_connection", "socket.socket",
    "urlopen", "urllib.request.urlopen",
    "subprocess.run", "subprocess.check_output",
    "subprocess.check_call", "subprocess.call",
})
BLOCKING_PREFIXES = ("socket.",)


def _direct_blocking(call: ast.Call) -> str:
    name = call_name(call) or ""
    if name in BLOCKING_CALLS or name.startswith(BLOCKING_PREFIXES):
        return name
    return ""


def _blocking_sets(model: _Model) -> dict:
    """fid -> set of blocking primitive names it may reach,
    transitively (same fixpoint shape as lockgraph's acquire sets)."""
    direct: dict = {}
    calls: dict = {}
    for fid, (f, cls, fn) in model.functions.items():
        mod = fid.split(":")[0]
        prims, callees = set(), set()
        for node in ast.walk(fn):
            if isinstance(node, ast.Call):
                prim = _direct_blocking(node)
                if prim:
                    prims.add(prim)
                callee = model.callee_of(node, mod, cls)
                if callee:
                    callees.add(callee)
        direct[fid] = prims
        calls[fid] = callees
    blocking = {fid: set(s) for fid, s in direct.items()}
    changed = True
    while changed:
        changed = False
        for fid, callees in calls.items():
            for callee in callees:
                extra = blocking.get(callee, set()) - blocking[fid]
                if extra:
                    blocking[fid] |= extra
                    changed = True
    return blocking


@checker("blocking")
def check(repo: Repo) -> list:
    model = _Model(repo)
    blocking = _blocking_sets(model)
    findings: list = []

    for fid, (f, cls, fn) in model.functions.items():
        mod = fid.split(":")[0]

        def visit(node, held):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)) and node is not fn:
                return  # nested defs are analyzed as their own entries
            if isinstance(node, ast.With):
                got = []
                for item in node.items:
                    visit(item.context_expr, held)
                    lock = model.lock_of(item.context_expr, mod, cls)
                    if lock:
                        got.append(lock)
                for stmt in node.body:
                    visit(stmt, held + got)
                return
            if isinstance(node, ast.Call) and held:
                prim = _direct_blocking(node)
                why = ""
                if prim:
                    why = prim
                else:
                    callee = model.callee_of(node, mod, cls)
                    if callee:
                        prims = blocking.get(callee, ())
                        if prims:
                            why = (f"{callee} -> "
                                   f"{'/'.join(sorted(prims))}")
                if why:
                    findings.append(Finding(
                        "blocking", f.path, node.lineno,
                        f"blocking call ({why}) while holding "
                        f"{', '.join(held)} in {fid} — a slow "
                        "disk/peer stalls every thread behind the "
                        "lock"))
            for child in ast.iter_child_nodes(node):
                visit(child, held)

        visit(fn, [])
    return findings
