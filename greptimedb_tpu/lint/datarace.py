"""datarace: attributes guarded by a lock in one method, bare in another.

The lockdep checkers prove the lock GRAPH is sound; this pass checks
that locks actually COVER the state they exist for. The bug class: a
class takes `self._lock` around `self._bytes`/`self._lru` in its hot
methods, then a later method (a stats property, an invalidation seam, a
`clear()`) touches the same attributes with no lock at all — reads see
torn multi-field state, writes race the guarded mutators. The repo's
caches and schedulers (PR 4-13) all follow the guarded-attr pattern, so
a bare access in new code is almost always an oversight, not a design.

Model (reuses lockgraph's scope + lock identities):

- an attribute access is GUARDED when it happens lexically inside a
  `with <lock>:` block (any lock the lockgraph model resolves), or in a
  method whose name ends with `_locked` (the repo's caller-holds-the-
  lock convention), or in a method a `_locked`-suffixed docstring
  contract marks ("caller holds");
- `__init__`/`__post_init__`/`__enter__`/`__exit__`/`__del__` don't
  count either way (construction and teardown happen-before/after
  sharing);
- an attribute is a FINDING when, within one class, it has at least one
  guarded access, at least one bare access in a DIFFERENT method, and
  at least one write outside construction (an attribute never written
  after __init__ is immutable config — reads need no lock).

One finding per (class, attribute), anchored at a representative bare
access. Deliberate unguarded fast paths (monotonic counters read for
stats, benign flag probes) go in lint_allow.toml with a reason.
"""

from __future__ import annotations

import ast

from greptimedb_tpu.lint import Finding, Repo, checker
from greptimedb_tpu.lint.lockgraph import _Model, _in_scope

#: methods whose accesses carry no concurrency (construction/teardown
#: happens-before or -after any sharing)
_EXEMPT_METHODS = frozenset({
    "__init__", "__post_init__", "__new__", "__del__",
    "__enter__", "__exit__",
})


def _caller_holds_lock(fn: ast.FunctionDef) -> bool:
    """The repo convention for lock-transfer methods: a `_locked` name
    suffix, or a docstring stating the caller holds the lock."""
    if fn.name.endswith("_locked"):
        return True
    doc = ast.get_docstring(fn) or ""
    low = doc.lower()
    return "caller holds" in low or "holds the lock" in low \
        or "under the lock" in low or "holding the lock" in low \
        or "holds self._lock" in low


class _AttrAccesses(ast.NodeVisitor):
    """Per-method walk: self.<attr> accesses partitioned by whether a
    lock is lexically held, plus the write set."""

    def __init__(self, model: _Model, mod: str, cls, fn):
        self.model = model
        self.mod = mod
        self.cls = cls
        self.fn = fn
        self.held = 0
        self.guarded: set = set()
        self.bare: dict = {}     # attr -> first bare (line)
        self.writes: set = set()
        self.always_held = _caller_holds_lock(fn)

    def visit_With(self, node: ast.With):
        got = 0
        for item in node.items:
            self.visit(item.context_expr)
            if self.model.lock_of(item.context_expr, self.mod, self.cls):
                got += 1
        self.held += got
        for stmt in node.body:
            self.visit(stmt)
        self.held -= got

    def visit_FunctionDef(self, node):
        if node is self.fn:
            self.generic_visit(node)
        # nested defs analyzed as their own entries by the caller loop

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Lambda(self, node):
        # a lambda capturing self runs at an unknowable time — its
        # accesses would need escape analysis; skip (conservative for
        # false positives, not false negatives we care about here)
        return

    def visit_Attribute(self, node: ast.Attribute):
        if isinstance(node.value, ast.Name) and node.value.id == "self":
            attr = node.attr
            lock_id = f"{self.mod}.{self.cls.name}.{attr}" \
                if self.cls is not None else None
            is_lock = lock_id in self.model.locks
            is_method = self.cls is not None and \
                f"{self.mod}:{self.cls.name}.{attr}" in self.model.functions
            if not is_lock and not is_method:
                if self.held or self.always_held:
                    self.guarded.add(attr)
                else:
                    self.bare.setdefault(attr, node.lineno)
                if isinstance(node.ctx, (ast.Store, ast.Del)):
                    self.writes.add(attr)
        self.generic_visit(node)


@checker("datarace")
def check(repo: Repo) -> list:
    model = _Model(repo)
    findings: list = []

    # group methods per (mod, class)
    classes: dict = {}
    for fid, (f, cls, fn) in model.functions.items():
        if cls is None or not _in_scope(f.path):
            continue
        mod = fid.split(":")[0]
        classes.setdefault((mod, cls.name), []).append((f, cls, fn))

    for (mod, cname), methods in sorted(classes.items()):
        # skip classes that own no lock at all — nothing to be bare OF
        has_lock = any(lid.startswith(f"{mod}.{cname}.")
                       for lid in model.locks)
        if not has_lock:
            continue
        guarded_in: dict = {}   # attr -> set of method names
        bare_in: dict = {}      # attr -> [(method, file, line)]
        written: set = set()
        init_only_writes: set = set()
        for f, cls, fn in methods:
            v = _AttrAccesses(model, mod, cls, fn)
            v.visit(fn)
            if fn.name in _EXEMPT_METHODS:
                init_only_writes |= v.writes
                continue
            for a in v.guarded:
                guarded_in.setdefault(a, set()).add(fn.name)
            for a, line in v.bare.items():
                bare_in.setdefault(a, []).append((fn.name, f, line))
            written |= v.writes
        for attr in sorted(set(guarded_in) & set(bare_in)):
            if attr not in written:
                continue  # immutable after construction: reads are safe
            others = [(m, f, ln) for m, f, ln in bare_in[attr]
                      if m not in guarded_in[attr]]
            if not others:
                # only the guarded methods themselves also touch it bare
                # (pre-lock probe / double-checked pattern) — a
                # different, deliberate idiom; not this checker's bug
                continue
            m, f, line = others[0]
            findings.append(Finding(
                "datarace", f.path, line,
                f"{cname}.{attr} is accessed under a lock in "
                f"{'/'.join(sorted(guarded_in[attr]))} but bare in "
                f"{m} — guard it (or allowlist with the reason the "
                "bare access is benign)"))
    return findings
