"""deadcode: unused imports, unused module-level names, unreachable
statements.

Kept deliberately conservative — a lint that cries wolf gets turned
off. A module-level name only counts as dead when nothing in its own
module loads it AND its bare identifier appears nowhere else in the
repo (so re-exports, cross-module constants, and `mod.NAME` accesses
all keep a name alive). The repo's existing `# noqa` convention on
re-export imports is honored.
"""

from __future__ import annotations

import ast

from greptimedb_tpu.lint import Finding, Repo, checker
from greptimedb_tpu.lint.astutil import has_noqa, names_loaded


def _bound_names(stmt) -> list:
    """(name, lineno) pairs an import statement binds."""
    out = []
    for alias in stmt.names:
        if alias.name == "*":
            continue
        if alias.asname:
            out.append((alias.asname, stmt.lineno))
        elif isinstance(stmt, ast.Import):
            out.append((alias.name.split(".")[0], stmt.lineno))
        else:
            out.append((alias.name, stmt.lineno))
    return out


def _all_exports(tree: ast.Module) -> set:
    for node in tree.body:
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Name) and t.id == "__all__":
                    if isinstance(node.value, (ast.List, ast.Tuple)):
                        return {c.value for c in node.value.elts
                                if isinstance(c, ast.Constant)}
    return set()


def _global_identifiers(repo: Repo) -> set:
    """Every identifier loaded, attribute-accessed, or imported-from
    anywhere in the repo — the cross-module liveness set."""
    out = set()
    for f in repo.files:
        for node in ast.walk(f.tree):
            if isinstance(node, ast.Name) and isinstance(node.ctx,
                                                         ast.Load):
                out.add(node.id)
            elif isinstance(node, ast.Attribute):
                out.add(node.attr)
            elif isinstance(node, ast.ImportFrom):
                for alias in node.names:
                    out.add(alias.name)
            elif isinstance(node, ast.Constant) and \
                    isinstance(node.value, str) and \
                    node.value.isidentifier():
                out.add(node.value)  # getattr()/dispatch-by-string uses
    return out


@checker("deadcode")
def check(repo: Repo) -> list:
    findings = []
    global_ids = _global_identifiers(repo)
    for f in repo.files:
        lines = f.text.splitlines()
        loaded = names_loaded(f.tree)
        exports = _all_exports(f.tree)
        # --- unused top-level imports
        for stmt in f.tree.body:
            if not isinstance(stmt, (ast.Import, ast.ImportFrom)):
                continue
            if isinstance(stmt, ast.ImportFrom) and \
                    stmt.module == "__future__":
                continue
            for name, lineno in _bound_names(stmt):
                if name in loaded or name in exports:
                    continue
                if has_noqa(lines, lineno):
                    continue
                if f.path.endswith("__init__.py"):
                    continue  # package inits re-export by convention
                findings.append(Finding(
                    "deadcode", f.path, lineno,
                    f"unused import {name!r}"))
        # --- unused module-level assignments
        for stmt in f.tree.body:
            if not isinstance(stmt, (ast.Assign, ast.AnnAssign)):
                continue
            targets = stmt.targets if isinstance(stmt, ast.Assign) \
                else [stmt.target]
            for t in targets:
                if not isinstance(t, ast.Name):
                    continue
                name = t.id
                if name.startswith("__") or name in exports:
                    continue
                if name in global_ids or has_noqa(lines, t.lineno):
                    continue  # loaded somewhere (this module included)
                findings.append(Finding(
                    "deadcode", f.path, t.lineno,
                    f"module-level name {name!r} is never used "
                    "(here or anywhere in the repo)"))
        # --- unreachable statements after a terminator
        for node in ast.walk(f.tree):
            body_blocks = []
            for attr in ("body", "orelse", "finalbody"):
                blk = getattr(node, attr, None)
                if isinstance(blk, list):
                    body_blocks.append(blk)
            for blk in body_blocks:
                for i, stmt in enumerate(blk[:-1]):
                    if isinstance(stmt, (ast.Return, ast.Raise,
                                         ast.Break, ast.Continue)):
                        nxt = blk[i + 1]
                        findings.append(Finding(
                            "deadcode", f.path, nxt.lineno,
                            "unreachable statement after "
                            f"{type(stmt).__name__.lower()} on line "
                            f"{stmt.lineno}"))
                        break
    return findings
