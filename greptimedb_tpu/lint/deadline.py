"""deadline: no unbounded blocking wait in serving scope.

The deadline plane (utils/deadline.py) only works if every wait a
query can park on eventually re-checks its CancelToken: an
`Event.wait()` with no timeout, a `Future.result()` with no bound, or
a bare `Queue.get()` is a hole the deadline cannot reach — a wedged
peer turns a 500 ms budget into a forever-hang and the typed
`DeadlineExceeded` contract silently becomes "hangs sometimes".

This checker flags unbounded waits in SERVING-scope files (the planes
a query or ingest request executes through). Flagged shapes:

  * ``x.wait()`` with no timeout — Event/Condition/Popen wait forever
  * ``x.result()`` with no timeout — Future.result parks the thread
  * ``x.get()`` / ``x.get(True)`` with no timeout — a blocking
    Queue.get (dict.get always passes a key, so it never matches)
  * ``x.join()`` with no timeout on thread-ish receivers
  * ``x.recv*()`` — socket reads, which bound only via settimeout the
    static check cannot see (allowlist with the reason stating where
    the timeout is configured)
  * ``x.acquire()`` with neither timeout nor ``blocking=False`` on
    non-lock receivers (semaphores; `with lock:` holds are lockgraph's
    domain, and plain mutex holds are expected to be short)

A timeout argument (positional or keyword) clears the finding — the
wait re-enters code that can call `deadline.check()`; waits routed
through `deadline.sleep/wait_event` never match (they are functions,
not methods, and poll the token by construction). Escape hatch:
lint_allow.toml, reason required.
"""

from __future__ import annotations

import ast

from greptimedb_tpu.lint import Finding, Repo, checker
from greptimedb_tpu.lint.astutil import call_name

#: path prefixes a query/ingest request executes through — the scope
#: where an unbounded wait is a deadline hole rather than an offline
#: tool parking deliberately
SERVING_PREFIXES = (
    "greptimedb_tpu/servers/",
    "greptimedb_tpu/query/",
    "greptimedb_tpu/concurrency/",
    "greptimedb_tpu/storage/",
    "greptimedb_tpu/cluster/",
    "greptimedb_tpu/flow/",
    "greptimedb_tpu/fault/",
    "greptimedb_tpu/utils/deadline.py",
    "greptimedb_tpu/ingest.py",
    "greptimedb_tpu/shm/",
)

#: method names whose zero-timeout call parks the thread
_WAIT_METHODS = frozenset({"wait", "result", "join"})
_RECV_METHODS = frozenset({"recv", "recvfrom", "recv_into",
                           "recvmsg", "readline"})

#: receivers whose .join/.readline are string/path ops, never waits
_STRING_RECEIVERS = frozenset({"str", "sep", "os.sep", "os.path.sep",
                               '", "', "', '"})


def _has_timeout(call: ast.Call) -> bool:
    if call.args:
        # Event.wait(5) / Future.result(5) / Condition.wait(0.05):
        # the first positional IS the timeout for the wait family;
        # Queue.get(True) (block flag) is handled by the caller
        return True
    return any(kw.arg == "timeout" for kw in call.keywords)


def _is_blocking_get(call: ast.Call) -> bool:
    """Bare ``q.get()`` / ``q.get(True)``: a Queue.get that blocks
    without bound. ``d.get(key)`` / ``q.get(timeout=...)`` pass."""
    if any(kw.arg == "timeout" for kw in call.keywords):
        return False
    if not call.args:
        return not call.keywords
    return (len(call.args) == 1
            and isinstance(call.args[0], ast.Constant)
            and call.args[0].value is True)


def _is_unbounded_acquire(call: ast.Call) -> bool:
    if any(kw.arg == "timeout" for kw in call.keywords):
        return False
    for kw in call.keywords:
        if kw.arg == "blocking" and isinstance(kw.value, ast.Constant) \
                and kw.value.value is False:
            return False
    if call.args and isinstance(call.args[0], ast.Constant) \
            and call.args[0].value is False:
        return False  # acquire(False): non-blocking try-lock
    return True


def _receiver(name: str) -> str:
    return name.rsplit(".", 1)[0] if "." in name else ""


@checker("deadline")
def check(repo: Repo) -> list:
    findings: list = []
    for f in repo.files:
        if not f.path.startswith(SERVING_PREFIXES):
            continue
        for node in ast.walk(f.tree):
            if not isinstance(node, ast.Call) \
                    or not isinstance(node.func, ast.Attribute):
                continue
            name = call_name(node) or ""
            meth = node.func.attr
            recv = _receiver(name)
            why = ""
            if meth in _WAIT_METHODS:
                if recv in _STRING_RECEIVERS:
                    continue
                if not _has_timeout(node):
                    why = (f"unbounded {meth}() — the deadline plane "
                           "cannot reach a wait that never wakes; pass "
                           "a timeout (and re-check the token) or use "
                           "deadline.wait_event")
            elif meth == "get" and _is_blocking_get(node):
                # only flag receivers that look like queues: dict.get
                # with a key never reaches here, but ({}).get() would —
                # demand a queue-ish receiver name to keep noise at zero
                if "queue" in recv.lower() or recv.lower().endswith("q"):
                    why = ("blocking Queue.get() with no timeout — a "
                           "dead producer parks this thread forever; "
                           "pass timeout= and loop on deadline.check()")
            elif meth in _RECV_METHODS and recv not in _STRING_RECEIVERS:
                why = (f"socket {meth}() — per-call reads bound only "
                       "via settimeout(); allowlist with the reason "
                       "naming where the timeout is configured")
            elif meth == "acquire" and _is_unbounded_acquire(node):
                # lock holds are lockgraph's domain; flag only
                # semaphore-ish receivers (slot/limiter waits a query
                # can park on)
                if "sem" in recv.lower() or "slot" in recv.lower() \
                        or "limiter" in recv.lower():
                    why = ("unbounded semaphore acquire() — a leaked "
                           "slot parks every later query; pass "
                           "timeout= and re-check the deadline token")
            if why:
                findings.append(Finding("deadline", f.path, node.lineno,
                                        f"{why} (call: {name})"))
    return findings
