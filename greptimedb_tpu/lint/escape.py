"""escape: no closure over lock-guarded state may outlive the guard.

The safe deferred-work idiom in this codebase is a *bound method*
handed to a pool — `self._prefetch_pool.submit(self._build_prefetched,
key, build, epoch)` — because the method is an entry point that takes
the lock again before touching shared state. The unsafe twin looks
almost identical: a lambda or nested def built INSIDE a `with lock:`
block that reads `self.<attr>` in its body and is handed to a pool,
queue, thread, or done-callback. The closure evaluates those reads
*later*, on another thread, after the `with` has exited — the guard
the author visibly wrote protects only the submission, not the work.
That is a data race with a lock right next to it, the hardest kind to
see in review.

This checker reuses the lockdep model (lock identities, constructor-
and annotation-inferred attribute types) and flags an escape sink call
made while a lock is lexically held whose callable payload is a
lambda / nested def / functools.partial-wrapped lambda that loads
guarded (`self.*`) state. Bound-method payloads and pre-evaluated
arguments (`pool.submit(work, list(self._q))` — the snapshot is taken
under the lock, now) stay quiet: they are the contract, not the bug.

Escape hatch: lint_allow.toml, reason required.
"""

from __future__ import annotations

import ast

from greptimedb_tpu.lint import Finding, Repo, checker
from greptimedb_tpu.lint.astutil import call_name
from greptimedb_tpu.lint.lockgraph import _Model

#: method names (last dotted component) that hand a callable to another
#: thread / a later time: executor pools, queues of work items, thread
#: and timer constructors, future callbacks, scheduler hooks
SINK_METHODS = frozenset({
    "submit", "put", "put_nowait", "apply_async", "add_done_callback",
    "call_soon", "call_soon_threadsafe", "call_later", "schedule",
    "defer", "enqueue",
})
#: full dotted names that spawn a thread around their target= payload
THREAD_CTORS = frozenset({
    "threading.Thread", "threading.Timer", "Thread", "Timer",
})


def _is_sink(call: ast.Call) -> str:
    name = call_name(call) or ""
    if name in THREAD_CTORS:
        return name
    last = name.rsplit(".", 1)[-1]
    if last in SINK_METHODS:
        return name
    return ""


def _guarded_loads(body: ast.AST) -> list:
    """`self.<attr>` reads inside a payload body — state the enclosing
    lock guards, re-read later without it. Writes count too (an unlocked
    `self.x = ...` from a worker thread is the same race)."""
    out = []
    for node in ast.walk(body):
        if isinstance(node, ast.Attribute) and \
                isinstance(node.value, ast.Name) and \
                node.value.id == "self":
            out.append(f"self.{node.attr}")
    return sorted(set(out))


def _nested_defs(fn: ast.AST) -> dict:
    """name -> def node for functions nested (at any depth) in `fn`."""
    out = {}
    for node in ast.walk(fn):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                and node is not fn:
            out[node.name] = node
    return out


def _payload_captures(expr: ast.expr, nested: dict) -> tuple:
    """(kind, guarded-loads) when `expr` is a closure payload that
    captures guarded state; ('', []) otherwise.

    - lambda: its own body
    - bare Name resolving to a nested def: that def's body
    - functools.partial(...): recurse into every argument — but a
      partial over a bound method (`partial(self._m, x)`) is the safe
      idiom, same as the bare bound method, and stays quiet
    """
    if isinstance(expr, ast.Lambda):
        loads = _guarded_loads(expr.body)
        return ("lambda", loads) if loads else ("", [])
    if isinstance(expr, ast.Name) and expr.id in nested:
        loads = _guarded_loads(nested[expr.id])
        return (f"closure {expr.id}()", loads) if loads else ("", [])
    if isinstance(expr, ast.Call):
        name = call_name(expr) or ""
        if name.rsplit(".", 1)[-1] == "partial":
            for sub in list(expr.args) + [k.value for k in expr.keywords]:
                kind, loads = _payload_captures(sub, nested)
                if kind:
                    return (f"partial({kind})", loads)
    return ("", [])


def _sink_payloads(call: ast.Call):
    """Candidate callable positions of a sink call: every positional
    arg plus the target=/func=/fn=/callback= keywords (Thread(target=),
    Timer(..., function=), loop.call_later(delay, cb))."""
    for a in call.args:
        yield a
    for kw in call.keywords:
        if kw.arg in ("target", "function", "func", "fn", "callback",
                      "item", "task"):
            yield kw.value


@checker("escape")
def check(repo: Repo) -> list:
    model = _Model(repo)
    findings: list = []

    for fid, (f, cls, fn) in model.functions.items():
        mod = fid.split(":")[0]
        nested = _nested_defs(fn)

        def visit(node, held):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)) and node is not fn:
                return  # nested defs are analyzed as their own entries
            if isinstance(node, ast.With):
                got = []
                for item in node.items:
                    visit(item.context_expr, held)
                    lock = model.lock_of(item.context_expr, mod, cls)
                    if lock:
                        got.append(lock)
                for stmt in node.body:
                    visit(stmt, held + got)
                return
            if isinstance(node, ast.Call) and held:
                sink = _is_sink(node)
                if sink:
                    for payload in _sink_payloads(node):
                        kind, loads = _payload_captures(payload, nested)
                        if kind:
                            findings.append(Finding(
                                "escape", f.path, node.lineno,
                                f"{kind} capturing lock-guarded state "
                                f"({', '.join(loads)}) escapes "
                                f"{', '.join(held)} into {sink}() in "
                                f"{fid} — it runs later without the "
                                "guard; hand over a bound method (which "
                                "re-locks) or snapshot the state into "
                                "plain arguments"))
            for child in ast.iter_child_nodes(node):
                visit(child, held)

        visit(fn, [])
    return findings
