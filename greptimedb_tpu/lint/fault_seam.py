"""fault-seam: direct I/O in the storage plane must route through the
FaultRegistry seams.

PR 1/3 threaded `FAULTS.fire`/`FAULTS.mangle*` through every I/O edge
(objectstore.read/write, wal.append/replay, flight, heartbeat,
metasrv.kv) so chaos schedules exercise every failure the reference
survives. The invariant: a module in `storage/`, `objectstore/`, or
`cluster/` performing raw file/socket I/O is either (a) a seam
implementation — it fires the registry itself, or its class subclasses
a base defined in a seam module (the object-store backends implement
`_read_impl`/`_write_impl` behind the FAULTS-wrapping `ObjStore`
base) — or (b) bypassing chaos coverage: a fault schedule armed at the
matching point would never fire on that path. (b) is a finding.
"""

from __future__ import annotations

import ast

from greptimedb_tpu.lint import Finding, Repo, checker
from greptimedb_tpu.lint.astutil import (
    call_name,
    enclosing_function,
    iter_calls,
)

SCOPE_PREFIXES = (
    "greptimedb_tpu/storage/",
    "greptimedb_tpu/objectstore/",
    "greptimedb_tpu/cluster/",
)

#: raw-I/O entry points whose use bypasses the registry
IO_CALLS = frozenset({
    "open",
    "os.replace", "os.rename", "os.remove", "os.unlink", "os.truncate",
    "urllib.request.urlopen",
    "socket.socket", "socket.create_connection",
    "shutil.copy", "shutil.copyfile", "shutil.move", "shutil.rmtree",
})


def _uses_faults(tree: ast.AST) -> bool:
    for node in ast.walk(tree):
        if isinstance(node, ast.Attribute) and \
                isinstance(node.value, ast.Name) and \
                node.value.id == "FAULTS":
            return True
    return False


def _seam_base_names(repo: Repo) -> set:
    """Class names defined in seam modules (modules that use FAULTS)
    inside the scope — subclassing one marks the subclass as a seam
    implementation (its raw I/O sits *behind* the registry wrapper)."""
    out = set()
    for f in repo.files:
        if not f.path.startswith(SCOPE_PREFIXES):
            continue
        if not _uses_faults(f.tree):
            continue
        for node in ast.walk(f.tree):
            if isinstance(node, ast.ClassDef):
                out.add(node.name)
    return out


@checker("fault-seam")
def check(repo: Repo) -> list:
    seam_bases = _seam_base_names(repo)
    findings = []
    for f in repo.files:
        if not f.path.startswith(SCOPE_PREFIXES):
            continue
        if _uses_faults(f.tree):
            continue  # seam implementation module
        exempt_classes = set()
        for node in ast.walk(f.tree):
            if isinstance(node, ast.ClassDef):
                bases = {b.id if isinstance(b, ast.Name) else
                         getattr(b, "attr", "") for b in node.bases}
                if bases & seam_bases:
                    exempt_classes.add(node)
        for call in iter_calls(f.tree):
            name = call_name(call)
            if name not in IO_CALLS:
                continue
            in_exempt = any(
                cls.lineno <= call.lineno <= max(
                    (n.lineno for n in ast.walk(cls)
                     if hasattr(n, "lineno")), default=cls.lineno)
                for cls in exempt_classes)
            if in_exempt:
                continue
            findings.append(Finding(
                "fault-seam", f.path, call.lineno,
                f"direct I/O call {name}() in "
                f"{enclosing_function(f.tree, call)}() bypasses the "
                "FaultRegistry seams — route it through the "
                "objectstore/WAL seam or fire the matching FAULTS "
                "point"))
    return findings
