"""jax-import: storage-only processes must not (non-lazily) import jax.

PR 7 introduced the `sys.modules` seam so `storage/region.py` can
notify the device hot set without ever importing the query layer: a
pure-storage datanode child must never pay jax's import cost (or touch
an accelerator tunnel) for work that is all parquet and WAL bytes.

Two rules, both verified over the *top-level* import graph (imports
inside a function are lazy and fine — only module-body imports execute
at import time):

1. Discipline: modules under `storage/`, `objectstore/`, `fault/`,
   `wal` must not top-level import `jax` or a device-layer package
   (`ops`, `parallel`, `query`, `promql`, `flow`, `config`).
2. Reachability: walking the import graph from the storage-only entry
   (`cluster.datanode_main`, function-level imports included — the
   entry imports them unconditionally at runtime), every reachable
   module that top-level imports jax is a finding. The package
   bootstrap (`greptimedb_tpu/__init__.py`) is expected here and
   carries an allowlist entry explaining the platform pin.
"""

from __future__ import annotations

import ast

from greptimedb_tpu.lint import Finding, Repo, checker
from greptimedb_tpu.lint.astutil import toplevel_imports

STORAGE_ONLY_PREFIXES = (
    "greptimedb_tpu/storage/",
    "greptimedb_tpu/objectstore/",
    "greptimedb_tpu/fault/",
)

DEVICE_LAYERS = (
    "greptimedb_tpu.ops", "greptimedb_tpu.parallel",
    "greptimedb_tpu.query", "greptimedb_tpu.promql",
    "greptimedb_tpu.flow", "greptimedb_tpu.config",
)

ENTRY_MODULES = ("greptimedb_tpu.cluster.datanode_main",)


def _imported_modules(stmts) -> set:
    """Absolute module names a list of import statements pulls in."""
    out = set()
    for stmt in stmts:
        if isinstance(stmt, ast.Import):
            for alias in stmt.names:
                out.add(alias.name)
        elif isinstance(stmt, ast.ImportFrom) and stmt.level == 0:
            if stmt.module:
                out.add(stmt.module)
                # `from pkg.mod import name`: `name` may itself be a
                # submodule; walking into it is conservative and only
                # matters for package-internal edges
                for alias in stmt.names:
                    out.add(f"{stmt.module}.{alias.name}")
    return out


def _relative_modules(stmts, module: str) -> set:
    out = set()
    pkg_parts = module.split(".")
    for stmt in stmts:
        if isinstance(stmt, ast.ImportFrom) and stmt.level > 0:
            base = pkg_parts[:len(pkg_parts) - stmt.level + 1] \
                if stmt.level <= len(pkg_parts) else []
            prefix = ".".join(base)
            target = f"{prefix}.{stmt.module}" if stmt.module else prefix
            out.add(target)
            for alias in stmt.names:
                out.add(f"{target}.{alias.name}")
    return out


def build_import_graph(repo: Repo):
    """(edges, jax_importers): top-level import edges between repo
    modules (including implicit parent-package execution), and the set
    of modules whose module body imports jax."""
    modules = repo.modules()
    edges: dict = {}
    jax_importers = set()
    for mod, f in modules.items():
        stmts = list(toplevel_imports(f.tree))
        imported = _imported_modules(stmts) | _relative_modules(stmts, mod)
        targets = set()
        for name in imported:
            if name == "jax" or name.startswith("jax."):
                jax_importers.add(mod)
            # restrict graph edges to repo-internal modules; add the
            # implicit parent-package executions Python performs
            parts = name.split(".")
            for i in range(1, len(parts) + 1):
                prefix = ".".join(parts[:i])
                if prefix in modules and prefix != mod:
                    targets.add(prefix)
        # importing this module executes its own parent packages first
        parts = mod.split(".")
        for i in range(1, len(parts)):
            prefix = ".".join(parts[:i])
            if prefix in modules:
                targets.add(prefix)
        edges[mod] = targets
    return edges, jax_importers


def _entry_roots(repo: Repo, entry: str) -> set:
    """The entry's import closure seed: top-level AND function-level
    imports (the entry main() imports its deps unconditionally)."""
    f = repo.modules().get(entry)
    if f is None:
        return set()
    stmts = [n for n in ast.walk(f.tree)
             if isinstance(n, (ast.Import, ast.ImportFrom))]
    modules = repo.modules()
    roots = {entry}
    for name in _imported_modules(stmts) | _relative_modules(stmts, entry):
        parts = name.split(".")
        for i in range(1, len(parts) + 1):
            prefix = ".".join(parts[:i])
            if prefix in modules:
                roots.add(prefix)
    return roots


@checker("jax-import")
def check(repo: Repo) -> list:
    findings = []
    modules = repo.modules()
    edges, jax_importers = build_import_graph(repo)

    # rule 1: storage-plane modules keep jax + device layers lazy
    for mod, f in modules.items():
        if not f.path.startswith(STORAGE_ONLY_PREFIXES):
            continue
        for stmt in toplevel_imports(f.tree):
            imported = _imported_modules([stmt]) \
                | _relative_modules([stmt], mod)
            for name in sorted(imported):
                if name == "jax" or name.startswith("jax."):
                    findings.append(Finding(
                        "jax-import", f.path, stmt.lineno,
                        f"storage-plane module top-level imports "
                        f"{name} — make it lazy (import inside the "
                        "function) or use the sys.modules seam"))
                elif any(name == d or name.startswith(d + ".")
                         for d in DEVICE_LAYERS):
                    findings.append(Finding(
                        "jax-import", f.path, stmt.lineno,
                        f"storage-plane module top-level imports "
                        f"device layer {name} — storage must stay "
                        "importable without the query/ops stack"))

    # rule 2: nothing reachable from a storage-only entry imports jax
    for entry in ENTRY_MODULES:
        seen = set()
        frontier = list(_entry_roots(repo, entry))
        parent: dict = {m: None for m in frontier}
        while frontier:
            mod = frontier.pop()
            if mod in seen:
                continue
            seen.add(mod)
            for nxt in edges.get(mod, ()):
                if nxt not in seen and nxt not in parent:
                    parent[nxt] = mod
                    frontier.append(nxt)
        for mod in sorted(seen):
            if mod not in jax_importers:
                continue
            chain = [mod]
            cur = parent.get(mod)
            while cur is not None:
                chain.append(cur)
                cur = parent.get(cur)
            via = " <- ".join(chain[:4])
            findings.append(Finding(
                "jax-import", modules[mod].path, 1,
                f"module top-level imports jax and is reachable from "
                f"storage-only entry {entry} (via {via})"))
    return findings
