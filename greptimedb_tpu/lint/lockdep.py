"""Runtime lock-order validator (GTPU_LOCKDEP=1) — lockdep's dynamic
twin.

The static checker (lint/lockgraph.py) proves the *resolvable* lock
graph acyclic; this module records what threads actually do. With
GTPU_LOCKDEP=1 in the environment, `greptimedb_tpu/__init__.py`
installs wrapped `threading.Lock`/`RLock` factories before any repo
module constructs a lock. Each wrapper knows its creation site
(file:line — the lockdep "lock class": every AdmissionController's
`self._lock` shares one identity), and every acquire records an edge
from each lock the thread already holds to the new one. An immediate
reversal (edge B->A when A->B exists) is flagged at acquire time;
`assert_acyclic()` runs the full cycle check — tier-1 exercises it
under the multithreaded scan-pool + admission test.

Overhead when not installed: zero (nothing is patched). Installed:
one thread-local list append per acquire plus a set lookup per held
lock — cheap enough for test runs, not meant for production serving.
"""

from __future__ import annotations

import os
import sys
import threading

_real_lock = None
_real_rlock = None
_installed = False

#: (held_site, acquired_site) -> example thread name; guarded by _meta
_edges: dict = {}
#: immediate order reversals noticed at acquire time
_violations: list = []
_meta = threading.Lock()
_tls = threading.local()


class LockOrderViolation(AssertionError):
    pass


def _creation_site() -> str:
    """First stack frame outside this module and threading.py — the
    lock's static identity (module-relative path:line)."""
    f = sys._getframe(2)
    while f is not None:
        fn = f.f_code.co_filename
        if not (fn.endswith("lockdep.py") or fn.endswith("threading.py")
                or "<frozen" in fn):
            short = fn
            for marker in ("greptimedb_tpu", "site-packages", "lib"):
                idx = fn.rfind(os.sep + marker + os.sep)
                if idx >= 0:
                    short = fn[idx + 1:]
                    break
            return f"{short}:{f.f_lineno}"
        f = f.f_back
    return "<unknown>"


def _held() -> list:
    stack = getattr(_tls, "stack", None)
    if stack is None:
        stack = _tls.stack = []
    return stack


def _on_acquired(site: str) -> None:
    stack = _held()
    new_edges = []
    for held_site in stack:
        if held_site == site:
            continue  # re-entrant / same lock class
        key = (held_site, site)
        if key not in _edges:
            new_edges.append(key)
    if new_edges:
        with _meta:
            for key in new_edges:
                if key not in _edges:
                    _edges[key] = threading.current_thread().name
                    rev = (key[1], key[0])
                    if rev in _edges:
                        _violations.append(
                            f"lock order reversal: {key[0]} -> {key[1]} "
                            f"(thread {_edges[key]}) vs {rev[0]} -> "
                            f"{rev[1]} (thread {_edges[rev]})")
    stack.append(site)


def _on_released(site: str) -> None:
    stack = _held()
    for i in range(len(stack) - 1, -1, -1):
        if stack[i] == site:
            del stack[i]
            return


class _LockdepBase:
    __slots__ = ("_inner", "_site")

    def __init__(self, inner, site: str):
        self._inner = inner
        self._site = site

    def acquire(self, blocking: bool = True, timeout: float = -1):
        got = self._inner.acquire(blocking, timeout)
        if got:
            _on_acquired(self._site)
        return got

    acquire_lock = acquire

    def release(self):
        self._inner.release()
        _on_released(self._site)

    release_lock = release

    def locked(self):
        return self._inner.locked()

    def _at_fork_reinit(self):
        # stdlib (concurrent.futures, logging) registers fork hooks on
        # its locks; forward so a wrapped lock survives os.fork
        self._inner._at_fork_reinit()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()

    def __repr__(self):
        return f"<lockdep {self._inner!r} @ {self._site}>"


class _LockdepLock(_LockdepBase):
    pass


class _LockdepRLock(_LockdepBase):
    # threading.Condition drives its lock through these when it is
    # given (or default-constructs) an RLock
    def _is_owned(self):
        return self._inner._is_owned()

    def _acquire_restore(self, state):
        self._inner._acquire_restore(state)
        _on_acquired(self._site)

    def _release_save(self):
        state = self._inner._release_save()
        _on_released(self._site)
        return state


def install() -> None:
    """Patch threading.Lock/RLock to lockdep-wrapped factories. Locks
    created *before* install (stdlib bootstrap, jax internals) stay
    unwrapped — the repo constructs its locks at module import /
    object construction, after `greptimedb_tpu/__init__` runs this."""
    global _installed, _real_lock, _real_rlock
    if _installed:
        return
    _real_lock = threading.Lock
    _real_rlock = threading.RLock

    def make_lock():
        return _LockdepLock(_real_lock(), _creation_site())

    def make_rlock():
        return _LockdepRLock(_real_rlock(), _creation_site())

    threading.Lock = make_lock
    threading.RLock = make_rlock
    _installed = True
    if os.environ.get("GTPU_LOCKDEP_DIR"):
        # cross-process mode (ProcessCluster children, encode workers):
        # leave this process's edge set behind for the parent's merge
        import atexit

        atexit.register(dump)


def uninstall() -> None:
    global _installed
    if not _installed:
        return
    threading.Lock = _real_lock
    threading.RLock = _real_rlock
    _installed = False


def enabled() -> bool:
    return _installed


def reset() -> None:
    with _meta:
        _edges.clear()
        _violations.clear()


def report() -> dict:
    from greptimedb_tpu.lint.astutil import find_cycle

    with _meta:
        edges = sorted(_edges)
        violations = list(_violations)
    graph: dict = {}
    for a, b in edges:
        graph.setdefault(a, set()).add(b)
    return {"edges": [list(e) for e in edges],
            "violations": violations,
            "cycle": find_cycle(graph)}


def assert_acyclic() -> dict:
    """Raise LockOrderViolation if the observed nesting has a cycle or
    an acquire-time reversal was recorded; return the report dict."""
    rep = report()
    problems = list(rep["violations"])
    if rep["cycle"]:
        problems.append("observed lock-order cycle: "
                        + " -> ".join(rep["cycle"]))
    if problems:
        raise LockOrderViolation("; ".join(problems))
    return rep


# ---- cross-process merge (the serving-fabric box: N frontends) -------------

def dump(dir_path: str = "") -> str | None:
    """Write this process's observed edge set to
    `<dir>/lockdep-<pid>.json` (atomic rename) so a coordinating parent
    can merge lock graphs across every process on the box. The dir
    comes from GTPU_LOCKDEP_DIR when not given; no dir = no-op."""
    dir_path = dir_path or os.environ.get("GTPU_LOCKDEP_DIR", "")
    if not dir_path:
        return None
    import json

    os.makedirs(dir_path, exist_ok=True)
    with _meta:
        edges = sorted(_edges)
        violations = list(_violations)
    path = os.path.join(dir_path, f"lockdep-{os.getpid()}.json")
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump({"pid": os.getpid(),
                   "edges": [list(e) for e in edges],
                   "violations": violations}, f)
    os.replace(tmp, path)
    return path


def merged_report(dir_path: str = "") -> dict:
    """The cross-process union: this process's live edges plus every
    `lockdep-*.json` a child/peer dumped. Lock identities are creation
    sites (file:line), so the same lock class in two processes merges
    into one node — exactly what makes the union meaningful."""
    import glob
    import json

    rep = report()
    edges = {tuple(e) for e in rep["edges"]}
    violations = list(rep["violations"])
    sources = 1
    dir_path = dir_path or os.environ.get("GTPU_LOCKDEP_DIR", "")
    if dir_path:
        for path in sorted(glob.glob(
                os.path.join(dir_path, "lockdep-*.json"))):
            try:
                with open(path) as f:
                    d = json.load(f)
            except (OSError, ValueError):
                continue  # a child died mid-dump: its edges are lost,
                #           not corrupting
            sources += 1
            edges.update(tuple(e) for e in d.get("edges", [])
                         if isinstance(e, list) and len(e) == 2)
            violations.extend(str(v) for v in d.get("violations", []))
    from greptimedb_tpu.lint.astutil import find_cycle

    graph: dict = {}
    for a, b in sorted(edges):
        graph.setdefault(a, set()).add(b)
    return {"edges": [list(e) for e in sorted(edges)],
            "violations": violations,
            "cycle": find_cycle(graph),
            "processes": sources}


def assert_acyclic_merged(dir_path: str = "") -> dict:
    """assert_acyclic over the merged cross-process graph."""
    rep = merged_report(dir_path)
    problems = list(rep["violations"])
    if rep["cycle"]:
        problems.append("observed lock-order cycle (merged): "
                        + " -> ".join(rep["cycle"]))
    if problems:
        raise LockOrderViolation("; ".join(problems))
    return rep
