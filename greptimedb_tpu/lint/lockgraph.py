"""lockdep (static): the concurrency plane's lock graph must stay
acyclic.

The frontend concurrency plane (PR 6), the maintenance scheduler
(PR 4), the scan pool (PR 5), and the device hot set (PR 7) each hold
their own locks, and the call paths between them nest: a region flush
holds region state while submitting to the scheduler, a scan holds the
pool lock while the part cache updates, the device cache invalidates
under region seams. One inverted pair under load is a process-wide
hang — the classic lockdep argument: assert the *order*, not the luck.

This checker extracts the static lock-acquisition graph:

- lock identities: `self._x = threading.Lock()/RLock()/Condition()` in
  a scoped class -> `Module.Class._x`; module-level `_x = ...Lock()`
  -> `Module._x`;
- per-function acquire sets via a fixpoint over resolvable calls
  (`self.m()`, module `fn()`, `self._attr.m()` with constructor-
  inferred attribute types, `mod.fn()` for scoped imports);
- an edge A -> B when B is acquired (directly or via a resolvable
  call) while A is held.

A cycle (or a non-reentrant self-edge) is a finding. The runtime twin
(`greptimedb_tpu.lint.lockdep`, GTPU_LOCKDEP=1) validates the *actual*
nesting under tier-1's multithreaded tests.
"""

from __future__ import annotations

import ast
from typing import Optional

from greptimedb_tpu.lint import Finding, Repo, checker
from greptimedb_tpu.lint.astutil import call_name, dotted, find_cycle

SCOPE_PREFIXES = (
    "greptimedb_tpu/concurrency/",
    "greptimedb_tpu/maintenance/",
    # the mesh hot path: shard dispatch runs under server threads and
    # shares the DeviceCache lock — machine-check it like the rest of
    # the serving plane
    "greptimedb_tpu/parallel/",
    # the serving fabric: every request thread may touch the shared
    # segment locks, so its nesting is part of the serving lock graph
    "greptimedb_tpu/shm/",
)
SCOPE_FILES = (
    "greptimedb_tpu/storage/scan_pool.py",
    "greptimedb_tpu/storage/region.py",
    "greptimedb_tpu/storage/engine.py",
    "greptimedb_tpu/storage/worker.py",
    "greptimedb_tpu/storage/memtable.py",
    "greptimedb_tpu/storage/wal.py",
    "greptimedb_tpu/storage/group_commit.py",
    "greptimedb_tpu/query/device_cache.py",
    # serving path: the vmapped batch leader and the result-encode
    # seam run under the batch-window/encode-pool locks (the
    # concurrency/ package itself is scope-prefixed)
    "greptimedb_tpu/query/vmapped.py",
    "greptimedb_tpu/servers/encode.py",
)

LOCK_CTORS = {"threading.Lock": "lock", "threading.RLock": "rlock",
              "threading.Condition": "condition"}


def _in_scope(path: str) -> bool:
    return path.startswith(SCOPE_PREFIXES) or path in SCOPE_FILES


class _Model:
    """Scoped-module model: lock definitions, class methods, attribute
    types, per-function acquire sets."""

    def __init__(self, repo: Repo):
        self.locks: dict = {}        # lock id -> kind
        self.functions: dict = {}    # fn id "mod:Class.m"/"mod:f" -> node
        self.classes: dict = {}      # class name -> (mod, node)
        self.attr_types: dict = {}   # (class name, attr) -> class name
        self.modname: dict = {}      # fn/class ids -> module short name
        for f in repo.files:
            if not _in_scope(f.path):
                continue
            mod = f.module.rsplit(".", 1)[-1] if f.module else f.path
            for node in f.tree.body:
                if isinstance(node, ast.ClassDef):
                    self.classes[node.name] = (mod, node)
                    for item in node.body:
                        if isinstance(item, (ast.FunctionDef,
                                             ast.AsyncFunctionDef)):
                            self.functions[f"{mod}:{node.name}.{item.name}"] \
                                = (f, node, item)
                elif isinstance(node, (ast.FunctionDef,
                                       ast.AsyncFunctionDef)):
                    self.functions[f"{mod}:{node.name}"] = (f, None, node)
                elif isinstance(node, ast.Assign) and \
                        isinstance(node.value, ast.Call):
                    kind = LOCK_CTORS.get(call_name(node.value) or "")
                    if kind:
                        for t in node.targets:
                            if isinstance(t, ast.Name):
                                self.locks[f"{mod}.{t.id}"] = kind
        # instance locks + attribute types (one pass over all methods):
        # `self.x = KnownClass(...)` types x by construction; `self.x =
        # param` with an annotated parameter (`param: KnownClass`) types
        # it by declaration — injected collaborators (Region's `wal:
        # Wal`) resolve the same as constructed ones
        for fid, (f, cls, fn) in self.functions.items():
            if cls is None:
                continue
            mod = fid.split(":")[0]
            ann = {}
            for a in fn.args.args + fn.args.kwonlyargs:
                t = a.annotation
                if isinstance(t, ast.Constant) and isinstance(t.value, str):
                    name = t.value.strip('"')
                elif t is not None:
                    name = (dotted(t) or "").split(".")[-1]
                else:
                    continue
                if name in self.classes:
                    ann[a.arg] = name
            for node in ast.walk(fn):
                if not isinstance(node, ast.Assign):
                    continue
                cn = ""
                param_cls = None
                if isinstance(node.value, ast.Call):
                    cn = call_name(node.value) or ""
                elif isinstance(node.value, ast.Name):
                    param_cls = ann.get(node.value.id)
                else:
                    continue
                for t in node.targets:
                    if not (isinstance(t, ast.Attribute)
                            and isinstance(t.value, ast.Name)
                            and t.value.id == "self"):
                        continue
                    kind = LOCK_CTORS.get(cn)
                    if kind:
                        self.locks[f"{mod}.{cls.name}.{t.attr}"] = kind
                    base = param_cls or cn.split(".")[-1]
                    if base in self.classes:
                        self.attr_types[(cls.name, t.attr)] = base

    # ---- resolution --------------------------------------------------------

    def lock_of(self, expr: ast.expr, mod: str,
                cls: Optional[ast.ClassDef]) -> Optional[str]:
        name = dotted(expr)
        if not name:
            return None
        if name.startswith("self.") and cls is not None:
            lock_id = f"{mod}.{cls.name}.{name[5:]}"
            if lock_id in self.locks:
                return lock_id
            # lock on an attribute of known type: self._sched._cv
            parts = name.split(".")
            if len(parts) == 3:
                owner = self.attr_types.get((cls.name, parts[1]))
                if owner:
                    lock_id = f"{self.classes[owner][0]}.{owner}.{parts[2]}"
                    if lock_id in self.locks:
                        return lock_id
            return None
        lock_id = f"{mod}.{name}"
        return lock_id if lock_id in self.locks else None

    def callee_of(self, call: ast.Call, mod: str,
                  cls: Optional[ast.ClassDef]) -> Optional[str]:
        name = dotted(call.func)
        if not name:
            return None
        parts = name.split(".")
        if parts[0] == "self" and cls is not None:
            if len(parts) == 2:
                fid = f"{mod}:{cls.name}.{parts[1]}"
                return fid if fid in self.functions else None
            if len(parts) == 3:
                owner = self.attr_types.get((cls.name, parts[1]))
                if owner:
                    fid = f"{self.classes[owner][0]}:{owner}.{parts[2]}"
                    return fid if fid in self.functions else None
            return None
        if len(parts) == 1:
            fid = f"{mod}:{parts[0]}"
            return fid if fid in self.functions else None
        if len(parts) == 2:
            # imported scoped module: scan_pool.get(...)
            fid = f"{parts[0]}:{parts[1]}"
            return fid if fid in self.functions else None
        return None


def _acquire_sets(model: _Model) -> dict:
    """Fixpoint: every lock a function may acquire, transitively."""
    direct: dict = {}
    calls: dict = {}
    for fid, (f, cls, fn) in model.functions.items():
        mod = fid.split(":")[0]
        acq, callees = set(), set()
        for node in ast.walk(fn):
            if isinstance(node, ast.With):
                for item in node.items:
                    lock = model.lock_of(item.context_expr, mod, cls)
                    if lock:
                        acq.add(lock)
            elif isinstance(node, ast.Call):
                callee = model.callee_of(node, mod, cls)
                if callee:
                    callees.add(callee)
        direct[fid] = acq
        calls[fid] = callees
    acquires = {fid: set(s) for fid, s in direct.items()}
    changed = True
    while changed:
        changed = False
        for fid, callees in calls.items():
            for callee in callees:
                extra = acquires.get(callee, set()) - acquires[fid]
                if extra:
                    acquires[fid] |= extra
                    changed = True
    return acquires


def build_edges(repo: Repo):
    """(edges, sites): directed held->acquired lock pairs with one
    representative (path, line, context) site each."""
    model = _Model(repo)
    acquires = _acquire_sets(model)
    edges: dict = {}

    def add(a: str, b: str, f, line: int, why: str):
        if a == b:
            return
        edges.setdefault((a, b), (f.path, line, why))

    for fid, (f, cls, fn) in model.functions.items():
        mod = fid.split(":")[0]

        def visit(node, held):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)) and node is not fn:
                return  # nested defs are analyzed as their own functions
            if isinstance(node, ast.With):
                got = []
                for item in node.items:
                    visit(item.context_expr, held)
                    lock = model.lock_of(item.context_expr, mod, cls)
                    if lock:
                        for h in held:
                            add(h, lock, f, node.lineno,
                                f"nested with in {fid}")
                        got.append(lock)
                for stmt in node.body:
                    visit(stmt, held + got)
                return
            if isinstance(node, ast.Call) and held:
                callee = model.callee_of(node, mod, cls)
                if callee:
                    for lock in acquires.get(callee, ()):
                        for h in held:
                            add(h, lock, f, node.lineno,
                                f"{fid} calls {callee}")
            for child in ast.iter_child_nodes(node):
                visit(child, held)

        visit(fn, [])

    # non-reentrant self-nesting: `with self._lock` containing an
    # acquire of the SAME plain Lock deadlocks immediately
    self_edges = []
    for fid, (f, cls, fn) in model.functions.items():
        mod = fid.split(":")[0]
        for node in ast.walk(fn):
            if not isinstance(node, ast.With):
                continue
            outer = [model.lock_of(i.context_expr, mod, cls)
                     for i in node.items]
            outer = [o for o in outer if o]
            for inner in ast.walk(node):
                if inner is node or not isinstance(inner, ast.With):
                    continue
                for item in inner.items:
                    lock = model.lock_of(item.context_expr, mod, cls)
                    if lock in outer and model.locks.get(lock) == "lock":
                        self_edges.append((lock, f.path, inner.lineno))
    return edges, self_edges, model


@checker("lockdep")
def check(repo: Repo) -> list:
    findings = []
    edges, self_edges, model = build_edges(repo)
    for lock, path, line in self_edges:
        findings.append(Finding(
            "lockdep", path, line,
            f"non-reentrant lock {lock} acquired while already held "
            "(lexically nested with) — immediate self-deadlock"))
    graph: dict = {}
    for (a, b) in edges:
        graph.setdefault(a, set()).add(b)
    cycle = find_cycle(graph)
    if cycle:
        detail = []
        for a, b in zip(cycle, cycle[1:]):
            path, line, why = edges[(a, b)]
            detail.append(f"{a} -> {b} ({path}:{line}, {why})")
        findings.append(Finding(
            "lockdep", edges[(cycle[0], cycle[1])][0],
            edges[(cycle[0], cycle[1])][1],
            "lock-order cycle: " + "; ".join(detail)))
    return findings
