"""metrics + options: the observable/config surface stays documented.

`metrics` folds tools/check_metrics.py in as a lint pass: every metric
registered on the process-wide REGISTRY carries the repo namespace
prefix, has help text, and is charted in the Grafana dashboard.

`options` keeps the config surface honest three ways:
- `config/standalone.example.toml` is byte-identical to
  `options.example_toml()` (the generator is the source of truth —
  regenerate the file after changing a dataclass);
- every scalar option path in the StandaloneOptions tree has a `_DOC`
  entry (the example file is the only config documentation operators
  get);
- every `_DOC` key still names a real option (stale docs are findings
  too).

`exemplars` keeps the trace<->metric correlation loop closed: every
serving-hot-path Histogram (query_/statement_/encode_/admission_ —
the latencies a dashboard spike sends an operator chasing) must be
registered with `exemplars=True`, so its buckets carry trace ids that
tools/trace_dump.py can pull. A p99 histogram an operator cannot pivot
into a concrete trace is a dead end.
"""

from __future__ import annotations

import ast
import dataclasses
import os

from greptimedb_tpu.lint import Finding, Repo, checker


@checker("metrics")
def check_metrics_pass(repo: Repo) -> list:
    if not repo.root:
        return []  # fixture repos have no live registry to import
    import importlib.util
    import json

    # tools/ is not a package; load the lint's metrics pass the same way
    # tests/test_check_metrics.py does
    path = os.path.join(repo.root, "tools", "check_metrics.py")
    spec = importlib.util.spec_from_file_location("check_metrics", path)
    cm = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(cm)

    findings = []
    try:
        with open(cm.DASHBOARD, encoding="utf-8") as f:
            dashboard_text = f.read()
        json.loads(dashboard_text)
    except (OSError, ValueError) as e:
        return [Finding("metrics", "grafana/greptimedb_tpu.json", 1,
                        f"dashboard unreadable: {e}")]
    for problem in cm.check(cm.registered_metrics(), dashboard_text):
        findings.append(Finding(
            "metrics", "greptimedb_tpu/utils/metrics.py", 1, problem))
    return findings


#: histogram-name prefixes on the serving hot path: a latency spike in
#: one of these is what sends an operator from the dashboard into a
#: trace — without exemplars that pivot is impossible
_EXEMPLAR_PREFIXES = (
    "greptimedb_tpu_query_",
    "greptimedb_tpu_statement_",
    "greptimedb_tpu_encode_",
    "greptimedb_tpu_admission_",
)


@checker("exemplars")
def check_exemplars(repo: Repo) -> list:
    findings = []
    for src in repo.files:
        for node in ast.walk(src.tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "histogram"):
                continue
            if not (node.args and isinstance(node.args[0], ast.Constant)
                    and isinstance(node.args[0].value, str)):
                continue
            name = node.args[0].value
            if not name.startswith(_EXEMPLAR_PREFIXES):
                continue
            ok = any(
                kw.arg == "exemplars"
                and isinstance(kw.value, ast.Constant)
                and kw.value.value is True
                for kw in node.keywords)
            if not ok:
                findings.append(Finding(
                    "exemplars", src.path, node.lineno,
                    f"serving-hot-path histogram '{name}' does not "
                    "declare exemplars=True — its buckets carry no "
                    "trace ids, so a latency spike here cannot be "
                    "pivoted into a trace (tools/trace_dump.py)"))
    return findings


def _scalar_paths(obj, prefix: str = ""):
    for f in dataclasses.fields(obj):
        value = getattr(obj, f.name)
        path = f"{prefix}{f.name}"
        if dataclasses.is_dataclass(value):
            yield from _scalar_paths(value, path + ".")
        else:
            # scalars AND array-of-tables fields: each needs one doc
            # line (element fields are emitted commented, undocumented)
            yield path


@checker("options")
def check_options(repo: Repo) -> list:
    if not repo.root:
        return []
    from greptimedb_tpu.options import _DOC, StandaloneOptions, example_toml

    findings = []
    opts_path = "greptimedb_tpu/options.py"
    example_path = os.path.join(repo.root, "config",
                                "standalone.example.toml")
    try:
        with open(example_path, encoding="utf-8") as f:
            on_disk = f.read()
    except OSError as e:
        return [Finding("options", "config/standalone.example.toml", 1,
                        f"example config unreadable: {e}")]
    generated = example_toml()
    if generated != on_disk:
        gen_lines = generated.splitlines()
        disk_lines = on_disk.splitlines()
        where, what = len(gen_lines), "trailing content differs"
        for i, line in enumerate(gen_lines, 1):
            if i > len(disk_lines) or disk_lines[i - 1] != line:
                where, what = i, f"expected {line!r}"
                break
        else:
            if len(disk_lines) > len(gen_lines):
                where = len(gen_lines) + 1
                what = f"unexpected extra line {disk_lines[len(gen_lines)]!r}"
        findings.append(Finding(
            "options", "config/standalone.example.toml", where,
            "drifted from options.example_toml() (first difference at "
            f"line {where}: {what}) — regenerate: python -c \"from "
            "greptimedb_tpu.options import example_toml; "
            "print(example_toml(), end='')\" "
            "> config/standalone.example.toml"))
    paths = set(_scalar_paths(StandaloneOptions()))
    for path in sorted(paths - set(_DOC)):
        findings.append(Finding(
            "options", opts_path, 1,
            f"option '{path}' has no _DOC entry — the generated "
            "example config is the operator documentation"))
    for key in sorted(set(_DOC) - paths):
        findings.append(Finding(
            "options", opts_path, 1,
            f"_DOC entry '{key}' names no existing option — stale doc"))
    return findings
