"""span_coverage: FAULTS-registered I/O seams and wire entry points must
execute inside a tracing span.

The trace tree (ISSUE 15) is only as good as its coverage: an I/O seam
that fires the fault registry but never opens a span is exactly the
place a production stall hides — chaos can reach it, the operator's
EXPLAIN ANALYZE cannot see it. The invariant (same discipline as the
fault-seam checker, same allowlist escape hatch):

- every `FAULTS.fire` / `FAULTS.mangle*` call site sits lexically inside
  a `with tracing.span(...)` / `request_span(...)` block (a seam that
  injects faults is an I/O boundary worth timing), and
- every wire entry point (the HTTP router, the MySQL/Postgres statement
  funnels, Flight do_get/do_put) opens a request/span context somewhere
  in its body — a protocol whose requests never root a span produces
  untraceable traffic.

Legitimate exceptions — background control-plane ticks (heartbeat,
election), commit-pipeline leaders that serve many writers' traces at
once — go in lint_allow.toml with a reason, and unused entries are
themselves findings, so the escape hatch cannot rot.
"""

from __future__ import annotations

import ast

from greptimedb_tpu.lint import Finding, Repo, checker
from greptimedb_tpu.lint.astutil import (
    call_name,
    enclosing_function,
    iter_calls,
)

#: fault-registry entry points whose call sites must be span-covered
FAULT_CALLS = frozenset({
    "FAULTS.fire", "FAULTS.mangle",
    "FAULTS.mangled_read", "FAULTS.mangled_write",
})

#: last dotted component of a call that opens a span context
SPAN_OPENERS = frozenset({"span", "request_span"})

#: calls that satisfy the wire-entry rule (adopt_remote installs the
#: caller's trace context server-side; the span itself opens just below)
WIRE_OPENERS = SPAN_OPENERS | {"adopt_remote"}

#: wire entry points: (repo path) -> function names that must open a
#: span/request context in their body
WIRE_ENTRIES = {
    "greptimedb_tpu/servers/http.py": ("_route",),
    "greptimedb_tpu/servers/mysql.py": ("_dispatch",),
    "greptimedb_tpu/servers/postgres.py": ("_run_simple",),
    "greptimedb_tpu/servers/flight.py": ("do_get", "do_put"),
}


def _span_ranges(tree: ast.AST) -> list:
    """(lineno, end_lineno) of every `with` whose context manager is a
    span-opening call. Lexical containment is the coverage test: a
    closure defined inside the block (retry bodies, pool thunks) counts
    as covered — tracing.propagate carries the context to wherever it
    actually runs."""
    out = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.With):
            continue
        for item in node.items:
            c = item.context_expr
            if not isinstance(c, ast.Call):
                continue
            name = call_name(c) or ""
            if name.split(".")[-1] in SPAN_OPENERS:
                out.append((node.lineno,
                            getattr(node, "end_lineno", node.lineno)))
                break
    return out


def _opens_wire_span(fn: ast.AST) -> bool:
    for call in iter_calls(fn):
        name = call_name(call) or ""
        if name.split(".")[-1] in WIRE_OPENERS:
            return True
    return False


@checker("span_coverage")
def check(repo: Repo) -> list:
    findings = []
    for f in repo.files:
        if not f.path.startswith("greptimedb_tpu/"):
            continue
        ranges = _span_ranges(f.tree)
        for call in iter_calls(f.tree):
            name = call_name(call)
            if name not in FAULT_CALLS:
                continue
            if any(lo <= call.lineno <= hi for lo, hi in ranges):
                continue
            findings.append(Finding(
                "span_coverage", f.path, call.lineno,
                f"{name}(...) in {enclosing_function(f.tree, call)}() "
                "runs outside any tracing.span — this I/O seam is "
                "invisible to span trees; wrap it in a span or "
                "allowlist with a reason"))
        for fn_name in WIRE_ENTRIES.get(f.path, ()):
            fns = [n for n in ast.walk(f.tree)
                   if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
                   and n.name == fn_name]
            if not fns:
                continue  # surface moved; the mapping is best-effort
            for fn in fns:
                if not _opens_wire_span(fn):
                    findings.append(Finding(
                        "span_coverage", f.path, fn.lineno,
                        f"wire entry point {fn_name}() opens no request "
                        "span — requests through this protocol produce "
                        "untraceable traffic"))
    return findings
