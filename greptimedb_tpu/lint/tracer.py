"""tracer: jit/pallas/donated functions must stay traceable.

Inside a function that jax traces (`@jax.jit`, `g = jax.jit(f)`,
`pl.pallas_call(kernel, ...)`), Python-level branching on a traced
value raises at trace time in the best case and silently bakes in one
branch in the worst (the tracer sees an abstract value, not data).
Host coercions (`float()`/`int()`/`.item()`) force a device sync and
break under jit; wall-clock/RNG calls freeze one sample into the
compiled executable. And an argument donated via `donate_argnums` is
DEALLOCATED by the call — reusing the Python reference afterwards
reads a dead buffer (PR 7's donation twins exist precisely so call
sites rebind: `acc = fold(acc, chunk)`).

Heuristics (tuned against ops/ and query/physical.py, escape hatch =
lint_allow.toml): traced names are the function's parameters plus
names assigned from expressions over traced names; tests touching only
`.shape`/`.ndim`/`.dtype`/`.size`/`len()`/`isinstance`/`is None` are
static and exempt.
"""

from __future__ import annotations

import ast
from typing import Optional

from greptimedb_tpu.lint import Finding, Repo, checker
from greptimedb_tpu.lint.astutil import call_name, decorator_names, dotted

TRACE_WRAPPERS = ("jax.jit", "jit", "pallas_call", "pl.pallas_call",
                  "jax.pmap", "pmap", "checkify.checkify")

HOST_CALLS = {
    "time.time", "time.perf_counter", "time.monotonic", "time.sleep",
    "datetime.now", "datetime.datetime.now", "datetime.utcnow",
}

STATIC_ATTRS = {"shape", "ndim", "dtype", "size"}


def _is_trace_wrapper(name: Optional[str]) -> bool:
    return bool(name) and (name in TRACE_WRAPPERS
                           or name.endswith(".jit")
                           or name.endswith("pallas_call"))


def _traced_functions(tree: ast.Module):
    """function node -> static param names, for every function the
    module traces: decorated, wrapped via assignment, or passed as a
    pallas kernel. `static_argnames`/`static_argnums` params are NOT
    traced — Python branching on them is exactly how trace-time
    specialization works."""
    by_name = {}
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            by_name.setdefault(node.name, node)
    out = {}

    def static_of(call: ast.Call, fn) -> set:
        names: set = set()
        for kw in call.keywords:
            if kw.arg == "static_argnames":
                vals = kw.value.elts if isinstance(
                    kw.value, (ast.Tuple, ast.List)) else [kw.value]
                names |= {c.value for c in vals
                          if isinstance(c, ast.Constant)}
            elif kw.arg == "static_argnums" and fn is not None \
                    and not isinstance(fn, ast.Lambda):
                nums = kw.value.elts if isinstance(
                    kw.value, (ast.Tuple, ast.List)) else [kw.value]
                params = [a.arg for a in fn.args.posonlyargs
                          + fn.args.args]
                for c in nums:
                    if isinstance(c, ast.Constant) and \
                            isinstance(c.value, int) and \
                            c.value < len(params):
                        names.add(params[c.value])
        return names

    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            names = decorator_names(node)
            if any(_is_trace_wrapper(n) for n in names):
                static: set = set()
                for dec in node.decorator_list:
                    if isinstance(dec, ast.Call):
                        static |= static_of(dec, node)
                out[node] = static
        elif isinstance(node, ast.Call) and _is_trace_wrapper(
                call_name(node)):
            for arg in node.args[:1]:
                target = None
                if isinstance(arg, ast.Name) and arg.id in by_name:
                    target = by_name[arg.id]
                elif isinstance(arg, ast.Lambda):
                    target = arg
                if target is not None:
                    out[target] = out.get(target, set()) \
                        | static_of(node, target)
    return out


def _param_names(fn) -> set:
    args = fn.args
    names = [a.arg for a in args.posonlyargs + args.args + args.kwonlyargs]
    if args.vararg:
        names.append(args.vararg.arg)
    if args.kwarg:
        names.append(args.kwarg.arg)
    if isinstance(fn, ast.Lambda):
        return set(names)
    return set(names) - {"self", "cls"}


def _traced_names(fn, static: set) -> set:
    """Parameters (minus the static ones) plus names assigned from
    expressions over traced *data* (one forward pass). An assignment
    whose value only touches traced names through shape/dtype/len()
    stays static — `squeeze = values.ndim == 1` is a Python bool."""
    traced = _param_names(fn) - set(static)
    body = fn.body if isinstance(fn.body, list) else [fn.body]
    for stmt in body:
        for node in ast.walk(stmt):
            if not isinstance(node, (ast.Assign, ast.AugAssign,
                                     ast.AnnAssign)):
                continue
            value = node.value
            if value is None:
                continue
            mentions = False
            for n in ast.walk(value):
                if isinstance(n, ast.Name) and n.id in traced:
                    mentions = True
                elif isinstance(n, ast.Call):
                    cn = call_name(n) or ""
                    if cn.startswith(("jnp.", "jax.", "lax.", "pl.")):
                        mentions = True
            if not mentions or _test_is_static(value, traced):
                continue
            targets = node.targets if isinstance(node, ast.Assign) \
                else [node.target]
            for t in targets:
                for n in ast.walk(t):
                    if isinstance(n, ast.Name):
                        traced.add(n.id)
    return traced


def _parents(root: ast.AST) -> dict:
    out = {}
    for node in ast.walk(root):
        for child in ast.iter_child_nodes(node):
            out[child] = node
    return out


def _test_is_static(test: ast.expr, traced: set) -> bool:
    """True when the condition never touches traced *data*: every
    traced-name read sits under a shape/dtype attribute, len(), or
    isinstance/is-None check."""
    parents = _parents(test)
    for node in ast.walk(test):
        if not (isinstance(node, ast.Name)
                and isinstance(node.ctx, ast.Load)
                and node.id in traced):
            continue
        cur, exempt = node, False
        while cur is not None:
            if isinstance(cur, ast.Attribute) and cur.attr in STATIC_ATTRS:
                exempt = True
                break
            if isinstance(cur, ast.Call):
                cn = call_name(cur) or ""
                if cn in ("len", "isinstance", "getattr", "hasattr",
                          "type", "id"):
                    exempt = True
                    break
            if isinstance(cur, ast.Compare) and any(
                    isinstance(op, (ast.Is, ast.IsNot))
                    for op in cur.ops):
                exempt = True
                break
            cur = parents.get(cur)
        if not exempt:
            return False
    return True


@checker("tracer")
def check(repo: Repo) -> list:
    findings = []
    for f in repo.files:
        if not f.path.startswith("greptimedb_tpu/"):
            continue
        traced_fns = _traced_functions(f.tree)
        for fn, static in traced_fns.items():
            label = getattr(fn, "name", "<lambda>")
            traced = _traced_names(fn, static)
            body = fn.body if isinstance(fn.body, list) else [fn.body]
            for stmt in body:
                for node in ast.walk(stmt):
                    if isinstance(node, (ast.If, ast.While)) and \
                            not _test_is_static(node.test, traced):
                        findings.append(Finding(
                            "tracer", f.path, node.lineno,
                            f"Python {'while' if isinstance(node, ast.While) else 'if'} "
                            f"on a traced value inside traced function "
                            f"{label}() — use jnp.where/lax.cond"))
                    elif isinstance(node, ast.Call):
                        cn = call_name(node) or ""
                        if cn in HOST_CALLS or cn.startswith(
                                ("random.", "np.random.",
                                 "numpy.random.")):
                            findings.append(Finding(
                                "tracer", f.path, node.lineno,
                                f"host wall-clock/RNG call {cn}() inside "
                                f"traced function {label}() — the result "
                                "is frozen into the compiled executable"))
                        elif isinstance(node.func, ast.Attribute) and \
                                node.func.attr == "item" and not node.args:
                            findings.append(Finding(
                                "tracer", f.path, node.lineno,
                                f".item() inside traced function "
                                f"{label}() forces a host sync and "
                                "fails under jit"))
                        elif cn in ("float", "int", "bool") and \
                                len(node.args) == 1 and not (
                                    isinstance(node.args[0], ast.Constant)
                                    or _test_is_static(node.args[0],
                                                       traced)):
                            findings.append(Finding(
                                "tracer", f.path, node.lineno,
                                f"{cn}() coercion of a traced value "
                                f"inside traced function {label}()"))
        findings.extend(_check_donation(f))
    return findings


def _check_donation(f) -> list:
    """Reuse of a donated buffer after the donating call."""
    findings = []
    donated_callables = {}
    for node in ast.walk(f.tree):
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            cn = call_name(node.value) or ""
            if not _is_trace_wrapper(cn):
                continue
            nums = None
            for kw in node.value.keywords:
                if kw.arg == "donate_argnums":
                    if isinstance(kw.value, ast.Tuple):
                        nums = tuple(c.value for c in kw.value.elts
                                     if isinstance(c, ast.Constant))
                    elif isinstance(kw.value, ast.Constant):
                        nums = (kw.value.value,)
            if nums:
                for t in node.targets:
                    name = dotted(t)
                    if name:
                        donated_callables[name] = nums
    if not donated_callables:
        return findings
    for fn in ast.walk(f.tree):
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        parents: dict = {}
        for node in ast.walk(fn):
            for child in ast.iter_child_nodes(node):
                parents[child] = node

        def branch_arms(node) -> dict:
            """{If-node-id: 'body'|'orelse'} for every If ancestor —
            two nodes in different arms of the same If can never both
            execute, so a load there is NOT a reuse."""
            arms = {}
            cur = node
            while cur in parents:
                parent = parents[cur]
                if isinstance(parent, ast.If):
                    if any(cur is s or _contains(s, cur)
                           for s in parent.orelse):
                        arms[id(parent)] = "orelse"
                    else:
                        arms[id(parent)] = "body"
                cur = parent
            return arms

        def _contains(root, target) -> bool:
            return any(n is target for n in ast.walk(root))

        stores: list = []
        loads: list = []
        calls: list = []
        for node in ast.walk(fn):
            if isinstance(node, ast.Name):
                (stores if isinstance(node.ctx, (ast.Store, ast.Del))
                 else loads).append(node)
            elif isinstance(node, ast.Call):
                cn = dotted(node.func)
                if cn in donated_callables:
                    calls.append((node, donated_callables[cn], cn))
        for call, nums, cn in calls:
            # a donating call inside a `return`/`raise` statement exits
            # the function — no later load is on the same path
            stmt = call
            while stmt in parents and not isinstance(stmt, ast.stmt):
                stmt = parents[stmt]
            if isinstance(stmt, (ast.Return, ast.Raise)):
                continue
            call_arms = branch_arms(call)
            for idx in nums:
                if not isinstance(idx, int) or idx >= len(call.args):
                    continue
                arg = call.args[idx]
                if not isinstance(arg, ast.Name):
                    continue
                rebound = [n.lineno for n in stores
                           if n.id == arg.id and n.lineno >= call.lineno]
                for load in loads:
                    if load.id != arg.id or load.lineno <= call.lineno:
                        continue
                    if any(st <= load.lineno for st in rebound):
                        continue  # rebound before this read
                    load_arms = branch_arms(load)
                    if any(load_arms.get(k) not in (None, v)
                           for k, v in call_arms.items()):
                        continue  # mutually exclusive If arms
                    findings.append(Finding(
                        "tracer", f.path, load.lineno,
                        f"buffer {arg.id!r} reused after being donated "
                        f"to {cn}() at line {call.lineno} — donation "
                        "deallocates it; rebind the result instead"))
                    break
    return findings
