"""typed-error: wire boundaries must keep typed degradation typed.

PR 1/6 route backpressure and degradation through typed exceptions —
`Unavailable` (retries exhausted, back off) and its subclass
`Overloaded` (admission rejection) map to HTTP 503 / MySQL 1040 so
clients back off instead of stack-tracing. A broad `except Exception`
at a request path that does NOT first branch on the typed errors
swallows that signal into a generic 400/500 — the client retries hot
and the operator loses the 503 metric.

Rules over `servers/` and `query/engine.py`:
- bare `except:` is always an error;
- an `except Exception` handler must be preceded (same `try`) by a
  handler naming a typed error (`Unavailable`/`Overloaded`/
  `FaultError`), or itself re-raise / raise a typed error / branch on
  `isinstance(e, Unavailable)`.
"""

from __future__ import annotations

import ast

from greptimedb_tpu.lint import Finding, Repo, checker
from greptimedb_tpu.lint.astutil import enclosing_function

SCOPE_PREFIXES = ("greptimedb_tpu/servers/",)
SCOPE_FILES = ("greptimedb_tpu/query/engine.py",)

TYPED_NAMES = {"Unavailable", "Overloaded", "FaultError", "AuthError"}


def _exc_names(node) -> set:
    if node is None:
        return set()
    if isinstance(node, ast.Tuple):
        out = set()
        for e in node.elts:
            out |= _exc_names(e)
        return out
    if isinstance(node, ast.Name):
        return {node.id}
    if isinstance(node, ast.Attribute):
        return {node.attr}
    return set()


def _handler_stays_typed(handler: ast.ExceptHandler) -> bool:
    """The broad handler itself preserves typing: re-raises, raises a
    typed error, or branches on isinstance(e, <typed>)."""
    for node in ast.walk(handler):
        if isinstance(node, ast.Raise):
            if node.exc is None:
                return True  # bare re-raise keeps the original type
            for n in ast.walk(node.exc):
                if isinstance(n, ast.Name) and n.id in TYPED_NAMES:
                    return True
        if isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Name) and \
                node.func.id == "isinstance":
            if _exc_names(node.args[1] if len(node.args) > 1 else None) \
                    & TYPED_NAMES:
                return True
    return False


@checker("typed-error")
def check(repo: Repo) -> list:
    findings = []
    for f in repo.files:
        if not (f.path.startswith(SCOPE_PREFIXES)
                or f.path in SCOPE_FILES):
            continue
        for node in ast.walk(f.tree):
            if not isinstance(node, ast.Try):
                continue
            typed_seen = False
            for handler in node.handlers:
                names = _exc_names(handler.type)
                if handler.type is None:
                    findings.append(Finding(
                        "typed-error", f.path, handler.lineno,
                        "bare `except:` in "
                        f"{enclosing_function(f.tree, handler)}() at a "
                        "wire boundary — catch Exception at most, with "
                        "a typed Unavailable branch first"))
                    continue
                if names & TYPED_NAMES:
                    typed_seen = True
                    continue
                if ("Exception" in names or "BaseException" in names) \
                        and not typed_seen \
                        and not _handler_stays_typed(handler):
                    findings.append(Finding(
                        "typed-error", f.path, handler.lineno,
                        "broad `except Exception` in "
                        f"{enclosing_function(f.tree, handler)}() "
                        "without a preceding typed Unavailable/"
                        "Overloaded branch — typed degradation would "
                        "reach the wire as a generic error"))
    return findings
