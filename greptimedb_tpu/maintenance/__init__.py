"""Background maintenance plane (datanode-local).

The reference mito2 engine never compacts or flushes on the foreground
write path: `FlushScheduler` (mito2/src/flush.rs) and the compaction
scheduler (compaction/scheduler) own all maintenance, writers only stall
at a hard limit. This package is that plane for the reproduction, plus
the two maintenance workloads the reference schedules elsewhere:

- `MaintenanceScheduler` (scheduler.py): a bounded priority queue and a
  small worker pool per datanode. Per-region jobs serialize (one running
  job per region; the merge itself still holds the region's
  `_compact_lock`); priority is flush > compaction > downsample > expiry;
  the write path only stalls when a region's memtable bytes or L0 file
  count cross a hard threshold (`greptimedb_tpu_write_stall_seconds_total`
  counts every stalled second).
- rollup/downsample jobs (rollup.py): inactive-window SSTs re-encoded
  into coarser-resolution plane SSTs (min/max/sum/count per field) that
  the query engine substitutes for eligible coarse-bucket aggregates.
- retention expiry (retention.py): TTL drops whole expired SSTs via one
  atomic manifest edit.

Job visibility: every job carries an id; ADMIN flush_table/compact_table/
rollup_table return it, ADMIN maintenance_status(job_id) polls it, and
`information_schema.maintenance_jobs` / `/v1/maintenance` list the live
queue + recent history. Chaos hooks: the `maintenance.job` fault point
fires at job start (labels op=kind, phase=start) and again at each job's
manifest-swap boundary (phase=swap), so a seeded schedule can crash a
compaction mid-swap and the tests assert the pre-compaction file list
stays readable.
"""

from __future__ import annotations

from .scheduler import Job, MaintenanceScheduler, PRIORITY, parse_duration_ms

__all__ = ["Job", "MaintenanceScheduler", "PRIORITY", "parse_duration_ms"]
