"""Retention expiry: TTL drops whole expired SSTs.

The cheap half of retention (the reference's TTL handling in its
compaction picker): an SST whose ts_max is older than `now - ttl` can be
dropped wholesale — one manifest edit removes the files atomically, the
purge queue deletes the bytes once no scan pins them. Rows inside a
straddling SST are NOT trimmed (that would be a rewrite, i.e. a
compaction's job); expiry is deliberately metadata-only."""

from __future__ import annotations

import time
from typing import Optional


def ms_to_units(ms: int, dtype) -> int:
    """Milliseconds -> the timestamp column's native unit (floor)."""
    nanos = dtype.time_unit.nanos_per_unit
    return int(ms * 1_000_000 // nanos)


def run_expiry(region, ttl_ms: int,
               now_ms: Optional[int] = None) -> dict:
    """Drop every SST fully older than the TTL from `region` with one
    atomic manifest edit. Returns {"removed": n, "cutoff": units}."""
    if ttl_ms <= 0:
        return {"removed": 0, "cutoff": None}
    from greptimedb_tpu.fault import FAULTS

    now = int(time.time() * 1000) if now_ms is None else int(now_ms)
    dtype = region.schema.time_index.dtype
    cutoff = ms_to_units(now - ttl_ms, dtype)
    # _compact_lock: a concurrent merge (inline stall-escape compaction
    # bypasses the scheduler's per-region serialization) reads its input
    # SSTs outside region._lock — expiry purging one mid-merge would
    # fail the merge or resurrect expired rows via the merged output
    with region._compact_lock, region._lock:
        expired = [f for f in region.files.values() if f.ts_max < cutoff]
        if not expired:
            return {"removed": 0, "cutoff": cutoff}
        # chaos seam: a crash here must leave the pre-expiry file list
        # fully readable (the manifest edit below is the atomic swap)
        FAULTS.fire("maintenance.job", op="expire", phase="swap")
        removed = [f.file_id for f in expired]
        for fid in removed:
            region.files.pop(fid, None)
        # expired files' decoded scan parts go with them (per-file
        # scan cache, storage/region.py)
        region._invalidate_file_parts(removed)
        # flushed_seq=None: expiry persists nothing from the memtable;
        # advancing flushed_seq would drop unflushed writes on replay
        region.manifest.record_flush(
            [], flushed_seq=None,
            tag_dicts=region.registry.snapshot(), removed=removed)
        now_mono = time.monotonic()
        region._purge_queue.extend((fid, now_mono) for fid in removed)
        region.data_version += 1
        region._drain_purge()
    from greptimedb_tpu.utils.metrics import EXPIRED_SSTS

    EXPIRED_SSTS.inc(len(removed))
    return {"removed": len(removed), "cutoff": cutoff}
