"""Downsample/rollup jobs + query-time rollup substitution.

A rollup job re-encodes a raw region's INACTIVE time windows (everything
strictly before the resolution bucket holding the newest raw timestamp)
into a coarser-resolution "plane" region: one row per (tags..., bucket)
carrying, for every numeric field `f`, the planes `f__min`, `f__max`,
`f__sum` (float64) and `f__count` (int64), plus `rows__count` (the raw
row count, for count(*)). The planes are produced by the same device
sort-dedup + segment kernels the query path uses (ops/dedup, jax segment
reductions), then written through the ordinary region write/flush path —
rollup SSTs are plain SSTs in a hidden companion region whose id embeds
the raw region id and the rule index.

Query-time substitution: an aggregate query whose group keys are tags
and/or a `date_bin`/`time_bucket` key at a multiple of the rollup
resolution, whose aggregates are min/max/sum/count/avg over plain field
columns, and whose WHERE is (aligned time range) AND (tag-only
predicates) is rewritten to scan the rollup region instead — e.g.
`avg(v)` becomes `sum(v__sum) / sum(v__count)`. Coverage and staleness
are checked per region: the queried range must sit inside the rolled-up
span, and any raw data newer than the rollup's `as_of_seq` overlapping
that span (a late/out-of-order write) disqualifies the substitution
until the next rollup run re-covers it. Re-runs are idempotent: rollup
rows share the (tags, bucket) primary key, so last-write-wins dedup
makes the newest run authoritative.

Crash safety: the coverage state file is written only AFTER the rollup
SST is durable; a crash mid-job leaves coverage un-advanced (the raw
data keeps serving queries) and the next run overwrites the partial
rows.
"""

from __future__ import annotations

import dataclasses
import json
import os
import threading
import time
from dataclasses import dataclass
from typing import Optional

import numpy as np

from greptimedb_tpu.maintenance.retention import ms_to_units

#: bit added to a raw region id to name its rollup companion; the rule
#: SLOT rides in bits 20.. so several resolutions coexist. Raw region
#: ids are (table_id << 32) | region_idx with small region_idx, so the
#: flag can't collide with a real region.
ROLLUP_RID_FLAG = 1 << 30
ROWS_COL = "rows__count"

_STATE_FILE = "rollup_state.json"


def rule_slot(resolution_ms: int) -> int:
    """Stable slot for a resolution: derived from the resolution itself
    (not list position), so the rollup region id survives restarts and
    config reordering. Collisions across distinct resolutions are
    possible but self-correcting — the region's state file records its
    resolution and a mismatch reads as 'no coverage'."""
    import zlib

    return zlib.crc32(b"rollup:%d" % int(resolution_ms)) % 509


@dataclass
class RollupRule:
    """One [[maintenance.rollup]] entry: the target resolution and which
    fields get planes (empty = every numeric field)."""

    resolution_ms: int = 60_000
    fields: tuple = ()
    #: submitted automatically on every scheduler tick (vs ADMIN-only)
    auto: bool = True

    @staticmethod
    def from_dict(d: dict) -> "RollupRule":
        from greptimedb_tpu.maintenance.scheduler import parse_duration_ms

        res = d.get("resolution_ms") or parse_duration_ms(
            d.get("resolution", "1m"))
        return RollupRule(resolution_ms=int(res),
                          fields=tuple(d.get("fields", ())),
                          auto=bool(d.get("auto", True)))


def rollup_region_id(raw_rid: int, rule_idx: int = 0) -> int:
    return raw_rid + ROLLUP_RID_FLAG + (rule_idx << 20)


def plane_fields(schema, rule: Optional[RollupRule] = None) -> list:
    """The raw FIELD columns that get rollup planes: numeric, and listed
    in the rule (when the rule names fields)."""
    out = []
    for c in schema.field_columns:
        if not (c.dtype.is_float or c.dtype.value.startswith(("int", "uint"))):
            continue
        if rule is not None and rule.fields and c.name not in rule.fields:
            continue
        out.append(c)
    return out


def rollup_schema(raw_schema, rule: Optional[RollupRule] = None):
    """Derive the plane schema: same tags + time index, plane fields."""
    from greptimedb_tpu.datatypes.schema import ColumnSchema, Schema
    from greptimedb_tpu.datatypes.types import DataType, SemanticType

    cols = [dataclasses.replace(c) for c in raw_schema.tag_columns]
    cols.append(dataclasses.replace(raw_schema.time_index))
    for f in plane_fields(raw_schema, rule):
        cols.append(ColumnSchema(f"{f.name}__min", f.dtype,
                                 SemanticType.FIELD, True))
        cols.append(ColumnSchema(f"{f.name}__max", f.dtype,
                                 SemanticType.FIELD, True))
        cols.append(ColumnSchema(f"{f.name}__sum", DataType.FLOAT64,
                                 SemanticType.FIELD, True))
        cols.append(ColumnSchema(f"{f.name}__count", DataType.INT64,
                                 SemanticType.FIELD, True))
    cols.append(ColumnSchema(ROWS_COL, DataType.INT64,
                             SemanticType.FIELD, True))
    return Schema(cols)


# ---- coverage state ---------------------------------------------------------


def _state_path(region_dir: str) -> str:
    return os.path.join(region_dir, _STATE_FILE)


#: read_state cache: path -> (monotonic deadline, state). Substitution
#: probes coverage on EVERY eligible aggregate query; on a remote object
#: store that is a GET per region per rule per query without this. The
#: short TTL only delays when a FRESH rollup becomes visible — staleness
#: in the other direction (late raw writes) is caught by the metadata
#: _late_data_since check, which never touches the store.
_STATE_TTL_S = 2.0
_state_cache: dict = {}
_state_lock = threading.Lock()

#: bumped on every rollup-substitution state change (a finished roll, a
#: dropped companion). The frontend plan cache stamps its memoized
#: "substitution ineligible — skip the probe" decisions with this
#: version, so a state change evicts those stale shapes immediately.
_sub_state_version = 0


def substitution_state_version() -> int:
    with _state_lock:
        return _sub_state_version


def _bump_substitution_state() -> None:
    global _sub_state_version
    with _state_lock:
        _sub_state_version += 1


def read_state(store, region_dir: str) -> Optional[dict]:
    path = _state_path(region_dir)
    now = time.monotonic()
    with _state_lock:
        hit = _state_cache.get(path)
        if hit is not None and hit[0] > now:
            return hit[1]
    try:
        state = json.loads(store.read(path).decode())
    except Exception:  # noqa: BLE001 — absent/corrupt = no coverage
        state = None
    with _state_lock:
        _state_cache[path] = (now + _STATE_TTL_S, state)
    return state


def write_state(store, region_dir: str, state: dict) -> None:
    path = _state_path(region_dir)
    store.write(path, json.dumps(state).encode())
    with _state_lock:
        _state_cache[path] = (time.monotonic() + _STATE_TTL_S, dict(state))
    _bump_substitution_state()


# ---- the job ---------------------------------------------------------------


def _ensure_rollup_region(engine, raw_region, rule_idx: int,
                          rule: RollupRule):
    rrid = rollup_region_id(raw_region.region_id, rule_idx)
    region = None
    try:
        region = engine.region(rrid)
    except KeyError:
        try:
            engine.open_region(rrid)
        except FileNotFoundError:
            engine.create_region(rrid,
                                 rollup_schema(raw_region.schema, rule))
        region = engine.region(rrid)
    # ALTER drift: a companion created before an ADD/DROP COLUMN must
    # follow the raw schema, or re-rolls would write mismatched batches
    # and substituted queries would reference absent plane columns
    want = rollup_schema(raw_region.schema, rule)
    if [(c.name, c.dtype) for c in region.schema.columns] != \
            [(c.name, c.dtype) for c in want.columns]:
        engine.alter_region_schema(rrid, want)
        region = engine.region(rrid)
    return region


def drop_companions(engine, raw_rid: int) -> int:
    """Drop every companion region of `raw_rid` (DROP/TRUNCATE TABLE
    must take the planes and their coverage down with the raw data, or
    substitution would resurrect it). Returns companions dropped."""
    maint = getattr(engine, "maintenance", None)
    if maint is None:
        return 0
    from greptimedb_tpu.storage.engine import RegionRequest, RequestType

    n = 0
    for rule in list(maint.rollup_rules):
        rrid = rollup_region_id(raw_rid, rule_slot(rule.resolution_ms))
        try:
            engine.region(rrid)
        except KeyError:
            try:
                engine.open_region(rrid)
            except Exception:  # noqa: BLE001 — no companion on disk
                continue
        region = engine.region(rrid)
        store = region.manifest.store
        region_dir = region.region_dir
        engine.handle_request(RegionRequest(RequestType.DROP, rrid))
        # erase coverage + manifest so a future companion at this id
        # starts clean instead of replaying ghost file entries
        state_path = _state_path(region_dir)
        try:
            store.delete(state_path)
        except Exception:  # noqa: BLE001
            pass
        try:
            for key in list(store.list(
                    os.path.join(region_dir, "manifest") + os.sep)):
                store.delete(key)
        except Exception:  # noqa: BLE001
            pass
        with _state_lock:
            _state_cache.pop(state_path, None)
            _state_cache.pop(f"open-miss:{rrid}", None)
        n += 1
    if n:
        _bump_substitution_state()
    return n


def _late_data_since(region, lo: int, hi: int, as_of_seq: int) -> bool:
    """Any raw source newer than `as_of_seq` overlapping [lo, hi)?
    Metadata-only: SST (max_seq, ts range) + memtable extent. `as_of_seq`
    is a next_seq snapshot, so rows with seq >= as_of_seq are late."""
    with region._lock:
        for m in region.files.values():
            if m.max_seq >= as_of_seq and m.ts_max >= lo and m.ts_min < hi:
                return True
        mem = region.memtable
        if mem.ts_min is not None and mem.ts_max >= lo and \
                mem.ts_min < hi and \
                getattr(mem, "max_seq", 1 << 62) >= as_of_seq:
            return True
    return False


def run_rollup_job(engine, raw_rid: int, rule_idx: int,
                   rule: RollupRule) -> dict:
    """Roll the raw region's un-covered inactive span into plane rows.
    Returns a detail dict for the job record."""
    if raw_rid & ROLLUP_RID_FLAG:
        # never roll a companion region (rollup-of-rollup would nest
        # plane regions without bound)
        return {"rows_in": 0, "rows_out": 0, "noop": True,
                "reason": "companion region"}
    region = engine.region(raw_rid)
    dtype = region.schema.time_index.dtype
    r_units = max(1, ms_to_units(rule.resolution_ms, dtype))
    extent = region.ts_extent()
    if extent is None:
        return {"rows_in": 0, "rows_out": 0, "noop": True,
                "reason": "empty region"}
    data_lo, data_hi = extent
    # the bucket holding the newest raw timestamp is the ACTIVE window:
    # it keeps taking writes, so it stays raw-only until it goes quiet
    cutoff = (data_hi // r_units) * r_units
    floor_lo = (data_lo // r_units) * r_units
    rollup_region = _ensure_rollup_region(engine, region, rule_idx, rule)
    store = region.store if region.store is not None \
        else rollup_region.manifest.store
    # snapshot the sequence BEFORE the staleness check: a write landing
    # between the check and the snapshot must read as late (seq >=
    # as_of) next time, not be silently claimed as covered
    as_of_seq = region.next_seq
    state = read_state(store, rollup_region.region_dir)
    expired_lo = None
    if state is not None and state.get("resolution_units") == r_units:
        # never roll below the retention horizon: data under it is
        # being TTL'd away, and claiming coverage there would resurrect
        # expired rows through substitution
        expired_lo = state.get("expired_lo")
        if expired_lo is not None:
            floor_lo = max(floor_lo, int(expired_lo))
    lo = floor_lo
    cov_lo_out = floor_lo
    if state is not None and state.get("resolution_units") == r_units:
        covered_lo, covered_hi = state["cov_lo"], state["cov_hi"]
        if floor_lo >= covered_lo and not _late_data_since(
                region, covered_lo, covered_hi,
                state.get("as_of_seq", -1)):
            # coverage is still authoritative: only extend forward, and
            # never CLAIM below what was actually rolled
            lo = max(floor_lo, covered_hi)
            cov_lo_out = covered_lo
        # else: late writes landed inside the covered span, or older
        # data appeared BELOW it — re-roll the whole inactive span so
        # the claimed coverage is really aggregated; LWW on
        # (tags, bucket) overwrites
    if lo >= cutoff:
        return {"rows_in": 0, "rows_out": 0, "noop": True,
                "reason": "no inactive span", "cutoff": int(cutoff)}
    scan = region.scan(ts_range=(int(lo), int(cutoff)))
    rows_out = 0
    batch = None
    if scan is not None and scan.num_rows:
        batch = _aggregate(region, scan, rule, r_units,
                           int(lo), int(cutoff))
    # a re-roll must also TOMBSTONE plane rows whose group vanished
    # (every raw row deleted, or a colliding old resolution's buckets):
    # LWW overwrite alone would let substituted aggregates resurrect
    # deleted data forever
    stale = _delete_stale_planes(rollup_region, int(lo), int(cutoff),
                                 batch)
    wrote = stale > 0
    if batch is not None and batch.num_rows:
        rows_out = batch.num_rows
        rollup_region.write(batch)
        wrote = True
    if wrote:
        rollup_region.flush()
        from greptimedb_tpu.fault import FAULTS

        # chaos seam: crash between the durable plane SST and the
        # coverage-state swap — coverage stays un-advanced, the next
        # run overwrites the rows (idempotent)
        FAULTS.fire("maintenance.job", op="rollup", phase="swap")
    new_state = {
        "raw_region_id": raw_rid,
        "resolution_units": int(r_units),
        "resolution_ms": int(rule.resolution_ms),
        "cov_lo": int(cov_lo_out),
        "cov_hi": int(cutoff),
        "as_of_seq": int(as_of_seq),
    }
    if expired_lo is not None:
        new_state["expired_lo"] = int(expired_lo)
    write_state(store, rollup_region.region_dir, new_state)
    return {"rows_in": 0 if scan is None else int(scan.num_rows),
            "rows_out": int(rows_out), "lo": int(lo),
            "cutoff": int(cutoff)}


def _delete_stale_planes(rollup_region, lo: int, hi: int,
                         new_batch) -> int:
    """Tombstone companion rows in [lo, hi) whose (tags, bucket) key is
    not re-produced by `new_batch`. Returns the number of keys deleted.
    Re-deleting an already-dead key is harmless (LWW), so this works
    from the raw (pre-dedup) companion scan."""
    import numpy as np  # noqa: F811 — local for clarity

    from greptimedb_tpu.datatypes.recordbatch import RecordBatch
    from greptimedb_tpu.datatypes.types import SemanticType
    from greptimedb_tpu.datatypes.vector import DictVector
    from greptimedb_tpu.storage.region import OP_DELETE

    scan = rollup_region.scan(ts_range=(lo, hi))
    if scan is None or not scan.num_rows:
        return 0
    schema = rollup_region.schema
    tag_names = [c.name for c in schema.tag_columns]
    ts_name = schema.time_index.name

    def batch_keys():
        if new_batch is None or not new_batch.num_rows:
            return set()
        cols = []
        for t in tag_names:
            v = new_batch.columns[t]
            cols.append(v.decode() if isinstance(v, DictVector)
                        else np.asarray(v, dtype=object))
        ts = np.asarray(new_batch.columns[ts_name], dtype=np.int64)
        return {tuple(list(vals) + [int(b)])
                for *vals, b in zip(*cols, ts.tolist())}

    keep = batch_keys()
    tag_vals = []
    for t in tag_names:
        d = scan.tag_dicts[t]
        codes = np.asarray(scan.columns[t])
        tag_vals.append([None if c < 0 else d[c] for c in codes.tolist()])
    ts_vals = np.asarray(scan.columns[ts_name], dtype=np.int64).tolist()
    stale = sorted({k for k in (
        tuple(list(vals) + [int(b)])
        for *vals, b in zip(*tag_vals, ts_vals)) if k not in keep},
        key=lambda k: tuple(map(str, k)))
    if not stale:
        return 0
    cols: dict = {}
    for i, t in enumerate(tag_names):
        cols[t] = DictVector.encode([k[i] for k in stale])
    cols[ts_name] = np.asarray([k[-1] for k in stale], dtype=np.int64)
    for c in schema.columns:
        if c.semantic is SemanticType.FIELD:
            fill = np.nan if c.dtype.is_float else 0
            cols[c.name] = np.full(len(stale), fill,
                                   dtype=c.dtype.to_numpy())
    rollup_region.write(RecordBatch(schema, cols), OP_DELETE)
    return len(stale)


def _aggregate(region, scan, rule: RollupRule, r_units: int,
               lo: int, hi: int):
    """ScanData (raw, needs dedup) -> one plane RecordBatch covering
    [lo, hi) only — the scan may have served a WIDER cached snapshot
    (covering-range widening), and active-window rows must not leak
    into the planes."""
    import jax
    import jax.numpy as jnp

    from greptimedb_tpu.datatypes.recordbatch import RecordBatch
    from greptimedb_tpu.datatypes.vector import DictVector
    from greptimedb_tpu.ops.dedup import sort_dedup
    from greptimedb_tpu.ops.segment import combine_group_ids

    schema = region.schema
    ts_name = schema.time_index.name
    tag_names = [c.name for c in schema.tag_columns]
    n = scan.num_rows

    # 1. last-write-wins dedup + tombstone apply (the same device kernel
    # compaction and query-time dedup run)
    sizes = [max(len(scan.tag_dicts[t]), 1) + 1 for t in tag_names]
    if tag_names:
        sid = combine_group_ids(
            [jnp.asarray(scan.columns[t] + 1) for t in tag_names], sizes,
            dtype=jnp.int64)
    else:
        sid = jnp.zeros(n, dtype=jnp.int64)
    ts_all = np.asarray(scan.columns[ts_name])
    in_range = jnp.asarray((ts_all >= lo) & (ts_all < hi))
    order, keep = sort_dedup(
        sid, jnp.asarray(ts_all), jnp.asarray(scan.seq),
        jnp.asarray(scan.op_type), in_range,
        keep_tombstones=False)
    idx = np.asarray(order)[np.asarray(keep)]
    if len(idx) == 0:
        return None
    ts = ts_all[idx]
    bucket = (ts // r_units) * r_units

    # 2. factorize (tags..., bucket) -> contiguous segment ids
    key_cols = [np.asarray(scan.columns[t])[idx] for t in tag_names]
    key_cols.append(bucket)
    keys = np.stack([np.asarray(k, dtype=np.int64) for k in key_cols],
                    axis=1)
    uniq, inverse = np.unique(keys, axis=0, return_inverse=True)
    num_groups = len(uniq)
    seg = jnp.asarray(inverse, dtype=jnp.int32)

    cols: dict = {}
    for i, t in enumerate(tag_names):
        d = scan.tag_dicts[t]
        codes = uniq[:, i]
        vals = [None if c < 0 else d[c] for c in codes.tolist()]
        cols[t] = DictVector.encode(vals)
    cols[ts_name] = uniq[:, -1].astype(np.int64)

    ones = jnp.ones(len(idx), dtype=jnp.int64)
    rows_per = jax.ops.segment_sum(ones, seg, num_segments=num_groups)
    cols[ROWS_COL] = np.asarray(rows_per, dtype=np.int64)

    for f in plane_fields(schema, rule):
        v = np.asarray(scan.columns[f.name])[idx]
        vj = jnp.asarray(v, dtype=jnp.float64)
        isnan = jnp.isnan(vj) if f.dtype.is_float \
            else jnp.zeros(len(idx), dtype=bool)
        valid = ~isnan
        count = jax.ops.segment_sum(valid.astype(jnp.int64), seg,
                                    num_segments=num_groups)
        total = jax.ops.segment_sum(jnp.where(valid, vj, 0.0), seg,
                                    num_segments=num_groups)
        vmin = jax.ops.segment_min(jnp.where(valid, vj, jnp.inf), seg,
                                   num_segments=num_groups)
        vmax = jax.ops.segment_max(jnp.where(valid, vj, -jnp.inf), seg,
                                   num_segments=num_groups)
        cnt = np.asarray(count, dtype=np.int64)
        empty = cnt == 0
        np_min = np.where(empty, np.nan,
                          np.asarray(vmin, dtype=np.float64))
        np_max = np.where(empty, np.nan,
                          np.asarray(vmax, dtype=np.float64))
        np_sum = np.where(empty, np.nan,
                          np.asarray(total, dtype=np.float64))
        out_dtype = f.dtype.to_numpy()
        if f.dtype.is_float:
            cols[f"{f.name}__min"] = np_min.astype(out_dtype)
            cols[f"{f.name}__max"] = np_max.astype(out_dtype)
        else:
            cols[f"{f.name}__min"] = np.where(empty, 0, np_min).astype(
                out_dtype)
            cols[f"{f.name}__max"] = np.where(empty, 0, np_max).astype(
                out_dtype)
        cols[f"{f.name}__sum"] = np_sum
        cols[f"{f.name}__count"] = cnt
    return RecordBatch(rollup_schema(schema, rule), cols)


# ---- query-time substitution -----------------------------------------------


def substitution_enabled() -> bool:
    return os.environ.get("GTPU_ROLLUP_SUBSTITUTE", "1").lower() \
        not in ("0", "false", "off")


def _conjuncts(e) -> list:
    from greptimedb_tpu.query.expr import split_conjuncts

    return split_conjuncts(e)


def _where_ok(where, schema) -> bool:
    """WHERE must be a conjunction of (a) range comparisons between the
    time index and a literal (never '=' — an instant predicate is not
    expressible over bucket rows) and (b) predicates touching only tag
    columns, which evaluate identically on rollup rows (every raw row of
    a (tags, bucket) group shares its tag values)."""
    from greptimedb_tpu.query.expr import collect_columns
    from greptimedb_tpu.sql import ast

    ts_name = schema.time_index.name
    tag_names = {c.name for c in schema.tag_columns}
    for atom in _conjuncts(where):
        refs: set = set()
        collect_columns(atom, refs)
        if ts_name not in refs:
            if refs <= tag_names:
                continue
            return False
        # time-index atom: one comparison or BETWEEN against literals
        if isinstance(atom, ast.Between) and not atom.negated and \
                isinstance(atom.expr, ast.Column) and \
                atom.expr.name == ts_name and \
                isinstance(atom.low, ast.Literal) and \
                isinstance(atom.high, ast.Literal):
            continue
        if isinstance(atom, ast.BinaryOp) and \
                atom.op in ("<", "<=", ">", ">="):
            lc, rc = atom.left, atom.right
            if (isinstance(lc, ast.Column) and lc.name == ts_name
                    and isinstance(rc, ast.Literal)) or \
               (isinstance(rc, ast.Column) and rc.name == ts_name
                    and isinstance(lc, ast.Literal)):
                continue
        return False
    return True


def _group_keys_ok(sel, info, r_units_of) -> Optional[list]:
    """Validate group keys (tags and/or aligned date_bin on the time
    index). Returns the list of bucket steps in column units (possibly
    empty), or None when ineligible."""
    from greptimedb_tpu.query import planner as _planner
    from greptimedb_tpu.query.expr import PlanError, _interval_in_col_unit
    from greptimedb_tpu.sql import ast

    schema = info.schema
    ts_name = schema.time_index.name
    tag_names = {c.name for c in schema.tag_columns}
    items = [(it.alias or _planner._default_name(it.expr), it.expr)
             for it in sel.items]
    alias_map = {name: expr for name, expr in items}
    steps: list[int] = []
    for g in sel.group_by:
        try:
            g = _planner._resolve_group_expr(g, items, alias_map)
        except PlanError:
            return None
        if isinstance(g, ast.Column) and g.name in tag_names:
            continue
        if isinstance(g, ast.FuncCall) and \
                g.name in ("date_bin", "time_bucket") and \
                len(g.args) in (2, 3) and \
                isinstance(g.args[1], ast.Column) and \
                g.args[1].name == ts_name:
            try:
                step = _interval_in_col_unit(g.args[0], g.args[1], schema)
            except Exception:  # noqa: BLE001 — unparseable interval
                return None
            origin = 0
            if len(g.args) == 3:
                if not isinstance(g.args[2], ast.Literal):
                    return None
                try:
                    origin = int(g.args[2].value)
                except (TypeError, ValueError):
                    return None
            r = r_units_of
            if step <= 0 or step % r or origin % r:
                return None
            steps.append(int(step))
            continue
        return None
    return steps


def _rewrite_aggs(sel, info, rule: RollupRule):
    """Rewrite every aggregate call over the raw table into its plane
    equivalent; returns the rewritten Select or None when any aggregate
    has no plane form. Output column names are preserved (the rewrite is
    invisible to the client)."""
    from greptimedb_tpu.query import planner as _planner
    from greptimedb_tpu.query.engine import _rewrite_tree
    from greptimedb_tpu.query.expr import collect_aggregates
    from greptimedb_tpu.sql import ast

    schema = info.schema
    plane_names = {c.name for c in plane_fields(schema, rule)}
    float_planes = {c.name for c in plane_fields(schema, rule)
                    if c.dtype.is_float}

    calls: list = []
    for it in sel.items:
        collect_aggregates(it.expr, calls)
    collect_aggregates(sel.having, calls)
    for o in sel.order_by:
        collect_aggregates(o.expr, calls)
    if not calls:
        return None

    def plane_agg(func: str, col: str) -> ast.Expr:
        return ast.FuncCall(func, (ast.Column(col),))

    def plane_count(col: str) -> ast.Expr:
        # sum over ZERO plane rows is NaN; raw count over zero rows is
        # 0 — coalesce before the integer cast (NaN->int is garbage)
        return ast.Cast(
            ast.FuncCall("coalesce",
                         (plane_agg("sum", col), ast.Literal(0))),
            "bigint")

    replacements: dict = {}
    for call in calls:
        if call in replacements:
            continue
        if call.distinct or call.order_within is not None \
                or call.over is not None:
            # window calls are diverted before substitution, but guard
            # anyway: rewriting sum(v) OVER () to a plain aggregate
            # would change the result SHAPE, not just the value
            return None
        fname = call.name.lower()
        if fname in ("count",) and len(call.args) == 1 and \
                isinstance(call.args[0], ast.Star):
            replacements[call] = plane_count(ROWS_COL)
            continue
        if len(call.args) != 1 or not isinstance(call.args[0], ast.Column):
            return None
        col = call.args[0].name
        if col not in plane_names:
            return None
        if fname == "min":
            replacements[call] = plane_agg("min", f"{col}__min")
        elif fname == "max":
            replacements[call] = plane_agg("max", f"{col}__max")
        elif fname == "count":
            replacements[call] = plane_count(f"{col}__count")
        elif fname == "sum" and col in float_planes:
            replacements[call] = plane_agg("sum", f"{col}__sum")
        elif fname in ("avg", "mean") and col in float_planes:
            replacements[call] = ast.BinaryOp(
                "/", plane_agg("sum", f"{col}__sum"),
                plane_agg("sum", f"{col}__count"))
        else:
            return None

    def leaf(e):
        if isinstance(e, ast.FuncCall) and e in replacements:
            return replacements[e]
        return NotImplemented

    new_items = [
        dataclasses.replace(
            it, expr=_rewrite_tree(it.expr, leaf),
            alias=it.alias or _planner._default_name(it.expr))
        for it in sel.items
    ]
    return dataclasses.replace(
        sel,
        items=new_items,
        having=_rewrite_tree(sel.having, leaf) if sel.having else None,
        order_by=[dataclasses.replace(o, expr=_rewrite_tree(o.expr, leaf))
                  for o in sel.order_by],
    )


def _companion_state(engine, region, rid: int, rule_idx: int,
                     r_units: int):
    """Locate `rid`'s rollup companion at this rule slot and read its
    coverage state. Returns (rollup_rid, state) or (None, None) when no
    companion with matching-resolution coverage exists. Shares the
    negative-open TTL cache with the query path (an absent rollup must
    not cost a manifest probe per region per rule per query)."""
    rrid = rollup_region_id(rid, rule_idx)
    try:
        engine.region(rrid)
    except KeyError:
        miss_key = f"open-miss:{rrid}"
        now = time.monotonic()
        with _state_lock:
            hit = _state_cache.get(miss_key)
        if hit is not None and hit[0] > now:
            return None, None
        try:
            engine.open_region(rrid)
        except Exception:  # noqa: BLE001 — no rollup yet
            with _state_lock:
                _state_cache[miss_key] = (now + _STATE_TTL_S, None)
            return None, None
    rollup_region = engine.region(rrid)
    store = region.store if region.store is not None \
        else rollup_region.manifest.store
    state = read_state(store, rollup_region.region_dir)
    if state is None or state.get("resolution_units") != r_units:
        return None, None
    return rrid, state


def probe_region_rollups(engine, region_id: int, lo: int,
                         hi: int) -> list:
    """Datanode-side rollup eligibility probe — the Partial half of
    DISTRIBUTED substitution. For each configured rule, answer whether
    this region's companion fully covers [lo, hi) with no late raw
    writes. Returns [{"resolution_ms", "rollup_rid", "fields"}] sorted
    coarsest-first; the frontend intersects the per-region answers,
    rewrites the aggregates to plane form, and ships ordinary
    partial-agg PlanFragments to the COMPANION regions — [G, F] planes
    come back, never raw rows (the cluster-mode analog of the local
    `try_substitute` fast path)."""
    from greptimedb_tpu.storage.region import Region

    maint = getattr(engine, "maintenance", None)
    if maint is None or not maint.rollup_rules or \
            not substitution_enabled():
        return []
    try:
        region = engine.region(region_id)
    except Exception:  # noqa: BLE001 — not open here (stale route)
        return []
    if not isinstance(region, Region):
        return []
    dtype = region.schema.time_index.dtype
    out = []
    for rule in sorted(maint.rollup_rules, key=lambda r: -r.resolution_ms):
        rule_idx = rule_slot(rule.resolution_ms)
        r_units = max(1, ms_to_units(rule.resolution_ms, dtype))
        if lo % r_units or hi % r_units:
            continue
        rrid, state = _companion_state(engine, region, region_id,
                                       rule_idx, r_units)
        if rrid is None:
            continue
        if not (state["cov_lo"] <= lo and hi <= state["cov_hi"]):
            continue
        if _late_data_since(region, lo, hi, state.get("as_of_seq", -1)):
            continue
        out.append({"resolution_ms": int(rule.resolution_ms),
                    "rollup_rid": int(rrid),
                    "fields": list(rule.fields)})
    return out


def try_substitute(qe, sel, info, ctx, shape_note=None):
    """Serve an eligible aggregate SELECT from rollup planes instead of
    raw SSTs. Returns a QueryResult, or None to fall through to the raw
    path. Never raises for ineligibility — any doubt means raw.

    `shape_note` (optional dict): on a None return,
    shape_note["memoizable"] says whether the fall-through was
    STRUCTURAL — no parameter values could make this statement shape
    substitute under the current rollup state. The frontend plan cache
    may memoize only those: coverage/alignment/late-data failures
    depend on the query's literal values (one probe over the live
    uncovered hour must not disable substitution for the same shape
    over fully-rolled history)."""
    from greptimedb_tpu.query.expr import extract_ts_bounds
    from greptimedb_tpu.query.planner import plan_select
    from greptimedb_tpu.storage.region import Region

    if shape_note is not None:
        shape_note["memoizable"] = True
    engine = qe.region_engine
    maint = getattr(engine, "maintenance", None)
    if maint is None or not maint.rollup_rules:
        # distributed frontend: no local maintenance plane, but the
        # region owners have one — classify eligibility here, probe the
        # datanodes, and serve from the companion plane regions
        if hasattr(engine, "rollup_probe") and substitution_enabled():
            return _try_substitute_distributed(qe, sel, info, ctx,
                                               shape_note)
        return None
    if not substitution_enabled():
        return None
    if sel.distinct or sel.joins or sel.ctes or sel.from_subquery is not None:
        return None
    schema = info.schema
    dtype = schema.time_index.dtype
    if not _where_ok(sel.where, schema):
        return None
    bounds = extract_ts_bounds(sel.where, schema.time_index.name, dtype)
    if bounds is None or bounds[0] is None or bounds[1] is None:
        # an unbounded scan always touches the active (raw-only) window
        return None
    lo, hi = int(bounds[0]), int(bounds[1])

    def value_dependent():
        # this shape COULD substitute with other literal values (or
        # after transient region/coverage state settles): the negative
        # outcome must not be memoized against the shape
        if shape_note is not None:
            shape_note["memoizable"] = False

    # coarsest eligible rule wins: fewest plane rows scanned
    rules = sorted(maint.rollup_rules, key=lambda r: -r.resolution_ms)
    for rule in rules:
        rule_idx = rule_slot(rule.resolution_ms)
        r_units = max(1, ms_to_units(rule.resolution_ms, dtype))
        steps = _group_keys_ok(sel, info, r_units)
        if steps is None:
            continue
        if lo % r_units or hi % r_units:
            value_dependent()
            continue
        rollup_rids = []
        ok = True
        for rid in info.region_ids:
            try:
                region = engine.region(rid)
            except Exception:  # noqa: BLE001 — remote/unroutable region
                value_dependent()  # transient routing: re-probe later
                return None
            if not isinstance(region, Region):
                return None  # frontend router: planes live datanode-side
            rrid, state = _companion_state(engine, region, rid, rule_idx,
                                           r_units)
            if rrid is None:
                ok = False
                break
            if not (state["cov_lo"] <= lo and hi <= state["cov_hi"]):
                ok = False  # THESE bounds uncovered; others may be
                value_dependent()
                break
            if _late_data_since(region, lo, hi,
                                state.get("as_of_seq", -1)):
                ok = False  # out-of-order write not yet re-rolled
                value_dependent()
                break
            rollup_rids.append(rrid)
        if not ok:
            continue
        new_sel = _rewrite_aggs(sel, info, rule)
        if new_sel is None:
            continue
        from greptimedb_tpu.catalog.catalog import TableInfo

        rollup_info = TableInfo(
            table_id=info.table_id, name=info.name, db=info.db,
            schema=rollup_schema(schema, rule), options={},
            region_ids=rollup_rids)
        try:
            plan = plan_select(new_sel, rollup_info)
            res = qe.executor.execute(plan)
        except Exception:  # noqa: BLE001 — odd rewrite / schema drift:
            value_dependent()  # any doubt: raw now, but re-probe
            continue           # the raw path is always correct
        from greptimedb_tpu.utils.metrics import ROLLUP_SUBSTITUTIONS

        ROLLUP_SUBSTITUTIONS.inc(table=info.name,
                                 resolution_ms=rule.resolution_ms)
        qe.executor.last_path = (qe.executor.last_path or "") + "+rollup"
        return res
    return None


def _try_substitute_distributed(qe, sel, info, ctx, shape_note=None):
    """Cluster-mode rollup substitution: the frontend classifies shape
    eligibility, fans a `rollup_probe` to each raw region's owner, and
    — when every region's companion covers the window at a common
    resolution — re-plans over the COMPANION region ids. The multi-
    region executor then ships ordinary partial-agg PlanFragments to
    the plane regions, so what crosses the wire is [G, F] partial
    planes over pre-aggregated rows, not raw scans (this used to fall
    back to a full raw-row gather — the known biggest cluster-mode
    perf cliff, ROADMAP item 3)."""
    from greptimedb_tpu.query.expr import extract_ts_bounds
    from greptimedb_tpu.query.planner import plan_select

    # structural gates first (mirroring the local path): a shape that
    # fails THESE can be memoized as ineligible — no literal values or
    # coverage state could make it substitute
    if sel.distinct or sel.joins or sel.ctes or sel.from_subquery is not None:
        return None
    schema = info.schema
    dtype = schema.time_index.dtype
    if not _where_ok(sel.where, schema):
        return None
    bounds = extract_ts_bounds(sel.where, schema.time_index.name, dtype)
    if bounds is None or bounds[0] is None or bounds[1] is None:
        # structurally unbounded (the shape has no ts literals to
        # parameterize): memoizable, same as the local path
        return None
    lo, hi = int(bounds[0]), int(bounds[1])
    # from here every outcome depends on live per-region coverage
    # state: the plan cache must keep re-probing
    if shape_note is not None:
        shape_note["memoizable"] = False

    engine = qe.region_engine
    rids = list(info.region_ids)
    try:
        if len(rids) > 1:
            from concurrent.futures import ThreadPoolExecutor

            with ThreadPoolExecutor(max_workers=min(8, len(rids))) as pool:
                per_region = list(pool.map(
                    lambda rid: engine.rollup_probe(rid, lo, hi), rids))
        else:
            per_region = [engine.rollup_probe(rids[0], lo, hi)]
    except Exception:  # noqa: BLE001 — probe RPC failed: raw is correct
        return None
    # intersect: a resolution is usable only when EVERY region's
    # companion covers the window (fields must agree too — they are
    # rule config, so a disagreement means mid-rollout drift)
    common: Optional[dict] = None
    for lst in per_region:
        if lst is None:
            return None
        here = {e["resolution_ms"]: e for e in lst}
        if common is None:
            common = {k: [v] for k, v in here.items()}
        else:
            common = {k: v + [here[k]] for k, v in common.items()
                      if k in here
                      and here[k].get("fields") == v[0].get("fields")}
    if not common:
        return None
    for res_ms in sorted(common, reverse=True):  # coarsest wins
        r_units = max(1, ms_to_units(res_ms, dtype))
        if lo % r_units or hi % r_units:
            continue
        steps = _group_keys_ok(sel, info, r_units)
        if steps is None:
            continue
        rule = RollupRule(resolution_ms=int(res_ms),
                          fields=tuple(common[res_ms][0].get("fields", ())))
        new_sel = _rewrite_aggs(sel, info, rule)
        if new_sel is None:
            continue
        from greptimedb_tpu.catalog.catalog import TableInfo

        rollup_info = TableInfo(
            table_id=info.table_id, name=info.name, db=info.db,
            schema=rollup_schema(schema, rule), options={},
            region_ids=[e["rollup_rid"] for e in common[res_ms]])
        try:
            plan = plan_select(new_sel, rollup_info)
            res = qe.executor.execute(plan)
        except Exception:  # noqa: BLE001 — drift/rewrite doubt: raw wins
            continue
        from greptimedb_tpu.utils.metrics import (
            FRAGMENT_PUSHDOWNS,
            ROLLUP_SUBSTITUTIONS,
        )

        ROLLUP_SUBSTITUTIONS.inc(table=info.name, resolution_ms=res_ms)
        FRAGMENT_PUSHDOWNS.inc(mode="rollup")
        qe.executor.last_path = (qe.executor.last_path or "") + "+rollup"
        return res
    return None
