"""Bounded-queue background job scheduler (one per RegionEngine).

Mirrors the reference's flush/compaction schedulers (mito2/src/flush.rs
FlushScheduler + compaction/scheduler): maintenance runs on a small
worker pool, never on the writer thread. Jobs for the same region
serialize (the reference keeps one in-flight task per region); across
regions the pool runs jobs concurrently. The queue is bounded — when it
fills, a submission degrades to running the job inline on the caller
(backpressure with forward progress, never unbounded memory).

Priority: flush > compaction > downsample(rollup) > expiry. A stalled
writer is waiting on flush, so flush must never queue behind a day-long
rollup re-encode.
"""

from __future__ import annotations

import heapq
import itertools
import logging
import threading
import time
from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Optional

from greptimedb_tpu.utils.metrics import (
    MAINTENANCE_JOB_SECONDS,
    MAINTENANCE_JOBS,
    MAINTENANCE_QUEUE_DEPTH,
)

logger = logging.getLogger(__name__)

#: job kinds in dispatch-priority order (lower = sooner)
PRIORITY = {"flush": 0, "compact": 1, "rollup": 2, "expire": 3}

#: completed/failed jobs kept for maintenance_status / info schema
HISTORY_LIMIT = 512

_DUR_UNITS = {"ms": 1, "s": 1000, "m": 60_000, "h": 3_600_000,
              "d": 86_400_000, "w": 7 * 86_400_000}


def parse_duration_ms(spec) -> int:
    """'90s' / '1m' / '7d' / bare int (ms) -> milliseconds."""
    if isinstance(spec, (int, float)):
        return int(spec)
    s = str(spec).strip().lower()
    if not s:
        return 0
    for unit in ("ms", "w", "d", "h", "m", "s"):
        if s.endswith(unit):
            return int(float(s[: -len(unit)]) * _DUR_UNITS[unit])
    return int(float(s))


@dataclass
class Job:
    """One maintenance job: identity + lifecycle + result detail."""

    job_id: int
    kind: str  # flush | compact | rollup | expire
    region_id: int
    params: dict = field(default_factory=dict)
    state: str = "queued"  # queued | running | done | failed
    error: str = ""
    detail: dict = field(default_factory=dict)
    queued_at: float = field(default_factory=time.time)
    started_at: Optional[float] = None
    finished_at: Optional[float] = None

    @property
    def duration_ms(self) -> Optional[float]:
        if self.started_at is None or self.finished_at is None:
            return None
        return (self.finished_at - self.started_at) * 1000.0

    @property
    def terminal(self) -> bool:
        return self.state in ("done", "failed")

    def to_dict(self) -> dict:
        return {
            "job_id": self.job_id, "kind": self.kind,
            "region_id": self.region_id, "state": self.state,
            "priority": PRIORITY.get(self.kind, 9), "error": self.error,
            "detail": dict(self.detail),
            "queued_at": self.queued_at, "started_at": self.started_at,
            "finished_at": self.finished_at,
            "duration_ms": self.duration_ms,
        }


class MaintenanceScheduler:
    def __init__(self, engine, workers: int = 1, queue_size: int = 64,
                 tick_interval_s: float = 0.0, retention_ttl_ms: int = 0,
                 rollup_rules: Optional[list] = None):
        from greptimedb_tpu.maintenance.rollup import RollupRule

        self.engine = engine
        self.queue_size = max(1, queue_size)
        self.retention_ttl_ms = retention_ttl_ms
        #: configured downsample rules; identity is the RESOLUTION (the
        #: rollup region id embeds rollup.rule_slot(resolution_ms)), so
        #: order never matters. ADMIN-registered ad-hoc rules persist to
        #: the data dir and are merged back in at boot — a restart must
        #: not silently stop substituting over existing plane SSTs.
        self.rollup_rules: list[RollupRule] = [
            r if isinstance(r, RollupRule) else RollupRule.from_dict(r)
            for r in (rollup_rules or [])
        ]
        for r in self._load_adhoc_rules():
            if all(r.resolution_ms != c.resolution_ms
                   for c in self.rollup_rules):
                self.rollup_rules.append(r)
        self._check_slot_collisions(self.rollup_rules)
        self._ids = itertools.count(1)
        self._seq = itertools.count()  # FIFO tie-break inside a priority
        self._heap: list[tuple[int, int, Job]] = []
        self._jobs: "OrderedDict[int, Job]" = OrderedDict()
        self._queued_keys: dict[tuple, Job] = {}  # dedup of queued jobs
        self._busy_regions: set[int] = set()
        #: region -> thread ident of the job currently running it; lets
        #: a job's own follow-up submission detect itself (re-entrant
        #: inline execution on the submitter's busy region = deadlock)
        self._region_owner: dict[int, int] = {}
        self._cv = threading.Condition()
        self._stopping = False
        self._workers: list[threading.Thread] = []
        n = max(1, int(workers))
        for i in range(n):
            t = threading.Thread(target=self._worker_loop,
                                 name=f"gtpu-maint-{i}", daemon=True)
            t.start()
            self._workers.append(t)
        self._ticker = None
        if tick_interval_s and tick_interval_s > 0:
            self.tick_interval_s = float(tick_interval_s)
            self._ticker = threading.Thread(target=self._tick_loop,
                                            name="gtpu-maint-tick",
                                            daemon=True)
            self._ticker.start()

    # ---- submission ---------------------------------------------------------

    def submit(self, kind: str, region_id: int,
               params: Optional[dict] = None) -> Job:
        """Enqueue a job and return it immediately (async). An identical
        (kind, region, params) job already queued is returned instead of
        double-queued — repeated auto-flush triggers while one flush is
        pending collapse to one job. When the queue is full the job runs
        INLINE on the caller (bounded queue, forward progress)."""
        if kind not in PRIORITY:
            raise ValueError(f"unknown maintenance job kind {kind!r} "
                             f"(have: {sorted(PRIORITY)})")
        params = params or {}
        key = (kind, region_id, tuple(sorted(params.items())))
        inline = False
        with self._cv:
            if self._stopping:
                raise RuntimeError("maintenance scheduler is stopped")
            dup = self._queued_keys.get(key)
            if dup is not None:
                return dup
            job = Job(job_id=next(self._ids), kind=kind,
                      region_id=region_id, params=params)
            self._remember(job)
            if len(self._heap) >= self.queue_size:
                inline = True  # full: degrade to caller-side execution
                # detail is REBOUND, never mutated: to_dict() snapshots
                # it without the scheduler lock
                job.detail = {**job.detail, "inline": True}
            else:
                heapq.heappush(
                    self._heap,
                    (PRIORITY[kind], next(self._seq), job))
                self._queued_keys[key] = job
                MAINTENANCE_QUEUE_DEPTH.set(len(self._heap))
                self._cv.notify_all()
        if inline:
            # inline degradation still honors per-region serialization:
            # claim the region like a worker would, or two compactions
            # could race on the same file set / coverage state
            me = threading.get_ident()
            deadline = time.monotonic() + 5.0
            with self._cv:
                claimed = False
                if self._region_owner.get(job.region_id) != me:
                    # bounded wait: a writer must never freeze behind a
                    # long-running job on this region (the re-entrant
                    # case — our own running job — never waits at all)
                    while job.region_id in self._busy_regions and \
                            time.monotonic() < deadline:
                        self._cv.wait(0.1)
                    claimed = job.region_id not in self._busy_regions
                if not claimed:
                    if self._stopping:
                        # stop() may have swept the heap while we waited
                        # — re-queueing now would strand the job
                        # 'queued' forever (wait() would never return)
                        job.state = "failed"
                        job.error = "scheduler stopped"
                        job.finished_at = time.time()
                        self._cv.notify_all()
                        return job
                    # region busy (or it's us): queue past the bound —
                    # soft overflow beats deadlock/frozen writers
                    job.detail = {k: v for k, v in job.detail.items()
                                  if k != "inline"}
                    heapq.heappush(
                        self._heap,
                        (PRIORITY[job.kind], next(self._seq), job))
                    self._queued_keys[key] = job
                    MAINTENANCE_QUEUE_DEPTH.set(len(self._heap))
                    self._cv.notify_all()
                    return job
                self._busy_regions.add(job.region_id)
                self._region_owner[job.region_id] = me
            try:
                self._run_job(job)
            finally:
                with self._cv:
                    self._busy_regions.discard(job.region_id)
                    self._region_owner.pop(job.region_id, None)
                    self._cv.notify_all()
        return job

    def _remember(self, job: Job) -> None:
        # under self._cv
        self._jobs[job.job_id] = job
        while len(self._jobs) > HISTORY_LIMIT:
            oldest = next(iter(self._jobs))
            if not self._jobs[oldest].terminal:
                break  # never forget a live job
            self._jobs.popitem(last=False)

    # ---- inspection ---------------------------------------------------------

    def job(self, job_id: int) -> Optional[Job]:
        with self._cv:
            return self._jobs.get(job_id)

    def jobs(self) -> list[Job]:
        """Newest first."""
        with self._cv:
            return list(reversed(self._jobs.values()))

    def queue_depth(self) -> int:
        with self._cv:
            return len(self._heap)

    def wait(self, job_id: int, timeout: Optional[float] = None) -> Job:
        """Block until the job reaches a terminal state (tests + inline
        callers); returns the job either way on timeout."""
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._cv:
            job = self._jobs.get(job_id)
            if job is None:
                raise KeyError(f"unknown maintenance job {job_id}")
            while not job.terminal:
                left = None if deadline is None \
                    else deadline - time.monotonic()
                if left is not None and left <= 0:
                    break
                self._cv.wait(left if left is not None else 0.5)
            return job

    def wait_idle(self, timeout: float = 30.0) -> bool:
        """Wait until no job is queued or running (tests/shutdown)."""
        deadline = time.monotonic() + timeout
        with self._cv:
            while self._heap or self._busy_regions:
                left = deadline - time.monotonic()
                if left <= 0:
                    return False
                self._cv.wait(left)
            return True

    # ---- rollup rule registry ----------------------------------------------

    @staticmethod
    def _check_slot_collisions(rules) -> None:
        """Two distinct resolutions hashing to the same companion slot
        would share one plane region and double-count each other's
        leftover buckets — refuse loudly instead of corrupting results
        (~0.2% chance per pair; pick a different resolution)."""
        from greptimedb_tpu.maintenance.rollup import rule_slot

        seen: dict[int, int] = {}
        for r in rules:
            slot = rule_slot(r.resolution_ms)
            other = seen.get(slot)
            if other is not None and other != r.resolution_ms:
                raise ValueError(
                    f"rollup resolutions {other}ms and "
                    f"{r.resolution_ms}ms collide on companion slot "
                    f"{slot}; choose a different resolution")
            seen[slot] = r.resolution_ms

    def _rules_path(self):
        data_dir = getattr(getattr(self.engine, "config", None),
                           "data_dir", None)
        if not data_dir:
            return None
        import os

        return os.path.join(data_dir, "maintenance_rules.json")

    def _load_adhoc_rules(self) -> list:
        from greptimedb_tpu.maintenance.rollup import RollupRule

        path = self._rules_path()
        if path is None:
            return []
        import json
        import os

        if not os.path.exists(path):
            return []
        try:
            with open(path, encoding="utf-8") as f:
                return [RollupRule.from_dict(d)
                        for d in json.load(f).get("rollup", [])]
        except (OSError, ValueError):
            return []

    def _persist_adhoc_rule(self, rule) -> None:
        """Record an ADMIN-registered rule next to FORMAT.json so the
        next boot keeps substituting over its plane SSTs."""
        path = self._rules_path()
        if path is None:
            return
        import json
        import os

        known = {r.resolution_ms: r for r in self._load_adhoc_rules()}
        known[rule.resolution_ms] = rule
        payload = {"rollup": [
            {"resolution_ms": r.resolution_ms, "fields": list(r.fields),
             "auto": r.auto}
            for r in known.values()]}
        tmp = f"{path}.{os.getpid()}.tmp"
        try:
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump(payload, f)
            os.replace(tmp, path)
        except OSError:
            pass  # best-effort: the rule still works this process

    def rule_for(self, resolution_ms: int):
        """(rule_slot, rule) for a resolution, registering (and
        persisting) an ad-hoc rule when no configured one matches
        (ADMIN rollup_table('t', '5m'))."""
        from greptimedb_tpu.maintenance.rollup import RollupRule, rule_slot

        with self._cv:
            for r in self.rollup_rules:
                if r.resolution_ms == resolution_ms:
                    return rule_slot(resolution_ms), r
            # auto=False: an operator's one-off ADMIN rollup must enable
            # substitution, not sign every region up for recurring
            # re-encodes on each tick
            rule = RollupRule(resolution_ms=resolution_ms, auto=False)
            self._check_slot_collisions(self.rollup_rules + [rule])
            self.rollup_rules.append(rule)
        self._persist_adhoc_rule(rule)
        return rule_slot(resolution_ms), rule

    # ---- worker pool --------------------------------------------------------

    def _pop_eligible(self) -> Optional[Job]:
        # under self._cv: highest-priority job whose region is idle.
        # The heap array only orders index 0, so scan a SORTED view —
        # otherwise a busy head could hand the slot to a lower-priority
        # sibling while an eligible flush waits
        for entry in sorted(self._heap):
            job = entry[2]
            if job.region_id not in self._busy_regions:
                self._heap.remove(entry)
                heapq.heapify(self._heap)
                key = (job.kind, job.region_id,
                       tuple(sorted(job.params.items())))
                self._queued_keys.pop(key, None)
                MAINTENANCE_QUEUE_DEPTH.set(len(self._heap))
                return job
        return None

    def _worker_loop(self) -> None:
        while True:
            with self._cv:
                job = self._pop_eligible()
                while job is None:
                    if self._stopping:
                        return
                    self._cv.wait(0.5)
                    job = self._pop_eligible()
                self._busy_regions.add(job.region_id)
                self._region_owner[job.region_id] = threading.get_ident()
            try:
                self._run_job(job)
            finally:
                with self._cv:
                    self._busy_regions.discard(job.region_id)
                    self._region_owner.pop(job.region_id, None)
                    self._cv.notify_all()

    def _run_job(self, job: Job) -> None:
        from greptimedb_tpu.fault import FAULTS

        job.state = "running"
        job.started_at = time.time()
        t0 = time.perf_counter()
        try:
            # chaos seam: a seeded schedule can fail or delay any job
            # class before it touches region state
            FAULTS.fire("maintenance.job", op=job.kind, phase="start")
            self._execute(job)
            job.state = "done"
        except Exception as e:  # noqa: BLE001 — a job must never kill a worker
            job.state = "failed"
            job.error = f"{type(e).__name__}: {e}"
            logger.warning("maintenance job %d (%s region=%d) failed: %s",
                           job.job_id, job.kind, job.region_id, job.error)
        finally:
            job.finished_at = time.time()
            MAINTENANCE_JOBS.inc(kind=job.kind, status=job.state)
            MAINTENANCE_JOB_SECONDS.observe(time.perf_counter() - t0,
                                            kind=job.kind)
            with self._cv:
                if job.state == "done" and job.params.get("auto") and \
                        job.detail.get("noop"):
                    # periodic-tick no-ops would otherwise flood the
                    # bounded history and evict real failure records
                    self._jobs.pop(job.job_id, None)
                self._cv.notify_all()

    def _execute(self, job: Job) -> None:
        from greptimedb_tpu.storage.compaction import TwcsPicker

        region = self.engine.region(job.region_id)
        if job.kind == "flush":
            meta = region.flush()
            job.detail = {**job.detail, "flushed_rows":
                          0 if meta is None else meta.num_rows}
            # TWCS follow-up: only when flushing actually tipped a
            # window over its file limit (an unconditional submission
            # would churn the queue and make ADMIN job ids racy)
            if TwcsPicker().pick(list(region.files.values())):
                self.submit("compact", job.region_id)
        elif job.kind == "compact":
            out = region.compact(strategy=job.params.get("strategy", "twcs"))
            job.detail = {**job.detail, "merged_files": len(out)}
        elif job.kind == "rollup":
            from greptimedb_tpu.maintenance.rollup import run_rollup_job

            idx, rule = self.rule_for(
                parse_duration_ms(job.params.get("resolution",
                                                 "60s")))
            job.detail = {**job.detail, **run_rollup_job(
                self.engine, job.region_id, idx, rule)}
        elif job.kind == "expire":
            from greptimedb_tpu.maintenance.retention import run_expiry

            ttl_ms = int(job.params.get("ttl_ms", 0)) \
                or self.retention_ttl_ms
            job.detail = {**job.detail, **run_expiry(region, ttl_ms)}
            if not job.detail.get("removed"):
                job.detail = {**job.detail, "noop": True}
            else:
                # raw data below the cutoff is gone: rollup coverage
                # claiming that span must retreat too, or substituted
                # aggregates would resurrect TTL-deleted rows
                self._truncate_rollup_coverage(
                    job.region_id, region, job.detail.get("cutoff"))
        else:  # pragma: no cover — submit() validates kinds
            raise ValueError(f"unknown job kind {job.kind!r}")

    def _truncate_rollup_coverage(self, rid: int, region,
                                  cutoff) -> None:
        """Raise every companion's cov_lo to the expiry cutoff (rounded
        UP to the rule's resolution: a partially-expired bucket is no
        longer fully aggregatable from raw, so it must not be served)."""
        if cutoff is None:
            return
        from greptimedb_tpu.maintenance.retention import ms_to_units
        from greptimedb_tpu.maintenance.rollup import (
            read_state,
            rollup_region_id,
            rule_slot,
            write_state,
        )

        dtype = region.schema.time_index.dtype
        for rule in list(self.rollup_rules):
            rrid = rollup_region_id(rid, rule_slot(rule.resolution_ms))
            try:
                self.engine.region(rrid)
            except KeyError:
                try:
                    self.engine.open_region(rrid)
                except Exception:  # noqa: BLE001 — no companion yet
                    continue
            companion = self.engine.region(rrid)
            store = region.store if region.store is not None \
                else companion.manifest.store
            state = read_state(store, companion.region_dir)
            if state is None or state["cov_lo"] >= cutoff:
                continue
            r_units = max(1, ms_to_units(rule.resolution_ms, dtype))
            aligned = -(-int(cutoff) // r_units) * r_units  # ceil
            state["cov_lo"] = min(aligned, state["cov_hi"])
            # TTL horizon: later rollup runs must not re-roll (and
            # re-claim) below this, or a straddling SST's ts_min would
            # read as "older data appeared" and undo the retreat with a
            # full-span re-encode on every expiry
            state["expired_lo"] = max(int(aligned),
                                      int(state.get("expired_lo", 0)))
            write_state(store, companion.region_dir, state)

    # ---- periodic tick ------------------------------------------------------

    def tick(self) -> int:
        """One maintenance sweep over every open region: submit flush for
        over-threshold memtables, compaction where a window exceeds its
        file limit, rollup for configured rules, and expiry when a TTL is
        set. Returns the number of jobs submitted. Runs from the ticker
        thread; tests call it directly."""
        from greptimedb_tpu.maintenance.rollup import ROLLUP_RID_FLAG
        from greptimedb_tpu.storage.compaction import TwcsPicker

        n = 0
        threshold = getattr(self.engine.config, "flush_threshold_bytes",
                            256 << 20)
        for rid, region in list(getattr(self.engine, "regions", {}).items()):
            try:
                if region.memtable_bytes >= threshold:
                    self.submit("flush", rid)
                    n += 1
                files = list(region.files.values())
                if len(files) > 1 and TwcsPicker().pick(files):
                    self.submit("compact", rid)
                    n += 1
                if rid & ROLLUP_RID_FLAG:
                    # companion regions get flush/compact hygiene only:
                    # rolling a rollup would nest planes without bound,
                    # and expiring planes out from under a coverage
                    # claim would serve wrong substituted results
                    continue
                for rule in list(self.rollup_rules):
                    if rule.auto:
                        self.submit("rollup", rid, {
                            "resolution": rule.resolution_ms,
                            "auto": True})
                        n += 1
                if self.retention_ttl_ms > 0:
                    self.submit("expire", rid, {"auto": True})
                    n += 1
            except Exception:  # noqa: BLE001 — a region mid-drop is fine
                continue
        return n

    def _tick_loop(self) -> None:
        while not self._stopping:
            with self._cv:
                self._cv.wait(self.tick_interval_s)
                if self._stopping:
                    return
            try:
                self.tick()
            except Exception:  # noqa: BLE001
                logger.exception("maintenance tick failed")

    # ---- lifecycle ----------------------------------------------------------

    def stop(self, timeout: float = 10.0) -> None:
        """Drain running jobs and stop the pool. Queued-but-unstarted
        jobs are dropped — flush durability is the WAL's job, and reopen
        replays it."""
        with self._cv:
            self._stopping = True
            for _, _, job in self._heap:
                job.state = "failed"
                job.error = "scheduler stopped"
                job.finished_at = time.time()
            self._heap.clear()
            self._queued_keys.clear()
            MAINTENANCE_QUEUE_DEPTH.set(0)
            self._cv.notify_all()
        deadline = time.monotonic() + timeout
        for t in self._workers:
            t.join(max(0.1, deadline - time.monotonic()))
