from .failure_detector import PhiAccrualFailureDetector
from .instruction import Instruction, InstructionKind
from .metasrv import Metasrv, MetasrvOptions
from .route import RegionRoute, TableRoute

__all__ = [
    "Instruction",
    "InstructionKind",
    "Metasrv",
    "MetasrvOptions",
    "PhiAccrualFailureDetector",
    "RegionRoute",
    "TableRoute",
]
