"""Distributed DDL as journaled, crash-recoverable procedures.

Mirrors the reference's `DdlManager` (common/meta/src/ddl_manager.rs) and
its per-statement procedures (common/meta/src/ddl/{create_table,
drop_table,alter_table}.rs): every DDL that touches more than one party
(catalog KV + N datanodes + route table) runs as a persistent state
machine via the shared procedure framework, so a coordinator crash
mid-DDL resumes — or rolls back — instead of leaving regions without
metadata (or metadata without regions).

Phase discipline (matching the reference's ordering):
- CREATE allocates ids, then creates regions on datanodes, and only then
  commits the catalog name entry (the compare-and-put is the commit
  point) — a crash before commit leaves only orphan regions, which
  rollback or the retried procedure cleans up; readers never see a
  half-created table.
- DROP removes the catalog entry FIRST (new queries fail fast), then
  drops regions and routes; every later phase is idempotent.
- ALTER updates region schemas first, then commits catalog metadata:
  regions accept the superset schema while the catalog still serves the
  old one, which is read-compatible.
"""

from __future__ import annotations

from typing import Optional

from greptimedb_tpu.catalog.catalog import CatalogError
from greptimedb_tpu.datatypes.schema import Schema
from greptimedb_tpu.procedure import Procedure, ProcedureManager, Status


class DdlError(Exception):
    pass


class CreateTableProcedure(Procedure):
    type_name = "ddl/create_table"

    def __init__(self, deps, state: dict):
        super().__init__(state)
        self.deps = deps

    def step(self, ctx) -> Status:
        s = self.state
        phase = s.setdefault("phase", "prepare")
        catalog, router = self.deps.catalog, self.deps.router
        if phase == "prepare":
            if catalog.table_exists(s["db"], s["name"]):
                if s.get("if_not_exists"):
                    s["phase"] = "done_existing"
                    return Status.finished({"existing": True})
                raise DdlError(f"table {s['db']}.{s['name']} already exists")
            # allocate ids once; a crash right after incr burns a table id,
            # which is harmless (reference sequences behave the same)
            if "table_id" not in s:
                s["table_id"] = catalog.kv.incr("__seq/table_id", start=1023)
                n = s.get("num_regions", 1)
                s["region_ids"] = [(s["table_id"] << 32) | i
                                   for i in range(n)]
            s["phase"] = "create_regions"
            return Status.executing()
        if phase == "create_regions":
            schema = Schema.from_dict(s["schema"])
            for rid in s["region_ids"]:
                # idempotent: an existing region is a no-op create
                router.create_region(rid, schema)
            s["phase"] = "commit_metadata"
            return Status.executing()
        if phase == "commit_metadata":
            schema = Schema.from_dict(s["schema"])
            try:
                catalog.create_table(
                    s["db"], s["name"], schema,
                    options=s.get("options") or {},
                    num_regions=len(s["region_ids"]),
                    partition_rules=s.get("partition_rules"),
                    column_order=s.get("column_order"),
                    region_ids=list(s["region_ids"]),
                    table_id=s["table_id"],
                )
            except CatalogError as e:
                # re-run after a crash inside create_table: if the name
                # now maps to OUR table id the commit already happened
                tid = catalog.kv.get(f"__table_name/{s['db']}/{s['name']}")
                if tid is None or int(tid) != s["table_id"]:
                    raise DdlError(str(e)) from None
            s["phase"] = "done"
            return Status.finished({"table_id": s["table_id"],
                                    "region_ids": s["region_ids"]})
        return Status.finished()

    def rollback(self, ctx) -> None:
        """Undo a create that failed before the metadata commit: drop any
        regions it managed to create (create_table.rs rollback analog)."""
        s = self.state
        if s.get("phase") in (None, "prepare", "done", "done_existing"):
            return
        for rid in s.get("region_ids", []):
            try:
                self.deps.router.drop_region(rid)
            except Exception:  # noqa: BLE001 — best-effort, region may not exist
                pass


class DropTableProcedure(Procedure):
    type_name = "ddl/drop_table"

    def __init__(self, deps, state: dict):
        super().__init__(state)
        self.deps = deps

    def step(self, ctx) -> Status:
        s = self.state
        phase = s.setdefault("phase", "deregister")
        catalog, router = self.deps.catalog, self.deps.router
        if phase == "deregister":
            try:
                info = catalog.drop_table(s["db"], s["name"],
                                          if_exists=s.get("if_exists", False))
            except CatalogError as e:
                raise DdlError(str(e)) from None
            if info is None:  # IF EXISTS on a missing table
                s["phase"] = "done"
                return Status.finished({"dropped": False})
            s["region_ids"] = list(info.region_ids)
            s["phase"] = "drop_regions"
            return Status.executing()
        if phase == "drop_regions":
            for rid in s.get("region_ids", []):
                try:
                    router.drop_region(rid)
                except Exception:  # noqa: BLE001 — already gone = idempotent
                    pass
            s["phase"] = "done"
            return Status.finished({"dropped": True})
        return Status.finished()


class AlterTableProcedure(Procedure):
    type_name = "ddl/alter_table"

    def __init__(self, deps, state: dict):
        super().__init__(state)
        self.deps = deps

    def step(self, ctx) -> Status:
        s = self.state
        phase = s.setdefault("phase", "alter_regions")
        catalog, router = self.deps.catalog, self.deps.router
        if phase == "alter_regions":
            schema = Schema.from_dict(s["new_schema"])
            for rid in s["region_ids"]:
                router.alter_region_schema(rid, schema)
            s["phase"] = "commit_metadata"
            return Status.executing()
        if phase == "commit_metadata":
            info = catalog.table(s["db"], s["name"])
            info.schema = Schema.from_dict(s["new_schema"])
            if s.get("column_order") is not None:
                info.column_order = s["column_order"]
            catalog.update_table(info)
            s["phase"] = "done"
            return Status.finished()
        return Status.finished()


class DdlManager:
    """Front door for distributed DDL (ddl_manager.rs): builds the
    procedure, submits it to the shared (persistent) procedure manager,
    and registers loaders so a recovering coordinator resumes in-flight
    DDL. One instance per cluster, living next to the metasrv's
    ProcedureManager."""

    def __init__(self, procedures: ProcedureManager, router, catalog):
        self.procedures = procedures
        self.router = router
        self.catalog = catalog
        procedures.register_loader(
            CreateTableProcedure.type_name,
            lambda st: CreateTableProcedure(self, st))
        procedures.register_loader(
            DropTableProcedure.type_name,
            lambda st: DropTableProcedure(self, st))
        procedures.register_loader(
            AlterTableProcedure.type_name,
            lambda st: AlterTableProcedure(self, st))

    def _run(self, proc: Procedure) -> dict:
        rec = self.procedures.submit(proc)
        if rec.status != "done":
            raise DdlError(
                f"{proc.type_name} {rec.status}: {rec.error or 'unknown'}")
        return rec.output or {}

    def create_table(
        self, db: str, name: str, schema: Schema,
        options: Optional[dict] = None, if_not_exists: bool = False,
        num_regions: int = 1, partition_rules: Optional[list] = None,
        column_order: Optional[list] = None,
    ):
        self._run(CreateTableProcedure(self, {
            "db": db, "name": name, "schema": schema.to_dict(),
            "options": options or {}, "if_not_exists": if_not_exists,
            "num_regions": num_regions, "partition_rules": partition_rules,
            "column_order": column_order,
        }))
        return self.catalog.table(db, name)

    def drop_table(self, db: str, name: str, if_exists: bool = False) -> bool:
        out = self._run(DropTableProcedure(
            self, {"db": db, "name": name, "if_exists": if_exists}))
        return bool(out.get("dropped"))

    def alter_table(self, db: str, name: str, new_schema: Schema,
                    region_ids: list, column_order: Optional[list] = None):
        self._run(AlterTableProcedure(self, {
            "db": db, "name": name, "new_schema": new_schema.to_dict(),
            "region_ids": list(region_ids), "column_order": column_order,
        }))
