"""Distributed DDL as journaled, crash-recoverable procedures.

Mirrors the reference's `DdlManager` (common/meta/src/ddl_manager.rs) and
its per-statement procedures (common/meta/src/ddl/{create_table,
drop_table,alter_table}.rs): every DDL that touches more than one party
(catalog KV + N datanodes + route table) runs as a persistent state
machine via the shared procedure framework, so a coordinator crash
mid-DDL resumes — or rolls back — instead of leaving regions without
metadata (or metadata without regions).

Phase discipline (matching the reference's ordering):
- CREATE allocates ids, then creates regions on datanodes, and only then
  commits the catalog name entry (the compare-and-put is the commit
  point) — a crash before commit leaves only orphan regions, which
  rollback or the retried procedure cleans up; readers never see a
  half-created table.
- DROP removes the catalog entry FIRST (new queries fail fast), then
  drops regions and routes; every later phase is idempotent.
- ALTER updates region schemas first, then commits catalog metadata:
  regions accept the superset schema while the catalog still serves the
  old one, which is read-compatible.
"""

from __future__ import annotations

from typing import Optional

from greptimedb_tpu.catalog.catalog import CatalogError
from greptimedb_tpu.datatypes.schema import Schema
from greptimedb_tpu.procedure import Procedure, ProcedureManager, Status


class DdlError(Exception):
    pass


class CreateTableProcedure(Procedure):
    type_name = "ddl/create_table"

    def __init__(self, deps, state: dict):
        super().__init__(state)
        self.deps = deps

    def step(self, ctx) -> Status:
        s = self.state
        phase = s.setdefault("phase", "prepare")
        catalog, router = self.deps.catalog, self.deps.router
        if phase == "prepare":
            if catalog.table_exists(s["db"], s["name"]):
                if s.get("if_not_exists"):
                    s["phase"] = "done_existing"
                    return Status.finished({"existing": True})
                raise DdlError(f"table {s['db']}.{s['name']} already exists")
            # allocate ids once; a crash right after incr burns a table id,
            # which is harmless (reference sequences behave the same)
            if "table_id" not in s:
                s["table_id"] = catalog.kv.incr("__seq/table_id", start=1023)
                n = s.get("num_regions", 1)
                s["region_ids"] = [(s["table_id"] << 32) | i
                                   for i in range(n)]
            s["phase"] = "create_regions"
            return Status.executing()
        if phase == "create_regions":
            # two sub-steps so placement survives a crash: (1) pin every
            # region's datanode and persist, (2) create on the PINNED
            # nodes — datanode-level create is a no-op when the region
            # exists, so retries/resumes never double-place (the selector
            # is stateful; re-selecting on retry would orphan regions)
            placements = s.setdefault("placements", {})
            missing = [rid for rid in s["region_ids"]
                       if str(rid) not in placements]
            if missing:
                if hasattr(router, "select_node"):
                    for rid in missing:
                        placements[str(rid)] = router.select_node()
                else:  # single-engine standalone: no placement concept
                    for rid in missing:
                        placements[str(rid)] = None
                return Status.executing()  # persist pins before acting
            schema = Schema.from_dict(s["schema"])
            for rid in s["region_ids"]:
                node = placements[str(rid)]
                if node is not None and hasattr(router, "create_region_on"):
                    router.create_region_on(node, rid, schema)
                else:
                    router.create_region(rid, schema)
            s["phase"] = "commit_metadata"
            return Status.executing()
        if phase == "commit_metadata":
            schema = Schema.from_dict(s["schema"])
            try:
                catalog.create_table(
                    s["db"], s["name"], schema,
                    options=s.get("options") or {},
                    num_regions=len(s["region_ids"]),
                    partition_rules=s.get("partition_rules"),
                    column_order=s.get("column_order"),
                    region_ids=list(s["region_ids"]),
                    table_id=s["table_id"],
                )
            except CatalogError as e:
                # re-run after a crash inside create_table: if the name
                # now maps to OUR table id the commit already happened
                if catalog.table_id(s["db"], s["name"]) != s["table_id"]:
                    raise DdlError(str(e)) from None
            s["phase"] = "done"
            return Status.finished({"table_id": s["table_id"],
                                    "region_ids": s["region_ids"]})
        return Status.finished()

    def rollback(self, ctx) -> None:
        """Undo a create that failed before the metadata commit: drop any
        regions it managed to create (create_table.rs rollback analog)."""
        s = self.state
        if s.get("phase") in (None, "prepare", "done", "done_existing"):
            return
        for rid in s.get("region_ids", []):
            try:
                self.deps.router.drop_region(rid)
            except Exception:  # noqa: BLE001 — best-effort, region may not exist
                pass


class DropTableProcedure(Procedure):
    type_name = "ddl/drop_table"

    def __init__(self, deps, state: dict):
        super().__init__(state)
        self.deps = deps

    def step(self, ctx) -> Status:
        s = self.state
        phase = s.setdefault("phase", "prepare")
        catalog, router = self.deps.catalog, self.deps.router
        if phase == "prepare":
            # capture region ids BEFORE touching the catalog, so a crash
            # anywhere later still knows what to clean up
            try:
                info = catalog.table(s["db"], s["name"])
            except CatalogError as e:
                if s.get("if_exists"):
                    s["phase"] = "done"
                    return Status.finished({"dropped": False})
                raise DdlError(str(e)) from None
            s["table_id"] = info.table_id
            s["region_ids"] = list(info.region_ids)
            s["phase"] = "deregister"
            return Status.executing()
        if phase == "deregister":
            try:
                catalog.drop_table(s["db"], s["name"], if_exists=False)
            except CatalogError:
                # idempotent resume: fine iff OUR table is the one gone —
                # a different table id under the same name must not be
                # dropped
                tid = catalog.table_id(s["db"], s["name"])
                if tid is not None and tid != s["table_id"]:
                    raise DdlError(
                        f"{s['db']}.{s['name']} was concurrently recreated"
                    ) from None
            s["phase"] = "drop_regions"
            return Status.executing()
        if phase == "drop_regions":
            for rid in s.get("region_ids", []):
                try:
                    router.drop_region(rid)
                except Exception:  # noqa: BLE001 — already gone = idempotent
                    pass
            s["phase"] = "done"
            return Status.finished({"dropped": True})
        return Status.finished()


class AlterTableProcedure(Procedure):
    type_name = "ddl/alter_table"

    def __init__(self, deps, state: dict):
        super().__init__(state)
        self.deps = deps

    def step(self, ctx) -> Status:
        s = self.state
        phase = s.setdefault("phase", "alter_regions")
        catalog, router = self.deps.catalog, self.deps.router
        if phase == "alter_regions":
            schema = Schema.from_dict(s["new_schema"])
            altered = s.setdefault("altered", [])
            for rid in s["region_ids"]:
                if rid in altered:
                    continue
                router.alter_region_schema(rid, schema)
                altered.append(rid)
            s["phase"] = "commit_metadata"
            return Status.executing()
        if phase == "commit_metadata":
            info = catalog.table(s["db"], s["name"])
            info.schema = Schema.from_dict(s["new_schema"])
            if s.get("column_order") is not None:
                info.column_order = s["column_order"]
            catalog.update_table(info)
            s["phase"] = "done"
            return Status.finished()
        return Status.finished()

    def rollback(self, ctx) -> None:
        """Re-apply the pre-alter schema to regions already altered, so a
        half-failed ALTER (e.g. DROP COLUMN with one datanode down) never
        leaves region schemas diverging from the catalog's."""
        s = self.state
        if s.get("phase") != "commit_metadata" and not s.get("altered"):
            return
        old = s.get("old_schema")
        if old is None:
            return
        schema = Schema.from_dict(old)
        for rid in s.get("altered", []):
            try:
                self.deps.router.alter_region_schema(rid, schema)
            except Exception:  # noqa: BLE001 — best effort per region
                pass


class DdlManager:
    """Front door for distributed DDL (ddl_manager.rs): builds the
    procedure, submits it to the shared (persistent) procedure manager,
    and registers loaders so a recovering coordinator resumes in-flight
    DDL. One instance per cluster, living next to the metasrv's
    ProcedureManager."""

    def __init__(self, procedures: ProcedureManager, router, catalog):
        self.procedures = procedures
        self.router = router
        self.catalog = catalog
        procedures.register_loader(
            CreateTableProcedure.type_name,
            lambda st: CreateTableProcedure(self, st))
        procedures.register_loader(
            DropTableProcedure.type_name,
            lambda st: DropTableProcedure(self, st))
        procedures.register_loader(
            AlterTableProcedure.type_name,
            lambda st: AlterTableProcedure(self, st))

    def _run(self, proc: Procedure) -> dict:
        rec = self.procedures.submit(proc)
        if rec.status != "done":
            raise DdlError(
                f"{proc.type_name} {rec.status}: {rec.error or 'unknown'}")
        return rec.output or {}

    def create_table(
        self, db: str, name: str, schema: Schema,
        options: Optional[dict] = None, if_not_exists: bool = False,
        num_regions: int = 1, partition_rules: Optional[list] = None,
        column_order: Optional[list] = None,
    ):
        self._run(CreateTableProcedure(self, {
            "db": db, "name": name, "schema": schema.to_dict(),
            "options": options or {}, "if_not_exists": if_not_exists,
            "num_regions": num_regions, "partition_rules": partition_rules,
            "column_order": column_order,
        }))
        return self.catalog.table(db, name)

    def drop_table(self, db: str, name: str, if_exists: bool = False) -> bool:
        out = self._run(DropTableProcedure(
            self, {"db": db, "name": name, "if_exists": if_exists}))
        return bool(out.get("dropped"))

    def alter_table(self, db: str, name: str, new_schema: Schema,
                    region_ids: list, column_order: Optional[list] = None,
                    old_schema: Optional[Schema] = None):
        self._run(AlterTableProcedure(self, {
            "db": db, "name": name, "new_schema": new_schema.to_dict(),
            "old_schema": old_schema.to_dict() if old_schema else None,
            "region_ids": list(region_ids), "column_order": column_order,
        }))
