"""Metasrv leader election over the shared KV backend.

Mirrors reference src/meta-srv/src/election/etcd.rs: a leader key under
`ELECTION_KEY` held with a lease; `campaign()` tries to acquire (or renew)
it; on lease expiry any candidate may take over via compare-and-put.
Differences from etcd are deliberate TPU-framework simplifications:

- etcd leases are server-side countdowns renewed by keep-alive streams
  (etcd.rs campaign -> keep_alive loop); here the lease deadline is stored
  *in* the leader value and checked against the caller-supplied clock, so
  election is deterministic under test (SURVEY.md §4 fake-clock strategy).
- leader-change notifications (etcd.rs leader_watcher broadcast) are
  synchronous callbacks fired from within `campaign`/`resign`.

Candidate registry mirrors CANDIDATES_ROOT (election.rs:30): every metasrv
advertises itself so `cluster_info` can list peers.
"""

from __future__ import annotations

import json
import time
from typing import Callable, Optional

from ..catalog.kv import KvBackend
from ..fault import FAULTS, FaultError

ELECTION_KEY = "__meta_election/leader"
CANDIDATES_ROOT = "__meta_election/candidates/"


class NotLeaderError(Exception):
    """Raised when a follower metasrv receives a leader-only request.
    Carries the current leader's identity so clients can redirect (the
    reference meta-client's ask-leader retry, meta-client/src/client.rs)."""

    def __init__(self, leader: Optional[str]):
        super().__init__(f"not leader (leader is {leader!r})")
        self.leader = leader


class KvElection:
    """Lease-based election: whoever CASes the leader key owns the lease
    until `lease_until_ms`; the holder renews by campaigning again before
    expiry; anyone else takes over after expiry."""

    def __init__(self, kv: KvBackend, node_id: str, lease_s: float = 3.0):
        self.kv = kv
        self.node_id = node_id
        self.lease_s = lease_s
        self._is_leader = False
        self._lease_until_ms = 0.0
        self._watchers: list[Callable[[str, str], None]] = []
        #: injectable clock skew (chaos): this node's view of "now" is
        #: shifted by this many ms — a skewed-forward node believes
        #: leases (its own included) expire early and churns elections,
        #: the Jepsen clock nemesis
        self.clock_skew_ms = 0.0

    def _resolve_now(self, now_ms: Optional[float]) -> float:
        now_ms = now_ms if now_ms is not None else time.time() * 1000
        return now_ms + self.clock_skew_ms

    def _lease_chaos(self) -> bool:
        """The election.lease fault point: a fired fault force-expires
        the held lease (models a GC pause / lost keep-alive stream —
        etcd would count the lease down server-side while this process
        was stalled). Returns True when the campaign round is lost."""
        try:
            FAULTS.fire("election.lease", node=self.node_id)
        except FaultError:
            # resign has exactly the forced-expiry semantics: zero the
            # lease on the KV so any candidate's next campaign takes
            # over immediately, and step down locally
            self.resign()
            return True
        return False

    # ------------------------------------------------------------ watchers
    def subscribe(self, fn: Callable[[str, str], None]) -> None:
        """fn(event, node_id) with event in {'elected', 'step_down'}."""
        self._watchers.append(fn)

    def _notify(self, event: str) -> None:
        for fn in self._watchers:
            fn(event, self.node_id)

    # -------------------------------------------------------------leader
    def _read(self) -> Optional[dict]:
        raw = self.kv.get(ELECTION_KEY)
        return json.loads(raw) if raw is not None else None

    def leader(self, now_ms: Optional[float] = None) -> Optional[str]:
        """Current leader's node id, or None if the lease lapsed (as
        judged by THIS node's possibly-skewed clock)."""
        now_ms = self._resolve_now(now_ms)
        cur = self._read()
        if cur is None or now_ms > cur["lease_until_ms"]:
            return None
        return cur["node"]

    def leader_hint(self) -> Optional[str]:
        """Last-known leader regardless of lease state — for redirect
        messages (a lapsed lease still names the best node to ask)."""
        cur = self._read()
        return cur["node"] if cur is not None else None

    def is_leader(self) -> bool:
        """Local view (the reference's AtomicBool is_leader) — authoritative
        only immediately after campaign()/resign()."""
        return self._is_leader

    def campaign(self, now_ms: Optional[float] = None) -> bool:
        """Try to acquire or renew leadership; returns is-leader after.
        Fires 'elected' on acquisition and 'step_down' on loss."""
        now_ms = self._resolve_now(now_ms)
        if self._lease_chaos():
            return False
        return self._campaign(now_ms)

    def _campaign(self, now_ms: float) -> bool:
        value = json.dumps(
            {"node": self.node_id, "lease_until_ms": now_ms + self.lease_s * 1000}
        )
        raw = self.kv.get(ELECTION_KEY)
        cur = json.loads(raw) if raw is not None else None
        was = self._is_leader
        won = False
        renewal = False
        if cur is None:
            won = self.kv.compare_and_put(ELECTION_KEY, None, value)
        elif cur["node"] == self.node_id:
            # renewal must CAS against the exact value we hold: if another
            # node took over and we missed it, the CAS fails and we step
            # down. Only a renewal while we still believed we led:
            # re-acquiring our own ZEROED lease (resign / chaos-forced
            # expiry) is a genuine new term and must re-fire 'elected' so
            # the leader-only bootstrap re-runs
            won = self.kv.compare_and_put(ELECTION_KEY, raw, value)
            renewal = won and was
        elif now_ms > cur["lease_until_ms"]:
            won = self.kv.compare_and_put(ELECTION_KEY, raw, value)
        self._is_leader = won
        self._lease_until_ms = now_ms + self.lease_s * 1000 if won else 0.0
        # 'elected' fires on every genuine acquisition — including a former
        # leader re-taking the key from a peer without ever having observed
        # its own loss (its local flag never flipped, but the interregnum
        # means its in-memory view is stale and must be re-inherited)
        if won and not renewal:
            self._notify("elected")
        elif was and not won:
            self._notify("step_down")
        return won

    def keep_alive(self, now_ms: Optional[float] = None) -> bool:
        """Cheap per-request renewal: campaign only once past the halfway
        point of the held lease. Every campaign is a KV compare-and-put
        whose value changes (on FileKv: a full-store rewrite + fsync), so
        calling campaign() per heartbeat would turn keep-alive into the
        dominant I/O load; this bounds it to ~2 writes per lease."""
        now_ms = self._resolve_now(now_ms)
        if self._lease_chaos():
            # forced expiry applies even mid-lease: the short-circuit
            # below must not shield a stalled leader from losing it
            return False
        if self._is_leader and \
                now_ms < self._lease_until_ms - self.lease_s * 500:
            return True
        return self._campaign(now_ms)

    def resign(self) -> None:
        """Voluntarily release leadership (etcd.rs resign): zero the lease
        so a peer's next campaign wins immediately."""
        raw = self.kv.get(ELECTION_KEY)
        if raw is None:
            cur = None
        else:
            cur = json.loads(raw)
        if cur is not None and cur["node"] == self.node_id:
            expired = json.dumps({"node": self.node_id, "lease_until_ms": 0})
            self.kv.compare_and_put(ELECTION_KEY, raw, expired)
        if self._is_leader:
            self._is_leader = False
            self._lease_until_ms = 0.0
            self._notify("step_down")

    # ---------------------------------------------------------- candidates
    def register_candidate(self, info: Optional[dict] = None) -> None:
        self.kv.put(
            CANDIDATES_ROOT + self.node_id,
            json.dumps(info or {"node": self.node_id}),
        )

    def all_candidates(self) -> list[dict]:
        return [json.loads(v) for _, v in self.kv.range(CANDIDATES_ROOT)]


class LeaderFollowClient:
    """Client-side leader following: routes leader-only calls to whichever
    metasrv currently leads, retrying once on redirect — the reference
    meta-client's AskLeader loop (src/meta-client/src/client/ask_leader.rs).

    `peers` maps node_id -> Metasrv (in-proc here; a gRPC stub in a real
    deployment — the call shape is identical)."""

    def __init__(self, peers: dict):
        self.peers = peers
        self._leader_hint: Optional[str] = None

    def leader_metasrv(self, now_ms: Optional[float] = None):
        # trust the cached hint first, then scan peers' local flags
        hint = self._leader_hint
        if hint is not None and self.peers.get(hint) is not None \
                and self.peers[hint].is_leader():
            return self.peers[hint]
        for node_id, m in self.peers.items():
            if m.is_leader():
                self._leader_hint = node_id
                return m
        raise NotLeaderError(None)

    def heartbeat(self, req, now_ms: Optional[float] = None):
        m = self.leader_metasrv(now_ms)
        resp = m.handle_heartbeat(req)
        if not resp.leader:
            self._leader_hint = resp.leader_hint
            m = self.leader_metasrv(now_ms)
            resp = m.handle_heartbeat(req)
        return resp
