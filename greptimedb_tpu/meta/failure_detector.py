"""Phi-accrual failure detector.

Mirrors reference src/meta-srv/src/failure_detector.rs:29-180: per-region
detector fed by heartbeat inter-arrival times; suspicion level phi is
-log10(P(no heartbeat for `elapsed` | history)) under a normal model of the
inter-arrival distribution (threshold/phi math at :134-179). A region whose
phi exceeds the threshold is suspected dead and triggers failover.
"""

from __future__ import annotations

import math
from collections import deque
from dataclasses import dataclass, field


@dataclass
class PhiAccrualFailureDetector:
    threshold: float = 8.0
    min_std_deviation_ms: float = 100.0
    acceptable_heartbeat_pause_ms: float = 3000.0
    first_heartbeat_estimate_ms: float = 1000.0
    max_sample_size: int = 1000
    _intervals: deque = field(default_factory=deque, repr=False)
    _last_heartbeat_ms: float | None = None
    _sum: float = 0.0
    _sum_sq: float = 0.0

    def heartbeat(self, now_ms: float) -> None:
        if self._last_heartbeat_ms is not None:
            interval = now_ms - self._last_heartbeat_ms
            self._push(interval)
        else:
            # bootstrap the window with a rough estimate (+/- one stddev),
            # as the reference does on first heartbeat
            std = self.first_heartbeat_estimate_ms / 4.0
            self._push(self.first_heartbeat_estimate_ms - std)
            self._push(self.first_heartbeat_estimate_ms + std)
        self._last_heartbeat_ms = now_ms

    def _push(self, interval: float) -> None:
        self._intervals.append(interval)
        self._sum += interval
        self._sum_sq += interval * interval
        if len(self._intervals) > self.max_sample_size:
            old = self._intervals.popleft()
            self._sum -= old
            self._sum_sq -= old * old

    @property
    def mean(self) -> float:
        n = len(self._intervals)
        return self._sum / n if n else 0.0

    @property
    def std_deviation(self) -> float:
        n = len(self._intervals)
        if n == 0:
            return self.min_std_deviation_ms
        var = max(self._sum_sq / n - self.mean**2, 0.0)
        return max(math.sqrt(var), self.min_std_deviation_ms)

    def phi(self, now_ms: float) -> float:
        """Suspicion level at `now_ms`; 0 when no heartbeats seen yet."""
        if self._last_heartbeat_ms is None:
            return 0.0
        elapsed = now_ms - self._last_heartbeat_ms
        mean = self.mean + self.acceptable_heartbeat_pause_ms
        std = self.std_deviation
        # logistic approximation to the normal CDF used by the reference
        # (failure_detector.rs:160-179): phi = -log10(1 - CDF(elapsed))
        y = (elapsed - mean) / std
        if y < -8.0:
            return 0.0  # far ahead of schedule: no suspicion
        if y > 30.0:
            return 1000.0  # saturate instead of overflowing exp
        e = math.exp(-y * (1.5976 + 0.070566 * y * y))
        if e == 0.0:
            return 1000.0  # exp underflowed: certainty of death
        if elapsed > mean:
            return -math.log10(e / (1.0 + e))
        return -math.log10(1.0 - 1.0 / (1.0 + e))

    def is_available(self, now_ms: float) -> bool:
        return self.phi(now_ms) < self.threshold
