"""Datanode-side heartbeat task and region-lease keeper.

Mirrors reference src/datanode/src/heartbeat.rs:47-183 (report RegionStats,
apply returned Instructions) and src/datanode/src/alive_keeper.rs:49-112
(`RegionAliveKeeper`: each region holds a lease countdown renewed by
heartbeat responses; when the metasrv stops renewing — e.g. the node was
failed over — the region closes itself; the split-brain guard).
"""

from __future__ import annotations

import time
from typing import Callable, Optional

from ..fault import FAULTS, FaultError
from .instruction import Instruction, InstructionKind
from .metasrv import HeartbeatRequest, HeartbeatResponse, Metasrv, RegionStat


class RegionAliveKeeper:
    """Per-datanode lease countdowns; `expired()` lists regions whose lease
    lapsed and must self-close."""

    def __init__(self):
        self._deadlines_ms: dict[int, float] = {}

    def renew(self, region_ids: list[int], deadline_ms: float) -> None:
        for rid in region_ids:
            self._deadlines_ms[rid] = deadline_ms

    def forget(self, region_id: int) -> None:
        self._deadlines_ms.pop(region_id, None)

    def expired(self, now_ms: Optional[float] = None) -> list[int]:
        now_ms = now_ms if now_ms is not None else time.time() * 1000
        return [rid for rid, dl in self._deadlines_ms.items() if now_ms > dl]


class HeartbeatTask:
    """One datanode's heartbeat loop, driven explicitly via `beat(now_ms)`.

    `stats_fn` supplies current RegionStats; `on_instruction` applies
    metasrv instructions against the local region server.
    """

    def __init__(
        self,
        node_id: str,
        metasrv: Metasrv,
        stats_fn: Callable[[], list[RegionStat]],
        on_instruction: Callable[[Instruction], None],
    ):
        self.node_id = node_id
        self.metasrv = metasrv
        self.stats_fn = stats_fn
        self.on_instruction = on_instruction
        self.alive_keeper = RegionAliveKeeper()

    def beat(self, now_ms: Optional[float] = None) -> Optional[HeartbeatResponse]:
        now_ms = now_ms if now_ms is not None else time.time() * 1000
        try:
            # src/dst make this an edge: a (node, <metasrv id>) partition
            # drops exactly this node's beats — dst is the REAL
            # coordinator identity so per-peer cuts work under HA
            # (MetaClient targets carry no node_id; they fall back to
            # the generic role name)
            FAULTS.fire("heartbeat.send", node=self.node_id,
                        src=self.node_id,
                        dst=getattr(self.metasrv, "node_id", "metasrv"))
        except FaultError:
            # dropped on the (virtual) wire: the metasrv never hears it —
            # no lease renewal, the failure detector's phi keeps climbing
            return None
        stats = self.stats_fn()
        # profiling digest rides the beat (the second rollup seam next
        # to the Flight piggyback): the metasrv aggregates a cluster
        # profile view even for nodes the frontend never scanned through
        from greptimedb_tpu.utils import flame

        profile = flame.summary(node=str(self.node_id)) \
            if flame.running() else None
        resp = self.metasrv.handle_heartbeat(
            HeartbeatRequest(node_id=self.node_id, region_stats=stats,
                             now_ms=now_ms, profile=profile)
        )
        if not resp.leader:
            # redirected by a follower: no lease grant in this response —
            # keep existing deadlines (do NOT stamp them to 0) and let the
            # caller re-ask the current leader (resp.leader_hint)
            return resp
        self.alive_keeper.renew([s.region_id for s in stats], resp.lease_deadline_ms)
        for inst in resp.instructions:
            if inst.kind == InstructionKind.CLOSE_REGION:
                self.alive_keeper.forget(inst.region_id)
            self.on_instruction(inst)
        return resp
