"""Instructions carried in heartbeat responses.

Mirrors reference src/common/meta/src/instruction.rs:182-197 — the metasrv
drives datanodes by piggybacking `Instruction`s on heartbeat acks: open/
close/downgrade/upgrade a region, invalidate frontend caches.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field


class InstructionKind(enum.Enum):
    OPEN_REGION = "open_region"
    CLOSE_REGION = "close_region"
    DOWNGRADE_REGION = "downgrade_region"
    UPGRADE_REGION = "upgrade_region"
    INVALIDATE_CACHES = "invalidate_caches"


@dataclass
class Instruction:
    kind: InstructionKind
    region_id: int = 0
    table: str = ""
    payload: dict = field(default_factory=dict)

    def to_json(self) -> dict:
        return {
            "kind": self.kind.value,
            "region_id": self.region_id,
            "table": self.table,
            "payload": self.payload,
        }

    @staticmethod
    def from_json(d: dict) -> "Instruction":
        return Instruction(
            kind=InstructionKind(d["kind"]),
            region_id=d.get("region_id", 0),
            table=d.get("table", ""),
            payload=d.get("payload", {}),
        )
