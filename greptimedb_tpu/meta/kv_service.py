"""Networked metadata plane: the metasrv process serves its KvBackend and
heartbeat pipeline over HTTP; frontends and datanodes connect with thin
clients.

This is the deployment-shaped analog of the reference's etcd-backed
metadata plane (src/common/meta/src/kv_backend/etcd.rs: every frontend /
datanode talks to a remote KV; src/meta-srv/src/election/etcd.rs: leader
election through remote CAS+lease; src/meta-client/: the RPC client every
other role embeds). The TPU-native redesign keeps the single ordered-KV
abstraction (catalog keys, table routes, procedure journal, election
leases all live in one KvBackend) and puts ONE wire in front of it:

  POST /kv/get|put|delete|range|cas     JSON bodies, the KvBackend ops
  POST /heartbeat                       datanode RegionStats -> lease +
                                        Instructions (mailbox drain)
  POST /admin/alive_nodes|node_stats|migrate_region|tick
  GET  /health

Served by `MetaHttpService` inside the metasrv process; consumed by
`HttpKv` (a KvBackend — so Catalog / TableRouteManager / ProcedureManager
/ KvElection work over the wire unchanged) and `MetaClient` (the
meta-client analog: handle_heartbeat for HeartbeatTask compatibility plus
the few admin calls frontends need).

Single-writer note: the metasrv process owns the FileKv; all remote
mutations funnel through its HTTP service, so CAS atomicity holds
process-wide (the reference gets the same from etcd transactions).
"""

from __future__ import annotations

import dataclasses
import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from ..catalog.kv import KvBackend
from .election import NotLeaderError
from .instruction import Instruction, InstructionKind
from .metasrv import (HeartbeatRequest, HeartbeatResponse, Metasrv,
                      RegionStat)

NODE_ADDR_ROOT = "__meta_node_addr/"


class NotifyingKv(KvBackend):
    """KvBackend decorator that fires subscribers on every mutation —
    wrap the metasrv's store in this BEFORE building the Metasrv so
    watch long-polls also wake for the coordinator's own writes
    (failover route swaps, procedure journal steps), not just the
    mutations that arrive over HTTP."""

    def __init__(self, inner: KvBackend):
        self.inner = inner
        self._subs: list = []

    def subscribe(self, fn) -> None:
        self._subs.append(fn)

    def _notify(self) -> None:
        for fn in self._subs:
            fn()

    def get(self, key):
        return self.inner.get(key)

    def range(self, prefix):
        return self.inner.range(prefix)

    def put(self, key, value):
        self.inner.put(key, value)
        self._notify()

    def delete(self, key):
        out = self.inner.delete(key)
        if out:
            self._notify()
        return out

    def compare_and_put(self, key, expect, value):
        ok = self.inner.compare_and_put(key, expect, value)
        if ok:
            self._notify()
        return ok


class MetaHttpService:
    """HTTP front for a Metasrv: its kv, heartbeats, and admin calls."""

    def __init__(self, metasrv: Metasrv, host: str = "127.0.0.1",
                 port: int = 0):
        self.metasrv = metasrv
        service = self
        self._addr_cache: dict[str, str] = {}
        # watch plane: a monotone service-wide revision bumped on every
        # mutation + a condition long-pollers wait on (the minimal
        # etcd-watch analog — no per-key history, watchers re-range)
        self._rev = 0
        self._rev_cond = threading.Condition()
        self._kv_notifies = isinstance(metasrv.kv, NotifyingKv)
        if self._kv_notifies:
            # coordinator-internal writes (failover route swaps, DDL
            # journal) wake watchers too — and the dispatch-level bumps
            # below are skipped so mutations don't double-wake watchers
            metasrv.kv.subscribe(self._bump)

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"  # keep-alive for client reuse
            disable_nagle_algorithm = True  # heartbeats are latency-bound

            def log_message(self, *a):  # quiet; errors surface to clients
                pass

            def _reply(self, obj, code=200):
                body = json.dumps(obj).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                if self.path == "/health":
                    self._reply({"ok": True,
                                 "leader": service.metasrv.is_leader()})
                else:
                    self._reply({"error": "not found"}, 404)

            def do_POST(self):
                n = int(self.headers.get("Content-Length", "0"))
                try:
                    req = json.loads(self.rfile.read(n) or b"{}")
                    out = service._dispatch(
                        self.path, req,
                        src=self.headers.get("X-GTPU-Src"))
                except NotLeaderError as e:
                    # structured redirect, not a bare 500: clients
                    # re-raise the typed NotLeaderError (leader hint
                    # included) instead of an opaque MetaServiceError —
                    # the meta-client ask-leader contract over the wire
                    self._reply({"error": f"NotLeaderError: {e}",
                                 "not_leader": True,
                                 "leader": e.leader}, 409)
                    return
                except Exception as e:  # noqa: BLE001 — wire boundary
                    self._reply({"error": f"{type(e).__name__}: {e}"}, 500)
                    return
                self._reply(out)

        self.httpd = ThreadingHTTPServer((host, port), Handler)
        self.httpd.daemon_threads = True
        self.port = self.httpd.server_address[1]
        self.addr = f"{host}:{self.port}"
        self._thread: Optional[threading.Thread] = None

    def _bump(self) -> None:
        with self._rev_cond:
            self._rev += 1
            self._rev_cond.notify_all()

    # ------------------------------------------------------------- dispatch
    def _dispatch(self, path: str, req: dict,
                  src: Optional[str] = None) -> dict:
        kv = self.metasrv.kv
        if path.startswith("/kv/"):
            # metadata-plane chaos seam (fault matrix: metasrv.kv): a
            # fail surfaces as HTTP 500 -> MetaServiceError at every
            # client; the op label makes injections per-op countable in
            # greptimedb_tpu_fault_injections_total. The caller's node
            # identity (X-GTPU-Src header) makes each op an edge, so a
            # (node, metasrv) partition cuts ONE role's KV access while
            # the rest of the cluster keeps talking.
            from greptimedb_tpu.fault import FAULTS

            FAULTS.fire("metasrv.kv", op=path[len("/kv/"):],
                        src=src or "?", dst=self.metasrv.node_id)
        if path == "/kv/get":
            return {"value": kv.get(req["key"])}
        if path == "/kv/put":
            kv.put(req["key"], req["value"])
            if not self._kv_notifies:
                self._bump()
            return {"ok": True}
        if path == "/kv/delete":
            deleted = kv.delete(req["key"])
            if deleted and not self._kv_notifies:
                self._bump()
            return {"deleted": deleted}
        if path == "/kv/range":
            return {"items": list(kv.range(req["prefix"]))}
        if path == "/kv/cas":
            ok = kv.compare_and_put(
                req["key"], req.get("expect"), req["value"])
            if ok and not self._kv_notifies:
                self._bump()
            return {"ok": ok}
        if path == "/kv/watch":
            # long-poll: block until the service revision passes
            # since_rev (any mutation), then return the fresh range —
            # the client diffs/re-reads (etcd-watch semantics minus
            # per-key event history)
            since = int(req.get("since_rev", 0))
            deadline = __import__("time").monotonic() + float(
                req.get("timeout_s", 30.0))
            with self._rev_cond:
                while self._rev <= since:
                    left = deadline - __import__("time").monotonic()
                    if left <= 0:
                        break
                    self._rev_cond.wait(timeout=left)
                rev = self._rev
            return {"rev": rev, "changed": rev > since,
                    "items": list(kv.range(req.get("prefix", "")))}
        if path == "/heartbeat":
            return self._heartbeat(req)
        if path == "/admin/alive_nodes":
            return {"nodes": self.metasrv.alive_nodes(req.get("now_ms"))}
        if path == "/admin/node_stats":
            return {"stats": self.metasrv.node_stats()}
        if path == "/admin/migrate_region":
            rec = self.metasrv.migrate_region(
                req["table"], req["region_id"], req["to_node"])
            return {"procedure_id": rec.procedure_id}
        if path == "/admin/tick":
            return {"started": self.metasrv.tick(req.get("now_ms"))}
        if path == "/admin/chaos_reset":
            # chaos-harness control: disarm THIS process's fault
            # registry so the explorer's final verification runs
            # chaos-free (deliberately NOT behind the metasrv.kv seam —
            # the disarm call must never be blocked by the very chaos it
            # clears)
            from greptimedb_tpu.fault import FAULTS

            FAULTS.reset()
            return {"ok": True}
        raise KeyError(f"unknown path {path}")

    def _heartbeat(self, req: dict) -> dict:
        node_id = req["node_id"]
        addr = req.get("addr")
        if addr and self._addr_cache.get(node_id) != addr:
            # registry for frontends: node_id -> Flight addr. Written
            # only on change — a FileKv put rewrites+fsyncs the store
            self.metasrv.kv.put(NODE_ADDR_ROOT + node_id, addr)
            self._addr_cache[node_id] = addr
        stats = [RegionStat(**s) for s in req.get("region_stats", [])]
        resp = self.metasrv.handle_heartbeat(HeartbeatRequest(
            node_id=node_id, region_stats=stats, now_ms=req.get("now_ms")))
        return {
            "leader": resp.leader,
            "leader_hint": resp.leader_hint,
            # the coordinator's real identity: clients adopt it as the
            # dst of their heartbeat edges, so @edge/partition specs
            # naming the metasrv's node id match over the wire too
            "node_id": self.metasrv.node_id,
            "lease_deadline_ms": resp.lease_deadline_ms,
            "instructions": [
                {"kind": i.kind.value, "region_id": i.region_id,
                 "table": i.table, "payload": i.payload}
                for i in resp.instructions
            ],
        }

    # -------------------------------------------------------------- control
    def start(self) -> int:
        self._thread = threading.Thread(target=self.httpd.serve_forever,
                                        daemon=True)
        self._thread.start()
        return self.port

    def stop(self) -> None:
        self.httpd.shutdown()
        self.httpd.server_close()


class _HttpJson:
    """Minimal keep-alive JSON-POST client (per-thread connections —
    http.client connections are not thread-safe)."""

    def __init__(self, addr: str, timeout_s: float = 10.0):
        self.host, _, port = addr.partition(":")
        self.port = int(port)
        self.timeout_s = timeout_s
        self._local = threading.local()

    def _conn(self):
        import http.client

        c = getattr(self._local, "conn", None)
        if c is None:
            c = http.client.HTTPConnection(self.host, self.port,
                                           timeout=self.timeout_s)
            self._local.conn = c
        return c

    def post(self, path: str, body: dict, idempotent: bool = True) -> dict:
        """`idempotent=False` (CAS and other effectful ops) never
        retries: a transport error after the server applied the op
        would make a blind retry observe its OWN effect and report
        failure (e.g. an election winner believing it lost) — raising
        'outcome unknown' is the honest answer."""
        from greptimedb_tpu.fault import local_node

        data = json.dumps(body).encode()
        last = None
        attempts = 2 if idempotent else 1  # reconnect on stale keep-alive
        for _ in range(attempts):
            c = self._conn()
            try:
                # identity header: lets the service's metasrv.kv fault
                # seam scope injections/partitions to one caller edge
                c.request("POST", path, body=data,
                          headers={"Content-Type": "application/json",
                                   "X-GTPU-Src": local_node()})
                r = c.getresponse()
                raw = r.read()
                if r.status != 200:
                    try:
                        err = json.loads(raw)
                    except ValueError:
                        err = {}
                    if isinstance(err, dict) and err.get("not_leader"):
                        # the follower's structured 409: surface the
                        # TYPED redirect (leader hint attached), never
                        # retried — redirecting is the caller's job
                        raise NotLeaderError(err.get("leader"))
                    raise MetaServiceError(
                        f"{path}: HTTP {r.status}: {raw[:200]!r}")
                return json.loads(raw)
            except (MetaServiceError, NotLeaderError):
                raise
            except Exception as e:  # noqa: BLE001 — transport layer
                last = e
                self._local.conn = None
                try:
                    c.close()
                except Exception:  # noqa: BLE001
                    pass
        raise MetaServiceError(f"{path}: {last}")


class MetaServiceError(Exception):
    pass


class HttpKv(KvBackend):
    """KvBackend over a MetaHttpService — the remote-KV client every
    non-metasrv role uses (reference kv_backend/etcd.rs analog)."""

    def __init__(self, addr: str, timeout_s: float = 10.0):
        self._http = _HttpJson(addr, timeout_s)

    def get(self, key):
        return self._http.post("/kv/get", {"key": key})["value"]

    def put(self, key, value):
        self._http.post("/kv/put", {"key": key, "value": value})

    def delete(self, key):
        return self._http.post("/kv/delete", {"key": key})["deleted"]

    def range(self, prefix):
        for k, v in self._http.post("/kv/range", {"prefix": prefix})["items"]:
            yield k, v

    def compare_and_put(self, key, expect, value):
        return self._http.post(
            "/kv/cas", {"key": key, "expect": expect, "value": value},
            idempotent=False)["ok"]

    def watch(self, prefix: str, since_rev: int = 0,
              timeout_s: float = 30.0) -> dict:
        """Long-poll until any mutation past `since_rev`; returns
        {"rev", "changed", "items"} — re-issue with the returned rev to
        keep watching (the etcd-watch analog frontends use for route
        invalidation instead of per-query polling).

        Dedicated one-shot connection: the server HOLDS the request up
        to `timeout_s`, so the keep-alive pool's fixed socket timeout
        would kill every idle poll."""
        import http.client

        c = http.client.HTTPConnection(self._http.host, self._http.port,
                                       timeout=timeout_s + 10.0)
        try:
            from greptimedb_tpu.fault import local_node

            c.request("POST", "/kv/watch", json.dumps(
                {"prefix": prefix, "since_rev": since_rev,
                 "timeout_s": timeout_s}).encode(),
                {"Content-Type": "application/json",
                 "X-GTPU-Src": local_node()})
            r = c.getresponse()
            raw = r.read()
            if r.status != 200:
                raise MetaServiceError(
                    f"/kv/watch: HTTP {r.status}: {raw[:200]!r}")
            return json.loads(raw)
        except MetaServiceError:
            raise
        except Exception as e:  # noqa: BLE001 — transport layer
            raise MetaServiceError(f"/kv/watch: {e}") from None
        finally:
            c.close()


class MetaClient:
    """The meta-client analog (reference src/meta-client/): heartbeats +
    admin calls against a remote metasrv. `handle_heartbeat` matches the
    in-process Metasrv signature so `HeartbeatTask` runs unchanged in a
    datanode process."""

    def __init__(self, addr: str, node_addr: Optional[str] = None,
                 timeout_s: float = 10.0,
                 metasrv_node_id: str = "metasrv"):
        self.addr = addr
        self.node_addr = node_addr  # this node's Flight addr (datanodes)
        #: the coordinator identity this client's heartbeat edges carry
        #: (HeartbeatTask reads it as dst): configure it with the remote
        #: metasrv's real node id so @edge/partition specs naming that
        #: id match over the wire; the default is the generic role name
        self.node_id = metasrv_node_id
        self._http = _HttpJson(addr, timeout_s)
        self.kv = HttpKv(addr, timeout_s)

    def handle_heartbeat(self, req: HeartbeatRequest) -> HeartbeatResponse:
        out = self._http.post("/heartbeat", {
            "node_id": req.node_id,
            "addr": self.node_addr,
            "now_ms": req.now_ms,
            "region_stats": [dataclasses.asdict(s)
                             for s in req.region_stats],
        })
        if out.get("node_id"):
            # adopt the coordinator's real identity (first beat still
            # carries the generic role default — steady-state edges
            # match the documented node-id form)
            self.node_id = out["node_id"]
        return HeartbeatResponse(
            leader=out.get("leader", True),
            leader_hint=out.get("leader_hint"),
            lease_deadline_ms=out.get("lease_deadline_ms", 0.0),
            instructions=[
                Instruction(InstructionKind(i["kind"]), i["region_id"],
                            i.get("table"), payload=i.get("payload"))
                for i in out.get("instructions", [])
            ],
        )

    def alive_nodes(self, now_ms: Optional[float] = None) -> list[str]:
        return self._http.post("/admin/alive_nodes",
                               {"now_ms": now_ms})["nodes"]

    def node_stats(self) -> dict:
        return self._http.post("/admin/node_stats", {})["stats"]

    def migrate_region(self, table: str, region_id: int,
                       to_node: str) -> str:
        return self._http.post("/admin/migrate_region", {
            "table": table, "region_id": region_id,
            "to_node": to_node})["procedure_id"]

    def tick(self, now_ms: Optional[float] = None) -> list[str]:
        """Drive the remote metasrv's virtual clock one step (the
        deterministic multi-process chaos harness beats real metasrv
        processes with explicit timestamps)."""
        return self._http.post("/admin/tick", {"now_ms": now_ms})["started"]

    def chaos_reset(self) -> None:
        """Disarm the remote process's fault registry (chaos harness)."""
        self._http.post("/admin/chaos_reset", {})

    def watch(self, prefix: str, since_rev: int = 0,
              timeout_s: float = 30.0) -> dict:
        return self.kv.watch(prefix, since_rev, timeout_s)

    def node_addrs(self) -> dict[str, str]:
        """node_id -> Flight addr registry (written on heartbeat)."""
        return {k[len(NODE_ADDR_ROOT):]: v
                for k, v in self.kv.range(NODE_ADDR_ROOT)}

    def health(self) -> bool:
        try:
            import http.client

            host, _, port = self.addr.partition(":")
            c = http.client.HTTPConnection(host, int(port), timeout=2.0)
            c.request("GET", "/health")
            ok = c.getresponse().status == 200
            c.close()
            return ok
        except Exception:  # noqa: BLE001 — health probe
            return False


class MetasrvTicker:
    """Real-clock tick loop for a deployed metasrv (the deterministic
    test harness calls tick() explicitly; a service process needs the
    wall clock to drive failure detection + failover)."""

    def __init__(self, metasrv: Metasrv, interval_s: float = 1.0):
        self.metasrv = metasrv
        self.interval_s = interval_s
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._run, daemon=True)

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.metasrv.tick()
            except Exception:  # noqa: BLE001 — tick must never die
                import traceback

                traceback.print_exc()

    def start(self) -> None:
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        self._thread.join(timeout=5)
