"""Metasrv: the metadata-plane coordinator.

Mirrors reference src/meta-srv (metasrv.rs:306 core; handler.rs heartbeat
pipeline; procedure/region_failover + region_migration state machines;
handler/region_lease_handler.rs leases). One instance coordinates N
datanodes:

- datanodes report `RegionStat`s via `handle_heartbeat` (the reference's
  gRPC heartbeat stream, datanode/src/heartbeat.rs:47-183);
- each heartbeat feeds a per-node phi-accrual failure detector
  (failure_detector.rs) and renews region leases;
- responses carry `Instruction`s (open/close/downgrade/upgrade region) and
  the lease grant — the only channel by which the metasrv drives datanodes;
- `tick(now_ms)` runs failure detection; a suspected-dead node's regions are
  failed over via a persistent `RegionFailoverProcedure`;
- `migrate_region` runs the downgrade→open-candidate→upgrade→swap-route
  handshake of procedure/region_migration/.

Deterministic by construction: no background threads — callers (or the
serve loop) drive `tick` with an explicit clock, which is what makes the
failover tests exact (SURVEY.md §4's in-memory-fake strategy).
"""

from __future__ import annotations

import json
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Optional

from ..catalog.kv import KvBackend
from ..procedure import Procedure, ProcedureManager, Status
from .election import KvElection, NotLeaderError
from .failure_detector import PhiAccrualFailureDetector
from .instruction import Instruction, InstructionKind
from .route import TableRouteManager
from .selector import SELECTORS, Selector


@dataclass
class MetasrvOptions:
    region_lease_s: float = 9.0  # reference: REGION_LEASE_SECS = 3*interval
    heartbeat_interval_s: float = 3.0  # distributed_time_constants.rs:18
    selector: str = "round_robin"
    failure_threshold: float = 8.0


@dataclass
class RegionStat:
    region_id: int
    table: str
    rows: int = 0
    sst_bytes: int = 0
    memtable_bytes: int = 0
    role: str = "leader"


@dataclass
class HeartbeatRequest:
    node_id: str
    region_stats: list[RegionStat] = field(default_factory=list)
    now_ms: Optional[float] = None
    #: optional continuous-profiling digest (utils/flame.summary()) —
    #: the heartbeat half of the cluster profile rollup
    profile: Optional[dict] = None


@dataclass
class HeartbeatResponse:
    instructions: list[Instruction] = field(default_factory=list)
    lease_deadline_ms: float = 0.0
    leader: bool = True
    leader_hint: Optional[str] = None  # who to talk to when leader=False


class Metasrv:
    def __init__(self, kv: KvBackend, opts: Optional[MetasrvOptions] = None,
                 node_id: str = "metasrv-0",
                 election: Optional[KvElection] = None):
        self.kv = kv
        self.node_id = node_id
        self.opts = opts or MetasrvOptions()
        self.routes = TableRouteManager(kv)
        self.procedures = ProcedureManager(kv)
        self.procedures.register_loader(
            RegionFailoverProcedure.type_name,
            lambda st: RegionFailoverProcedure(self, state=st),
        )
        self.procedures.register_loader(
            RegionMigrationProcedure.type_name,
            lambda st: RegionMigrationProcedure(self, state=st),
        )
        self.selector: Selector = SELECTORS[self.opts.selector]()
        self._detectors: dict[str, PhiAccrualFailureDetector] = {}
        self._node_stats: dict[str, dict] = {}
        self._node_profiles: dict[str, dict] = {}
        self._node_regions: dict[str, dict[int, RegionStat]] = {}
        self._pending: dict[str, list[Instruction]] = {}
        self._failed_over: set[str] = set()  # nodes already handled
        self._journal_meta: dict[str, tuple] = {}  # node -> (ms, regions)
        self._lock = threading.RLock()
        # cache-invalidation fanout to frontends (cache crate analog)
        self._invalidation_subs: list[Callable[[str], None]] = []
        # pub/sub plane: heartbeats fan out to subscribed components
        # (meta-srv/src/pubsub/, e.g. frontend stats caches)
        from .pubsub import SubscribeManager
        self.pubsub = SubscribeManager()
        # HA: when an election is attached, leader-only APIs are fenced and
        # a newly-elected leader resumes the shared procedure store
        # (meta-srv/src/metasrv.rs try_start leader-only bootstrap)
        self.election = election
        if election is not None:
            election.register_candidate({"node": node_id})
            election.subscribe(self._on_leader_change)

    # ------------------------------------------------------------- election
    def is_leader(self) -> bool:
        """Standalone metasrv (no election) is always the leader."""
        return self.election is None or self.election.is_leader()

    def ensure_leader(self, now_ms: Optional[float] = None) -> None:
        """Fence leader-only APIs with the authoritative KV lease check
        (same as the heartbeat path) — the local flag of a paused,
        since-deposed leader is stale until its next campaign, and route
        mutations from it would race the real leader's."""
        if self.election is None:
            return
        if self.election.leader(now_ms) != self.node_id:
            raise NotLeaderError(self.election.leader_hint())

    NODE_INFO_ROOT = "__meta_nodes/"

    def _persist_node_info(self, node_id: str, now_ms: float,
                           failed_over: bool = False) -> None:
        """Journal the node's liveness + region set to the shared KV so a
        newly-elected leader inherits cluster membership (the reference
        stores NodeInfo in the meta KV, meta-srv/src/cluster.rs).

        Throttled: a FileKv put rewrites+fsyncs the whole store, so only
        persist when the region set changed or half a lease elapsed —
        journal staleness is bounded by lease/2, well inside the failure
        detector's acceptable pause."""
        import dataclasses

        regions = frozenset(self._node_regions.get(node_id, {}))
        last_ms, last_regions = self._journal_meta.get(node_id, (-1e18, None))
        if not failed_over and regions == last_regions and \
                now_ms - last_ms < self.opts.region_lease_s * 1000 / 2:
            return
        self._journal_meta[node_id] = (now_ms, regions)
        self.kv.put(
            self.NODE_INFO_ROOT + node_id,
            json.dumps({
                "last_heartbeat_ms": now_ms,
                "failed_over": failed_over,
                "stats": self._node_stats.get(node_id, {}),
                "regions": [
                    dataclasses.asdict(s)
                    for s in self._node_regions.get(node_id, {}).values()
                ],
            }),
        )

    def _inherit_cluster_state(self) -> None:
        """Seed detectors/region maps from the KV-journaled node infos: a
        node that stops heartbeating across a coordinator failover must
        still be detected dead by the NEW leader — and a node the old
        leader ALREADY failed over must not be failed over again."""
        with self._lock:
            for key, raw in self.kv.range(self.NODE_INFO_ROOT):
                node = key[len(self.NODE_INFO_ROOT):]
                info = json.loads(raw)
                if info.get("failed_over"):
                    self._failed_over.add(node)
                    continue
                det = self._detectors.get(node)
                if det is not None and det._last_heartbeat_ms is not None \
                        and det._last_heartbeat_ms >= info["last_heartbeat_ms"]:
                    continue  # our own view is at least as fresh
                # stale or absent view (e.g. a re-elected former leader):
                # re-seed from the journal written by the last leader.
                # Bootstrap with the real heartbeat cadence — the default
                # 1s estimate plus journal staleness (<= lease/2) would
                # read a healthy 3s-cadence node as dead on arrival.
                det = PhiAccrualFailureDetector(
                    threshold=self.opts.failure_threshold,
                    first_heartbeat_estimate_ms=(
                        self.opts.heartbeat_interval_s * 1000
                    ),
                )
                det.heartbeat(info["last_heartbeat_ms"])
                self._detectors[node] = det
                self._node_stats[node] = info.get("stats", {})
                self._node_regions[node] = {
                    s["region_id"]: RegionStat(**s)
                    for s in info.get("regions", [])
                }

    def _on_leader_change(self, event: str, node_id: str) -> None:
        if event == "elected":
            # inherit membership, then resume in-flight procedures
            # journaled by the previous leader (both live in the shared KV,
            # so failover/migration state machines continue from their
            # persisted phase)
            self._inherit_cluster_state()
            self.procedures.recover()

    # ---------------------------------------------------------------- stats
    def subscribe_invalidation(self, fn: Callable[[str], None]) -> None:
        self._invalidation_subs.append(fn)

    def invalidate_caches(self, table: str) -> None:
        for fn in self._invalidation_subs:
            fn(table)

    def alive_nodes(self, now_ms: Optional[float] = None) -> list[str]:
        now_ms = now_ms if now_ms is not None else time.time() * 1000
        with self._lock:
            return sorted(
                n
                for n, d in self._detectors.items()
                if d.is_available(now_ms) and n not in self._failed_over
            )

    def node_stats(self) -> dict[str, dict]:
        with self._lock:
            return dict(self._node_stats)

    def node_profiles(self) -> dict[str, dict]:
        """Latest continuous-profiling digest per node (heartbeat-fed;
        nodes with profiling off simply never appear)."""
        with self._lock:
            return dict(self._node_profiles)

    # ------------------------------------------------------------ heartbeat
    def handle_heartbeat(self, req: HeartbeatRequest) -> HeartbeatResponse:
        """The heartbeat handler pipeline (meta-srv/src/handler.rs):
        collect_stats → failure detector feed → mailbox drain →
        region-lease renewal. Followers redirect (handler.rs is_not_leader
        check → client re-asks the leader)."""
        now_ms = req.now_ms if req.now_ms is not None else time.time() * 1000
        if self.election is not None:
            # serving heartbeats doubles as election keep-alive: a busy
            # leader must not lose the lease between ticks (the reference
            # keep-alive stream runs independently of the handler loop)
            if self.election.is_leader():
                self.election.keep_alive(now_ms)
            if self.election.leader(now_ms) != self.node_id:
                # authoritative KV check, not the local flag: a deposed
                # leader whose flag is stale must not grant leases
                # (split-brain guard)
                return HeartbeatResponse(
                    leader=False, leader_hint=self.election.leader_hint()
                )
        with self._lock:
            det = self._detectors.setdefault(
                req.node_id,
                PhiAccrualFailureDetector(threshold=self.opts.failure_threshold),
            )
            det.heartbeat(now_ms)
            # a node that re-appears after failover may rejoin empty-handed;
            # its journal entry still says failed_over=True — drop the
            # throttle memo so the clearing write below cannot be skipped
            if req.node_id in self._failed_over:
                self._failed_over.discard(req.node_id)
                self._journal_meta.pop(req.node_id, None)
            self._node_regions[req.node_id] = {s.region_id: s for s in req.region_stats}
            self._node_stats[req.node_id] = {
                "region_count": len(req.region_stats),
                "write_bytes": sum(s.memtable_bytes for s in req.region_stats),
                "last_heartbeat_ms": now_ms,
            }
            if req.profile is not None:
                self._node_profiles[req.node_id] = req.profile
            instructions = self._pending.pop(req.node_id, [])
            lease = now_ms + self.opts.region_lease_s * 1000
            if self.election is not None:
                self._persist_node_info(req.node_id, now_ms)
        from .pubsub import TOPIC_HEARTBEAT
        self.pubsub.publish(TOPIC_HEARTBEAT, req)
        return HeartbeatResponse(instructions=instructions, lease_deadline_ms=lease)

    def send_instruction(self, node_id: str, inst: Instruction) -> None:
        """Queue an instruction for the node's next heartbeat (the mailbox,
        common/meta/src/heartbeat/mailbox.rs analog)."""
        with self._lock:
            self._pending.setdefault(node_id, []).append(inst)

    # ------------------------------------------------------- failure detect
    def tick(self, now_ms: Optional[float] = None) -> list[str]:
        """Run failure detection; submit failover for newly-dead nodes.
        Returns the list of failover procedure ids started.

        With an election attached this doubles as the keep-alive loop:
        campaign (acquire or renew the lease) first; followers do nothing —
        only the leader drives failure detection and failover."""
        now_ms = now_ms if now_ms is not None else time.time() * 1000
        if self.election is not None:
            self.election.keep_alive(now_ms)
            if not self.election.is_leader():
                return []
        with self._lock:
            dead = [
                n
                for n, d in self._detectors.items()
                if not d.is_available(now_ms) and n not in self._failed_over
            ]
        started = []
        for node in dead:
            with self._lock:
                self._failed_over.add(node)
                if self.election is not None:
                    # journal the decision: a future leader inheriting the
                    # node journal must not fail this node over a second
                    # time (it would orphan the region's current holder)
                    self._persist_node_info(node, now_ms, failed_over=True)
            regions = list(self._node_regions.get(node, {}).values())
            for stat in regions:
                if stat.role != "leader":
                    continue
                proc = RegionFailoverProcedure(
                    self,
                    state={
                        "table": stat.table,
                        "region_id": stat.region_id,
                        "from_node": node,
                        "now_ms": now_ms,
                    },
                )
                rec = self.procedures.submit(proc)
                started.append(rec.procedure_id)
        return started

    # ------------------------------------------------------------ migration
    def migrate_region(self, table: str, region_id: int, to_node: str,
                       now_ms: Optional[float] = None):
        """Manual region migration (migrate_region() SQL admin function,
        common/function/src/table/migrate_region.rs). Leader-only."""
        self.ensure_leader(now_ms)
        route = self.routes.get(table)
        if route is None:
            raise KeyError(f"no route for table {table}")
        from_node = route.region(region_id).leader_node
        proc = RegionMigrationProcedure(
            self,
            state={
                "table": table,
                "region_id": region_id,
                "from_node": from_node,
                "to_node": to_node,
            },
        )
        return self.procedures.submit(proc)


class RegionFailoverProcedure(Procedure):
    """failover_start → select candidate → activate (OpenRegion instruction)
    → update route metadata → invalidate caches → end.

    Mirrors meta-srv/src/procedure/region_failover/ phase-per-step so a
    metasrv crash resumes at the persisted phase.
    """

    type_name = "region_failover"

    def __init__(self, metasrv: Metasrv, state: Optional[dict] = None):
        super().__init__(state)
        self.metasrv = metasrv
        self.state.setdefault("phase", "start")

    def step(self, ctx) -> Status:
        st = self.state
        phase = st["phase"]
        m = self.metasrv
        if phase == "start":
            # deactivate: the old node is dead; make sure it closes the
            # region if it ever comes back (split-brain guard; the lease
            # expiry on the datanode side enforces the same)
            m.send_instruction(
                st["from_node"],
                Instruction(InstructionKind.CLOSE_REGION, st["region_id"], st["table"]),
            )
            st["phase"] = "select_candidate"
            return Status.executing()
        if phase == "select_candidate":
            candidate = m.selector.select(
                m.alive_nodes(st.get("now_ms")),
                m.node_stats(),
                exclude=[st["from_node"]],
            )
            if candidate is None:
                raise RuntimeError(
                    f"no candidate datanode for region {st['region_id']}"
                )
            st["candidate"] = candidate
            st["phase"] = "activate"
            return Status.executing()
        if phase == "activate":
            m.send_instruction(
                st["candidate"],
                Instruction(
                    InstructionKind.OPEN_REGION,
                    st["region_id"],
                    st["table"],
                    payload={"replay_wal": True},
                ),
            )
            st["phase"] = "update_metadata"
            return Status.executing()
        if phase == "update_metadata":
            route = m.routes.get(st["table"])
            if route is not None:
                rr = route.region(st["region_id"])
                rr.leader_node = st["candidate"]
                rr.leader_state = "leader"
                m.routes.update(route)
            st["phase"] = "invalidate_cache"
            return Status.executing()
        if phase == "invalidate_cache":
            m.invalidate_caches(st["table"])
            st["phase"] = "end"
            return Status.finished(
                {"region_id": st["region_id"], "to_node": st["candidate"]}
            )
        return Status.finished()


class RegionMigrationProcedure(Procedure):
    """migration_start → downgrade leader → open candidate (WAL catchup) →
    upgrade candidate → update metadata → end.

    Mirrors meta-srv/src/procedure/region_migration/ including the
    downgrade/upgrade handshake (instruction.rs:199-203).
    """

    type_name = "region_migration"

    def __init__(self, metasrv: Metasrv, state: Optional[dict] = None):
        super().__init__(state)
        self.metasrv = metasrv
        self.state.setdefault("phase", "start")

    def step(self, ctx) -> Status:
        st = self.state
        m = self.metasrv
        phase = st["phase"]
        if phase == "start":
            route = m.routes.get(st["table"])
            if route is not None:
                rr = route.region(st["region_id"])
                rr.leader_state = "downgraded"
                m.routes.update(route)
            m.send_instruction(
                st["from_node"],
                Instruction(
                    InstructionKind.DOWNGRADE_REGION, st["region_id"], st["table"]
                ),
            )
            st["phase"] = "open_candidate"
            return Status.executing()
        if phase == "open_candidate":
            m.send_instruction(
                st["to_node"],
                Instruction(
                    InstructionKind.OPEN_REGION,
                    st["region_id"],
                    st["table"],
                    payload={"replay_wal": True, "follower": True},
                ),
            )
            st["phase"] = "upgrade_candidate"
            return Status.executing()
        if phase == "upgrade_candidate":
            m.send_instruction(
                st["to_node"],
                Instruction(
                    InstructionKind.UPGRADE_REGION, st["region_id"], st["table"]
                ),
            )
            st["phase"] = "update_metadata"
            return Status.executing()
        if phase == "update_metadata":
            route = m.routes.get(st["table"])
            if route is not None:
                rr = route.region(st["region_id"])
                rr.leader_node = st["to_node"]
                rr.leader_state = "leader"
                m.routes.update(route)
            m.send_instruction(
                st["from_node"],
                Instruction(
                    InstructionKind.CLOSE_REGION, st["region_id"], st["table"]
                ),
            )
            m.invalidate_caches(st["table"])
            st["phase"] = "end"
            return Status.finished({"region_id": st["region_id"], "to_node": st["to_node"]})
        return Status.finished()
