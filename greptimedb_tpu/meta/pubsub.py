"""Metasrv pub/sub — heartbeat fanout to interested components.

Mirrors reference src/meta-srv/src/pubsub/ (publish.rs DefaultPublisher,
subscribe_manager.rs DefaultSubscribeManager, subscriber.rs): components
subscribe to topics; the metasrv publishes a message once and the
manager fans it out to every subscriber of that topic. The reference
uses this to stream datanode heartbeats to the frontends' statistics
caches; here delivery is a synchronous callback (single-process
metadata plane), with the same subscribe/unsubscribe-by-name surface.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Callable

TOPIC_HEARTBEAT = "heartbeat"


@dataclass
class Subscriber:
    id: int
    name: str
    topics: set
    deliver: Callable[[str, object], None]


class SubscribeManager:
    """Topic registry + fanout (DefaultSubscribeManager +
    DefaultPublisher in one: the split only matters across gRPC)."""

    def __init__(self):
        self._subs: dict[int, Subscriber] = {}
        self._next_id = 1
        self._lock = threading.Lock()

    def subscribe(self, name: str, topics: list[str],
                  deliver: Callable[[str, object], None]) -> int:
        with self._lock:
            sid = self._next_id
            self._next_id += 1
            self._subs[sid] = Subscriber(sid, name, set(topics), deliver)
            return sid

    def unsubscribe(self, sub_id: int) -> bool:
        with self._lock:
            return self._subs.pop(sub_id, None) is not None

    def unsubscribe_all(self, name: str) -> int:
        """Drop every subscription registered under `name`
        (subscribe_manager.rs unsubscribe_all)."""
        with self._lock:
            doomed = [sid for sid, s in self._subs.items() if s.name == name]
            for sid in doomed:
                del self._subs[sid]
            return len(doomed)

    def subscribers_by_topic(self, topic: str) -> list[Subscriber]:
        with self._lock:
            return [s for s in self._subs.values() if topic in s.topics]

    def publish(self, topic: str, message: object) -> int:
        """Deliver to every subscriber; a failing subscriber never blocks
        the others (or the heartbeat path publishing to it)."""
        delivered = 0
        for sub in self.subscribers_by_topic(topic):
            try:
                sub.deliver(topic, message)
                delivered += 1
            except Exception:  # noqa: BLE001 — fanout isolation
                pass
        return delivered
