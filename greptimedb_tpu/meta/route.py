"""Table route metadata: which datanode serves which region.

Mirrors reference src/common/meta/src/key/table_route.rs +
datanode_table.rs: the route is the authoritative region→node placement,
stored in the kv backend and updated transactionally by DDL / failover /
migration procedures. Frontends cache routes and re-fetch on invalidation.
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import Optional

from ..catalog.kv import KvBackend

ROUTE_PREFIX = "__meta/table_route/"


@dataclass
class RegionRoute:
    region_id: int
    leader_node: Optional[str]  # datanode id; None while failing over
    follower_nodes: list[str] = field(default_factory=list)
    leader_state: str = "leader"  # leader | downgraded

    def to_json(self) -> dict:
        return {
            "region_id": self.region_id,
            "leader_node": self.leader_node,
            "follower_nodes": self.follower_nodes,
            "leader_state": self.leader_state,
        }

    @staticmethod
    def from_json(d: dict) -> "RegionRoute":
        return RegionRoute(
            region_id=d["region_id"],
            leader_node=d.get("leader_node"),
            follower_nodes=d.get("follower_nodes", []),
            leader_state=d.get("leader_state", "leader"),
        )


@dataclass
class TableRoute:
    table: str  # db.table
    regions: list[RegionRoute] = field(default_factory=list)
    version: int = 0

    def region(self, region_id: int) -> RegionRoute:
        for r in self.regions:
            if r.region_id == region_id:
                return r
        raise KeyError(f"region {region_id} not in route for {self.table}")

    def to_json(self) -> str:
        return json.dumps(
            {
                "table": self.table,
                "regions": [r.to_json() for r in self.regions],
                "version": self.version,
            }
        )

    @staticmethod
    def from_json(s: str) -> "TableRoute":
        d = json.loads(s)
        return TableRoute(
            table=d["table"],
            regions=[RegionRoute.from_json(r) for r in d["regions"]],
            version=d.get("version", 0),
        )


class TableRouteManager:
    """CAS-updated route storage (the txn_helper.rs analog)."""

    def __init__(self, kv: KvBackend):
        self._kv = kv

    def get(self, table: str) -> Optional[TableRoute]:
        raw = self._kv.get(ROUTE_PREFIX + table)
        return TableRoute.from_json(raw) if raw is not None else None

    def put_new(self, route: TableRoute) -> bool:
        return self._kv.compare_and_put(ROUTE_PREFIX + route.table, None, route.to_json())

    def update(self, route: TableRoute) -> bool:
        """Bump version with CAS against the previously-read version."""
        old = self.get(route.table)
        expect = old.to_json() if old is not None else None
        route.version = (old.version if old else 0) + 1
        return self._kv.compare_and_put(ROUTE_PREFIX + route.table, expect, route.to_json())

    def delete(self, table: str) -> None:
        self._kv.delete(ROUTE_PREFIX + table)

    def all(self) -> list[TableRoute]:
        return [TableRoute.from_json(v) for _, v in self._kv.range(ROUTE_PREFIX)]
