"""Datanode selectors: where to place new/failed-over regions.

Mirrors reference src/meta-srv/src/selector/: `lease_based` (any live node),
`round_robin`, and `load_based` (weighted by region count / write load from
heartbeat stats, weight_compute.rs).
"""

from __future__ import annotations

import itertools
from typing import Optional, Sequence


class Selector:
    def select(
        self,
        alive_nodes: Sequence[str],
        stats: dict[str, dict],
        exclude: Sequence[str] = (),
    ) -> Optional[str]:
        raise NotImplementedError


class LeaseBasedSelector(Selector):
    def select(self, alive_nodes, stats, exclude=()):
        for n in alive_nodes:
            if n not in exclude:
                return n
        return None


class RoundRobinSelector(Selector):
    def __init__(self):
        self._counter = itertools.count()

    def select(self, alive_nodes, stats, exclude=()):
        candidates = [n for n in alive_nodes if n not in exclude]
        if not candidates:
            return None
        return candidates[next(self._counter) % len(candidates)]


class LoadBasedSelector(Selector):
    """Pick the node with the fewest regions (ties by write bytes)."""

    def select(self, alive_nodes, stats, exclude=()):
        candidates = [n for n in alive_nodes if n not in exclude]
        if not candidates:
            return None

        def load(n: str):
            s = stats.get(n, {})
            return (s.get("region_count", 0), s.get("write_bytes", 0))

        return min(candidates, key=load)


SELECTORS = {
    "lease_based": LeaseBasedSelector,
    "round_robin": RoundRobinSelector,
    "load_based": LoadBasedSelector,
}
