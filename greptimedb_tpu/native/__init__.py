"""Native (C++) runtime hot paths, loaded via ctypes.

Compiles src/gtpu_native.cpp with g++ on first import and caches the
shared object next to the package (keyed by a source hash, so edits
rebuild). Everything here is OPTIONAL: callers check `AVAILABLE` and
keep pure-Python fallbacks, matching the task constraint that nothing
may hard-require a toolchain at runtime.

Exposes:
- crc32(data, seed=0)           — bit-identical to zlib.crc32
- snappy_compress(data)         — real back-reference compression
- snappy_decompress(data)       — block-format decoder
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import tempfile

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "src", "gtpu_native.cpp")

AVAILABLE = False
_lib = None


def _build() -> str | None:
    try:
        with open(_SRC, "rb") as f:
            digest = hashlib.sha256(f.read()).hexdigest()[:16]
    except OSError:
        return None
    so_path = os.path.join(_HERE, f"_gtpu_native_{digest}.so")
    if os.path.exists(so_path):
        return so_path
    tmp = tempfile.mktemp(suffix=".so", dir=_HERE)
    cmd = ["g++", "-O3", "-shared", "-fPIC", "-o", tmp, _SRC]
    try:
        r = subprocess.run(cmd, capture_output=True, timeout=120)
    except (OSError, subprocess.TimeoutExpired):
        return None
    if r.returncode != 0:
        return None
    os.replace(tmp, so_path)
    # drop stale builds only AFTER the replacement landed — a failed
    # compile must not destroy the last working library
    for old in os.listdir(_HERE):
        if old.startswith("_gtpu_native_") and old.endswith(".so") \
                and old != os.path.basename(so_path):
            try:
                os.unlink(os.path.join(_HERE, old))
            except OSError:
                pass
    return so_path


def _load() -> None:
    global _lib, AVAILABLE
    so = _build()
    if so is None:
        return
    try:
        lib = ctypes.CDLL(so)
    except OSError:
        return
    lib.gtpu_crc32.restype = ctypes.c_uint32
    lib.gtpu_crc32.argtypes = [ctypes.c_char_p, ctypes.c_size_t,
                               ctypes.c_uint32]
    lib.gtpu_snappy_max_compressed.restype = ctypes.c_size_t
    lib.gtpu_snappy_max_compressed.argtypes = [ctypes.c_size_t]
    lib.gtpu_snappy_compress.restype = ctypes.c_longlong
    lib.gtpu_snappy_compress.argtypes = [
        ctypes.c_char_p, ctypes.c_size_t, ctypes.c_char_p, ctypes.c_size_t]
    lib.gtpu_snappy_uncompressed_length.restype = ctypes.c_longlong
    lib.gtpu_snappy_uncompressed_length.argtypes = [
        ctypes.c_char_p, ctypes.c_size_t]
    lib.gtpu_snappy_decompress.restype = ctypes.c_longlong
    lib.gtpu_snappy_decompress.argtypes = [
        ctypes.c_char_p, ctypes.c_size_t, ctypes.c_char_p, ctypes.c_size_t]
    lib.gtpu_wal_scan.restype = ctypes.c_longlong
    lib.gtpu_wal_scan.argtypes = [
        ctypes.c_char_p, ctypes.c_size_t,
        ctypes.POINTER(ctypes.c_uint64), ctypes.POINTER(ctypes.c_uint32),
        ctypes.POINTER(ctypes.c_uint64), ctypes.POINTER(ctypes.c_uint64),
        ctypes.POINTER(ctypes.c_uint8), ctypes.c_size_t,
        ctypes.POINTER(ctypes.c_uint64)]
    _lib = lib
    AVAILABLE = True


_load()


def try_load():
    """The guarded accessor every caller should use: returns this module
    when the native library built, else None. Centralizes the policy that
    a broken toolchain must never take down a pure-Python code path."""
    import sys
    return sys.modules[__name__] if AVAILABLE else None


def crc32(data: bytes, seed: int = 0) -> int:
    return _lib.gtpu_crc32(data, len(data), seed & 0xFFFFFFFF)


def snappy_compress(data: bytes) -> bytes:
    cap = _lib.gtpu_snappy_max_compressed(len(data))
    dst = ctypes.create_string_buffer(cap)
    n = _lib.gtpu_snappy_compress(data, len(data), dst, cap)
    if n < 0:
        raise ValueError("snappy compression failed")
    return dst.raw[:n]


def wal_scan(data: bytes):
    """Validate + index WAL frames in one native pass.

    Returns (records, valid_end): records is a list of
    (payload_off, payload_len, region_id, seq, op_type); valid_end is
    the truncation point after the last intact frame."""
    max_records = len(data) // 25 + 1
    off = (ctypes.c_uint64 * max_records)()
    plen = (ctypes.c_uint32 * max_records)()
    rid = (ctypes.c_uint64 * max_records)()
    seq = (ctypes.c_uint64 * max_records)()
    op = (ctypes.c_uint8 * max_records)()
    valid_end = ctypes.c_uint64(0)
    n = _lib.gtpu_wal_scan(data, len(data), off, plen, rid, seq, op,
                           max_records, ctypes.byref(valid_end))
    recs = [(off[i], plen[i], rid[i], seq[i], op[i]) for i in range(n)]
    return recs, valid_end.value


def snappy_decompress(data: bytes) -> bytes:
    want = _lib.gtpu_snappy_uncompressed_length(data, len(data))
    if want < 0:
        raise ValueError("malformed snappy header")
    # the header varint is attacker-controlled (e.g. Prometheus remote
    # write bodies): bound it by the format's maximum expansion (~64x for
    # copy-2 runs) before allocating, or a 7-byte request could demand TBs
    if want > max(64 * len(data), 1 << 16):
        raise ValueError(
            f"snappy header claims {want} bytes from {len(data)} input")
    dst = ctypes.create_string_buffer(max(int(want), 1))
    n = _lib.gtpu_snappy_decompress(data, len(data), dst, want)
    if n == -1:
        raise ValueError("malformed snappy data")
    if n == -2:
        raise ValueError("snappy output overflow")
    return dst.raw[:n]
