// Native runtime hot paths: snappy block codec + CRC32.
//
// The reference's runtime leans on native crates for exactly these
// (snap for Prometheus remote write/read bodies, crc32fast in the WAL
// framing — src/servers/src/prom_store.rs, src/log-store). The Python
// substrate keeps pure-Python fallbacks; this library is the fast path,
// loaded via ctypes (no pybind11 in the image).
//
// ABI: plain extern "C", buffers in / buffers out, negative return =
// error. Compiled by greptimedb_tpu/native/__init__.py with
//   g++ -O3 -shared -fPIC
// on first import and cached beside the package.

#include <cstdint>
#include <cstring>
#include <cstddef>

extern "C" {

// ---------------------------------------------------------------- crc32
// IEEE polynomial (0xEDB88320), bit-identical to Python's zlib.crc32 —
// the WAL's on-disk frame checksum (storage/wal.py) must not change
// meaning between the Python and native paths.
static uint32_t CRC_TABLE[8][256];
static bool crc_init_done = false;

static void crc_init() {
    for (uint32_t i = 0; i < 256; i++) {
        uint32_t c = i;
        for (int k = 0; k < 8; k++)
            c = (c & 1) ? 0xEDB88320u ^ (c >> 1) : c >> 1;
        CRC_TABLE[0][i] = c;
    }
    for (uint32_t i = 0; i < 256; i++)
        for (int s = 1; s < 8; s++)
            CRC_TABLE[s][i] =
                (CRC_TABLE[s - 1][i] >> 8) ^ CRC_TABLE[0][CRC_TABLE[s - 1][i] & 0xFF];
    crc_init_done = true;
}

uint32_t gtpu_crc32(const uint8_t* buf, size_t len, uint32_t seed) {
    if (!crc_init_done) crc_init();
    uint32_t c = seed ^ 0xFFFFFFFFu;
    // slice-by-8
    while (len >= 8) {
        c ^= (uint32_t)buf[0] | ((uint32_t)buf[1] << 8) |
             ((uint32_t)buf[2] << 16) | ((uint32_t)buf[3] << 24);
        uint32_t hi = (uint32_t)buf[4] | ((uint32_t)buf[5] << 8) |
                      ((uint32_t)buf[6] << 16) | ((uint32_t)buf[7] << 24);
        c = CRC_TABLE[7][c & 0xFF] ^ CRC_TABLE[6][(c >> 8) & 0xFF] ^
            CRC_TABLE[5][(c >> 16) & 0xFF] ^ CRC_TABLE[4][c >> 24] ^
            CRC_TABLE[3][hi & 0xFF] ^ CRC_TABLE[2][(hi >> 8) & 0xFF] ^
            CRC_TABLE[1][(hi >> 16) & 0xFF] ^ CRC_TABLE[0][hi >> 24];
        buf += 8;
        len -= 8;
    }
    while (len--) c = CRC_TABLE[0][(c ^ *buf++) & 0xFF] ^ (c >> 8);
    return c ^ 0xFFFFFFFFu;
}

// --------------------------------------------------------------- snappy
// Block format (https://github.com/google/snappy/blob/main/format_description.txt):
// varint uncompressed length, then literal / copy-1 / copy-2 / copy-4
// elements. Compression is the standard greedy 4-byte hash matcher.

static inline size_t put_varint(uint8_t* dst, uint64_t v) {
    size_t i = 0;
    while (v >= 0x80) { dst[i++] = (uint8_t)(v) | 0x80; v >>= 7; }
    dst[i++] = (uint8_t)v;
    return i;
}

static inline int get_varint(const uint8_t* src, size_t n, uint64_t* v) {
    uint64_t r = 0; int shift = 0; size_t i = 0;
    while (i < n) {
        uint8_t b = src[i++];
        r |= (uint64_t)(b & 0x7F) << shift;
        if (!(b & 0x80)) { *v = r; return (int)i; }
        shift += 7;
        if (shift > 35) return -1;
    }
    return -1;
}

size_t gtpu_snappy_max_compressed(size_t n) {
    return 32 + n + n / 6;
}

static inline uint32_t load32(const uint8_t* p) {
    uint32_t v; memcpy(&v, p, 4); return v;
}

static inline size_t emit_literal(uint8_t* dst, const uint8_t* src, size_t len) {
    size_t o = 0;
    size_t l = len - 1;
    if (l < 60) {
        dst[o++] = (uint8_t)(l << 2);
    } else if (l < (1u << 8)) {
        dst[o++] = 60 << 2; dst[o++] = (uint8_t)l;
    } else if (l < (1u << 16)) {
        dst[o++] = 61 << 2; dst[o++] = (uint8_t)l; dst[o++] = (uint8_t)(l >> 8);
    } else if (l < (1u << 24)) {
        dst[o++] = 62 << 2; dst[o++] = (uint8_t)l; dst[o++] = (uint8_t)(l >> 8);
        dst[o++] = (uint8_t)(l >> 16);
    } else {
        dst[o++] = 63 << 2; dst[o++] = (uint8_t)l; dst[o++] = (uint8_t)(l >> 8);
        dst[o++] = (uint8_t)(l >> 16); dst[o++] = (uint8_t)(l >> 24);
    }
    memcpy(dst + o, src, len);
    return o + len;
}

static inline size_t emit_copy(uint8_t* dst, size_t offset, size_t len) {
    size_t o = 0;
    // prefer copy-1 (4..11 bytes, offset < 2048)
    while (len > 0) {
        if (len >= 4 && len <= 11 && offset < 2048) {
            dst[o++] = (uint8_t)(1 | ((len - 4) << 2) | ((offset >> 8) << 5));
            dst[o++] = (uint8_t)offset;
            return o;
        }
        size_t chunk = len > 64 ? 64 : len;  // copy-2/4 encode 1..64
        if (offset < (1u << 16)) {
            dst[o++] = (uint8_t)(2 | ((chunk - 1) << 2));
            dst[o++] = (uint8_t)offset; dst[o++] = (uint8_t)(offset >> 8);
        } else {
            dst[o++] = (uint8_t)(3 | ((chunk - 1) << 2));
            dst[o++] = (uint8_t)offset; dst[o++] = (uint8_t)(offset >> 8);
            dst[o++] = (uint8_t)(offset >> 16); dst[o++] = (uint8_t)(offset >> 24);
        }
        len -= chunk;
    }
    return o;
}

// returns compressed size, or -1 if dst too small (callers size with
// gtpu_snappy_max_compressed)
long long gtpu_snappy_compress(const uint8_t* src, size_t n,
                               uint8_t* dst, size_t dst_cap) {
    if (dst_cap < gtpu_snappy_max_compressed(n)) return -1;
    size_t o = put_varint(dst, n);
    if (n == 0) return (long long)o;

    const size_t HASH_BITS = 14;
    uint32_t table[1 << 14];
    memset(table, 0xFF, sizeof(table));

    size_t ip = 0;          // input position
    size_t lit_start = 0;   // start of pending literal run
    while (ip + 4 <= n) {
        uint32_t h = (load32(src + ip) * 0x1E35A7BDu) >> (32 - HASH_BITS);
        uint32_t cand = table[h];
        table[h] = (uint32_t)ip;
        if (cand != 0xFFFFFFFFu && cand < ip &&
            ip - cand < (1u << 16) &&  // keep offsets in copy-2 range
            load32(src + cand) == load32(src + ip)) {
            // extend the match
            size_t mlen = 4;
            while (ip + mlen < n && src[cand + mlen] == src[ip + mlen] &&
                   mlen < 0xFFFF)
                mlen++;
            if (ip > lit_start)
                o += emit_literal(dst + o, src + lit_start, ip - lit_start);
            o += emit_copy(dst + o, ip - cand, mlen);
            ip += mlen;
            lit_start = ip;
        } else {
            ip++;
        }
    }
    if (n > lit_start)
        o += emit_literal(dst + o, src + lit_start, n - lit_start);
    return (long long)o;
}

// returns uncompressed size, -1 on malformed input, -2 if dst too small
long long gtpu_snappy_uncompressed_length(const uint8_t* src, size_t n) {
    uint64_t len;
    if (get_varint(src, n, &len) < 0) return -1;
    return (long long)len;
}

long long gtpu_snappy_decompress(const uint8_t* src, size_t n,
                                 uint8_t* dst, size_t dst_cap) {
    uint64_t expect;
    int hdr = get_varint(src, n, &expect);
    if (hdr < 0) return -1;
    if (expect > dst_cap) return -2;
    size_t ip = (size_t)hdr, op = 0;
    while (ip < n) {
        uint8_t tag = src[ip++];
        uint32_t kind = tag & 3;
        if (kind == 0) {  // literal
            size_t len = (tag >> 2) + 1;
            if (len > 60) {
                size_t extra = len - 60;
                if (ip + extra > n) return -1;
                len = 0;
                for (size_t k = 0; k < extra; k++)
                    len |= (size_t)src[ip + k] << (8 * k);
                len += 1;
                ip += extra;
            }
            if (ip + len > n || op + len > expect) return -1;
            memcpy(dst + op, src + ip, len);
            ip += len; op += len;
            continue;
        }
        size_t len, offset;
        if (kind == 1) {
            len = ((tag >> 2) & 7) + 4;
            if (ip >= n) return -1;
            offset = ((size_t)(tag >> 5) << 8) | src[ip++];
        } else if (kind == 2) {
            len = (tag >> 2) + 1;
            if (ip + 2 > n) return -1;
            offset = (size_t)src[ip] | ((size_t)src[ip + 1] << 8);
            ip += 2;
        } else {
            len = (tag >> 2) + 1;
            if (ip + 4 > n) return -1;
            offset = (size_t)src[ip] | ((size_t)src[ip + 1] << 8) |
                     ((size_t)src[ip + 2] << 16) | ((size_t)src[ip + 3] << 24);
            ip += 4;
        }
        if (offset == 0 || offset > op || op + len > expect) return -1;
        // byte-wise: overlapping copies are the RLE idiom
        for (size_t k = 0; k < len; k++) dst[op + k] = dst[op + k - offset];
        op += len;
    }
    return op == expect ? (long long)op : -1;
}

// ------------------------------------------------------------- WAL scan
// Frame layout (storage/wal.py _HEADER "<IIQQB", packed):
//   u32 payload_len | u32 crc32(payload) | u64 region_id | u64 seq | u8 op
// One pass: validate every frame's bounds + checksum, emit the record
// table. Returns record count; *valid_end is the byte offset after the
// last intact frame (the torn-tail truncation point).
static inline uint32_t rd32(const uint8_t* p) {
    uint32_t v; memcpy(&v, p, 4); return v;
}
static inline uint64_t rd64(const uint8_t* p) {
    uint64_t v; memcpy(&v, p, 8); return v;
}

long long gtpu_wal_scan(const uint8_t* buf, size_t n,
                        uint64_t* payload_off, uint32_t* payload_len,
                        uint64_t* region_id, uint64_t* seq, uint8_t* op,
                        size_t max_records, uint64_t* valid_end) {
    const size_t HDR = 25;
    size_t pos = 0, cnt = 0;
    *valid_end = 0;
    while (pos + HDR <= n && cnt < max_records) {
        uint32_t plen = rd32(buf + pos);
        uint32_t crc = rd32(buf + pos + 4);
        if (pos + HDR + plen > n) break;                       // torn tail
        if (gtpu_crc32(buf + pos + HDR, plen, 0) != crc) break;  // corrupt
        payload_off[cnt] = pos + HDR;
        payload_len[cnt] = plen;
        region_id[cnt] = rd64(buf + pos + 8);
        seq[cnt] = rd64(buf + pos + 16);
        op[cnt] = buf[pos + 24];
        pos += HDR + plen;
        *valid_end = pos;
        cnt++;
    }
    return (long long)cnt;
}

}  // extern "C"
