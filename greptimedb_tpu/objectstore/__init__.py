"""Object storage abstraction (mirrors reference `src/object-store`: the
OpenDAL wrapper with fs/s3/oss/azblob/gcs backends and the
`LruCacheLayer` read-through disk cache, src/object-store/src/layers/
lru_cache/, backend selection at src/datanode/src/store.rs:44-116).

Backends here: `FsStore` (local filesystem, atomic writes via
tmp+rename) and `MemoryStore` (tests / ephemeral). Remote backends (s3
etc.) would slot in behind the same five-method interface; this
environment has no egress, so none are shipped — the cache layer is
where remote-read economics happen anyway.

`LruCacheLayer` wraps any store with a byte-budgeted read-through LRU —
the analog of the reference's disk cache for object-store reads. SST
reads go through `open_input`, which returns a zero-copy reader:
memory-mapped for fs, buffer-backed for memory/cached stores.

Resilience: the base class owns read/write/open_input as templates over
backend `_do_*` primitives, wrapping every call with the shared fault
hooks (`FAULTS.fire`/`mangle` at `objectstore.read`/`objectstore.write`)
and `retry_call` backoff (reference object-store RetryLayer analog).
Backends raise `ObjectStoreError` with `transient=True` for errors a
retry can fix (5xx, network); not-found stays non-transient.
"""

from __future__ import annotations

import os
import threading
from collections import OrderedDict
from typing import Optional

import pyarrow as pa

from greptimedb_tpu.fault import FAULTS, retry_call
from greptimedb_tpu.utils import tracing
from greptimedb_tpu.utils.metrics import REGISTRY

OBJECT_STORE_READS = REGISTRY.counter(
    "greptimedb_tpu_object_store_reads_total",
    "Object store reads by backend and cache outcome")
OBJECT_STORE_BYTES = REGISTRY.counter(
    "greptimedb_tpu_object_store_read_bytes_total",
    "Object store bytes read")


class ObjectStoreError(Exception):
    #: True when a retry could plausibly succeed (5xx, network reset);
    #: not-found/misconfiguration stay False and surface immediately
    transient = False


class ObjectStore:
    """Five-method contract: read / write / delete / exists / list,
    plus `open_input` for zero-copy columnar reads. Backends implement
    `_do_read`/`_do_write`; the base templates add fault injection and
    retry uniformly."""

    name = "base"

    def read(self, key: str) -> bytes:
        # the span joins the request trace when a scan-pool worker runs
        # this under tracing.propagate — SST reads become visible (and
        # attributable) in EXPLAIN ANALYZE trees
        with tracing.span("objectstore_read", backend=self.name) as attrs:
            def op():
                return FAULTS.mangled_read("objectstore.read",
                                           self._do_read(key))
            data = retry_call(op, point="objectstore.read")
            attrs["bytes"] = len(data)
            return data

    def write(self, key: str, data: bytes) -> None:
        with tracing.span("objectstore_write", backend=self.name,
                          bytes=len(data)):
            retry_call(
                lambda: FAULTS.mangled_write(
                    "objectstore.write", data,
                    lambda blob: self._do_write(key, blob),
                    spill=lambda blob: self._spill_partial(key, blob)),
                point="objectstore.write")

    def _do_read(self, key: str) -> bytes:
        raise NotImplementedError

    def _do_write(self, key: str, data: bytes) -> None:
        raise NotImplementedError

    def _spill_partial(self, key: str, partial: bytes) -> None:
        """ENOSPC staging contract: the bytes that reached the backend
        before the device filled. Backends with on-disk staging (fs tmp
        files, multipart uploads) must erase it here — a leaked partial
        is the bug the enospc chaos shape exists to catch. Atomic
        backends have nothing staged: default no-op."""

    def delete(self, key: str) -> None:
        raise NotImplementedError

    def exists(self, key: str) -> bool:
        raise NotImplementedError

    def list(self, prefix: str) -> list[str]:
        raise NotImplementedError

    def open_input(self, key: str):
        """A pyarrow-compatible random-access input for `key`."""
        return pa.BufferReader(self.read(key))

    def size(self, key: str) -> int:
        return len(self.read(key))


class FsStore(ObjectStore):
    """Local filesystem backend; keys are paths. Writes are atomic
    (tmp + rename), reads memory-map."""

    name = "fs"

    def _do_read(self, key: str) -> bytes:
        OBJECT_STORE_READS.inc(backend="fs", outcome="read")
        try:
            with open(key, "rb") as f:
                data = f.read()
        except FileNotFoundError as e:
            raise ObjectStoreError(f"object {key!r} not found") from e
        OBJECT_STORE_BYTES.inc(len(data))
        return data

    def _do_write(self, key: str, data: bytes) -> None:
        parent = os.path.dirname(key)
        if parent:
            os.makedirs(parent, exist_ok=True)
        tmp = key + ".tmp"
        with open(tmp, "wb") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())  # durable before rename (manifest contract)
        os.replace(tmp, key)

    def _spill_partial(self, key: str, partial: bytes) -> None:
        """A real mid-write ENOSPC dies inside the tmp write above, so
        the visible object is never partial — but the tmp file is, and
        leaking one per failed flush would fill the disk for good. Stage
        the partial exactly where _do_write would, then erase it."""
        parent = os.path.dirname(key)
        if parent:
            os.makedirs(parent, exist_ok=True)
        tmp = key + ".tmp"
        try:
            with open(tmp, "wb") as f:
                f.write(partial)
        finally:
            try:
                os.remove(tmp)
            except FileNotFoundError:
                pass

    def delete(self, key: str) -> None:
        try:
            os.remove(key)
        except FileNotFoundError:
            pass

    def exists(self, key: str) -> bool:
        return os.path.exists(key)

    def list(self, prefix: str) -> list[str]:
        """Keys under a directory prefix (non-recursive, like a flat
        object listing of `prefix/`)."""
        d = prefix if os.path.isdir(prefix) else os.path.dirname(prefix)
        if not os.path.isdir(d):
            return []
        return sorted(
            os.path.join(d, n) for n in os.listdir(d)
            if os.path.join(d, n).startswith(prefix)
            and os.path.isfile(os.path.join(d, n)))

    def open_input(self, key: str):
        with tracing.span("objectstore_read", backend="fs", mmap=True):
            def op():
                FAULTS.fire("objectstore.read")
                OBJECT_STORE_READS.inc(backend="fs", outcome="mmap")
                try:
                    return pa.memory_map(key, "rb")
                except FileNotFoundError as e:
                    raise ObjectStoreError(f"object {key!r} not found") from e
            return retry_call(op, point="objectstore.read")

    def size(self, key: str) -> int:
        return os.path.getsize(key)


class MemoryStore(ObjectStore):
    """In-memory backend (reference kv_backend/memory analog for blobs)."""

    name = "memory"

    def __init__(self):
        self._data: dict[str, bytes] = {}
        self._lock = threading.Lock()

    def _do_read(self, key: str) -> bytes:
        OBJECT_STORE_READS.inc(backend="memory", outcome="read")
        with self._lock:
            if key not in self._data:
                raise ObjectStoreError(f"object {key!r} not found")
            return self._data[key]

    def _do_write(self, key: str, data: bytes) -> None:
        with self._lock:
            self._data[key] = bytes(data)

    def delete(self, key: str) -> None:
        with self._lock:
            self._data.pop(key, None)

    def exists(self, key: str) -> bool:
        with self._lock:
            return key in self._data

    def list(self, prefix: str) -> list[str]:
        with self._lock:
            return sorted(k for k in self._data if k.startswith(prefix))


class LruCacheLayer(ObjectStore):
    """Read-through LRU over another store, bounded by total bytes
    (reference LruCacheLayer, object-store/src/layers/lru_cache/).

    Writes go straight through and refresh the cache; deletes
    invalidate. `open_input` serves a BufferReader over cached bytes —
    repeated SST scans of remote objects skip the backend entirely."""

    name = "lru_cache"

    def __init__(self, inner: ObjectStore, capacity_bytes: int = 256 << 20):
        self.inner = inner
        self.capacity = capacity_bytes
        self._cache: "OrderedDict[str, bytes]" = OrderedDict()
        self._bytes = 0
        self._lock = threading.Lock()

    def _get_cached(self, key: str) -> Optional[bytes]:
        with self._lock:
            data = self._cache.get(key)
            if data is not None:
                self._cache.move_to_end(key)
                OBJECT_STORE_READS.inc(backend=self.inner.name, outcome="hit")
            return data

    def _put_cached(self, key: str, data: bytes) -> None:
        if len(data) > self.capacity:
            return
        with self._lock:
            old = self._cache.pop(key, None)
            if old is not None:
                self._bytes -= len(old)
            self._cache[key] = data
            self._bytes += len(data)
            while self._bytes > self.capacity:
                _, evicted = self._cache.popitem(last=False)
                self._bytes -= len(evicted)

    def read(self, key: str) -> bytes:
        data = self._get_cached(key)
        if data is None:
            OBJECT_STORE_READS.inc(backend=self.inner.name, outcome="miss")
            data = self.inner.read(key)
            self._put_cached(key, data)
        return data

    def write(self, key: str, data: bytes) -> None:
        self.inner.write(key, data)
        self._put_cached(key, bytes(data))

    def delete(self, key: str) -> None:
        self.inner.delete(key)
        with self._lock:
            old = self._cache.pop(key, None)
            if old is not None:
                self._bytes -= len(old)

    def exists(self, key: str) -> bool:
        with self._lock:
            if key in self._cache:
                return True
        return self.inner.exists(key)

    def list(self, prefix: str) -> list[str]:
        return self.inner.list(prefix)

    def open_input(self, key: str):
        # local fs already serves lazy page reads via mmap — caching the
        # whole object would defeat row-group pruning; only buffer-cache
        # for backends without cheap random access
        if isinstance(self.inner, FsStore):
            return self.inner.open_input(key)
        return pa.BufferReader(self.read(key))

    def size(self, key: str) -> int:
        with self._lock:
            data = self._cache.get(key)
            if data is not None:
                return len(data)
        return self.inner.size(key)

    @property
    def cached_bytes(self) -> int:
        return self._bytes


#: process-wide default backend (local fs) — storage components that are
#: constructed without an explicit store share this
DEFAULT_FS = FsStore()


def default_store(store: Optional[ObjectStore]) -> ObjectStore:
    return store if store is not None else DEFAULT_FS


def build_store(kind: str = "fs", cache_bytes: int = 0, **kwargs) -> ObjectStore:
    """Backend selection (reference datanode/src/store.rs:44-56)."""
    if kind == "fs":
        store: ObjectStore = FsStore()
    elif kind == "memory":
        store = MemoryStore()
    elif kind == "s3":
        from greptimedb_tpu.objectstore.s3 import S3Store

        try:
            store = S3Store(**kwargs)
        except TypeError as e:
            raise ObjectStoreError(f"s3 store misconfigured: {e}") from None
    elif kind in ("gcs", "azblob"):
        if kind == "gcs":
            from greptimedb_tpu.objectstore.gcs import GcsStore as cls
        else:
            from greptimedb_tpu.objectstore.azblob import AzblobStore as cls
        try:
            store = cls(**kwargs)
        except TypeError as e:
            raise ObjectStoreError(
                f"{kind} store misconfigured: {e}") from None
    else:
        raise ObjectStoreError(
            f"unsupported object store {kind!r} "
            "(supported: fs, memory, s3, gcs, azblob)")
    if cache_bytes > 0:
        store = LruCacheLayer(store, cache_bytes)
    return store
