"""Azure Blob Storage backend with SharedKey request signing.

Mirrors the reference's azblob provider (datanode/src/store.rs:44-116 via
OpenDAL `services-azblob`): the SharedKey scheme signs
VERB + canonicalized headers + canonicalized resource with HMAC-SHA256
over the base64 account key. Endpoint injectable for Azurite-style
emulators and the in-process conformance fake."""

from __future__ import annotations

import base64
import datetime
import hashlib
import hmac
import os
import urllib.error
import urllib.parse
import urllib.request
import xml.etree.ElementTree as ET
from typing import Optional

from greptimedb_tpu.objectstore import ObjectStore, ObjectStoreError

API_VERSION = "2021-08-06"


def sign_shared_key(method: str, url: str, headers: dict, account: str,
                    key_b64: str) -> str:
    """Authorization header value for a SharedKey request. `headers` must
    already include x-ms-date and x-ms-version."""
    parts = urllib.parse.urlsplit(url)
    # canonicalized x-ms-* headers, lower-cased, sorted
    ms = sorted((k.lower(), v.strip()) for k, v in headers.items()
                if k.lower().startswith("x-ms-"))
    canon_headers = "".join(f"{k}:{v}\n" for k, v in ms)
    # canonicalized resource: /account/path + sorted query params
    canon_res = f"/{account}{parts.path}"
    if parts.query:
        q = urllib.parse.parse_qs(parts.query, keep_blank_values=True)
        for k in sorted(q):
            canon_res += f"\n{k.lower()}:{','.join(sorted(q[k]))}"
    length = headers.get("Content-Length", "")
    if length == "0":
        length = ""  # 2015-02-21+ semantics: empty when zero
    to_sign = "\n".join([
        method.upper(),
        headers.get("Content-Encoding", ""),
        headers.get("Content-Language", ""),
        length,
        headers.get("Content-MD5", ""),
        headers.get("Content-Type", ""),
        "",  # Date (empty: x-ms-date is set)
        headers.get("If-Modified-Since", ""),
        headers.get("If-Match", ""),
        headers.get("If-None-Match", ""),
        headers.get("If-Unmodified-Since", ""),
        headers.get("Range", ""),
    ]) + "\n" + canon_headers + canon_res
    mac = hmac.new(base64.b64decode(key_b64), to_sign.encode("utf-8"),
                   hashlib.sha256)
    return f"SharedKey {account}:{base64.b64encode(mac.digest()).decode()}"


class AzblobStore(ObjectStore):
    name = "azblob"

    def __init__(self, container: str, prefix: str = "", *,
                 account_name: Optional[str] = None,
                 account_key: Optional[str] = None,
                 endpoint: Optional[str] = None):
        if not container:
            raise ObjectStoreError("azblob store requires a container")
        self.container = container
        self.prefix = prefix.strip("/")
        self.account = account_name or os.environ.get(
            "AZURE_STORAGE_ACCOUNT", "")
        self.key = account_key or os.environ.get("AZURE_STORAGE_KEY", "")
        if not self.account or not self.key:
            raise ObjectStoreError(
                "azblob store requires account_name and account_key")
        self.endpoint = (endpoint or os.environ.get("AZBLOB_ENDPOINT")
                         or f"https://{self.account}.blob.core.windows.net"
                         ).rstrip("/")

    # ---- helpers -----------------------------------------------------------

    def _key(self, key: str) -> str:
        key = key.lstrip("/")
        return f"{self.prefix}/{key}" if self.prefix else key

    def _url(self, key: str) -> str:
        enc = urllib.parse.quote(self._key(key))
        return f"{self.endpoint}/{self.container}/{enc}"

    def _request(self, method: str, url: str,
                 data: Optional[bytes] = None,
                 extra_headers: Optional[dict] = None) -> bytes:
        return self._request_full(method, url, data, extra_headers)[0]

    def _request_full(self, method: str, url: str,
                      data: Optional[bytes] = None,
                      extra_headers: Optional[dict] = None
                      ) -> tuple[bytes, dict]:
        """(body, response headers) — headers returned locally, never
        stashed on the instance (the store is shared across threads)."""
        now = datetime.datetime.now(datetime.timezone.utc)
        headers = {
            "x-ms-date": now.strftime("%a, %d %b %Y %H:%M:%S GMT"),
            "x-ms-version": API_VERSION,
            "Content-Length": str(len(data) if data is not None else 0),
            **(extra_headers or {}),
        }
        headers["Authorization"] = sign_shared_key(
            method, url, headers, self.account, self.key)
        req = urllib.request.Request(url, data=data, method=method,
                                     headers=headers)
        try:
            with urllib.request.urlopen(req, timeout=60) as resp:
                return resp.read(), dict(resp.headers)
        except urllib.error.HTTPError as e:
            err = ObjectStoreError(
                f"azblob {method} {url}: HTTP {e.code} {e.read()[:200]!r}")
            err.http_code = e.code
            err.transient = e.code >= 500 or e.code == 429
            raise err from None
        except urllib.error.URLError as e:
            err = ObjectStoreError(f"azblob {method} {url}: {e}")
            err.transient = True
            raise err from None

    # ---- surface -----------------------------------------------------------

    def _do_read(self, key: str) -> bytes:
        try:
            return self._request("GET", self._url(key))
        except ObjectStoreError as e:
            if getattr(e, "http_code", None) == 404:
                raise ObjectStoreError(f"not found: {key}") from None
            raise

    def _do_write(self, key: str, data: bytes) -> None:
        self._request("PUT", self._url(key), data=data,
                      extra_headers={"x-ms-blob-type": "BlockBlob",
                                     "Content-Type":
                                         "application/octet-stream"})

    def delete(self, key: str) -> None:
        try:
            self._request("DELETE", self._url(key))
        except ObjectStoreError as e:
            if getattr(e, "http_code", None) != 404:
                raise

    def exists(self, key: str) -> bool:
        try:
            self._request("HEAD", self._url(key))
            return True
        except ObjectStoreError as e:
            if getattr(e, "http_code", None) == 404:
                return False
            raise

    def size(self, key: str) -> int:
        try:
            _, headers = self._request_full("HEAD", self._url(key))
        except ObjectStoreError as e:
            if getattr(e, "http_code", None) == 404:
                raise ObjectStoreError(f"not found: {key}") from None
            raise
        return int(headers.get("Content-Length", 0))

    def list(self, prefix: str) -> list[str]:
        full = self._key(prefix)
        plen = len(self.prefix) + 1 if self.prefix else 0
        out: list[str] = []
        marker = ""
        while True:
            q = {"restype": "container", "comp": "list", "prefix": full}
            if marker:
                q["marker"] = marker
            url = (f"{self.endpoint}/{self.container}?"
                   + urllib.parse.urlencode(q))
            root = ET.fromstring(self._request("GET", url).decode())
            for blob in root.iter("Blob"):
                out.append(blob.findtext("Name")[plen:])
            marker = root.findtext("NextMarker") or ""
            if not marker:
                return out

    # open_input: inherited (pa.BufferReader over read())
