"""Google Cloud Storage backend over the JSON API.

Mirrors the reference's GCS provider (datanode/src/store.rs:44-116 via
OpenDAL `services-gcs`): bearer-token auth, media upload/download, paged
object listing. The endpoint is injectable for emulators/tests (OpenDAL's
GCS endpoint option), which is also how the conformance test drives an
in-process fake."""

from __future__ import annotations

import json
import os
import urllib.error
import urllib.parse
import urllib.request
from typing import Optional

from greptimedb_tpu.objectstore import ObjectStore, ObjectStoreError


class GcsStore(ObjectStore):
    name = "gcs"

    def __init__(self, bucket: str, prefix: str = "", *,
                 endpoint: Optional[str] = None,
                 token: Optional[str] = None):
        if not bucket:
            raise ObjectStoreError("gcs store requires a bucket")
        self.bucket = bucket
        self.prefix = prefix.strip("/")
        self.endpoint = (endpoint or os.environ.get("GCS_ENDPOINT")
                         or "https://storage.googleapis.com").rstrip("/")
        self.token = token or os.environ.get("GCS_TOKEN", "")

    # ---- helpers -----------------------------------------------------------

    def _key(self, key: str) -> str:
        key = key.lstrip("/")
        return f"{self.prefix}/{key}" if self.prefix else key

    def _object_url(self, key: str, media: bool) -> str:
        enc = urllib.parse.quote(self._key(key), safe="")
        url = f"{self.endpoint}/storage/v1/b/{self.bucket}/o/{enc}"
        return url + "?alt=media" if media else url

    def _request(self, method: str, url: str,
                 data: Optional[bytes] = None,
                 headers: Optional[dict] = None) -> bytes:
        h = dict(headers or {})
        if self.token:
            h["Authorization"] = f"Bearer {self.token}"
        req = urllib.request.Request(url, data=data, method=method,
                                     headers=h)
        try:
            with urllib.request.urlopen(req, timeout=60) as resp:
                return resp.read()
        except urllib.error.HTTPError as e:
            err = ObjectStoreError(
                f"gcs {method} {url}: HTTP {e.code} {e.read()[:200]!r}")
            err.http_code = e.code
            err.transient = e.code >= 500 or e.code == 429
            raise err from None
        except urllib.error.URLError as e:
            err = ObjectStoreError(f"gcs {method} {url}: {e}")
            err.transient = True
            raise err from None

    # ---- surface -----------------------------------------------------------

    def _do_read(self, key: str) -> bytes:
        try:
            return self._request("GET", self._object_url(key, media=True))
        except ObjectStoreError as e:
            if getattr(e, "http_code", None) == 404:
                raise ObjectStoreError(f"not found: {key}") from None
            raise

    def _do_write(self, key: str, data: bytes) -> None:
        name = urllib.parse.quote(self._key(key), safe="")
        url = (f"{self.endpoint}/upload/storage/v1/b/{self.bucket}/o"
               f"?uploadType=media&name={name}")
        self._request("POST", url, data=data,
                      headers={"Content-Type": "application/octet-stream"})

    def delete(self, key: str) -> None:
        try:
            self._request("DELETE", self._object_url(key, media=False))
        except ObjectStoreError as e:
            if getattr(e, "http_code", None) != 404:
                raise

    def exists(self, key: str) -> bool:
        try:
            self._request("GET", self._object_url(key, media=False))
            return True
        except ObjectStoreError as e:
            if getattr(e, "http_code", None) == 404:
                return False
            raise

    def size(self, key: str) -> int:
        try:
            meta = json.loads(
                self._request("GET", self._object_url(key, media=False)))
        except ObjectStoreError as e:
            if getattr(e, "http_code", None) == 404:
                raise ObjectStoreError(f"not found: {key}") from None
            raise
        return int(meta.get("size", 0))

    def list(self, prefix: str) -> list[str]:
        full = self._key(prefix)
        plen = len(self.prefix) + 1 if self.prefix else 0
        out: list[str] = []
        page_token = None
        while True:
            q = {"prefix": full}
            if page_token:
                q["pageToken"] = page_token
            url = (f"{self.endpoint}/storage/v1/b/{self.bucket}/o?"
                   + urllib.parse.urlencode(q))
            body = json.loads(self._request("GET", url))
            for item in body.get("items", []):
                out.append(item["name"][plen:])
            page_token = body.get("nextPageToken")
            if not page_token:
                return out

    # open_input: inherited (pa.BufferReader over read())
