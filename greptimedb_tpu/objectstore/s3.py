"""S3-compatible object-store backend (AWS SigV4 over plain HTTP(S)).

Mirrors reference src/datanode/src/store/s3.rs (+ oss.rs / gcs.rs /
azblob.rs, selected at store.rs:44-116 via OpenDAL). One REST backend
covers the practical surface here: AWS S3, MinIO, Ceph RGW, and the
S3-compatible modes of OSS and GCS all speak this API — endpoint +
credentials select the vendor (see `from_url`). Implemented with the
standard library only (urllib + hmac): request signing is AWS Signature
Version 4 with the payload hash in x-amz-content-sha256.

This environment has no egress, so conformance is tested against an
in-process fake S3 server that validates the SigV4 signature by
recomputation (tests/test_objectstore_s3.py).
"""

from __future__ import annotations

import datetime
import hashlib
import hmac
import os
import urllib.error
import urllib.parse
import urllib.request
from typing import Optional

from greptimedb_tpu.objectstore import ObjectStore, ObjectStoreError


def _sha256(data: bytes) -> str:
    return hashlib.sha256(data).hexdigest()


def _hmac(key: bytes, msg: str) -> bytes:
    return hmac.new(key, msg.encode(), hashlib.sha256).digest()


def sign_v4(method: str, url: str, headers: dict, payload_hash: str,
            access_key: str, secret_key: str, region: str,
            service: str = "s3",
            now: Optional[datetime.datetime] = None) -> dict:
    """Return the headers to add (Authorization, x-amz-date,
    x-amz-content-sha256) for an AWS SigV4-signed request."""
    now = now or datetime.datetime.now(datetime.timezone.utc)
    amz_date = now.strftime("%Y%m%dT%H%M%SZ")
    datestamp = now.strftime("%Y%m%d")
    parsed = urllib.parse.urlsplit(url)
    # the caller's URL path is ALREADY percent-encoded (it is what goes
    # on the wire); AWS's canonical URI is that single-encoded path —
    # re-quoting here would double-encode and break the signature for any
    # key containing characters that need escaping
    canonical_uri = parsed.path or "/"
    # canonical query: sorted by key, values URI-encoded
    q = urllib.parse.parse_qsl(parsed.query, keep_blank_values=True)
    canonical_query = "&".join(
        f"{urllib.parse.quote(k, safe='-_.~')}="
        f"{urllib.parse.quote(v, safe='-_.~')}"
        for k, v in sorted(q))
    all_headers = {
        **{k.lower(): v.strip() for k, v in headers.items()},
        "host": parsed.netloc,
        "x-amz-content-sha256": payload_hash,
        "x-amz-date": amz_date,
    }
    signed_names = ";".join(sorted(all_headers))
    canonical_headers = "".join(
        f"{k}:{all_headers[k]}\n" for k in sorted(all_headers))
    canonical_request = "\n".join([
        method, canonical_uri, canonical_query, canonical_headers,
        signed_names, payload_hash,
    ])
    scope = f"{datestamp}/{region}/{service}/aws4_request"
    string_to_sign = "\n".join([
        "AWS4-HMAC-SHA256", amz_date, scope,
        _sha256(canonical_request.encode()),
    ])
    k_date = _hmac(b"AWS4" + secret_key.encode(), datestamp)
    k_region = _hmac(k_date, region)
    k_service = _hmac(k_region, service)
    k_signing = _hmac(k_service, "aws4_request")
    signature = hmac.new(k_signing, string_to_sign.encode(),
                         hashlib.sha256).hexdigest()
    auth = (
        f"AWS4-HMAC-SHA256 Credential={access_key}/{scope}, "
        f"SignedHeaders={signed_names}, Signature={signature}"
    )
    return {
        "Authorization": auth,
        "x-amz-date": amz_date,
        "x-amz-content-sha256": payload_hash,
    }


class S3Store(ObjectStore):
    """Bucket + key-prefix store over the S3 REST API."""

    def __init__(self, bucket: str, prefix: str = "", *,
                 endpoint: Optional[str] = None,
                 access_key: Optional[str] = None,
                 secret_key: Optional[str] = None,
                 region: str = "us-east-1"):
        self.bucket = bucket
        self.prefix = prefix.strip("/")
        self.endpoint = (endpoint
                         or os.environ.get("S3_ENDPOINT")
                         or f"https://s3.{region}.amazonaws.com").rstrip("/")
        self.access_key = access_key or os.environ.get("AWS_ACCESS_KEY_ID", "")
        self.secret_key = secret_key or os.environ.get(
            "AWS_SECRET_ACCESS_KEY", "")
        self.region = region

    # ------------------------------------------------------------ plumbing
    def _key(self, key: str) -> str:
        key = key.lstrip("/")
        return f"{self.prefix}/{key}" if self.prefix else key

    def _url(self, key: str = "", query: str = "") -> str:
        # path-style addressing: endpoint/bucket/key — what MinIO and
        # S3-compatible vendors accept universally
        path = f"/{self.bucket}"
        if key:
            path += "/" + urllib.parse.quote(self._key(key), safe="/-_.~")
        return self.endpoint + path + (f"?{query}" if query else "")

    def _request(self, method: str, url: str, data: bytes = b"") -> bytes:
        payload_hash = _sha256(data)
        headers = sign_v4(method, url, {}, payload_hash,
                          self.access_key, self.secret_key, self.region)
        req = urllib.request.Request(url, data=data or None, method=method,
                                     headers=headers)
        try:
            with urllib.request.urlopen(req, timeout=60) as resp:
                return resp.read()
        except urllib.error.HTTPError as e:
            if e.code == 404:
                err = ObjectStoreError(f"not found: {url}")
            else:
                err = ObjectStoreError(
                    f"s3 {method} {url}: HTTP {e.code} "
                    f"{e.read()[:200]!r}")
                # 5xx/throttling are worth the shared retry policy;
                # 4xx (auth, missing) are not
                err.transient = e.code >= 500 or e.code == 429
            err.http_code = e.code
            raise err from None
        except urllib.error.URLError as e:
            err = ObjectStoreError(f"s3 {method} {url}: {e}")
            err.transient = True  # network-shaped: retryable
            raise err from None

    # ------------------------------------------------------------- surface
    def _do_read(self, key: str) -> bytes:
        return self._request("GET", self._url(key))

    def _do_write(self, key: str, data: bytes) -> None:
        self._request("PUT", self._url(key), data)

    def delete(self, key: str) -> None:
        self._request("DELETE", self._url(key))

    def exists(self, key: str) -> bool:
        try:
            self._request("HEAD", self._url(key))
            return True
        except ObjectStoreError as e:
            if getattr(e, "http_code", None) == 404:
                return False
            raise  # 403/5xx/network errors are NOT "does not exist"

    def size(self, key: str) -> int:
        url = self._url(key)
        payload_hash = _sha256(b"")
        headers = sign_v4("HEAD", url, {}, payload_hash,
                          self.access_key, self.secret_key, self.region)
        req = urllib.request.Request(url, method="HEAD", headers=headers)
        try:
            with urllib.request.urlopen(req, timeout=60) as resp:
                return int(resp.headers.get("Content-Length", 0))
        except urllib.error.HTTPError as e:
            if e.code == 404:
                raise ObjectStoreError(f"not found: {key}") from None
            raise ObjectStoreError(
                f"s3 HEAD {url}: HTTP {e.code}") from None
        except urllib.error.URLError as e:
            raise ObjectStoreError(f"s3 HEAD {url}: {e}") from None

    def list(self, prefix: str) -> list[str]:
        full = self._key(prefix)
        plen = len(self.prefix) + 1 if self.prefix else 0
        return [k[plen:] for k, _ in self._list_with_sizes(full)]

    def _list_with_sizes(self, full_prefix: str) -> list[tuple[str, int]]:
        """ListObjectsV2 with continuation (minimal XML scrape — the
        response schema is stable enough that a parser dependency isn't
        warranted)."""
        import html
        import re

        out: list[tuple[str, int]] = []
        token = None
        while True:
            q = ("list-type=2&prefix="
                 + urllib.parse.quote(full_prefix, safe=""))
            if token:
                q += "&continuation-token=" + urllib.parse.quote(token,
                                                                 safe="")
            body = self._request("GET", self._url("", q)).decode()
            for m in re.finditer(
                    r"<Contents>.*?<Key>(.*?)</Key>.*?<Size>(\d+)</Size>"
                    r".*?</Contents>", body, re.S):
                # keys come back XML-entity-encoded (& -> &amp;, etc.)
                out.append((html.unescape(m.group(1)), int(m.group(2))))
            t = re.search(r"<NextContinuationToken>(.*?)"
                          r"</NextContinuationToken>", body)
            if not t:
                return out
            token = t.group(1)

def from_url(url: str, **kw) -> ObjectStore:
    """Backend selection by URL scheme (store.rs:44-116 analog):
    s3://bucket/prefix, oss://bucket/prefix (via OSS's S3-compatible
    endpoint), gs://bucket/prefix (GCS XML API)."""
    p = urllib.parse.urlsplit(url)
    bucket, prefix = p.netloc, p.path.strip("/")
    if p.scheme == "s3":
        return S3Store(bucket, prefix, **kw)
    if p.scheme == "oss":
        region = kw.pop("region", os.environ.get("OSS_REGION",
                                                 "oss-cn-hangzhou"))
        # OSS's S3-COMPATIBLE endpoint (the native one expects OSS's own
        # signature scheme, not SigV4)
        kw.setdefault("endpoint", f"https://s3.{region}.aliyuncs.com")
        return S3Store(bucket, prefix, region=region, **kw)
    if p.scheme == "gs":
        kw.setdefault("endpoint", "https://storage.googleapis.com")
        return S3Store(bucket, prefix, **kw)
    raise ObjectStoreError(f"unsupported object store scheme {p.scheme!r}")
