"""Device kernel library — the TPU-native replacement for the reference's
CPU hot loops (SURVEY.md §3.2 "hot loops"): DataFusion hash aggregates become
segment reductions, mito2's MergeReader k-way heap merge becomes sort-based
dedup, RANGE/PromQL range vectors become blockwise windowed reductions.

Everything here is shape-static, mask-carrying, jit-compatible JAX. Hosts
pad ragged data into power-of-two blocks (ops/blocks.py) so jit caches stay
small (SURVEY.md §7 hard part #1).
"""

from greptimedb_tpu.ops.blocks import pad_rows, block_size_for
from greptimedb_tpu.ops.segment import segment_agg, combine_group_ids, time_bucket
from greptimedb_tpu.ops.dedup import sort_dedup

# every jit entry point below feeds XLA compile count/duration metrics
# through one jax.monitoring listener (utils/device_telemetry)
from greptimedb_tpu.utils import device_telemetry as _device_telemetry

_device_telemetry.install()

__all__ = [
    "pad_rows",
    "block_size_for",
    "segment_agg",
    "combine_group_ids",
    "time_bucket",
    "sort_dedup",
]
