"""Fixed-shape column blocks.

XLA traces/compiles once per shape; ragged scan output must therefore be
padded into a small set of block shapes. We bucket row counts to powers of
two (floor 1024, cap via streaming in the scan layer), so a region scan
compiles at most ~20 kernel variants regardless of data size. The validity
mask rides alongside the data; kernels never compact (dynamic shapes) —
they mask.
"""

from __future__ import annotations

import math

import numpy as np

MIN_BLOCK_ROWS = 1024
# Streaming block cap: few dispatches per query (round-trips dominate on
# remote-attached devices) while keeping kernel temporaries ([block, F]
# stacked values + element masks) well under HBM: 2^23 rows x 10 f32
# fields ~= 335 MiB per temporary.
DEFAULT_BLOCK_ROWS = 1 << 23
_COARSE = 1 << 20


def block_size_for(n: int, min_rows: int = MIN_BLOCK_ROWS) -> int:
    """Block shape bucket for n rows: powers of two up to 1M rows, then
    multiples of 1M (pow2 padding wastes up to 2x at scan scale; 1M-step
    buckets keep the jit cache small AND the padding <6%)."""
    if n <= min_rows:
        return min_rows
    if n >= _COARSE:
        return ((n + _COARSE - 1) // _COARSE) * _COARSE
    return 1 << math.ceil(math.log2(n))


def pad_rows(arr: np.ndarray, size: int, fill=0) -> np.ndarray:
    """Pad axis 0 of `arr` to `size` with `fill`."""
    n = arr.shape[0]
    if n == size:
        return arr
    assert n < size, (n, size)
    pad_width = [(0, size - n)] + [(0, 0)] * (arr.ndim - 1)
    return np.pad(arr, pad_width, constant_values=fill)


def make_mask(n: int, size: int) -> np.ndarray:
    """Validity mask for a block holding n real rows padded to size."""
    mask = np.zeros(size, dtype=bool)
    mask[:n] = True
    return mask
