"""Sort-based merge-dedup: the TPU-native MergeReader.

The reference merges memtable + SST iterators with a k-way binary-heap merge
and dedups by (primary key, timestamp) keeping the highest sequence
(mito2 read/merge.rs:39-115, dedup in read.rs). Branchy heap code is hostile
to TPU; the idiomatic equivalent (SURVEY.md §7) is:

    concat all sources -> lexsort by (series, ts, seq) -> run-boundary mask
    -> keep the last (highest-seq) row of each (series, ts) run
    -> drop rows whose winner is a DELETE tombstone (op_type, read.rs:59-73)

The output is a permutation + keep-mask; downstream kernels consume the
sorted order directly (group ids become sorted, enabling
indices_are_sorted=True segment reductions).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

OP_PUT = 0
OP_DELETE = 1


@functools.partial(jax.jit, static_argnames=("assume_unique_ts", "keep_tombstones"))
def sort_dedup(
    series_ids: jax.Array,  # [N] int32 dense series/primary-key ids
    ts: jax.Array,  # [N] int64
    seq: jax.Array,  # [N] int64 write sequence (monotone per region)
    op_type: jax.Array,  # [N] int8 OP_PUT/OP_DELETE
    mask: jax.Array,  # [N] bool validity
    assume_unique_ts: bool = False,
    keep_tombstones: bool = False,
) -> tuple[jax.Array, jax.Array]:
    """Returns (order, keep): `order` sorts rows by (series, ts); `keep` is a
    mask in sorted order marking last-write-wins survivors.

    With `assume_unique_ts` (append-mode regions, reference
    scan_region.rs:204-212 UnorderedScan), the dedup mask collapses to the
    validity mask and only the sort remains.
    """
    n = series_ids.shape[0]
    big = jnp.iinfo(series_ids.dtype).max
    # push invalid rows to the end so the valid prefix stays dense
    s = jnp.where(mask, series_ids, big)
    # lexsort: last key is the primary key
    order = jnp.lexsort((seq, ts, s))
    s_sorted = s[order]
    if assume_unique_ts:
        keep = s_sorted != big
        return order, keep
    t_sorted = ts[order]
    op_sorted = op_type[order]
    # run boundary: row i is the last of its (series, ts) run
    nxt_s = jnp.concatenate([s_sorted[1:], jnp.full((1,), big, s_sorted.dtype)])
    nxt_t = jnp.concatenate([t_sorted[1:], jnp.full((1,), jnp.iinfo(jnp.int64).min, t_sorted.dtype)])
    is_last = (s_sorted != nxt_s) | (t_sorted != nxt_t)
    keep = is_last & (s_sorted != big)
    if not keep_tombstones:
        # partial (windowed) compactions must retain winning tombstones —
        # an older shadowed PUT may live in a file outside the merge group
        keep = keep & (op_sorted != OP_DELETE)
    return order, keep


def apply_dedup(columns: dict, order: jax.Array, keep: jax.Array) -> tuple[dict, jax.Array]:
    """Gather columns into sorted order; returns (sorted columns, keep mask)."""
    return {k: v[order] for k, v in columns.items()}, keep
