"""Pallas TPU kernel: fused dense segment-sum as a one-hot matmul.

The dense prepared aggregation path (physical.py::_agg_scan_prepared)
reduces a query-invariant [N, W] plane with one dead-segment segment-sum
per block. XLA lowers `jax.ops.segment_sum` to a scatter-add — serialized
row updates that leave the MXU idle. For the dashboard-shaped group
counts (G up to a few thousand: per-minute buckets, host subsets,
bucket x host grids) the same reduction is a matmul:

    out[G, W] = onehot[G, Nb] @ plane[Nb, W]

with the one-hot built in-register from an iota comparison — which is
exactly the systolic array's shape (SURVEY.md §7's fused
filter+bucket+reduce design: the filter arrives as dead-segment ids, the
bucket as the group id, the reduce as the matmul). The grid walks row
blocks sequentially, accumulating into a VMEM-resident [G, W] output
(TPU grids execute in order, so read-modify-write accumulation across
grid steps is the standard reduction pattern, pallas_guide.md).

Selection: ops/segment.py::dense_segment_sum auto-picks this kernel on
TPU backends for eligible shapes and falls back to XLA's scatter
otherwise; GREPTIMEDB_TPU_PALLAS=on forces it (interpret mode off-TPU,
which is how the differential tests run on CPU), =off disables.

Reference analog: DataFusion's row-hash GroupedHashAggregateStream
(src/query — the CPU bottleneck of TSBS double-groupby); this kernel is
its MXU-native replacement for the dense-id case, no hashing at all.
"""

from __future__ import annotations

import contextlib
import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:
    # The engine runs under jax_enable_x64 (int64 timestamps), but x64
    # tracing poisons Mosaic lowering of ANY gridded pallas_call on real
    # TPU ("failed to legalize operation 'func.return'" from the AOT
    # compile helper — reproduced 2026-07-31 on v5e; interpret mode never
    # sees it). Tracing the pallas_call under an x64-off scope keeps the
    # grid/index arithmetic i32 and compiles clean. All kernel operands
    # are explicit f32/i32, so no semantics change.
    from jax._src.config import enable_x64 as _enable_x64
except ImportError:  # private API — degrade to "hope x64 is off"
    def _enable_x64(_v):
        return contextlib.nullcontext()

#: widest plane the kernel accepts (lane tile); prepared planes are
#: 2F+1 <= 21 for TSBS's 10 fields
MAX_WIDTH = 128
#: largest padded segment count: out[G, 128] f32 must sit in VMEM with
#: the one-hot block and the plane block
MAX_SEGMENTS = 4096
#: fused kernel: raw value lanes F padded to 8, [vals | valid | rows]
#: layout 2*FW+1 must fit the 128-lane output tile
MAX_FUSED_FIELDS = 56
#: with the sumsq lanes riding along ([vals | valid | rows | sq]):
#: 3*FW+1 <= 128
MAX_FUSED_FIELDS_SUMSQ = 40


def _round_up(x: int, m: int) -> int:
    return (x + m - 1) // m * m


def _kernel(ids_ref, plane_ref, out_ref):
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _init():
        out_ref[...] = jnp.zeros_like(out_ref)

    ids = ids_ref[...]  # [1, Nb] int32
    gp = out_ref.shape[0]
    nb = ids.shape[1]
    # [Gp, Nb] one-hot from an iota comparison — built in registers,
    # never materialized in HBM
    onehot = (jax.lax.broadcasted_iota(jnp.int32, (gp, nb), 0)
              == ids).astype(plane_ref.dtype)
    # HIGHEST: the MXU's default f32 matmul is a single bf16 pass
    # (~1e-2 abs error on segment sums — observed on v5e); multi-pass
    # recovers f32 accuracy and this workload is bandwidth-bound anyway
    out_ref[...] += jnp.dot(onehot, plane_ref[...],
                            preferred_element_type=out_ref.dtype,
                            precision=jax.lax.Precision.HIGHEST)


@functools.partial(jax.jit,
                   static_argnames=("num_segments", "block_rows",
                                    "interpret"))
def pallas_dense_segment_sum(
    plane: jax.Array,  # [N, W] float values (zeros on invalid rows)
    ids: jax.Array,  # [N] int32 segment ids (dead rows -> num_segments-1)
    num_segments: int,
    block_rows: int = 512,
    interpret: bool = False,
) -> jax.Array:
    """segment_sum(plane, ids, num_segments) on the MXU. Caller must
    pre-check eligible(); padding rows are appended with zero values
    into the last segment (harmless by construction — the dense
    prepared path's dead segment)."""
    n, w = plane.shape
    wp = MAX_WIDTH
    gp = _round_up(max(num_segments, 8), 8)
    npad = _round_up(max(n, 1), block_rows)
    plane_p = jnp.pad(plane, ((0, npad - n), (0, wp - w)))
    ids_p = jnp.pad(ids.astype(jnp.int32), (0, npad - n),
                    constant_values=num_segments - 1)[None, :]
    # x64-off only for the 32-bit chip path: under it, tracing would
    # canonicalize the f64 interpret-mode planes (CPU differential
    # tests) down to f32 and break the kernel's ref dtypes
    ctx = _enable_x64(False) if plane.dtype != jnp.float64 \
        else contextlib.nullcontext()
    with ctx:
        out = pl.pallas_call(
            _kernel,
            grid=(npad // block_rows,),
            in_specs=[
                pl.BlockSpec((1, block_rows), lambda i: (0, i)),
                pl.BlockSpec((block_rows, wp), lambda i: (i, 0)),
            ],
            out_specs=pl.BlockSpec((gp, wp), lambda i: (0, 0)),
            out_shape=jax.ShapeDtypeStruct((gp, wp), plane.dtype),
            interpret=interpret,
        )(ids_p, plane_p)
    return out[:num_segments, :w]


def eligible(shape: tuple, num_segments: int) -> bool:
    """Shapes the kernel handles; everything else takes XLA's scatter."""
    return (len(shape) == 2 and 0 < shape[1] <= MAX_WIDTH
            and 0 < num_segments <= MAX_SEGMENTS)


# ---- fused scan→filter→bucket→aggregate kernel ------------------------------
#
# The dense prepared path pays one host-built [N, 2F+1] plane upload plus
# one segment-sum, one segment-min, and one segment-max dispatch per block
# (each an XLA scatter off the MXU). The fused kernel below replaces the
# whole chain with ONE pallas_call over the RAW value columns: validity
# (NaN) masks, the [vals | valid | rows] reduction plane, and the one-hot
# group matrix are all built in-register — none of them ever exists in
# HBM — and min/max reduce in the same pass. The HBM-resident hot set
# therefore caches only the F raw value lanes per block instead of the
# 2F+1 sum plane plus two F-wide identity-filled extreme planes.


def _fused_kernel(ids_ref, vals_ref, *out_refs, nf, fw, want_min, want_max,
                  want_sumsq):
    i = pl.program_id(0)
    sum_ref = out_refs[0]
    min_ref = out_refs[1] if want_min else None
    max_ref = out_refs[1 + bool(want_min)] if want_max else None
    dt = vals_ref.dtype

    @pl.when(i == 0)
    def _init():
        sum_ref[...] = jnp.zeros_like(sum_ref)
        if want_min:
            min_ref[...] = jnp.full_like(min_ref, jnp.inf)
        if want_max:
            max_ref[...] = jnp.full_like(max_ref, -jnp.inf)

    ids = ids_ref[...]    # [1, Nb] int32 (masked/padding rows -> dead id)
    vals = vals_ref[...]  # [Nb, FW] raw values, NaN = NULL
    gp = sum_ref.shape[0]
    nb = ids.shape[1]
    onehot_b = (jax.lax.broadcasted_iota(jnp.int32, (gp, nb), 0) == ids)
    valid = ~jnp.isnan(vals)                       # [Nb, FW] in-register
    zeroed = jnp.where(valid, vals, jnp.asarray(0, dt))
    pad_w = sum_ref.shape[1] - (3 if want_sumsq else 2) * fw
    # [zeroed | valid | rows-one | 0-pad (| squares)]: the prepared-plane
    # layout, assembled in VMEM registers instead of host RAM + H2D; the
    # variance moment rides the SAME matmul as extra lanes (NaN already
    # zeroed, so squares contribute exactly where elem-valid)
    rows_col = (jax.lax.broadcasted_iota(jnp.int32, (nb, pad_w), 1)
                == 0).astype(dt)
    segs = [zeroed, valid.astype(dt), rows_col]
    if want_sumsq:
        segs.append(zeroed * zeroed)
    plane = jnp.concatenate(segs, axis=1)
    # see _kernel: HIGHEST recovers f32 accuracy from the bf16 MXU passes
    sum_ref[...] += jnp.dot(onehot_b.astype(dt), plane,
                            preferred_element_type=dt,
                            precision=jax.lax.Precision.HIGHEST)
    if want_min or want_max:
        # only the nf real field lanes; fw-nf padding lanes stay at the
        # _init identities (the [:nf] unpack slice discards them anyway)
        mins, maxs = [], []
        for f in range(nf):
            col = vals[:, f][None, :]              # [1, Nb]
            sel = onehot_b & ~jnp.isnan(col)       # NaN: SQL NULL skip
            if want_min:
                mins.append(jnp.min(
                    jnp.where(sel, col, jnp.asarray(jnp.inf, dt)), axis=1))
            if want_max:
                maxs.append(jnp.max(
                    jnp.where(sel, col, jnp.asarray(-jnp.inf, dt)), axis=1))

        def _lanes(cols, ident):
            stacked = jnp.stack(cols, axis=1)      # [gp, nf]
            if fw > nf:                            # full-width store: pad
                stacked = jnp.concatenate(         # identity lanes back on
                    [stacked, jnp.full((gp, fw - nf), ident, dt)], axis=1)
            return stacked

        if want_min:
            min_ref[...] = jnp.minimum(min_ref[...],
                                       _lanes(mins, jnp.inf))
        if want_max:
            max_ref[...] = jnp.maximum(max_ref[...],
                                       _lanes(maxs, -jnp.inf))


@functools.partial(jax.jit,
                   static_argnames=("num_segments", "want_min", "want_max",
                                    "want_sumsq", "block_rows", "interpret"))
def pallas_fused_segment_agg(
    vals: jax.Array,  # [N, F] raw field values (NaN = NULL)
    ids: jax.Array,  # [N] int32 group ids (masked rows -> num_segments-1)
    num_segments: int,
    want_min: bool = False,
    want_max: bool = False,
    want_sumsq: bool = False,
    block_rows: int = 512,
    interpret: bool = False,
) -> dict:
    """Fused masked segment aggregation on the MXU/VPU: one pallas_call
    emits {"sum" [G, F], "count" [G, F], "rows" [G], "min"/"max" [G, F],
    "sumsq" [G, F]}. Caller must pre-check fused_eligible() and prove
    the values finite (Inf would poison the 0*x matmul — same contract
    as the sum kernel); NaN is handled in-register as SQL NULL. The
    sumsq lanes accumulate in the kernel dtype — callers needing the
    f64 variance contract must feed f64 values (interpret mode / x64
    chips) or stay on the prepared path. Masked rows arrive encoded
    into the dead segment num_segments-1, exactly like the sum kernel;
    empty/all-NULL groups come back as 0 counts and ±inf extremes."""
    n, nf = vals.shape
    fw = _round_up(max(nf, 1), 8)
    gp = _round_up(max(num_segments, 8), 8)
    npad = _round_up(max(n, 1), block_rows)
    vals_p = jnp.pad(vals, ((0, npad - n), (0, fw - nf)))
    ids_p = jnp.pad(ids.astype(jnp.int32), (0, npad - n),
                    constant_values=num_segments - 1)[None, :]
    out_shapes = [jax.ShapeDtypeStruct((gp, MAX_WIDTH), vals.dtype)]
    out_specs = [pl.BlockSpec((gp, MAX_WIDTH), lambda i: (0, 0))]
    if want_min:
        out_shapes.append(jax.ShapeDtypeStruct((gp, fw), vals.dtype))
        out_specs.append(pl.BlockSpec((gp, fw), lambda i: (0, 0)))
    if want_max:
        out_shapes.append(jax.ShapeDtypeStruct((gp, fw), vals.dtype))
        out_specs.append(pl.BlockSpec((gp, fw), lambda i: (0, 0)))
    kern = functools.partial(_fused_kernel, nf=nf, fw=fw,
                             want_min=want_min, want_max=want_max,
                             want_sumsq=want_sumsq)
    ctx = _enable_x64(False) if vals.dtype != jnp.float64 \
        else contextlib.nullcontext()
    with ctx:
        outs = pl.pallas_call(
            kern,
            grid=(npad // block_rows,),
            in_specs=[
                pl.BlockSpec((1, block_rows), lambda i: (0, i)),
                pl.BlockSpec((block_rows, fw), lambda i: (i, 0)),
            ],
            out_specs=out_specs,
            out_shape=out_shapes,
            interpret=interpret,
        )(ids_p, vals_p)
    total = outs[0]
    g = num_segments
    out = {
        "sum": total[:g, :nf],
        "count": total[:g, fw:fw + nf],
        "rows": total[:g, 2 * fw],
    }
    if want_sumsq:
        # squares sit at the plane's tail: [.. | rows+pad | sq] layout
        out["sumsq"] = total[:g, MAX_WIDTH - fw:MAX_WIDTH - fw + nf]
    k = 1
    if want_min:
        out["min"] = outs[k][:g, :nf]
        k += 1
    if want_max:
        out["max"] = outs[k][:g, :nf]
    return out


def fused_eligible(nf: int, num_segments: int,
                   want_sumsq: bool = False) -> bool:
    """Shapes the fused kernel handles; everything else takes the
    prepared-plane path (XLA scatter reductions). The sumsq lanes eat a
    third field-width stripe of the 128-lane output tile: 3*FW+1 <= 128
    caps the field count at 40 when the variance moment rides along."""
    limit = MAX_FUSED_FIELDS_SUMSQ if want_sumsq else MAX_FUSED_FIELDS
    return 0 < nf <= limit and 0 < num_segments <= MAX_SEGMENTS


_TPU_COMPILE_OK: bool | None = None
_FUSED_COMPILE_OK: bool | None = None


def fused_tpu_compile_ok() -> bool:
    """One-shot Mosaic canary for the FUSED kernel (min/max loop + the
    in-register plane assembly exercise lowering paths the plain sum
    kernel never touches): auto mode consults this before routing a
    query, so a chip that cannot compile the fused program degrades to
    the prepared-plane path instead of sinking the query."""
    global _FUSED_COMPILE_OK
    if _FUSED_COMPILE_OK is None:
        try:
            out = pallas_fused_segment_agg(
                jnp.ones((8, 2), jnp.float32), jnp.zeros(8, jnp.int32), 2,
                want_min=True, want_max=True)
            _FUSED_COMPILE_OK = (
                abs(float(out["sum"][0, 0]) - 8.0) < 1e-6
                and abs(float(out["min"][0, 0]) - 1.0) < 1e-6)
        except Exception:  # noqa: BLE001 — any compile failure means "don't"
            _FUSED_COMPILE_OK = False
    return _FUSED_COMPILE_OK


def tpu_compile_ok() -> bool:
    """One-shot canary: Mosaic compilation through this host's compile
    path (on tunneled setups, a remote AOT helper) can fail in ways
    interpret mode never exercises — round-5 incident: x64 tracing made
    every gridded kernel unlowerable and sank the whole query instead
    of degrading. `auto` mode consults this before routing planes to
    the kernel; on failure the XLA scatter path serves instead."""
    global _TPU_COMPILE_OK
    if _TPU_COMPILE_OK is None:
        try:
            out = pallas_dense_segment_sum(
                jnp.ones((8, 2), jnp.float32),
                jnp.zeros(8, jnp.int32), 2)
            _TPU_COMPILE_OK = abs(float(out[0, 0]) - 8.0) < 1e-6
        except Exception:  # noqa: BLE001 — any compile failure means "don't"
            _TPU_COMPILE_OK = False
    return _TPU_COMPILE_OK
