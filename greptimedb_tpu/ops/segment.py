"""Segment reductions: the TPU-native group-by.

The reference's group-by is DataFusion's row-hash aggregate, the CPU
bottleneck of the TSBS double-groupby queries (SURVEY.md §6). On TPU,
group-by over dictionary-encoded tags + time buckets is a *segment
reduction*: group ids are computed arithmetically (no hashing — tag codes
and bucket indices are already dense ints), then reduced with
scatter-adds/mins/maxes that XLA lowers natively.

All kernels are mask-carrying: padding rows and filtered-out rows simply
contribute identity elements. NaN field values (NULLs) are treated as
SQL semantics: excluded from sum/count/min/max/avg.
"""

from __future__ import annotations

import functools
from typing import Optional, Sequence

import jax
import jax.numpy as jnp

# Aggregate ops supported by the kernel. "first"/"last" are by time order
# within the segment (used by lastpoint / PromQL instant selection);
# "rows" counts rows irrespective of NULLs (count(*) / group presence);
# "sumsq" feeds stddev/variance.
AGG_OPS = ("sum", "count", "min", "max", "mean", "first", "last", "rows", "sumsq")


def time_bucket(ts: jax.Array, interval: int, origin: int = 0) -> jax.Array:
    """Floor-align int64 timestamps into buckets of `interval` (same unit).

    Mirrors date_bin / RANGE align (reference query/src/range_select/plan.rs:413)
    and PromQL step alignment. Floor division matches SQL date_bin semantics
    for timestamps before the origin as well.
    """
    return (ts - origin) // interval


def combine_group_ids(
    keys: Sequence[jax.Array],
    sizes: Sequence[int],
    dtype=jnp.int32,
) -> jax.Array:
    """Fuse several dense int keys (tag codes, bucket indices) into one
    dense group id: id = ((k0 * s1 + k1) * s2 + k2) ... Row-major, so sort
    order of the combined id equals lexicographic order of the keys.
    Use dtype=int64 when the product of sizes can exceed 2^31 (e.g. the
    dedup series id over many high-cardinality tags).
    """
    assert len(keys) == len(sizes) and keys
    gid = keys[0].astype(dtype)
    for k, s in zip(keys[1:], sizes[1:]):
        gid = gid * jnp.asarray(s, dtype) + k.astype(dtype)
    return gid


def _masked(values: jax.Array, mask: jax.Array, fill) -> jax.Array:
    return jnp.where(mask, values, jnp.asarray(fill, dtype=values.dtype))


def _pallas_mode() -> str:
    import os

    return os.environ.get("GREPTIMEDB_TPU_PALLAS", "auto").lower()


def dense_segment_sum(plane: jax.Array, ids: jax.Array,
                      num_segments: int, finite: bool = False) -> jax.Array:
    """segment_sum for the dense prepared planes, MXU-routed when it
    pays: on TPU backends (or GREPTIMEDB_TPU_PALLAS=on, interpret mode
    elsewhere — how the CPU differential tests drive it) eligible shapes
    run the pallas one-hot-matmul kernel (ops/pallas_segment.py);
    everything else takes XLA's scatter-add. =off pins the scatter.

    The mode is read at TRACE time and baked into the enclosing jit
    cache — set the env var before the engine starts, not per-query.

    `finite` (static, from the caller's cached plane scan): the one-hot
    matmul computes 0*x for every row outside a group, so a single
    +/-Inf value would poison EVERY group with NaN — callers must prove
    the plane finite (the same host pass that detects NaNs) before the
    kernel is allowed. f64 planes only ride in interpret mode: Mosaic
    cannot lower f64 matmuls on the chip."""
    mode = _pallas_mode()
    if mode != "off" and finite and plane.ndim == 2:
        from greptimedb_tpu.ops import pallas_segment as ps

        backend = jax.default_backend()
        dtype_ok = plane.dtype in (jnp.float32, jnp.bfloat16) \
            or backend != "tpu"
        # cheap pure checks first: the canary costs one Mosaic compile,
        # so consult it only for planes that could actually route here
        if dtype_ok and ps.eligible(plane.shape, num_segments) and (
                mode == "on" or (mode == "auto" and backend == "tpu"
                                 and ps.tpu_compile_ok())):
            return ps.pallas_dense_segment_sum(
                plane, ids, num_segments,
                interpret=backend != "tpu")
    return jax.ops.segment_sum(plane, ids, num_segments=num_segments)


@functools.partial(
    jax.jit,
    static_argnames=("num_segments", "ops", "indices_are_sorted"),
)
def segment_agg(
    values: jax.Array,  # [N] or [N, F] field values (float)
    seg_ids: jax.Array,  # [N] int32 dense group ids
    mask: jax.Array,  # [N] bool validity (padding & filter)
    num_segments: int,
    ops: tuple[str, ...] = ("sum", "count"),
    ts: Optional[jax.Array] = None,  # [N] int64, required for first/last
    indices_are_sorted: bool = False,
) -> dict[str, jax.Array]:
    """Masked segment reduction. Returns {op: [G] or [G, F]} arrays.

    NULL handling: NaN values are excluded per-element (SQL aggregate
    semantics); `mask` excludes whole rows (padding / WHERE / dedup).
    """
    squeeze = values.ndim == 1
    if squeeze:
        values = values[:, None]
    n, f = values.shape
    row_mask = mask
    # element validity: row valid and value not NaN
    if jnp.issubdtype(values.dtype, jnp.floating):
        elem_mask = row_mask[:, None] & ~jnp.isnan(values)
    else:
        elem_mask = jnp.broadcast_to(row_mask[:, None], values.shape)
    # invalid rows scatter into a dead segment G (we allocate G+1 and drop it)
    ids = jnp.where(row_mask, seg_ids, jnp.int32(num_segments))
    gsz = num_segments + 1

    seg_sum = functools.partial(
        jax.ops.segment_sum,
        segment_ids=ids,
        num_segments=gsz,
        indices_are_sorted=indices_are_sorted,
    )

    out: dict[str, jax.Array] = {}
    need_sum = any(o in ops for o in ("sum", "mean"))
    need_count = any(o in ops for o in ("count", "mean"))
    # variance = (sumsq - sum^2/n) is catastrophically cancellation-prone:
    # both moments must carry ~2x the data's precision for the subtraction
    # to survive, so sumsq (and the sum it is differenced against) always
    # accumulate in f64 — even on the f32 TPU fast path, where only
    # stddev/variance queries pay the emulation cost
    moment_vals = values
    if "sumsq" in ops and jnp.issubdtype(values.dtype, jnp.floating) \
            and values.dtype != jnp.float64:
        moment_vals = values.astype(jnp.float64)
    sums = counts = None
    if need_sum or "sumsq" in ops:
        sums = seg_sum(
            jnp.where(elem_mask, moment_vals, 0).astype(moment_vals.dtype))
    if need_count:
        # int32: exact per-block (block rows << 2^31); cross-block combine
        # upcasts to int64
        counts = seg_sum(elem_mask.astype(jnp.int32))
    if "sum" in ops:
        out["sum"] = sums
    if "count" in ops:
        out["count"] = counts
    if "rows" in ops:
        # [G, 1]: per-group, not per-field
        out["rows"] = seg_sum(row_mask.astype(jnp.int32)[:, None])
    if "sumsq" in ops:
        out["sumsq"] = seg_sum(
            jnp.where(elem_mask, moment_vals * moment_vals, 0)
            .astype(moment_vals.dtype))
    if "mean" in ops:
        denom = jnp.maximum(counts, 1).astype(values.dtype)
        mean = sums.astype(values.dtype) / denom
        out["mean"] = jnp.where(counts > 0, mean, jnp.nan)
    if "min" in ops:
        big = _type_max(values.dtype)
        mins = jax.ops.segment_min(
            jnp.where(elem_mask, values, big),
            ids, num_segments=gsz, indices_are_sorted=indices_are_sorted,
        )
        out["min"] = jnp.where(mins == big, _null_of(values.dtype), mins)
    if "max" in ops:
        small = _type_min(values.dtype)
        maxs = jax.ops.segment_max(
            jnp.where(elem_mask, values, small),
            ids, num_segments=gsz, indices_are_sorted=indices_are_sorted,
        )
        out["max"] = jnp.where(maxs == small, _null_of(values.dtype), maxs)
    if "first" in ops or "last" in ops:
        assert ts is not None, "first/last need the time column"
        # argmin/argmax of ts per segment: reduce packed (ts, row index).
        # ts fits int64; break ties by row index using a second reduction.
        idx = jnp.arange(n, dtype=jnp.int64)
        if "last" in ops:
            best_ts = jax.ops.segment_max(
                jnp.where(row_mask, ts, jnp.iinfo(jnp.int64).min),
                ids, num_segments=gsz, indices_are_sorted=indices_are_sorted,
            )
            at_best = row_mask & (ts == best_ts[ids])
            best_idx = jax.ops.segment_max(
                jnp.where(at_best, idx, -1), ids, num_segments=gsz,
                indices_are_sorted=indices_are_sorted,
            )
            safe = jnp.clip(best_idx, 0, n - 1)
            vals = values[safe]
            out["last"] = jnp.where(best_idx[:, None] >= 0, vals, _null_of(values.dtype))
            out["last_ts"] = best_ts
        if "first" in ops:
            best_ts = jax.ops.segment_min(
                jnp.where(row_mask, ts, jnp.iinfo(jnp.int64).max),
                ids, num_segments=gsz, indices_are_sorted=indices_are_sorted,
            )
            at_best = row_mask & (ts == best_ts[ids])
            # tie-break by MIN row index: the earliest-positioned sample
            # of the earliest instant. Symmetric with `last` (max ts, max
            # idx) and identical to the sorted-input bucketization in
            # ops/window.py — the two flavors must match bit-for-bit or
            # CPU and TPU backends would answer `first` differently for
            # samples sharing a millisecond. (SQL ties can only arise in
            # append-mode tables, where the winner is undefined; LWW
            # dedup removes same-(series, ts) rows everywhere else.)
            best_idx = jax.ops.segment_min(
                jnp.where(at_best, idx, jnp.int64(n)), ids,
                num_segments=gsz, indices_are_sorted=indices_are_sorted,
            )
            safe = jnp.clip(best_idx, 0, n - 1)
            vals = values[safe]
            out["first"] = jnp.where(best_idx[:, None] < n, vals,
                                     _null_of(values.dtype))
            out["first_ts"] = best_ts

    # drop the dead padding segment; restore caller's rank
    trimmed = {}
    for k, v in out.items():
        v = v[:num_segments]
        if squeeze and v.ndim == 2:
            v = v[:, 0]
        trimmed[k] = v
    return trimmed


def combine_partial_aggs(
    partials: dict[str, jax.Array], axis_name: str, with_mean: bool = False
) -> dict[str, jax.Array]:
    """Merge per-shard partial aggregates across a mesh axis with XLA
    collectives — the TPU-native MergeScan (reference
    query/src/dist_plan/merge_scan.rs:122 gathers region streams over
    Flight; here partial sums/counts ride ICI via psum).

    NULL semantics match single-device `segment_agg`: an all-NULL group's
    min/max is NaN (NaN partials are filled with ±inf for the collective,
    then groups that stayed at the fill value are restored to NaN).
    Counts upcast to int64 before psum so >2^31-row totals stay exact.
    """
    out = {}
    for op, v in partials.items():
        if op in ("first", "last", "first_ts", "last_ts"):
            continue  # combined below by (value, ts) pairing
        if op in ("count", "rows"):
            out[op] = jax.lax.psum(v.astype(jnp.int64), axis_name)
        elif op in ("sum", "sumsq"):
            out[op] = jax.lax.psum(v, axis_name)
        elif op == "min":
            big = _type_max(v.dtype)
            mn = jax.lax.pmin(_nan_to(v, big), axis_name)
            out[op] = jnp.where(mn == big, _null_of(v.dtype), mn)
        elif op == "max":
            small = _type_min(v.dtype)
            mx = jax.lax.pmax(_nan_to(v, small), axis_name)
            out[op] = jnp.where(mx == small, _null_of(v.dtype), mx)
        else:
            raise ValueError(f"non-commutative partial agg: {op}")
    # first/last ARE collective-combinable once paired with their
    # companion timestamps: the shard holding the globally oldest/newest
    # ts per group wins; exact-ts ties break deterministically by shard
    # index (lowest wins for first, highest for last). Empty groups keep
    # ts sentinels and so never beat a shard with data; an all-empty
    # group's winner contributes its NaN value, which psum propagates.
    for op, ts_op, pick_last in (("first", "first_ts", False),
                                 ("last", "last_ts", True)):
        if op not in partials:
            continue
        ts = partials[ts_op]
        idx = jax.lax.axis_index(axis_name).astype(ts.dtype)
        if pick_last:
            best = jax.lax.pmax(ts, axis_name)
            wrank = jax.lax.pmax(
                jnp.where(ts == best, idx, jnp.asarray(-1, ts.dtype)),
                axis_name)
        else:
            best = jax.lax.pmin(ts, axis_name)
            hi = jnp.asarray(jnp.iinfo(jnp.int32).max, ts.dtype)
            wrank = jax.lax.pmin(jnp.where(ts == best, idx, hi), axis_name)
        sel = (ts == best) & (idx == wrank)
        v = partials[op]
        selv = jnp.broadcast_to(sel, v.shape)
        out[op] = jax.lax.psum(jnp.where(selv, v, 0.0), axis_name)
        out[ts_op] = best
    if with_mean and "sum" in out and "count" in out:
        denom = jnp.maximum(out["count"], 1).astype(out["sum"].dtype)
        out["mean"] = jnp.where(out["count"] > 0, out["sum"] / denom, jnp.nan)
    return out


def _nan_to(v, fill):
    if jnp.issubdtype(v.dtype, jnp.floating):
        return jnp.where(jnp.isnan(v), jnp.asarray(fill, v.dtype), v)
    return v


def _type_max(dt):
    return jnp.inf if jnp.issubdtype(dt, jnp.floating) else jnp.iinfo(dt).max


def _type_min(dt):
    return -jnp.inf if jnp.issubdtype(dt, jnp.floating) else jnp.iinfo(dt).min


def _null_of(dt):
    return jnp.nan if jnp.issubdtype(dt, jnp.floating) else jnp.asarray(0, dt)
